"""Trial schedulers: FIFO, ASHA, HyperBand (ASHA-based), Median stopping,
PBT.

Parity with the reference's tune.schedulers (ref: python/ray/tune/
schedulers/ — async_hyperband.py ASHA rung logic, median_stopping_rule.py,
pbt.py exploit/explore via checkpoint swap)."""
from __future__ import annotations

import math
import random
from collections import defaultdict
from typing import Any, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"
# checkpoint + release resources; a scheduler resumes it later via
# controller.resume_trial (ref: trial_scheduler.py PAUSE)
PAUSE = "PAUSE"


class TrialScheduler:
    def set_metric(self, metric: str, mode: str) -> None:
        self.metric = metric
        self.mode = mode

    def _score(self, result: dict) -> float:
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def on_result(self, trial, result: dict) -> str:
        return CONTINUE

    def on_complete(self, trial, result: Optional[dict]) -> None:
        pass

    def choose_action(self, controller) -> None:
        """Hook for schedulers that mutate trials (PBT) or resume paused
        ones (HyperBand promotions)."""

    def on_deadlock(self, controller) -> None:
        """Every live trial is paused and nothing is pending: the
        scheduler MUST make progress (resume or stop someone). Default:
        resume everything — safe for schedulers that never pause."""
        for t in controller.paused_trials():
            controller.resume_trial(t)


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (ref: schedulers/async_hyperband.py). Rungs at
    grace_period * reduction_factor^k; a trial reaching a rung stops unless
    in the top 1/reduction_factor of completed scores at that rung."""

    def __init__(self, time_attr: str = "training_iteration",
                 grace_period: int = 1, reduction_factor: float = 3,
                 max_t: int = 100):
        self.time_attr = time_attr
        self.grace = grace_period
        self.rf = reduction_factor
        self.max_t = max_t
        self._rungs: Dict[int, List[float]] = defaultdict(list)

    def _rung_levels(self) -> List[int]:
        levels = []
        t = self.grace
        while t < self.max_t:
            levels.append(int(t))
            t *= self.rf
        return levels

    def on_result(self, trial, result: dict) -> str:
        t = int(result.get(self.time_attr, 0))
        if t >= self.max_t:
            return STOP
        score = self._score(result)
        for level in self._rung_levels():
            if t == level:
                rung = self._rungs[level]
                rung.append(score)
                k = max(1, int(len(rung) / self.rf))
                cutoff = sorted(rung, reverse=True)[k - 1]
                if score < cutoff:
                    return STOP
        return CONTINUE


ASHAScheduler = AsyncHyperBandScheduler


class _Bracket:
    """One HyperBand bracket: `target` trials entering at budget r0, then
    successive halving at rungs r0*eta^k (ref: hyperband.py Bracket)."""

    def __init__(self, s: int, target: int, r0: int, eta: float,
                 max_t: int):
        self.s = s
        self.target = target
        self.members: set = set()
        self.live: set = set()
        self.closed = False  # no further members will join
        levels = []
        r = float(r0)
        while r < max_t:
            levels.append(max(1, int(round(r))))
            r *= eta
        self.levels = levels
        self.rungs: Dict[int, Dict[str, float]] = {lv: {} for lv in levels}
        self.waiting: Dict[int, set] = {lv: set() for lv in levels}

    def full(self) -> bool:
        return len(self.members) >= self.target


class HyperBandScheduler(TrialScheduler):
    """Synchronous HyperBand (ref: schedulers/hyperband.py; Li et al.
    2018). Brackets are created with their canonical population
    n_s = ceil((s_max+1) * eta^s / (s+1)) and filled sequentially,
    exploration-heaviest first (s = s_max down to 0, then repeat). A
    trial reaching a rung PAUSES (checkpoint + release resources); when
    every live member of a CLOSED bracket has reported at the rung, the
    top ceil(n/eta) resume and the rest stop. Brackets close when full,
    or when the searcher is exhausted (on_deadlock / choose_action with
    no unassigned trials left). The async variant is
    AsyncHyperBandScheduler; this one gives the bracket-diversity
    guarantee BOHB builds on (hb_bohb.py)."""

    def __init__(self, time_attr: str = "training_iteration",
                 max_t: int = 81, reduction_factor: float = 3):
        self.time_attr = time_attr
        self.max_t = max_t
        self.eta = reduction_factor
        self.s_max = max(0, int(
            math.log(max_t) / math.log(reduction_factor) + 1e-9))
        self._brackets: List[_Bracket] = []
        self._bracket_of: Dict[str, _Bracket] = {}
        self._next_s = self.s_max

    def _new_bracket(self) -> _Bracket:
        s = self._next_s
        self._next_s = self._next_s - 1 if self._next_s > 0 else self.s_max
        n = int(math.ceil((self.s_max + 1) * (self.eta ** s) / (s + 1)))
        r0 = max(1, int(round(self.max_t * (self.eta ** -s))))
        b = _Bracket(s, n, r0, self.eta, self.max_t)
        self._brackets.append(b)
        return b

    def _assign(self, trial) -> _Bracket:
        b = self._bracket_of.get(trial.trial_id)
        if b is None:
            b = next((x for x in self._brackets
                      if not x.full() and not x.closed), None)
            if b is None:
                b = self._new_bracket()
            b.members.add(trial.trial_id)
            b.live.add(trial.trial_id)
            if b.full():
                b.closed = True
            self._bracket_of[trial.trial_id] = b
        return b

    def on_result(self, trial, result: dict) -> str:
        b = self._assign(trial)
        t = int(result.get(self.time_attr, 0))
        if t >= self.max_t:
            return STOP
        for level in b.levels:
            if t >= level and trial.trial_id not in b.rungs[level]:
                b.rungs[level][trial.trial_id] = self._score(result)
                b.waiting[level].add(trial.trial_id)
                return PAUSE
        return CONTINUE

    def on_complete(self, trial, result: Optional[dict]) -> None:
        b = self._bracket_of.get(trial.trial_id)
        if b is not None:
            b.live.discard(trial.trial_id)

    def _decide_rung(self, b: _Bracket, level: int, controller,
                     force: bool = False) -> None:
        waiting = b.waiting[level]
        if not waiting:
            return
        rung = b.rungs[level]
        if not force and (not b.closed
                          or any(tid not in rung for tid in b.live)):
            return  # population incomplete or stragglers still climbing
        scored = sorted(((s, tid) for tid, s in rung.items()
                         if tid in b.live), reverse=True)
        keep = max(1, int(math.ceil(len(scored) / self.eta)))
        promoted = {tid for _, tid in scored[:keep]}
        trials = {t.trial_id: t for t in controller.all_trials()}
        for tid in list(waiting):
            waiting.discard(tid)
            t = trials.get(tid)
            if t is None:
                continue
            if tid in promoted:
                controller.resume_trial(t)
            else:
                controller.stop_trial(t)

    def _maybe_close_brackets(self, controller) -> None:
        """The searcher is exhausted and every trial has a bracket: no
        bracket will ever gain members — close them all."""
        if not getattr(controller, "_exhausted", False):
            return
        if any(t.trial_id not in self._bracket_of
               for t in controller.all_trials()
               if t.status in ("PENDING", "RUNNING", "PAUSED")):
            return
        for b in self._brackets:
            b.closed = True

    def choose_action(self, controller) -> None:
        self._maybe_close_brackets(controller)
        for b in self._brackets:
            for level in b.levels:
                self._decide_rung(b, level, controller)

    def on_deadlock(self, controller) -> None:
        # nothing can run and nothing is pending: rung populations will
        # never complete — force decisions from whatever has reported
        for b in self._brackets:
            b.closed = True
            for level in b.levels:
                self._decide_rung(b, level, controller, force=True)


# BOHB = HyperBand brackets + the TPE model-based searcher
# (ref: schedulers/hb_bohb.py pairs HyperBandForBOHB with TuneBOHB)
HyperBandForBOHB = HyperBandScheduler


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best score is below the median of running averages
    (ref: schedulers/median_stopping_rule.py)."""

    def __init__(self, time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.time_attr = time_attr
        self.grace = grace_period
        self.min_samples = min_samples_required
        self._avgs: Dict[str, List[float]] = defaultdict(list)

    def on_result(self, trial, result: dict) -> str:
        t = int(result.get(self.time_attr, 0))
        score = self._score(result)
        self._avgs[trial.trial_id].append(score)
        if t <= self.grace or len(self._avgs) < self.min_samples:
            return CONTINUE
        running = [sum(v) / len(v) for v in self._avgs.values()]
        running.sort()
        median = running[len(running) // 2]
        mine = self._avgs[trial.trial_id]
        if sum(mine) / len(mine) < median and max(mine) < median:
            return STOP
        return CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (ref: schedulers/pbt.py): at each perturbation interval, bottom-
    quartile trials copy the checkpoint of a top-quartile trial (exploit)
    and perturb hyperparameters (explore). The controller performs the
    restart; we record the decision on the trial."""

    def __init__(self, time_attr: str = "training_iteration",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = dict(hyperparam_mutations or {})
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self._rng = random.Random(seed)
        self._latest: Dict[str, dict] = {}

    def on_result(self, trial, result: dict) -> str:
        self._latest[trial.trial_id] = result
        t = int(result.get(self.time_attr, 0))
        if t > 0 and t % self.interval == 0:
            trial.pbt_ready = True
        return CONTINUE

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        new = dict(config)
        for key, spec in self.mutations.items():
            if key not in new:
                continue
            if callable(spec):
                new[key] = spec()
            elif isinstance(spec, list):
                if self._rng.random() < self.resample_prob:
                    new[key] = self._rng.choice(spec)
                else:
                    cur = new[key]
                    idx = spec.index(cur) if cur in spec else 0
                    idx = max(0, min(len(spec) - 1,
                                     idx + self._rng.choice([-1, 1])))
                    new[key] = spec[idx]
            else:  # Domain
                if self._rng.random() < self.resample_prob:
                    new[key] = spec.sample(self._rng)
                else:
                    new[key] = new[key] * self._rng.choice([0.8, 1.2])
        return new

    def choose_action(self, controller) -> None:
        ready = [t for t in controller.running_trials()
                 if getattr(t, "pbt_ready", False)]
        if not ready:
            return
        scored = [(self._score(self._latest[t.trial_id]), t)
                  for t in controller.all_trials()
                  if t.trial_id in self._latest and t.status in ("RUNNING", "PAUSED")]
        if len(scored) < 2:
            for t in ready:
                t.pbt_ready = False
            return
        scored.sort(key=lambda x: x[0])
        n = len(scored)
        k = max(1, int(n * self.quantile))
        bottom = {t.trial_id for _, t in scored[:k]}
        top = [t for _, t in scored[-k:]]
        for t in ready:
            t.pbt_ready = False
            if t.trial_id in bottom:
                donor = self._rng.choice(top)
                if donor.trial_id == t.trial_id or donor.latest_checkpoint is None:
                    continue
                new_config = self._explore(donor.config)
                controller.exploit_trial(t, donor, new_config)


class ResourceChangingScheduler(TrialScheduler):
    """Reallocate trial resources mid-run (ref:
    tune/schedulers/resource_changing_scheduler.py). Wraps a base
    scheduler; after each result, `resources_allocation_function(
    controller, trial, result, scheduler) -> Optional[dict]` may return
    a new resource dict for the trial. A change pauses the trial
    (checkpoint + release its placement group) and resumes it with the
    new allocation — the same save/stop/restart mechanics HyperBand
    rungs use, so trainables need only normal checkpointing."""

    def __init__(self, base_scheduler: Optional[TrialScheduler] = None,
                 resources_allocation_function=None):
        self.base = base_scheduler or FIFOScheduler()
        self.fn = resources_allocation_function
        self._pending: list = []      # trials awaiting reallocation
        self._resuming: set = set()   # trial_ids we paused for resize

    def set_metric(self, metric: str, mode: str) -> None:
        super().set_metric(metric, mode)
        self.base.set_metric(metric, mode)

    def on_result(self, trial, result: dict) -> str:
        decision = self.base.on_result(trial, result)
        if decision == CONTINUE and self.fn is not None:
            self._pending.append((trial, dict(result)))
        return decision

    def on_complete(self, trial, result) -> None:
        self.base.on_complete(trial, result)

    def choose_action(self, controller) -> None:
        self.base.choose_action(controller)
        pending, self._pending = self._pending, []
        for trial, result in pending:
            if trial.status != "RUNNING":
                continue
            try:
                new_res = self.fn(controller, trial, result, self)
            except Exception:
                continue
            if not new_res:
                continue
            current = trial.resources or controller.tc.trial_resources
            if dict(new_res) == dict(current):
                continue
            trial.resources = dict(new_res)
            controller._pause_trial(trial)
            self._resuming.add(trial.trial_id)
        # resume resized trials immediately (their pause was ours, not a
        # rung barrier)
        for t in controller.paused_trials():
            if t.trial_id in self._resuming:
                self._resuming.discard(t.trial_id)
                controller.resume_trial(t)

    def on_deadlock(self, controller) -> None:
        for t in controller.paused_trials():
            if t.trial_id in self._resuming:
                self._resuming.discard(t.trial_id)
                controller.resume_trial(t)
        self.base.on_deadlock(controller)


def even_cpu_distribution(max_cpu_per_trial: float = 4.0):
    """A simple resources_allocation_function: spread the cluster's CPUs
    evenly over live trials, capped (the reference's
    DistributeResources analog)."""
    import ray_tpu

    def fn(controller, trial, result, scheduler):
        live = max(1, len(controller.running_trials())
                   + len(controller.paused_trials()))
        total = ray_tpu.cluster_resources().get("CPU", 1.0)
        share = max(1.0, min(max_cpu_per_trial, total // live))
        # only the CPU share changes — accelerator/custom reservations
        # from the trial's current allocation ride along untouched
        current = dict(trial.resources or controller.tc.trial_resources)
        current["CPU"] = float(share)
        return current

    return fn
