"""Trial schedulers: FIFO, ASHA, HyperBand (ASHA-based), Median stopping,
PBT.

Parity with the reference's tune.schedulers (ref: python/ray/tune/
schedulers/ — async_hyperband.py ASHA rung logic, median_stopping_rule.py,
pbt.py exploit/explore via checkpoint swap)."""
from __future__ import annotations

import math
import random
from collections import defaultdict
from typing import Any, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def set_metric(self, metric: str, mode: str) -> None:
        self.metric = metric
        self.mode = mode

    def _score(self, result: dict) -> float:
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def on_result(self, trial, result: dict) -> str:
        return CONTINUE

    def on_complete(self, trial, result: Optional[dict]) -> None:
        pass

    def choose_action(self, controller) -> None:
        """Hook for schedulers that mutate trials (PBT)."""


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (ref: schedulers/async_hyperband.py). Rungs at
    grace_period * reduction_factor^k; a trial reaching a rung stops unless
    in the top 1/reduction_factor of completed scores at that rung."""

    def __init__(self, time_attr: str = "training_iteration",
                 grace_period: int = 1, reduction_factor: float = 3,
                 max_t: int = 100):
        self.time_attr = time_attr
        self.grace = grace_period
        self.rf = reduction_factor
        self.max_t = max_t
        self._rungs: Dict[int, List[float]] = defaultdict(list)

    def _rung_levels(self) -> List[int]:
        levels = []
        t = self.grace
        while t < self.max_t:
            levels.append(int(t))
            t *= self.rf
        return levels

    def on_result(self, trial, result: dict) -> str:
        t = int(result.get(self.time_attr, 0))
        if t >= self.max_t:
            return STOP
        score = self._score(result)
        for level in self._rung_levels():
            if t == level:
                rung = self._rungs[level]
                rung.append(score)
                k = max(1, int(len(rung) / self.rf))
                cutoff = sorted(rung, reverse=True)[k - 1]
                if score < cutoff:
                    return STOP
        return CONTINUE


ASHAScheduler = AsyncHyperBandScheduler


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best score is below the median of running averages
    (ref: schedulers/median_stopping_rule.py)."""

    def __init__(self, time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.time_attr = time_attr
        self.grace = grace_period
        self.min_samples = min_samples_required
        self._avgs: Dict[str, List[float]] = defaultdict(list)

    def on_result(self, trial, result: dict) -> str:
        t = int(result.get(self.time_attr, 0))
        score = self._score(result)
        self._avgs[trial.trial_id].append(score)
        if t <= self.grace or len(self._avgs) < self.min_samples:
            return CONTINUE
        running = [sum(v) / len(v) for v in self._avgs.values()]
        running.sort()
        median = running[len(running) // 2]
        mine = self._avgs[trial.trial_id]
        if sum(mine) / len(mine) < median and max(mine) < median:
            return STOP
        return CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (ref: schedulers/pbt.py): at each perturbation interval, bottom-
    quartile trials copy the checkpoint of a top-quartile trial (exploit)
    and perturb hyperparameters (explore). The controller performs the
    restart; we record the decision on the trial."""

    def __init__(self, time_attr: str = "training_iteration",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = dict(hyperparam_mutations or {})
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self._rng = random.Random(seed)
        self._latest: Dict[str, dict] = {}

    def on_result(self, trial, result: dict) -> str:
        self._latest[trial.trial_id] = result
        t = int(result.get(self.time_attr, 0))
        if t > 0 and t % self.interval == 0:
            trial.pbt_ready = True
        return CONTINUE

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        new = dict(config)
        for key, spec in self.mutations.items():
            if key not in new:
                continue
            if callable(spec):
                new[key] = spec()
            elif isinstance(spec, list):
                if self._rng.random() < self.resample_prob:
                    new[key] = self._rng.choice(spec)
                else:
                    cur = new[key]
                    idx = spec.index(cur) if cur in spec else 0
                    idx = max(0, min(len(spec) - 1,
                                     idx + self._rng.choice([-1, 1])))
                    new[key] = spec[idx]
            else:  # Domain
                if self._rng.random() < self.resample_prob:
                    new[key] = spec.sample(self._rng)
                else:
                    new[key] = new[key] * self._rng.choice([0.8, 1.2])
        return new

    def choose_action(self, controller) -> None:
        ready = [t for t in controller.running_trials()
                 if getattr(t, "pbt_ready", False)]
        if not ready:
            return
        scored = [(self._score(self._latest[t.trial_id]), t)
                  for t in controller.all_trials()
                  if t.trial_id in self._latest and t.status in ("RUNNING", "PAUSED")]
        if len(scored) < 2:
            for t in ready:
                t.pbt_ready = False
            return
        scored.sort(key=lambda x: x[0])
        n = len(scored)
        k = max(1, int(n * self.quantile))
        bottom = {t.trial_id for _, t in scored[:k]}
        top = [t for _, t in scored[-k:]]
        for t in ready:
            t.pbt_ready = False
            if t.trial_id in bottom:
                donor = self._rng.choice(top)
                if donor.trial_id == t.trial_id or donor.latest_checkpoint is None:
                    continue
                new_config = self._explore(donor.config)
                controller.exploit_trial(t, donor, new_config)
