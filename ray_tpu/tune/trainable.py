"""Trainable — the unit of execution for one trial.

Parity with the reference (ref: python/ray/tune/trainable/trainable.py —
class API setup/step/save_checkpoint/load_checkpoint; trainable.py:1398
save/restore; function_trainable.py runs the user function on a thread and
streams reports). The controller talks to a `_TrialRunner` actor hosting
either form behind one interface: step() -> result dict, save() -> dict,
restore(dict).
"""
from __future__ import annotations

import threading
import time
import traceback
from typing import Any, Callable, Dict, Optional

from . import session as _session


class Trainable:
    """Class-API base. Subclass and override setup/step/save_checkpoint/
    load_checkpoint."""

    def setup(self, config: Dict[str, Any]) -> None:
        self.config = config

    def step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def save_checkpoint(self) -> Dict[str, Any]:
        return {}

    def load_checkpoint(self, checkpoint: Dict[str, Any]) -> None:
        pass

    def cleanup(self) -> None:
        pass

    def reset_config(self, new_config: Dict[str, Any]) -> bool:
        """Return True if the trainable can adopt new_config in place
        (PBT fast path; ref: trainable.py reset_config)."""
        return False


class FunctionRunner:
    """Adapts a function trainable to the step() interface: the function
    runs on a daemon thread, `tune.report` enqueues results, step() pops
    one per call."""

    def __init__(self, fn: Callable, config: Dict[str, Any], checkpoint):
        self._sess = _session._init_session(checkpoint)
        self._config = config

        def runner():
            try:
                fn(config)
            except BaseException as e:  # noqa: BLE001 — surfaced via step()
                self._sess.error = e
                traceback.print_exc()
            finally:
                self._sess.done.set()

        self._thread = threading.Thread(target=runner, daemon=True)
        self._thread.start()
        self._last_checkpoint: Optional[Dict[str, Any]] = None

    def step(self, timeout: float = 600.0) -> Optional[Dict[str, Any]]:
        import queue

        deadline = time.monotonic() + timeout
        while True:
            try:
                item = self._sess.results.get(timeout=0.05)
            except queue.Empty:
                item = None
            if item is not None:
                if item.get("checkpoint") is not None:
                    ck = item["checkpoint"]
                    self._last_checkpoint = (
                        ck.to_dict() if hasattr(ck, "to_dict") else dict(ck))
                return item["metrics"]
            if self._sess.done.is_set() and self._sess.results.empty():
                if self._sess.error is not None:
                    raise self._sess.error
                return None  # function returned: trial complete
            if time.monotonic() > deadline:
                raise TimeoutError("function trainable produced no report")

    def save_checkpoint(self) -> Dict[str, Any]:
        return dict(self._last_checkpoint or {})

    def cleanup(self) -> None:
        _session._shutdown_session()


class _TrialRunner:
    """Actor hosting one trial (function or class trainable)."""

    def __init__(self, trainable: Any, config: Dict[str, Any],
                 checkpoint: Optional[Dict[str, Any]] = None):
        import cloudpickle

        if isinstance(trainable, bytes):
            trainable = cloudpickle.loads(trainable)
        self._config = dict(config)
        ck = dict(checkpoint or {})
        self._iteration = int(ck.pop("__iteration__", 0))
        if isinstance(trainable, type) and issubclass(trainable, Trainable):
            self._kind = "class"
            self._obj = trainable()
            self._obj.setup(dict(config))
            if ck:
                self._obj.load_checkpoint(ck)
        else:
            self._kind = "function"
            self._obj = FunctionRunner(trainable, dict(config), ck or None)

    def step(self) -> Optional[Dict[str, Any]]:
        """One training iteration; None when the trainable is finished."""
        result = self._obj.step()
        if result is None:
            return None
        self._iteration += 1
        result = dict(result)
        result.setdefault("training_iteration", self._iteration)
        result.setdefault("trial_iteration", self._iteration)
        return result

    def save(self) -> Dict[str, Any]:
        ck = self._obj.save_checkpoint()
        return {"__iteration__": self._iteration, **(ck or {})}

    def restore(self, checkpoint: Dict[str, Any]) -> bool:
        ck = dict(checkpoint)
        self._iteration = int(ck.pop("__iteration__", self._iteration))
        if self._kind == "class":
            self._obj.load_checkpoint(ck)
            return True
        return False  # function trainables restart via a fresh actor

    def reset(self, new_config: Dict[str, Any]) -> bool:
        if self._kind == "class":
            ok = self._obj.reset_config(dict(new_config))
            if ok:
                self._config = dict(new_config)
            return bool(ok)
        return False

    def stop(self) -> bool:
        try:
            self._obj.cleanup()
        except Exception:
            pass
        return True
