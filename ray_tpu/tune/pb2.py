"""PB2 — Population Based Bandits.

ref: python/ray/tune/schedulers/pb2.py (Parker-Holder et al. 2020,
"Provably Efficient Online Hyperparameter Optimization with
Population-Based Bandits"). Same exploit step as PBT (bottom-quantile
trials adopt a top trial's checkpoint), but the EXPLORE step replaces
random perturbation with a GP-UCB acquisition: a Gaussian process is fit
on (time, hyperparams) -> reward improvement observations collected from
the whole population, and the new config maximizes UCB over the bounded
search box. Numpy GP (RBF kernel, Cholesky) — no sklearn/GPy dependency,
matching the repo's no-new-deps rule.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .schedulers import PopulationBasedTraining


class _GP:
    """Minimal RBF-kernel GP regressor with a white-noise term."""

    def __init__(self, lengthscale: float = 0.3, noise: float = 1e-2):
        self.ls = lengthscale
        self.noise = noise
        self._X: Optional[np.ndarray] = None
        self._alpha: Optional[np.ndarray] = None
        self._L: Optional[np.ndarray] = None

    def _k(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return np.exp(-0.5 * d2 / (self.ls ** 2))

    def fit(self, X: np.ndarray, y: np.ndarray) -> None:
        K = self._k(X, X) + self.noise * np.eye(len(X))
        self._L = np.linalg.cholesky(K)
        self._alpha = np.linalg.solve(
            self._L.T, np.linalg.solve(self._L, y))
        self._X = X

    def predict(self, Xs: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        Ks = self._k(Xs, self._X)
        mu = Ks @ self._alpha
        v = np.linalg.solve(self._L, Ks.T)
        var = np.clip(1.0 - (v ** 2).sum(0), 1e-9, None)
        return mu, np.sqrt(var)


class PB2(PopulationBasedTraining):
    """Drop-in beside PopulationBasedTraining: pass continuous
    `hyperparam_bounds` ({key: (low, high)}) instead of mutation specs.
    Controller interaction (exploit_trial) is inherited unchanged."""

    def __init__(self, time_attr: str = "training_iteration",
                 perturbation_interval: int = 4,
                 hyperparam_bounds: Optional[Dict[str, Sequence[float]]] = None,
                 quantile_fraction: float = 0.25,
                 ucb_kappa: float = 2.0,
                 num_candidates: int = 256,
                 log_scale: bool = True,
                 seed: Optional[int] = None):
        if not hyperparam_bounds:
            raise ValueError("PB2 needs hyperparam_bounds={key: (lo, hi)}")
        super().__init__(time_attr=time_attr,
                         perturbation_interval=perturbation_interval,
                         hyperparam_mutations={},
                         quantile_fraction=quantile_fraction,
                         seed=seed)
        self.bounds = {k: (float(lo), float(hi))
                       for k, (lo, hi) in hyperparam_bounds.items()}
        self.kappa = ucb_kappa
        self.num_candidates = num_candidates
        self.log_scale = log_scale
        self._np_rng = np.random.default_rng(seed)
        # observations: (t, config-vector) -> score improvement since the
        # trial's previous perturbation window
        self._obs_X: List[List[float]] = []
        self._obs_y: List[float] = []
        self._prev_score: Dict[str, float] = {}
        self._prev_cfg: Dict[str, tuple] = {}

    # -- observation collection ---------------------------------------------

    def _vec(self, t: float, config: Dict[str, Any]) -> List[float]:
        out = [t]
        for k in sorted(self.bounds):
            lo, hi = self.bounds[k]
            v = float(config.get(k, lo))
            if self.log_scale and lo > 0:
                import math

                v = (math.log(v) - math.log(lo)) / max(
                    math.log(hi) - math.log(lo), 1e-12)
            else:
                v = (v - lo) / max(hi - lo, 1e-12)
            out.append(min(max(v, 0.0), 1.0))
        return out

    def on_result(self, trial, result: dict) -> str:
        action = super().on_result(trial, result)
        if getattr(trial, "pbt_ready", False):
            score = self._score(result)
            cfg_sig = tuple(sorted(
                (k, float(trial.config.get(k, 0.0))) for k in self.bounds))
            prev = self._prev_score.get(trial.trial_id)
            # an exploit swaps in the donor's checkpoint AND a new config:
            # the resulting score jump is NOT improvement attributable to
            # the config — start a fresh window instead of recording it
            same_cfg = self._prev_cfg.get(trial.trial_id) == cfg_sig
            if prev is not None and same_cfg \
                    and np.isfinite(score) and np.isfinite(prev):
                t = float(result.get(self.time_attr, 0))
                self._obs_X.append(self._vec(t, trial.config))
                self._obs_y.append(score - prev)
            self._prev_score[trial.trial_id] = score
            self._prev_cfg[trial.trial_id] = cfg_sig
        return action

    # -- GP-UCB explore -------------------------------------------------------

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        keys = sorted(self.bounds)
        cands = self._np_rng.random((self.num_candidates, len(keys)))
        if len(self._obs_y) >= 4:
            X = np.asarray(self._obs_X)
            y = np.asarray(self._obs_y)
            t_now = X[:, 0].max()
            # normalize: time to [0,1] over the window, y standardized
            tden = max(t_now, 1.0)
            Xn = X.copy()
            Xn[:, 0] = X[:, 0] / tden
            ystd = y.std() or 1.0
            yn = (y - y.mean()) / ystd
            gp = _GP()
            try:
                gp.fit(Xn, yn)
                Xc = np.concatenate(
                    [np.full((len(cands), 1), t_now / tden), cands], axis=1)
                mu, sd = gp.predict(Xc)
                best = int(np.argmax(mu + self.kappa * sd))
            except np.linalg.LinAlgError:
                best = int(self._np_rng.integers(len(cands)))
        else:
            best = int(self._np_rng.integers(len(cands)))
        new = dict(config)
        for i, k in enumerate(keys):
            lo, hi = self.bounds[k]
            u = float(cands[best, i])
            if self.log_scale and lo > 0:
                import math

                new[k] = math.exp(
                    math.log(lo) + u * (math.log(hi) - math.log(lo)))
            else:
                new[k] = lo + u * (hi - lo)
        return new
