"""Function-trainable session: `tune.report` / `tune.get_checkpoint`.

Parity with the reference's session bridge (ref: python/ray/tune/
trainable/function_trainable.py — function trainables report through
`session.report`, results are consumed by the controller one iteration at
a time). Each trial actor owns a dedicated worker process, so the session
is a module global guarded by a lock.
"""
from __future__ import annotations

import queue
import threading
from typing import Any, Optional


class _Session:
    def __init__(self, checkpoint=None):
        self.results: "queue.Queue[Any]" = queue.Queue()
        self.checkpoint = checkpoint
        self.done = threading.Event()
        self.error: Optional[BaseException] = None


_lock = threading.Lock()
_session: Optional[_Session] = None


def _init_session(checkpoint=None) -> _Session:
    global _session
    with _lock:
        _session = _Session(checkpoint)
        return _session


def _get_session() -> Optional[_Session]:
    return _session


def _shutdown_session() -> None:
    global _session
    with _lock:
        _session = None


def report(metrics: Optional[dict] = None, checkpoint=None, **kw) -> None:
    """Report one iteration's metrics (and optionally a checkpoint) from a
    function trainable (ref: tune's session.report)."""
    s = _get_session()
    if s is None:
        raise RuntimeError(
            "tune.report() called outside a Tune trial; run this function "
            "via Tuner(...).fit()")
    m = dict(metrics or {})
    m.update(kw)
    s.results.put({"metrics": m, "checkpoint": checkpoint})


def get_checkpoint():
    """The checkpoint this trial should resume from (or None)."""
    s = _get_session()
    return s.checkpoint if s is not None else None
