"""Experiment/checkpoint syncing to remote storage.

ref: python/ray/tune/syncer.py:345 (Syncer/_ BackgroundSyncer uploading
trial + experiment state to cloud storage via pyarrow/fsspec
filesystems). Here: an fsspec-backed Syncer pushes the experiment
directory (experiment_state.pkl + per-trial checkpoints) to an
`upload_dir` URI after every driver snapshot, and `pull_experiment`
restores it onto a local path so `Tuner.restore` resumes a sweep on a
fresh machine. Any fsspec protocol works (file://, gs://, s3://,
memory:// in tests); plain local paths sync with stdlib copy.
"""
from __future__ import annotations

import os
import shutil
import time
import traceback
from typing import Optional


from ..util.fs import split_fs_url as _split  # shared with the spill tier


class Syncer:
    """Push a local experiment dir to remote storage (and pull it back).

    Incremental: files are re-uploaded only when size or mtime-tracked
    content changed since the last push (driver-side cache)."""

    def __init__(self, upload_dir: str, sync_period_s: float = 5.0):
        self.upload_dir = upload_dir.rstrip("/")
        self.period = sync_period_s
        self._fs, self._root = _split(self.upload_dir)
        self._last_sync = 0.0
        self._pushed: dict = {}  # relpath -> (size, mtime)
        # uploads run off-thread: the tune controller calls sync_up from
        # its single-threaded event loop, and a slow cloud push must not
        # stall trial scheduling (the reference's _BackgroundSyncer)
        from concurrent.futures import ThreadPoolExecutor

        self._executor = ThreadPoolExecutor(1, thread_name_prefix="syncer")
        self._inflight = None

    # -- push ----------------------------------------------------------------

    def sync_up(self, local_dir: str, force: bool = False) -> bool:
        now = time.monotonic()
        if not force and now - self._last_sync < self.period:
            return False
        if force:
            # final sync: wait out any background push, then run inline
            # so callers observe a complete mirror on return
            if self._inflight is not None:
                try:
                    self._inflight.result(timeout=300)
                except Exception:  # noqa: BLE001
                    traceback.print_exc()
                self._inflight = None
            self._last_sync = now
            try:
                self._push_dir(local_dir)
                return True
            except Exception:  # noqa: BLE001 — syncing is best-effort
                traceback.print_exc()
                return False
        if self._inflight is not None and not self._inflight.done():
            return False  # previous push still draining
        self._last_sync = now

        def push():
            try:
                self._push_dir(local_dir)
            except Exception:  # noqa: BLE001
                traceback.print_exc()

        self._inflight = self._executor.submit(push)
        return True

    def _push_dir(self, local_dir: str) -> None:
        base = os.path.abspath(local_dir)
        for root, _dirs, files in os.walk(base):
            for f in files:
                if f.endswith(".tmp"):
                    continue
                src = os.path.join(root, f)
                rel = os.path.relpath(src, base)
                st = os.stat(src)
                sig = (st.st_size, st.st_mtime_ns)
                if self._pushed.get(rel) == sig:
                    continue
                dst = f"{self._root}/{rel}"
                if self._fs is None:
                    os.makedirs(os.path.dirname(dst), exist_ok=True)
                    shutil.copy2(src, dst)
                else:
                    self._fs.makedirs(os.path.dirname(dst), exist_ok=True)
                    self._fs.put_file(src, dst)
                self._pushed[rel] = sig

    def close(self) -> None:
        """Release the background upload thread (the controller calls
        this after the final force-sync)."""
        if self._inflight is not None:
            try:
                self._inflight.result(timeout=60)
            except Exception:  # noqa: BLE001
                traceback.print_exc()
            self._inflight = None
        self._executor.shutdown(wait=False)

    # -- pull ----------------------------------------------------------------

    def sync_down(self, local_dir: str) -> None:
        """Mirror the remote experiment dir onto local_dir."""
        os.makedirs(local_dir, exist_ok=True)
        if self._fs is None:
            for root, _dirs, files in os.walk(self._root):
                for f in files:
                    src = os.path.join(root, f)
                    rel = os.path.relpath(src, self._root)
                    dst = os.path.join(local_dir, rel)
                    os.makedirs(os.path.dirname(dst), exist_ok=True)
                    shutil.copy2(src, dst)
            return
        for src in self._fs.find(self._root):
            rel = os.path.relpath(src, self._root)
            dst = os.path.join(local_dir, rel)
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            self._fs.get_file(src, dst)


def pull_experiment(upload_dir: str, local_dir: str) -> str:
    """Restore a synced experiment onto local_dir; returns the local
    experiment path to hand to Tuner.restore."""
    Syncer(upload_dir).sync_down(local_dir)
    return local_dir
