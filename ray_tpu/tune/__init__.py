"""ray_tpu.tune — hyperparameter sweep engine.

Parity with the reference's Ray Tune (ref: python/ray/tune/__init__.py):
Tuner/TuneConfig/ResultGrid, function + class Trainables with
tune.report(), search spaces (grid/random/domains), trial schedulers
(FIFO/ASHA/MedianStopping/PBT). Trials run as actors in per-trial
placement groups under a single-threaded controller event loop.
"""
from ..train.config import RunConfig
from .pb2 import PB2
from .syncer import Syncer, pull_experiment
from .schedulers import (ASHAScheduler, AsyncHyperBandScheduler,
                         FIFOScheduler, HyperBandForBOHB,
                         HyperBandScheduler, MedianStoppingRule,
                         PopulationBasedTraining,
                         ResourceChangingScheduler, TrialScheduler,
                         even_cpu_distribution)
from .search import (BasicVariantGenerator, Choice, ConcurrencyLimiter,
                     Domain, GPSearcher,
                     GridSearch, LogUniform, Randint, RandomSearch,
                     Repeater, Searcher, TPESearcher, TuneBOHB, Uniform, choice,
                     grid_search, loguniform, randint, uniform)
from .session import get_checkpoint, report
from .trainable import Trainable
from .tuner import (ResultGrid, Trial, TuneConfig, TuneController, Tuner,
                    run)

__all__ = [
    "Tuner", "TuneConfig", "TuneController", "ResultGrid", "Trial", "run",
    "Trainable", "report", "get_checkpoint", "RunConfig",
    "TrialScheduler", "FIFOScheduler", "AsyncHyperBandScheduler",
    "ASHAScheduler", "HyperBandScheduler", "HyperBandForBOHB",
    "MedianStoppingRule", "PB2", "PopulationBasedTraining",
    "Syncer", "pull_experiment",
    "Searcher", "BasicVariantGenerator", "RandomSearch", "TPESearcher",
    "TuneBOHB", "GPSearcher", "ConcurrencyLimiter", "Repeater",
    "ResourceChangingScheduler", "even_cpu_distribution",
    "Domain", "Uniform", "LogUniform", "Randint", "Choice", "GridSearch",
    "uniform", "loguniform", "randint", "choice", "grid_search",
]
