"""Search spaces and suggestion generation.

Parity with the reference's tune.search (ref: python/ray/tune/search/ —
grid/random via basic_variant.py; sample.py domains: uniform/loguniform/
choice/randint; external searchers Optuna/HyperOpt/... are optional deps
there and are represented here by the Searcher plug-in base)."""
from __future__ import annotations

import itertools
import math
import random
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, List, Optional


# ---- sampling domains (ref: tune/search/sample.py) -------------------------

class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


@dataclass
class Uniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


@dataclass
class LogUniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        return math.exp(rng.uniform(math.log(self.low), math.log(self.high)))


@dataclass
class Randint(Domain):
    low: int
    high: int

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


@dataclass
class Choice(Domain):
    values: List[Any]

    def sample(self, rng):
        return rng.choice(self.values)


@dataclass
class GridSearch:
    values: List[Any]


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> Randint:
    return Randint(low, high)


def choice(values: List[Any]) -> Choice:
    return Choice(list(values))


def grid_search(values: List[Any]) -> Dict[str, Any]:
    return {"grid_search": list(values)}


def _is_grid(v) -> bool:
    return isinstance(v, dict) and set(v.keys()) == {"grid_search"}


# ---- searchers -------------------------------------------------------------

class Searcher:
    """Suggestion plug-in (ref: tune/search/searcher.py). suggest() returns a
    config dict, None when exhausted, or Searcher.PENDING when the searcher
    cannot produce a config RIGHT NOW but is not done (the reference's
    Searcher.FINISHED/None distinction; the tuner retries PENDING on its
    next loop tick). on_trial_complete feeds results back (used by
    adaptive searchers)."""

    PENDING = "__searcher_pending__"

    def set_space(self, param_space: Dict[str, Any], metric: str, mode: str):
        self.param_space = param_space
        self.metric = metric
        self.mode = mode

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: dict) -> None:
        """Intermediate feedback (every reported result) — budget-aware
        searchers (GP/BOHB) refine on rung results, not just finals."""

    def on_trial_complete(self, trial_id: str, result: Optional[dict]) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """Grid x random sampling (ref: tune/search/basic_variant.py). Grid keys
    expand combinatorially; Domain keys sample per trial; num_samples
    multiplies the whole expansion."""

    def __init__(self, num_samples: int = 1, seed: Optional[int] = None):
        self.num_samples = num_samples
        self._rng = random.Random(seed)
        # materialized at first suggest (not a lazy generator) so the
        # searcher pickles cleanly into experiment-state snapshots and
        # resumes exactly where it left off
        self._configs: Optional[List[Dict[str, Any]]] = None
        self._pos = 0

    def _expand(self) -> Iterator[Dict[str, Any]]:
        space = self.param_space
        grid_keys = [k for k, v in space.items() if _is_grid(v)]
        grids = [space[k]["grid_search"] for k in grid_keys]
        for _ in range(self.num_samples):
            for combo in itertools.product(*grids) if grids else [()]:
                cfg = {}
                for k, v in space.items():
                    if _is_grid(v):
                        continue
                    cfg[k] = v.sample(self._rng) if isinstance(v, Domain) else v
                cfg.update(dict(zip(grid_keys, combo)))
                yield cfg

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._configs is None:
            self._configs = list(self._expand())
        if self._pos >= len(self._configs):
            return None
        cfg = self._configs[self._pos]
        self._pos += 1
        return cfg


class RandomSearch(BasicVariantGenerator):
    """Alias emphasizing pure sampling (no grid keys)."""


class TPESearcher(Searcher):
    """Native Tree-structured Parzen Estimator (the model-based searcher
    the reference gets from Optuna/BOHB external deps — ref:
    tune/search/optuna/optuna_search.py, bohb/bohb_search.py TuneBOHB;
    Bergstra et al. 2011). No external dependency: per-dimension KDEs.

    Observations split into good (top `gamma` quantile) and bad; each
    candidate is scored by sum_k log(l_k(x)/g_k(x)) where l/g are
    Gaussian KDEs (continuous dims, log-space for LogUniform) or
    Laplace-smoothed frequencies (categorical dims) over the good/bad
    sets; the best of `n_candidates` samples drawn from l() wins."""

    def __init__(self, n_initial_points: int = 8, gamma: float = 0.25,
                 n_candidates: int = 24, seed: Optional[int] = None):
        self.n_initial = n_initial_points
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._rng = random.Random(seed)
        self._obs: List[tuple] = []  # (config, score) — score: higher=better
        self._suggested: Dict[str, Dict[str, Any]] = {}

    # -- domain helpers ----------------------------------------------------

    def _dims(self):
        out = {}
        for k, v in self.param_space.items():
            if _is_grid(v):
                out[k] = Choice(list(v["grid_search"]))
            elif isinstance(v, Domain):
                out[k] = v
        return out

    @staticmethod
    def _to_real(dom, x):
        return math.log(x) if isinstance(dom, LogUniform) else float(x)

    @staticmethod
    def _from_real(dom, z):
        if isinstance(dom, LogUniform):
            z = math.exp(z)
            return min(max(z, dom.low), dom.high)
        if isinstance(dom, Randint):
            return min(max(int(round(z)), dom.low), dom.high - 1)
        return min(max(z, dom.low), dom.high)

    def _kde_sample(self, dom, values: List[float]):
        """Draw from a KDE mixture over observed (real-space) values."""
        lo = self._to_real(dom, dom.low)
        hi = self._to_real(dom, dom.high if not isinstance(dom, Randint)
                           else dom.high - 1)
        if not values:
            return self._rng.uniform(lo, hi)
        bw = max((hi - lo) / max(1.0, math.sqrt(len(values))), 1e-12)
        center = self._rng.choice(values)
        return min(max(self._rng.gauss(center, bw), lo), hi)

    @staticmethod
    def _kde_logpdf(values: List[float], bw: float, x: float) -> float:
        if not values:
            return 0.0
        acc = 0.0
        for c in values:
            acc += math.exp(-0.5 * ((x - c) / bw) ** 2)
        return math.log(max(acc / (len(values) * bw), 1e-300))

    # -- Searcher API ------------------------------------------------------

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        dims = self._dims()
        fixed = {k: v for k, v in self.param_space.items() if k not in dims}
        if len(self._obs) < self.n_initial:
            cfg = {k: d.sample(self._rng) for k, d in dims.items()}
        else:
            ranked = sorted(self._obs, key=lambda o: -o[1])
            n_good = max(1, int(math.ceil(len(ranked) * self.gamma)))
            good = [c for c, _ in ranked[:n_good]]
            bad = [c for c, _ in ranked[n_good:]] or good
            best_cfg, best_score = None, -math.inf
            for _ in range(self.n_candidates):
                cand = {}
                logratio = 0.0
                for k, dom in dims.items():
                    if isinstance(dom, Choice):
                        counts_g = {v: 1.0 for v in map(repr, dom.values)}
                        counts_b = dict(counts_g)
                        for c in good:
                            counts_g[repr(c.get(k))] = counts_g.get(
                                repr(c.get(k)), 1.0) + 1.0
                        for c in bad:
                            counts_b[repr(c.get(k))] = counts_b.get(
                                repr(c.get(k)), 1.0) + 1.0
                        zg = sum(counts_g.values())
                        zb = sum(counts_b.values())
                        # sample categorical from the good distribution
                        r = self._rng.random() * zg
                        pick = dom.values[-1]
                        for v in dom.values:
                            r -= counts_g[repr(v)]
                            if r <= 0:
                                pick = v
                                break
                        cand[k] = pick
                        logratio += math.log(
                            (counts_g[repr(pick)] / zg)
                            / (counts_b[repr(pick)] / zb))
                    else:
                        gv = [self._to_real(dom, c[k]) for c in good
                              if k in c]
                        bv = [self._to_real(dom, c[k]) for c in bad
                              if k in c]
                        lo = self._to_real(dom, dom.low)
                        hi = self._to_real(
                            dom, dom.high if not isinstance(dom, Randint)
                            else dom.high - 1)
                        bw_g = max((hi - lo) / max(1.0, math.sqrt(
                            max(1, len(gv)))), 1e-12)
                        bw_b = max((hi - lo) / max(1.0, math.sqrt(
                            max(1, len(bv)))), 1e-12)
                        z = self._kde_sample(dom, gv)
                        cand[k] = self._from_real(dom, z)
                        logratio += (self._kde_logpdf(gv, bw_g, z)
                                     - self._kde_logpdf(bv, bw_b, z))
                if logratio > best_score:
                    best_score, best_cfg = logratio, cand
            cfg = best_cfg or {}
        cfg.update(fixed)
        self._suggested[trial_id] = dict(cfg)
        return cfg

    def on_trial_complete(self, trial_id: str,
                          result: Optional[dict]) -> None:
        cfg = self._suggested.pop(trial_id, None)
        if cfg is None or not result or self.metric not in result:
            return
        score = float(result[self.metric])
        if self.mode == "min":
            score = -score
        self._obs.append((cfg, score))


class GPSearcher(Searcher):
    """Native Bayesian optimization: the PB2 GP promoted to a standalone
    searcher (ref: tune/search/bayesopt/bayesopt_search.py — there via
    the bayesian-optimization package; here the same RBF-GP that powers
    PB2, with Expected Improvement over a random candidate pool).

    Configs encode into the unit cube (LogUniform in log space, Randint
    scaled, Choice as index); y is z-normalized per fit. Budget-aware
    observations (on_trial_result) keep only each trial's HIGHEST-budget
    score, so pairing this searcher with HyperBand brackets gives the
    BOHB shape: the model trains on the deepest evaluations available
    (ref: tune/search/bohb/bohb_search.py)."""

    def __init__(self, n_initial_points: int = 6, n_candidates: int = 256,
                 kappa_ei: float = 0.01, seed: Optional[int] = None):
        self.n_initial = n_initial_points
        self.n_candidates = n_candidates
        self.xi = kappa_ei
        self._rng = random.Random(seed)
        self._np_rng = None  # numpy rng, created lazily (pickle-friendly)
        # trial_id -> (config, score, budget); model uses the latest
        self._obs: Dict[str, tuple] = {}
        self._suggested: Dict[str, Dict[str, Any]] = {}

    # encoding -----------------------------------------------------------

    def _dims(self):
        out = {}
        for k, v in self.param_space.items():
            if _is_grid(v):
                out[k] = Choice(list(v["grid_search"]))
            elif isinstance(v, Domain):
                out[k] = v
        return out

    @staticmethod
    def _unit(dom, x) -> float:
        if isinstance(dom, LogUniform):
            lo, hi = math.log(dom.low), math.log(dom.high)
            return (math.log(x) - lo) / max(hi - lo, 1e-12)
        if isinstance(dom, Uniform):
            return (x - dom.low) / max(dom.high - dom.low, 1e-12)
        if isinstance(dom, Randint):
            return (x - dom.low) / max(dom.high - 1 - dom.low, 1)
        if isinstance(dom, Choice):
            vals = list(map(repr, dom.values))
            return vals.index(repr(x)) / max(len(vals) - 1, 1)
        return 0.0

    @staticmethod
    def _from_unit(dom, u: float):
        u = min(max(u, 0.0), 1.0)
        if isinstance(dom, LogUniform):
            lo, hi = math.log(dom.low), math.log(dom.high)
            return math.exp(lo + u * (hi - lo))
        if isinstance(dom, Uniform):
            return dom.low + u * (dom.high - dom.low)
        if isinstance(dom, Randint):
            return dom.low + int(round(u * (dom.high - 1 - dom.low)))
        if isinstance(dom, Choice):
            return dom.values[int(round(u * (len(dom.values) - 1)))]
        return u

    # Searcher API -------------------------------------------------------

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        import numpy as np

        dims = self._dims()
        fixed = {k: v for k, v in self.param_space.items() if k not in dims}
        obs = list(self._obs.values())
        if len(obs) < self.n_initial or not dims:
            cfg = {k: d.sample(self._rng) for k, d in dims.items()}
        else:
            from .pb2 import _GP

            keys = sorted(dims)
            X = np.array([[self._unit(dims[k], c.get(k)) for k in keys]
                          for c, _, _ in obs], np.float64)
            y = np.array([s for _, s, _ in obs], np.float64)
            mu_y, sd_y = float(y.mean()), float(y.std() or 1.0)
            gp = _GP(lengthscale=0.25)
            gp.fit(X, (y - mu_y) / sd_y)
            if self._np_rng is None:
                self._np_rng = np.random.default_rng(
                    self._rng.randrange(2 ** 31))
            cand = self._np_rng.random((self.n_candidates, len(keys)))
            mu, sd = gp.predict(cand)
            best = float(((y - mu_y) / sd_y).max())
            # Expected Improvement (maximization in normalized space)
            z = (mu - best - self.xi) / np.maximum(sd, 1e-9)
            phi = np.exp(-0.5 * z * z) / math.sqrt(2 * math.pi)
            Phi = 0.5 * (1.0 + np.vectorize(math.erf)(z / math.sqrt(2)))
            ei = (mu - best - self.xi) * Phi + sd * phi
            u = cand[int(np.argmax(ei))]
            cfg = {k: self._from_unit(dims[k], float(u[i]))
                   for i, k in enumerate(keys)}
        cfg.update(fixed)
        self._suggested[trial_id] = dict(cfg)
        return cfg

    def _record(self, trial_id: str, result: Optional[dict]) -> None:
        cfg = self._suggested.get(trial_id)
        if cfg is None or not result or self.metric not in result:
            return
        score = float(result[self.metric])
        if self.mode == "min":
            score = -score
        budget = float(result.get("training_iteration", 0))
        prev = self._obs.get(trial_id)
        if prev is None or budget >= prev[2]:
            self._obs[trial_id] = (cfg, score, budget)

    def on_trial_result(self, trial_id: str, result: dict) -> None:
        self._record(trial_id, result)

    def on_trial_complete(self, trial_id: str,
                          result: Optional[dict]) -> None:
        self._record(trial_id, result)
        self._suggested.pop(trial_id, None)


class ConcurrencyLimiter(Searcher):
    """Caps how many of the wrapped searcher's suggestions run at once
    (ref: tune/search/concurrency_limiter.py). Model-based searchers
    (GP/TPE) suggest better when each batch of results lands before the
    next batch of suggestions; this enforces that independently of the
    cluster's trial capacity."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        if max_concurrent < 1:
            raise ValueError("max_concurrent must be >= 1")
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    def set_space(self, param_space, metric, mode):
        super().set_space(param_space, metric, mode)
        self.searcher.set_space(param_space, metric, mode)

    def suggest(self, trial_id: str):
        if len(self._live) >= self.max_concurrent:
            return Searcher.PENDING
        cfg = self.searcher.suggest(trial_id)
        if cfg is not None and cfg is not Searcher.PENDING:
            self._live.add(trial_id)
        return cfg

    def on_trial_result(self, trial_id: str, result: dict) -> None:
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id: str,
                          result: Optional[dict]) -> None:
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result)

    def on_experiment_end(self) -> None:
        hook = getattr(self.searcher, "on_experiment_end", None)
        if hook is not None:
            hook()


class Repeater(Searcher):
    """Runs every underlying suggestion `repeat` times and reports the
    MEAN metric back to the wrapped searcher (ref:
    tune/search/repeater.py — variance reduction for noisy objectives;
    the wrapped searcher sees one averaged observation per config)."""

    def __init__(self, searcher: Searcher, repeat: int = 3):
        if repeat < 1:
            raise ValueError("repeat must be >= 1")
        self.searcher = searcher
        self.repeat = repeat
        # lead_tid -> {cfg, dispatched, completed, scores}
        self._groups: Dict[str, Dict[str, Any]] = {}
        self._open: Optional[str] = None   # lead of the filling group
        self._group_of: Dict[str, str] = {}

    def set_space(self, param_space, metric, mode):
        super().set_space(param_space, metric, mode)
        self.searcher.set_space(param_space, metric, mode)

    def suggest(self, trial_id: str):
        if self._open is None:
            cfg = self.searcher.suggest(trial_id)
            if cfg is None or cfg is Searcher.PENDING:
                return cfg
            self._open = trial_id
            self._groups[trial_id] = {"cfg": dict(cfg), "dispatched": 0,
                                      "completed": 0, "scores": []}
        lead = self._open
        g = self._groups[lead]
        g["dispatched"] += 1
        self._group_of[trial_id] = lead
        if g["dispatched"] >= self.repeat:
            self._open = None
        return dict(g["cfg"])

    def on_trial_result(self, trial_id: str, result: dict) -> None:
        pass  # intermediate results are per-repeat noise; hold them back

    def _maybe_close(self, lead: str, final: bool = False) -> None:
        g = self._groups.get(lead)
        if g is None or g["completed"] < g["dispatched"]:
            return
        if g["dispatched"] < self.repeat and not final:
            return  # group still filling (or truncated — see flush)
        # report the mean; an all-errored group resolves the inner
        # searcher's pending suggestion with None instead of leaking it
        if g["scores"]:
            mean = sum(g["scores"]) / len(g["scores"])
            self.searcher.on_trial_complete(lead, {self.metric: mean})
        else:
            self.searcher.on_trial_complete(lead, None)
        self._groups.pop(lead, None)
        if self._open == lead:
            self._open = None

    def on_trial_complete(self, trial_id: str,
                          result: Optional[dict]) -> None:
        lead = self._group_of.pop(trial_id, None)
        if lead is None or lead not in self._groups:
            return
        g = self._groups[lead]
        g["completed"] += 1
        if result and self.metric in result:
            g["scores"].append(float(result[self.metric]))
        self._maybe_close(lead)

    def on_experiment_end(self) -> None:
        """Flush partially-dispatched groups (a num_samples budget can
        truncate the final group) so the wrapped searcher still sees
        their observations and drops its pending state."""
        for lead in list(self._groups):
            self._maybe_close(lead, final=True)


# the BOHB pairing name (model-based half; pair with HyperBandForBOHB)
TuneBOHB = TPESearcher
