"""Search spaces and suggestion generation.

Parity with the reference's tune.search (ref: python/ray/tune/search/ —
grid/random via basic_variant.py; sample.py domains: uniform/loguniform/
choice/randint; external searchers Optuna/HyperOpt/... are optional deps
there and are represented here by the Searcher plug-in base)."""
from __future__ import annotations

import itertools
import math
import random
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, List, Optional


# ---- sampling domains (ref: tune/search/sample.py) -------------------------

class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


@dataclass
class Uniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


@dataclass
class LogUniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        return math.exp(rng.uniform(math.log(self.low), math.log(self.high)))


@dataclass
class Randint(Domain):
    low: int
    high: int

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


@dataclass
class Choice(Domain):
    values: List[Any]

    def sample(self, rng):
        return rng.choice(self.values)


@dataclass
class GridSearch:
    values: List[Any]


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> Randint:
    return Randint(low, high)


def choice(values: List[Any]) -> Choice:
    return Choice(list(values))


def grid_search(values: List[Any]) -> Dict[str, Any]:
    return {"grid_search": list(values)}


def _is_grid(v) -> bool:
    return isinstance(v, dict) and set(v.keys()) == {"grid_search"}


# ---- searchers -------------------------------------------------------------

class Searcher:
    """Suggestion plug-in (ref: tune/search/searcher.py). suggest() returns a
    config dict or None when exhausted; on_trial_complete feeds results back
    (used by adaptive searchers)."""

    def set_space(self, param_space: Dict[str, Any], metric: str, mode: str):
        self.param_space = param_space
        self.metric = metric
        self.mode = mode

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: Optional[dict]) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """Grid x random sampling (ref: tune/search/basic_variant.py). Grid keys
    expand combinatorially; Domain keys sample per trial; num_samples
    multiplies the whole expansion."""

    def __init__(self, num_samples: int = 1, seed: Optional[int] = None):
        self.num_samples = num_samples
        self._rng = random.Random(seed)
        self._iter: Optional[Iterator[Dict[str, Any]]] = None

    def _expand(self) -> Iterator[Dict[str, Any]]:
        space = self.param_space
        grid_keys = [k for k, v in space.items() if _is_grid(v)]
        grids = [space[k]["grid_search"] for k in grid_keys]
        for _ in range(self.num_samples):
            for combo in itertools.product(*grids) if grids else [()]:
                cfg = {}
                for k, v in space.items():
                    if _is_grid(v):
                        continue
                    cfg[k] = v.sample(self._rng) if isinstance(v, Domain) else v
                cfg.update(dict(zip(grid_keys, combo)))
                yield cfg

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._iter is None:
            self._iter = self._expand()
        try:
            return next(self._iter)
        except StopIteration:
            return None


class RandomSearch(BasicVariantGenerator):
    """Alias emphasizing pure sampling (no grid keys)."""
