"""DatasetPipeline — windowed, optionally repeating execution.

ref: python/ray/data/dataset_pipeline.py (Dataset.window /
Dataset.repeat -> DatasetPipeline; per-window lazy transforms;
pipeline.split for per-worker ingest). A pipeline is a sequence of
WINDOWS — each a small Dataset over a slice of the source's read tasks —
executed one window at a time, so a transform chain over a dataset far
larger than cluster memory holds only one window's blocks live, and
epoch-style training loops (`ds.window(...).repeat()`) re-read from
source instead of materializing everything.
"""
from __future__ import annotations

import math
from typing import Any, Callable, Dict, Iterator, List, Optional

from .plan import SourceOp


class DatasetPipeline:
    """A lazy sequence of Dataset windows. `length` is None for infinite
    pipelines (repeat() without a count)."""

    def __init__(self, window_factories: Callable[[], Iterator],
                 length: Optional[int]):
        # window_factories: zero-arg callable returning a FRESH iterator
        # of () -> Dataset thunks (so the pipeline can be consumed, and
        # split children can iterate, independently)
        self._factories = window_factories
        self.length = length

    # -- construction --------------------------------------------------------

    @staticmethod
    def from_windows(thunks: List[Callable[[], Any]]) -> "DatasetPipeline":
        return DatasetPipeline(lambda: iter(list(thunks)), len(thunks))

    # -- transforms (applied per window, lazily) -----------------------------

    def _map_windows(self, f: Callable[[Any], Any],
                     length: Optional[int] = -1) -> "DatasetPipeline":
        factories = self._factories

        def gen():
            for thunk in factories():
                yield (lambda t=thunk: f(t()))
        return DatasetPipeline(gen,
                               self.length if length == -1 else length)

    def map_batches(self, fn, **kw) -> "DatasetPipeline":
        return self._map_windows(lambda ds: ds.map_batches(fn, **kw))

    def map(self, fn, **kw) -> "DatasetPipeline":
        return self._map_windows(lambda ds: ds.map(fn, **kw))

    def filter(self, fn, **kw) -> "DatasetPipeline":
        return self._map_windows(lambda ds: ds.filter(fn, **kw))

    def random_shuffle(self, **kw) -> "DatasetPipeline":
        """Per-window shuffle (the reference's pipeline shuffle scope:
        global shuffles don't fit a windowed execution model)."""
        return self._map_windows(lambda ds: ds.random_shuffle(**kw))

    def repeat(self, times: Optional[int] = None) -> "DatasetPipeline":
        """Cycle the pipeline's windows `times` times (None = forever) —
        the epoch loop (ref: dataset_pipeline.py repeat)."""
        if self.length is None:
            raise ValueError("cannot repeat an already-infinite pipeline")
        factories = self._factories

        def gen():
            if times is None:
                while True:
                    yield from factories()
            else:
                for _ in range(times):
                    yield from factories()
        return DatasetPipeline(
            gen, None if times is None else self.length * times)

    # -- consumption ---------------------------------------------------------

    def iter_windows(self) -> Iterator:
        for thunk in self._factories():
            yield thunk()

    def iter_batches(self, **kw) -> Iterator[Dict]:
        for ds in self.iter_windows():
            yield from ds.iter_batches(**kw)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for ds in self.iter_windows():
            yield from ds.iter_rows()

    def take(self, n: int) -> List[Any]:
        out: List[Any] = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def count(self) -> int:
        if self.length is None:
            raise ValueError("cannot count an infinite pipeline")
        return sum(ds.count() for ds in self.iter_windows())

    def split(self, n: int) -> List["DatasetPipeline"]:
        """Round-robin windows into n sub-pipelines — one per Train
        worker (ref: dataset_pipeline.py split)."""
        if self.length is None:
            raise ValueError("split an infinite pipeline before repeat()")
        thunks = list(self._factories())
        return [DatasetPipeline.from_windows(thunks[i::n])
                for i in range(n)]

    def __repr__(self):
        n = "inf" if self.length is None else self.length
        return f"DatasetPipeline(windows={n})"


def window_dataset(ds, *, blocks_per_window: int = 10) -> DatasetPipeline:
    """Dataset.window implementation: slice the source's read tasks into
    windows; the transform tail re-applies per window."""
    from .dataset import Dataset

    from .plan import AllToAllOp

    if not ds._ops or not isinstance(ds._ops[0], SourceOp):
        raise ValueError("window() needs a source-rooted dataset")
    if getattr(ds, "_limit", None) is not None:
        raise ValueError(
            "window() after limit() is unsupported: the limit is applied "
            "at iteration time and would silently vanish per window — "
            "call limit() on the pipeline output instead (take(n))")
    for op in ds._ops[1:]:
        if isinstance(op, AllToAllOp):
            raise ValueError(
                f"window() cannot re-apply the GLOBAL op "
                f"{getattr(op, 'name', op.kind)!r} per window (a windowed "
                f"sort/groupby/shuffle would be window-local and silently "
                f"wrong); apply it per window AFTER windowing "
                f"(pipe.random_shuffle()) or materialize first")
    src: SourceOp = ds._ops[0]
    tail = ds._ops[1:]
    if src.read_fns is None and src.refs is None \
            and getattr(src, "thunk", None) is not None:
        # deferred source (union/zip/split view): windowing needs a
        # concrete block list — run the upstream plans once, into a
        # LOCAL SourceOp (mutating the shared op would freeze these
        # blocks into every other derived view of `ds`)
        src = SourceOp(refs=list(src.thunk()), name=src.name)
    items = src.read_fns if src.read_fns is not None else src.refs
    use_fns = src.read_fns is not None
    nwin = max(1, math.ceil(len(items) / blocks_per_window))
    thunks = []
    for w in range(nwin):
        chunk = items[w * blocks_per_window:(w + 1) * blocks_per_window]
        op = (SourceOp(read_fns=chunk, name=f"{src.name}[w{w}]") if use_fns
              else SourceOp(refs=chunk, name=f"{src.name}[w{w}]"))
        thunks.append(lambda op=op: Dataset([op] + list(tail), ds._ctx))
    return DatasetPipeline.from_windows(thunks)


def repeat_dataset(ds, times: Optional[int] = None) -> DatasetPipeline:
    """Dataset.repeat: the whole dataset as one window, cycled (each
    epoch re-executes the read tasks — nothing is pinned across
    epochs)."""
    return DatasetPipeline.from_windows([lambda: ds]).repeat(times)
