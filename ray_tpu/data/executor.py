"""Streaming executor for ray_tpu.data.

Equivalent of the reference's StreamingExecutor driving a PhysicalOperator
DAG over tasks/actor pools with bounded in-flight blocks (ref:
python/ray/data/_internal/execution/streaming_executor.py:49, loop in
streaming_executor_state.py; actor pools:
_internal/execution/operators/actor_pool_map_operator.py:34).

Design here: the logical plan is fused into *segments* — a source (read
tasks or materialized block refs) followed by a chain of block→block
transforms — separated by all-to-all barriers (repartition / shuffle).
Each segment streams: inputs are submitted as remote tasks with a bounded
in-flight window (backpressure); outputs yield in plan order by default
(DataContext.preserve_order) or completion order, and flow into the next
segment without a barrier.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Iterator, List, Optional

import cloudpickle
import numpy as np

import ray_tpu
from ray_tpu.core import runtime as _rt
from ray_tpu.util import metrics as _metrics

from .block import (Block, block_concat, block_num_rows, block_select,
                    block_slice)

# byte-budget backpressure instruments (docs/DATA.md). Worker-process
# executions register these in the worker's registry and their values
# ship to the head on the standard metrics_push delta path.
_G_BYTES_INFLIGHT = _metrics.Gauge(
    "ray_tpu_data_bytes_inflight",
    "bytes held by live streaming data segments in this process: "
    "completed-but-unemitted blocks at store-reported size plus "
    "in-flight tasks at the segment's running average")
_C_BLOCKS_EMITTED = _metrics.Counter(
    "ray_tpu_data_blocks_emitted_total",
    "blocks emitted downstream by streaming data segments")

# process-wide ledger behind the gauge: every live segment window posts
# its outstanding-bytes delta here, so one scrape sees the sum over
# concurrent executions without the windows sharing any other state
_LEDGER_LOCK = threading.Lock()
_LEDGER_BYTES = 0


def _ledger_post(delta: int) -> None:
    global _LEDGER_BYTES
    if delta == 0:
        return
    with _LEDGER_LOCK:
        _LEDGER_BYTES = max(0, _LEDGER_BYTES + delta)
        _G_BYTES_INFLIGHT.set(float(_LEDGER_BYTES))


def _ref_size_hint(ref) -> Optional[int]:
    """Store-reported serialized size of a completed block ref, when the
    process can see the object table (driver); None -> estimate."""
    rt = _rt.maybe_runtime()
    hint = getattr(rt, "object_size_hint", None)
    if hint is None:
        return None
    try:
        return hint(ref.id)
    except Exception:
        return None


class _ByteWindow:
    """Per-segment byte accounting for admit-against-budget
    backpressure (DataContext.target_max_bytes_inflight; the way
    serve/llm's BlockPool admits KV blocks — all-or-nothing against a
    fixed budget, the admitter blocks rather than overshoots).

    Completed-but-unemitted blocks count at their store-reported size —
    including the ordered-mode head-of-line buffer, which the block
    window already throttles but whose BYTES were previously invisible.
    In-flight tasks count at the segment's running-average block size
    (their real size is unknowable until the store seals them)."""

    # in-flight estimate before the first completion is measured
    _BOOTSTRAP_EST = 1 << 16

    def __init__(self, stats: "ExecStats", budget: int):
        self.budget = max(0, int(budget))
        self.stats = stats
        self._sizes: dict = {}     # emit index -> measured bytes
        self._buffered = 0         # completed-but-unemitted bytes
        self._avg = 0.0
        self._seen = 0
        self._posted = 0           # this window's share of the ledger

    def outstanding(self, n_in_flight: int) -> int:
        est = self._avg if self._seen else float(self._BOOTSTRAP_EST)
        return self._buffered + int(est * n_in_flight)

    def admit(self, n_in_flight: int) -> bool:
        """May one more task be submitted? Always true with the budget
        off; with everything drained (nothing in flight or buffered)
        always true, so one oversized block can never wedge a stream."""
        if self.budget <= 0:
            return True
        if n_in_flight == 0 and self._buffered == 0:
            return True
        return self.outstanding(n_in_flight) < self.budget

    def on_complete(self, ref, idx: int) -> None:
        size = _ref_size_hint(ref)
        if size is None:
            size = int(self._avg) if self._seen else self._BOOTSTRAP_EST
        self._sizes[idx] = size
        self._seen += 1
        self._avg += (size - self._avg) / self._seen
        self._buffered += size

    def on_emit(self, idx: int) -> None:
        self._buffered -= self._sizes.pop(idx, 0)
        self.stats.on_emit()
        _C_BLOCKS_EMITTED.inc()

    def publish(self, n_in_flight: int) -> None:
        now = self.outstanding(n_in_flight)
        self.stats.on_bytes(now)
        _ledger_post(now - self._posted)
        self._posted = now

    def close(self) -> None:
        _ledger_post(-self._posted)
        self._posted = 0

# ---------------------------------------------------------------------------
# remote helpers (module-level so the function blob is exported once)
# ---------------------------------------------------------------------------


def _apply_chain(chain_blob: bytes, block: Block) -> Block:
    fns: List[Callable[[Block], Block]] = cloudpickle.loads(chain_blob)
    for fn in fns:
        block = fn(block)
    return block


def _read_and_apply(read_blob: bytes, chain_blob: bytes) -> Block:
    read_fn = cloudpickle.loads(read_blob)
    return _apply_chain(chain_blob, read_fn())


def _count_rows(block: Block) -> int:
    return block_num_rows(block)


def _slice_concat(plan: List[tuple], *blocks: Block) -> Block:
    """plan: [(input_index, start, stop), ...] into *blocks."""
    parts = [block_slice(blocks[i], a, b) for (i, a, b) in plan]
    return block_concat(parts)


def _shuffle_map(block: Block, n: int, seed: int):
    rng = np.random.default_rng(seed)
    n_rows = block_num_rows(block)
    assign = rng.integers(0, n, size=n_rows)
    outs = [block_select(block, np.nonzero(assign == j)[0]) for j in range(n)]
    return tuple(outs) if n > 1 else outs[0]


def _shuffle_reduce(seed: int, *parts: Block) -> Block:
    merged = block_concat(parts)
    n_rows = block_num_rows(merged)
    perm = np.random.default_rng(seed).permutation(n_rows)
    return block_select(merged, perm)


def _merge_parts(*parts: Block) -> Block:
    """Intermediate merge of one reducer's parts from one map wave
    (push-based shuffle's merge stage)."""
    return block_concat(parts)


# -- sort (range partition; ref: planner/exchange/sort_task_spec.py) --------


def _sort_sample(block: Block, key: str, k: int) -> np.ndarray:
    col = block[key]
    if len(col) <= k:
        return np.asarray(col)
    idx = np.linspace(0, len(col) - 1, k).astype(np.int64)
    return np.asarray(col)[idx]


def _sort_map(block: Block, key: str, boundaries: np.ndarray):
    """Range-partition one block by the sampled boundaries."""
    col = np.asarray(block[key])
    assign = np.searchsorted(boundaries, col, side="right")
    n = len(boundaries) + 1
    outs = [block_select(block, np.nonzero(assign == j)[0])
            for j in range(n)]
    return tuple(outs) if n > 1 else outs[0]


def _sort_reduce(key: str, descending: bool, *parts: Block) -> Block:
    merged = block_concat(parts)
    if not merged:
        # every map task routed zero rows into this range partition
        # (skewed/constant keys): an empty block sorts to itself
        return merged
    order = np.argsort(np.asarray(merged[key]), kind="stable")
    if descending:
        order = order[::-1]
    return block_select(merged, order)


# -- groupby/aggregate (hash partition of per-block partial states;
#    ref: _internal/planner/exchange + push_based_shuffle reduce stage) ----


def _bucket_of(values: np.ndarray, n: int) -> np.ndarray:
    """Deterministic cross-process bucket assignment per key value."""
    import zlib

    v = np.asarray(values)
    if v.dtype.kind in "iub":
        # Fibonacci multiplicative hash spreads adjacent ints
        return ((v.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15))
                >> np.uint64(40)).astype(np.int64) % n
    if v.dtype.kind == "f":
        v = v.astype(np.float64) + 0.0  # -0.0 -> 0.0: equal keys, one bucket
        return _bucket_of(v.view(np.uint64), n)
    return np.asarray([zlib.crc32(repr(x).encode()) % n for x in v],
                      np.int64)


def _partial_agg(block: Block, key: str, specs: List[tuple]) -> Block:
    """-> partial-state block: unique keys + accumulator columns."""
    keys = np.asarray(block[key])
    uniq, inv = np.unique(keys, return_inverse=True)
    out: Block = {key: uniq}
    for i, (op, col) in enumerate(specs):
        if op == "count":
            out[f"__a{i}_c"] = np.bincount(inv, minlength=len(uniq))
            continue
        vals = np.asarray(block[col], np.float64)
        if op in ("sum", "mean"):
            out[f"__a{i}_s"] = np.bincount(inv, weights=vals,
                                           minlength=len(uniq))
            if op == "mean":
                out[f"__a{i}_c"] = np.bincount(inv, minlength=len(uniq))
        elif op in ("min", "max"):
            fill = np.inf if op == "min" else -np.inf
            acc = np.full(len(uniq), fill)
            fn = np.minimum if op == "min" else np.maximum
            fn.at(acc, inv, vals)
            out[f"__a{i}_m"] = acc
        else:
            raise ValueError(f"unknown aggregate {op!r}")
    return out


def _groupby_map(block: Block, key: str, specs: List[tuple], n: int):
    partial = _partial_agg(block, key, specs)
    assign = _bucket_of(partial[key], n)
    outs = [block_select(partial, np.nonzero(assign == j)[0])
            for j in range(n)]
    return tuple(outs) if n > 1 else outs[0]


def _groupby_reduce(key: str, specs: List[tuple], *parts: Block) -> Block:
    merged = block_concat([p for p in parts if block_num_rows(p)])
    if not merged:
        return {key: np.asarray([])}
    keys = np.asarray(merged[key])
    uniq, inv = np.unique(keys, return_inverse=True)
    out: Block = {key: uniq}
    for i, (op, col) in enumerate(specs):
        name = f"{op}()" if col is None else f"{op}({col})"
        if op == "count":
            out[name] = np.bincount(
                inv, weights=merged[f"__a{i}_c"],
                minlength=len(uniq)).astype(np.int64)
        elif op == "sum":
            out[name] = np.bincount(inv, weights=merged[f"__a{i}_s"],
                                    minlength=len(uniq))
        elif op == "mean":
            s = np.bincount(inv, weights=merged[f"__a{i}_s"],
                            minlength=len(uniq))
            c = np.bincount(inv, weights=merged[f"__a{i}_c"],
                            minlength=len(uniq))
            out[name] = s / np.maximum(c, 1)
        else:  # min / max
            fill = np.inf if op == "min" else -np.inf
            acc = np.full(len(uniq), fill)
            fn = np.minimum if op == "min" else np.maximum
            fn.at(acc, inv, np.asarray(merged[f"__a{i}_m"]))
            out[name] = acc
    return out


class _BlockWorker:
    """Actor-pool worker for map_batches(compute=ActorPoolStrategy(...)).
    Holds the deserialized chain so per-block calls skip unpickling; a
    class-based UDF's constructor runs once here (ref:
    actor_pool_map_operator.py _MapWorker)."""

    def __init__(self, chain_blob: bytes):
        self._fns = cloudpickle.loads(chain_blob)

    def apply(self, block: Block) -> Block:
        for fn in self._fns:
            block = fn(block)
        return block

    def ping(self) -> bool:
        return True


# ---------------------------------------------------------------------------
# execution
# ---------------------------------------------------------------------------


class ExecStats:
    def __init__(self):
        self.lock = threading.Lock()
        self.tasks_submitted = 0
        self.blocks_produced = 0
        self.blocks_emitted = 0
        self.peak_in_flight = 0
        self.bytes_inflight = 0
        self.peak_bytes_inflight = 0

    def on_submit(self, in_flight: int) -> None:
        with self.lock:
            self.tasks_submitted += 1
            self.peak_in_flight = max(self.peak_in_flight, in_flight)

    def on_emit(self) -> None:
        with self.lock:
            self.blocks_emitted += 1

    def on_bytes(self, outstanding: int) -> None:
        with self.lock:
            self.bytes_inflight = outstanding
            self.peak_bytes_inflight = max(self.peak_bytes_inflight,
                                           outstanding)

    def summary(self) -> dict:
        return {"tasks_submitted": self.tasks_submitted,
                "blocks_produced": self.blocks_produced,
                "blocks_emitted": self.blocks_emitted,
                "peak_in_flight": self.peak_in_flight,
                "bytes_inflight": self.bytes_inflight,
                "peak_bytes_inflight": self.peak_bytes_inflight}


class StreamingExecutor:
    """Drives one dataset execution; yields output block refs."""

    def __init__(self, context, epoch: int = 0):
        self.ctx = context
        # epoch index threaded into windowed-shuffle seeds: Dataset
        # .iter_epochs() re-executes the plan with epoch=e so every
        # windowed_shuffle stage reshuffles deterministically per epoch
        self.epoch = int(epoch)
        self.stats = ExecStats()
        self._apply_remote = ray_tpu.remote(_apply_chain)
        self._read_remote = ray_tpu.remote(_read_and_apply)

    # -- segment drivers -----------------------------------------------------

    def _stream_tasks(self, inputs: Iterator[Any], chain_blob: bytes,
                      reads: bool) -> Iterator[Any]:
        """Submit one task per input with a bounded in-flight window.
        With ctx.preserve_order (default), blocks emit in PLAN order —
        completed-out-of-order refs buffer until their turn. Admission
        is gated by the block-count window AND (when set) the byte
        budget: ctx.target_max_bytes_inflight against this segment's
        outstanding bytes."""
        cap = max(1, int(self.ctx.max_in_flight_blocks))
        ordered = bool(self.ctx.preserve_order)
        bw = _ByteWindow(self.stats,
                         getattr(self.ctx, "target_max_bytes_inflight", 0))
        in_flight: dict = {}   # ref -> submission index
        ready: dict = {}       # submission index -> ref (ordered mode)
        submitted = 0
        next_emit = 0
        inputs = iter(inputs)
        exhausted = False
        try:
            while True:
                # buffered-but-unemitted refs count against the window: one
                # stalled head-of-line block must throttle submission, not
                # let the whole dataset materialize behind it
                while not exhausted and len(in_flight) + len(ready) < cap \
                        and bw.admit(len(in_flight)):
                    try:
                        item = next(inputs)
                    except StopIteration:
                        exhausted = True
                        break
                    if reads:
                        ref = self._read_remote.remote(item, chain_blob)
                    else:
                        ref = self._apply_remote.remote(chain_blob, item)
                    in_flight[ref] = submitted
                    submitted += 1
                    self.stats.on_submit(len(in_flight))
                    bw.publish(len(in_flight))
                if not in_flight:
                    if exhausted:
                        for idx in sorted(ready):
                            ref = ready.pop(idx)
                            bw.on_emit(idx)
                            bw.publish(0)
                            yield ref
                        return
                    continue
                done, _ = ray_tpu.wait(list(in_flight), num_returns=1,
                                       timeout=None, fetch_local=False)
                for ref in done:
                    idx = in_flight.pop(ref)
                    self.stats.blocks_produced += 1
                    bw.on_complete(ref, idx)
                    if not ordered:
                        bw.on_emit(idx)
                        bw.publish(len(in_flight))
                        yield ref
                        continue
                    ready[idx] = ref
                    while next_emit in ready:
                        out = ready.pop(next_emit)
                        bw.on_emit(next_emit)
                        next_emit += 1
                        bw.publish(len(in_flight))
                        yield out
                bw.publish(len(in_flight))
        finally:
            bw.close()

    def _stream_actor_pool(self, inputs: Iterator[Any], chain_blob: bytes,
                           pool_size: int,
                           resources: Optional[dict]) -> Iterator[Any]:
        cls = ray_tpu.remote(_BlockWorker)
        opts = {}
        if resources:
            opts["num_cpus"] = resources.get("CPU", 1.0)
            extra = {k: v for k, v in resources.items() if k != "CPU"}
            if extra:
                opts["resources"] = extra
        actors = [cls.options(**opts).remote(chain_blob) if opts
                  else cls.remote(chain_blob) for _ in range(pool_size)]
        bw = _ByteWindow(self.stats,
                         getattr(self.ctx, "target_max_bytes_inflight", 0))
        try:
            ray_tpu.get([a.ping.remote() for a in actors], timeout=60)
            per_actor_cap = max(
                1, int(self.ctx.max_in_flight_blocks) // pool_size) + 1
            ordered = bool(self.ctx.preserve_order)
            in_flight: dict = {}   # ref -> (actor index, submission index)
            ready: dict = {}
            submitted = 0
            next_emit = 0
            load = {i: 0 for i in range(pool_size)}
            inputs = iter(inputs)
            exhausted = False
            while True:
                while not exhausted:
                    i = min(load, key=lambda k: load[k])
                    if load[i] >= per_actor_cap or len(ready) >= len(actors) \
                            * per_actor_cap or not bw.admit(len(in_flight)):
                        break  # window full (incl. head-of-line buffer)
                    try:
                        item = next(inputs)
                    except StopIteration:
                        exhausted = True
                        break
                    ref = actors[i].apply.remote(item)
                    in_flight[ref] = (i, submitted)
                    submitted += 1
                    load[i] += 1
                    self.stats.on_submit(len(in_flight))
                    bw.publish(len(in_flight))
                if not in_flight:
                    if exhausted:
                        for idx in sorted(ready):
                            ref = ready.pop(idx)
                            bw.on_emit(idx)
                            bw.publish(0)
                            yield ref
                        return
                    continue
                done, _ = ray_tpu.wait(list(in_flight), num_returns=1,
                                       timeout=None, fetch_local=False)
                for ref in done:
                    i, idx = in_flight.pop(ref)
                    load[i] -= 1
                    self.stats.blocks_produced += 1
                    bw.on_complete(ref, idx)
                    if not ordered:
                        bw.on_emit(idx)
                        bw.publish(len(in_flight))
                        yield ref
                        continue
                    ready[idx] = ref
                    while next_emit in ready:
                        out = ready.pop(next_emit)
                        bw.on_emit(next_emit)
                        next_emit += 1
                        bw.publish(len(in_flight))
                        yield out
                bw.publish(len(in_flight))
        finally:
            bw.close()
            for a in actors:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass

    # -- barriers ------------------------------------------------------------

    def _repartition(self, refs: List[Any], n: int) -> List[Any]:
        counts = ray_tpu.get(
            [ray_tpu.remote(_count_rows).remote(r) for r in refs], timeout=300)
        total = sum(counts)
        slice_remote = ray_tpu.remote(_slice_concat)
        offsets = [0]
        for c in counts:
            offsets.append(offsets[-1] + c)
        outs = []
        for j in range(n):
            lo = total * j // n
            hi = total * (j + 1) // n
            plan, args = [], []
            for i, c in enumerate(counts):
                a, b = max(lo, offsets[i]), min(hi, offsets[i + 1])
                if a < b:
                    plan.append((len(args), a - offsets[i], b - offsets[i]))
                    args.append(refs[i])
            outs.append(slice_remote.remote(plan, *args))
        return outs

    # maps per merge wave for the push-based path (ref:
    # push_based_shuffle.py _MergeTaskScheduler merge_factor)
    _SHUFFLE_MERGE_FACTOR = 8

    def _random_shuffle(self, refs: List[Any], seed: Optional[int]) -> List[Any]:
        n = len(refs)
        if n == 0:
            return refs
        base = seed if seed is not None else 0x5EED
        map_remote = ray_tpu.remote(_shuffle_map)
        reduce_remote = ray_tpu.remote(_shuffle_reduce)
        M = self._SHUFFLE_MERGE_FACTOR
        if n <= M:
            # small: simple pull shuffle, every reducer takes N parts
            parts = [map_remote.options(num_returns=n).remote(r, n, base + i)
                     for i, r in enumerate(refs)]
            if n == 1:
                cols = [[p] for p in parts]
            else:
                cols = [[parts[i][j] for i in range(n)] for j in range(n)]
            return [reduce_remote.remote(base ^ (j * 2654435761), *col)
                    for j, col in enumerate(cols)]
        # Push-based two-stage shuffle (ref: _internal/push_based_shuffle.py):
        # maps run in waves of M; each wave's per-reducer parts merge
        # IMMEDIATELY into one block per (wave, reducer), so the N x N
        # intermediate object matrix never exists at once — per-wave parts
        # become garbage as soon as their merge lands, in-flight objects
        # stay O(M*N), and wave w+1's maps overlap wave w's merges through
        # ordinary async scheduling.
        merge_remote = ray_tpu.remote(_merge_parts)
        merged_cols: List[List[Any]] = [[] for _ in range(n)]
        for w0 in range(0, n, M):
            wave = refs[w0:w0 + M]
            parts = [map_remote.options(num_returns=n).remote(
                r, n, base + w0 + i) for i, r in enumerate(wave)]
            for j in range(n):
                # n > M >= 8 here, so num_returns is always a list
                col = [parts[i][j] for i in range(len(wave))]
                merged_cols[j].append(merge_remote.remote(*col))
        return [reduce_remote.remote(base ^ (j * 2654435761),
                                     *merged_cols[j])
                for j in range(n)]

    # -- windowed shuffle (streaming, not a barrier) -------------------------

    def _windowed_shuffle(self, stream: Iterator[Any], window: int,
                          seed: Optional[int]) -> Iterator[Any]:
        """Buffer up to `window` upstream block refs, emit their rows
        globally permuted within the window, repeat. Replaces the
        all-to-all random_shuffle barrier for training loops: the first
        shuffled block is available after W upstream blocks land, and
        peak held refs stay O(W) instead of O(dataset).

        Every RNG in the stage is seeded by the tuple (base seed, epoch,
        window index, task index) via np SeedSequence, so the emitted
        row order is a pure function of (seed, epoch) — same seed+epoch
        replays bit-identically, the next epoch reshuffles."""
        window = max(1, int(window))
        base = seed if seed is not None else 0x5EED
        map_remote = ray_tpu.remote(_shuffle_map)
        reduce_remote = ray_tpu.remote(_shuffle_reduce)

        def shuffle_one(refs: List[Any], widx: int) -> List[Any]:
            w = len(refs)
            parts = [map_remote.options(num_returns=w).remote(
                r, w, [base, self.epoch, widx, i])
                for i, r in enumerate(refs)]
            if w == 1:
                cols = [[parts[0]]]
            else:
                cols = [[parts[i][j] for i in range(w)] for j in range(w)]
            return [reduce_remote.remote([base, self.epoch, widx, w + j],
                                         *col)
                    for j, col in enumerate(cols)]

        buf: List[Any] = []
        widx = 0
        for ref in stream:
            buf.append(ref)
            if len(buf) >= window:
                # emit refs (futures) immediately: downstream pulls
                # overlap this window's shuffle tasks and the upstream
                # segment's production of the next window
                for out in shuffle_one(buf, widx):
                    yield out
                buf = []
                widx += 1
        if buf:
            for out in shuffle_one(buf, widx):
                yield out

    def _sort(self, refs: List[Any], key: str, descending: bool) -> List[Any]:
        """Distributed sort: sample -> range partition -> per-partition
        sort (ref: planner/exchange/sort_task_spec.py SortTaskSpec)."""
        n = len(refs)
        if n == 0:
            return refs
        sample_remote = ray_tpu.remote(_sort_sample)
        samples = ray_tpu.get(
            [sample_remote.remote(r, key, 64) for r in refs], timeout=300)
        vals = np.concatenate([s for s in samples if len(s)]) \
            if any(len(s) for s in samples) else np.asarray([])
        if len(vals) == 0 or n == 1:
            reduce_remote = ray_tpu.remote(_sort_reduce)
            return [reduce_remote.remote(key, descending, r) for r in refs]
        boundaries = np.quantile(np.sort(vals),
                                 [j / n for j in range(1, n)]) \
            if vals.dtype.kind == "f" else np.sort(vals)[
                [min(len(vals) - 1, len(vals) * j // n)
                 for j in range(1, n)]]
        map_remote = ray_tpu.remote(_sort_map)
        reduce_remote = ray_tpu.remote(_sort_reduce)
        parts = [map_remote.options(num_returns=n).remote(r, key, boundaries)
                 for r in refs]
        # n > 1 here: the single-partition case early-returned above
        cols = [[parts[i][j] for i in range(n)] for j in range(n)]
        out = [reduce_remote.remote(key, descending, *col) for col in cols]
        # ascending partitions ordered low->high; descending reverses
        return out[::-1] if descending else out

    def _groupby(self, refs: List[Any], key: str,
                 specs: List[tuple]) -> List[Any]:
        n = len(refs)
        if n == 0:
            return refs
        map_remote = ray_tpu.remote(_groupby_map)
        reduce_remote = ray_tpu.remote(_groupby_reduce)
        parts = [map_remote.options(num_returns=n).remote(r, key, specs, n)
                 for r in refs]
        cols = ([[p] for p in parts] if n == 1
                else [[parts[i][j] for i in range(n)] for j in range(n)])
        return [reduce_remote.remote(key, specs, *col) for col in cols]

    # -- plan driver ---------------------------------------------------------

    def execute(self, segments: List[dict]) -> Iterator[Any]:
        """segments: produced by plan.build_segments(). Each is a dict:
        {source: ('reads', [blobs]) | ('refs', [refs]) | ('barrier', op),
         chain: bytes, compute: None | (pool_size, resources)}"""
        stream: Optional[Iterator[Any]] = None
        for seg in segments:
            kind, payload = seg["source"]
            if kind == "reads":
                # the map chain is fused into the read task itself
                stream = self._stream_tasks(iter(payload), seg["chain"],
                                            reads=True)
                continue
            if kind == "refs":
                inputs: Iterator[Any] = iter(payload)
            elif kind == "thunk":
                # deferred source (union/split views): the upstream
                # dataset plans execute now, on the driver
                inputs = iter(payload())
            elif kind == "chained":
                assert stream is not None
                inputs = stream
            elif kind == "wshuffle":
                # streaming stage: window-buffered shuffle over the
                # previous segment's stream — no materialization
                assert stream is not None
                inputs = self._windowed_shuffle(stream, payload[0],
                                                payload[1])
            elif kind == "barrier":
                op, arg = payload
                upstream = list(stream) if stream is not None else []
                if op == "repartition":
                    refs = self._repartition(upstream, arg)
                elif op == "random_shuffle":
                    refs = self._random_shuffle(upstream, arg)
                elif op == "sort":
                    refs = self._sort(upstream, arg[0], arg[1])
                elif op == "groupby":
                    refs = self._groupby(upstream, arg[0], arg[1])
                else:
                    raise ValueError(f"unknown barrier {op}")
                inputs = iter(refs)
            else:  # pragma: no cover
                raise ValueError(kind)
            if seg["identity"]:
                stream = inputs
            elif seg["compute"] is not None:
                size, res = seg["compute"]
                stream = self._stream_actor_pool(inputs, seg["chain"],
                                                 size, res)
            else:
                stream = self._stream_tasks(inputs, seg["chain"], reads=False)
        assert stream is not None
        return stream
