"""TFRecord datasource — no TensorFlow dependency.

The reference reads TFRecords through tf.data / tf.train.Example (ref:
python/ray/data/read_api.py read_tfrecords,
data/_internal/datasource/tfrecords_datasource.py). This image ships no
TensorFlow, so both layers are implemented directly:

- the TFRecord framing: each record is
  u64 length | u32 masked-crc32c(length) | data | u32 masked-crc32c(data)
- the tf.train.Example payload: a protobuf Example{features: Features{
  feature: map<string, Feature>}} where Feature is a oneof
  {bytes_list, float_list, int64_list}. The subset of protobuf wire
  format needed (varint, length-delimited, fixed32/64, packed repeats)
  is ~100 lines and decoded here without any protobuf runtime.

CRCs are verified on read (torn/corrupt records raise), matching the
reference's integrity behavior.
"""
from __future__ import annotations

import struct
from typing import Any, Dict, Iterator, List

import numpy as np

# crc32c (Castagnoli); zlib.crc32 is crc32b — wrong polynomial for
# TFRecords, so a small table-driven implementation lives here
_CRC_TABLE = None


def _crc32c(data: bytes) -> int:
    global _CRC_TABLE
    if _CRC_TABLE is None:
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (0x82F63B78 ^ (c >> 1)) if (c & 1) else (c >> 1)
            table.append(c)
        _CRC_TABLE = table
    crc = 0xFFFFFFFF
    tbl = _CRC_TABLE
    for b in data:
        crc = tbl[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF)


# ---------------------------------------------------------------------------
# protobuf wire-format subset
# ---------------------------------------------------------------------------


def _read_varint(buf: bytes, pos: int):
    out = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def _write_varint(out: bytearray, v: int) -> None:
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _fields(buf: bytes) -> Iterator[tuple]:
    """Yield (field_number, wire_type, value) over a protobuf message."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        field, wt = key >> 3, key & 7
        if wt == 0:  # varint
            v, pos = _read_varint(buf, pos)
        elif wt == 1:  # fixed64
            v = buf[pos:pos + 8]
            pos += 8
        elif wt == 2:  # length-delimited
            ln, pos = _read_varint(buf, pos)
            v = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:  # fixed32
            v = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, v


def _decode_feature(buf: bytes):
    """tf.train.Feature: oneof bytes_list=1 / float_list=2 / int64_list=3."""
    for field, _wt, v in _fields(buf):
        if field == 1:  # BytesList{value: repeated bytes = 1}
            return [fv for f2, _w, fv in _fields(v) if f2 == 1]
        if field == 2:  # FloatList{value: repeated float = 1, packed}
            out: List[float] = []
            for f2, w2, fv in _fields(v):
                if f2 != 1:
                    continue
                if w2 == 2:  # packed
                    out.extend(struct.unpack(f"<{len(fv) // 4}f", fv))
                else:
                    out.append(struct.unpack("<f", fv)[0])
            return out
        if field == 3:  # Int64List{value: repeated int64 = 1, packed}
            out = []
            for f2, w2, fv in _fields(v):
                if f2 != 1:
                    continue
                if w2 == 2:
                    pos = 0
                    while pos < len(fv):
                        iv, pos = _read_varint(fv, pos)
                        out.append(iv - (1 << 64) if iv >= (1 << 63) else iv)
                else:
                    out.append(fv - (1 << 64) if fv >= (1 << 63) else fv)
            return out
    return []


def decode_example(buf: bytes) -> Dict[str, Any]:
    """tf.train.Example -> {name: list-of-values}."""
    out: Dict[str, Any] = {}
    for field, _wt, v in _fields(buf):          # Example{features = 1}
        if field != 1:
            continue
        for f2, _w2, fv in _fields(v):          # Features{feature map = 1}
            if f2 != 1:
                continue
            name = value = None
            for f3, _w3, mv in _fields(fv):     # map entry {key=1, value=2}
                if f3 == 1:
                    name = mv.decode()
                elif f3 == 2:
                    value = _decode_feature(mv)
            if name is not None:
                out[name] = value
    return out


def encode_example(features: Dict[str, Any]) -> bytes:
    """{name: value(s)} -> tf.train.Example bytes (bytes/float/int64 lists
    inferred from the python types) — the test/round-trip half."""
    def ld(out: bytearray, field: int, payload: bytes) -> None:
        _write_varint(out, (field << 3) | 2)
        _write_varint(out, len(payload))
        out += payload

    fmap = bytearray()
    for name, vals in features.items():
        if not isinstance(vals, (list, tuple, np.ndarray)):
            vals = [vals]
        inner = bytearray()
        first = vals[0] if len(vals) else 0
        if isinstance(first, (bytes, str)):
            blist = bytearray()
            for v in vals:
                ld(blist, 1, v.encode() if isinstance(v, str) else v)
            ld(inner, 1, bytes(blist))
        elif isinstance(first, (float, np.floating)):
            ld(inner, 2, _float_list([float(v) for v in vals]))
        else:
            ints = bytearray()
            _write_varint(ints, (1 << 3) | 2)
            payload = bytearray()
            for v in vals:
                _write_varint(payload, int(v) & ((1 << 64) - 1))
            _write_varint(ints, len(payload))
            ints += payload
            ld(inner, 3, bytes(ints))
        entry = bytearray()
        ld(entry, 1, name.encode())
        ld(entry, 2, bytes(inner))
        ld(fmap, 1, bytes(entry))
    out = bytearray()
    ld(out, 1, bytes(fmap))
    return bytes(out)


def _float_list(vals) -> bytes:
    """FloatList message body: packed repeated float, field 1."""
    packed = struct.pack(f"<{len(vals)}f", *vals)
    out = bytearray()
    _write_varint(out, (1 << 3) | 2)
    _write_varint(out, len(packed))
    out += packed
    return bytes(out)


# ---------------------------------------------------------------------------
# record-level IO
# ---------------------------------------------------------------------------


def read_tfrecord_file(path: str) -> Iterator[bytes]:
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if not header:
                return
            if len(header) < 12:
                raise ValueError(f"{path}: truncated record header")
            (length,), (crc,) = (struct.unpack("<Q", header[:8]),
                                 struct.unpack("<I", header[8:]))
            if _masked_crc(header[:8]) != crc:
                raise ValueError(f"{path}: corrupt length crc")
            data = f.read(length)
            if len(data) < length:
                raise ValueError(f"{path}: truncated record")
            (dcrc,) = struct.unpack("<I", f.read(4))
            if _masked_crc(data) != dcrc:
                raise ValueError(f"{path}: corrupt data crc")
            yield data


def write_tfrecord_file(path: str, records: List[bytes]) -> None:
    with open(path, "wb") as f:
        for data in records:
            hdr = struct.pack("<Q", len(data))
            f.write(hdr)
            f.write(struct.pack("<I", _masked_crc(hdr)))
            f.write(data)
            f.write(struct.pack("<I", _masked_crc(data)))


def tfrecords_to_block(path: str) -> Dict[str, np.ndarray]:
    """One TFRecord file of Examples -> a columnar block. Single-value
    features become scalar columns; multi-value become object columns."""
    rows = [decode_example(rec) for rec in read_tfrecord_file(path)]
    if not rows:
        return {}
    # union of feature names across ALL rows — tf.train.Example features
    # are optional, so a key absent from the first record must not drop
    # the whole column
    keys: Dict[str, None] = {}
    for r in rows:
        for k in r:
            keys.setdefault(k)
    cols: Dict[str, list] = {k: [] for k in keys}
    for r in rows:
        for k in cols:
            v = r.get(k)
            cols[k].append(v[0] if isinstance(v, list) and len(v) == 1 else v)
    out: Dict[str, np.ndarray] = {}
    for k, vals in cols.items():
        try:
            arr = np.asarray(vals)
            if arr.dtype == object:
                raise ValueError
        except Exception:
            arr = np.empty(len(vals), object)
            for i, v in enumerate(vals):
                arr[i] = v
        out[k] = arr
    return out
