"""Logical plan + fusion for ray_tpu.data.

Mirrors the reference's logical-plan → physical-plan split (ref:
python/ray/data/_internal/logical/, planner/plan_udf_map_op.py fusion):
consecutive block→block transforms fuse into one task per block; all-to-all
ops (repartition / random_shuffle) are barriers; an actor-pool compute
strategy cuts the fusion so the chain runs on the pool.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Tuple

import cloudpickle


@dataclass
class SourceOp:
    """Produces blocks: read-task callables, already-materialized refs,
    or a deferred thunk () -> [refs] (union / split views over other
    datasets: the upstream plans execute when THIS plan executes)."""
    read_fns: Optional[List[bytes]] = None   # cloudpickled () -> Block
    refs: Optional[List[Any]] = None
    thunk: Optional[Callable[[], List[Any]]] = None
    name: str = "source"
    # column-aware sources (parquet) accept a projection: called with the
    # selected column names, returns replacement read_fns that fetch only
    # those columns (optimizer.py projection pushdown)
    project: Optional[Callable[[List[str]], List[bytes]]] = None


@dataclass
class MapOp:
    """A block -> block transform, optionally on an actor pool."""
    fn: Callable  # Block -> Block
    name: str = "map"
    compute: Optional[Tuple[int, Optional[dict]]] = None  # (pool, resources)
    # row-wise content-preserving ops commute with order-only all-to-all
    # barriers (optimizer.py reordering); batch-boundary-dependent ops
    # (map_batches) must keep False
    commutes: bool = False
    # set by select_columns: the column list, for projection pushdown
    projection: Optional[List[str]] = None


@dataclass
class AllToAllOp:
    kind: str  # "repartition" | "random_shuffle"
    arg: Any = None
    name: str = "all_to_all"


@dataclass
class WindowedShuffleOp:
    """Streaming windowed shuffle (Dataset.windowed_shuffle): buffers
    `window` upstream blocks, emits their rows globally permuted by a
    seeded RNG, then moves to the next window — NOT a barrier, so the
    consumer starts pulling shuffled blocks after the first W blocks
    land instead of after the whole dataset materializes. The executor
    derives each window's RNG stream from (seed, epoch, window index),
    so iter_epochs() reshuffles deterministically per epoch."""
    window: int
    seed: Optional[int] = None
    name: str = "windowed_shuffle"


def build_segments(ops: List[Any]) -> List[dict]:
    """Fuse the op list into executor segments (see StreamingExecutor.execute)."""
    if not ops or not isinstance(ops[0], SourceOp):
        raise ValueError("plan must start with a SourceOp")
    segments: List[dict] = []
    src = ops[0]
    if src.read_fns is not None:
        pending_source = ("reads", list(src.read_fns))
    elif src.thunk is not None:
        pending_source = ("thunk", src.thunk)
    else:
        pending_source = ("refs", list(src.refs or []))
    chain: List[Callable] = []
    compute: Optional[Tuple[int, Optional[dict]]] = None

    def flush():
        nonlocal pending_source, chain, compute
        segments.append({
            "source": pending_source,
            "chain": cloudpickle.dumps(list(chain)),
            "identity": not chain,
            "compute": compute,
        })
        chain = []
        compute = None

    for op in ops[1:]:
        if isinstance(op, MapOp):
            if op.compute is not None:
                # actor-pool op: cut fusion before and run the pool segment
                if chain or pending_source[0] == "reads":
                    flush()
                    pending_source = ("chained", None)
                chain.append(op.fn)
                compute = op.compute
                flush()
                pending_source = ("chained", None)
            else:
                if compute is not None:
                    flush()
                    pending_source = ("chained", None)
                chain.append(op.fn)
        elif isinstance(op, AllToAllOp):
            flush()
            pending_source = ("barrier", (op.kind, op.arg))
        elif isinstance(op, WindowedShuffleOp):
            # streaming stage: consumes the previous segment's stream
            # window-by-window (no materialization barrier)
            flush()
            pending_source = ("wshuffle", (op.window, op.seed))
        else:
            raise TypeError(f"unknown op {op!r}")
    flush()

    # resolve "chained" placeholders: those segments consume the previous
    # segment's stream — the executor handles this by treating them as
    # ("refs", <upstream stream>) at run time.
    return segments
