"""Execution context / tunables (ref: python/ray/data/context.py DataContext)."""
from __future__ import annotations

import threading
from dataclasses import dataclass, field


@dataclass
class DataContext:
    # backpressure: max blocks in flight per streaming stage
    # (ref: streaming_executor_state.py resource limits)
    max_in_flight_blocks: int = 16
    # byte-budget backpressure: per-segment admission stops once the
    # tracked bytes of outstanding blocks (completed-but-unemitted at
    # their store-reported size + in-flight tasks at the running average)
    # reach this budget. 0 disables; the block-count window above always
    # applies too (ref: ExecutionResources.object_store_memory)
    target_max_bytes_inflight: int = 0
    # emit blocks in plan order rather than completion order (ref:
    # execution_options.preserve_order — the reference defaults False for
    # throughput; here determinism wins by default; buffered out-of-order
    # refs count against max_in_flight_blocks so the stream stays bounded)
    preserve_order: bool = True
    default_parallelism: int = 8
    target_min_rows_per_block: int = 1000

    _current = None
    _lock = threading.Lock()

    @classmethod
    def get_current(cls) -> "DataContext":
        with cls._lock:
            if cls._current is None:
                cls._current = cls()
            return cls._current
