"""Dataset — the lazy, distributed data API.

Parity with the reference's Dataset (ref: python/ray/data/dataset.py;
read_api.py; plan execution via _internal/plan.py:544 → streaming
executor). Transforms are lazy logical ops; execution streams blocks
through tasks/actor pools with bounded in-flight blocks. Blocks are
columnar numpy dicts (see block.py) — the natural feed format for jax.
"""
from __future__ import annotations

import builtins
import math
import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, List, Optional, Union

import cloudpickle
import numpy as np

import ray_tpu

from .block import (Block, block_concat, block_from_batch, block_from_items,
                    block_num_rows, block_size_bytes, block_to_batch,
                    block_to_rows)
from .context import DataContext
from .executor import StreamingExecutor
from .iterator import DataShard, Shardable, _iter_batches_from_blocks
from .plan import (AllToAllOp, MapOp, SourceOp, WindowedShuffleOp,
                   build_segments)


def _block_rows(block: Block) -> int:
    return block_num_rows(block)


def _num_rows_remote():
    """Tiny metadata task: count a block's rows where it lives (no
    transfer). Wrapped at call time — the house convention keeps
    RemoteFunction construction out of import paths."""
    return ray_tpu.remote(_block_rows)


@dataclass
class ActorPoolStrategy:
    """compute= strategy running the UDF on a pool of actors (ref:
    python/ray/data/_internal/compute.py ActorPoolStrategy)."""
    size: int = 2
    resources: Optional[Dict[str, float]] = None


class Dataset(Shardable):
    def __init__(self, ops: List[Any], context: Optional[DataContext] = None):
        self._ops = ops
        self._ctx = context or DataContext.get_current()
        self._last_stats: Optional[dict] = None

    # -- transforms (lazy) ---------------------------------------------------

    def _with(self, op) -> "Dataset":
        if getattr(self, "_limit", None) is not None:
            # limit() then transform: the transform must see only the
            # truncated rows (ds.limit(3).flat_map(f) maps 3 rows, not
            # all). A deferred thunk source applies the limit when the
            # derived plan executes, keeping the chain lazy.
            src = SourceOp(thunk=self._execute_refs, name="limited")
            return Dataset([src, op], self._ctx)
        return Dataset(self._ops + [op], self._ctx)

    def map_batches(self, fn: Union[Callable, type], *,
                    batch_format: str = "numpy",
                    fn_constructor_args: tuple = (),
                    compute: Optional[ActorPoolStrategy] = None,
                    **_ignored) -> "Dataset":
        """Apply fn to whole blocks. A class UDF runs on an actor pool
        (constructed once per actor)."""
        if isinstance(fn, type):
            ctor_args = fn_constructor_args

            class _Bound:
                def __init__(self, cls=fn, args=ctor_args):
                    self._inst = cls(*args)

                def __call__(self, batch):
                    return self._inst(batch)

            inst_holder: list = []

            def block_fn(block: Block) -> Block:
                if not inst_holder:
                    inst_holder.append(_Bound())
                return block_from_batch(
                    inst_holder[0](block_to_batch(block, batch_format)))

            if compute is None:
                compute = ActorPoolStrategy()
        else:
            def block_fn(block: Block) -> Block:
                return block_from_batch(fn(block_to_batch(block, batch_format)))

        c = (compute.size, compute.resources) if compute is not None else None
        return self._with(MapOp(block_fn, name="map_batches", compute=c))

    def map(self, fn: Callable[[Any], Any]) -> "Dataset":
        def block_fn(block: Block) -> Block:
            return block_from_items([fn(r) for r in block_to_rows(block)])

        return self._with(MapOp(block_fn, name="map", commutes=True))

    def flat_map(self, fn: Callable[[Any], List[Any]]) -> "Dataset":
        def block_fn(block: Block) -> Block:
            out: List[Any] = []
            for r in block_to_rows(block):
                out.extend(fn(r))
            return block_from_items(out)

        return self._with(MapOp(block_fn, name="flat_map"))

    def filter(self, fn: Callable[[Any], bool]) -> "Dataset":
        def block_fn(block: Block) -> Block:
            rows = [r for r in block_to_rows(block) if fn(r)]
            return block_from_items(rows)

        return self._with(MapOp(block_fn, name="filter", commutes=True))

    def add_column(self, name: str, fn: Callable[[Dict[str, np.ndarray]], Any]
                   ) -> "Dataset":
        def block_fn(block: Block) -> Block:
            out = dict(block)
            out[name] = np.asarray(fn(block))
            return out

        return self._with(MapOp(block_fn, name=f"add_column[{name}]",
                                commutes=True))

    def drop_columns(self, cols: List[str]) -> "Dataset":
        def block_fn(block: Block) -> Block:
            return {k: v for k, v in block.items() if k not in cols}

        return self._with(MapOp(block_fn, name="drop_columns",
                                commutes=True))

    def select_columns(self, cols: List[str]) -> "Dataset":
        """Keep only `cols` (ref: dataset.py select_columns). Directly
        after a column-aware read (parquet) the optimizer pushes the
        projection into the read tasks, so dropped columns are never
        fetched at all."""
        cols = list(cols)

        def block_fn(block: Block) -> Block:
            missing = [c for c in cols if c not in block]
            if missing:
                raise KeyError(f"select_columns: missing {missing}; "
                               f"have {sorted(block)}")
            return {c: block[c] for c in cols}

        return self._with(MapOp(block_fn, name=f"select[{','.join(cols)}]",
                                commutes=True, projection=cols))

    def sort(self, key: str = "id", *, descending: bool = False) -> "Dataset":
        """Distributed sort by a column: sample -> range partition ->
        per-partition sort (ref: dataset.py sort;
        planner/exchange/sort_task_spec.py)."""
        return self._with(AllToAllOp("sort", (key, descending), name="sort"))

    def groupby(self, key: str) -> "GroupedDataset":
        """-> GroupedDataset with count/sum/mean/min/max aggregations
        (ref: dataset.py groupby; grouped_data.py)."""
        return GroupedDataset(self, key)

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._with(AllToAllOp("repartition", num_blocks))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return self._with(AllToAllOp("random_shuffle", seed))

    def windowed_shuffle(self, *, window_blocks: int = 8,
                         seed: Optional[int] = None) -> "Dataset":
        """Streaming shuffle: buffer `window_blocks` upstream blocks,
        emit their rows globally permuted by a seeded RNG, move to the
        next window. Unlike random_shuffle this is NOT an all-to-all
        barrier — the consumer starts after W blocks land and peak held
        refs stay O(W) — which is the right trade for training input
        pipelines (approximate global order, streaming memory).

        The permutation is a pure function of (seed, epoch): replaying
        the same epoch via iter_epochs() yields bit-identical order,
        the next epoch reshuffles deterministically."""
        if window_blocks < 1:
            raise ValueError("window_blocks must be >= 1")
        return self._with(WindowedShuffleOp(window_blocks, seed))

    def union(self, *others: "Dataset") -> "Dataset":
        """Lazy concatenation of datasets (ref: dataset.py union):
        the inputs' plans execute when the union executes; blocks flow
        through in order."""
        parts = [self, *others]

        def thunk():
            refs: List[Any] = []
            for p in parts:
                refs.extend(p._execute_refs())
            return refs

        return Dataset([SourceOp(thunk=thunk, name="union")], self._ctx)

    def zip(self, other: "Dataset") -> "Dataset":
        """Column-wise zip of two equal-length datasets (ref: dataset.py
        zip — the right side's conflicting column names get a "_1"
        suffix). Blocks realign on the driver, so this materializes both
        sides; prefer add_column for derived columns."""
        def thunk():
            a = block_concat([ray_tpu.get(r)
                              for r in self._execute_refs()])
            b = block_concat([ray_tpu.get(r)
                              for r in other._execute_refs()])
            na, nb = block_num_rows(a), block_num_rows(b)
            if na != nb:
                raise ValueError(
                    f"zip needs equal row counts, got {na} vs {nb}")
            merged = dict(a)
            for k, v in b.items():
                name, i = k, 1
                while name in merged:  # find a FREE suffix: zipping an
                    name = f"{k}_{i}"  # already-zipped ds must not
                    i += 1             # clobber its existing k_1
                merged[name] = v
            n_blocks = max(1, min(self._ctx.default_parallelism,
                                  math.ceil(na / max(
                                      1, self._ctx.target_min_rows_per_block)
                                  )))
            refs = []
            for i in builtins.range(n_blocks):
                lo = na * i // n_blocks
                hi = na * (i + 1) // n_blocks
                if hi > lo:
                    refs.append(ray_tpu.put(
                        {k: v[lo:hi] for k, v in merged.items()}))
            return refs

        return Dataset([SourceOp(thunk=thunk, name="zip")], self._ctx)

    def train_test_split(self, test_size: float, *,
                         shuffle: bool = False,
                         seed: Optional[int] = None
                         ) -> tuple:
        """-> (train, test) row-split at 1 - test_size (ref: dataset.py
        train_test_split). The upstream plan executes ONCE; both halves
        are views over the cached block refs."""
        if not 0.0 < test_size < 1.0:
            raise ValueError("test_size must be in (0, 1)")
        base = self.random_shuffle(seed=seed) if shuffle else self
        cache: Dict[str, Any] = {}

        def _splits():
            if "parts" not in cache:
                from .block import block_slice

                refs = base._execute_refs()
                blocks = [ray_tpu.get(r) for r in refs]
                total = sum(block_num_rows(b) for b in blocks)
                cut = int(total * (1.0 - test_size))
                train_refs, test_refs, seen = [], [], 0
                for r, b in zip(refs, blocks):
                    n = block_num_rows(b)
                    if seen + n <= cut:
                        train_refs.append(r)  # whole block: reuse ref
                    elif seen >= cut:
                        test_refs.append(r)
                    else:  # only the straddling block is re-put
                        k = cut - seen
                        train_refs.append(
                            ray_tpu.put(block_slice(b, 0, k)))
                        test_refs.append(
                            ray_tpu.put(block_slice(b, k, n)))
                    seen += n
                cache["parts"] = (train_refs, test_refs)
            return cache["parts"]

        train = Dataset([SourceOp(thunk=lambda: list(_splits()[0]),
                                  name="train_split")], self._ctx)
        test = Dataset([SourceOp(thunk=lambda: list(_splits()[1]),
                                 name="test_split")], self._ctx)
        return train, test

    def random_sample(self, fraction: float, *,
                      seed: Optional[int] = None) -> "Dataset":
        """Bernoulli row sample (ref: dataset.py random_sample). Each
        block samples with its own derived seed in a remote task."""
        if not 0.0 <= fraction <= 1.0:
            raise ValueError("fraction must be in [0, 1]")

        def _sample(block: Block, frac: float, s: int) -> Block:
            rng = np.random.default_rng(s)
            mask = rng.random(block_num_rows(block)) < frac
            return {k: v[mask] for k, v in block.items()}

        sample_remote = ray_tpu.remote(_sample)

        # unseeded calls must be independent draws (the reference's
        # contract) — freeze a fresh base per random_sample() call
        base = (int(np.random.default_rng().integers(2 ** 31))
                if seed is None else seed)

        def thunk():
            return [sample_remote.remote(r, fraction, base + 7919 * i)
                    for i, r in enumerate(self._execute_refs())]

        return Dataset([SourceOp(thunk=thunk, name="random_sample")],
                       self._ctx)

    def unique(self, column: str) -> List[Any]:
        """Distinct values of a column (ref: dataset.py unique)."""
        seen: set = set()
        for b in self._stream_blocks():
            if column in b:
                seen.update(np.unique(b[column]).tolist())
        return sorted(seen)

    def limit(self, n: int) -> "Dataset":
        """Applied exactly at iteration time (truncates the block stream)."""
        ds = Dataset(self._ops, self._ctx)
        ds._limit = n  # type: ignore[attr-defined]
        return ds

    # -- execution -----------------------------------------------------------

    def _segments(self) -> List[dict]:
        """Logical-plan optimization (optimizer.py rules) then fusion
        (plan.build_segments); applied rules surface in stats()."""
        from .optimizer import optimize

        ops, rules = optimize(self._ops)
        self._opt_rules = rules
        return build_segments(ops)

    def _execute_refs(self) -> List[Any]:
        ex = StreamingExecutor(self._ctx, epoch=getattr(self, "_epoch", 0))
        refs = list(ex.execute(self._segments()))
        self._last_stats = ex.stats.summary()
        limit = getattr(self, "_limit", None)
        if limit is not None:
            # ref-path consumers (materialize, union/zip/split thunks,
            # to_arrow_refs) must see the truncation too, not just the
            # block-stream path. Row counts come from tiny remote tasks
            # so whole-kept blocks never travel to the driver; only the
            # one straddling block is fetched and re-put sliced.
            from .block import block_slice

            nrows = _num_rows_remote()
            counts = ray_tpu.get(
                [nrows.remote(r) for r in refs], timeout=600)
            kept, seen = [], 0
            for r, n in zip(refs, counts):
                if seen >= limit:
                    break
                take = min(n, limit - seen)
                kept.append(r if take == n else ray_tpu.put(
                    block_slice(ray_tpu.get(r), 0, take)))
                seen += take
            refs = kept
        return refs

    def _stream_blocks(self) -> Iterator[Block]:
        ex = StreamingExecutor(self._ctx, epoch=getattr(self, "_epoch", 0))
        limit = getattr(self, "_limit", None)
        seen = 0
        for ref in ex.execute(self._segments()):
            block = ray_tpu.get(ref)
            if limit is not None:
                take = min(block_num_rows(block), limit - seen)
                if take <= 0:
                    break
                from .block import block_slice

                block = block_slice(block, 0, take)
                seen += take
                yield block
                if seen >= limit:
                    break
            else:
                yield block
        self._last_stats = ex.stats.summary()

    def materialize(self) -> "Dataset":
        refs = self._execute_refs()
        return Dataset([SourceOp(refs=refs, name="materialized")], self._ctx)

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy", drop_last: bool = False,
                     local_shuffle_seed: Optional[int] = None) -> Iterator[Any]:
        return _iter_batches_from_blocks(self._stream_blocks(), batch_size,
                                         batch_format, drop_last,
                                         local_shuffle_seed)

    def iter_epochs(self, num_epochs: Optional[int] = None
                    ) -> Iterator["Dataset"]:
        """Epoch-aware re-execution for training loops: yields one
        Dataset view per epoch, each re-running THIS plan with the
        epoch index threaded into every windowed_shuffle stage — epoch
        e replays bit-identically given the same seed, epoch e+1
        reshuffles deterministically. num_epochs=None iterates forever
        (ref: dataset_pipeline.py iter_epochs; here epochs re-execute
        the lazy plan rather than replaying a pipeline log)."""
        e = 0
        while num_epochs is None or e < num_epochs:
            ds = Dataset(self._ops, self._ctx)
            lim = getattr(self, "_limit", None)
            if lim is not None:
                ds._limit = lim  # type: ignore[attr-defined]
            ds._epoch = e  # type: ignore[attr-defined]
            yield ds
            e += 1

    def iter_torch_batches(self, *, batch_size: Optional[int] = 256,
                           dtypes=None, device: str = "cpu",
                           drop_last: bool = False,
                           local_shuffle_seed: Optional[int] = None
                           ) -> Iterator[Any]:
        """Batches as dicts of torch tensors (zero-copy from the block's
        numpy columns on cpu; ref: data/iterator.py iter_torch_batches)."""
        from .block import block_to_torch

        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last,
                                       local_shuffle_seed=local_shuffle_seed):
            yield block_to_torch(batch, dtypes=dtypes, device=device)

    def iter_tf_batches(self, *, batch_size: Optional[int] = 256,
                        dtypes=None, drop_last: bool = False,
                        local_shuffle_seed: Optional[int] = None
                        ) -> Iterator[Any]:
        """Batches as dicts of tf.Tensors (ref: dataset.py
        iter_tf_batches)."""
        from .block import block_to_tf

        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last,
                                       local_shuffle_seed=local_shuffle_seed):
            yield block_to_tf(batch, dtypes=dtypes)

    def to_arrow_refs(self) -> List[Any]:
        """Blocks as pyarrow.Table object refs (ref:
        dataset.py to_arrow_refs)."""
        from .block import block_to_arrow

        @ray_tpu.remote
        def conv(block):
            return block_to_arrow(block)

        return [conv.remote(ref) for ref in self._execute_refs()]

    # -- writes (ref: dataset.py write_parquet/write_csv/write_json:
    # one output file per block, written by parallel tasks) ------------------

    def _write_files(self, path: str, ext: str, write_one) -> List[str]:
        """Distributed write: each block becomes <path>/part-<i>.<ext>,
        written by a task per block. `path` may be an fsspec URL
        (s3://, gs://; memory:// is per-process and suits only
        single-process use). Writers receive an open binary file and
        must not close it. Stale part-*.<ext> files from a previous,
        larger write are removed first — a smaller re-write must not
        leave a mix a re-read would silently merge."""
        from ..util.fs import split_fs_url

        fs, root = split_fs_url(path)
        if fs is None:
            os.makedirs(root, exist_ok=True)
            for name in os.listdir(root):
                if name.startswith("part-") and name.endswith("." + ext):
                    os.unlink(os.path.join(root, name))
        else:
            try:
                fs.makedirs(root, exist_ok=True)
                for p in fs.ls(root, detail=False):
                    base = str(p).rsplit("/", 1)[-1]
                    if base.startswith("part-") \
                            and base.endswith("." + ext):
                        fs.rm(p)
            except FileNotFoundError:
                pass
        writer_blob = cloudpickle.dumps(write_one)

        @ray_tpu.remote
        def _write(block, dest: str) -> str:
            import cloudpickle as cp

            from ..util.fs import split_fs_url as _split

            w = cp.loads(writer_blob)
            # dest keeps the user's scheme: each worker resolves the
            # filesystem itself (cloud targets are shared across hosts)
            f_fs, f_path = _split(dest)
            if f_fs is None:
                os.makedirs(os.path.dirname(f_path) or ".", exist_ok=True)
                with open(f_path, "wb") as f:
                    w(block, f)
            else:
                try:
                    f_fs.makedirs(f_path.rsplit("/", 1)[0],
                                  exist_ok=True)
                except Exception:
                    pass
                with f_fs.open(f_path, "wb") as f:
                    w(block, f)
            return dest

        # compose dests on the ORIGINAL path so the scheme survives to
        # the workers; plain local paths use the OS separator
        base = path.rstrip("/") if "://" in path else path
        sep = "/" if "://" in path else os.sep
        refs = [
            _write.remote(ref, f"{base}{sep}part-{i:06d}.{ext}")
            for i, ref in enumerate(self._execute_refs())
        ]
        return ray_tpu.get(refs)

    def write_parquet(self, path: str) -> List[str]:
        def write_one(block: Block, f) -> None:
            import pyarrow.parquet as pq

            from .block import block_to_arrow

            pq.write_table(block_to_arrow(block), f)

        return self._write_files(path, "parquet", write_one)

    def write_csv(self, path: str) -> List[str]:
        def write_one(block: Block, f) -> None:
            import csv
            import io

            cols = list(block)
            buf = io.StringIO()
            w = csv.writer(buf)
            w.writerow(cols)
            # builtins.range: this module's `range` is the Dataset
            # factory (ray_tpu.data.range) and shadows the builtin
            # inside functions pickled out of this namespace
            for i in builtins.range(block_num_rows(block)):
                w.writerow([block[c][i] for c in cols])
            f.write(buf.getvalue().encode())

        return self._write_files(path, "csv", write_one)

    def write_json(self, path: str) -> List[str]:
        def write_one(block: Block, f) -> None:
            import json as _json

            lines = []
            for row in block_to_rows(block):
                if isinstance(row, dict):
                    row = {k: (v.tolist() if hasattr(v, "tolist") else v)
                           for k, v in row.items()}
                elif hasattr(row, "tolist"):
                    row = row.tolist()
                lines.append(_json.dumps(row))
            f.write(("\n".join(lines) + "\n").encode())

        return self._write_files(path, "json", write_one)

    def iter_rows(self) -> Iterator[Any]:
        for block in self._stream_blocks():
            for row in block_to_rows(block):
                yield row

    def take(self, n: int = 20) -> List[Any]:
        out: List[Any] = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Any]:
        return list(self.iter_rows())

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            # print IS the surface here (interactive inspection API)
            print(row)  # graftcheck: disable=GC007

    def count(self) -> int:
        return sum(block_num_rows(b) for b in self._stream_blocks())

    def sum(self, column: str = "item") -> float:
        total = 0.0
        for b in self._stream_blocks():
            if column in b and block_num_rows(b):
                total += float(np.sum(b[column]))
        return total

    def _column_stats(self, column: str) -> tuple:
        """Streaming (n, sum, sumsq, min, max) over one pass."""
        n, s, ss = 0, 0.0, 0.0
        mn, mx = math.inf, -math.inf
        for b in self._stream_blocks():
            if column in b and block_num_rows(b):
                v = np.asarray(b[column], np.float64)
                n += v.size
                s += float(v.sum())
                ss += float((v * v).sum())
                mn = min(mn, float(v.min()))
                mx = max(mx, float(v.max()))
        return n, s, ss, mn, mx

    def mean(self, column: str = "item") -> float:
        n, s, _, _, _ = self._column_stats(column)
        return s / n if n else float("nan")

    def std(self, column: str = "item", ddof: int = 1) -> float:
        """ref: dataset.py std (sample std by default, like the
        reference's ddof=1)."""
        n, s, ss, _, _ = self._column_stats(column)
        if n <= ddof:
            return float("nan")
        var = (ss - s * s / n) / (n - ddof)
        return math.sqrt(max(var, 0.0))

    def min(self, column: str = "item") -> float:
        n, _, _, mn, _ = self._column_stats(column)
        return mn if n else float("nan")

    def max(self, column: str = "item") -> float:
        n, _, _, _, mx = self._column_stats(column)
        return mx if n else float("nan")

    def schema(self) -> Optional[Dict[str, str]]:
        for b in self._stream_blocks():
            return {k: str(v.dtype) for k, v in b.items()}
        return None

    def num_blocks(self) -> int:
        src = self._ops[0]
        if src.read_fns is None and src.refs is None \
                and src.thunk is not None:
            # deferred source (union/zip/split): block count is only
            # knowable by running the upstream plans. Executed LOCALLY —
            # mutating the shared SourceOp here would silently freeze
            # one execution's blocks into every derived view (an
            # unseeded shuffle upstream would stop reshuffling)
            return len(list(src.thunk()))
        n = len(src.read_fns) if src.read_fns is not None else len(src.refs or [])
        for op in self._ops[1:]:
            if isinstance(op, AllToAllOp) and op.kind == "repartition":
                n = op.arg
        return n

    def size_bytes(self) -> int:
        return sum(block_size_bytes(b) for b in self._stream_blocks())

    def stats(self) -> dict:
        out = dict(self._last_stats or {})
        rules = getattr(self, "_opt_rules", None)
        if rules:
            out["optimizer_rules"] = list(rules)
        return out

    # -- splitting (Train ingest) --------------------------------------------

    def split_shards(self, n: int, *, equal: bool = True,
                     locality_hints=None) -> List[DataShard]:
        """Materialize and split into n shards for n Train workers (ref:
        python/ray/data/dataset.py split / streaming_split feeding
        train/_internal/data_config.py)."""
        refs = self._execute_refs()
        if equal and refs and len(refs) % n != 0 or (refs and len(refs) < n):
            ex = StreamingExecutor(self._ctx)
            per = max(1, math.ceil(len(refs) / n)) if refs else 1
            refs = ex.execute(build_segments(
                [SourceOp(refs=refs), AllToAllOp("repartition", n * per)]))
            refs = list(refs)
        return [DataShard(refs[i::n], name=f"shard_{i}") for i in builtins.range(n)]

    def split(self, n: int, **kw) -> List[DataShard]:
        return self.split_shards(n, **kw)

    def window(self, *, blocks_per_window: int = 10):
        """-> DatasetPipeline of windows over the source read tasks
        (ref: dataset.py window / dataset_pipeline.py): one window's
        blocks live at a time."""
        from .pipeline import window_dataset

        return window_dataset(self, blocks_per_window=blocks_per_window)

    def repeat(self, times: Optional[int] = None):
        """-> DatasetPipeline cycling this dataset (epochs; re-reads
        from source each pass)."""
        from .pipeline import repeat_dataset

        return repeat_dataset(self, times)

    def __repr__(self):
        names = [getattr(op, "name", op.__class__.__name__)
                 for op in self._ops]
        return f"Dataset({' -> '.join(names)})"


# ---------------------------------------------------------------------------
# read API (ref: python/ray/data/read_api.py)
# ---------------------------------------------------------------------------


class GroupedDataset:
    """Aggregations over groups of a key column. Two-stage: per-block
    partial aggregate states hash-partition by key, then merge — the
    classic map-side combine (ref: python/ray/data/grouped_data.py)."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _agg(self, specs: List[tuple]) -> Dataset:
        return self._ds._with(
            AllToAllOp("groupby", (self._key, specs), name="groupby"))

    def count(self) -> Dataset:
        return self._agg([("count", None)])

    def sum(self, on: str) -> Dataset:
        return self._agg([("sum", on)])

    def mean(self, on: str) -> Dataset:
        return self._agg([("mean", on)])

    def min(self, on: str) -> Dataset:
        return self._agg([("min", on)])

    def max(self, on: str) -> Dataset:
        return self._agg([("max", on)])

    def aggregate(self, *specs: tuple) -> Dataset:
        """specs: ("count", None) / ("sum"|"mean"|"min"|"max", column)."""
        return self._agg(list(specs))


def _make_dataset(read_fns: List[Callable[[], Block]], name: str) -> Dataset:
    blobs = [cloudpickle.dumps(fn) for fn in read_fns]
    return Dataset([SourceOp(read_fns=blobs, name=name)])


def from_items(items: List[Any], *, parallelism: int = -1) -> Dataset:
    n = len(items)
    ctx = DataContext.get_current()
    if parallelism <= 0:
        parallelism = max(1, min(ctx.default_parallelism,
                                 math.ceil(n / ctx.target_min_rows_per_block)))
    parallelism = max(1, min(parallelism, n or 1))
    fns = []
    for i in builtins.range(parallelism):
        chunk = items[n * i // parallelism: n * (i + 1) // parallelism]
        fns.append(lambda c=chunk: block_from_items(c))
    return _make_dataset(fns, "from_items")


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    ctx = DataContext.get_current()
    if parallelism <= 0:
        parallelism = max(1, min(ctx.default_parallelism,
                                 math.ceil(n / ctx.target_min_rows_per_block)))
    parallelism = max(1, min(parallelism, n or 1))
    fns = []
    for i in builtins.range(parallelism):
        lo, hi = n * i // parallelism, n * (i + 1) // parallelism
        fns.append(lambda a=lo, b=hi: {"id": np.arange(a, b)})
    return _make_dataset(fns, "range")


def from_arrow(tables, *, parallelism: int = -1) -> Dataset:
    """One or more pyarrow Tables -> Dataset (ref: data/read_api.py
    from_arrow). A single table splits by row range; a list keeps one
    block per table."""
    from .block import arrow_to_block

    if not isinstance(tables, (list, tuple)):
        return from_numpy(arrow_to_block(tables), parallelism=parallelism)
    fns = [lambda t=t: arrow_to_block(t) for t in tables]
    return _make_dataset(fns, "from_arrow")


def from_numpy(arrays: Union[np.ndarray, Dict[str, np.ndarray]], *,
               parallelism: int = -1) -> Dataset:
    block = block_from_batch(arrays)
    n = block_num_rows(block)
    ctx = DataContext.get_current()
    if parallelism <= 0:
        parallelism = max(1, min(ctx.default_parallelism,
                                 math.ceil(n / ctx.target_min_rows_per_block)))
    parallelism = max(1, min(parallelism, n or 1))
    fns = []
    for i in builtins.range(parallelism):
        lo, hi = n * i // parallelism, n * (i + 1) // parallelism
        sub = {k: v[lo:hi] for k, v in block.items()}
        fns.append(lambda s=sub: s)
    return _make_dataset(fns, "from_numpy")


def from_blocks(blocks: List[Block]) -> Dataset:
    return _make_dataset([lambda b=b: block_from_batch(b) for b in blocks],
                         "from_blocks")


def _file_read_fns(paths: Union[str, List[str]], reader: Callable[[str], Block],
                   suffixes: tuple) -> List[Callable[[], Block]]:
    import os

    if isinstance(paths, str):
        paths = [paths]
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                files.extend(os.path.join(root, f) for f in sorted(names)
                             if f.endswith(suffixes))
        else:
            files.append(p)
    if not files:
        raise FileNotFoundError(f"No input files under {paths}")
    return [lambda f=f: reader(f) for f in files]


def read_parquet(paths: Union[str, List[str]],
                 columns: Optional[List[str]] = None, **kw) -> Dataset:
    def make_reader(cols):
        def reader(path: str) -> Block:
            import pyarrow.parquet as pq

            table = pq.read_table(path, columns=cols)
            return {name: table.column(name).to_numpy(zero_copy_only=False)
                    for name in table.column_names}

        return reader

    ds = _make_dataset(_file_read_fns(paths, make_reader(columns),
                                      (".parquet",)), "read_parquet")
    if columns is None:
        # parquet is column-aware: a select_columns directly downstream
        # rewrites the read tasks to fetch only those columns
        # (optimizer.py projection pushdown)
        ds._ops[0].project = lambda cols: [
            cloudpickle.dumps(fn)
            for fn in _file_read_fns(paths, make_reader(list(cols)),
                                     (".parquet",))]
    return ds


def read_csv(paths: Union[str, List[str]], **kw) -> Dataset:
    def reader(path: str) -> Block:
        import csv

        with open(path, newline="") as f:
            rows = list(csv.DictReader(f))
        block = block_from_items(rows)
        out: Block = {}
        for k, v in block.items():
            try:
                out[k] = v.astype(np.float64)
            except (ValueError, TypeError):
                out[k] = v
        return out

    return _make_dataset(_file_read_fns(paths, reader, (".csv",)), "read_csv")


def read_json(paths: Union[str, List[str]], **kw) -> Dataset:
    def reader(path: str) -> Block:
        import json

        rows = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    rows.append(json.loads(line))
        return block_from_items(rows)

    return _make_dataset(_file_read_fns(paths, reader, (".json", ".jsonl")),
                         "read_json")


def read_numpy(paths: Union[str, List[str]], **kw) -> Dataset:
    def reader(path: str) -> Block:
        return {"data": np.load(path)}

    return _make_dataset(_file_read_fns(paths, reader, (".npy",)), "read_numpy")


def read_images(paths: Union[str, List[str]], *,
                size: Optional[tuple] = None,
                mode: str = "RGB", include_paths: bool = False,
                **kw) -> Dataset:
    """Image files -> blocks with an 'image' column ([H,W,C] uint8 per
    row; uniform sizes stack into one [N,H,W,C] array). PIL decodes
    (ref: python/ray/data/read_api.py read_images /
    _internal/datasource/image_datasource.py)."""
    def reader(path: str) -> Block:
        from PIL import Image

        img = Image.open(path).convert(mode)
        if size is not None:
            img = img.resize((size[1], size[0]))  # PIL takes (W, H)
        arr = np.asarray(img, np.uint8)
        block: Block = {"image": arr[None]}
        if include_paths:
            block["path"] = np.asarray([path], object)
        return block

    return _make_dataset(
        _file_read_fns(paths, reader,
                       (".png", ".jpg", ".jpeg", ".bmp", ".gif")),
        "read_images")


def read_sql(sql: str, connection_factory: Union[str, Callable], *,
             parallelism: int = 1, **kw) -> Dataset:
    """SQL query -> Dataset (ref: python/ray/data/read_api.py read_sql).
    connection_factory: a zero-arg callable returning a DB-API 2.0
    connection, or a string path treated as a sqlite3 database file.
    parallelism > 1 shards the query rows round-robin into that many
    blocks (each read task re-runs the query and keeps its slice — the
    portable strategy when the dialect lacks OFFSET pushdown)."""
    if isinstance(connection_factory, str):
        db_path = connection_factory

        def connection_factory():  # noqa: F811 — intentional rebind
            import sqlite3

            return sqlite3.connect(db_path)

    conn_blob = cloudpickle.dumps(connection_factory)

    def read_shard(shard: int, nshards: int) -> Block:
        factory = cloudpickle.loads(conn_blob)
        conn = factory()
        try:
            cur = conn.cursor()
            cur.execute(sql)
            cols = [d[0] for d in cur.description]
            # iterate the cursor: fetchall() would hold the FULL result
            # in every shard task simultaneously (nshards x table memory)
            rows = [r for i, r in enumerate(cur) if i % nshards == shard]
        finally:
            conn.close()
        return block_from_items([dict(zip(cols, r)) for r in rows])

    n = max(1, int(parallelism))
    fns = [lambda s=s: read_shard(s, n) for s in builtins.range(n)]
    return _make_dataset(fns, "read_sql")


def read_webdataset(paths: Union[str, List[str]], *,
                    decode: bool = True, **kw) -> Dataset:
    """WebDataset tar shards -> Dataset (ref: python/ray/data/read_api.py
    read_webdataset). Each tar member group sharing a basename prefix
    (before the first dot) is one sample; columns are named by member
    extension. With decode=True: jpg/png/bmp decode via PIL to uint8
    arrays, txt/cls to str, json to parsed objects, everything else
    stays bytes. One block per tar shard — the format's unit of
    streaming."""
    def reader(path: str) -> Block:
        import io
        import json as _json
        import os
        import tarfile

        samples: Dict[str, Dict[str, Any]] = {}
        order: List[str] = []
        with tarfile.open(path) as tar:
            for m in tar:
                if not m.isfile():
                    continue
                # key = FULL path up to the basename's first dot (the
                # webdataset base_plus_ext rule: train/a/0001.jpg and
                # train/b/0001.jpg are DIFFERENT samples)
                dirname, base = os.path.split(m.name)
                stem, _, ext = base.partition(".")
                key = os.path.join(dirname, stem) if dirname else stem
                data = tar.extractfile(m).read()
                if decode:
                    # decode dispatches on the LAST extension segment so
                    # 0001.seg.png decodes like 0001.png
                    lext = ext.lower().rsplit(".", 1)[-1]
                    if lext in ("jpg", "jpeg", "png", "bmp"):
                        from PIL import Image

                        data = np.asarray(
                            Image.open(io.BytesIO(data)).convert("RGB"),
                            np.uint8)
                    elif lext in ("txt", "cls"):
                        data = data.decode()
                    elif lext == "json":
                        data = _json.loads(data)
                if key not in samples:
                    samples[key] = {"__key__": key}
                    order.append(key)
                samples[key][ext] = data
        # union of extensions across samples: optional members (.cls on
        # some samples only) must not vanish because the shard's FIRST
        # sample lacked them (block_from_items seeds columns from row 0)
        all_keys: Dict[str, None] = {}
        for k in order:
            for col in samples[k]:
                all_keys.setdefault(col)
        rows = [{col: samples[k].get(col) for col in all_keys}
                for k in order]
        return block_from_items(rows)

    return _make_dataset(
        _file_read_fns(paths, reader, (".tar",)), "read_webdataset")


def read_tfrecords(paths: Union[str, List[str]], **kw) -> Dataset:
    """TFRecord files of tf.train.Example -> columnar blocks. No
    TensorFlow needed: framing + the Example protobuf subset are decoded
    natively with CRC verification (data/tfrecords.py; ref:
    python/ray/data/read_api.py read_tfrecords)."""
    from .tfrecords import tfrecords_to_block

    return _make_dataset(
        _file_read_fns(paths, tfrecords_to_block, (".tfrecord", ".tfrecords")),
        "read_tfrecords")
