"""DataIterator / shard consumption.

Equivalent of the reference's DatasetIterator (ref:
python/ray/data/iterator.py — iter_batches/iter_rows over streamed blocks;
train/_internal/session.py:470 get_dataset_shard). A DataShard is what a
Train worker receives: a picklable handle to a list of block refs (refs
serialize as borrows, so the blocks stay alive while any worker holds the
shard).
"""
from __future__ import annotations

import abc
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

import ray_tpu

from .block import (Block, block_concat, block_num_rows, block_select,
                    block_slice, block_to_batch, block_to_rows)


class Shardable(abc.ABC):
    """The sharding contract the Train layer consumes (`DataParallelTrainer`
    ``datasets=``): ``split_shards(n)`` returns exactly ``n``
    :class:`DataShard` handles whose rows are **disjoint** and
    **exhaustive** — every row of the dataset lands in exactly one
    shard. ``Dataset`` implements it; anything else that wants to feed
    Train workers per-rank slices implements/registers this instead of
    relying on a ``hasattr`` duck-type."""

    @abc.abstractmethod
    def split_shards(self, n: int, *, equal: bool = True,
                     locality_hints=None) -> List["DataShard"]:
        """Split into exactly ``n`` disjoint, exhaustive shards."""


def _iter_batches_from_blocks(blocks: Iterator[Block], batch_size: Optional[int],
                              batch_format: str, drop_last: bool,
                              local_shuffle_seed: Optional[int]) -> Iterator[Any]:
    if batch_size is None:
        for b in blocks:
            if block_num_rows(b):
                yield block_to_batch(b, batch_format)
        return
    carry: Optional[Block] = None
    rng = (np.random.default_rng(local_shuffle_seed)
           if local_shuffle_seed is not None else None)
    for b in blocks:
        if rng is not None and block_num_rows(b):
            b = block_select(b, rng.permutation(block_num_rows(b)))
        cur = b if carry is None else block_concat([carry, b])
        carry = None
        n = block_num_rows(cur)
        off = 0
        while n - off >= batch_size:
            yield block_to_batch(block_slice(cur, off, off + batch_size),
                                 batch_format)
            off += batch_size
        if off < n:
            carry = block_slice(cur, off, n)
    if carry is not None and block_num_rows(carry) and not drop_last:
        yield block_to_batch(carry, batch_format)


class DataShard:
    """One worker's slice of a dataset: a list of materialized block refs."""

    def __init__(self, refs: List[Any], name: str = "shard"):
        self._refs = list(refs)
        self._name = name

    def __len__(self) -> int:
        return len(self._refs)

    def _blocks(self) -> Iterator[Block]:
        for ref in self._refs:
            yield ray_tpu.get(ref)

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy", drop_last: bool = False,
                     local_shuffle_seed: Optional[int] = None) -> Iterator[Any]:
        return _iter_batches_from_blocks(self._blocks(), batch_size,
                                         batch_format, drop_last,
                                         local_shuffle_seed)

    def iter_torch_batches(self, *, batch_size: Optional[int] = 256,
                           dtypes=None, device: str = "cpu",
                           drop_last: bool = False,
                           local_shuffle_seed: Optional[int] = None
                           ) -> Iterator[Any]:
        """Train-loop sugar: batches as dicts of torch tensors (ref:
        iterator.py iter_torch_batches)."""
        from .block import block_to_torch

        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last,
                                       local_shuffle_seed=local_shuffle_seed):
            yield block_to_torch(batch, dtypes=dtypes, device=device)

    def iter_tf_batches(self, *, batch_size: Optional[int] = 256,
                        dtypes=None, drop_last: bool = False,
                        local_shuffle_seed: Optional[int] = None
                        ) -> Iterator[Any]:
        """Batches as dicts of tf.Tensors (ref: dataset.py
        iter_tf_batches)."""
        from .block import block_to_tf

        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last,
                                       local_shuffle_seed=local_shuffle_seed):
            yield block_to_tf(batch, dtypes=dtypes)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for b in self._blocks():
            for row in block_to_rows(b):
                yield row

    def count(self) -> int:
        return sum(block_num_rows(b) for b in self._blocks())

    def materialize_numpy(self) -> Block:
        return block_concat(list(self._blocks()))

    def __repr__(self):
        return f"DataShard({self._name}, {len(self._refs)} blocks)"
