"""ray_tpu.data — lazy, streaming, distributed datasets.

Equivalent of Ray Data (ref: python/ray/data/): logical plan + streaming
executor over ray_tpu tasks/actor pools, columnar numpy blocks in the
object store, sharded ingest for ray_tpu.train workers.
"""
from .block import Block
from .context import DataContext
from .dataset import (ActorPoolStrategy, Dataset, GroupedDataset,
                      from_arrow, from_blocks, from_items, from_numpy, range, read_csv,
                      read_images, read_json, read_numpy,
                      read_parquet, read_sql, read_tfrecords,
                      read_webdataset)
from .pipeline import DatasetPipeline
from .iterator import DataShard, Shardable
from .feed import DataFeed

__all__ = [
    "ActorPoolStrategy", "Block", "DataContext", "DataFeed", "DataShard",
    "Dataset", "Shardable",
    "GroupedDataset", "from_arrow", "from_blocks", "from_items", "from_numpy", "range",
    "DatasetPipeline",
    "read_csv", "read_images", "read_json", "read_numpy",
    "read_parquet", "read_sql", "read_tfrecords", "read_webdataset",
]
