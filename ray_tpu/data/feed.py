"""DataFeed — the terminal stage of the data plane: prefetch actors
that pump (inputs, targets) microbatches straight into a
CompiledPipelineEngine's input rings.

The driver-fed pipeline engine sends every microbatch down the
``r{r}:in->c0`` / ``r{r}:in->targets`` cgraph channels from ``step()``.
``engine.attach_feed(feed)`` moves that producer role OUT of the driver:
one ``_FeedPump`` actor per dp replica pulls block refs from its shard,
packs fixed-shape ``(inputs, targets)`` microbatches, and writes the
SAME envelopes into the SAME pre-allocated rings — ``engine.step()``
with no batch then only *reads* losses/reports, so the steady-state
train loop runs with zero driver round-trips (asserted against
``runtime.dispatch_counts()``).

Why this composes instead of being a second system:

- **Channels**: a ShmChannel's seq ledger lives in the shared segment,
  not in the endpoint, so the writer role hands off between processes
  by just opening the segment; cross-node rpc edges hand off by passing
  the current seq. No new channel kinds, no reallocation.
- **Backpressure**: ring slot occupancy IS the admit signal — a pump
  blocks in ``send`` once it runs ``slots`` (= num_microbatches)
  envelopes ahead of the consuming stage, exactly like the byte-budget
  admits upstream (executor.py _ByteWindow) throttle the segment above.
- **Faults**: pump actors are a stateless tier. Death aborts the engine
  with a typed :class:`ray_tpu.exceptions.DataFeedError`;
  ``engine.recover()`` respawns stages, recompiles channels, and
  re-attaches the feed from its factories. Preemption drains them like
  any stateless pool.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Sequence

import cloudpickle

from ..util import metrics as _metrics

_C_FEED_MB = _metrics.Counter(
    "ray_tpu_data_feed_microbatches_total",
    "(inputs, targets) microbatch pairs pushed into pipeline-engine "
    "input rings by data-feed pump actors")


class DataFeed:
    """Driver-side descriptor of a dp-sharded feed.

    ``factories`` is one zero-arg callable per dp replica; each runs
    INSIDE that replica's pump actor and must return an iterator of
    ``(inputs, targets)`` microbatch pairs (the exact values ``step()``
    would have been hand-fed, in the same order — the engine's loss
    trajectory is then bit-identical to hand-feeding). The callables are
    cloudpickled at construction, so captured DataShard block refs
    travel to the pump actors and are pulled there, not on the driver.
    """

    def __init__(self, factories: Sequence[Callable[[], Any]], *,
                 name: str = "feed"):
        if not factories:
            raise ValueError("DataFeed needs at least one shard factory")
        self.name = str(name)
        self.shard_blobs: List[bytes] = [cloudpickle.dumps(f)
                                         for f in factories]

    @property
    def dp(self) -> int:
        return len(self.shard_blobs)

    @classmethod
    def from_shards(cls, shards: Sequence[Any],
                    to_microbatches: Callable[[Any], Any], *,
                    name: str = "feed") -> "DataFeed":
        """Build a feed over ``Dataset.split_shards(dp)`` output:
        ``to_microbatches(shard)`` runs inside the pump actor and
        returns the shard's ``(inputs, targets)`` iterator (typically a
        generator over ``shard.iter_batches(...)``)."""
        return cls([(lambda s=s: to_microbatches(s)) for s in shards],
                   name=name)


def _make_writer(spec: dict, graph_id: bytes, start_seq: int,
                 interrupt: threading.Event):
    """Writer endpoint onto an engine input edge, from inside a pump
    actor. shm: attach to the ring segment (the seq ledger is
    segment-resident, so the handoff from the driver's endpoint is
    free — this requires running on the segment's node, which
    attach_feed guarantees by placement). rpc: ship envelopes up this
    worker's control channel; the head routes them to the consuming
    stage exactly as driver sends were, continuing at ``start_seq``."""
    from ..cgraph.channel import RpcSender, ShmChannel
    from ..core import runtime as _rt
    from ..core.object_store import SegmentReader

    if spec["kind"] == "shm":
        return ShmChannel(SegmentReader(), spec["name"], spec["size"],
                          edge=spec.get("edge", ""), interrupt=interrupt,
                          slots=spec.get("slots", 1))
    rt = _rt.get_runtime()
    channel = rt.channel

    def send(cid, seq, data):
        channel.call("cgraph_send", {"graph_id": graph_id, "cid": cid,
                                     "seq": seq, "data": data},
                     timeout=120)

    sender = RpcSender(send, spec["cid"], edge=spec.get("edge", ""))
    sender._seq = int(start_seq)
    return sender


class _FeedPump:
    """One dp replica's prefetch/pump actor (spawned by
    ``CompiledPipelineEngine.attach_feed``). A resident thread drains
    the shard iterator into the input rings; ring slot occupancy
    backpressures it, channel poisoning (engine teardown/abort) stops
    it."""

    def setup(self, in_spec: dict, tgt_spec: dict, in_seq: int,
              tgt_seq: int, graph_id: bytes, factory_blob: bytes,
              tag: str) -> bool:
        self._stopev = threading.Event()
        self._in_w = _make_writer(in_spec, graph_id, in_seq, self._stopev)
        self._tgt_w = _make_writer(tgt_spec, graph_id, tgt_seq,
                                   self._stopev)
        self._factory = cloudpickle.loads(factory_blob)
        self._tag = str(tag)
        self._sent = 0
        self._exhausted = False
        self._error: Optional[str] = None
        self._thread: Optional[threading.Thread] = None
        return True

    def start(self) -> bool:
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"data-feed-{self._tag}")
        self._thread.start()
        return True

    def _run(self) -> None:
        from ..cgraph.channel import pack_envelope
        from ..core import serialization
        from ..exceptions import CompiledGraphClosedError

        try:
            for x, tgt in self._factory():
                if self._stopev.is_set():
                    return
                # same envelope bytes the driver's hand-fed step()
                # writes — the stage actors cannot tell the difference,
                # so the loss trajectory is bit-identical
                env_x = pack_envelope(0, "", serialization.dumps(x))
                env_t = pack_envelope(0, "", serialization.dumps(tgt))
                # blocks here once `slots` envelopes ahead of the
                # consuming stage: slot occupancy is the admit signal
                self._in_w.send(env_x)
                self._tgt_w.send(env_t)
                self._sent += 1
                _C_FEED_MB.inc()
            self._exhausted = True
        except CompiledGraphClosedError:
            pass  # engine teardown/abort poisoned the ring: clean stop
        except BaseException as e:  # noqa: BLE001 — surfaced via stats()
            self._error = repr(e)

    def stats(self) -> dict:
        return {"sent": self._sent,
                "exhausted": self._exhausted,
                "error": self._error,
                "in_seq": getattr(self._in_w, "_seq", None),
                "tgt_seq": getattr(self._tgt_w, "_seq", None)}

    def stop(self) -> dict:
        """Stop the pump and release the endpoints; returns final stats
        (the engine resyncs rpc writer seqs from in_seq/tgt_seq when
        hand-feeding resumes after detach)."""
        self._stopev.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        st = self.stats()
        for ch in (self._in_w, self._tgt_w):
            try:
                # detach, never close: closing poisons the ring ledger
                # and would kill the engine this pump is handing the
                # writer role back to
                if hasattr(ch, "detach"):
                    ch.detach()
                else:
                    ch.close()
            except Exception:
                pass
        return st
