"""Rule-based logical-plan optimization for ray_tpu.data.

The analog of the reference's logical optimizer (ref:
python/ray/data/_internal/logical/optimizers.py LogicalOptimizer +
rules/: operator fusion, limit/projection pushdown). Fusion of adjacent
block transforms already lives in plan.build_segments; this pass runs
BEFORE it and applies plan-shape rules:

- **Projection pushdown**: a `select_columns` op directly downstream of
  a column-aware source (parquet) rewrites the read tasks to fetch only
  those columns — IO and memory drop at the reader, not after it.
- **Commute reordering**: row-wise content-preserving ops (filter,
  select/drop_columns, row map) commute with content-preserving
  all-to-all ops — random_shuffle and repartition only. `sort` needs
  its key column (a later drop/select may remove it) and `groupby`
  changes the row set entirely, so nothing moves across those. Ops
  that depend on block/batch boundaries (map_batches) are never moved
  either.

`optimize` is pure: it returns a new op list plus the list of rule
applications (surfaced via Dataset.stats()["optimizer_rules"]).
"""
from __future__ import annotations

from dataclasses import replace
from typing import Any, List, Tuple

from .plan import AllToAllOp, MapOp, SourceOp

_MAX_PASSES = 10
# only content-preserving barriers commute with row-wise ops: sort
# consumes its key column, groupby replaces the row set
_COMMUTABLE_BARRIERS = ("repartition", "random_shuffle")


def optimize(ops: List[Any]) -> Tuple[List[Any], List[str]]:
    applied: List[str] = []
    ops = list(ops)
    ops = _push_projection_into_source(ops, applied)
    for _ in range(_MAX_PASSES):
        changed = False
        for i in range(1, len(ops) - 1):
            a, b = ops[i], ops[i + 1]
            if (isinstance(a, AllToAllOp)
                    and a.kind in _COMMUTABLE_BARRIERS
                    and isinstance(b, MapOp)
                    and getattr(b, "commutes", False)
                    and b.compute is None):
                ops[i], ops[i + 1] = b, a
                applied.append(f"commute[{b.name} <-> {a.name}]")
                changed = True
                break
        if not changed:
            break
    return ops, applied


def _push_projection_into_source(ops: List[Any],
                                 applied: List[str]) -> List[Any]:
    if len(ops) < 2:
        return ops
    src = ops[0]
    if not isinstance(src, SourceOp) or src.project is None:
        return ops
    op1 = ops[1]
    cols = getattr(op1, "projection", None)
    if not isinstance(op1, MapOp) or not cols:
        return ops
    try:
        new_fns = src.project(list(cols))
    except Exception:
        return ops  # source declined (e.g. unknown columns) — run as-is
    applied.append(f"projection_pushdown[{','.join(cols)}]")
    new_src = replace(src, read_fns=new_fns,
                      name=f"{src.name}[{','.join(cols)}]")
    new_src.project = None  # already applied
    # the reader now returns exactly the selected columns; the
    # projection op is identity — drop it
    return [new_src] + ops[2:]
