"""Block representation for ray_tpu.data.

The reference's blocks are Arrow tables / pandas DataFrames moved through
plasma (ref: python/ray/data/block.py, _internal/arrow_block.py). Here the
canonical block is a **columnar dict of numpy arrays** — the zero-copy
friendly layout for feeding jax (`jnp.asarray(col)` is a device put of a
contiguous buffer; no row pivot on the hot path). Rows are a derived view.
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, List, Sequence

import numpy as np

Block = Dict[str, np.ndarray]


def _as_column(values: List[Any]) -> np.ndarray:
    try:
        arr = np.asarray(values)
        if arr.dtype == object:
            raise ValueError
        return arr
    except Exception:
        arr = np.empty(len(values), dtype=object)
        for i, v in enumerate(values):
            arr[i] = v
        return arr


def block_from_items(items: Sequence[Any]) -> Block:
    """Rows that are dicts become columns; bare values become column 'item'."""
    if not items:
        return {}
    if isinstance(items[0], dict):
        cols: Dict[str, List[Any]] = {k: [] for k in items[0]}
        for row in items:
            for k in cols:
                cols[k].append(row.get(k))
        return {k: _as_column(v) for k, v in cols.items()}
    return {"item": _as_column(list(items))}


def block_from_batch(batch: Any) -> Block:
    """Accept a columnar dict, a pandas DataFrame, a pyarrow Table, torch
    tensors, or a list of rows."""
    if batch is None:
        return {}
    if isinstance(batch, dict):
        return {k: _any_to_numpy(v) for k, v in batch.items()}
    if isinstance(batch, (list, tuple)):
        return block_from_items(list(batch))
    if type(batch).__module__.startswith("pyarrow"):  # Arrow Table
        return arrow_to_block(batch)
    if hasattr(batch, "to_dict") and hasattr(batch, "columns"):  # DataFrame
        return {c: batch[c].to_numpy() for c in batch.columns}
    if isinstance(batch, np.ndarray):
        return {"item": batch}
    raise TypeError(f"Cannot convert {type(batch).__name__} to a block")


def _any_to_numpy(v: Any) -> np.ndarray:
    if isinstance(v, np.ndarray):
        return v
    if type(v).__module__.startswith("torch"):
        return v.detach().cpu().numpy()
    if type(v).__module__.startswith("pyarrow"):
        return v.to_numpy(zero_copy_only=False)
    return np.asarray(v)


# -- Arrow interop (ref: python/ray/data/block.py BlockAccessor.to_arrow /
# _internal/arrow_block.py). Numeric columns cross zero-copy; strings and
# nested values go through Arrow's own conversion. --------------------------

def arrow_to_block(table) -> Block:
    return {name: table.column(name).to_numpy(zero_copy_only=False)
            for name in table.column_names}


def block_to_arrow(block: Block):
    import pyarrow as pa

    cols = {}
    for k, v in block.items():
        if v.dtype == object:
            cols[k] = pa.array(list(v))
        elif v.ndim > 1:
            # tensors become fixed-size lists (ArrowTensorArray analog)
            flat = pa.array(v.reshape(len(v), -1).tolist())
            cols[k] = flat
        else:
            cols[k] = pa.array(v)  # zero-copy for numeric dtypes
    return pa.table(cols)


def block_to_torch(block: Block, dtypes=None, device: str = "cpu"):
    """dict of torch tensors; torch.from_numpy is zero-copy on cpu (ref:
    python/ray/data/iterator.py iter_torch_batches; air/_internal/
    torch_utils.py convert_ndarray_batch_to_torch_tensor_batch)."""
    import torch

    out = {}
    for k, v in block.items():
        if v.dtype == object:
            raise TypeError(f"column {k!r} has object dtype; cast it "
                            f"before iter_torch_batches")
        arr = np.ascontiguousarray(v)
        if not arr.flags.writeable:
            arr = arr.copy()  # torch rejects non-writable zero-copy views
        t = torch.from_numpy(arr)
        dt = dtypes.get(k) if isinstance(dtypes, dict) else dtypes
        if dt is not None:
            t = t.to(dt)
        if device not in ("cpu", None):
            t = t.to(device)
        out[k] = t
    return out


def block_num_rows(block: Block) -> int:
    for col in block.values():
        return len(col)
    return 0


def block_slice(block: Block, start: int, stop: int) -> Block:
    return {k: v[start:stop] for k, v in block.items()}


def block_select(block: Block, indices: np.ndarray) -> Block:
    return {k: v[indices] for k, v in block.items()}


def block_concat(blocks: Iterable[Block]) -> Block:
    blocks = [b for b in blocks if block_num_rows(b) > 0]
    if not blocks:
        return {}
    keys = list(blocks[0].keys())
    return {k: np.concatenate([b[k] for b in blocks]) for k in keys}


def block_to_rows(block: Block) -> List[Dict[str, Any]]:
    n = block_num_rows(block)
    keys = list(block.keys())
    rows = [{k: block[k][i] for k in keys} for i in range(n)]
    # unbox the bare-value column
    if keys == ["item"]:
        return [r["item"] for r in rows]
    return rows


def block_to_batch(block: Block, batch_format: str = "numpy") -> Any:
    if batch_format in ("numpy", "default", None):
        return dict(block)
    if batch_format == "pandas":
        import pandas as pd

        return pd.DataFrame({k: list(v) if v.dtype == object else v
                             for k, v in block.items()})
    if batch_format in ("pyarrow", "arrow"):
        return block_to_arrow(block)
    if batch_format == "torch":
        return block_to_torch(block)
    if batch_format in ("tf", "tensorflow"):
        return block_to_tf(block)
    if batch_format == "rows":
        return block_to_rows(block)
    raise ValueError(f"Unknown batch_format {batch_format!r}")


def block_size_bytes(block: Block) -> int:
    total = 0
    for v in block.values():
        if v.dtype == object:
            total += sum(len(str(x)) for x in v) + 8 * len(v)
        else:
            total += v.nbytes
    return total

def block_to_tf(block, dtypes=None):
    """Columns -> dict of tf.Tensors (TF shares the numpy buffer where
    dtypes allow; ref: data/iterator.py iter_tf_batches)."""
    import tensorflow as tf

    out = {}
    for k, v in block.items():
        t = tf.convert_to_tensor(v)
        if dtypes:
            want = dtypes.get(k) if isinstance(dtypes, dict) else dtypes
            if want is not None:
                t = tf.cast(t, want)
        out[k] = t
    return out
