"""Deterministic, seeded fault injection threaded through the runtime.

The chaos layer (ref: Jepsen/chaos-mesh style nemeses, and the reference
repo's ``RAY_testing_*`` fault-injection flags in ray_config_def.h) turns
the failure modes a preemptible TPU-pod deployment actually sees —
dropped control frames, slow links, duplicated deliveries, worker and
agent death, poisoned channels, failed object pulls — into a
*replayable* schedule: every probabilistic draw comes from a per-point
RNG seeded by ``(plan.seed, point)``, and every kill fires at a fixed
offset from :func:`enable`, so a failing CI run reproduces with the same
``RAY_TPU_CHAOS`` spec.

Plan spec (env ``RAY_TPU_CHAOS`` or :meth:`ChaosPlan.parse`), entries
separated by ``;``::

    seed=42                       fixed RNG seed (default 0)
    rpc_drop=0.05                 drop 5% of oneway frames (send side)
    rpc_drop=0.05:direct_result   ...only frames whose method contains
                                  "direct_result"
    rpc_delay=0.1@0.02            10% of writer flushes sleep 20ms
    rpc_dup=0.02                  duplicate 2% of oneway frames
    rpc_reorder=0.05              swap adjacent oneway frames in a batch
    recv_drop=0.01                drop oneway frames at the receiver
    pull_fail=0.2                 20% of remote object pulls raise a
                                  transient error (the retry path runs)
    channel_poison=0.001:c0->c1   poison matching cgraph channels
    kill=actor:trainer@5.0        kill the named actor 5s after enable
    kill=worker@7.5               kill a seeded-random live worker at 7.5s
    preempt=node:ab12@5+2.0       scheduled preemption of the node whose
                                  id starts ab12: NOTICE at t=5 (the
                                  NODE_PREEMPTING drain path runs), then
                                  SIGKILL of its agent at t=5+2.0 —
                                  scale-down rehearsals, seeded and
                                  replayable like every other fault

Only ONEWAY frames are droppable/duplicable: dropping a request or
response frame models a hang the channel layer has no retransmit for
(the real-world analog is a TCP connection that died, which surfaces as
a channel close, not a silent void). Delays and reorders apply to any
frame. This matches where the recovery machinery lives: direct submits,
direct results, cgraph pushes, task_done floods, and heartbeats all ride
oneway frames.

Zero overhead when disabled: host modules (core.rpc, core.runtime,
cgraph.channel) carry a module-level ``_CHAOS`` that is ``None`` until
:func:`enable` installs the engine — the hot paths pay one global
is-None test, and nothing imports this package until chaos is asked for.

Metrics: every injection counts in
``ray_tpu_chaos_injected_total{kind}``.
"""
from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Union

from ..util import metrics as _metrics

__all__ = [
    "ChaosRule", "KillSpec", "PreemptSpec", "ChaosPlan", "ChaosEngine",
    "enable", "disable", "is_enabled", "engine",
    "plan_from_env", "maybe_enable_from_env", "ENV_VAR",
]

ENV_VAR = "RAY_TPU_CHAOS"

_C_INJECTED = _metrics.Counter(
    "ray_tpu_chaos_injected_total",
    "faults injected by the chaos layer", tag_keys=("kind",))

_RULE_KINDS = ("rpc_drop", "rpc_delay", "rpc_dup", "rpc_reorder",
               "recv_drop", "pull_fail", "channel_poison")


@dataclass(frozen=True)
class ChaosRule:
    kind: str            # one of _RULE_KINDS
    prob: float          # injection probability per opportunity
    param: float = 0.0   # kind-specific (delay seconds)
    match: str = ""      # substring filter on method/edge ("" = all)

    def matches(self, label: str) -> bool:
        return not self.match or self.match in label


@dataclass(frozen=True)
class KillSpec:
    at_s: float
    # "actor:<name-or-hex-prefix>" | "actor" (seeded random) |
    # "worker" | "worker:<hex-prefix>" | a callable for programmatic
    # plans (invoked with the runtime)
    target: Union[str, Callable[[Any], None]] = "worker"


@dataclass(frozen=True)
class PreemptSpec:
    """Scheduled node preemption: notice at ``at_s`` (the runtime's
    ``NODE_PREEMPTING`` drain path runs — scheduler drain filter, serve
    replica draining, pipeline shrink-before-the-axe), SIGKILL of the
    node's agent process at ``at_s + grace_s`` whether or not anyone
    drained. Target: "node:<hex-prefix>" or "node" (seeded random
    remote node)."""

    at_s: float
    grace_s: float = 5.0
    target: str = "node"


@dataclass(frozen=True)
class ChaosPlan:
    seed: int = 0
    rules: tuple = ()
    kills: tuple = ()
    preempts: tuple = ()

    @classmethod
    def parse(cls, spec: str) -> "ChaosPlan":
        seed = 0
        rules: List[ChaosRule] = []
        kills: List[KillSpec] = []
        preempts: List[PreemptSpec] = []
        for raw in spec.split(";"):
            entry = raw.strip()
            if not entry:
                continue
            key, _, value = entry.partition("=")
            key = key.strip()
            value = value.strip()
            if key == "seed":
                seed = int(value)
            elif key == "kill":
                target, _, at = value.partition("@")
                kills.append(KillSpec(at_s=float(at or 0.0),
                                      target=target))
            elif key == "preempt":
                # preempt=node:<id>@t+grace — notice at t, axe at t+grace
                target, _, timing = value.partition("@")
                at_s, _, grace = timing.partition("+")
                preempts.append(PreemptSpec(
                    at_s=float(at_s or 0.0),
                    grace_s=float(grace) if grace else 5.0,
                    target=target or "node"))
            elif key in _RULE_KINDS:
                body, _, match = value.partition(":")
                prob_s, _, param_s = body.partition("@")
                rules.append(ChaosRule(
                    kind=key, prob=float(prob_s),
                    param=float(param_s) if param_s else 0.0,
                    match=match))
            else:
                raise ValueError(
                    f"unknown chaos spec entry {entry!r} (known: seed, "
                    f"kill, preempt, {', '.join(_RULE_KINDS)})")
        return cls(seed=seed, rules=tuple(rules), kills=tuple(kills),
                   preempts=tuple(preempts))


class ChaosEngine:
    """Live injector for one plan. Each (rule index, kind) gets its own
    seeded RNG + lock, so a rule's draw sequence depends only on how many
    opportunities ITS injection point saw — not on interleaving with
    other points."""

    def __init__(self, plan: ChaosPlan):
        self.plan = plan
        self._by_kind: Dict[str, List[ChaosRule]] = {}
        for r in plan.rules:
            self._by_kind.setdefault(r.kind, []).append(r)
        self._rngs: Dict[ChaosRule, random.Random] = {}
        self._rng_locks: Dict[ChaosRule, threading.Lock] = {}
        for i, r in enumerate(plan.rules):
            self._rngs[r] = random.Random(f"{plan.seed}/{i}/{r.kind}")
            self._rng_locks[r] = threading.Lock()
        self._kill_rng = random.Random(f"{plan.seed}/kill")
        self._preempt_victims: Dict[PreemptSpec, Any] = {}
        self.injected: Dict[str, int] = {}
        self._inj_lock = threading.Lock()
        self._stop = threading.Event()
        self._kill_thread: Optional[threading.Thread] = None
        self.t0 = time.monotonic()

    # -- draw machinery ----------------------------------------------------

    def _fire(self, rule: ChaosRule, label: str) -> bool:
        if not rule.matches(label):
            return False
        with self._rng_locks[rule]:
            hit = self._rngs[rule].random() < rule.prob
        if hit:
            self.record(rule.kind)
        return hit

    def record(self, kind: str) -> None:
        with self._inj_lock:
            self.injected[kind] = self.injected.get(kind, 0) + 1
        _C_INJECTED.inc(tags={"kind": kind})

    def _first_hit(self, kind: str, label: str) -> Optional[ChaosRule]:
        for rule in self._by_kind.get(kind, ()):
            if self._fire(rule, label):
                return rule
        return None

    # -- RPC frame hooks (core/rpc.py writer drain + oneway dispatch) ------

    _ONEWAY = 3  # mirrors rpc._ONEWAY; rpc is not imported here

    def rpc_send(self, msgs: list) -> list:
        """Transform one writer-lane flush: msgs are decoded frame tuples
        ``(kind, msg_id, method, payload)``. Runs on a pool thread, so a
        delay here stalls exactly this channel's writer — the fault being
        modeled. Drop/dup/reorder touch ONEWAY frames only."""
        if not self._by_kind:
            return msgs
        delay = 0.0
        out: list = []
        for msg in msgs:
            kind = msg[0]
            method = msg[2] if isinstance(msg[2], str) else ""
            rule = self._first_hit("rpc_delay", method)
            if rule is not None:
                delay = max(delay, rule.param or 0.001)
            if kind != self._ONEWAY:
                out.append(msg)
                continue
            if self._first_hit("rpc_drop", method) is not None:
                continue
            out.append(msg)
            if self._first_hit("rpc_dup", method) is not None:
                out.append(msg)
            if len(out) >= 2 and out[-2][0] == self._ONEWAY \
                    and self._first_hit("rpc_reorder", method) is not None:
                out[-1], out[-2] = out[-2], out[-1]
        if delay > 0:
            time.sleep(delay)
        return out

    def recv_drop(self, method: str) -> bool:
        """Receiver-side oneway drop (models a frame lost after the
        sender's syscall succeeded)."""
        return self._first_hit("recv_drop", method or "") is not None

    # -- object-store pull hook (core/runtime.py _pull_once) ---------------

    def pull_fail(self, label: str = "") -> bool:
        return self._first_hit("pull_fail", label) is not None

    # -- cgraph channel hook (cgraph/channel.py send) ----------------------

    def channel_poison(self, edge: str) -> bool:
        return self._first_hit("channel_poison", edge or "") is not None

    # -- kill schedule -----------------------------------------------------

    def start_kills(self, runtime) -> None:
        if (not self.plan.kills and not self.plan.preempts) \
                or self._kill_thread is not None:
            return
        self._kill_thread = threading.Thread(
            target=self._kill_loop, args=(runtime,), daemon=True,
            name="chaos-kills")
        self._kill_thread.start()

    def _kill_loop(self, runtime) -> None:
        # one merged timeline: kills fire once; each preempt expands to
        # a NOTICE event at t and an AXE event at t+grace — the axe
        # falls whether or not anything drained (spot semantics)
        events = [(spec.at_s, "kill", spec) for spec in self.plan.kills]
        for spec in self.plan.preempts:
            events.append((spec.at_s, "preempt_notice", spec))
            events.append((spec.at_s + spec.grace_s, "preempt_kill",
                           spec))
        for at_s, kind, spec in sorted(events, key=lambda e: e[0]):
            wait = self.t0 + at_s - time.monotonic()
            if wait > 0 and self._stop.wait(wait):
                return
            if self._stop.is_set():
                return
            try:
                if kind == "kill":
                    self._execute_kill(runtime, spec)
                elif kind == "preempt_notice":
                    self._execute_preempt_notice(runtime, spec)
                else:
                    self._execute_preempt_kill(runtime, spec)
                self.record(kind)
            except Exception:
                import traceback

                traceback.print_exc()

    def _execute_kill(self, runtime, spec: KillSpec) -> None:
        if callable(spec.target):
            spec.target(runtime)
            return
        kind, _, sel = spec.target.partition(":")
        if kind == "actor":
            self._kill_actor(runtime, sel)
        elif kind == "worker":
            self._kill_worker(runtime, sel)
        else:
            raise ValueError(f"unknown kill target {spec.target!r}")

    def _kill_actor(self, runtime, sel: str) -> None:
        from ..core.gcs import ActorState

        if sel:
            info = runtime.gcs.get_named_actor(sel, runtime.namespace)
            if info is None:
                # hex-prefix match over live actors
                cands = [i for i in runtime.gcs.list_actors()
                         if i.state == ActorState.ALIVE
                         and i.actor_id.hex().startswith(sel)]
                info = cands[0] if cands else None
            if info is None:
                raise ValueError(f"chaos kill: no actor matches {sel!r}")
            runtime.kill_actor(info.actor_id, no_restart=False)
            return
        cands = sorted(
            (i for i in runtime.gcs.list_actors()
             if i.state == ActorState.ALIVE),
            key=lambda i: i.actor_id.hex())
        if not cands:
            raise ValueError("chaos kill: no live actor to kill")
        victim = cands[self._kill_rng.randrange(len(cands))]
        runtime.kill_actor(victim.actor_id, no_restart=False)

    def _kill_worker(self, runtime, sel: str) -> None:
        """SIGKILL a live worker process (preemption model). Selection is
        seeded-random over workers with a local process handle, or by
        worker-id hex prefix."""
        import signal

        cands = []
        for node in getattr(runtime, "nodes", {}).values():
            for w in getattr(node, "_workers", {}).values():
                proc = getattr(w, "proc", None)
                if proc is None or proc.poll() is not None:
                    continue
                if sel and not w.worker_id.hex().startswith(sel):
                    continue
                cands.append(proc)
        if not cands:
            raise ValueError(
                f"chaos kill: no live local worker matches {sel!r}")
        cands.sort(key=lambda p: p.pid)
        victim = cands[self._kill_rng.randrange(len(cands))]
        os.kill(victim.pid, signal.SIGKILL)

    # -- preempt schedule (notice at t, SIGKILL at t+grace) ----------------

    def _resolve_preempt_node(self, runtime, spec: PreemptSpec):
        kind, _, sel = spec.target.partition(":")
        if kind != "node":
            raise ValueError(
                f"preempt target must be node[:<hex-prefix>], got "
                f"{spec.target!r}")
        cands = sorted(
            (node for node in getattr(runtime, "nodes", {}).values()
             if node.alive and getattr(node, "is_remote", False)
             and (not sel or node.node_id.hex().startswith(sel))),
            key=lambda n: n.node_id.hex())
        if not cands:
            raise ValueError(
                f"chaos preempt: no live remote node matches {sel!r}")
        if sel:
            return cands[0]
        return cands[self._kill_rng.randrange(len(cands))]

    def _execute_preempt_notice(self, runtime, spec: PreemptSpec) -> None:
        node = self._resolve_preempt_node(runtime, spec)
        # remember the victim so the axe hits the SAME node the notice
        # named even if other nodes joined/left in the grace window
        self._preempt_victims[spec] = node.node_id
        runtime.on_preemption_notice(node.node_id, spec.grace_s,
                                     reason="chaos preempt schedule")

    def _execute_preempt_kill(self, runtime, spec: PreemptSpec) -> None:
        import signal

        node_id = self._preempt_victims.pop(spec, None)
        node = runtime.nodes.get(node_id) if node_id is not None else None
        if node is None or not node.alive:
            return  # drained and exited before the axe: nothing to kill
        proc = getattr(node, "_agent_proc", None)
        if proc is not None and proc.poll() is None:
            os.kill(proc.pid, signal.SIGKILL)
        else:
            # no local process handle (agent launched elsewhere): model
            # the platform kill head-side — channel loss semantics
            runtime.on_remote_node_lost(node_id)

    def stop(self) -> None:
        self._stop.set()


# ---------------------------------------------------------------------------
# global enable/disable — installs hooks into the host modules
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_ENGINE: Optional[ChaosEngine] = None


def enable(plan: Union[ChaosPlan, str], runtime=None) -> ChaosEngine:
    """Install the plan's hooks process-wide and start its kill schedule
    (when a runtime is given). Idempotent per plan object; re-enabling
    replaces the previous engine."""
    global _ENGINE
    if isinstance(plan, str):
        plan = ChaosPlan.parse(plan)
    eng = ChaosEngine(plan)
    with _LOCK:
        if _ENGINE is not None:
            _ENGINE.stop()
        _ENGINE = eng
    _install_hooks(eng)
    if runtime is not None:
        eng.start_kills(runtime)
    return eng


def disable() -> None:
    global _ENGINE
    with _LOCK:
        eng, _ENGINE = _ENGINE, None
    if eng is not None:
        eng.stop()
    _install_hooks(None)


def is_enabled() -> bool:
    return _ENGINE is not None


def engine() -> Optional[ChaosEngine]:
    return _ENGINE


def _install_hooks(eng: Optional[ChaosEngine]) -> None:
    from ..cgraph import channel as channel_mod
    from ..core import rpc as rpc_mod
    from ..core import runtime as runtime_mod

    rpc_mod._CHAOS = eng
    runtime_mod._CHAOS = eng
    channel_mod._CHAOS = eng


def plan_from_env() -> Optional[ChaosPlan]:
    spec = os.environ.get(ENV_VAR, "").strip()
    if not spec:
        return None
    return ChaosPlan.parse(spec)


def maybe_enable_from_env(runtime=None) -> Optional[ChaosEngine]:
    """Called at process bring-up (driver runtime, node agent, worker):
    installs the env-specified plan, if any. Each process draws from its
    own RNGs — determinism is per-process, per-point."""
    plan = plan_from_env()
    if plan is None:
        return None
    return enable(plan, runtime=runtime)
