"""Minimal cluster dashboard.

Equivalent of the reference's dashboard backend (ref: dashboard/
dashboard.py + datacenter.py aggregation; the React frontend is out of
scope — the reference ships ~1MB of compiled JS). One stdlib HTTP server
over the existing state API: `/` renders a self-refreshing HTML overview
(nodes, actors, tasks, placement groups, jobs, object stores) and
`/api/*` serves the same data as JSON for tooling.
"""
from __future__ import annotations

import html
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .util import state as state_api


def _jobs_rows():
    try:
        from . import jobs

        return jobs.list_jobs()
    except Exception:
        return []


_API = {
    "nodes": state_api.list_nodes,
    "actors": state_api.list_actors,
    "tasks": lambda: state_api.list_tasks(limit=200),
    "objects": lambda: state_api.list_objects(limit=200),
    "placement_groups": state_api.list_placement_groups,
    "object_store": state_api.object_store_stats,
    "summary": state_api.summary,
    "jobs": _jobs_rows,
}


def _table(title: str, rows) -> str:
    if isinstance(rows, dict):
        rows = [{"key": k, **v} if isinstance(v, dict) else
                {"key": k, "value": v} for k, v in rows.items()]
    if not rows:
        return f"<h2>{title}</h2><p class='empty'>none</p>"
    cols = list(rows[0].keys())
    head = "".join(f"<th>{html.escape(str(c))}</th>" for c in cols)
    body = "".join(
        "<tr>" + "".join(
            f"<td>{html.escape(str(r.get(c, '')))[:64]}</td>"
            for c in cols) + "</tr>"
        for r in rows[:100])
    return (f"<h2>{title} ({len(rows)})</h2>"
            f"<table><tr>{head}</tr>{body}</table>")


_STYLE = """<style>
body{font-family:monospace;margin:1.5em;background:#fafafa;color:#222}
table{border-collapse:collapse;margin-bottom:1em;font-size:12px}
td,th{border:1px solid #ccc;padding:2px 8px;text-align:left}
th{background:#eee}h1{font-size:18px}h2{font-size:14px;margin:0.6em 0 0.2em}
.empty{color:#999;font-size:12px}</style>"""


class Dashboard:
    """Serves the overview; run on the head (in-process thread, off the
    scheduling hot path)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8265):
        dash = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                path = self.path.split("?")[0].strip("/")
                if path.startswith("api/"):
                    fn = _API.get(path[4:])
                    if fn is None:
                        self._send(404, b'{"error": "unknown endpoint"}',
                                   "application/json")
                        return
                    try:
                        body = json.dumps(fn(), default=str).encode()
                        self._send(200, body, "application/json")
                    except Exception as e:  # noqa: BLE001
                        self._send(500, json.dumps(
                            {"error": repr(e)}).encode(),
                            "application/json")
                    return
                self._send(200, dash._render().encode(), "text/html")

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="dashboard")
        self._thread.start()

    def _render(self) -> str:
        parts = ["<html><head><title>ray_tpu dashboard</title>",
                 "<meta http-equiv='refresh' content='5'>", _STYLE,
                 "</head><body><h1>ray_tpu cluster</h1>"]
        try:
            parts.append(_table("Summary", [state_api.summary()]))
            parts.append(_table("Nodes", state_api.list_nodes()))
            parts.append(_table("Actors", state_api.list_actors()))
            parts.append(_table("Jobs", _jobs_rows()))
            parts.append(_table("Placement groups",
                                state_api.list_placement_groups()))
            parts.append(_table("Object stores",
                                state_api.object_store_stats()))
            parts.append(_table("Recent tasks",
                                state_api.list_tasks(limit=50)))
        except Exception as e:  # noqa: BLE001 — render what we can
            parts.append(f"<p class='empty'>error: {html.escape(repr(e))}"
                         f"</p>")
        parts.append("</body></html>")
        return "".join(parts)

    def address(self) -> tuple:
        return ("127.0.0.1", self._port)

    def shutdown(self) -> None:
        self._server.shutdown()


_dashboard: Optional[Dashboard] = None


def start_dashboard(host: str = "127.0.0.1", port: int = 8265) -> tuple:
    """Start (or return) the head's dashboard; -> (host, port)."""
    global _dashboard
    if _dashboard is None:
        _dashboard = Dashboard(host, port)
    return _dashboard.address()
