"""Cluster dashboard — single-page app over the state API.

Equivalent of the reference's dashboard (ref: dashboard/dashboard.py +
datacenter.py aggregation + the React SPA in dashboard/client; the
reference ships ~1MB of compiled JS — here the SPA is ~150 lines of
vanilla JS embedded below, served by a stdlib HTTP server). Views: live
overview with utilization bars and sparklines, nodes, actors, tasks
(filterable), placement groups, objects, jobs, and serve deployments.
`/api/*` serves every view's data as JSON for tooling; a background
sampler keeps a short metrics history for the sparklines (the analog of
the reference's metrics dashboard integration, scoped to in-process
history instead of Prometheus/Grafana).
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .util import state as state_api


def _jobs_rows():
    try:
        from . import jobs

        return jobs.list_jobs()
    except Exception:
        return []


def _serve_rows():
    try:
        import ray_tpu
        from .serve.controller import CONTROLLER_NAME

        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        status = ray_tpu.get(controller.status.remote(), timeout=5)
        return [{"deployment": name, **st} for name, st in status.items()]
    except Exception:
        return []


_API = {
    "nodes": state_api.list_nodes,
    "actors": state_api.list_actors,
    "tasks": lambda: state_api.list_tasks(limit=300),
    "objects": lambda: state_api.list_objects(limit=300),
    "placement_groups": state_api.list_placement_groups,
    "object_store": state_api.object_store_stats,
    "summary": state_api.summary,
    "rpc": state_api.rpc_method_stats,
    "latency": state_api.latency_summary,
    "jobs": _jobs_rows,
    "serve": _serve_rows,
    "logs": lambda: state_api.logs(limit=400)["records"],
    "stacks": lambda: state_api.stack_report(timeout=3.0),
    "log_store": state_api.log_store_stats,
    "timeline": state_api.timeline,
    "traces": lambda: state_api.traces(limit=100),
    "trace_store": state_api.trace_store_stats,
}

# parameterized drill-downs: /api/actor/<id>, /api/task/<id>,
# /api/logs/<worker_id_prefix>, /api/trace/<trace_id_prefix>
_API_ONE = {
    "actor": state_api.actor_detail,
    "task": state_api.task_detail,
    "logs": lambda wid: state_api.recent_logs(worker_id=wid, limit=400),
    "trace": state_api.trace_detail,
}

_HISTORY_LEN = 120  # 2s cadence -> 4 minutes of sparkline


class _MetricsSampler:
    """Background thread appending one overview sample every 2s — feeds
    the sparklines without a Prometheus round-trip."""

    def __init__(self):
        self.history: deque = deque(maxlen=_HISTORY_LEN)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        threading.Thread(target=self._loop, daemon=True,
                         name="dash-sampler").start()

    def snapshot(self) -> list:
        with self._lock:
            return list(self.history)

    def _loop(self) -> None:
        while not self._stop.wait(2.0):
            try:
                s = state_api.summary()
                stores = s.get("object_store", {})  # summary() already
                # computed this; a second call would double the per-node
                # RPC load on remote clusters
                if isinstance(stores, dict):
                    stores = list(stores.values())
                used = sum(st.get("used_bytes", st.get("used", 0))
                           for st in stores if isinstance(st, dict))
                tasks = s.get("task_events_by_state", {})
                fin = int(tasks.get("FINISHED", 0))
                with self._lock:
                    prev = self.history[-1] if self.history else None
                    rate = 0.0
                    if prev is not None:
                        dt = max(1e-9, time.time() - prev["t"])
                        rate = max(0.0,
                                   (fin - prev["finished_tasks"]) / dt)
                    self.history.append({
                        "t": time.time(),
                        "alive_nodes": s.get("nodes_alive", 0),
                        "actors": sum(s.get("actors_by_state",
                                            {}).values()),
                        "finished_tasks": fin,
                        "task_rate": round(rate, 2),
                        "store_used_bytes": used,
                    })
            except Exception:
                pass

    def stop(self) -> None:
        self._stop.set()


_PAGE = """<!doctype html><html><head><title>ray_tpu dashboard</title>
<style>
body{font-family:-apple-system,'Segoe UI',sans-serif;margin:0;background:#f6f7f9;color:#1a1d21}
header{background:#1a1d21;color:#fff;padding:10px 20px;display:flex;align-items:center;gap:16px}
header h1{font-size:16px;margin:0}
nav button{background:none;border:none;color:#aab;padding:6px 10px;cursor:pointer;font-size:13px;border-bottom:2px solid transparent}
nav button.active{color:#fff;border-color:#4c8dff}
main{padding:16px 20px;max-width:1200px}
table{border-collapse:collapse;width:100%;font-size:12px;font-family:ui-monospace,monospace;background:#fff}
td,th{border:1px solid #e2e5e9;padding:4px 8px;text-align:left;white-space:nowrap;overflow:hidden;max-width:260px;text-overflow:ellipsis}
th{background:#eef0f3;position:sticky;top:0}
.cards{display:flex;gap:12px;flex-wrap:wrap;margin-bottom:16px}
.card{background:#fff;border:1px solid #e2e5e9;border-radius:6px;padding:10px 14px;min-width:130px}
.card .v{font-size:22px;font-weight:600}.card .k{font-size:11px;color:#667}
.bar{background:#e8eaee;border-radius:3px;height:8px;width:120px;display:inline-block;vertical-align:middle}
.bar i{display:block;height:8px;border-radius:3px;background:#4c8dff}
input#q{padding:4px 8px;font-size:12px;margin-bottom:8px;width:240px}
svg.spark{vertical-align:middle}
.empty{color:#99a;font-size:12px;padding:12px}
</style></head><body>
<header><h1>ray_tpu</h1><nav id=nav></nav>
<a href="/api/timeline" download="timeline.json"
   style="font-size:11px;color:#8bf;margin-left:8px">timeline</a>
<span id=updated style="margin-left:auto;font-size:11px;color:#889"></span></header>
<main id=main></main>
<script>
const TABS=["overview","nodes","actors","tasks","placement_groups","objects","jobs","serve","logs","metrics"];
let tab="overview", filter="", detail=null;
const nav=document.getElementById("nav");
TABS.forEach(t=>{const b=document.createElement("button");b.textContent=t.replace("_"," ");
 b.onclick=()=>{tab=t;detail=null;render()};b.id="tab_"+t;nav.appendChild(b)});
function openDetail(kind,id){detail={kind,id};render()}
function esc(s){return String(s??"").replace(/[&<>"']/g,c=>({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;"}[c]))}
async function api(p){const r=await fetch("/api/"+p);return r.json()}
function spark(vals,w=140,h=28){if(!vals.length)return "";
 const mn=Math.min(...vals),mx=Math.max(...vals),rg=(mx-mn)||1;
 const pts=vals.map((v,i)=>`${(i/(vals.length-1||1)*w).toFixed(1)},${(h-2-(v-mn)/rg*(h-4)).toFixed(1)}`).join(" ");
 return `<svg class=spark width=${w} height=${h}><polyline points="${pts}" fill=none stroke=#4c8dff stroke-width=1.5/></svg>`}
function table(rows){if(!rows||!rows.length)return "<div class=empty>none</div>";
 const cols=Object.keys(rows[0]);
 let html="<table><tr>"+cols.map(c=>`<th>${esc(c)}</th>`).join("")+"</tr>";
 for(const r of rows.slice(0,200)){html+="<tr>"+cols.map(c=>{
  let v=r[c];if(v&&typeof v==="object")v=JSON.stringify(v);
  // drill-down links: actor/task ids open their detail page
  if(c==="actor_id"&&v)return `<td><a href="#" onclick="openDetail('actor','${esc(v)}');return false">${esc(v)}</a></td>`;
  if(c==="task_id"&&v)return `<td><a href="#" onclick="openDetail('task','${esc(v)}');return false">${esc(v)}</a></td>`;
  return `<td title="${esc(v)}">${esc(v)}</td>`}).join("")+"</tr>"}
 return html+"</table>"}
function logLines(rows){if(!rows||!rows.length)return "<div class=empty>no captured output</div>";
 return "<pre style='background:#fff;border:1px solid #e2e5e9;padding:8px;font-size:11px;overflow:auto;max-height:480px'>"+
  rows.map(r=>{const attrib=(r.task_id?` task=${esc(r.task_id.slice(0,8))}`:"")+(r.actor_id?` actor=${esc(r.actor_id.slice(0,8))}`:"");
   const mark=r.stream==="stderr"?" err":(r.stream==="log"?` ${esc(r.level||"INFO")}`:"");
   return `<span style="color:#99a">${new Date((r.ts||r.t)*1000).toLocaleTimeString()} [${esc((r.worker_id||"").slice(0,8))} pid=${esc(r.pid)}${attrib}${mark}]</span> ${esc(r.line)}`}).join("\\n")+"</pre>"}
function card(k,v,extra=""){return `<div class=card><div class=v>${esc(v)}</div><div class=k>${esc(k)}</div>${extra}</div>`}
async function render(){
 TABS.forEach(t=>document.getElementById("tab_"+t).classList.toggle("active",t===tab));
 const main=document.getElementById("main");
 try{
  if(detail){
   const d=await api(detail.kind+"/"+detail.id);
   let html=`<button onclick="detail=null;render()" style="margin-bottom:10px">&larr; back</button>`;
   if(!d){html+="<div class=empty>not found</div>";main.innerHTML=html;return}
   if(detail.kind==="actor"){
    html+=`<h3 style="font-size:14px">actor ${esc(d.actor_id)} — ${esc(d.class_name)} (${esc(d.state)})</h3>`;
    html+=table([{name:d.name,namespace:d.namespace,node:d.node_id,worker:d.worker_id,
                  restarts:d.num_restarts,detached:d.detached,death_cause:d.death_cause}]);
    html+=`<h4 style="font-size:12px">recent task events</h4>`+table(d.recent_events);
    html+=`<h4 style="font-size:12px">worker logs</h4>`+logLines(d.logs);
   } else {
    html+=`<h3 style="font-size:14px">task ${esc(d.task_id)} — ${esc(d.name)}</h3>`;
    if(d.pending)html+=table([d.pending]);
    html+=`<h4 style="font-size:12px">state transitions</h4>`+table(d.events);
   }
   main.innerHTML=html;
   document.getElementById("updated").textContent="updated "+new Date().toLocaleTimeString();
   return;
  }
  if(tab==="metrics"){
   const [hist,rpc,lat]=await Promise.all([api("metrics_history"),api("rpc"),api("latency")]);
   let html="";
   const series=[["finished tasks/s",h=>h.task_rate],["actors",h=>h.actors],
                 ["store used bytes",h=>h.store_used_bytes],["alive nodes",h=>h.alive_nodes]];
   for(const [name,f] of series){
    const vals=hist.map(f).map(v=>v??0);
    html+=`<div style="margin-bottom:14px"><div style="font-size:12px;color:#667">${esc(name)}
      <span style="float:right">${esc(vals.length?(Math.round(vals[vals.length-1]*100)/100):"-")}</span></div>
      ${spark(vals,560,60)}</div>`;
   }
   html+=`<h4 style="font-size:12px">latency percentiles (s, cluster-wide)</h4>`;
   const lrows=Object.entries(lat).map(([m,s])=>({histogram:m,count:s.count,
     mean:s.mean,p50:s.p50,p95:s.p95,p99:s.p99}));
   html+=table(lrows.sort((a,b)=>(b.count||0)-(a.count||0)));
   html+=`<h4 style="font-size:12px">per-RPC-method stats</h4>`;
   const rows=Object.entries(rpc).map(([m,s])=>({method:m,...s}));
   html+=table(rows.sort((a,b)=>(b.calls||0)-(a.calls||0)));
   main.innerHTML=html;
  } else if(tab==="logs"){
   const rows=await api("logs");
   const f=filter.toLowerCase();
   const shown=f?rows.filter(r=>JSON.stringify(r).toLowerCase().includes(f)):rows;
   main.innerHTML=`<input id=q placeholder="filter logs..." value="${esc(filter)}">`+logLines(shown);
   const q=document.getElementById("q");
   q.oninput=()=>{filter=q.value;render()};
  } else if(tab==="overview"){
   const [s,nodes,hist]=await Promise.all([api("summary"),api("nodes"),api("metrics_history")]);
   let cards="";
   const nact=Object.values(s.actors_by_state||{}).reduce((a,b)=>a+b,0);
   const nfin=(s.task_events_by_state||{}).FINISHED||0;
   cards+=card("alive nodes",s.nodes_alive??"-",spark(hist.map(h=>h.alive_nodes)));
   cards+=card("actors",nact,spark(hist.map(h=>h.actors)));
   cards+=card("finished tasks",nfin,spark(hist.map(h=>h.finished_tasks)));
   cards+=card("store used",fmtB(hist.length?hist[hist.length-1].store_used_bytes:0),
               spark(hist.map(h=>h.store_used_bytes)));
   let bars="<h3 style='font-size:13px'>Per-node CPU utilization</h3>";
   for(const n of nodes){const tot=(n.resources_total&&n.resources_total.CPU)||(n.total&&n.total.CPU)||0;
    const av=(n.resources_available&&n.resources_available.CPU)??(n.available&&n.available.CPU)??tot;
    const used=tot-av,pct=tot?Math.round(used/tot*100):0;
    bars+=`<div style="font-size:12px;margin:3px 0">${esc((n.node_id||"").slice(0,12))}
      <span class=bar><i style="width:${pct}%"></i></span> ${used.toFixed(1)}/${tot} CPU</div>`}
   main.innerHTML=`<div class=cards>${cards}</div>${bars}`;
  } else if(tab==="tasks"){
   const rows=await api("tasks");
   const f=filter.toLowerCase();
   const shown=f?rows.filter(r=>JSON.stringify(r).toLowerCase().includes(f)):rows;
   main.innerHTML=`<input id=q placeholder="filter tasks..." value="${esc(filter)}">`+table(shown);
   const q=document.getElementById("q");
   q.oninput=()=>{filter=q.value;render()};q.focus();q.setSelectionRange(filter.length,filter.length);
  } else {
   main.innerHTML=table(await api(tab));
  }
  document.getElementById("updated").textContent="updated "+new Date().toLocaleTimeString();
 }catch(e){main.innerHTML=`<div class=empty>error: ${e}</div>`}
}
function fmtB(b){if(!b)return "0";const u=["B","KB","MB","GB"];let i=0;
 while(b>=1024&&i<u.length-1){b/=1024;i++}return b.toFixed(1)+u[i]}
render();
setInterval(()=>{if(detail)return;if((tab==="tasks"||tab==="logs")&&filter)return;render()},2000);
</script></body></html>"""


class Dashboard:
    """Serves the SPA + JSON API; runs on the head (in-process thread,
    off the scheduling hot path)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8265):
        dash = self
        self._sampler: Optional[_MetricsSampler] = None

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                path = self.path.split("?")[0].strip("/")
                if path == "api/logs" and "?" in self.path:
                    # filtered log queries: /api/logs?task=&actor=&
                    # worker=&node=&stream=&errors=1&limit=N
                    from urllib.parse import parse_qs, urlparse

                    q = {k: v[0] for k, v in
                         parse_qs(urlparse(self.path).query).items()}
                    try:
                        rows = state_api.logs(
                            task_id=q.get("task") or None,
                            actor_id=q.get("actor") or None,
                            worker_id=q.get("worker") or None,
                            node_id=q.get("node") or None,
                            stream=q.get("stream") or None,
                            errors_only=q.get("errors") in ("1", "true"),
                            limit=int(q.get("limit", 400)))["records"]
                        self._send(200, json.dumps(
                            rows, default=str).encode(),
                            "application/json")
                    except Exception as e:  # noqa: BLE001
                        self._send(500, json.dumps(
                            {"error": repr(e)}).encode(),
                            "application/json")
                    return
                if path == "api/traces" and "?" in self.path:
                    # filtered trace queries: /api/traces?request=&
                    # session=&deployment=&slowest=N&limit=N
                    from urllib.parse import parse_qs, urlparse

                    q = {k: v[0] for k, v in
                         parse_qs(urlparse(self.path).query).items()}
                    try:
                        res = state_api.traces(
                            request_id=q.get("request") or None,
                            session=q.get("session") or None,
                            deployment=q.get("deployment") or None,
                            slowest=(int(q["slowest"])
                                     if q.get("slowest") else None),
                            limit=int(q.get("limit", 100)))
                        self._send(200, json.dumps(
                            res, default=str).encode(),
                            "application/json")
                    except Exception as e:  # noqa: BLE001
                        self._send(500, json.dumps(
                            {"error": repr(e)}).encode(),
                            "application/json")
                    return
                if path == "api/metrics_history":
                    samples = (dash._sampler.snapshot()
                               if dash._sampler is not None else [])
                    body = json.dumps(samples, default=str).encode()
                    self._send(200, body, "application/json")
                    return
                if path.startswith("api/"):
                    rest = path[4:]
                    fn = _API.get(rest)
                    arg = None
                    if fn is None and "/" in rest:
                        kind, _, arg = rest.partition("/")
                        one = _API_ONE.get(kind)
                        if one is not None and arg:
                            fn = lambda: one(arg)  # noqa: E731
                    if fn is None:
                        self._send(404, b'{"error": "unknown endpoint"}',
                                   "application/json")
                        return
                    try:
                        body = json.dumps(fn(), default=str).encode()
                        self._send(200, body, "application/json")
                    except Exception as e:  # noqa: BLE001
                        self._send(500, json.dumps(
                            {"error": repr(e)}).encode(),
                            "application/json")
                    return
                self._send(200, _PAGE.encode(), "text/html")

        # bind FIRST: a port-in-use failure must not leak a forever-
        # polling sampler thread
        self._server = ThreadingHTTPServer((host, port), Handler)
        self._sampler = _MetricsSampler()
        self._port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="dashboard")
        self._thread.start()

    def address(self) -> tuple:
        return ("127.0.0.1", self._port)

    def shutdown(self) -> None:
        if self._sampler is not None:
            self._sampler.stop()
        self._server.shutdown()
        self._server.server_close()  # release the listening fd


_dashboard: Optional[Dashboard] = None


def start_dashboard(host: str = "127.0.0.1", port: int = 8265) -> tuple:
    """Start (or return) the head's dashboard; -> (host, port)."""
    global _dashboard
    if _dashboard is None:
        _dashboard = Dashboard(host, port)
    return _dashboard.address()
