"""Wire codecs for compiled-graph channel payloads.

Generalizes the host-collective codec (parallel/quant.py,
docs/COLLECTIVES.md) onto the cgraph data plane: a producer whose node
plan negotiated a codec walks its output value, replaces every LARGE
float array (>= :data:`MIN_QUANT_BYTES`, float16/bfloat16/float32/
float64) with a block-scaled :class:`~ray_tpu.parallel.quant.
QuantizedTensor` wire record, and stamps the codec id into the
envelope's flag byte (channel.py bits 8-15). The consumer decodes
statelessly from that byte — no per-edge handshake, and an envelope
whose payload had nothing worth quantizing ships raw with flag 0, so
readers never pay a walk for small control traffic.

What this buys (the two spend sites named in ROADMAP item 2): pipeline
activation/cotangent hops between stage actors
(``CompiledPipelineEngine(wire_codec=...)``) and the disagg
prefill→decode KV shipment (``DisaggLLM(codec=...)``) cross the wire
at ~1/4 of their fp32 bytes. Error envelopes (FLAG_ERROR) are never
codec-encoded — fault propagation semantics are byte-identical with a
codec on.

Lossy by construction: values decode to their block-quantized image.
Callers opt in per graph/engine; integer/bool/bytes payloads and small
floats are always exact.
"""
from __future__ import annotations

from typing import Any, Optional, Tuple

import numpy as np

from ..core import serialization
from .channel import CODEC_IDS, CODEC_NAMES, FLAG_CODEC_MASK, \
    FLAG_CODEC_SHIFT

__all__ = ["MIN_QUANT_BYTES", "decode_value", "encode_value"]


def _q():
    # lazy: ray_tpu.parallel pulls in jax at import time, and cgraph
    # must stay importable by plain (non-jax) actors; the codec paths
    # only run where a codec was negotiated — jax territory already
    from ..parallel import quant

    return quant

# arrays below this size ship raw: the scale overhead and the walk are
# not worth it, and small control values (losses, reports, token ids)
# stay bit-exact by construction
MIN_QUANT_BYTES = 4096

_FLOAT_NAMES = ("float16", "bfloat16", "float32", "float64")


def _quantizable(x) -> bool:
    dt = getattr(x, "dtype", None)
    if dt is None or getattr(x, "ndim", None) is None:
        return False
    try:
        if str(dt) not in _FLOAT_NAMES:
            return False
        return int(x.size) * np.dtype(str(dt)).itemsize >= MIN_QUANT_BYTES
    except Exception:
        return False


def _walk(value, fn):
    """Structurally rebuild dict/list/tuple containers, applying ``fn``
    to array leaves. Anything else passes through untouched (a pickled
    object graph with arrays buried in custom classes ships raw — the
    codec only chases the shapes channel traffic actually has:
    arrays, and containers of arrays)."""
    if isinstance(value, dict):
        return {k: _walk(v, fn) for k, v in value.items()}
    if isinstance(value, tuple):
        return tuple(_walk(v, fn) for v in value)
    if isinstance(value, list):
        return [_walk(v, fn) for v in value]
    return fn(value)


def encode_value(value: Any, codec: Optional[str]) -> Tuple[int, bytes]:
    """-> (codec_flag_bits, body). Bits are 0 (and the body a plain
    serialization) when no codec is set or nothing crossed the size
    floor — the reader then takes the exact fast path."""
    if codec is None:
        return 0, serialization.dumps(value)
    quant = _q()
    quant.check_codec(codec)
    hit = False

    def enc(x):
        nonlocal hit
        if _quantizable(x):
            hit = True
            return quant.quantize(np.asarray(x), codec)
        return x

    transformed = _walk(value, enc)
    if not hit:
        return 0, serialization.dumps(value)
    return (CODEC_IDS[codec] << FLAG_CODEC_SHIFT,
            serialization.dumps(transformed))


def decode_value(flags: int, body: bytes) -> Any:
    """Inverse of :func:`encode_value`, driven entirely by the
    envelope's flag byte."""
    cid = (flags & FLAG_CODEC_MASK) >> FLAG_CODEC_SHIFT
    if cid == 0:
        return serialization.loads(body)
    if cid not in CODEC_NAMES:
        raise ValueError(
            f"envelope carries unknown wire-codec id {cid} — producer "
            f"and consumer disagree on the codec table")
    quant = _q()
    value = serialization.loads(body)

    def dec(x):
        if isinstance(x, quant.QuantizedTensor):
            return quant.dequantize(x)
        return x

    return _walk(value, dec)
