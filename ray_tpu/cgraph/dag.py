"""Bind-style DAG authoring surface for compiled graphs.

Mirrors the reference's accelerated-DAG authoring API (ref:
python/ray/dag/ — ``InputNode``, ``actor.method.bind(...)``,
``MultiOutputNode``, ``dag.experimental_compile()``): a DAG is declared
once over live ActorHandles, then compiled into persistent per-actor
execution loops fed by pre-allocated channels (see compiled.py).

    with InputNode() as inp:
        x = stage_a.fwd.bind(inp)
        x = stage_b.fwd.bind(x)
        dag = stage_c.fwd.bind(x)
    compiled = dag.experimental_compile()
    out = compiled.execute(batch).get()
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple


class DAGNode:
    """Base: a node in a statically-declared dataflow graph."""

    def experimental_compile(self, channel_bytes: Optional[int] = None,
                             max_inflight: int = 16,
                             codec: Optional[str] = None):
        """Compile the graph rooted at this output node. See
        ``CompiledDAG`` for the execution surface. ``codec``
        ("int8"/"e4m3", docs/COLLECTIVES.md) block-quantizes large
        float arrays in every edge payload — lossy, ~1/4 the channel
        bytes; error/seq semantics unchanged."""
        from .compiled import compile_dag

        return compile_dag(self, channel_bytes=channel_bytes,
                           max_inflight=max_inflight, codec=codec)

    def _upstream(self) -> List["DAGNode"]:
        return []


class InputNode(DAGNode):
    """Placeholder for the value passed to ``compiled.execute(x)``.

    Usable bare (``inp = InputNode()``) or as a context manager, matching
    the reference's ``with InputNode() as inp:`` idiom. Exactly one
    InputNode may appear in a graph; pass a tuple/dict through it when a
    stage needs several values.
    """

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc) -> None:
        pass

    def __repr__(self) -> str:
        return "InputNode()"


class ClassMethodNode(DAGNode):
    """``actor.method.bind(*args, **kwargs)`` — one actor-method call in
    the static graph. Args/kwargs may be other DAGNodes (dataflow edges)
    or plain values (constants, serialized once at compile time).
    ``ActorMethod.options(num_returns=, concurrency_group=)`` carries
    through ``bind()`` exactly as it does through ``remote()``."""

    def __init__(self, handle, method_name: str, args: Tuple,
                 kwargs: Dict[str, Any], num_returns: int = 1,
                 concurrency_group: str = ""):
        self._handle = handle
        self._method_name = method_name
        self._bound_args = tuple(args)
        self._bound_kwargs = dict(kwargs)
        self._num_returns = num_returns
        self._concurrency_group = concurrency_group

    def _upstream(self) -> List[DAGNode]:
        ups = [a for a in self._bound_args if isinstance(a, DAGNode)]
        ups += [v for v in self._bound_kwargs.values()
                if isinstance(v, DAGNode)]
        return ups

    def __repr__(self) -> str:
        return (f"ClassMethodNode({self._handle._description}."
                f"{self._method_name})")


class MultiOutputNode(DAGNode):
    """Bundle several terminal nodes; ``execute().get()`` returns their
    results as a list in declaration order."""

    def __init__(self, outputs: List[DAGNode]):
        if not outputs or not all(isinstance(o, DAGNode) for o in outputs):
            raise TypeError(
                "MultiOutputNode takes a non-empty list of DAGNodes")
        self._outputs = list(outputs)

    def _upstream(self) -> List[DAGNode]:
        return list(self._outputs)

    def __repr__(self) -> str:
        return f"MultiOutputNode({len(self._outputs)} outputs)"


def topological_nodes(root: DAGNode) -> List[DAGNode]:
    """All nodes reachable upstream of ``root``, topologically sorted
    (producers before consumers). Cycles raise — a static graph is a DAG."""
    order: List[DAGNode] = []
    state: Dict[int, int] = {}  # id -> 0 visiting, 1 done
    keep: Dict[int, DAGNode] = {}

    def visit(node: DAGNode) -> None:
        nid = id(node)
        st = state.get(nid)
        if st == 1:
            return
        if st == 0:
            raise ValueError("cycle detected in DAG — compiled graphs "
                             "must be acyclic")
        state[nid] = 0
        keep[nid] = node
        for up in node._upstream():
            visit(up)
        state[nid] = 1
        order.append(node)

    visit(root)
    return order
