"""Compiled-graph channels: pre-allocated, single-slot, point-to-point.

The data plane of `ray_tpu.cgraph` (ref: the reference's accelerated-DAG
channels — python/ray/experimental/channel/shared_memory_channel.py: a
mutable plasma object written in place per execution instead of one
immutable object per call). Two transports behind one reader/writer
contract:

- ``ShmChannel``: a pinned, never-sealed PlasmaStore segment
  (``store.allocate_channel``) shared by two processes on one host. The
  segment head is a tiny seq ledger (write_seq / read_seq / len /
  closed); the writer spins for slot vacancy (write_seq - read_seq <
  slots), writes the envelope, and publishes by bumping write_seq; the
  reader mirrors it. Slot occupancy IS the backpressure: a producer can
  run at most ``slots`` envelopes ahead of its consumer. DAG-mode
  compiled graphs use the classic single slot; the iterative pipeline
  engine (train/pipeline_cgraph.py) allocates ``slots=num_microbatches``
  rings so a whole 1F1B round's activations stream without a driver
  round trip per hop.

- ``QueueChannel``: the cross-node fallback fed by the existing worker
  RPC path — the producer ships the envelope up its node channel
  (``cgraph_send``), the head routes it to the consumer process
  (``cgraph_push``), and the consumer's loop pops it from this local
  queue. Latency is one control-plane hop; ordering is preserved by the
  per-channel monotonic seq.

Envelope: ``<II`` (flags, trace_len) + trace utf-8 + serialized body.
flags bit 0 = the body is a serialized exception (error propagation
through the graph); flags bits 8-15 carry the negotiated wire-codec id
(0 = raw, cgraph/codec.py — the body's large float arrays are
block-quantized and the READER decodes statelessly from this byte, so
mixed raw/compressed traffic shares one channel and seq/error
semantics never change); trace carries "trace_id:span_id" so per-stage
SPANs link into one cross-process flow in ``timeline()``.

Every producer-side ``send`` counts its envelope into
``ray_tpu_cgraph_channel_bytes_total{edge,codec}`` (the codec label
read from the envelope's own flag byte), so the bytes a codec saves on
a given edge are scrape-visible (docs/OBSERVABILITY.md).
"""
from __future__ import annotations

import queue as queue_mod
import struct
import threading
import time
from typing import Callable, Optional, Tuple

from ..exceptions import (ChannelFullError, CompiledGraphClosedError,
                          GetTimeoutError)
from ..perf.recorder import get_recorder
from ..util import metrics as _metrics

FLAG_ERROR = 1
# bits 8-15 of flags: wire-codec id stamped by the producer at pack
# time; 0 = raw body. The mapping is part of the envelope format so
# readers decode without per-edge negotiation state.
FLAG_CODEC_SHIFT = 8
FLAG_CODEC_MASK = 0xFF << FLAG_CODEC_SHIFT
CODEC_IDS = {"int8": 1, "e4m3": 2}
CODEC_NAMES = {v: k for k, v in CODEC_IDS.items()}

# fault-injection hook (ray_tpu.chaos): None until chaos.enable()
# installs an engine; hot paths pay one global is-None test
_CHAOS = None

# flight recorder (perf/recorder.py): every send/recv stamps its seq
# into the process ring when enabled; one attribute test when not
_FLREC = get_recorder()

# segment layout: header then the slot payload area
_HDR = struct.Struct("<QQQQ")  # write_seq, read_seq, data_len, closed
HEADER_BYTES = 64
_ENV = struct.Struct("<II")  # flags, trace_len

_H_EDGE_WAIT = _metrics.Histogram(
    "ray_tpu_cgraph_edge_wait_seconds",
    "blocking wait for a compiled-graph channel slot (read side)",
    boundaries=_metrics.FAST_BOUNDARIES, tag_keys=("edge",))
_C_CHAN_BYTES = _metrics.Counter(
    "ray_tpu_cgraph_channel_bytes_total",
    "envelope bytes written to compiled-graph channels, by edge and "
    "the envelope's own wire-codec byte",
    tag_keys=("edge", "codec"))


def _count_send(edge: str, data: bytes) -> None:
    """Producer-side bytes accounting; codec label comes from the
    envelope's flag byte so the counter reports what actually shipped
    (a small payload under the codec floor counts as raw)."""
    try:
        flags = _ENV.unpack_from(data, 0)[0]
        codec = CODEC_NAMES.get(
            (flags & FLAG_CODEC_MASK) >> FLAG_CODEC_SHIFT, "none")
    except struct.error:
        codec = "none"
    _C_CHAN_BYTES.inc(len(data), tags={"edge": edge, "codec": codec})


def pack_envelope(flags: int, trace: str, body: bytes) -> bytes:
    t = trace.encode()
    return _ENV.pack(flags, len(t)) + t + body


def unpack_envelope(data: bytes) -> Tuple[int, str, bytes]:
    flags, tlen = _ENV.unpack_from(data, 0)
    off = _ENV.size
    trace = data[off:off + tlen].decode()
    return flags, trace, data[off + tlen:]


class _Backoff:
    """Spin-then-yield-then-sleep poll ladder. The hot window (pipelined
    steady state) resolves in the spin/yield phases; an idle resident
    loop decays to ~2 ms sleeps so parked graphs cost ~no CPU."""

    __slots__ = ("spins",)

    def __init__(self):
        self.spins = 0

    def wait(self) -> None:
        self.spins += 1
        if self.spins < 100:
            return
        if self.spins < 5000:
            time.sleep(0)  # yield the core between probes
            return
        time.sleep(min(0.002, 0.00005 * (self.spins / 5000.0)))


def segment_size(slot_bytes: int, slots: int = 1) -> int:
    """Bytes to allocate for a channel of `slots` slots each holding
    envelopes up to `slot_bytes`. Single-slot keeps the original compact
    layout (len lives in the main header); rings prepend an 8-byte len
    word to every slot."""
    if slots <= 1:
        return HEADER_BYTES + slot_bytes
    return HEADER_BYTES + slots * (8 + slot_bytes)


class ShmChannel:
    """One endpoint of a shared-memory ring channel (`slots` >= 1).

    Both endpoints attach to the same segment through a SegmentReader
    mmap; role (reader/writer) is fixed at compile time. `interrupt` is
    an optional Event polled while blocked (teardown / stop signal).
    slots=1 is the classic compiled-graph single-slot layout; slots>1
    lays the payload area out as a ring of (len, data) slots indexed by
    seq % slots — same ledger, deeper backpressure window."""

    def __init__(self, reader, name: str, size: int, edge: str = "",
                 interrupt: Optional[threading.Event] = None,
                 slots: int = 1):
        self._segreader = reader
        self._name = name
        self._size = size
        self.edge = edge
        self._interrupt = interrupt
        self._mv = reader.read(name, size)
        self._slots = max(1, int(slots))
        if self._slots == 1:
            self.capacity = size - HEADER_BYTES
        else:
            self._slot_bytes = (size - HEADER_BYTES) // self._slots
            self.capacity = self._slot_bytes - 8

    # -- ledger ----------------------------------------------------------

    def _hdr(self) -> Tuple[int, int, int, int]:
        return _HDR.unpack_from(self._mv, 0)

    def _check_alive(self) -> None:
        if self._mv is None:
            raise CompiledGraphClosedError(
                f"channel {self._name} is closed")
        closed = _HDR.unpack_from(self._mv, 0)[3]
        if closed:
            raise CompiledGraphClosedError(
                f"channel {self._name} was closed by its peer")
        if self._interrupt is not None and self._interrupt.is_set():
            raise CompiledGraphClosedError(
                f"channel {self._name}: graph stopping")

    def mark_closed(self) -> None:
        """Poison the ledger so the peer's next poll raises (teardown /
        fault fencing); safe to call from either endpoint."""
        if self._mv is not None:
            try:
                struct.pack_into("<Q", self._mv, 24, 1)
            except ValueError:
                pass  # segment already unmapped

    # -- writer ----------------------------------------------------------

    def send(self, data: bytes, timeout: Optional[float] = None) -> None:
        if _CHAOS is not None and _CHAOS.channel_poison(self.edge):
            self.mark_closed()  # _check_alive below raises for both ends
        if len(data) > self.capacity:
            raise ChannelFullError(
                f"payload of {len(data)} bytes exceeds channel capacity "
                f"{self.capacity} (raise channel_bytes at compile time)")
        deadline = None if timeout is None else time.monotonic() + timeout
        bo = _Backoff()
        blocked = False
        while True:
            self._check_alive()
            w, r, _, _ = self._hdr()
            if w - r < self._slots:  # a slot is vacant
                break
            if not blocked:
                # a send stuck on a dead/stalled consumer leaves this
                # begin dangling — the post-mortem in-flight marker
                blocked = True
                if _FLREC.enabled:
                    _FLREC.record("chan.send.begin",
                                  self.edge or self._name, {"seq": w})
            if deadline is not None and time.monotonic() > deadline:
                raise GetTimeoutError(
                    f"channel {self.edge or self._name}: send timed out "
                    f"(all {self._slots} slots occupied — consumer "
                    f"stalled)")
            bo.wait()
        if self._slots == 1:
            self._mv[HEADER_BYTES:HEADER_BYTES + len(data)] = data
            struct.pack_into("<Q", self._mv, 16, len(data))
        else:
            # _slot_bytes INCLUDES the slot's 8-byte len word — it is
            # the stride, not the payload capacity (capacity above)
            off = HEADER_BYTES + (w % self._slots) * self._slot_bytes
            struct.pack_into("<Q", self._mv, off, len(data))
            self._mv[off + 8:off + 8 + len(data)] = data
        struct.pack_into("<Q", self._mv, 0, w + 1)  # publish
        _count_send(self.edge or self._name, data)
        if _FLREC.enabled:
            if blocked:
                _FLREC.record("chan.send.end", self.edge or self._name,
                              {"seq": w})
            _FLREC.record("chan.send", self.edge or self._name,
                          {"seq": w})

    # -- reader ----------------------------------------------------------

    def recv(self, timeout: Optional[float] = None) -> bytes:
        deadline = None if timeout is None else time.monotonic() + timeout
        bo = _Backoff()
        t0 = time.perf_counter()
        while True:
            self._check_alive()
            w, r, n, _ = self._hdr()
            if w > r:
                break
            if deadline is not None and time.monotonic() > deadline:
                raise GetTimeoutError(
                    f"channel {self.edge or self._name}: recv timed out")
            bo.wait()
        waited = time.perf_counter() - t0
        if waited > 1e-5:
            _H_EDGE_WAIT.observe(waited, tags={"edge": self.edge})
        # copy out BEFORE releasing the slot: the deserialized value may
        # alias these bytes zero-copy, and the producer overwrites the
        # slot the moment read_seq advances
        if self._slots == 1:
            data = bytes(self._mv[HEADER_BYTES:HEADER_BYTES + n])
        else:
            off = HEADER_BYTES + (r % self._slots) * self._slot_bytes
            n = struct.unpack_from("<Q", self._mv, off)[0]
            data = bytes(self._mv[off + 8:off + 8 + n])
        struct.pack_into("<Q", self._mv, 8, r + 1)  # release the slot
        if _FLREC.enabled:
            _FLREC.record("chan.recv", self.edge or self._name,
                          {"seq": r})
        return data

    def close(self) -> None:
        self.mark_closed()
        mv = self._mv
        self._mv = None
        if mv is not None:
            del mv
            try:
                self._segreader.release(self._name)
            except Exception:
                pass

    def detach(self) -> None:
        """Release this endpoint's mapping WITHOUT poisoning the
        ledger: the peer — and any successor endpoint attaching to the
        same segment — keeps running. This is the writer-role handoff
        primitive (the seq ledger is segment-resident, so a new writer
        resumes exactly where this one left off); the data feed's
        detach path uses it to hand the input rings back to the
        driver."""
        mv = self._mv
        self._mv = None
        if mv is not None:
            del mv
            try:
                self._segreader.release(self._name)
            except Exception:
                pass


class QueueChannel:
    """Consumer endpoint of a cross-node edge: a local queue fed by
    ``cgraph_push`` deliveries relayed through the head. Relay hops run
    on RPC handler POOLS (worker -> agent -> head -> consumer), so two
    back-to-back envelopes can arrive reordered when the pipeline engine
    streams a whole microbatch round down one edge; ``deliver`` holds
    early arrivals in a reorder buffer and releases them to the consumer
    strictly in seq order. (DAG-mode graphs never have two envelopes in
    flight per edge, so the buffer stays empty there.)"""

    def __init__(self, cid: str, edge: str = "",
                 interrupt: Optional[threading.Event] = None):
        self.cid = cid
        self.edge = edge
        self._interrupt = interrupt
        self._q: "queue_mod.Queue" = queue_mod.Queue()
        self._next_seq = 0
        self._closed = threading.Event()
        self._dlock = threading.Lock()
        self._deliver_seq = 0
        self._pending: dict = {}

    def deliver(self, seq: int, data: bytes) -> None:
        with self._dlock:
            self._pending[seq] = data
            while self._deliver_seq in self._pending:
                self._q.put((self._deliver_seq,
                             self._pending.pop(self._deliver_seq)))
                self._deliver_seq += 1

    def recv(self, timeout: Optional[float] = None) -> bytes:
        deadline = None if timeout is None else time.monotonic() + timeout
        t0 = time.perf_counter()
        while True:
            if self._closed.is_set() or (
                    self._interrupt is not None
                    and self._interrupt.is_set()):
                raise CompiledGraphClosedError(
                    f"channel {self.edge or self.cid}: graph stopping")
            try:
                seq, data = self._q.get(timeout=0.05)
            except queue_mod.Empty:
                if deadline is not None and time.monotonic() > deadline:
                    raise GetTimeoutError(
                        f"channel {self.edge or self.cid}: recv timed out")
                continue
            if data is None:  # close sentinel
                raise CompiledGraphClosedError(
                    f"channel {self.edge or self.cid} closed")
            if seq != self._next_seq:
                raise CompiledGraphClosedError(
                    f"channel {self.edge or self.cid}: out-of-order "
                    f"delivery (seq {seq}, expected {self._next_seq})")
            self._next_seq += 1
            waited = time.perf_counter() - t0
            if waited > 1e-5:
                _H_EDGE_WAIT.observe(waited, tags={"edge": self.edge})
            if _FLREC.enabled:
                _FLREC.record("chan.recv", self.edge or self.cid,
                              {"seq": seq})
            return data

    def close(self) -> None:
        self._closed.set()
        self._q.put((0, None))

    def mark_closed(self) -> None:
        self.close()


class RpcSender:
    """Producer endpoint of a cross-node edge: ships each envelope up the
    process's control channel (`send_fn`); the head routes it to the
    consumer. Seq stamps preserve the single-slot FIFO contract."""

    def __init__(self, send_fn: Callable[[str, int, bytes], None],
                 cid: str, edge: str = ""):
        self._send_fn = send_fn
        self.cid = cid
        self.edge = edge
        self._seq = 0

    def send(self, data: bytes, timeout: Optional[float] = None) -> None:
        seq = self._seq
        self._seq += 1
        self._send_fn(self.cid, seq, data)
        _count_send(self.edge or self.cid, data)
        if _FLREC.enabled:
            _FLREC.record("chan.send", self.edge or self.cid,
                          {"seq": seq})

    def close(self) -> None:
        pass

    def mark_closed(self) -> None:
        pass
