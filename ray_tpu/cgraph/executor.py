"""Worker-side compiled-graph execution: the resident loop.

The worker half of `ray_tpu.cgraph` (ref: the reference's accelerated-DAG
executor — python/ray/_private/worker.py exec_compiled_dag loop): at
``cgraph_load`` the worker builds its channel endpoints and method
dispatch table ONCE, then a resident thread runs the static plan forever
— read input slots, call the bound actor methods, write output slots —
with zero per-call scheduling, leasing, or task-spec traffic. Normal
``.remote()`` dispatch on the actor keeps working alongside the loop.

Error semantics: a stage exception becomes an error envelope forwarded
through the SAME channels (downstream stages skip execution and
propagate), so the driver's ``execute()`` ref raises the original
``TaskError``. An unexpected loop death poisons the node's channels so
peers (and ultimately the driver) fail fast instead of wedging.
"""
from __future__ import annotations

import threading
import time
import traceback
from typing import Any, Dict, List, Optional

from ..core import serialization
from ..exceptions import CompiledGraphClosedError, TaskError
from ..perf import oplog as _oplog
from ..perf.recorder import get_recorder
from ..util import metrics as _metrics
from ..util.logs import get_logger
from .channel import (FLAG_ERROR, QueueChannel, RpcSender, ShmChannel,
                      pack_envelope, unpack_envelope)
from .codec import decode_value, encode_value

_H_NODE_EXEC = _metrics.Histogram(
    "ray_tpu_cgraph_node_exec_seconds",
    "compiled-graph per-node method execution time",
    boundaries=_metrics.FAST_BOUNDARIES, tag_keys=("method",))
_H_STAGE_EXEC = _metrics.Histogram(
    "ray_tpu_pipeline_stage_exec_seconds",
    "pipeline-engine per-op compute time on a stage actor",
    boundaries=_metrics.FAST_BOUNDARIES, tag_keys=("stage",))
_H_BUBBLE_WAIT = _metrics.Histogram(
    "ray_tpu_pipeline_bubble_wait_seconds",
    "pipeline-engine time a stage spent blocked on channel input "
    "before an op (the 1F1B bubble as observed from inside the stage)",
    boundaries=_metrics.FAST_BOUNDARIES, tag_keys=("stage",))

_log = get_logger("ray_tpu.cgraph")

# flight recorder (perf/recorder.py): one enabled-flag test per record,
# chaos-style module handle so the A/B off leg costs one attribute load
_FLREC = get_recorder()


class _NodePlan:
    __slots__ = ("key", "method", "fn", "num_returns", "concurrency_group",
                 "args", "kwargs", "outs", "codec", "n_chan_args")


class _GraphRun:
    """One loaded graph on one actor worker."""

    def __init__(self, graph_id: bytes):
        self.graph_id = graph_id
        self.stop = threading.Event()
        self.readers: Dict[str, Any] = {}  # cid hex -> channel endpoint
        self.writers: List[Any] = []
        self.writer_cache: Dict[str, Any] = {}  # cid/shm name -> endpoint
        self.nodes: List[_NodePlan] = []
        self.thread: Optional[threading.Thread] = None
        # iterative (pipeline) mode: the node list is a per-STEP op
        # schedule — the same channel is read once per op, never cached
        # across ops, and pipeline stage metrics are recorded
        self.iterative = False
        self.stage_tag = ""


class CGraphExecutor:
    """Per-worker registry of loaded graphs + their resident threads."""

    def __init__(self, worker):
        self.worker = worker  # WorkerProcess
        self._lock = threading.Lock()
        self._graphs: Dict[bytes, _GraphRun] = {}
        # dedicated segment reader: channel attachments must not collide
        # with the task-result reader's cache lifecycle
        from ..core.object_store import SegmentReader

        self._segreader = SegmentReader()

    # -- control-plane entry points (worker_main.handle) -----------------

    def load(self, plan: dict) -> bool:
        actor = self.worker._actor
        if actor is None:
            raise RuntimeError(
                "cgraph_load sent to a worker that hosts no actor")
        gid = plan["graph_id"]
        with self._lock:
            if self._graphs:
                raise RuntimeError(
                    "actor already participates in a live compiled graph; "
                    "teardown() it before compiling another")
            run = _GraphRun(gid)
            self._graphs[gid] = run
        try:
            self._build(run, plan, actor)
        except BaseException:
            with self._lock:
                self._graphs.pop(gid, None)
            raise
        run.thread = threading.Thread(
            target=self._loop, args=(run,), daemon=True,
            name=f"cgraph-{gid.hex()[:8]}")
        run.thread.start()
        return True

    def push(self, payload: dict) -> None:
        """A cross-node envelope routed to one of our queue channels."""
        with self._lock:
            run = self._graphs.get(payload["graph_id"])
        if run is None:
            return  # late delivery after stop: drop
        ch = run.readers.get(payload["cid"])
        if isinstance(ch, QueueChannel):
            ch.deliver(payload["seq"], payload["data"])

    def stop(self, graph_id: bytes) -> bool:
        with self._lock:
            run = self._graphs.pop(graph_id, None)
        if run is None:
            return True
        run.stop.set()
        for ch in list(run.readers.values()) + run.writers:
            try:
                ch.mark_closed()
            except Exception:
                pass
        if run.thread is not None:
            run.thread.join(timeout=3.0)
        for ch in list(run.readers.values()) + run.writers:
            try:
                ch.close()
            except Exception:
                pass
        # ship this run's metric deltas NOW: a short-lived graph (a fast
        # pipeline engine torn down within the export interval) would
        # otherwise lose its stage_exec/bubble_wait samples when the
        # driver kills the actor right after this stop returns
        try:
            self.worker._flush_metrics()
        except Exception:
            pass
        return True

    def stop_all(self) -> None:
        with self._lock:
            gids = list(self._graphs)
        for gid in gids:
            self.stop(gid)

    # -- plan materialization --------------------------------------------

    def _make_reader(self, spec: dict, run: _GraphRun):
        if spec["kind"] == "shm":
            return ShmChannel(self._segreader, spec["name"], spec["size"],
                              edge=spec.get("edge", ""), interrupt=run.stop,
                              slots=spec.get("slots", 1))
        return QueueChannel(spec["cid"], edge=spec.get("edge", ""),
                            interrupt=run.stop)

    def _make_writer(self, spec: dict, run: _GraphRun):
        # one endpoint per channel per run: several ops write the same
        # edge in iterative (pipeline) plans — e.g. every microbatch's
        # fwd shares the activation edge — and a fresh RpcSender per op
        # would restart its seq stamp at 0 for each (shm endpoints share
        # the segment ledger, which masked this on single-host graphs)
        key = spec["name"] if spec["kind"] == "shm" else spec["cid"]
        cached = run.writer_cache.get(key)
        if cached is not None:
            return cached
        if spec["kind"] == "shm":
            ch = ShmChannel(self._segreader, spec["name"], spec["size"],
                            edge=spec.get("edge", ""), interrupt=run.stop,
                            slots=spec.get("slots", 1))
        else:
            gid = run.graph_id

            def send(cid, seq, data):
                self.worker.channel.call(
                    "cgraph_send", {"graph_id": gid, "cid": cid,
                                    "seq": seq, "data": data}, timeout=120)

            ch = RpcSender(send, spec["cid"], edge=spec.get("edge", ""))
        run.writer_cache[key] = ch
        run.writers.append(ch)
        return ch

    def _build(self, run: _GraphRun, plan: dict, actor) -> None:
        run.iterative = bool(plan.get("iterative"))
        run.stage_tag = str(plan.get("stage", ""))
        for spec in plan["in_channels"]:
            run.readers[spec["cid"]] = self._make_reader(spec, run)
        groups = getattr(actor, "_group_pools", {}) or {}
        for nspec in plan["nodes"]:
            np = _NodePlan()
            np.key = nspec["key"]
            np.method = nspec["method"]
            np.fn = getattr(actor.instance, nspec["method"])
            np.num_returns = int(nspec.get("num_returns", 1))
            np.concurrency_group = nspec.get("concurrency_group", "")
            if np.concurrency_group and np.concurrency_group not in groups:
                raise ValueError(
                    f"concurrency group {np.concurrency_group!r} bound via "
                    f".options() was not declared in concurrency_groups="
                    f"{sorted(groups)}")
            np.args = [self._load_argspec(a) for a in nspec["args"]]
            np.kwargs = {k: self._load_argspec(a)
                         for k, a in nspec["kwargs"].items()}
            np.n_chan_args = sum(
                1 for a in list(np.args) + list(np.kwargs.values())
                if a[0] == "chan")
            np.outs = [self._make_writer(w, run) for w in nspec["outs"]]
            # wire codec negotiated at compile time for this node's
            # output envelopes (cgraph/codec.py); readers are stateless
            # — the codec id rides in each envelope's flag byte
            np.codec = nspec.get("codec")
            run.nodes.append(np)

    @staticmethod
    def _load_argspec(spec):
        kind = spec[0]
        if kind == "const":
            return ("const", serialization.loads(spec[1]))
        return tuple(spec)  # ("chan", cid) | ("local", key)

    # -- the resident loop -----------------------------------------------

    def _loop(self, run: _GraphRun) -> None:
        try:
            while not run.stop.is_set():
                self._iteration(run)
        except CompiledGraphClosedError:
            pass  # clean stop/teardown
        except BaseException:
            # unexpected loop death: poison every endpoint so producers,
            # consumers, and ultimately the driver unblock with a typed
            # error instead of wedging on a silent half-dead pipeline
            _log.error("compiled-graph loop died:\n%s",
                       traceback.format_exc())
            _FLREC.record("cgraph.loop.death",
                          run.stage_tag or run.graph_id.hex()[:8],
                          {"error": traceback.format_exc(limit=3)})
            for ch in list(run.readers.values()) + run.writers:
                try:
                    ch.mark_closed()
                except Exception:
                    pass
            # worker-side half of the post-mortem: the driver's merged
            # bundle RPC can only reach us while we're alive, so dump
            # this process's ring locally too (throttled)
            try:
                from ..perf.postmortem import dump_bundle

                dump_bundle("cgraph loop death",
                            origin=f"worker:{run.stage_tag or 'dag'}",
                            meta={"graph_id": run.graph_id.hex()})
            except Exception:
                pass

    def _iteration(self, run: _GraphRun) -> None:
        local: Dict[str, tuple] = {}  # node key -> ("val", v)|("err", bytes)
        # DAG mode caches one envelope per cid per iteration so diamond
        # fan-outs share a single slot read; iterative (pipeline) plans
        # read the SAME channel once per op (M microbatches stream
        # through one edge per step), so caching would replay stale data
        chan_cache: Optional[Dict[str, tuple]] = (
            None if run.iterative else {})
        # iterative mode: errors can reach ops with NO outs (chunk 0's
        # backward, tied_add) where the envelope would otherwise die —
        # the step would then report clean losses over corrupted grads.
        # Latch the first error and ship it from the final op (the
        # update, whose out is the driver's report channel) instead of
        # applying an update over a broken accumulation.
        iter_err: Optional[bytes] = None
        last = run.nodes[-1] if run.nodes else None
        tag = run.stage_tag or run.graph_id.hex()[:8]
        for np in run.nodes:
            err_bytes = None
            parent_trace = ""
            t_waited = 0.0
            n_chan = 0
            args: List[Any] = []
            kwargs: Dict[str, Any] = {}

            def resolve(spec):
                nonlocal err_bytes, parent_trace, t_waited, n_chan
                kind = spec[0]
                if kind == "const":
                    return spec[1]
                if kind == "chan":
                    n_chan += 1
                    cid = spec[1]
                    env = None if chan_cache is None \
                        else chan_cache.get(cid)
                    if env is None:
                        # time ONLY the blocking recv — deserialization
                        # below is compute, not 1F1B bubble
                        t0 = time.perf_counter()
                        data = run.readers[cid].recv()
                        t_waited += time.perf_counter() - t0
                        env = unpack_envelope(data)
                        if chan_cache is not None:
                            chan_cache[cid] = env
                    flags, trace, body = env
                    if trace:
                        parent_trace = trace
                    if flags & FLAG_ERROR:
                        err_bytes = body
                        return None
                    return decode_value(flags, body)
                # ("local", key): same-actor edge, no channel round trip
                state, val = local[spec[1]]
                if state == "err":
                    err_bytes = val
                    return None
                return val

            # recv begin/end bracket the whole arg-resolve phase: a stage
            # blocked on a dead/stalled peer leaves a dangling begin the
            # post-mortem renderer flags as in-flight at death
            rec_on = _FLREC.enabled and np.n_chan_args
            if rec_on:
                _FLREC.record("cgraph.recv.begin", f"{tag}:{np.key}")
            for spec in np.args:
                args.append(resolve(spec))
            for k, spec in np.kwargs.items():
                kwargs[k] = resolve(spec)
            if rec_on:
                _FLREC.record("cgraph.recv.end", f"{tag}:{np.key}",
                              {"waited_ms": round(t_waited * 1e3, 3)}
                              if t_waited > 1e-4 else None)
            if run.iterative and n_chan:
                # ops with no channel inputs (update, tied_grad) would
                # pad the bubble histogram with guaranteed-zero samples
                _H_BUBBLE_WAIT.observe(t_waited,
                                       tags={"stage": run.stage_tag})
                _oplog.bubble_record(run.stage_tag, t_waited)
            if run.stop.is_set():
                raise CompiledGraphClosedError("graph stopping")

            if err_bytes is None and run.iterative and np is last \
                    and iter_err is not None:
                err_bytes = iter_err  # poison the report, skip the update
            trace_out = ""
            if err_bytes is None:
                if _FLREC.enabled:
                    _FLREC.record("cgraph.op.begin", f"{tag}:{np.key}",
                                  {"method": np.method})
                t_wall0 = time.time()
                t_exec0 = time.perf_counter()
                value, err_bytes, trace_out = self._exec_node(
                    np, args, kwargs, parent_trace)
                dt = time.perf_counter() - t_exec0
                if _FLREC.enabled:
                    _FLREC.record("cgraph.op.end", f"{tag}:{np.key}",
                                  {"error": True} if err_bytes else None)
                if run.iterative:
                    _H_STAGE_EXEC.observe(dt,
                                          tags={"stage": run.stage_tag})
                    _oplog.op_record(run.stage_tag, np.key, np.method,
                                     t_wall0, t_wall0 + dt)
            t_send0 = time.perf_counter() \
                if run.iterative and np.outs else 0.0
            if err_bytes is not None:
                if run.iterative:
                    iter_err = iter_err or err_bytes
                else:
                    local[np.key] = ("err", err_bytes)
                env = pack_envelope(FLAG_ERROR, trace_out or parent_trace,
                                    err_bytes)
            else:
                # iterative (pipeline) plans wire everything through
                # channels and never use ("local", key) args — retaining
                # every op's output here would hold all M activations/
                # cotangents live per step, breaking the bounded 1F1B
                # in-flight-memory property
                if not run.iterative:
                    local[np.key] = ("val", value)
                if np.outs:
                    cbits, body = encode_value(value, np.codec)
                else:
                    cbits, body = 0, b""
                env = pack_envelope(cbits, trace_out, body)
            for w in np.outs:
                w.send(env)
            if t_send0:
                # encode + channel writes, backpressure block included —
                # the step profiler's third measured phase
                _oplog.send_record(run.stage_tag,
                                   time.perf_counter() - t_send0)

    def _exec_node(self, np: _NodePlan, args, kwargs, parent_trace: str):
        """-> (value, error_bytes, downstream_trace)."""
        from ..util import tracing

        span_ctx = None
        token = None
        if parent_trace:
            tid, _, sid = parent_trace.partition(":")
            token = tracing.activate((tid, sid))
            span_ctx = tracing.trace(f"cgraph:{np.key}.{np.method}",
                                     method=np.method,
                                     concurrency_group=np.concurrency_group)
            span = span_ctx.__enter__()
        t0 = time.perf_counter()
        try:
            value = np.fn(*args, **kwargs)
            if np.num_returns > 1:
                if not isinstance(value, (tuple, list)) \
                        or len(value) != np.num_returns:
                    raise ValueError(
                        f"{np.method} bound with num_returns="
                        f"{np.num_returns} returned "
                        f"{type(value).__name__} instead of a "
                        f"{np.num_returns}-tuple")
            err = None
        except BaseException as e:  # noqa: BLE001 — shipped downstream
            err = serialization.dumps(TaskError(
                cause=e, remote_traceback=traceback.format_exc(),
                task_desc=f"cgraph:{np.key}.{np.method}"))
            value = None
        finally:
            _H_NODE_EXEC.observe(time.perf_counter() - t0,
                                 tags={"method": np.method})
            trace_out = ""
            if span_ctx is not None:
                try:
                    span_ctx.__exit__(None, None, None)
                    trace_out = f"{span.trace_id}:{span.span_id}"
                finally:
                    tracing.deactivate(token)
        return value, err, trace_out
