"""ray_tpu.cgraph — compiled graphs (accelerated DAGs).

Statically-declared dataflow over actors, compiled once into resident
per-actor execution loops fed by pre-allocated single-slot channels:
shared-memory segments for same-host edges, the worker RPC path across
nodes. Steady-state ``execute()`` bypasses the entire
submit→schedule→lease→RPC→put→get task pipeline — the execution shape
MPMD pipeline-parallel training and stage-to-stage serving need.

    import ray_tpu
    from ray_tpu.cgraph import InputNode

    with InputNode() as inp:
        dag = stage_b.fwd.bind(stage_a.fwd.bind(inp))
    compiled = dag.experimental_compile()
    try:
        out = compiled.execute(batch).get()
    finally:
        compiled.teardown()

See docs/COMPILED_GRAPHS.md for the channel design, failure semantics,
and benchmark numbers.
"""
from .compiled import CGraphRef, CompiledDAG, compile_dag
from .dag import ClassMethodNode, DAGNode, InputNode, MultiOutputNode

__all__ = [
    "InputNode", "MultiOutputNode", "DAGNode", "ClassMethodNode",
    "CompiledDAG", "CGraphRef", "compile_dag",
]
