"""Driver-side compiled graphs: compile, execute, teardown, fault paths.

The control half of `ray_tpu.cgraph` (ref: the reference's 3.0 headline
accelerated DAGs — python/ray/dag/compiled_dag_node.py): compile walks
the bound DAG once, resolves every actor's placement, pre-allocates one
single-slot channel per edge (shared-memory segments for same-host
edges, the worker RPC path across nodes), ships each actor a static
execution plan, and starts resident loops. Steady-state ``execute(x)``
then does ZERO scheduling, leasing, task-spec serialization, or GCS
traffic — the driver writes the input envelope into the first-stage
slots and the pipeline flows.

Fault contract: a participating actor dying (or a channel peer closing)
aborts the graph — every in-flight ``execute()`` ref raises
``CompiledGraphClosedError``; stage-level user exceptions propagate
through the channels and raise the original ``TaskError`` from the ref
without killing the graph. ``teardown()`` stops the loops, releases
every pre-allocated segment (PlasmaStore accounting returns to
pre-compile levels), and frees the actors for normal ``.remote()`` use
or a fresh compile.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..core import serialization
from ..core.ids import ObjectId
from ..exceptions import (CompiledGraphClosedError, CompiledGraphError,
                          GetTimeoutError)
from ..util import metrics as _metrics
from ..util import tracing
from .channel import (FLAG_ERROR, QueueChannel, RpcSender, ShmChannel,
                      pack_envelope, segment_size, unpack_envelope)
from .codec import decode_value, encode_value
from .dag import (ClassMethodNode, DAGNode, InputNode, MultiOutputNode,
                  topological_nodes)

DEFAULT_CHANNEL_BYTES = 4 * 1024 * 1024

_H_ROUNDTRIP = _metrics.Histogram(
    "ray_tpu_cgraph_roundtrip_seconds",
    "compiled-graph execute() -> result latency as observed by the driver",
    boundaries=_metrics.FAST_BOUNDARIES, tag_keys=("graph",))
_C_EXECUTIONS = _metrics.Counter(
    "ray_tpu_cgraph_executions_total",
    "executions submitted to a compiled graph", tag_keys=("graph",))


class CGraphRef:
    """Future-like handle for one ``execute()``. ``ray_tpu.get(ref)``
    works through the ``__rtpu_result__`` protocol."""

    __slots__ = ("_dag", "seq")

    def __init__(self, dag: "CompiledDAG", seq: int):
        self._dag = dag
        self.seq = seq

    def get(self, timeout: Optional[float] = None):
        return self._dag._fetch(self.seq, timeout)

    def __rtpu_result__(self, timeout: Optional[float] = None):
        return self.get(timeout)

    def __repr__(self) -> str:
        return f"CGraphRef(graph={self._dag.graph_id.hex()[:8]}, " \
               f"seq={self.seq})"


class _ActorPlan:
    __slots__ = ("actor_id", "node", "worker", "nodes", "in_specs")

    def __init__(self, actor_id, node, worker):
        self.actor_id = actor_id
        self.node = node
        self.worker = worker
        self.nodes: List[dict] = []
        self.in_specs: List[dict] = []


class CompiledDAG:
    """A live compiled graph. Built by ``compile_dag`` (via
    ``DAGNode.experimental_compile()``); never constructed directly."""

    def __init__(self, rt, output_node: DAGNode, channel_bytes: int,
                 max_inflight: int, codec: Optional[str] = None):
        self._rt = rt
        self._output_node = output_node
        self.graph_id = os.urandom(16)
        self._channel_bytes = int(channel_bytes)
        self._max_inflight = int(max_inflight)
        # wire codec for every edge payload (cgraph/codec.py): large
        # float arrays ship block-quantized; None = raw envelopes
        self._codec = codec
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # serializes execute(): input-slot writes must land in issue
        # order or concurrent submitters would cross-wire result seqs
        self._send_lock = threading.Lock()
        # serializes the teardown BODY: a concurrent teardown (atexit vs
        # actor-death abort vs explicit call) must block until channels
        # are actually released, not return while segments are still
        # allocated. REENTRANT so a signal handler or close-callback
        # re-entering on the tearing thread returns via the torn flag
        # instead of self-deadlocking.
        self._teardown_lock = threading.RLock()
        self._stop = threading.Event()  # interrupt for blocked endpoints
        self._torn = False
        self._closed_error: Optional[Exception] = None
        self._issued = 0
        self._next_out = 0
        self._results: Dict[int, tuple] = {}
        self._issue_t: Dict[int, float] = {}
        self._drainer_active = False
        # envelopes already consumed for the in-progress execution: a
        # timeout mid-way through a multi-output drain must not discard
        # them (channel reads are destructive) or every later result
        # would cross-wire between terminals
        self._partial_outs: List[tuple] = []
        # filled by compile
        self._actor_plans: Dict[bytes, _ActorPlan] = {}
        self._input_writers: List[Any] = []
        self._output_readers: List[Any] = []
        self._alloc: List[Tuple[Any, ObjectId]] = []  # (node, cid)
        self._multi_output = False
        self._unsub = None
        self._gtag = self.graph_id.hex()[:8]

    # -- execution surface -----------------------------------------------

    def execute(self, value: Any = None,
                timeout: Optional[float] = None) -> CGraphRef:
        """Push one input through the graph; returns a ref whose
        ``get()`` blocks for that execution's output. Raises
        ``CompiledGraphError`` when more than ``max_inflight`` results
        are outstanding (consume earlier refs first)."""
        with self._send_lock:
            with self._lock:
                self._check_open()
                if self._issued - self._next_out >= self._max_inflight:
                    raise CompiledGraphError(
                        f"{self._issued - self._next_out} executions "
                        f"already in flight (max_inflight="
                        f"{self._max_inflight}); get() earlier results "
                        f"before submitting more")
                seq = self._issued
                self._issued += 1
                self._issue_t[seq] = time.perf_counter()
            ctx = tracing.current_context()
            trace = f"{ctx[0]}:{ctx[1]}" if ctx else ""
            cbits, body = encode_value(value, self._codec)
            env = pack_envelope(cbits, trace, body)
            sent = 0
            try:
                for w in self._input_writers:
                    w.send(env, timeout=timeout)
                    sent += 1
            except BaseException as e:
                if sent == 0:
                    # nothing entered the pipeline: retract the seq so
                    # result ordering stays aligned (caller may retry;
                    # safe under _send_lock — no later seq exists yet)
                    with self._lock:
                        self._issue_t.pop(seq, None)
                        self._issued -= 1
                else:
                    # partial delivery: some first stages consumed input
                    # #seq, others never will — pipeline inconsistent
                    self._abort(CompiledGraphClosedError(
                        f"compiled graph {self._gtag}: input {seq} was "
                        f"only partially delivered ({sent}/"
                        f"{len(self._input_writers)} first-stage "
                        f"channels)"))
                if isinstance(e, CompiledGraphClosedError):
                    raise self._closed_reason()
                raise
        _C_EXECUTIONS.inc(tags={"graph": self._gtag})
        return CGraphRef(self, seq)

    async def execute_async(self, value: Any = None):
        """Async variant: ``fut = await dag.execute_async(x)`` submits
        without blocking the event loop and returns an awaitable that
        resolves to the result."""
        import asyncio

        loop = asyncio.get_running_loop()
        ref = await loop.run_in_executor(None, self.execute, value)
        return loop.run_in_executor(None, ref.get)

    def _check_open(self) -> None:
        if self._closed_error is not None or self._torn:
            raise self._closed_reason()

    def _closed_reason(self) -> Exception:
        err = self._closed_error
        if err is None:
            err = CompiledGraphClosedError(
                f"compiled graph {self._gtag} was torn down")
        return type(err)(str(err))

    # -- result intake -----------------------------------------------------

    def _fetch(self, seq: int, timeout: Optional[float]):
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._cond:
                res = self._results.pop(seq, None)
                if res is None and seq < self._next_out:
                    raise CompiledGraphError(
                        f"result {seq} was already consumed")
                if res is not None:
                    self._issue_t.pop(seq, None)
                    state, val = res
                    if state == "err":
                        raise val  # the stage's TaskError, verbatim
                    return val
                if self._closed_error is not None:
                    raise self._closed_reason()
                if self._drainer_active:
                    self._cond.wait(timeout=0.1)
                    if deadline is not None \
                            and time.monotonic() > deadline:
                        raise GetTimeoutError(
                            f"cgraph result {seq} not ready in time")
                    continue
                self._drainer_active = True
            try:
                self._drain_one(deadline)
            finally:
                with self._cond:
                    self._drainer_active = False
                    self._cond.notify_all()

    def _drain_one(self, deadline: Optional[float]) -> None:
        """Read ONE execution's outputs (one envelope per terminal) and
        buffer them under the next output seq. Resumes from
        ``_partial_outs`` after a mid-drain timeout — reads are
        destructive, so consumed envelopes must survive the raise."""
        outs = self._partial_outs
        while len(outs) < len(self._output_readers):
            r = self._output_readers[len(outs)]
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            try:
                data = r.recv(timeout=remaining)
            except GetTimeoutError:
                raise  # outs stays stashed; the next drain resumes here
            except CompiledGraphClosedError:
                with self._cond:
                    if self._closed_error is None:
                        self._closed_error = CompiledGraphClosedError(
                            f"compiled graph {self._gtag}: channel peer "
                            f"closed while executions were in flight")
                    self._cond.notify_all()
                raise self._closed_reason()
            flags, _trace, body = unpack_envelope(data)
            if flags & FLAG_ERROR:
                outs.append(("err", serialization.loads(body)))
            else:
                outs.append(("val", decode_value(flags, body)))
        self._partial_outs = []
        err = next((o for o in outs if o[0] == "err"), None)
        if err is not None:
            res = err
        elif self._multi_output:
            res = ("val", [o[1] for o in outs])
        else:
            res = ("val", outs[0][1])
        with self._cond:
            seq = self._next_out
            self._next_out += 1
            self._results[seq] = res
            t0 = self._issue_t.get(seq)
            if t0 is not None:
                _H_ROUNDTRIP.observe(time.perf_counter() - t0,
                                     tags={"graph": self._gtag})
            self._cond.notify_all()

    def _deliver(self, cid: str, seq: int, data: bytes) -> None:
        """Cross-node terminal envelope routed here by the head."""
        for r in self._output_readers:
            if isinstance(r, QueueChannel) and r.cid == cid:
                r.deliver(seq, data)
                return

    # -- fault + teardown --------------------------------------------------

    def _on_actor_event(self, msg) -> None:
        try:
            actor_id, state = msg
        except Exception:
            return
        from ..core.gcs import ActorState

        if state != ActorState.DEAD:
            return
        key = actor_id.binary() if hasattr(actor_id, "binary") else None
        if key in self._actor_plans:
            self._abort(CompiledGraphClosedError(
                f"compiled graph {self._gtag}: actor "
                f"{actor_id.hex()[:8]} died while the graph was live"))

    def _abort(self, err: Exception) -> None:
        with self._cond:
            if self._closed_error is None:
                self._closed_error = err
            self._cond.notify_all()
        self.teardown()

    def teardown(self) -> None:
        """Stop the resident loops, release every pre-allocated channel
        segment, and error any still-pending refs. Idempotent AND
        race-safe: a second concurrent caller blocks until the first
        finished releasing; a reentrant call (signal handler on the
        tearing thread) returns immediately via the torn flag."""
        with self._teardown_lock:
            self._teardown_locked()

    def _teardown_locked(self) -> None:
        with self._cond:
            if self._torn:
                return
            self._torn = True
            if self._closed_error is None:
                self._closed_error = CompiledGraphClosedError(
                    f"compiled graph {self._gtag} was torn down")
            self._cond.notify_all()
        self._stop.set()
        if self._unsub is not None:
            try:
                self._unsub()
            except Exception:
                pass
        # poison driver endpoints first so blocked peers unblock
        for ch in self._input_writers + self._output_readers:
            try:
                ch.mark_closed()
            except Exception:
                pass
        # stop the resident loops (best effort — a dead actor's worker
        # is gone, which is exactly why we are here)
        for plan in self._actor_plans.values():
            try:
                plan.node.worker_cgraph_call(
                    plan.worker, "cgraph_stop",
                    {"graph_id": self.graph_id}, timeout=10.0)
            except Exception:
                pass
        for ch in self._input_writers + self._output_readers:
            try:
                ch.close()
            except Exception:
                pass
        # release the segments — store accounting returns to pre-compile
        for node, cid in self._alloc:
            try:
                if getattr(node, "is_remote", False):
                    node.channel.call("cgraph_release_channel",
                                      {"cid": cid}, timeout=10)
                else:
                    node.store.release_channel(cid)
            except Exception:
                pass
        self._alloc = []
        self._rt._cgraph_unregister(self)
        # the DAG object becomes compilable again
        try:
            self._output_node._cgraph_compiled = False
        except Exception:
            pass

    def __del__(self):
        try:
            if not self._torn:
                self.teardown()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# compilation
# ---------------------------------------------------------------------------


def compile_dag(output_node: DAGNode, channel_bytes: Optional[int] = None,
                max_inflight: int = 16,
                codec: Optional[str] = None) -> CompiledDAG:
    from ..core import runtime as runtime_mod

    rt = runtime_mod.get_runtime()
    if not hasattr(rt, "gcs"):
        raise CompiledGraphError(
            "experimental_compile() must run on the driver")
    if getattr(output_node, "_cgraph_compiled", False):
        raise CompiledGraphError(
            "this DAG is already compiled; call teardown() on the "
            "existing CompiledDAG before compiling it again")

    nodes = topological_nodes(output_node)
    multi = output_node if isinstance(output_node, MultiOutputNode) else None
    if any(isinstance(n, MultiOutputNode) and n is not multi for n in nodes):
        raise CompiledGraphError(
            "MultiOutputNode may only be the root of the DAG")
    inputs = [n for n in nodes if isinstance(n, InputNode)]
    if len(inputs) != 1:
        raise CompiledGraphError(
            f"a compiled graph needs exactly one InputNode "
            f"(found {len(inputs)}); pass a tuple through it for "
            f"multi-value inputs")
    cnodes: List[ClassMethodNode] = [
        n for n in nodes if isinstance(n, ClassMethodNode)]
    if not cnodes:
        raise CompiledGraphError("a compiled graph needs at least one "
                                 "actor.method.bind(...) node")
    terminals = list(multi._outputs) if multi is not None else [output_node]
    for t in terminals:
        if not isinstance(t, ClassMethodNode):
            raise CompiledGraphError(
                "graph outputs must be actor-method nodes")
    for n in cnodes:
        if not isinstance(n._num_returns, int):
            raise CompiledGraphError(
                f"num_returns={n._num_returns!r} is not supported in "
                f"compiled graphs (streaming methods need the dynamic "
                f".remote() path)")

    if codec is not None:
        from ..parallel.quant import check_codec

        check_codec(codec)
    dag = CompiledDAG(rt, output_node, channel_bytes
                      or DEFAULT_CHANNEL_BYTES, max_inflight, codec=codec)
    try:
        _compile_into(dag, rt, cnodes, inputs[0], terminals,
                      multi is not None)
    except BaseException:
        # unwind partial allocations/loads — a failed compile must leak
        # nothing and leave the actors free
        try:
            dag.teardown()
        except Exception:
            pass
        raise
    output_node._cgraph_compiled = True
    return dag


def _compile_into(dag: CompiledDAG, rt, cnodes, input_node, terminals,
                  multi_output: bool) -> None:
    seg_size = segment_size(dag._channel_bytes)
    dag._multi_output = multi_output

    # -- placement: every bound actor must be alive with a resident worker
    for n in cnodes:
        akey = n._handle._actor_id.binary()
        if akey in dag._actor_plans:
            continue
        if rt._cgraph_actor_in_use(n._handle._actor_id):
            raise CompiledGraphError(
                f"actor {n._handle._actor_id.hex()[:8]} already "
                f"participates in another live compiled graph; "
                f"teardown() it first")
        rt.wait_for_actor(n._handle._actor_id, timeout=60.0)
        rec = rt._actors.get(n._handle._actor_id)
        if rec is None or rec.worker is None or rec.node_id is None:
            raise CompiledGraphError(
                f"actor {n._handle._actor_id.hex()[:8]} has no resident "
                f"worker to compile onto")
        node = rt.nodes.get(rec.node_id)
        if node is None or not node.alive:
            raise CompiledGraphError(
                f"actor {n._handle._actor_id.hex()[:8]}'s node is gone")
        dag._actor_plans[akey] = _ActorPlan(n._handle._actor_id, node,
                                            rec.worker)

    keys: Dict[int, str] = {}
    for idx, n in enumerate(cnodes):
        keys[id(n)] = f"{idx}:{n._method_name}"

    from ..core.object_store import SegmentReader

    dag._segreader = SegmentReader()

    def alloc_on(node) -> Tuple[ObjectId, str]:
        cid = ObjectId.from_random()
        if getattr(node, "is_remote", False):
            name = node.channel.call(
                "cgraph_alloc_channel", {"cid": cid, "size": seg_size},
                timeout=30)
        else:
            name = node.store.allocate_channel(cid, seg_size)
        dag._alloc.append((node, cid))
        return cid, name

    def make_edge(producer, consumer_plan: _ActorPlan, edge: str):
        """Allocate the channel for one producer->consumer edge. Returns
        (writer_spec_for_producer_plan, reader_spec_for_consumer_plan);
        `producer` is an _ActorPlan or "driver"."""
        same_host = (
            producer == "driver" and not getattr(consumer_plan.node,
                                                 "is_remote", False)
        ) or (
            producer != "driver"
            and producer.node is consumer_plan.node)
        if same_host:
            cid, name = alloc_on(consumer_plan.node)
            spec = {"kind": "shm", "name": name, "size": seg_size,
                    "cid": cid.hex(), "edge": edge}
            return spec, dict(spec)
        cid = ObjectId.from_random()
        wspec = {"kind": "rpc", "cid": cid.hex(), "edge": edge}
        rspec = {"kind": "queue", "cid": cid.hex(), "edge": edge}
        rt._cgraph_routes[cid.hex()] = (
            "worker", consumer_plan.node, consumer_plan.worker,
            dag.graph_id)
        return wspec, rspec

    # -- build node plans in topo order, wiring channels per edge. One
    # channel per (producer, consumer ACTOR): a diamond fan-out into
    # several nodes of one actor shares a single slot — the producer
    # writes once, and the consumer loop's per-iteration envelope cache
    # serves every node reading that cid.
    out_writer_specs: Dict[int, List[dict]] = {id(n): [] for n in cnodes}
    edge_cache: Dict[tuple, tuple] = {}
    for n in cnodes:
        plan = dag._actor_plans[n._handle._actor_id.binary()]
        nkey = keys[id(n)]

        def argspec(a):
            if isinstance(a, InputNode):
                cached = edge_cache.get((id(a), id(plan)))
                if cached is not None:
                    return cached
                edge = f"in->{nkey}"
                wspec, rspec = make_edge("driver", plan, edge)
                if wspec["kind"] == "shm":
                    dag._input_writers.append(ShmChannel(
                        dag._segreader, wspec["name"], wspec["size"],
                        edge=edge, interrupt=dag._stop))
                else:
                    dag._input_writers.append(_driver_sender(
                        dag, plan, wspec))
                plan.in_specs.append(rspec)
                spec = ("chan", rspec["cid"])
                edge_cache[(id(a), id(plan))] = spec
                return spec
            if isinstance(a, ClassMethodNode):
                pplan = dag._actor_plans[a._handle._actor_id.binary()]
                if pplan is plan:
                    return ("local", keys[id(a)])
                cached = edge_cache.get((id(a), id(plan)))
                if cached is not None:
                    return cached
                edge = f"{keys[id(a)]}->{nkey}"
                wspec, rspec = make_edge(pplan, plan, edge)
                out_writer_specs[id(a)].append(wspec)
                plan.in_specs.append(rspec)
                spec = ("chan", rspec["cid"])
                edge_cache[(id(a), id(plan))] = spec
                return spec
            if isinstance(a, DAGNode):
                raise CompiledGraphError(
                    f"cannot bind a {type(a).__name__} as an argument")
            return ("const", serialization.dumps(a))

        nspec = {"key": nkey, "method": n._method_name,
                 "num_returns": int(n._num_returns),
                 "concurrency_group": n._concurrency_group,
                 "codec": dag._codec,
                 "args": [argspec(a) for a in n._bound_args],
                 "kwargs": {k: argspec(v)
                            for k, v in n._bound_kwargs.items()},
                 "outs": out_writer_specs[id(n)]}
        plan.nodes.append(nspec)

    # -- terminal edges: each graph output flows back to the driver
    for t in terminals:
        tplan = dag._actor_plans[t._handle._actor_id.binary()]
        tkey = keys[id(t)]
        edge = f"{tkey}->out"
        if not getattr(tplan.node, "is_remote", False):
            cid, name = alloc_on(tplan.node)
            spec = {"kind": "shm", "name": name, "size": seg_size,
                    "cid": cid.hex(), "edge": edge}
            out_writer_specs[id(t)].append(spec)
            dag._output_readers.append(ShmChannel(
                dag._segreader, name, seg_size, edge=edge,
                interrupt=dag._stop))
        else:
            cid = ObjectId.from_random()
            out_writer_specs[id(t)].append(
                {"kind": "rpc", "cid": cid.hex(), "edge": edge})
            q = QueueChannel(cid.hex(), edge=edge, interrupt=dag._stop)
            dag._output_readers.append(q)
            rt._cgraph_routes[cid.hex()] = ("driver", dag, None,
                                            dag.graph_id)

    # note: `outs` lists inside nspec alias out_writer_specs entries, so
    # terminal specs appended above are already visible in the plans

    # -- register, then load every worker (routes must exist before the
    # first resident loop sends anything)
    rt._cgraph_register(dag)
    for plan in dag._actor_plans.values():
        payload = {"graph_id": dag.graph_id,
                   "actor_id": plan.actor_id,
                   "in_channels": plan.in_specs,
                   "nodes": plan.nodes}
        plan.node.worker_cgraph_call(plan.worker, "cgraph_load", payload,
                                     timeout=30.0)
    dag._unsub = rt.gcs.pubsub.subscribe("actor", dag._on_actor_event)


def _driver_sender(dag: CompiledDAG, plan: _ActorPlan,
                   wspec: dict) -> RpcSender:
    """Driver -> remote first stage: push envelopes straight down the
    agent channel (no head hop — the driver IS the head)."""

    def send(cid, seq, data):
        plan.node.worker_notify(plan.worker, "cgraph_push",
                                {"graph_id": dag.graph_id, "cid": cid,
                                 "seq": seq, "data": data})

    return RpcSender(send, wspec["cid"], edge=wspec["edge"])
