"""Gang-scheduled mesh formation.

The TPU analog of the reference's process-group bootstrap: TorchConfig's
`_setup_torch_process_group` (ref: python/ray/train/torch/config.py:69 —
rank-0 rendezvous address, dist.init_process_group :113) and the
WorkerGroup it runs on (ref: python/ray/train/_internal/worker_group.py:100).

A "task" on a TPU slice is N coordinated host processes entering the same
pjit program — a gang. `MeshGroup` owns that gang: it spawns one actor per
host (in a placement group so they land on distinct nodes), passes each its
process index + coordinator address, has each call `jax.distributed.
initialize` (multi-host) or just claim local devices (single host / CPU
tests), and then `run()` broadcasts a callable for SPMD execution.
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import ray_tpu
from ray_tpu.core.placement_group import placement_group, remove_placement_group

from .mesh import MeshSpec


class MeshWorkerMixin:
    """Mixin giving an actor the mesh-formation protocol. Train workers and
    RL learners inherit this; `setup_mesh` is invoked once by MeshGroup.

    Mesh construction/validation goes through the shared ownership layer
    (parallel.sharding.MeshOwner) — the same object the LLM engine's tp
    lowering and the pipeline stages' fsdp plane consume, so every stack
    agrees on axis names and sharding factories (docs/SHARDING.md)."""

    def setup_mesh(self, process_id: int, num_processes: int,
                   coordinator: Optional[str], spec_kwargs: dict,
                   devices_per_process: Optional[int] = None) -> int:
        import jax

        self._process_id = process_id
        self._num_processes = num_processes
        if num_processes > 1 and coordinator:
            # Real multi-host path: one jax process per TPU host. Guarded so
            # CPU CI (everything in one OS process) skips the barrier.
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=num_processes,
                process_id=process_id)
        devs = jax.devices()
        if devices_per_process is not None:
            lo = process_id * devices_per_process
            devs = devs[lo:lo + devices_per_process]
        from .sharding import MeshOwner

        self._mesh_devices = devs
        self._owner = MeshOwner(MeshSpec(**spec_kwargs), devices=devs,
                                name=f"gang-p{process_id}")
        self._mesh = self._owner.mesh
        return len(devs)

    @property
    def mesh(self):
        return self._mesh

    @property
    def mesh_owner(self):
        """The sharding-layer MeshOwner (NamedSharding factory, layout,
        per-device accounting) backing :attr:`mesh`."""
        return self._owner

    def mesh_run(self, fn_blob: bytes, *args, **kwargs):
        import cloudpickle

        fn = cloudpickle.loads(fn_blob)
        return fn(self, *args, **kwargs)


class MeshGroup:
    """Forms and drives a gang of mesh workers.

    worker_cls must mix in MeshWorkerMixin. On a v5e-256 this is 64 host
    actors each owning 4 chips; on CPU CI it is N actors sharing the
    virtual-device pool (partitioned via devices_per_process).
    """

    def __init__(self, num_workers: int,
                 spec: Optional[MeshSpec] = None,
                 worker_cls: Optional[type] = None,
                 devices_per_process: Optional[int] = None,
                 resources_per_worker: Optional[dict] = None,
                 coordinator: Optional[str] = None):
        self.num_workers = num_workers
        self.spec = spec or MeshSpec()
        cls = worker_cls or _DefaultMeshWorker
        res = dict(resources_per_worker or {"CPU": 1.0})
        bundles = [dict(res) for _ in range(num_workers)]
        self._pg = placement_group(bundles, strategy="SPREAD")
        if not self._pg.ready():
            raise TimeoutError("MeshGroup placement group not ready")
        remote_cls = ray_tpu.remote(cls)
        self.workers = [
            remote_cls.options(
                num_cpus=res.get("CPU", 1.0),
                resources={k: v for k, v in res.items() if k != "CPU"},
                placement_group=self._pg,
                placement_group_bundle_index=i,
            ).remote()
            for i in range(num_workers)
        ]
        counts = ray_tpu.get([
            w.setup_mesh.remote(i, num_workers, coordinator,
                                _spec_kwargs(self.spec), devices_per_process)
            for i, w in enumerate(self.workers)
        ])
        self.devices_per_worker = counts

    def run(self, fn: Callable, *args, **kwargs) -> List[Any]:
        """Gang-invoke fn(worker_self, *args) on every worker; returns all
        results. This is the gang-scheduling primitive the reference lacks
        (SURVEY.md §7 hard parts)."""
        import cloudpickle

        blob = cloudpickle.dumps(fn)
        return ray_tpu.get([
            w.mesh_run.remote(blob, *args, **kwargs) for w in self.workers])

    def run_async(self, fn: Callable, *args, **kwargs):
        import cloudpickle

        blob = cloudpickle.dumps(fn)
        return [w.mesh_run.remote(blob, *args, **kwargs) for w in self.workers]

    def shutdown(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        try:
            remove_placement_group(self._pg)
        except Exception:
            pass


class _DefaultMeshWorker(MeshWorkerMixin):
    pass


def _spec_kwargs(spec: MeshSpec) -> dict:
    return {"dp": spec.dp, "fsdp": spec.fsdp, "tp": spec.tp,
            "sp": spec.sp, "ep": spec.ep, "pp": spec.pp}
