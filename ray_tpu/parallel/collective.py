"""Explicit collectives between actors/tasks.

Host-side equivalent of the reference's `ray.util.collective`
(ref: python/ray/util/collective/collective.py:258-615 — allreduce/reduce/
broadcast/allgather/reducescatter/send/recv; GroupManager :40; rendezvous
via a named store actor, collective_group/nccl_util + gloo).

TPU-native stance: *device* collectives belong to XLA (psum/all_gather/
ppermute over ICI inside jit — see ray_tpu.parallel.mesh); this module is
the host/DCN plane used for control tensors, rollout-weight broadcast, and
CPU-side aggregation, implemented over the object store with a named
rendezvous actor instead of NCCL rings.

Every collective that moves tensors accepts ``codec=`` — an EQuARX-style
block-scaled wire codec (``"int8"`` / ``"e4m3"``, parallel/quant.py):
each rank quantizes its contribution BEFORE it crosses the wire (per
block absmax scales, deterministic rounding) and every reduction runs
over the dequantized fp32 values, so accumulation precision is full
even when the wire carries ~1/4 of the bytes. ``codec=None`` (default)
is byte-identical to the pre-codec behavior. Bytes shipped per op are
counted in ``ray_tpu_collective_bytes_total{op,codec}``
(docs/OBSERVABILITY.md; design in docs/COLLECTIVES.md).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu

from ..util import metrics as _metrics
from . import quant as _quant

_C_BYTES = _metrics.Counter(
    "ray_tpu_collective_bytes_total",
    "bytes this process shipped into host collectives (the rank's "
    "wire contribution per op, after any codec)",
    tag_keys=("op", "codec"))

_REDUCE_OPS = {
    "sum": lambda xs: _tree_reduce(xs, np.add),
    "product": lambda xs: _tree_reduce(xs, np.multiply),
    "max": lambda xs: _tree_reduce(xs, np.maximum),
    "min": lambda xs: _tree_reduce(xs, np.minimum),
}


def _tree_reduce(xs: List[Any], op) -> Any:
    out = xs[0]
    for x in xs[1:]:
        out = op(out, x)
    return out


class _CollectiveStore:
    """Named rendezvous actor: one per group. Ranks deposit contributions
    keyed by (op sequence number, rank); readers block-poll until the op's
    row is complete. Mirrors the reference's NamedActor store rendezvous
    (ref: util/collective/collective_group/base_collective_group.py)."""

    def __init__(self, world_size: int):
        self._world = world_size
        self._slots: Dict[int, Dict[int, Any]] = {}
        self._p2p: Dict[tuple, Any] = {}

    def put(self, seq: int, rank: int, value):
        self._slots.setdefault(seq, {})[rank] = value
        return True

    def gather(self, seq: int) -> Optional[List[Any]]:
        row = self._slots.get(seq)
        if row is None or len(row) < self._world:
            return None
        return [row[r] for r in range(self._world)]

    def present(self, seq: int) -> List[int]:
        """Ranks that have deposited for ``seq`` — the timeout
        diagnostic surface (which ranks a wedged sync is missing)."""
        return sorted(self._slots.get(seq, {}))

    def done(self, seq: int, rank: int):
        """Each rank acks after consuming; last ack frees the row."""
        row = self._slots.get(seq)
        if row is not None:
            acks = self._slots.setdefault(-seq - 1, {})
            acks[rank] = True
            if len(acks) >= self._world:
                self._slots.pop(seq, None)
                self._slots.pop(-seq - 1, None)
        return True

    def p2p_put(self, seq: int, src: int, dst: int, value):
        self._p2p[(seq, src, dst)] = value
        return True

    def p2p_take(self, seq: int, src: int, dst: int):
        return self._p2p.pop((seq, src, dst), _MISSING)


_MISSING = "__rtpu_missing__"
# Process-global registry: a worker process holds one rank per group, but
# actor tasks may execute on different threads (executor pool), so the
# registry must not be thread-local.
_GROUPS: Dict[str, "CollectiveGroup"] = {}


class CollectiveGroup:
    """Per-process view of a collective group (rank-local state)."""

    def __init__(self, group_name: str, world_size: int, rank: int):
        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        self._seq = 0
        store_cls = ray_tpu.remote(_CollectiveStore)
        # num_cpus=0: the store is a pure rendezvous point and must schedule
        # even on a fully-subscribed cluster (ranks hold all the CPUs while
        # they block in _exchange).
        # Name scoped by world_size so re-creating a group with a different
        # size can never attach to a stale store left by the old group.
        self._store_name = f"rtpu_collective:{group_name}:{world_size}"
        self._store = store_cls.options(
            name=self._store_name,
            get_if_exists=True, lifetime="detached", num_cpus=0,
            max_concurrency=max(8, world_size * 2),
        ).remote(world_size)

    def _next_seq(self) -> int:
        self._seq += 1
        return self._seq

    def _exchange(self, value, timeout: float = 120.0,
                  op: str = "exchange",
                  codec: Optional[str] = None) -> List[Any]:
        seq = self._next_seq()
        _C_BYTES.inc(_quant.wire_bytes(value),
                     tags={"op": op, "codec": codec or "none"})
        ray_tpu.get(self._store.put.remote(seq, self.rank, value))
        deadline = time.monotonic() + timeout
        delay = 0.0005
        while True:
            row = ray_tpu.get(self._store.gather.remote(seq))
            if row is not None:
                self._store.done.remote(seq, self.rank)
                return row
            if time.monotonic() > deadline:
                # name exactly what a wedged multi-node sync needs: the
                # group, the op, the seq, and which ranks never showed
                try:
                    present = ray_tpu.get(
                        self._store.present.remote(seq), timeout=5.0)
                    missing = [r for r in range(self.world_size)
                               if r not in present]
                    who = f"missing ranks {missing} of {self.world_size}"
                except Exception:
                    who = "missing-rank query failed (store unreachable?)"
                raise TimeoutError(
                    f"collective {op} on group {self.group_name!r} "
                    f"seq={seq} timed out after {timeout}s at rank "
                    f"{self.rank}: {who}")
            time.sleep(delay)
            delay = min(delay * 2, 0.05)


def _groups() -> Dict[str, CollectiveGroup]:
    return _GROUPS


def create_collective_group(world_size: int, rank: int,
                            group_name: str = "default",
                            backend: str = "object_store") -> CollectiveGroup:
    """Called by every participant (ref: collective.py:90 init_collective_group).
    backend is accepted for API parity; only object_store exists (device
    collectives are XLA's job)."""
    g = CollectiveGroup(group_name, world_size, rank)
    _groups()[group_name] = g
    return g


def destroy_collective_group(group_name: str = "default") -> None:
    """Tear down the local view AND the detached rendezvous store, so a
    future group with the same name starts from a clean slate (a leaked
    detached store would otherwise survive across jobs with stale slot
    rows from any timed-out collective)."""
    g = _groups().pop(group_name, None)
    if g is not None:
        try:
            ray_tpu.kill(g._store)
        except Exception:
            pass


def get_group(group_name: str = "default") -> CollectiveGroup:
    try:
        return _groups()[group_name]
    except KeyError:
        raise ValueError(
            f"Collective group {group_name!r} not initialized in this "
            "process; call create_collective_group first") from None


def _encode(tensor, codec: Optional[str]):
    """Quantize a contribution for the wire (None = pass through)."""
    if codec is None:
        return tensor
    _quant.check_codec(codec)
    return _quant.quantize(np.asarray(tensor), codec)


def _decode_row(row: List[Any]) -> List[Any]:
    """Dequantize gathered contributions to fp32 — reductions always
    accumulate over full-precision values, never over the narrow
    payloads themselves."""
    return [_quant.dequantize(v) if isinstance(v, _quant.QuantizedTensor)
            else v for v in row]


def allreduce(tensor, group_name: str = "default", op: str = "sum",
              codec: Optional[str] = None):
    g = get_group(group_name)
    row = g._exchange(_encode(tensor, codec), op="allreduce", codec=codec)
    return _REDUCE_OPS[op](_decode_row(row))


def reduce(tensor, dst_rank: int = 0, group_name: str = "default",
           op: str = "sum", codec: Optional[str] = None):
    g = get_group(group_name)
    row = g._exchange(_encode(tensor, codec), op="reduce", codec=codec)
    if g.rank == dst_rank:
        return _REDUCE_OPS[op](_decode_row(row))
    return tensor


def allgather(tensor, group_name: str = "default",
              codec: Optional[str] = None) -> List[Any]:
    g = get_group(group_name)
    row = g._exchange(_encode(tensor, codec), op="allgather", codec=codec)
    return _decode_row(row)


def reducescatter(tensor, group_name: str = "default", op: str = "sum",
                  codec: Optional[str] = None):
    g = get_group(group_name)
    row = g._exchange(_encode(tensor, codec), op="reducescatter",
                      codec=codec)
    total = _REDUCE_OPS[op](_decode_row(row))
    return np.array_split(np.asarray(total), g.world_size)[g.rank]


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    g = get_group(group_name)
    row = g._exchange(tensor if g.rank == src_rank else None,
                      op="broadcast")
    return row[src_rank]


def barrier(group_name: str = "default") -> None:
    get_group(group_name)._exchange(0, op="barrier")


def send(tensor, dst_rank: int, group_name: str = "default",
         tag: int = 0) -> None:
    g = get_group(group_name)
    ray_tpu.get(g._store.p2p_put.remote(tag, g.rank, dst_rank, tensor))


def recv(src_rank: int, group_name: str = "default", tag: int = 0,
         timeout: float = 120.0):
    g = get_group(group_name)
    deadline = time.monotonic() + timeout
    delay = 0.0005
    while True:
        v = ray_tpu.get(g._store.p2p_take.remote(tag, src_rank, g.rank))
        if not (isinstance(v, str) and v == _MISSING):
            return v
        if time.monotonic() > deadline:
            raise TimeoutError(f"recv from rank {src_rank} timed out")
        time.sleep(delay)
        delay = min(delay * 2, 0.05)
