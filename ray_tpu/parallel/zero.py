"""ZeRO-style cross-replica sharding of the weight update.

"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (PAPERS.md): in plain data parallelism every replica holds the
FULL optimizer state and applies the FULL update — O(N) redundant memory
and compute per replica. Sharding the update makes both scale with the
dp axis: each replica reduce-scatters gradients (so it receives only its
1/dp shard, already summed), applies the optimizer to that shard with
1/dp of the optimizer state, and all-gathers the fresh parameters.
Elementwise optimizers (sgd/adam/adamw) commute with the flat-vector
sharding, so the sharded update is numerically the replicated update.

Two planes, mirroring parallel/collective.py's stance:

- **Host plane** (:class:`ZeroUpdater`): cross-ACTOR dp groups over the
  object-store collective (reducescatter/allgather from
  parallel/collective.py). This is what the compiled-graph pipeline
  engine (train/pipeline_cgraph.py) uses between dp replicas of one
  stage — replicas live in different processes, often different hosts.

- **In-jit plane** (:func:`make_zero_update_spmd`): ``psum_scatter`` /
  ``all_gather`` inside one jitted program over a mesh dp axis, for the
  case where a stage's replicas are chips of one mesh.

Both operate on the FLAT parameter vector: pytrees are raveled into one
1-D array (uniform dtype enforced), sharded in contiguous chunks that
match ``np.array_split`` boundaries (what collective.reducescatter
emits), and unraveled after the gather.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

__all__ = [
    "TreeSpec", "flatten_tree", "unflatten_tree", "shard_bounds",
    "tree_bytes", "ZeroUpdater", "make_zero_update_spmd",
    "merge_opt_shards", "split_opt_state", "flatten_opt_state",
    "unflatten_opt_state",
]


class TreeSpec:
    """Shapes/dtype/treedef needed to unflatten a flat vector."""

    __slots__ = ("treedef", "shapes", "dtype", "size")

    def __init__(self, treedef, shapes, dtype, size):
        self.treedef = treedef
        self.shapes = shapes
        self.dtype = dtype
        self.size = size


def flatten_tree(tree) -> Tuple[Any, TreeSpec]:
    """Pytree -> (flat 1-D array, spec). Leaves must share one dtype —
    the flat shard boundary would otherwise cut through a dtype change
    and reinterpret bytes."""
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        raise ValueError("cannot flatten an empty pytree")
    dtypes = {jnp.asarray(l).dtype for l in leaves}
    if len(dtypes) > 1:
        raise ValueError(
            f"ZeRO flat sharding needs a uniform leaf dtype, got "
            f"{sorted(str(d) for d in dtypes)}")
    shapes = [jnp.asarray(l).shape for l in leaves]
    flat = jnp.concatenate([jnp.asarray(l).ravel() for l in leaves])
    return flat, TreeSpec(treedef, shapes, flat.dtype, int(flat.size))


def unflatten_tree(flat, spec: TreeSpec):
    import jax
    import numpy as _np

    leaves = []
    off = 0
    for shape in spec.shapes:
        n = int(_np.prod(shape)) if shape else 1
        leaves.append(flat[off:off + n].reshape(shape))
        off += n
    return jax.tree.unflatten(spec.treedef, leaves)


def shard_bounds(n: int, world: int) -> List[Tuple[int, int]]:
    """Contiguous (lo, hi) per rank, matching np.array_split: the first
    n % world shards get one extra element."""
    base, extra = divmod(n, world)
    bounds = []
    lo = 0
    for r in range(world):
        hi = lo + base + (1 if r < extra else 0)
        bounds.append((lo, hi))
        lo = hi
    return bounds


def tree_bytes(tree) -> int:
    """Total bytes across a pytree's array leaves (optimizer-state
    footprint accounting; scalars count their numpy size)."""
    import jax

    total = 0
    for leaf in jax.tree.leaves(tree):
        total += int(np.asarray(leaf).nbytes)
    return total


# ---------------------------------------------------------------------------
# opt-state resharding — the elastic-capacity vocabulary
# (docs/FAULT_TOLERANCE.md "Elasticity"): ZeRO shards saved at one dp
# width re-split exactly across another, and the flat plane converts
# to/from the replicated tree plane, so `resize(dp±k)` and
# cross-width checkpoint restores are pure byte movement — bit-exact.
# ---------------------------------------------------------------------------


def merge_opt_shards(shards: List[Any]):
    """Per-rank ZeRO opt-state shards (rank order) -> one flat-vector
    opt state over the FULL parameter vector. Array leaves are
    shard-sized (optimizer moments) and concatenate in rank order —
    matching the ``shard_bounds`` contiguous layout they were split
    under; scalar leaves (adam's step count) are collectively identical
    and come from rank 0."""
    import jax
    import numpy as _np

    if not shards:
        raise ValueError("merge_opt_shards needs at least one shard")

    def _merge(*leaves):
        if _np.ndim(leaves[0]) >= 1:
            return _np.concatenate([_np.asarray(l) for l in leaves])
        return leaves[0]

    return jax.tree.map(_merge, *shards)


def split_opt_state(full, world: int, size: int) -> List[Any]:
    """Inverse of :func:`merge_opt_shards`: a flat-vector opt state over
    ``size`` parameters -> ``world`` per-rank shards on the
    ``shard_bounds`` layout. Array leaves of length ``size`` are
    sliced; everything else (scalars, oddly-shaped leaves) replicates."""
    import jax
    import numpy as _np

    bounds = shard_bounds(size, world)

    def _slice(lo, hi):
        def f(leaf):
            arr = _np.asarray(leaf)
            if arr.ndim == 1 and arr.shape[0] == size:
                return arr[lo:hi]
            return leaf
        return f

    return [jax.tree.map(_slice(lo, hi), full) for lo, hi in bounds]


def flatten_opt_state(state, params):
    """Replicated TREE-plane opt state (``tx.init(params_tree)``) -> the
    flat-vector plane (``tx.init(flat_params)``): every params-shaped
    subtree of the state (adam's mu/nu, momentum's trace, ...) collapses
    into one raveled vector on the :func:`flatten_tree` layout; scalar
    leaves pass through. This is the grow path — a dp=1 engine's full
    opt state becomes ZeRO shards for dp>1."""
    import jax
    import jax.numpy as jnp

    p_def = jax.tree.structure(params)
    p_shapes = [jnp.shape(l) for l in jax.tree.leaves(params)]

    def _params_shaped(x) -> bool:
        try:
            if jax.tree.structure(x) != p_def:
                return False
            return [jnp.shape(l) for l in jax.tree.leaves(x)] == p_shapes
        except Exception:
            return False

    def _collapse(sub):
        if _params_shaped(sub):
            return jnp.concatenate(
                [jnp.asarray(l).ravel() for l in jax.tree.leaves(sub)])
        return sub

    return jax.tree.map(_collapse, state, is_leaf=_params_shaped)


def unflatten_opt_state(flat_state, spec: TreeSpec):
    """Flat-vector-plane opt state -> the replicated TREE plane: leaves
    of length ``spec.size`` unflatten back into params-shaped subtrees
    (the shrink-to-dp=1 path)."""
    import jax
    import numpy as _np

    def _expand(leaf):
        arr = _np.asarray(leaf)
        if arr.ndim == 1 and arr.shape[0] == spec.size:
            return unflatten_tree(leaf, spec)
        return leaf

    return jax.tree.map(_expand, flat_state)


# ---------------------------------------------------------------------------
# host plane: cross-actor dp groups over parallel/collective.py
# ---------------------------------------------------------------------------


class ZeroUpdater:
    """Rank-local view of a ZeRO-sharded optimizer over a host collective
    group.

    Each dp replica constructs one with its rank, inits optimizer state
    for ITS shard only (the ~1/dp memory win), and calls
    :meth:`update` once per optimizer step. The gradient mean, shard
    update, and parameter gather all ride the named collective group —
    every rank must call update() collectively.

    ``grad_codec`` (``"int8"``/``"e4m3"``, docs/COLLECTIVES.md)
    compresses BOTH wire legs of the dp sync with the block-scaled
    codec: the gradient reduce-scatter ships quantized grads (summed in
    fp32 after dequantize) and the parameter all-gather ships quantized
    fresh shards. So the wire-precision params don't become the
    optimization state itself (sub-quantization-step updates would
    round away and training would stall on the int8 grid), each rank
    keeps a persistent fp32 MASTER copy of its own shard: the optimizer
    updates the master, the wire carries its quantized image, and
    compute everywhere runs on the wire-precision params — standard
    master-weight mixed precision, applied to the ZeRO gather.
    ``grad_codec=None`` is bit-identical to the pre-codec updater.
    """

    def __init__(self, tx, world: int, rank: int,
                 group_name: str = "default",
                 grad_codec: Optional[str] = None):
        from . import quant as _quant

        self.tx = tx
        self.world = int(world)
        self.rank = int(rank)
        self.group_name = group_name
        self.grad_codec = _quant.check_codec(grad_codec)
        self._spec: Optional[TreeSpec] = None
        self._opt_state = None
        self._master = None   # fp32 shard master copy (codec path only)
        self._jit_update = None
        # collective sync-exposed wall time (step profiler, ISSUE 17):
        # the two wire legs of the last update() and the running total
        self.last_rs_s = 0.0   # gradient reduce-scatter leg
        self.last_ag_s = 0.0   # parameter all-gather leg
        self.sync_s = 0.0      # cumulative rs+ag over this updater's life

    def init(self, params) -> "ZeroUpdater":
        import jax

        flat, spec = flatten_tree(params)
        self._spec = spec
        lo, hi = shard_bounds(spec.size, self.world)[self.rank]
        self._opt_state = jax.jit(self.tx.init)(flat[lo:hi])
        if self.grad_codec is not None:
            self._master = flat[lo:hi]

        @jax.jit
        def _upd(g_shard, opt_state, p_shard):
            import optax

            updates, new_state = self.tx.update(g_shard, opt_state,
                                                p_shard)
            return optax.apply_updates(p_shard, updates), new_state

        self._jit_update = _upd
        return self

    def opt_state_bytes(self) -> int:
        """Bytes of optimizer state THIS replica holds (~ full/dp)."""
        return tree_bytes(self._opt_state)

    def opt_state(self):
        """This rank's optimizer-state SHARD (checkpointing surface —
        the pipeline engine persists one shard per dp rank and hands it
        back through :meth:`set_opt_state` on restore). With a
        ``grad_codec`` the fp32 master shard rides along as a shard-
        sized leaf (``{"tx": ..., "master": ...}``) so the elastic
        reshard vocabulary (merge/split over shard-sized leaves) moves
        it across dp widths like any other moment."""
        if self.grad_codec is not None:
            return {"tx": self._opt_state,
                    "master": np.asarray(self._master)}
        return self._opt_state

    def set_opt_state(self, state) -> None:
        """Restore this rank's shard (must come from the same (rank,
        world, param-tree) layout it was saved under). Accepts both the
        raw optimizer state and the codec-era ``{"tx", "master"}``
        wrapper; a raw state under a codec updater re-seeds the master
        from the next update's incoming params."""
        if self._spec is None:
            raise RuntimeError("ZeroUpdater.set_opt_state() before init()")
        if isinstance(state, dict) and set(state) == {"tx", "master"}:
            self._opt_state = state["tx"]
            self._master = state["master"]
        else:
            self._opt_state = state
            if self.grad_codec is not None:
                self._master = None  # lazily re-seeded at next update()

    def update(self, params, grads):
        """Collective optimizer step: reduce-scatter the gradient mean,
        update this rank's shard, all-gather fresh parameters. Returns
        the full updated parameter pytree. With ``grad_codec`` both
        collectives ship block-scaled quantized payloads and the
        optimizer runs on this rank's fp32 master shard."""
        import jax.numpy as jnp

        from . import collective

        if self._spec is None:
            raise RuntimeError("ZeroUpdater.update() before init()")
        flat_g, gspec = flatten_tree(grads)
        if gspec.size != self._spec.size:
            raise ValueError(
                f"grad tree size {gspec.size} != param tree size "
                f"{self._spec.size}")
        codec = self.grad_codec
        # reducescatter SUMS then slices; divide for the dp mean
        # (codec: rows dequantize to fp32 BEFORE the sum, so gradient
        # accumulation precision is full — only the wire is narrow)
        import time as _time

        t0 = _time.perf_counter()
        g_shard = collective.reducescatter(
            np.asarray(flat_g), self.group_name, codec=codec) / self.world
        self.last_rs_s = _time.perf_counter() - t0
        flat_p, _ = flatten_tree(params)
        lo, hi = shard_bounds(self._spec.size, self.world)[self.rank]
        if codec is not None and self._master is None:
            self._master = flat_p[lo:hi]
        p_shard = flat_p[lo:hi] if codec is None \
            else jnp.asarray(self._master, dtype=self._spec.dtype)
        new_shard, self._opt_state = self._jit_update(
            jnp.asarray(g_shard, dtype=self._spec.dtype),
            self._opt_state, p_shard)
        if codec is not None:
            self._master = new_shard
        t1 = _time.perf_counter()
        parts = collective.allgather(np.asarray(new_shard),
                                     self.group_name, codec=codec)
        self.last_ag_s = _time.perf_counter() - t1
        self.sync_s += self.last_rs_s + self.last_ag_s
        full = jnp.asarray(np.concatenate(parts), dtype=self._spec.dtype)
        return unflatten_tree(full, self._spec)


# ---------------------------------------------------------------------------
# in-jit plane: psum_scatter / all_gather over a mesh dp axis
# ---------------------------------------------------------------------------


def make_zero_update_spmd(tx, mesh, axis: str = "dp",
                          grad_codec: Optional[str] = None,
                          codec_block: int = 256
                          ) -> Tuple[Callable, Callable]:
    """Build the in-mesh sharded update: ``(init_fn, update_fn)``.

    ``grad_codec`` ("int8"/"e4m3") swaps the gradient ``psum_scatter``
    for the quantized scatter kernel
    (parallel/sharding/codec.quantized_scatter_mean): per-block absmax
    quantize → all_to_all → dequantize → fp32 sum, so the dp wire
    carries ~1/4 of the gradient bytes; the parameter all-gather stays
    full precision (the in-jit plane syncs over ICI/one host, where
    params are cheap relative to the DCN-crossing host plane).
    ``grad_codec=None`` compiles the exact pre-codec program.

    - ``init_fn(params)`` -> flat optimizer state laid out over the
      mesh ``axis`` (each device materializes only its 1/dp chunk under
      shard_map).
    - ``update_fn(params, grads_stacked, opt_state)`` ->
      ``(new_params, new_opt_state)`` where ``grads_stacked`` carries a
      leading ``axis``-sharded replica dimension (each replica's own
      gradients, e.g. from per-shard ``value_and_grad``). Inside the
      program: ``psum_scatter`` hands each device its summed 1/dp
      gradient chunk, the optimizer updates that chunk, and a tiled
      ``all_gather`` rebuilds the full parameter vector — no device
      ever holds full optimizer state.

    The flat vector is zero-padded to a multiple of the axis size so
    chunks tile exactly.
    """
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    from ..jax_compat import shard_map

    from . import quant as _quant

    _quant.check_codec(grad_codec)
    world = mesh.shape[axis]

    def _pad(flat):
        pad = (-flat.size) % world
        if pad:
            flat = jnp.concatenate(
                [flat, jnp.zeros((pad,), flat.dtype)])
        return flat

    def _opt_specs(chunk, dtype):
        # moment leaves ([chunk] per rank) shard over the axis; scalar
        # leaves (adam's step count) stay replicated
        shapes = jax.eval_shape(tx.init,
                                jax.ShapeDtypeStruct((chunk,), dtype))
        return jax.tree.map(
            lambda s: P(axis) if len(s.shape) >= 1 else P(), shapes)

    def init_fn(params):
        flat, _spec = flatten_tree(params)
        flat = _pad(flat)
        chunk = flat.size // world

        def _init_local(p_local):
            idx = jax.lax.axis_index(axis)
            p_shard = jax.lax.dynamic_slice(p_local, (idx * chunk,),
                                            (chunk,))
            return tx.init(p_shard)

        fn = shard_map(_init_local, mesh=mesh, in_specs=(P(),),
                       out_specs=_opt_specs(chunk, flat.dtype),
                       axis_names=frozenset({axis}))
        return jax.jit(fn)(flat)

    # one jitted program per (param size, grad width, dtype) — a fresh
    # shard_map closure per call would miss jit's identity-keyed cache
    # and re-trace + re-compile the update EVERY training step
    _progs: dict = {}

    def _update_prog(chunk, g_width, dtype):
        key = (chunk, g_width, str(dtype))
        prog = _progs.get(key)
        if prog is not None:
            return prog

        def _upd_local(p_local, g_local, opt_local):
            idx = jax.lax.axis_index(axis)
            # g_local: [1, Np] — this replica's own full gradient.
            # psum_scatter hands back chunk #idx of the cross-replica
            # SUM; with a codec the quantized kernel decomposes it so
            # only narrow payloads cross the wire (fp32 sum after
            # dequantize — parallel/sharding/codec.py)
            if grad_codec is None:
                g_shard = jax.lax.psum_scatter(
                    g_local[0], axis, tiled=True) / world
            else:
                from .sharding.codec import quantized_scatter_mean

                g_shard = quantized_scatter_mean(
                    g_local[0], axis, world, codec=grad_codec,
                    block=codec_block)
            p_shard = jax.lax.dynamic_slice(p_local, (idx * chunk,),
                                            (chunk,))
            updates, new_opt = tx.update(g_shard, opt_local, p_shard)
            new_shard = optax.apply_updates(p_shard, updates)
            new_flat = jax.lax.all_gather(new_shard, axis, tiled=True)
            return new_flat, new_opt

        ospecs = _opt_specs(chunk, dtype)
        prog = jax.jit(shard_map(_upd_local, mesh=mesh,
                                 in_specs=(P(), P(axis), ospecs),
                                 out_specs=(P(), ospecs),
                                 axis_names=frozenset({axis})))
        _progs[key] = prog
        return prog

    def update_fn(params, grads_stacked, opt_state):
        flat_p, spec = flatten_tree(params)
        flat_p = _pad(flat_p)
        chunk = flat_p.size // world
        g_leaves, _ = jax.tree.flatten(grads_stacked)
        flat_g = jnp.concatenate(
            [jnp.asarray(l).reshape(world, -1) for l in g_leaves],
            axis=1)
        pad = (-flat_g.shape[1]) % world
        if pad:
            flat_g = jnp.concatenate(
                [flat_g, jnp.zeros((world, pad), flat_g.dtype)], axis=1)
        prog = _update_prog(chunk, flat_g.shape[1], flat_p.dtype)
        new_flat, new_opt = prog(flat_p, flat_g, opt_state)
        return unflatten_tree(new_flat[:spec.size], spec), new_opt

    return init_fn, update_fn
