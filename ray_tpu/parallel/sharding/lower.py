"""Lowering helpers — the two ways a program enters a MeshOwner's mesh.

- :func:`lower_jit` — the GSPMD/pjit plane: annotate inputs/outputs
  with PartitionSpecs and let XLA's SPMD partitioner place the
  collectives. This is the serve-tp path (LLM prefill/decode lowered
  with heads/FFN on ``tp`` and the KV pool block-sharded) — the
  original brief's "pjit-compiled inference shards".

- :func:`lower_shard_map` — the manual plane: the body is written
  per-shard and collectives are explicit (``jax.lax.psum`` etc. over
  axes the *owning mesh* binds). This is the fsdp plane's path, and
  the one graftcheck GC020/GC021 police: the helper always passes
  ``axis_names=`` derived from the owner's mesh, so a collective over
  an unbound axis is a static error, not an XLA lowering surprise.

Both return jitted callables; specs may be PartitionSpecs or pytrees
of them, pruned per-mesh by the owner (absent axes replicate).
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence, Union

from ...jax_compat import shard_map
from .owner import MeshOwner


def _shardings(owner: MeshOwner, specs):
    import jax
    from jax.sharding import PartitionSpec

    return jax.tree.map(
        lambda s: owner.sharding(s),
        specs, is_leaf=lambda s: s is None or isinstance(s,
                                                        PartitionSpec))


def lower_jit(fn: Callable, owner: MeshOwner, *,
              in_specs=None, out_specs=None,
              donate_argnums: Union[int, Sequence[int]] = (),
              static_argnums: Union[int, Sequence[int]] = ()) -> Callable:
    """jit ``fn`` under the owner's mesh with PartitionSpec-annotated
    inputs/outputs (GSPMD partitions the body automatically).

    ``in_specs``/``out_specs`` mirror ``jax.jit``'s
    ``in_shardings``/``out_shardings`` trees but hold PartitionSpecs
    (or logical-axis tuples); ``None`` leaves let GSPMD propagate.
    ``donate_argnums`` passes through — the tp decode step donates its
    KV cache buffers so XLA reuses the pool allocation in place.
    """
    import jax

    kw: dict = {}
    if in_specs is not None:
        kw["in_shardings"] = _shardings(owner, in_specs)
    if out_specs is not None:
        kw["out_shardings"] = _shardings(owner, out_specs)
    if donate_argnums != ():
        kw["donate_argnums"] = donate_argnums
    if static_argnums != ():
        kw["static_argnums"] = static_argnums
    return jax.jit(fn, **kw)


def lower_shard_map(fn: Callable, owner: MeshOwner, *,
                    in_specs, out_specs,
                    axis_names: Optional[frozenset] = None,
                    jit: bool = True) -> Callable:
    """shard_map ``fn`` over the owner's mesh, manual over
    ``axis_names`` (default: every axis the mesh carries).

    The body sees per-shard arrays and must name only bound axes in
    its collectives — graftcheck GC020 statically checks call sites
    written against this helper's convention.
    """
    import jax

    if axis_names is None:
        axis_names = frozenset(owner.mesh.axis_names)
    mapped = shard_map(fn, mesh=owner.mesh, in_specs=in_specs,
                       out_specs=out_specs, axis_names=axis_names)
    return jax.jit(mapped) if jit else mapped


def sharded_init(init_fn: Callable, owner: MeshOwner,
                 out_specs) -> Callable:
    """jit an init so its outputs materialize already sharded on the
    owner's mesh (no replicated transient of the full tree)."""
    import jax

    return jax.jit(init_fn, out_shardings=_shardings(owner, out_specs))
