"""In-jit quantized collective kernels — the shard_map codec plane.

The device-side half of the EQuARX recipe (parallel/quant.py is the
host/numpy half, docs/COLLECTIVES.md the design): inside a jitted
shard_map program, a quantized reduce-scatter is

    quantize (per-block absmax) → all_to_all (narrow payload + fp32
    scales move over the wire) → dequantize → fp32 sum

— the interconnect carries ~1/4 of the fp32 bytes while every
accumulation happens in fp32 AFTER dequantization, exactly like the
host plane. ``jax.lax.psum_scatter`` itself would sum in transit (and
sum int8 payloads into garbage), so the kernel decomposes it: the
all_to_all delivers each device the OTHER replicas' quantized images of
*its* chunk, and the sum runs locally.

Kernels here are written for shard_map bodies in the GC020/GC021
idiom (docs/GRAFTCHECK.md): collectives name only the axis the caller
passes — which the *enclosing* shard_map must bind — and the
:func:`lower_quantized_scatter` builder wraps the body through
``lower_shard_map`` so ``axis_names`` is always owner-bound.

Customers: ``parallel.zero.make_zero_update_spmd(grad_codec=...)``
swaps its gradient psum_scatter for :func:`quantized_scatter_mean`;
the train backends reach it through the same knob.
"""
from __future__ import annotations

from typing import Callable, Optional

__all__ = [
    "dequantize_blocks", "lower_quantized_scatter", "quantize_blocks",
    "quantized_scatter_mean",
]

_INT8_MAX = 127.0
_E4M3_MAX = 448.0


def quantize_blocks(x, codec: str = "int8", block: int = 256):
    """Pure per-block quantization of ``x`` along its LAST dim (must be
    a multiple of ``block``): -> ``(payload, scales)`` where payload is
    int8 (or e4m3 bits as uint8) shaped like ``x`` and scales is fp32
    with the last dim reduced to blocks. Deterministic ties-to-even
    rounding — the same grid the host codec lands on."""
    import jax
    import jax.numpy as jnp

    from ..quant import check_codec

    check_codec(codec)
    shape = x.shape
    blocks = x.reshape(shape[:-1] + (shape[-1] // block, block))
    absmax = jnp.max(jnp.abs(blocks), axis=-1)
    if codec == "int8":
        scales = (absmax / _INT8_MAX).astype(jnp.float32)
        denom = jnp.where(scales > 0.0, scales, 1.0)[..., None]
        q = jnp.clip(jnp.round(blocks / denom), -_INT8_MAX, _INT8_MAX)
        payload = q.astype(jnp.int8)
    else:  # e4m3
        scales = (absmax / _E4M3_MAX).astype(jnp.float32)
        denom = jnp.where(scales > 0.0, scales, 1.0)[..., None]
        f8 = (blocks / denom).astype(jnp.float8_e4m3fn)
        # bitcast for transport: collectives over u8 are supported on
        # every backend; the receiver bitcasts back before dequantize
        payload = jax.lax.bitcast_convert_type(f8, jnp.uint8)
    return payload.reshape(shape[:-1] + (-1, block)), scales


def dequantize_blocks(payload, scales, codec: str = "int8"):
    """Inverse of :func:`quantize_blocks` (fp32 out, blocks merged back
    into the last dim)."""
    import jax
    import jax.numpy as jnp

    if codec == "int8":
        vals = payload.astype(jnp.float32)
    else:
        vals = jax.lax.bitcast_convert_type(
            payload, jnp.float8_e4m3fn).astype(jnp.float32)
    out = vals * scales[..., None]
    return out.reshape(out.shape[:-2] + (-1,))


def quantized_scatter_mean(g, axis: str, world: int,
                           codec: str = "int8", block: int = 256):
    """Quantized reduce-scatter-mean INSIDE a shard_map body.

    ``g``: this replica's full flat gradient ``[world * chunk]``
    (``axis`` must be bound by the enclosing shard_map). Returns this
    device's ``[chunk]`` slice of the cross-replica MEAN. The wire
    carries the narrow payload + per-block fp32 scales; the sum over
    replicas runs in fp32 after dequantize.
    """
    import jax
    import jax.numpy as jnp

    chunk = g.shape[0] // world
    gb = g.reshape(world, chunk)
    pad = (-chunk) % block
    if pad:
        gb = jnp.pad(gb, ((0, 0), (0, pad)))
    payload, scales = quantize_blocks(gb, codec, block)
    # row r of payload is the image of rank r's chunk: all_to_all hands
    # each device every replica's image of ITS chunk (row axis 0)
    wire_q = jax.lax.all_to_all(payload, axis, split_axis=0,
                                concat_axis=0)
    wire_s = jax.lax.all_to_all(scales, axis, split_axis=0,
                                concat_axis=0)
    deq = dequantize_blocks(wire_q, wire_s, codec)  # [world, chunk+pad]
    summed = jnp.sum(deq, axis=0)[:chunk]
    return summed / world


def lower_quantized_scatter(owner, axis: str, codec: str = "int8",
                            block: int = 256,
                            jit: bool = True) -> Callable:
    """Build a jitted ``grads_stacked [world, n] -> mean shard
    [ceil(n/world)]-per-device`` program over an owning mesh — the
    standalone spelling of the kernel for callers outside
    ``make_zero_update_spmd`` (and the shape graftcheck's codec
    fixtures pin). ``axis`` must be one of the owner's mesh axes."""
    from jax.sharding import PartitionSpec as P

    from .lower import lower_shard_map

    world = owner.mesh.shape[axis]

    def body(g_stacked):
        return quantized_scatter_mean(g_stacked[0], axis, world,
                                      codec=codec, block=block)

    return lower_shard_map(body, owner, in_specs=(P(axis),),
                           out_specs=P(axis),
                           axis_names=frozenset({axis}), jit=jit)
