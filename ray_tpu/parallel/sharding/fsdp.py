"""The fsdp plane — in-jit sharded parameter/optimizer storage for the
pipeline stage programs.

ZeRO-3-style storage over a mesh ``fsdp`` axis: between steps each chip
holds only its contiguous 1/fsdp chunk of the FLAT parameter vector and
1/fsdp of the optimizer moments; the forward gathers the exact full
vector once per step (a tiled ``all_gather`` is a pure concatenation —
bit-exact), and the update runs entirely shard-local (each chip
``dynamic_slice`` s its gradient chunk and applies the elementwise
optimizer to its shard — no collective at all in the update program).

Because the gather is exact and elementwise optimizers commute with
contiguous sharding, a stage trained on this plane produces a loss
trajectory **bit-identical** to the replicated stage — the property
test_sharding.py / test_pipeline_cgraph assert and the design carries
over from parallel/zero.py (same flat-vector discipline, same
"Automatic Cross-Replica Sharding of Weight Update" lineage). Compute
is replicated across the fsdp chips on this plane (the memory win is
the point; on real TPU meshes the GSPMD plane in lower.py additionally
splits the batch — docs/SHARDING.md).

Composition: the dp axis stays OUTSIDE (host-collective grad sync
between stage replicas — pipeline_cgraph.py), the pp axis stays in the
cgraph schedule; fsdp is the in-actor chip axis. That's the full 3D:
pp x dp x fsdp.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

from ..zero import TreeSpec, flatten_tree, tree_bytes, unflatten_tree
from .lower import lower_shard_map
from .owner import MeshOwner

__all__ = ["FsdpPlane", "FsdpParams"]


class FsdpParams:
    """One pytree's sharded residence: the padded flat vector (sharded
    over fsdp) plus the spec to unflatten it."""

    __slots__ = ("flat", "spec", "pad")

    def __init__(self, flat, spec: TreeSpec, pad: int):
        self.flat = flat
        self.spec = spec
        self.pad = pad

    def nbytes_per_device(self) -> Dict[int, int]:
        return {sh.device.id: int(sh.data.nbytes)
                for sh in self.flat.addressable_shards}


class FsdpPlane:
    """Sharded param/opt-state storage + the three jitted programs
    (gather / opt-init / update) over one MeshOwner's fsdp axis.

    Programs are cached per flat size+dtype, so hosting several model
    chunks (interleaved virtual stages) reuses compilations of equal
    geometry.
    """

    def __init__(self, owner: MeshOwner, tx=None):
        self.owner = owner
        self.axis = owner.layout.fsdp_axis
        self.world = owner.axis_size(self.axis)
        if self.world < 2:
            raise ValueError(
                f"FsdpPlane needs a mesh {self.axis!r} axis of size "
                f">= 2, got {self.world}")
        self.tx = tx
        self._progs: Dict[tuple, Any] = {}

    # -- placement ----------------------------------------------------------

    def shard(self, tree) -> FsdpParams:
        """Pytree -> sharded flat residence (1/fsdp per chip)."""
        import jax
        import jax.numpy as jnp

        flat, spec = flatten_tree(tree)
        pad = (-flat.size) % self.world
        if pad:
            flat = jnp.concatenate(
                [flat, jnp.zeros((pad,), flat.dtype)])
        sharded = jax.device_put(
            flat, self.owner.sharding(self.owner.layout.flat_params()))
        return FsdpParams(sharded, spec, pad)

    def gather(self, fp: FsdpParams):
        """Sharded residence -> the full pytree (exact reassembly; the
        per-step transient the forward consumes)."""
        prog = self._gather_prog(fp.flat.size, fp.flat.dtype)
        full = prog(fp.flat)
        return unflatten_tree(full[:fp.spec.size], fp.spec)

    # -- optimizer ----------------------------------------------------------

    def init_opt(self, fp: FsdpParams):
        """Optimizer state for the LOCAL shard only — each chip
        materializes 1/fsdp of the moments under shard_map."""
        if self.tx is None:
            raise ValueError("FsdpPlane built without an optimizer")
        prog = self._init_prog(fp.flat.size, fp.flat.dtype)
        return prog(fp.flat)

    def update(self, fp: FsdpParams, grads, opt_state
               ) -> Tuple[FsdpParams, Any]:
        """One sharded optimizer step. ``grads`` is the FULL gradient
        pytree (already dp-synced by the caller when dp > 1); each chip
        slices its chunk and updates its param/moment shards in place —
        zero collectives, bit-identical to the replicated update."""
        import jax.numpy as jnp

        if self.tx is None:
            raise ValueError("FsdpPlane built without an optimizer")
        flat_g, gspec = flatten_tree(grads)
        if gspec.size != fp.spec.size:
            raise ValueError(
                f"grad tree size {gspec.size} != param tree size "
                f"{fp.spec.size}")
        if fp.pad:
            flat_g = jnp.concatenate(
                [flat_g, jnp.zeros((fp.pad,), flat_g.dtype)])
        prog = self._update_prog(fp.flat.size, fp.flat.dtype)
        new_flat, new_opt = prog(fp.flat, flat_g, opt_state)
        return FsdpParams(new_flat, fp.spec, fp.pad), new_opt

    # -- accounting / checkpointing -----------------------------------------

    def opt_state_bytes(self, opt_state) -> int:
        return tree_bytes(opt_state)

    def per_device_bytes(self, fp: FsdpParams, opt_state=None
                         ) -> Dict[int, int]:
        """device id -> resident bytes (params + moments) — the
        ~1/fsdp acceptance number."""
        out = fp.nbytes_per_device()
        if opt_state is not None:
            for dev, b in self.owner.per_device_bytes(opt_state).items():
                out[dev] = out.get(dev, 0) + b
        return out

    def to_host(self, fp: FsdpParams, opt_state=None):
        """Checkpoint payload: full params pytree + opt-state leaves as
        numpy. Params restore onto any geometry; the flat moment
        leaves carry this width's padding, so opt state restores onto
        the SAME fsdp width only (the pipeline engine's geometry check
        enforces it)."""
        import numpy as np

        import jax

        params = jax.tree.map(np.asarray, self.gather(fp))
        opt = None if opt_state is None else jax.tree.map(
            np.asarray, opt_state)
        return params, opt

    def from_host(self, params, opt) -> Tuple[FsdpParams, Any]:
        """Restore a to_host() payload (same fsdp width for the opt
        leaves — they were saved in sharded-flat layout)."""
        fp = self.shard(params)
        if opt is None:
            return fp, None
        return fp, self.place_opt(fp, opt)

    def place_opt(self, fp: FsdpParams, opt_host):
        """Re-shard host (numpy) optimizer state onto the mesh in the
        layout init_opt produced (moments on fsdp, scalars replicated)."""
        import jax

        ospecs = self._opt_specs(fp.flat.size // self.world,
                                 fp.flat.dtype)
        return jax.tree.map(
            lambda leaf, spec: jax.device_put(
                leaf, self.owner.sharding(spec)),
            opt_host, ospecs)

    # -- cached programs ----------------------------------------------------

    def _opt_specs(self, chunk: int, dtype):
        """Spec tree for the sharded opt state: moment vectors ([chunk]
        per chip) on the fsdp axis, scalar leaves (adam's step count)
        replicated."""
        import jax
        from jax.sharding import PartitionSpec as P

        shapes = jax.eval_shape(self.tx.init,
                                jax.ShapeDtypeStruct((chunk,), dtype))
        return jax.tree.map(
            lambda s: P(self.axis) if len(s.shape) >= 1 else P(),
            shapes)

    def _gather_prog(self, size: int, dtype):
        import jax
        from jax.sharding import PartitionSpec as P

        key = ("gather", size, str(dtype))
        if key not in self._progs:
            axis = self.axis

            def _gather_local(p_shard):
                return jax.lax.all_gather(p_shard, axis, tiled=True)

            self._progs[key] = lower_shard_map(
                _gather_local, self.owner,
                in_specs=(P(axis),), out_specs=P(),
                axis_names=frozenset({axis}))
        return self._progs[key]

    def _init_prog(self, size: int, dtype):
        import jax
        from jax.sharding import PartitionSpec as P

        key = ("init", size, str(dtype))
        if key not in self._progs:
            axis, world, tx = self.axis, self.world, self.tx
            chunk = size // world

            def _init_local(p_shard):
                return tx.init(p_shard)

            self._progs[key] = lower_shard_map(
                _init_local, self.owner,
                in_specs=(P(axis),),
                out_specs=self._opt_specs(chunk, dtype),
                axis_names=frozenset({axis}))
        return self._progs[key]

    def _update_prog(self, size: int, dtype):
        import jax
        from jax.sharding import PartitionSpec as P

        key = ("update", size, str(dtype))
        if key not in self._progs:
            axis, world, tx = self.axis, self.world, self.tx
            chunk = size // world

            def _upd_local(p_shard, g_full, opt_local):
                import optax

                idx = jax.lax.axis_index(axis)
                g_shard = jax.lax.dynamic_slice(
                    g_full, (idx * chunk,), (chunk,))
                updates, new_opt = tx.update(g_shard, opt_local,
                                             p_shard)
                return optax.apply_updates(p_shard, updates), new_opt

            ospecs = self._opt_specs(chunk, dtype)
            self._progs[key] = lower_shard_map(
                _upd_local, self.owner,
                in_specs=(P(axis), P(), ospecs),
                out_specs=(P(axis), ospecs),
                axis_names=frozenset({axis}))
        return self._progs[key]
