"""MeshOwner — the one place device meshes are built, validated, and
handed out.

Before this layer, three call sites each built their own ``Mesh``
(parallel/mesh.py free functions, parallel/mesh_group.py gang workers,
serve/mesh_replica.py inference gangs) and every consumer re-derived
shardings ad hoc. ``MeshOwner`` centralizes that: it builds the mesh
(through the existing :func:`~ray_tpu.parallel.mesh.build_mesh`
topology logic), validates the degree layout against the available
devices, carries the :class:`SpecLayout`, and is the only factory for
``NamedSharding`` s — pruning spec axes the mesh doesn't carry, so the
canonical family specs target any mesh shape.

Both stacks consume the same object: the LLM engine lowers its
prefill/decode programs under ``owner.mesh`` (serve tp), and the
pipeline stage actors build their fsdp plane on one
(train/pipeline_cgraph.py). ``ray_tpu_mesh_devices`` gauges every live
owner (OBSERVABILITY.md).
"""
from __future__ import annotations

import itertools
import math
from typing import Any, Dict, Optional, Sequence, Union

from ...util import metrics as _metrics
from ..mesh import MESH_AXES, MeshSpec, build_mesh
from .layout import DEFAULT_LAYOUT, SpecLayout, prune_spec

_G_MESH = _metrics.Gauge(
    "ray_tpu_mesh_devices",
    "devices spanned by a live MeshOwner", tag_keys=("owner",))


class MeshOwner:
    """Owns one device mesh + its SpecLayout.

    Build from a :class:`MeshSpec` (or plain ``{axis: degree}`` dict)
    over explicit devices, or adopt an existing ``jax.sharding.Mesh``
    with :meth:`from_mesh`. All sharding decisions downstream go
    through :meth:`sharding` / :meth:`param_shardings` / :meth:`place`.
    """

    _ids = itertools.count()

    def __init__(self, spec: Union[MeshSpec, Dict[str, int], None] = None,
                 devices: Optional[Sequence[Any]] = None,
                 layout: Optional[SpecLayout] = None,
                 name: str = ""):
        import jax

        devices = list(devices if devices is not None else jax.devices())
        if isinstance(spec, dict):
            # partial degree dicts are the common spelling
            # ({"tp": 2}); fill the other axes at 1 and take exactly
            # the devices the layout needs (-1 wildcards keep every
            # device, mirroring MeshSpec semantics)
            spec = {a: int(spec.get(a, 1)) for a in MESH_AXES}
            if all(v > 0 for v in spec.values()):
                need = math.prod(spec.values())
                if need > len(devices):
                    raise ValueError(
                        f"mesh {spec} needs {need} devices; "
                        f"{len(devices)} available (is "
                        f"--xla_force_host_platform_device_count set on "
                        f"the verification backend?)")
                devices = devices[:need]
        self.mesh = build_mesh(spec, devices=devices)
        self.layout = layout or DEFAULT_LAYOUT
        self.name = name or f"mesh-{next(self._ids)}"
        self.axis_sizes: Dict[str, int] = dict(self.mesh.shape)
        _G_MESH.set(self.num_devices, tags={"owner": self.name})

    @classmethod
    def from_mesh(cls, mesh, layout: Optional[SpecLayout] = None,
                  name: str = "") -> "MeshOwner":
        self = cls.__new__(cls)
        self.mesh = mesh
        self.layout = layout or DEFAULT_LAYOUT
        self.name = name or f"mesh-{next(cls._ids)}"
        self.axis_sizes = dict(mesh.shape)
        _G_MESH.set(self.num_devices, tags={"owner": self.name})
        return self

    @classmethod
    def _one_axis_mesh(cls, what: str, axis: str, n: int,
                       devices: Optional[Sequence[Any]],
                       layout: Optional[SpecLayout],
                       name: str) -> "MeshOwner":
        import numpy as np

        import jax
        from jax.sharding import Mesh

        devices = list(devices if devices is not None
                       else jax.local_devices())
        if n < 1:
            raise ValueError(f"{what} must be >= 1, got {n}")
        if n > len(devices):
            raise ValueError(
                f"{what}={n} needs {n} devices; {len(devices)} available "
                f"(tests force host devices via XLA_FLAGS="
                f"--xla_force_host_platform_device_count=N)")
        mesh = Mesh(np.asarray(devices[:n]), (axis,))
        return cls.from_mesh(mesh, layout=layout, name=name)

    @classmethod
    def tp_mesh(cls, tp: int, devices: Optional[Sequence[Any]] = None,
                layout: Optional[SpecLayout] = None,
                name: str = "") -> "MeshOwner":
        """One-axis tensor-parallel mesh over the first ``tp`` devices —
        the serve-replica shape (one replica = one mesh spanning tp
        chips)."""
        lay = layout or DEFAULT_LAYOUT
        return cls._one_axis_mesh("tp", lay.tp_axis, tp, devices, lay,
                                  name)

    @classmethod
    def fsdp_mesh(cls, fsdp: int,
                  devices: Optional[Sequence[Any]] = None,
                  layout: Optional[SpecLayout] = None,
                  name: str = "") -> "MeshOwner":
        """One-axis fsdp mesh over the first ``fsdp`` local devices —
        the pipeline-stage shape (each stage actor spreads its chunk
        params/opt-state across its host's chips)."""
        lay = layout or DEFAULT_LAYOUT
        return cls._one_axis_mesh("fsdp", lay.fsdp_axis, fsdp, devices,
                                  lay, name)

    # -- introspection ------------------------------------------------------

    @property
    def num_devices(self) -> int:
        return int(math.prod(self.axis_sizes.values())) \
            if self.axis_sizes else 1

    @property
    def devices(self):
        return list(self.mesh.devices.flat)

    def axis_size(self, axis: str) -> int:
        return self.axis_sizes.get(axis, 1)

    def describe(self) -> Dict[str, Any]:
        return {"name": self.name, "axes": dict(self.axis_sizes),
                "devices": self.num_devices,
                "platform": self.devices[0].platform}

    # -- sharding factory ---------------------------------------------------

    def sharding(self, spec) -> Any:
        """PartitionSpec (or logical-axis tuple) -> NamedSharding on
        this mesh, with axes the mesh doesn't carry pruned to
        replication."""
        from jax.sharding import NamedSharding, PartitionSpec

        if spec is None:
            spec = PartitionSpec()
        elif not isinstance(spec, PartitionSpec):
            spec = self.layout.spec_for_logical(spec)
        return NamedSharding(self.mesh, prune_spec(spec,
                                                   self.axis_sizes))

    def replicated(self) -> Any:
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(self.mesh, PartitionSpec())

    def param_shardings(self, model) -> Dict[str, Any]:
        """Per-param NamedShardings from the model's logical axes
        through the layout's family mapping."""
        return {name: self.sharding(spec)
                for name, spec in self.layout.param_specs(model).items()}

    def place(self, tree, specs=None):
        """device_put a pytree onto this mesh. ``specs`` may be a
        matching pytree of PartitionSpecs, a single spec for every
        leaf, or None (replicate)."""
        import jax
        from jax.sharding import PartitionSpec

        if specs is None:
            sh = self.replicated()
            return jax.tree.map(lambda x: jax.device_put(x, sh), tree)
        if isinstance(specs, PartitionSpec):
            sh = self.sharding(specs)
            return jax.tree.map(lambda x: jax.device_put(x, sh), tree)
        return jax.tree.map(
            lambda x, s: jax.device_put(x, self.sharding(s)),
            tree, specs,
            is_leaf=lambda x: x is None)

    # -- validation ---------------------------------------------------------

    def validate_divisible(self, axis: str, dim: int, what: str) -> None:
        """Loud error when a dimension can't tile the mesh axis and the
        caller requires exact tiling (the fsdp flat plane does; GSPMD
        paths pad and don't)."""
        size = self.axis_size(axis)
        if size > 1 and dim % size:
            raise ValueError(
                f"{what} dimension {dim} not divisible by mesh axis "
                f"{axis!r} (size {size})")

    def per_device_bytes(self, tree) -> Dict[int, int]:
        """device id -> bytes this pytree's leaves keep resident there
        (the 1/fsdp / 1-per-chip-KV acceptance numbers read off this)."""
        out: Dict[int, int] = {d.id: 0 for d in self.devices}
        import jax

        for leaf in jax.tree.leaves(tree):
            if not hasattr(leaf, "addressable_shards"):
                continue
            for sh in leaf.addressable_shards:
                if sh.device.id in out:
                    out[sh.device.id] += int(sh.data.nbytes)
        return out
