"""SpecLayout — the canonical axis vocabulary for sharded execution.

Every sharded program in the framework speaks three mesh axes:

- ``data``  — batch parallelism (replicated params, sharded batch);
- ``fsdp``  — data parallelism with *sharded* params/opt-state
  (ZeRO-3 style: storage scales 1/fsdp, compute gathers);
- ``tp``    — tensor (megatron) parallelism: attention heads, FFN
  hidden, and the vocab dimension split across chips so a single
  program spans the mesh.

``SpecLayout`` turns that vocabulary into canonical
:class:`~jax.sharding.PartitionSpec` s per *parameter family* — the
SNIPPETS.md [3] shape. The family methods are the single source of
truth for how each kind of tensor shards; model code never spells a
raw ``PartitionSpec``. Models bridge in through their existing
``logical_axes()`` tables via :meth:`spec_for_logical`, so the same
annotations that drove the pure-dp paths now drive tp/fsdp lowering.

A spec may name axes the actual mesh doesn't have (a serve-tp mesh has
no ``fsdp`` axis); :class:`MeshOwner` prunes absent axes to replication
at ``NamedSharding`` time, so one layout serves every mesh shape.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Sequence, Tuple

from jax.sharding import PartitionSpec as P

Axis = str

#: logical axis name (models' ``logical_axes()``) -> SpecLayout axis
#: vocabulary. ``batch`` spreads over data+fsdp (fsdp acts as extra data
#: parallelism for activations); ``embed`` is the fsdp param-sharding
#: dim; heads/mlp/vocab are the megatron dims.
LOGICAL_TO_AXES: Dict[str, Optional[Tuple[Axis, ...]]] = {
    "batch": ("data", "fsdp"),
    "seq": None,
    "embed": None,          # contraction dim of every projection: keep
    # it whole so tp matmuls never partition the reduction (exactness)
    "heads": ("tp",),
    "kv": ("tp",),
    "mlp": ("tp",),
    "vocab": ("tp",),
    "expert": None,
    "stage": None,
}


@dataclass(frozen=True)
class SpecLayout:
    """Canonical PartitionSpecs per parameter/activation family.

    The default axis names match the framework vocabulary; rebinding
    them (e.g. ``SpecLayout(tp_axis="model")``) retargets every family
    spec at once.
    """

    data_axis: Axis = "data"
    fsdp_axis: Axis = "fsdp"
    tp_axis: Axis = "tp"

    # -- parameter families -------------------------------------------------

    def embeddings(self) -> P:
        """Token/positional embedding tables ``[V, D]``: vocab rows over
        tp (the LM-head matmul then contracts the *un*sharded D — every
        chip computes exact logits for its vocab slice)."""
        return P(self.tp_axis, None)

    def qkv_projection(self) -> P:
        """Attention input projections ``[.., D, H*hd]``: output heads
        over tp; the contraction dim D stays whole."""
        return P(None, None, self.tp_axis)

    def attn_output(self) -> P:
        """Attention output projection ``[.., H*hd, D]``: input heads
        over tp (pairs with qkv — the psum lives here)."""
        return P(None, self.tp_axis, None)

    def ffn_up(self) -> P:
        """FFN up/gate projections ``[.., D, F]``: hidden F over tp."""
        return P(None, None, self.tp_axis)

    def ffn_down(self) -> P:
        """FFN down projection ``[.., F, D]``: hidden F over tp."""
        return P(None, self.tp_axis, None)

    def norm(self) -> P:
        """Norm scales/biases: replicated (tiny, every chip needs all)."""
        return P()

    def bias(self, sharded: bool = False) -> P:
        """Projection biases ``[.., out]``: shard with their matmul's
        output dim when that dim is tp-sharded."""
        return P(None, self.tp_axis) if sharded else P()

    # -- activation / cache families ---------------------------------------

    def activations(self) -> P:
        """``[B, S, D]`` residual-stream activations: batch over
        data(+fsdp), everything else whole."""
        return P((self.data_axis, self.fsdp_axis), None, None)

    def kv_cache_blocks(self) -> P:
        """Paged KV pool ``[L, N, Bs, KH, hd]``: the *block* axis over
        tp — each chip owns 1/tp of the pool's blocks (the serve-tp
        memory win; docs/SHARDING.md)."""
        return P(None, self.tp_axis, None, None, None)

    def flat_params(self) -> P:
        """ZeRO/fsdp flat parameter vector: contiguous chunks over
        fsdp (parallel.sharding.fsdp plane)."""
        return P(self.fsdp_axis)

    def replicated(self) -> P:
        return P()

    # -- logical-axis bridge ------------------------------------------------

    def spec_for_logical(self,
                         logical: Sequence[Optional[str]]) -> P:
        """Map a model's per-param logical-axis tuple (its
        ``logical_axes()`` row) to a PartitionSpec in this layout's
        vocabulary. Unknown logical names replicate.

        The mapping deliberately never shards a contraction dimension
        (``embed``): tp matmuls then split only output/batch dims, so
        each partial program computes bit-exact slices and the only
        cross-chip reduction is the attention-output/FFN-down psum.
        """
        names = {"data": self.data_axis, "fsdp": self.fsdp_axis,
                 "tp": self.tp_axis}
        out = []
        for name in logical:
            axes = LOGICAL_TO_AXES.get(name) if name else None
            if not axes:
                out.append(None)
            elif len(axes) == 1:
                out.append(names[axes[0]])
            else:
                out.append(tuple(names[a] for a in axes))
        while out and out[-1] is None:
            out.pop()
        return P(*out)

    def param_specs(self, model) -> Dict[str, P]:
        """Per-parameter PartitionSpecs for any model exposing
        ``logical_axes()`` (gpt/llama/mlp/...)."""
        return {name: self.spec_for_logical(axes)
                for name, axes in model.logical_axes().items()}


#: the default layout instance shared framework-wide
DEFAULT_LAYOUT = SpecLayout()


def prune_spec(spec: P, axis_sizes: Dict[str, int]) -> P:
    """Drop spec axes the mesh doesn't carry (absent axis == size-1 ==
    replicated). A canonical family spec can then target any mesh —
    a tp-only serve mesh simply ignores the fsdp entries."""
    out = []
    for entry in spec:
        if entry is None:
            out.append(None)
        elif isinstance(entry, tuple):
            kept = tuple(a for a in entry if axis_sizes.get(a, 0) > 1)
            out.append(kept if len(kept) > 1 else
                       (kept[0] if kept else None))
        else:
            out.append(entry if axis_sizes.get(entry, 0) > 1 else None)
    while out and out[-1] is None:
        out.pop()
    return P(*out)
