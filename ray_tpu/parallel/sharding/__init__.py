"""ray_tpu.parallel.sharding — the sharded execution layer.

One subsystem owns mesh construction and parameter/activation layout
for the whole framework (docs/SHARDING.md):

- :class:`SpecLayout` (layout.py) — the ``data``/``fsdp``/``tp`` axis
  vocabulary producing canonical PartitionSpecs per parameter family,
  bridged to model ``logical_axes()`` tables.
- :class:`MeshOwner` (owner.py) — builds/validates device meshes and is
  the single NamedSharding factory; serve replicas and train stage
  actors consume the same object.
- lowering helpers (lower.py) — :func:`lower_jit` (GSPMD/pjit plane:
  the LLM engine's tp prefill/decode) and :func:`lower_shard_map`
  (manual plane: explicit collectives over owner-bound axes).
- :class:`FsdpPlane` (fsdp.py) — in-jit sharded param/opt-state storage
  for the pipeline stage programs (bit-identical to replicated).
"""
from .fsdp import FsdpParams, FsdpPlane
from .layout import DEFAULT_LAYOUT, LOGICAL_TO_AXES, SpecLayout, prune_spec
from .lower import lower_jit, lower_shard_map, sharded_init
from .owner import MeshOwner

__all__ = [
    "DEFAULT_LAYOUT", "FsdpParams", "FsdpPlane", "LOGICAL_TO_AXES",
    "MeshOwner", "SpecLayout", "lower_jit", "lower_shard_map",
    "prune_spec", "sharded_init",
]
