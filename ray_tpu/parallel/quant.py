"""Block-scaled quantization codecs for slow-wire transfers.

EQuARX ("Efficient Quantized AllReduce in XLA", PAPERS.md) shows that
block-scaled low-precision collectives recover near-fp32 quality at a
fraction of the bytes: split the flat tensor into fixed-size blocks,
scale each block by its absmax so the payload fits the narrow format's
range, ship narrow payload + one fp32 scale per block, and accumulate
the *dequantized* (fp32) values at the reduce point. This module is the
numpy/host half of that recipe — the wire format every slow-wire hop in
the framework shares:

- host collectives (`parallel/collective.py` ``codec=``) quantize the
  contribution each rank deposits in the rendezvous store;
- the ZeRO dp sync (`parallel/zero.py` ``grad_codec=``) compresses the
  gradient reduce-scatter and the parameter all-gather;
- cgraph channels (`cgraph/codec.py`) quantize large float arrays
  inside envelope payloads (pipeline activations/cotangents, disagg
  prefill→decode KV blocks).

The in-jit analog (quantize → all_to_all → dequantize under shard_map)
lives in `parallel/sharding/codec.py`.

Codecs:

- ``"int8"``: symmetric linear int8; per-block ``scale = absmax / 127``,
  payload ``rint(x / scale)`` (ties-to-even — deterministic, and the
  rounding numpy and XLA agree on). 4 bytes -> 1 + 4/block.
- ``"e4m3"``: float8 e4m3fn (4 exponent / 3 mantissa bits, max 448)
  via ml_dtypes (a jax dependency — no new install); per-block
  ``scale = absmax / 448`` so every block spends the format's full
  dynamic range. Same wire size as int8; relative error is more
  uniform across magnitudes within a block.

Both dequantize to fp32 and cast back to the source dtype; reductions
over quantized rows always happen AFTER dequantization, in fp32
("fp32 accumulation of scales").

Design notes (docs/COLLECTIVES.md): scales are fp32 absmax — never
rounded themselves; all-zero blocks keep scale 0 and decode to exact
zeros; payload + scales ship as one picklable :class:`QuantizedTensor`.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

__all__ = [
    "CODECS", "DEFAULT_BLOCK", "QuantizedTensor", "check_codec",
    "dequantize", "quantize", "wire_bytes",
]

CODECS = ("int8", "e4m3")
DEFAULT_BLOCK = 256

_INT8_MAX = 127.0
_E4M3_MAX = 448.0  # ml_dtypes.finfo(float8_e4m3fn).max


def check_codec(codec: Optional[str]) -> Optional[str]:
    """Validate a codec name (None passes through)."""
    if codec is None:
        return None
    if codec not in CODECS:
        raise ValueError(
            f"unknown codec {codec!r}; known codecs: {CODECS} "
            f"(None = full precision)")
    return codec


class QuantizedTensor:
    """One block-scaled quantized array: narrow payload + fp32 scales +
    the metadata to reconstruct shape/dtype. Picklable — this IS the
    wire record the host collectives and cgraph channels ship."""

    __slots__ = ("codec", "shape", "dtype", "block", "payload", "scales")

    def __init__(self, codec: str, shape: Tuple[int, ...], dtype: str,
                 block: int, payload: np.ndarray, scales: np.ndarray):
        self.codec = codec
        self.shape = tuple(shape)
        self.dtype = dtype
        self.block = int(block)
        self.payload = payload   # int8 [nblocks, block] (e4m3: uint8 bits)
        self.scales = scales     # float32 [nblocks]

    def __getstate__(self):
        return (self.codec, self.shape, self.dtype, self.block,
                self.payload, self.scales)

    def __setstate__(self, st):
        (self.codec, self.shape, self.dtype, self.block,
         self.payload, self.scales) = st

    def nbytes(self) -> int:
        """Bytes this record puts on the wire (payload + scales)."""
        return int(self.payload.nbytes + self.scales.nbytes)

    def source_nbytes(self) -> int:
        """Bytes the full-precision original would have shipped."""
        return int(np.prod(self.shape, dtype=np.int64)
                   * np.dtype(self.dtype).itemsize) if self.shape else \
            np.dtype(self.dtype).itemsize

    def __repr__(self) -> str:
        return (f"QuantizedTensor(codec={self.codec}, shape={self.shape},"
                f" dtype={self.dtype}, block={self.block},"
                f" wire={self.nbytes()}B)")


def _block_view(flat: np.ndarray, block: int) -> np.ndarray:
    """Pad to a block multiple and view as [nblocks, block]."""
    pad = (-flat.size) % block
    if pad:
        flat = np.concatenate([flat, np.zeros((pad,), flat.dtype)])
    return flat.reshape(-1, block)


def quantize(arr, codec: str = "int8",
             block: int = DEFAULT_BLOCK) -> QuantizedTensor:
    """Block-scaled quantization of an array-like to a wire record.

    Deterministic: same input bytes -> same output bytes, on every
    host (pure numpy, ties-to-even rounding).
    """
    check_codec(codec)
    a = np.asarray(arr)
    src_dtype = str(a.dtype)
    flat = np.ascontiguousarray(a, dtype=np.float32).ravel()
    blocks = _block_view(flat, block)
    absmax = np.max(np.abs(blocks), axis=1)
    if codec == "int8":
        scales = (absmax / _INT8_MAX).astype(np.float32)
        # all-zero blocks: scale 0 -> divide-by-1, payload exact zeros
        denom = np.where(scales > 0.0, scales, 1.0)[:, None]
        q = np.rint(blocks / denom)
        payload = np.clip(q, -_INT8_MAX, _INT8_MAX).astype(np.int8)
    else:  # e4m3
        import ml_dtypes

        scales = (absmax / _E4M3_MAX).astype(np.float32)
        denom = np.where(scales > 0.0, scales, 1.0)[:, None]
        scaled = (blocks / denom).astype(ml_dtypes.float8_e4m3fn)
        payload = scaled.view(np.uint8)
    return QuantizedTensor(codec, a.shape, src_dtype, block, payload,
                           scales)


def dequantize(qt: QuantizedTensor) -> np.ndarray:
    """Wire record -> array in the source shape/dtype. Values decode in
    fp32 (payload * per-block scale) before the final dtype cast."""
    if qt.codec == "int8":
        vals = qt.payload.astype(np.float32)
    else:
        import ml_dtypes

        vals = qt.payload.view(ml_dtypes.float8_e4m3fn).astype(np.float32)
    out = (vals * qt.scales[:, None]).ravel()
    n = int(np.prod(qt.shape, dtype=np.int64)) if qt.shape else 1
    out = out[:n].reshape(qt.shape)
    return out.astype(np.dtype(qt.dtype), copy=False)


def wire_bytes(value) -> int:
    """Bytes a collective contribution occupies on the wire: quantized
    records report payload+scales, arrays report nbytes, scalars their
    numpy size; opaque values report 0 (counted nowhere rather than
    paying a serialization just to measure)."""
    if isinstance(value, QuantizedTensor):
        return value.nbytes()
    if isinstance(value, np.ndarray):
        return int(value.nbytes)
    if isinstance(value, (int, float, np.number, bool)):
        return int(np.asarray(value).nbytes)
    try:
        a = np.asarray(value)
        if a.dtype != object:
            return int(a.nbytes)
    except Exception:
        pass
    return 0
