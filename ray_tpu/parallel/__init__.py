"""ray_tpu.parallel — the mesh/collective layer.

This package replaces the reference's NCCL/Gloo collective stack
(ref: python/ray/util/collective/collective.py:40 GroupManager,
collective_group/nccl_collective_group.py) and torch process-group
bootstrap (ref: python/ray/train/torch/config.py:69
_setup_torch_process_group) with TPU-native equivalents:

- `MeshSpec` / `build_mesh`: declarative device-mesh construction over
  dp/fsdp/tp/sp/ep/pp axes (jax.sharding.Mesh), on real TPU slices or
  virtual CPU devices for tests.
- logical-axis sharding rules (`AxisRules`, `logical_to_mesh`,
  `shard_constraint`): annotate pytrees once, let pjit/XLA insert the
  ICI collectives.
- `collective`: an explicit actor-to-actor collective API with the same
  verbs as the reference (allreduce/allgather/reducescatter/broadcast/
  send/recv), implemented over the object store for host tensors and
  over XLA collectives (psum/all_gather/ppermute) inside jit.
- `MeshGroup`: gang formation — hands each Train worker its mesh slice
  (the analog of TorchConfig handing each worker a process group).
- `zero`: ZeRO-style cross-replica sharding of the optimizer update
  (host-plane `ZeroUpdater` over the collective, in-jit
  `make_zero_update_spmd` over a mesh dp axis).
"""
from .mesh import (AxisRules, MeshSpec, build_mesh, default_axis_rules,
                   local_mesh, mesh_shape_for, named_sharding,
                   shard_constraint, logical_to_mesh, virtual_mesh)
from .collective import (allgather, allreduce, barrier, broadcast,
                         create_collective_group, destroy_collective_group,
                         get_group, recv, reduce, reducescatter, send)
from .mesh_group import MeshGroup, MeshWorkerMixin
from .quant import QuantizedTensor, dequantize, quantize
from .sharding import (FsdpPlane, MeshOwner, SpecLayout, lower_jit,
                       lower_shard_map)
from .zero import ZeroUpdater, make_zero_update_spmd

__all__ = [
    "MeshSpec", "build_mesh", "virtual_mesh", "local_mesh", "named_sharding",
    "shard_constraint", "logical_to_mesh", "AxisRules", "default_axis_rules",
    "mesh_shape_for",
    "create_collective_group", "destroy_collective_group", "get_group",
    "allreduce", "allgather", "reducescatter", "broadcast", "reduce",
    "send", "recv", "barrier",
    "MeshGroup", "MeshWorkerMixin",
    "MeshOwner", "SpecLayout", "FsdpPlane", "lower_jit", "lower_shard_map",
    "ZeroUpdater", "make_zero_update_spmd",
    "QuantizedTensor", "quantize", "dequantize",
]
