"""Device-mesh construction and sharding rules.

TPU-native replacement for the reference's process-group bootstrap
(ref: python/ray/train/torch/config.py:69 _setup_torch_process_group,
python/ray/util/collective/collective.py:258-615). On TPU there is no
per-tensor NCCL group: the unit of parallelism is a `jax.sharding.Mesh`
over which pjit/shard_map place XLA collectives on ICI. This module owns:

- `MeshSpec`: declarative parallelism degrees (dp/fsdp/tp/sp/ep/pp).
- `build_mesh`: devices -> Mesh, preferring ICI-contiguous axis order.
- logical axis rules: model code annotates pytrees with *logical* axes
  ("batch", "embed", "heads", ...) which map to mesh axes here — the
  flax `logical_axis_rules` idea, reimplemented standalone.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Canonical mesh axis names, outermost (slowest/DCN-most) first.  Ordering
# matters: jax lays devices out so the *last* axes are ICI-nearest, so we put
# tensor/seq (latency-sensitive, every-layer collectives) last and dp/pp
# (per-step collectives, DCN-tolerant) first.  This mirrors the scaling-book
# recipe: data outermost, model innermost.
MESH_AXES = ("pp", "dp", "fsdp", "ep", "sp", "tp")


@dataclass(frozen=True)
class MeshSpec:
    """Parallelism degrees. -1 on exactly one axis means "fill with all
    remaining devices" (like torch DeviceMesh / t5x partitioning)."""
    dp: int = -1      # pure data parallel (replicated params)
    fsdp: int = 1     # data parallel with sharded params (zero-3 style)
    tp: int = 1       # tensor (megatron) parallel
    sp: int = 1       # sequence/context parallel (ring attention axis)
    ep: int = 1       # expert parallel (MoE)
    pp: int = 1       # pipeline parallel

    def degrees(self) -> Dict[str, int]:
        return {"pp": self.pp, "dp": self.dp, "fsdp": self.fsdp,
                "ep": self.ep, "sp": self.sp, "tp": self.tp}

    def resolve(self, n_devices: int) -> Dict[str, int]:
        """Fill the single -1 axis so the product equals n_devices."""
        d = self.degrees()
        wild = [k for k, v in d.items() if v == -1]
        if len(wild) > 1:
            raise ValueError(f"At most one mesh axis may be -1, got {wild}")
        fixed = math.prod(v for v in d.values() if v != -1)
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}")
            d[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"Mesh {d} wants {fixed} devices but {n_devices} are available")
        return d


def build_mesh(spec: Union[MeshSpec, Dict[str, int], None] = None,
               devices: Optional[Sequence[jax.Device]] = None,
               axis_names: Sequence[str] = MESH_AXES) -> Mesh:
    """Build a Mesh from a spec over the given (default: all) devices.

    Uses `mesh_utils.create_device_mesh` when possible so the physical ICI
    topology lines up with the logical axes; falls back to a plain reshape
    on virtual/CPU devices.
    """
    devices = list(devices if devices is not None else jax.devices())
    if spec is None:
        spec = MeshSpec()
    degrees = spec.resolve(len(devices)) if isinstance(spec, MeshSpec) else dict(spec)
    shape = tuple(degrees[a] for a in axis_names)
    try:
        from jax.experimental import mesh_utils
        if devices[0].platform == "tpu":
            dev_array = mesh_utils.create_device_mesh(
                shape, devices=devices, allow_split_physical_axes=True)
        else:
            raise ValueError  # virtual devices: plain reshape is fine
    except Exception:
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, axis_names)


def virtual_mesh(n_devices: int,
                 spec: Union[MeshSpec, Dict[str, int], None] = None) -> Mesh:
    """Mesh over the first n host/virtual devices — the test path
    (conftest sets xla_force_host_platform_device_count)."""
    return build_mesh(spec, devices=jax.devices()[:n_devices])


def local_mesh() -> Mesh:
    """Single-process mesh over all local devices, dp-major."""
    return build_mesh(MeshSpec(dp=-1), devices=jax.local_devices())


def mesh_shape_for(n_devices: int, prefer_tp: int = 1) -> MeshSpec:
    """Heuristic spec: cap tp at prefer_tp (and at n), rest goes to dp."""
    tp = math.gcd(prefer_tp, n_devices)
    return MeshSpec(dp=-1, tp=tp)


# ---------------------------------------------------------------------------
# Logical axis rules
# ---------------------------------------------------------------------------

#: rule list: logical axis name -> mesh axis (or tuple of mesh axes, or None)
Rules = Sequence[Tuple[str, Union[str, Tuple[str, ...], None]]]


@dataclass
class AxisRules:
    """Maps logical axis names used by model code to physical mesh axes.

    Equivalent in spirit to flax.linen.logical_axis_rules; standalone so
    models can be plain pytrees. First matching rule wins; unknown logical
    axes are unsharded (None).
    """
    rules: Rules = field(default_factory=lambda: default_axis_rules())

    def mesh_axes(self, logical: Sequence[Optional[str]]) -> P:
        out: List[Union[str, Tuple[str, ...], None]] = []
        for name in logical:
            if name is None:
                out.append(None)
                continue
            for key, axes in self.rules:
                if key == name:
                    out.append(axes)
                    break
            else:
                out.append(None)
        # Trim trailing Nones (canonical PartitionSpec form).
        while out and out[-1] is None:
            out.pop()
        return P(*out)


def default_axis_rules(fsdp_enabled: bool = True) -> Rules:
    """The standard decoder-LM mapping (scaling-book style):
    batch -> dp(+fsdp), sequence -> sp, embed -> fsdp (param sharding),
    heads/mlp -> tp, experts -> ep, pipeline stage handled outside."""
    return (
        ("batch", ("dp", "fsdp") if fsdp_enabled else "dp"),
        ("seq", "sp"),
        ("embed", "fsdp" if fsdp_enabled else None),
        ("heads", "tp"),
        ("kv", None),
        ("mlp", "tp"),
        ("vocab", "tp"),
        ("expert", "ep"),
        ("stage", "pp"),
    )


def logical_to_mesh(tree: Any, logical_tree: Any, mesh: Mesh,
                    rules: Optional[AxisRules] = None) -> Any:
    """Map a pytree of logical-axis tuples to a pytree of NamedShardings."""
    rules = rules or AxisRules()
    return jax.tree.map(
        lambda logical: NamedSharding(mesh, rules.mesh_axes(logical)),
        logical_tree, is_leaf=lambda x: isinstance(x, tuple))


def named_sharding(mesh: Mesh, *axes: Union[str, Tuple[str, ...], None]) -> NamedSharding:
    return NamedSharding(mesh, P(*axes))


def shard_constraint(x: Any, mesh: Mesh,
                     *logical: Optional[str],
                     rules: Optional[AxisRules] = None) -> Any:
    """with_sharding_constraint via logical axis names. Safe to call outside
    jit (no-op annotation will still place the array)."""
    rules = rules or AxisRules()
    spec = rules.mesh_axes(logical)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
