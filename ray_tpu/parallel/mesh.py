"""Device-mesh construction and sharding rules.

TPU-native replacement for the reference's process-group bootstrap
(ref: python/ray/train/torch/config.py:69 _setup_torch_process_group,
python/ray/util/collective/collective.py:258-615). On TPU there is no
per-tensor NCCL group: the unit of parallelism is a `jax.sharding.Mesh`
over which pjit/shard_map place XLA collectives on ICI. This module owns:

- `MeshSpec`: declarative parallelism degrees (dp/fsdp/tp/sp/ep/pp).
- `build_mesh`: devices -> Mesh, preferring ICI-contiguous axis order.
- logical axis rules: model code annotates pytrees with *logical* axes
  ("batch", "embed", "heads", ...) which map to mesh axes here — the
  flax `logical_axis_rules` idea, reimplemented standalone.

Mesh OWNERSHIP (who builds/validates the mesh and hands out
NamedShardings) lives one level up in `parallel.sharding.MeshOwner`:
this module provides the topology primitives, the sharding package the
layer both serve (LLM tp) and train (pipeline fsdp) consume
(docs/SHARDING.md).
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Canonical mesh axis names, outermost (slowest/DCN-most) first.  Ordering
# matters: jax lays devices out so the *last* axes are ICI-nearest, so we put
# tensor/seq (latency-sensitive, every-layer collectives) last and dp/pp
# (per-step collectives, DCN-tolerant) first.  This mirrors the scaling-book
# recipe: data outermost, model innermost.
MESH_AXES = ("pp", "dp", "fsdp", "ep", "sp", "tp")


@dataclass(frozen=True)
class MeshSpec:
    """Parallelism degrees. -1 on exactly one axis means "fill with all
    remaining devices" (like torch DeviceMesh / t5x partitioning).

    `slices` > 1 declares a MULTI-SLICE job: devices span that many TPU
    slices joined by DCN (no ICI between slices). The mesh gains an
    outermost "slice" axis; per-slice ICI meshes compose under it, so
    collectives over "slice" ride DCN and everything else stays on ICI —
    the megascale recipe (dp over DCN, model axes within a slice)."""
    dp: int = -1      # pure data parallel (replicated params)
    fsdp: int = 1     # data parallel with sharded params (zero-3 style)
    tp: int = 1       # tensor (megatron) parallel
    sp: int = 1       # sequence/context parallel (ring attention axis)
    ep: int = 1       # expert parallel (MoE)
    pp: int = 1       # pipeline parallel
    slices: int = 1   # DCN-connected slices (outermost axis when > 1)

    def degrees(self) -> Dict[str, int]:
        return {"pp": self.pp, "dp": self.dp, "fsdp": self.fsdp,
                "ep": self.ep, "sp": self.sp, "tp": self.tp}

    def resolve(self, n_devices: int) -> Dict[str, int]:
        """Fill the single -1 axis so the per-slice product equals
        n_devices / slices."""
        if self.slices < 1:
            raise ValueError(f"slices must be >= 1, got {self.slices}")
        if n_devices % self.slices:
            raise ValueError(
                f"{n_devices} devices not divisible into {self.slices} slices")
        per_slice = n_devices // self.slices
        d = self.degrees()
        wild = [k for k, v in d.items() if v == -1]
        if len(wild) > 1:
            raise ValueError(f"At most one mesh axis may be -1, got {wild}")
        fixed = math.prod(v for v in d.values() if v != -1)
        if wild:
            if per_slice % fixed:
                raise ValueError(
                    f"{per_slice} per-slice devices not divisible by fixed "
                    f"axes product {fixed}")
            d[wild[0]] = per_slice // fixed
        elif fixed != per_slice:
            raise ValueError(
                f"Mesh {d} wants {fixed} devices/slice but {per_slice} "
                f"are available")
        return d


def build_mesh(spec: Union[MeshSpec, Dict[str, int], None] = None,
               devices: Optional[Sequence[jax.Device]] = None,
               axis_names: Sequence[str] = MESH_AXES) -> Mesh:
    """Build a Mesh from a spec over the given (default: all) devices.

    Uses `mesh_utils.create_device_mesh` when possible so the physical ICI
    topology lines up with the logical axes; falls back to a plain reshape
    on virtual/CPU devices. A MeshSpec with slices > 1 produces a
    DCN-aware mesh: outermost "slice" axis over per-slice ICI meshes.
    """
    devices = list(devices if devices is not None else jax.devices())
    if spec is None:
        spec = MeshSpec()
    if isinstance(spec, MeshSpec) and spec.slices > 1:
        return build_multislice_mesh(spec, devices, axis_names)
    degrees = spec.resolve(len(devices)) if isinstance(spec, MeshSpec) else dict(spec)
    shape = tuple(degrees[a] for a in axis_names)
    try:
        from jax.experimental import mesh_utils
        if devices[0].platform == "tpu":
            dev_array = mesh_utils.create_device_mesh(
                shape, devices=devices, allow_split_physical_axes=True)
        else:
            raise ValueError  # virtual devices: plain reshape is fine
    except Exception:
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, axis_names)


def group_devices_by_slice(devices: Sequence[jax.Device],
                           num_slices: int) -> List[List[jax.Device]]:
    """Partition devices into their physical slices. Real multi-slice TPU
    devices carry `slice_index`; virtual/CPU devices (tests) split into
    contiguous equal groups."""
    by_idx: Dict[int, List[jax.Device]] = {}
    if all(getattr(d, "slice_index", None) is not None for d in devices):
        for d in devices:
            by_idx.setdefault(d.slice_index, []).append(d)
        if len(by_idx) == num_slices:
            return [by_idx[i] for i in sorted(by_idx)]
        # topology disagrees with the spec: fall through to error
        raise ValueError(
            f"spec wants {num_slices} slices but devices report "
            f"{len(by_idx)} distinct slice_index values")
    per = len(devices) // num_slices
    return [list(devices[i * per:(i + 1) * per]) for i in range(num_slices)]


def build_multislice_mesh(spec: MeshSpec,
                          devices: Optional[Sequence[jax.Device]] = None,
                          axis_names: Sequence[str] = MESH_AXES) -> Mesh:
    """Compose per-slice ICI meshes under an outermost "slice" DCN axis
    (SURVEY §5 comm-backend: DCN-aware multi-slice meshes; the analog of
    mesh_utils.create_hybrid_device_mesh). Collectives that name "slice"
    lower to DCN transfers; all other axes stay within a slice's ICI."""
    devices = list(devices if devices is not None else jax.devices())
    degrees = spec.resolve(len(devices))
    inner_shape = tuple(degrees[a] for a in axis_names)
    groups = group_devices_by_slice(devices, spec.slices)
    per_slice = []
    for g in groups:
        try:
            from jax.experimental import mesh_utils
            if g[0].platform == "tpu":
                arr = mesh_utils.create_device_mesh(
                    inner_shape, devices=g, allow_split_physical_axes=True)
            else:
                raise ValueError
        except Exception:
            arr = np.asarray(g).reshape(inner_shape)
        per_slice.append(arr)
    dev_array = np.stack(per_slice, axis=0)
    return Mesh(dev_array, ("slice", *axis_names))


def virtual_mesh(n_devices: int,
                 spec: Union[MeshSpec, Dict[str, int], None] = None) -> Mesh:
    """Mesh over the first n host/virtual devices — the test path
    (conftest sets xla_force_host_platform_device_count)."""
    return build_mesh(spec, devices=jax.devices()[:n_devices])


def local_mesh() -> Mesh:
    """Single-process mesh over all local devices, dp-major."""
    return build_mesh(MeshSpec(dp=-1), devices=jax.local_devices())


def mesh_shape_for(n_devices: int, prefer_tp: int = 1) -> MeshSpec:
    """Heuristic spec: cap tp at prefer_tp (and at n), rest goes to dp."""
    tp = math.gcd(prefer_tp, n_devices)
    return MeshSpec(dp=-1, tp=tp)


# ---------------------------------------------------------------------------
# Logical axis rules
# ---------------------------------------------------------------------------

#: rule list: logical axis name -> mesh axis (or tuple of mesh axes, or None)
Rules = Sequence[Tuple[str, Union[str, Tuple[str, ...], None]]]


@dataclass
class AxisRules:
    """Maps logical axis names used by model code to physical mesh axes.

    Equivalent in spirit to flax.linen.logical_axis_rules; standalone so
    models can be plain pytrees. First matching rule wins; unknown logical
    axes are unsharded (None).
    """
    rules: Rules = field(default_factory=lambda: default_axis_rules())

    def mesh_axes(self, logical: Sequence[Optional[str]]) -> P:
        out: List[Union[str, Tuple[str, ...], None]] = []
        for name in logical:
            if name is None:
                out.append(None)
                continue
            for key, axes in self.rules:
                if key == name:
                    out.append(axes)
                    break
            else:
                out.append(None)
        # Trim trailing Nones (canonical PartitionSpec form).
        while out and out[-1] is None:
            out.pop()
        return P(*out)


def default_axis_rules(fsdp_enabled: bool = True,
                       multislice: bool = False) -> Rules:
    """The standard decoder-LM mapping (scaling-book style):
    batch -> dp(+fsdp), sequence -> sp, embed -> fsdp (param sharding),
    heads/mlp -> tp, experts -> ep, pipeline stage handled outside.
    multislice=True prepends the DCN "slice" axis to the batch mapping —
    data parallel across slices, model axes within a slice."""
    if multislice:
        batch_axes = (("slice", "dp", "fsdp") if fsdp_enabled
                      else ("slice", "dp"))
        return (("batch", batch_axes),) + tuple(
            r for r in default_axis_rules(fsdp_enabled) if r[0] != "batch")
    return (
        ("batch", ("dp", "fsdp") if fsdp_enabled else "dp"),
        ("seq", "sp"),
        ("embed", "fsdp" if fsdp_enabled else None),
        ("heads", "tp"),
        ("kv", None),
        ("mlp", "tp"),
        ("vocab", "tp"),
        ("expert", "ep"),
        ("stage", "pp"),
    )


def logical_to_mesh(tree: Any, logical_tree: Any, mesh: Mesh,
                    rules: Optional[AxisRules] = None) -> Any:
    """Map a pytree of logical-axis tuples to a pytree of NamedShardings."""
    rules = rules or AxisRules()
    return jax.tree.map(
        lambda logical: NamedSharding(mesh, rules.mesh_axes(logical)),
        logical_tree, is_leaf=lambda x: isinstance(x, tuple))


def named_sharding(mesh: Mesh, *axes: Union[str, Tuple[str, ...], None]) -> NamedSharding:
    return NamedSharding(mesh, P(*axes))


def shard_constraint(x: Any, mesh: Mesh,
                     *logical: Optional[str],
                     rules: Optional[AxisRules] = None) -> Any:
    """with_sharding_constraint via logical axis names. Safe to call outside
    jit (no-op annotation will still place the array)."""
    rules = rules or AxisRules()
    spec = rules.mesh_axes(logical)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
