"""Pipeline parallelism over the `pp` mesh axis.

The reference has NO pipeline engine (SURVEY.md §5: tensor/pipeline
parallelism is first-class new work for the TPU build; RLlib/Train are DP
only — ref: python/ray/train/torch/train_loop_utils.py:329 wraps DDP/FSDP,
nothing stage-parallel). Two layers live here:

1. `pipeline_spmd` — the TPU-native core: a collective microbatch pipeline
   INSIDE one jitted program. Stage parameters are stacked on a leading
   axis sharded over `pp`; activations flow stage-to-stage with
   `lax.ppermute` (ICI neighbor hops) inside a `lax.scan` over
   M + P - 1 ticks (GPipe schedule). `jax.shard_map(axis_names={'pp'})`
   keeps `pp` manual while dp/fsdp/tp stay GSPMD-auto, so the pipeline
   composes with data/tensor sharding without hand-written collectives.
   The whole thing is differentiable: AD reverses the scan and transposes
   each ppermute, yielding the backward pipeline automatically.

2. `schedule_1f1b` — the explicit per-stage 1F1B order (warmup fwds, then
   alternating 1F/1B, then cooldown bwds). The actor-hosted engine
   (ray_tpu/train/pipeline_engine.py) executes this schedule across stage
   actors; tests assert its bubble structure.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


# ---------------------------------------------------------------------------
# 1F1B schedule (host-level description; used by the actor engine + tests)
# ---------------------------------------------------------------------------


def schedule_1f1b(num_stages: int, num_microbatches: int
                  ) -> List[List[Tuple[str, int]]]:
    """Per-stage operation order for one training step.

    Returns `sched[stage] = [("fwd", mb) | ("bwd", mb), ...]` with the
    classic 1F1B structure: stage i runs `min(num_stages - i, M)` warmup
    forwards, then alternates one-forward-one-backward, then drains the
    remaining backwards. Properties (asserted by tests):
      - each stage does M forwards and M backwards, each microbatch once;
      - backward of mb j on stage i only after forward of mb j on stage i;
      - in-flight forwards on stage i never exceed num_stages - i
        (the activation-memory bound that motivates 1F1B over GPipe).
    """
    P_, M = num_stages, num_microbatches
    sched: List[List[Tuple[str, int]]] = []
    for i in range(P_):
        ops: List[Tuple[str, int]] = []
        warmup = min(P_ - i, M)
        f = b = 0
        for _ in range(warmup):
            ops.append(("fwd", f))
            f += 1
        while b < M:
            ops.append(("bwd", b))
            b += 1
            if f < M:
                ops.append(("fwd", f))
                f += 1
        sched.append(ops)
    return sched


def schedule_interleaved_1f1b(num_stages: int, num_microbatches: int,
                              virtual: int = 1
                              ) -> List[List[Tuple[str, int, int]]]:
    """Per-ACTOR op order for interleaved 1F1B with ``virtual`` model
    chunks per actor (the Megatron/MPMD interleaved schedule shape:
    actor i hosts global chunks i, i+P, i+2P, ...).

    Returns ``sched[actor] = [(kind, v, mb), ...]`` where ``v`` is the
    local virtual-stage index (global chunk ``g = v*P + i``). For
    virtual == 1 this is exactly :func:`schedule_1f1b` lifted to
    triples, so the non-interleaved engine path keeps the proven
    schedule bit-for-bit.

    For virtual > 1 the order comes from a tick-based list-scheduling
    simulation: each actor executes at most one op per tick, preferring
    a ready backward (eager-backward bounds in-flight activations),
    else the shallowest ready forward. Because the emitted per-actor
    order IS a linear extension of the fwd/bwd dependency DAG realized
    by the simulation, executing it with blocking channel reads (and
    non-blocking sends, i.e. >= M slots per edge) cannot deadlock.
    """
    P_, M, V = num_stages, num_microbatches, virtual
    if V <= 1:
        return [[(kind, 0, mb) for kind, mb in ops]
                for ops in schedule_1f1b(P_, M)]
    G = P_ * V
    done: Dict[Tuple[str, int, int], int] = {}  # (kind, g, mb) -> tick
    fnext = [0] * G  # next fwd microbatch per global chunk
    bnext = [0] * G  # next bwd microbatch per global chunk
    sched: List[List[Tuple[str, int, int]]] = [[] for _ in range(P_)]
    t = 0
    total = 2 * G * M
    while len(done) < total:
        progressed = False
        picks = []
        for i in range(P_):
            best = None
            for v in range(V):
                g = v * P_ + i
                mb = bnext[g]
                if mb < M and ("fwd", g, mb) in done \
                        and done[("fwd", g, mb)] <= t \
                        and (g == G - 1
                             or done.get(("bwd", g + 1, mb), t + 1) <= t):
                    cand = ("bwd", v, mb, g)
                    # drain the oldest microbatch first, deepest chunk
                    # first (its grad unblocks the longest chain)
                    if best is None \
                            or (cand[2], -cand[3]) < (best[2], -best[3]):
                        best = cand
            if best is None:
                for v in range(V):
                    g = v * P_ + i
                    mb = fnext[g]
                    if mb < M and (g == 0
                                   or done.get(("fwd", g - 1, mb),
                                               t + 1) <= t):
                        cand = ("fwd", v, mb, g)
                        # fill shallow chunks first: warmup order
                        if best is None or (cand[1], cand[2]) \
                                < (best[1], best[2]):
                            best = cand
            if best is not None:
                kind, v, mb, g = best
                picks.append((kind, g, mb))
                sched[i].append((kind, v, mb))
                if kind == "fwd":
                    fnext[g] += 1
                else:
                    bnext[g] += 1
                progressed = True
        # ops picked this tick complete at t+1 (unit latency keeps the
        # realized order consistent with the cross-actor dependencies)
        for kind, g, mb in picks:
            done[(kind, g, mb)] = t + 1
        t += 1
        if not progressed and len(done) < total:
            raise RuntimeError(
                "interleaved 1F1B simulation stalled (bug): "
                f"P={P_} M={M} V={V} done={len(done)}/{total}")
    return sched


# ---------------------------------------------------------------------------
# In-XLA collective pipeline (GPipe schedule, AD gives the reverse pipeline)
# ---------------------------------------------------------------------------


def pipeline_spmd(stage_fn: Callable[[Any, jax.Array], jax.Array],
                  stage_params: Any,
                  x_mb: jax.Array,
                  mesh: Mesh,
                  pp_axis: str = "pp") -> jax.Array:
    """Run `stage_fn` over P pipeline stages for M microbatches.

    stage_params: pytree whose leaves have leading axis P (one slice per
        stage); sharded over `pp_axis` by the shard_map in_spec.
    x_mb: [M, ...] microbatched input of stage 0. Batch/seq sharding over
        other mesh axes is preserved (they stay GSPMD-auto).
    Returns [M, ...] outputs of the last stage, replicated over `pp_axis`.
    """
    P_ = mesh.shape[pp_axis]
    M = x_mb.shape[0]
    if P_ == 1:
        sp = jax.tree.map(lambda a: a[0], stage_params)
        return jnp.stack([stage_fn(sp, x_mb[i]) for i in range(M)])

    perm = [(i, (i + 1) % P_) for i in range(P_)]

    def body(sp_local, x_loc):
        # sp_local leaves: [1, ...] (this stage's slice) — drop the axis
        sp = jax.tree.map(lambda a: a[0], sp_local)
        idx = jax.lax.axis_index(pp_axis)
        # initial carries must be marked pp-varying: the ticks fill them
        # with per-stage values, and scan requires carry types to be stable
        def _vary(x):
            from ..jax_compat import pvary

            return pvary(x, (pp_axis,))
        state = _vary(jnp.zeros_like(x_loc[0]))
        ybuf = _vary(jnp.zeros_like(x_loc))

        def tick(carry, t):
            state, ybuf = carry
            mb = jax.lax.dynamic_index_in_dim(
                x_loc, jnp.clip(t, 0, M - 1), 0, keepdims=False)
            inp = jnp.where(idx == 0, mb, state)
            out = stage_fn(sp, inp)
            # stage P-1 emitted microbatch t-(P-1) this tick
            ot = t - (P_ - 1)
            upd = jax.lax.dynamic_update_index_in_dim(
                ybuf, out, jnp.clip(ot, 0, M - 1), 0)
            ybuf = jnp.where(jnp.logical_and(idx == P_ - 1, ot >= 0),
                             upd, ybuf)
            state = jax.lax.ppermute(out, pp_axis, perm)
            return (state, ybuf), None

        (_, ybuf), _ = jax.lax.scan(tick, (state, ybuf),
                                    jnp.arange(M + P_ - 1))
        # only the last stage holds real outputs; replicate over the ring
        ybuf = jax.lax.psum(
            jnp.where(idx == P_ - 1, ybuf, jnp.zeros_like(ybuf)), pp_axis)
        return ybuf

    param_specs = jax.tree.map(lambda _: P(pp_axis), stage_params)
    from ..jax_compat import shard_map

    fn = shard_map(body, mesh=mesh,
                   in_specs=(param_specs, P()), out_specs=P(),
                   axis_names=frozenset({pp_axis}))
    return fn(stage_params, x_mb)


def stack_stages(layer_params: Dict[str, jax.Array], num_stages: int
                 ) -> Dict[str, jax.Array]:
    """[L, ...] stacked per-layer params -> [P, L/P, ...] per-stage."""
    out = {}
    for k, v in layer_params.items():
        L = v.shape[0]
        if L % num_stages:
            raise ValueError(
                f"{k}: {L} layers not divisible into {num_stages} stages")
        out[k] = v.reshape(num_stages, L // num_stages, *v.shape[1:])
    return out
