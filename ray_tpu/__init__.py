"""ray_tpu — a TPU-native distributed ML framework with the capabilities of Ray.

Public core API mirrors the reference's surface
(ref: python/ray/__init__.py; worker.py:1108 init, :2390 get, :2519 put,
:2582 wait) while the runtime underneath is single-controller and
mesh-first — see README.md and SURVEY.md.
"""
from __future__ import annotations

import os
import time as _time
from typing import Any, Dict, List, Optional, Sequence

from ._version import __version__
from . import exceptions
from . import cgraph
from .cgraph import InputNode, MultiOutputNode
from .core import runtime as _runtime_mod
from .core.actor import ActorClass, ActorHandle, get_actor
from .core.config import Config
from .core.ids import ActorId, JobId, NodeId, ObjectId, TaskId, WorkerId
from .core.object_ref import ObjectRef, ObjectRefGenerator
from .core.placement_group import (PlacementGroup, placement_group,
                                   placement_group_table,
                                   remove_placement_group)
from .core.remote_function import RemoteFunction
from .core.runtime import DriverRuntime, RuntimeContext

__all__ = [
    "init", "shutdown", "is_initialized", "remote", "get", "put", "wait",
    "kill", "cancel", "get_actor", "nodes", "cluster_resources",
    "available_resources", "get_runtime_context", "ObjectRef",
    "ObjectRefGenerator",
    "placement_group", "remove_placement_group", "placement_group_table",
    "PlacementGroup", "exceptions", "method", "__version__",
    "cgraph", "InputNode", "MultiOutputNode",
]


def init(num_cpus: Optional[float] = None,
         num_tpus: Optional[float] = None,
         num_nodes: int = 1,
         resources: Optional[Dict[str, float]] = None,
         address: Optional[str] = None,
         authkey: Optional[str] = None,
         namespace: str = "default",
         system_config: Optional[Dict[str, Any]] = None,
         ignore_reinit_error: bool = False,
         object_store_memory: Optional[int] = None,
         runtime_env: Optional[Dict[str, Any]] = None,
         **_ignored) -> DriverRuntime:
    """Start (or connect to) the runtime. Inside a worker this is a no-op
    returning the ambient WorkerRuntime, matching the reference's behavior."""
    if address:
        # remote-driver mode (the Ray Client equivalent): attach to a
        # running head instead of starting a local cluster
        from .client import ClientRuntime

        existing = _runtime_mod.maybe_runtime()
        if existing is not None:
            # silently handing back a DIFFERENT cluster's runtime would
            # run the caller's work on the wrong cluster
            if getattr(existing, "_address", None) == address:
                return existing
            raise RuntimeError(
                f"ray_tpu.init(address={address!r}) called but this "
                f"process already has a runtime "
                f"({type(existing).__name__}); call ray_tpu.shutdown() "
                f"first")
        client = ClientRuntime(address, authkey=authkey)
        client._address = address
        _runtime_mod.set_runtime(client)
        return client
    existing = _runtime_mod.maybe_runtime()
    if existing is not None:
        if isinstance(existing, DriverRuntime) and not ignore_reinit_error:
            raise RuntimeError(
                "ray_tpu.init() called twice; pass ignore_reinit_error=True")
        if runtime_env and isinstance(existing, DriverRuntime):
            # re-init with a job env must not silently drop it: it becomes
            # the new job-level default for subsequent submissions
            from .core import runtime_env as _renv_mod

            existing.default_runtime_env = _renv_mod.validate(runtime_env)
        return existing
    res: Dict[str, float] = dict(resources or {})
    res.setdefault("CPU", float(num_cpus if num_cpus is not None
                                else (os.cpu_count() or 1)))
    if num_tpus is not None:
        res["TPU"] = float(num_tpus)
    if object_store_memory is not None:
        res["object_store_memory"] = float(object_store_memory)
    rt = DriverRuntime(resources=res, num_nodes=num_nodes,
                       config=Config(system_config), namespace=namespace)
    if int(rt.config.metrics_export_port):
        # opt-in Prometheus exposition at a fixed port (config/env
        # RTPU_METRICS_EXPORT_PORT); ephemeral-port serving remains
        # available any time via metrics.start_metrics_server()
        from .util import metrics as _metrics_mod

        global _metrics_server_from_init
        was_running = _metrics_mod._server is not None
        try:
            _metrics_mod.start_metrics_server(
                port=int(rt.config.metrics_export_port))
            # only own the lifecycle when init() actually bound it — a
            # user-started server must survive ray_tpu.shutdown()
            _metrics_server_from_init = not was_running
        except OSError:
            pass  # port taken: init must not fail over observability
    if runtime_env:
        # job-level default: merged under every task/actor env (ref:
        # job_config.py runtime_env; validated now so errors hit at init)
        from .core import runtime_env as _renv_mod

        rt.default_runtime_env = _renv_mod.validate(runtime_env)
    _runtime_mod.set_runtime(rt)
    return rt


_metrics_server_from_init = False


def shutdown() -> None:
    global _metrics_server_from_init
    rt = _runtime_mod.maybe_runtime()
    if rt is not None:
        rt.shutdown()
        _runtime_mod.set_runtime(None)
        if isinstance(rt, DriverRuntime):
            # the shipped worker/agent series died with the cluster; a
            # re-init must not serve them merged into the new cluster's
            from .util import metrics as _metrics_mod

            _metrics_mod.reset_remote_metrics()
            if _metrics_server_from_init:
                # init() bound it, so init() owns its lifecycle — a
                # re-init with a different port must actually rebind
                _metrics_server_from_init = False
                _metrics_mod.stop_metrics_server()


def is_initialized() -> bool:
    return _runtime_mod.maybe_runtime() is not None


def remote(*args, **options):
    """Decorator turning a function into a RemoteFunction or a class into an
    ActorClass. Usable bare (@remote) or with options (@remote(num_cpus=2))."""
    if len(args) == 1 and not options and (callable(args[0]) or isinstance(args[0], type)):
        target = args[0]
        if isinstance(target, type):
            return ActorClass(target)
        return RemoteFunction(target)
    if args:
        raise TypeError("@remote takes keyword options only")

    def wrap(target):
        if isinstance(target, type):
            return ActorClass(target, options)
        return RemoteFunction(target, options)

    return wrap


def method(num_returns=None, concurrency_group: Optional[str] = None):
    """Per-method defaults on actor classes: num_returns (int or
    "streaming") and concurrency_group (ref: python/ray/actor.py method
    decorator; concurrency groups per
    transport/concurrency_group_manager.cc)."""

    def wrap(m):
        if num_returns is not None:
            m._rtpu_num_returns = num_returns
        if concurrency_group is not None:
            m._rtpu_concurrency_group = concurrency_group
        return m

    return wrap


def get(refs, timeout: Optional[float] = None):
    # future-like objects (e.g. serve.DeploymentResponse) resolve through
    # the __rtpu_result__ protocol
    if hasattr(refs, "__rtpu_result__"):
        return refs.__rtpu_result__(timeout)
    rt = _runtime_mod.get_runtime()
    if isinstance(refs, ObjectRef):
        return rt.get(refs, timeout)
    if isinstance(refs, (list, tuple)):
        if all(hasattr(r, "__rtpu_result__") for r in refs) and refs:
            deadline = None if timeout is None else _time.monotonic() + timeout
            return [r.__rtpu_result__(
                None if deadline is None
                else max(0.0, deadline - _time.monotonic()))
                for r in refs]
        if not all(isinstance(r, ObjectRef) for r in refs):
            raise TypeError("ray_tpu.get accepts an ObjectRef or a list of them")
        return rt.get(list(refs), timeout)
    raise TypeError(f"Cannot get {type(refs)}")


def put(value: Any) -> ObjectRef:
    rt = _runtime_mod.get_runtime()
    return rt.put(value)


def wait(refs: Sequence[ObjectRef], num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True):
    rt = _runtime_mod.get_runtime()
    return rt.wait(list(refs), num_returns=num_returns, timeout=timeout,
                   fetch_local=fetch_local)


def kill(actor: ActorHandle, no_restart: bool = True) -> None:
    rt = _runtime_mod.get_runtime()
    rt.kill_actor(actor._actor_id, no_restart=no_restart)


def cancel(ref: ObjectRef, force: bool = False, recursive: bool = True) -> None:
    rt = _runtime_mod.get_runtime()
    rt.cancel(ref, force=force)


def free(refs: Sequence[ObjectRef]) -> None:
    rt = _runtime_mod.get_runtime()
    rt.free(list(refs))


def nodes() -> List[dict]:
    rt = _runtime_mod.get_runtime()
    return [
        {"NodeID": n.node_id.hex(), "Alive": n.alive,
         "Resources": dict(n.total_resources.items()),
         "Labels": dict(n.labels)}
        for n in rt.gcs.nodes()
    ]


def cluster_resources() -> Dict[str, float]:
    return _runtime_mod.get_runtime().cluster_resources()


def available_resources() -> Dict[str, float]:
    return _runtime_mod.get_runtime().available_resources()


def get_runtime_context() -> RuntimeContext:
    return _runtime_mod.get_runtime().runtime_context()
