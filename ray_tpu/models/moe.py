"""Sparse Mixture-of-Experts transformer LM (Mixtral-style) — the `ep`
mesh axis made real.

TPU-first design: routing uses the static dispatch/combine einsum
formulation (Shazeer et al. 2017; GShard) — top-k gating builds dense
[T, E, C] dispatch and combine tensors so every step compiles to fixed
shapes and large MXU einsums; no data-dependent gathers, no dynamic
shapes (XLA cannot tile those). Expert weights carry the "expert"
logical axis, which AxisRules maps onto the mesh's `ep` dimension —
with experts sharded over ep, XLA inserts the all-to-alls over ICI
exactly where the einsums demand them (the scaling-book recipe).

Reference capability note: the reference's MoE support lives in user
code atop torch; this is new TPU-native work per SURVEY.md §5. Attention
reuses the flash kernel (ops/flash_attention.py).
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops import flash_attention, gelu, layernorm


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@dataclass(frozen=True)
class MoEConfig:
    vocab_size: int = 50257
    n_layer: int = 12
    n_head: int = 12
    d_model: int = 768
    d_ff: int = 3072
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_coeff: float = 0.01
    max_seq: int = 1024
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    use_flash: bool = True
    flash_block_q: int = 1024
    flash_block_k: int = 1024

    @property
    def padded_vocab(self) -> int:
        return _round_up(self.vocab_size, 128)

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_head

    @staticmethod
    def tiny(**kw) -> "MoEConfig":
        return MoEConfig(vocab_size=512, n_layer=2, n_head=4, d_model=64,
                         d_ff=128, num_experts=4, max_seq=128, **kw)

    @staticmethod
    def small(**kw) -> "MoEConfig":
        return MoEConfig(**kw)


class MoE:
    """init/apply pytree model in the house style (gpt.py/llama.py)."""

    def __init__(self, config: MoEConfig):
        self.config = config

    def init(self, rng: jax.Array) -> Dict[str, jax.Array]:
        c = self.config
        pd = c.param_dtype
        L, D, F, V = c.n_layer, c.d_model, c.d_ff, c.padded_vocab
        E = c.num_experts
        k = jax.random.split(rng, 12)
        std = 0.02
        res_std = std / math.sqrt(2 * L)
        return {
            "wte": jax.random.normal(k[0], (V, D), pd) * std,
            "wpe": jax.random.normal(k[1], (c.max_seq, D), pd) * std,
            "ln1_g": jnp.ones((L, D), pd), "ln1_b": jnp.zeros((L, D), pd),
            "w_qkv": jax.random.normal(k[2], (L, D, 3 * D), pd) * std,
            "b_qkv": jnp.zeros((L, 3 * D), pd),
            "w_proj": jax.random.normal(k[3], (L, D, D), pd) * res_std,
            "b_proj": jnp.zeros((L, D), pd),
            "ln2_g": jnp.ones((L, D), pd), "ln2_b": jnp.zeros((L, D), pd),
            # router + per-expert FFNs: the "expert" axis shards over ep
            "w_router": jax.random.normal(k[4], (L, D, E), pd) * std,
            "w_up": jax.random.normal(k[5], (L, E, D, F), pd) * std,
            "b_up": jnp.zeros((L, E, F), pd),
            "w_down": jax.random.normal(k[6], (L, E, F, D), pd) * res_std,
            "b_down": jnp.zeros((L, E, D), pd),
            "lnf_g": jnp.ones((D,), pd), "lnf_b": jnp.zeros((D,), pd),
        }

    @staticmethod
    def logical_axes() -> Dict[str, Tuple[Optional[str], ...]]:
        return {
            "wte": ("vocab", "embed"), "wpe": (None, "embed"),
            "ln1_g": (None, None), "ln1_b": (None, None),
            "w_qkv": (None, "embed", "heads"), "b_qkv": (None, "heads"),
            "w_proj": (None, "heads", "embed"), "b_proj": (None, None),
            "ln2_g": (None, None), "ln2_b": (None, None),
            "w_router": (None, "embed", None),
            "w_up": (None, "expert", "embed", "mlp"),
            "b_up": (None, "expert", "mlp"),
            "w_down": (None, "expert", "mlp", "embed"),
            "b_down": (None, "expert", "embed"),
            "lnf_g": (None,), "lnf_b": (None,),
        }

    def param_shardings(self, mesh, rules=None):
        from jax.sharding import NamedSharding

        from ..parallel.mesh import AxisRules

        rules = rules or AxisRules()
        return {n: NamedSharding(mesh, rules.mesh_axes(a))
                for n, a in self.logical_axes().items()}

    def num_params(self) -> int:
        return sum(int(v.size) for v in jax.eval_shape(
            self.init, jax.random.PRNGKey(0)).values())

    # -- MoE layer ---------------------------------------------------------

    def _moe_ffn(self, x: jax.Array, lp: Dict[str, jax.Array]
                 ) -> Tuple[jax.Array, jax.Array]:
        """x [B, S, D] -> (out [B, S, D], aux_loss scalar). Static-shape
        top-k dispatch: tokens over capacity are DROPPED (zero combine
        weight) and pass through the residual — standard GShard/Switch
        behavior that keeps shapes compile-time constant."""
        c = self.config
        B, S, D = x.shape
        T = B * S
        E, K = c.num_experts, c.top_k
        cap = max(1, int(c.capacity_factor * T * K / E))
        xt = x.reshape(T, D)
        logits = (xt @ lp["w_router"].astype(jnp.float32)
                  if lp["w_router"].dtype != jnp.float32
                  else xt.astype(jnp.float32) @ lp["w_router"])  # [T, E] f32
        probs = jax.nn.softmax(logits, axis=-1)
        # aux load-balancing loss (Switch Transformer eq. 4): mean prob x
        # mean assignment fraction per expert, scaled by E
        top_w, top_e = jax.lax.top_k(probs, K)           # [T, K]
        assign = jax.nn.one_hot(top_e, E, dtype=jnp.float32)  # [T, K, E]
        frac_tokens = assign.sum(axis=1).mean(axis=0)    # [E]
        frac_probs = probs.mean(axis=0)                  # [E]
        aux = c.aux_loss_coeff * E * jnp.sum(frac_tokens * frac_probs)
        # position of each (token, k) within its expert's capacity buffer
        pos = (jnp.cumsum(assign.reshape(T * K, E), axis=0)
               - assign.reshape(T * K, E)).reshape(T, K, E)
        pos = jnp.sum(pos * assign, axis=-1)             # [T, K]
        keep = (pos < cap) & (top_w > 0)
        top_w = jnp.where(keep, top_w, 0.0)
        # renormalize kept weights so each token's routes sum to 1
        denom = jnp.maximum(top_w.sum(axis=-1, keepdims=True), 1e-9)
        top_w = top_w / denom
        pos_oh = jax.nn.one_hot(jnp.where(keep, pos, cap), cap + 1,
                                dtype=jnp.float32)[..., :cap]  # [T, K, C]
        # combine [T, E, C] = sum_k weight_k * onehot(expert_k, pos_k)
        combine = jnp.einsum("tke,tkc,tk->tec", assign, pos_oh, top_w)
        dispatch = (combine > 0).astype(c.dtype)
        # expert compute: three big einsums, all static shapes
        ein = jnp.einsum("tec,td->ecd", dispatch, xt.astype(c.dtype))
        h = gelu(jnp.einsum("ecd,edf->ecf", ein, lp["w_up"].astype(c.dtype))
                 + lp["b_up"].astype(c.dtype)[:, None, :])
        eout = jnp.einsum("ecf,efd->ecd", h, lp["w_down"].astype(c.dtype)) \
            + lp["b_down"].astype(c.dtype)[:, None, :]
        out = jnp.einsum("tec,ecd->td", combine.astype(c.dtype), eout)
        return out.reshape(B, S, D), aux

    def _block(self, x: jax.Array, lp: Dict[str, jax.Array]
               ) -> Tuple[jax.Array, jax.Array]:
        c = self.config
        B, S, D = x.shape
        h = layernorm(x, lp["ln1_g"], lp["ln1_b"])
        qkv = (h @ lp["w_qkv"].astype(c.dtype)) + lp["b_qkv"].astype(c.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        H, hd = c.n_head, c.head_dim
        shp = lambda t: t.reshape(B, S, H, hd)  # noqa: E731
        if c.use_flash:
            attn = flash_attention(shp(q), shp(k), shp(v), causal=True,
                                   block_q=c.flash_block_q,
                                   block_k=c.flash_block_k)
        else:
            from ..ops import mha_reference

            attn = mha_reference(shp(q), shp(k), shp(v), causal=True)
        attn = attn.reshape(B, S, D)
        x = x + (attn @ lp["w_proj"].astype(c.dtype)) \
            + lp["b_proj"].astype(c.dtype)
        h = layernorm(x, lp["ln2_g"], lp["ln2_b"])
        ffn, aux = self._moe_ffn(h, lp)
        return x + ffn, aux

    def apply(self, params: Dict[str, jax.Array],
              tokens: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """tokens [B, S] -> (logits [B, S, V] f32, aux_loss scalar)."""
        c = self.config
        B, S = tokens.shape
        x = params["wte"].astype(c.dtype)[tokens] \
            + params["wpe"].astype(c.dtype)[jnp.arange(S)][None, :]
        aux_total = jnp.float32(0.0)
        layer_params = {n: v for n, v in params.items()
                        if n not in ("wte", "wpe", "lnf_g", "lnf_b")}
        for i in range(c.n_layer):
            lp = {n: v[i] for n, v in layer_params.items()}
            x, aux = self._block(x, lp)
            aux_total = aux_total + aux
        x = layernorm(x, params["lnf_g"], params["lnf_b"])
        logits = jnp.einsum("bsd,vd->bsv", x, params["wte"].astype(c.dtype),
                            preferred_element_type=jnp.float32)
        return logits, aux_total

    def loss(self, params: Dict[str, jax.Array], tokens: jax.Array,
             targets: jax.Array) -> jax.Array:
        from ..ops import cross_entropy_loss

        logits, aux = self.apply(params, tokens)
        return cross_entropy_loss(logits, targets) + aux
