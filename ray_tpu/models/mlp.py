"""Small MLP — RL policy/value nets and test fixtures."""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class MLPConfig:
    in_dim: int
    hidden: Tuple[int, ...] = (256, 256)
    out_dim: int = 1
    activation: str = "tanh"
    dtype: Any = jnp.float32


_ACTS = {"tanh": jnp.tanh, "relu": jax.nn.relu, "gelu": jax.nn.gelu,
         "silu": jax.nn.silu}


class MLP:
    def __init__(self, config: MLPConfig):
        self.config = config

    def init(self, rng: jax.Array) -> Dict[str, jax.Array]:
        c = self.config
        dims = (c.in_dim,) + tuple(c.hidden) + (c.out_dim,)
        params = {}
        keys = jax.random.split(rng, len(dims))
        for i, (a, b) in enumerate(zip(dims[:-1], dims[1:])):
            # orthogonal init — the PPO-stable choice
            w = jax.random.orthogonal(keys[i], max(a, b))[:a, :b]
            scale = 0.01 if i == len(dims) - 2 else (2.0 ** 0.5)
            params[f"w{i}"] = (w * scale).astype(c.dtype)
            params[f"b{i}"] = jnp.zeros((b,), c.dtype)
        return params

    def apply(self, params: Dict[str, jax.Array], x: jax.Array) -> jax.Array:
        c = self.config
        act = _ACTS[c.activation]
        n = len(c.hidden) + 1
        h = x.astype(c.dtype)
        for i in range(n):
            h = h @ params[f"w{i}"] + params[f"b{i}"]
            if i < n - 1:
                h = act(h)
        return h
