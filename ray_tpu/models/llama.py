"""Llama-family decoder (rmsnorm + rope + swiglu + GQA).

Backs the BASELINE.md "Llama-2-7B pjit-sharded Serve inference" config.
Same scan-over-stacked-layers + logical-axis design as gpt.py; adds
grouped-query attention (n_kv_head < n_head) and a KV-cache decode path
for the Serve layer.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops import (apply_rope, cross_entropy_loss, flash_attention,
                   mha_reference, rmsnorm, rope_cache)


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    n_layer: int = 32
    n_head: int = 32
    n_kv_head: int = 32
    d_model: int = 4096
    d_ff: int = 11008
    max_seq: int = 4096
    rope_base: float = 10000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    use_flash: bool = True

    @property
    def padded_vocab(self) -> int:
        return _round_up(self.vocab_size, 128)

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_head

    @staticmethod
    def tiny(**kw) -> "LlamaConfig":
        base = dict(vocab_size=512, n_layer=2, n_head=4, n_kv_head=2,
                    d_model=64, d_ff=128, max_seq=128)
        base.update(kw)            # callers may stretch max_seq etc.
        return LlamaConfig(**base)

    @staticmethod
    def llama2_7b(**kw) -> "LlamaConfig":
        return LlamaConfig(**kw)

    @staticmethod
    def llama2_13b(**kw) -> "LlamaConfig":
        return LlamaConfig(n_layer=40, n_head=40, n_kv_head=40, d_model=5120,
                           d_ff=13824, **kw)


class Llama:
    def __init__(self, config: LlamaConfig):
        self.config = config

    def init(self, rng: jax.Array) -> Dict[str, jax.Array]:
        c = self.config
        pd = c.param_dtype
        L, D, F, V = c.n_layer, c.d_model, c.d_ff, c.padded_vocab
        hd, H, KH = c.head_dim, c.n_head, c.n_kv_head
        k = jax.random.split(rng, 10)
        std = 0.02
        res_std = std / math.sqrt(2 * L)
        return {
            "wte": jax.random.normal(k[0], (V, D), pd) * std,
            "attn_norm": jnp.ones((L, D), pd),
            "w_q": jax.random.normal(k[1], (L, D, H * hd), pd) * std,
            "w_k": jax.random.normal(k[2], (L, D, KH * hd), pd) * std,
            "w_v": jax.random.normal(k[3], (L, D, KH * hd), pd) * std,
            "w_o": jax.random.normal(k[4], (L, H * hd, D), pd) * res_std,
            "mlp_norm": jnp.ones((L, D), pd),
            "w_gate": jax.random.normal(k[5], (L, D, F), pd) * std,
            "w_up": jax.random.normal(k[6], (L, D, F), pd) * std,
            "w_down": jax.random.normal(k[7], (L, F, D), pd) * res_std,
            "out_norm": jnp.ones((D,), pd),
            "lm_head": jax.random.normal(k[8], (V, D), pd) * std,
        }

    @staticmethod
    def logical_axes() -> Dict[str, Tuple[Optional[str], ...]]:
        return {
            "wte": ("vocab", "embed"),
            "attn_norm": (None, None),
            "w_q": (None, "embed", "heads"),
            "w_k": (None, "embed", "heads"),
            "w_v": (None, "embed", "heads"),
            "w_o": (None, "heads", "embed"),
            "mlp_norm": (None, None),
            "w_gate": (None, "embed", "mlp"),
            "w_up": (None, "embed", "mlp"),
            "w_down": (None, "mlp", "embed"),
            "out_norm": (None,),
            "lm_head": ("vocab", "embed"),
        }

    def param_shardings(self, mesh, rules=None):
        from jax.sharding import NamedSharding
        from ..parallel.mesh import AxisRules

        rules = rules or AxisRules()
        return {n: NamedSharding(mesh, rules.mesh_axes(a))
                for n, a in self.logical_axes().items()}

    def num_params(self) -> int:
        shapes = jax.eval_shape(self.init, jax.random.PRNGKey(0))
        return sum(int(math.prod(s.shape)) for s in jax.tree.leaves(shapes))

    def flops_per_token(self, seq: Optional[int] = None) -> int:
        """Forward+backward matmul FLOPs per token (6N rule + attention),
        the same accounting as GPT.flops_per_token so MFU numbers are
        comparable across models.

        The attention score/value matmuls run at FULL head count even
        under GQA (k/v broadcast to n_head before QK^T / PV), so the
        attention term uses n_head * head_dim, not the smaller KV
        projection width: 6 * L * S * (H * hd), already halved for
        causal masking."""
        c = self.config
        s = c.max_seq if seq is None else seq
        n = self.num_params()
        attn = 6 * c.n_layer * c.n_head * c.head_dim * s
        return 6 * n + attn

    def _block(self, x, lp, cos, sin, positions):
        c = self.config
        B, S, D = x.shape
        H, KH, hd = c.n_head, c.n_kv_head, c.head_dim
        h = rmsnorm(x, lp["attn_norm"], c.rms_eps)
        q = (h @ lp["w_q"].astype(c.dtype)).reshape(B, S, H, hd)
        k = (h @ lp["w_k"].astype(c.dtype)).reshape(B, S, KH, hd)
        v = (h @ lp["w_v"].astype(c.dtype)).reshape(B, S, KH, hd)
        q = apply_rope(q, cos, sin, positions)
        k = apply_rope(k, cos, sin, positions)
        if KH != H:  # GQA: broadcast kv heads to query heads
            rep = H // KH
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        if c.use_flash:
            attn = flash_attention(q, k, v, causal=True)
        else:
            attn = mha_reference(q, k, v, causal=True)
        x = x + attn.reshape(B, S, H * hd) @ lp["w_o"].astype(c.dtype)
        h = rmsnorm(x, lp["mlp_norm"], c.rms_eps)
        gate = jax.nn.silu(h @ lp["w_gate"].astype(c.dtype))
        up = h @ lp["w_up"].astype(c.dtype)
        x = x + (gate * up) @ lp["w_down"].astype(c.dtype)
        return x

    def apply(self, params, tokens, positions=None):
        c = self.config
        B, S = tokens.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))
        x = params["wte"].astype(c.dtype)[tokens]
        cos, sin = rope_cache(c.max_seq, c.head_dim, c.rope_base)
        lp_names = [n for n, a in self.logical_axes().items()
                    if a[0] is None and len(a) > 1 and n not in ("out_norm",)]
        layer_params = {n: params[n] for n in lp_names}

        def block_fn(x, lp):
            return self._block(x, lp, cos, sin, positions), None

        if c.remat:
            block_fn = jax.checkpoint(block_fn)
        x, _ = jax.lax.scan(block_fn, x, layer_params)
        x = rmsnorm(x, params["out_norm"], c.rms_eps)
        return jnp.einsum("bsd,vd->bsv", x.astype(jnp.float32),
                          params["lm_head"].astype(jnp.float32))

    def loss(self, params, tokens, targets):
        return cross_entropy_loss(self.apply(params, tokens), targets)

    # ---- paged-KV serving path (ray_tpu.serve.llm) ------------------------

    def init_paged_cache(self, num_blocks: int,
                         block_size: int) -> Dict[str, jax.Array]:
        """Block-pool KV cache: k/v [L, num_blocks, block_size, KH, hd]."""
        c = self.config
        shape = (c.n_layer, num_blocks, block_size, c.n_kv_head, c.head_dim)
        return {"k": jnp.zeros(shape, c.dtype),
                "v": jnp.zeros(shape, c.dtype)}

    _PAGED_LP = ("attn_norm", "w_q", "w_k", "w_v", "w_o", "mlp_norm",
                 "w_gate", "w_up", "w_down")

    def _paged_mlp(self, x, lp):
        c = self.config
        h = rmsnorm(x, lp["mlp_norm"], c.rms_eps)
        gate = jax.nn.silu(h @ lp["w_gate"].astype(c.dtype))
        up = h @ lp["w_up"].astype(c.dtype)
        return x + (gate * up) @ lp["w_down"].astype(c.dtype)

    def paged_prefill(self, params, cache, tokens, length, block_row):
        """Prompt pass at a static bucket shape (see GPT.paged_prefill —
        same contract: tokens [1, S], length scalar, block_row [M] ->
        (last-token logits [V], cache))."""
        from ..ops import paged_write_prefill

        c = self.config
        S = tokens.shape[1]
        H, KH, hd = c.n_head, c.n_kv_head, c.head_dim
        x = params["wte"].astype(c.dtype)[tokens]              # [1, S, D]
        cos, sin = rope_cache(c.max_seq, hd, c.rope_base)
        kc, vc = cache["k"], cache["v"]
        new_k, new_v = [], []
        for li in range(c.n_layer):
            lp = {n: params[n][li] for n in self._PAGED_LP}
            h = rmsnorm(x, lp["attn_norm"], c.rms_eps)
            q = (h @ lp["w_q"].astype(c.dtype)).reshape(1, S, H, hd)
            k = (h @ lp["w_k"].astype(c.dtype)).reshape(1, S, KH, hd)
            v = (h @ lp["w_v"].astype(c.dtype)).reshape(1, S, KH, hd)
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
            new_k.append(paged_write_prefill(kc[li], block_row, k[0], length))
            new_v.append(paged_write_prefill(vc[li], block_row, v[0], length))
            if KH != H:
                rep = H // KH
                k = jnp.repeat(k, rep, axis=2)
                v = jnp.repeat(v, rep, axis=2)
            attn = mha_reference(q, k, v, causal=True)
            x = x + attn.reshape(1, S, H * hd) @ lp["w_o"].astype(c.dtype)
            x = self._paged_mlp(x, lp)
        x = rmsnorm(x, params["out_norm"], c.rms_eps)
        last = jax.lax.dynamic_index_in_dim(
            x[0], jnp.maximum(length - 1, 0), axis=0, keepdims=False)
        logits = jnp.einsum("d,vd->v", last.astype(jnp.float32),
                            params["lm_head"].astype(jnp.float32))
        return logits, {"k": jnp.stack(new_k), "v": jnp.stack(new_v)}

    def paged_prefill_extend(self, params, cache, tokens, start, length,
                             block_row):
        """Suffix prefill over a cached prefix (see
        GPT.paged_prefill_extend — same contract: tokens [1, S] are the
        suffix only, RoPE'd at absolute positions start.., written into
        ``block_row`` at start.., attended over the full paged context
        incl. the reused [0, start) KV)."""
        from ..ops import paged_attention_prefill, paged_write_prefill

        c = self.config
        S = tokens.shape[1]
        H, KH, hd = c.n_head, c.n_kv_head, c.head_dim
        x = params["wte"].astype(c.dtype)[tokens]              # [1, S, D]
        cos, sin = rope_cache(c.max_seq, hd, c.rope_base)
        positions = (start + jnp.arange(S))[None]              # [1, S]
        kc, vc = cache["k"], cache["v"]
        new_k, new_v = [], []
        for li in range(c.n_layer):
            lp = {n: params[n][li] for n in self._PAGED_LP}
            h = rmsnorm(x, lp["attn_norm"], c.rms_eps)
            q = (h @ lp["w_q"].astype(c.dtype)).reshape(1, S, H, hd)
            k = (h @ lp["w_k"].astype(c.dtype)).reshape(1, S, KH, hd)
            v = (h @ lp["w_v"].astype(c.dtype)).reshape(1, S, KH, hd)
            q = apply_rope(q, cos, sin, positions)
            k = apply_rope(k, cos, sin, positions)
            kl = paged_write_prefill(kc[li], block_row, k[0], length,
                                     start)
            vl = paged_write_prefill(vc[li], block_row, v[0], length,
                                     start)
            new_k.append(kl)
            new_v.append(vl)
            attn = paged_attention_prefill(q[0], kl, vl, block_row,
                                           start, length)
            x = x + attn.reshape(1, S, H * hd) @ lp["w_o"].astype(c.dtype)
            x = self._paged_mlp(x, lp)
        x = rmsnorm(x, params["out_norm"], c.rms_eps)
        last = jax.lax.dynamic_index_in_dim(
            x[0], jnp.maximum(length - 1, 0), axis=0, keepdims=False)
        logits = jnp.einsum("d,vd->v", last.astype(jnp.float32),
                            params["lm_head"].astype(jnp.float32))
        return logits, {"k": jnp.stack(new_k), "v": jnp.stack(new_v)}

    def paged_decode_step(self, params, cache, tokens, positions,
                          block_rows, active):
        """One continuous-batching iteration at a fixed batch shape (see
        GPT.paged_decode_step — same contract)."""
        from ..ops import paged_attention_decode, paged_write_step

        c = self.config
        B = tokens.shape[0]
        H, KH, hd = c.n_head, c.n_kv_head, c.head_dim
        x = params["wte"].astype(c.dtype)[tokens]              # [B, D]
        cos, sin = rope_cache(c.max_seq, hd, c.rope_base)
        kc, vc = cache["k"], cache["v"]
        lengths = positions + 1
        new_k, new_v = [], []
        for li in range(c.n_layer):
            lp = {n: params[n][li] for n in self._PAGED_LP}
            h = rmsnorm(x, lp["attn_norm"], c.rms_eps)
            q = (h @ lp["w_q"].astype(c.dtype)).reshape(B, 1, H, hd)
            k = (h @ lp["w_k"].astype(c.dtype)).reshape(B, 1, KH, hd)
            v = (h @ lp["w_v"].astype(c.dtype)).reshape(B, 1, KH, hd)
            q = apply_rope(q, cos, sin, positions[:, None])
            k = apply_rope(k, cos, sin, positions[:, None])
            kl = paged_write_step(kc[li], block_rows, positions,
                                  k[:, 0], active)
            vl = paged_write_step(vc[li], block_rows, positions,
                                  v[:, 0], active)
            new_k.append(kl)
            new_v.append(vl)
            attn = paged_attention_decode(q[:, 0], kl, vl, block_rows,
                                          lengths)
            x = x + attn.reshape(B, H * hd) @ lp["w_o"].astype(c.dtype)
            x = self._paged_mlp(x, lp)
        x = rmsnorm(x, params["out_norm"], c.rms_eps)
        logits = jnp.einsum("bd,vd->bv", x.astype(jnp.float32),
                            params["lm_head"].astype(jnp.float32))
        return logits, {"k": jnp.stack(new_k), "v": jnp.stack(new_v)}

    # ---- decode path (Serve) ----------------------------------------------

    def init_cache(self, batch: int) -> Dict[str, jax.Array]:
        c = self.config
        shape = (c.n_layer, batch, c.max_seq, c.n_kv_head, c.head_dim)
        return {"k": jnp.zeros(shape, c.dtype), "v": jnp.zeros(shape, c.dtype),
                "pos": jnp.zeros((batch,), jnp.int32)}

    def decode_step(self, params, cache, tokens):
        """One autoregressive step. tokens [B, 1] -> (logits [B, V], cache).
        Dense-layer loop (no scan) so each layer can dynamic-update its
        cache slice; decode is bandwidth-bound anyway."""
        c = self.config
        B = tokens.shape[0]
        H, KH, hd = c.n_head, c.n_kv_head, c.head_dim
        pos = cache["pos"]                      # [B]
        x = params["wte"].astype(c.dtype)[tokens]  # [B, 1, D]
        cos, sin = rope_cache(c.max_seq, c.head_dim, c.rope_base)
        new_k, new_v = [], []
        for li in range(c.n_layer):
            lp = {n: params[n][li] for n in
                  ("attn_norm", "w_q", "w_k", "w_v", "w_o", "mlp_norm",
                   "w_gate", "w_up", "w_down")}
            h = rmsnorm(x, lp["attn_norm"], c.rms_eps)
            q = (h @ lp["w_q"].astype(c.dtype)).reshape(B, 1, H, hd)
            k = (h @ lp["w_k"].astype(c.dtype)).reshape(B, 1, KH, hd)
            v = (h @ lp["w_v"].astype(c.dtype)).reshape(B, 1, KH, hd)
            q = apply_rope(q, cos, sin, pos[:, None])
            k = apply_rope(k, cos, sin, pos[:, None])
            # per-batch positions differ: scatter via one_hot multiply
            onehot = jax.nn.one_hot(pos, c.max_seq, dtype=c.dtype)  # [B, S]
            ck = cache["k"][li] * (1 - onehot[:, :, None, None]) \
                + onehot[:, :, None, None] * k
            cv = cache["v"][li] * (1 - onehot[:, :, None, None]) \
                + onehot[:, :, None, None] * v
            new_k.append(ck)
            new_v.append(cv)
            kk, vv = ck, cv
            if KH != H:
                rep = H // KH
                kk = jnp.repeat(kk, rep, axis=2)
                vv = jnp.repeat(vv, rep, axis=2)
            # masked attention over the cache
            scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                                kk.astype(jnp.float32)) / math.sqrt(hd)
            mask = (jnp.arange(c.max_seq)[None, :] <= pos[:, None])
            scores = jnp.where(mask[:, None, None, :], scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1)
            attn = jnp.einsum("bhqk,bkhd->bqhd", probs,
                              vv.astype(jnp.float32)).astype(c.dtype)
            x = x + attn.reshape(B, 1, H * hd) @ lp["w_o"].astype(c.dtype)
            h = rmsnorm(x, lp["mlp_norm"], c.rms_eps)
            gate = jax.nn.silu(h @ lp["w_gate"].astype(c.dtype))
            up = h @ lp["w_up"].astype(c.dtype)
            x = x + (gate * up) @ lp["w_down"].astype(c.dtype)
        x = rmsnorm(x, params["out_norm"], c.rms_eps)
        logits = jnp.einsum("bsd,vd->bsv", x.astype(jnp.float32),
                            params["lm_head"].astype(jnp.float32))[:, 0]
        cache = {"k": jnp.stack(new_k), "v": jnp.stack(new_v), "pos": pos + 1}
        return logits, cache
