"""ResNet for CIFAR/ImageNet (BASELINE.md: ResNet-18/CIFAR-10 2-worker ref).

Convs map straight onto the MXU via lax.conv_general_dilated (XLA tiles
them like matmuls); batch-norm statistics in f32. Functional init/apply
with explicit batch-stat state (train step threads it through)."""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class ResNetConfig:
    num_classes: int = 10
    stage_sizes: Tuple[int, ...] = (2, 2, 2, 2)   # resnet-18
    width: int = 64
    small_inputs: bool = True   # CIFAR stem (3x3, no maxpool)
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @staticmethod
    def resnet18_cifar(**kw) -> "ResNetConfig":
        return ResNetConfig(**kw)

    @staticmethod
    def resnet50_imagenet(**kw) -> "ResNetConfig":
        return ResNetConfig(stage_sizes=(3, 4, 6, 3), small_inputs=False,
                            num_classes=1000, **kw)


def _conv_init(key, shape, dtype):
    fan_in = shape[0] * shape[1] * shape[2]
    return jax.random.normal(key, shape, dtype) * math.sqrt(2.0 / fan_in)


class ResNet:
    """Basic-block ResNet. Params/state: nested dicts keyed by layer path."""

    def __init__(self, config: ResNetConfig):
        self.config = config

    def init(self, rng: jax.Array) -> Tuple[Dict, Dict]:
        c = self.config
        pd = c.param_dtype
        params: Dict[str, Any] = {}
        state: Dict[str, Any] = {}
        keys = iter(jax.random.split(rng, 256))

        def bn(path, ch):
            params[path + "/g"] = jnp.ones((ch,), pd)
            params[path + "/b"] = jnp.zeros((ch,), pd)
            state[path + "/mean"] = jnp.zeros((ch,), jnp.float32)
            state[path + "/var"] = jnp.ones((ch,), jnp.float32)

        stem = 3 if c.small_inputs else 7
        params["stem/w"] = _conv_init(next(keys), (stem, stem, 3, c.width), pd)
        bn("stem/bn", c.width)
        ch_in = c.width
        for si, blocks in enumerate(c.stage_sizes):
            ch = c.width * (2 ** si)
            for bi in range(blocks):
                p = f"s{si}b{bi}"
                stride = 2 if (bi == 0 and si > 0) else 1
                params[p + "/c1"] = _conv_init(next(keys), (3, 3, ch_in, ch), pd)
                bn(p + "/bn1", ch)
                params[p + "/c2"] = _conv_init(next(keys), (3, 3, ch, ch), pd)
                bn(p + "/bn2", ch)
                if stride != 1 or ch_in != ch:
                    params[p + "/proj"] = _conv_init(next(keys), (1, 1, ch_in, ch), pd)
                    bn(p + "/bnp", ch)
                ch_in = ch
        params["head/w"] = jax.random.normal(
            next(keys), (ch_in, c.num_classes), pd) * 0.01
        params["head/b"] = jnp.zeros((c.num_classes,), pd)
        return params, state

    def _bn(self, x, params, state, path, train: bool, updates):
        g = params[path + "/g"].astype(jnp.float32)
        b = params[path + "/b"].astype(jnp.float32)
        xf = x.astype(jnp.float32)
        if train:
            mu = jnp.mean(xf, axis=(0, 1, 2))
            var = jnp.var(xf, axis=(0, 1, 2))
            m = 0.9
            updates[path + "/mean"] = m * state[path + "/mean"] + (1 - m) * mu
            updates[path + "/var"] = m * state[path + "/var"] + (1 - m) * var
        else:
            mu = state[path + "/mean"]
            var = state[path + "/var"]
        y = (xf - mu) * jax.lax.rsqrt(var + 1e-5) * g + b
        return y.astype(x.dtype)

    def _conv(self, x, w, stride=1):
        return jax.lax.conv_general_dilated(
            x, w.astype(x.dtype), (stride, stride), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    def apply(self, params: Dict, state: Dict, images: jax.Array,
              train: bool = False) -> Tuple[jax.Array, Dict]:
        """images [B, H, W, 3] -> (logits [B, classes], new_state)."""
        c = self.config
        x = images.astype(c.dtype)
        updates = dict(state)
        x = self._conv(x, params["stem/w"], 1 if c.small_inputs else 2)
        x = self._bn(x, params, state, "stem/bn", train, updates)
        x = jax.nn.relu(x)
        if not c.small_inputs:
            x = jax.lax.reduce_window(
                x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME")
        ch_in = c.width
        for si, blocks in enumerate(c.stage_sizes):
            ch = c.width * (2 ** si)
            for bi in range(blocks):
                p = f"s{si}b{bi}"
                stride = 2 if (bi == 0 and si > 0) else 1
                res = x
                y = self._conv(x, params[p + "/c1"], stride)
                y = jax.nn.relu(self._bn(y, params, state, p + "/bn1", train, updates))
                y = self._conv(y, params[p + "/c2"], 1)
                y = self._bn(y, params, state, p + "/bn2", train, updates)
                if p + "/proj" in params:
                    res = self._conv(res, params[p + "/proj"], stride)
                    res = self._bn(res, params, state, p + "/bnp", train, updates)
                x = jax.nn.relu(y + res)
                ch_in = ch
        x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
        logits = x @ params["head/w"].astype(jnp.float32) \
            + params["head/b"].astype(jnp.float32)
        return logits, updates

    def loss(self, params, state, images, labels, train: bool = True):
        logits, new_state = self.apply(params, state, images, train=train)
        onehot = jax.nn.one_hot(labels, self.config.num_classes)
        loss = -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), axis=-1))
        return loss, new_state
