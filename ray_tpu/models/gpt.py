"""GPT-2 family — the flagship model (BASELINE.md north star: >50% MFU).

TPU-first choices:
- layer params stacked on a leading axis and driven by lax.scan: one
  compiled transformer block regardless of depth (fast compile, XLA
  pipelines the scan).
- vocab padded to a multiple of 128 so the embedding/LM-head matmuls tile
  the MXU exactly.
- flash-attention Pallas kernel on the hot path; jax.checkpoint around the
  block for rematerialisation.
- every parameter carries a logical-axis tuple (see `logical_axes`) that
  AxisRules maps to the dp/fsdp/tp/sp mesh — pure data parallel, ZeRO-3
  style fsdp, megatron tp, and sequence parallel all fall out of the same
  annotations.
"""
from __future__ import annotations

import dataclasses
import functools
import math
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops import cross_entropy_loss, flash_attention, gelu, layernorm
from ..ops.ring_attention import ring_attention


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50257
    n_layer: int = 12
    n_head: int = 12
    d_model: int = 768
    d_ff: int = 3072
    max_seq: int = 1024
    dropout: float = 0.0          # inference/bench default; train sets >0
    dtype: Any = jnp.bfloat16     # activation/compute dtype
    param_dtype: Any = jnp.float32
    remat: bool = True
    use_flash: bool = True
    flash_block_q: int = 1024     # flash kernel tile sizes (clamped to seq)
    flash_block_k: int = 1024
    # scan_layers=True compiles one block body (fast compile, the right
    # default for deep models); False unrolls the layer loop — slower to
    # compile but removes the scan's per-layer residual-stacking
    # dynamic-update-slices, worth ~6% MFU on the training bench
    scan_layers: bool = True
    seq_axis: Optional[str] = None  # set to "sp" to use ring attention
    # hand-fused LN+matmul block entry / matmul+residual block exit
    # (ops/fused.py Pallas kernels). A/B'd against XLA's own fusion in
    # docs/PERF_NOTES.md round 5 — kept as a measured option, not the
    # default
    fused_entry_exit: bool = False

    @property
    def padded_vocab(self) -> int:
        return _round_up(self.vocab_size, 128)

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_head == 0
        return self.d_model // self.n_head

    # ---- presets ----------------------------------------------------------
    @staticmethod
    def tiny(**kw) -> "GPTConfig":
        base = dict(vocab_size=512, n_layer=2, n_head=2, d_model=64,
                    d_ff=256, max_seq=128)
        base.update(kw)            # callers may stretch max_seq etc.
        return GPTConfig(**base)

    @staticmethod
    def small(**kw) -> "GPTConfig":      # GPT-2 124M
        return GPTConfig(**kw)

    @staticmethod
    def medium(**kw) -> "GPTConfig":     # 350M
        return GPTConfig(n_layer=24, n_head=16, d_model=1024, d_ff=4096, **kw)

    @staticmethod
    def large(**kw) -> "GPTConfig":      # 774M
        return GPTConfig(n_layer=36, n_head=20, d_model=1280, d_ff=5120, **kw)

    @staticmethod
    def xl(**kw) -> "GPTConfig":         # 1.5B
        return GPTConfig(n_layer=48, n_head=25, d_model=1600, d_ff=6400, **kw)


class GPT:
    """init/apply pair. Params are a flat dict of stacked arrays."""

    def __init__(self, config: GPTConfig):
        self.config = config

    # ---- parameters --------------------------------------------------------

    def init(self, rng: jax.Array) -> Dict[str, jax.Array]:
        c = self.config
        pd = c.param_dtype
        L, D, F, V, S = c.n_layer, c.d_model, c.d_ff, c.padded_vocab, c.max_seq
        k = jax.random.split(rng, 8)
        std = 0.02
        # residual-path projections scaled per GPT-2 (1/sqrt(2L))
        res_std = std / math.sqrt(2 * L)
        return {
            "wte": jax.random.normal(k[0], (V, D), pd) * std,
            "wpe": jax.random.normal(k[1], (S, D), pd) * std,
            "ln1_g": jnp.ones((L, D), pd), "ln1_b": jnp.zeros((L, D), pd),
            "w_qkv": jax.random.normal(k[2], (L, D, 3 * D), pd) * std,
            "b_qkv": jnp.zeros((L, 3 * D), pd),
            "w_proj": jax.random.normal(k[3], (L, D, D), pd) * res_std,
            "b_proj": jnp.zeros((L, D), pd),
            "ln2_g": jnp.ones((L, D), pd), "ln2_b": jnp.zeros((L, D), pd),
            "w_fc": jax.random.normal(k[4], (L, D, F), pd) * std,
            "b_fc": jnp.zeros((L, F), pd),
            "w_out": jax.random.normal(k[5], (L, F, D), pd) * res_std,
            "b_out": jnp.zeros((L, D), pd),
            "lnf_g": jnp.ones((D,), pd), "lnf_b": jnp.zeros((D,), pd),
        }

    @staticmethod
    def logical_axes() -> Dict[str, Tuple[Optional[str], ...]]:
        """Per-param logical axes; leading layer-stack axis is unsharded
        (scan carries it). Mapped to mesh axes by AxisRules."""
        return {
            "wte": ("vocab", "embed"),
            "wpe": (None, "embed"),
            "ln1_g": (None, None), "ln1_b": (None, None),
            "w_qkv": (None, "embed", "heads"),
            "b_qkv": (None, "heads"),
            "w_proj": (None, "heads", "embed"),
            "b_proj": (None, "embed"),
            "ln2_g": (None, None), "ln2_b": (None, None),
            "w_fc": (None, "embed", "mlp"),
            "b_fc": (None, "mlp"),
            "w_out": (None, "mlp", "embed"),
            "b_out": (None, "embed"),
            "lnf_g": (None,), "lnf_b": (None,),
        }

    def param_shardings(self, mesh, rules=None):
        from ..parallel.mesh import AxisRules
        from jax.sharding import NamedSharding

        rules = rules or AxisRules()
        return {
            name: NamedSharding(mesh, rules.mesh_axes(axes))
            for name, axes in self.logical_axes().items()
        }

    def num_params(self) -> int:
        shapes = jax.eval_shape(self.init, jax.random.PRNGKey(0))
        return sum(int(math.prod(s.shape)) for s in jax.tree.leaves(shapes))

    def flops_per_token(self, seq: Optional[int] = None) -> int:
        """Forward+backward matmul FLOPs per token (6N rule + attention).

        Attention term: QK^T + PV are each 2·S·D MAC-FLOPs per token per
        layer forward (4·S·D), ×3 for fwd+bwd = 12·S·D, halved for causal
        masking → 6·L·S·D. This is the single source of truth; bench.py
        calls it rather than duplicating the formula."""
        c = self.config
        s = c.max_seq if seq is None else seq
        n = self.num_params()
        attn = 6 * c.n_layer * c.d_model * s
        return 6 * n + attn

    # ---- forward -----------------------------------------------------------

    def _dropout(self, x: jax.Array, key: jax.Array) -> jax.Array:
        rate = self.config.dropout
        keep = jax.random.bernoulli(key, 1.0 - rate, x.shape)
        return jnp.where(keep, x / jnp.asarray(1.0 - rate, x.dtype),
                         jnp.zeros_like(x))

    def _block(self, x: jax.Array, lp: Dict[str, jax.Array],
               rng: Optional[jax.Array]) -> jax.Array:
        c = self.config
        B, S, D = x.shape
        H, hd = c.n_head, c.head_dim
        # per-layer dropout key rides in the (stacked) layer params so one
        # scanned block body serves every layer
        key = lp.get("_dropout_key")
        drop = c.dropout > 0.0 and key is not None
        if drop:
            k_attn, k_mlp = jax.random.split(key)
        if c.fused_entry_exit:
            from ..ops.fused import ln_matmul

            qkv = ln_matmul(
                x.reshape(B * S, D), lp["ln1_g"], lp["ln1_b"],
                lp["w_qkv"].astype(c.dtype),
                lp["b_qkv"].astype(c.dtype)).reshape(B, S, 3 * D)
        else:
            h = layernorm(x, lp["ln1_g"], lp["ln1_b"])
            qkv = (h @ lp["w_qkv"].astype(c.dtype)) \
                + lp["b_qkv"].astype(c.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, S, H, hd)
        k = k.reshape(B, S, H, hd)
        v = v.reshape(B, S, H, hd)
        if c.seq_axis is not None:
            attn = ring_attention(q, k, v, axis_name=c.seq_axis, causal=True)
        elif c.use_flash:
            attn = flash_attention(q, k, v, causal=True,
                                   block_q=c.flash_block_q,
                                   block_k=c.flash_block_k)
        else:
            from ..ops import mha_reference

            attn = mha_reference(q, k, v, causal=True)
        attn = attn.reshape(B, S, D)
        if c.fused_entry_exit and not drop:
            from ..ops.fused import ln_matmul, matmul_residual

            x = matmul_residual(attn.reshape(B * S, D),
                                lp["w_proj"].astype(c.dtype),
                                lp["b_proj"].astype(c.dtype),
                                x.reshape(B * S, D)).reshape(B, S, D)
            h = ln_matmul(x.reshape(B * S, D), lp["ln2_g"], lp["ln2_b"],
                          lp["w_fc"].astype(c.dtype),
                          lp["b_fc"].astype(c.dtype))
            h = gelu(h)
            x = matmul_residual(h, lp["w_out"].astype(c.dtype),
                                lp["b_out"].astype(c.dtype),
                                x.reshape(B * S, D)).reshape(B, S, D)
            return x
        proj = (attn @ lp["w_proj"].astype(c.dtype)) + lp["b_proj"].astype(c.dtype)
        if drop:
            proj = self._dropout(proj, k_attn)
        x = x + proj
        h = layernorm(x, lp["ln2_g"], lp["ln2_b"])
        h = gelu((h @ lp["w_fc"].astype(c.dtype)) + lp["b_fc"].astype(c.dtype))
        out = (h @ lp["w_out"].astype(c.dtype)) + lp["b_out"].astype(c.dtype)
        if drop:
            out = self._dropout(out, k_mlp)
        x = x + out
        return x

    @staticmethod
    def _remat_policy():
        """Save matmul outputs + flash-attention kernel outputs, recompute
        only the cheap elementwise chain in the backward — full-block remat
        costs +1/3 step FLOPs, which this policy avoids while still
        bounding activation memory."""
        cp = jax.checkpoint_policies
        policy = getattr(cp, "dots_with_no_batch_dims_saveable", None)
        names = getattr(cp, "save_only_these_names", None)
        both = getattr(cp, "save_from_both_policies", None)
        if policy and names and both:
            # see flash_attention._flash_vjp_fwd: saving these means the
            # backward never re-runs the forward kernel
            policy = both(policy, names("flash_out", "flash_lse"))
        return policy

    def _embed(self, wte: jax.Array, wpe: jax.Array, tokens: jax.Array,
               positions: Optional[jax.Array] = None) -> jax.Array:
        """Token + position embedding — the single definition all paths
        (apply/loss, loss_pp, actor-pipeline stage 0) share."""
        c = self.config
        if positions is None:
            positions = jnp.arange(tokens.shape[1])[None, :]
        return wte.astype(c.dtype)[tokens] + wpe.astype(c.dtype)[positions]

    def _lm_head(self, head_w: jax.Array, x: jax.Array) -> jax.Array:
        """Tied LM head in bf16 on the MXU fast path, f32 accumulation —
        a f32xf32 matmul here runs at 1/4 MXU rate and doubles HBM
        traffic on the [B,S,V] logits. Single definition for all paths."""
        return jnp.einsum("bsd,vd->bsv", x,
                          head_w.astype(self.config.dtype),
                          preferred_element_type=jnp.float32)

    def apply(self, params: Dict[str, jax.Array], tokens: jax.Array,
              positions: Optional[jax.Array] = None,
              rng: Optional[jax.Array] = None) -> jax.Array:
        """tokens [B, S] int32 -> logits [B, S, padded_vocab] (f32)."""
        x = self._backbone(params, tokens, rng, positions=positions)
        return self._lm_head(params["wte"], x)

    def loss(self, params: Dict[str, jax.Array], tokens: jax.Array,
             targets: jax.Array, rng: Optional[jax.Array] = None) -> jax.Array:
        logits = self.apply(params, tokens, rng=rng)
        return cross_entropy_loss(logits, targets)

    def loss_chunked(self, params: Dict[str, jax.Array], tokens: jax.Array,
                     targets: jax.Array, rng: Optional[jax.Array] = None,
                     num_chunks: int = 8) -> jax.Array:
        """Cross-entropy without materializing the full [B,S,V] f32 logits:
        the LM head + logsumexp run per token-chunk under jax.checkpoint,
        so only per-chunk logits ever exist (fwd and bwd) — e.g. 3.3 GB of
        GPT-2-small logits at B=16,S=1024 become 8 × 412 MB transients.
        This is the bench configuration (bench.py): marginally faster than
        plain `loss` at B=32+ and the only option once vocab*batch*seq
        logits stop fitting HBM."""
        x = self._backbone(params, tokens, rng)         # [B,S,D] bf16
        return self._chunked_head_nll(params["wte"], x, targets, num_chunks)

    def _chunked_head_nll(self, wte: jax.Array, x: jax.Array,
                          targets: jax.Array, num_chunks: int) -> jax.Array:
        """Head + token-mean NLL per chunk under jax.checkpoint — shared by
        loss_chunked and loss_pp so the no-full-logits property holds on
        every path."""
        wte = wte.astype(self.config.dtype)
        T = targets.size
        xt = x.reshape(T, -1)
        tg = targets.reshape(T)
        assert T % num_chunks == 0
        xt = xt.reshape(num_chunks, T // num_chunks, -1)
        tg = tg.reshape(num_chunks, T // num_chunks)

        @functools.partial(jax.checkpoint,
                           policy=jax.checkpoint_policies.nothing_saveable)
        def chunk_nll(carry, xt_tg):
            xc, tc = xt_tg
            logits = jnp.einsum("td,vd->tv", xc, wte,
                                preferred_element_type=jnp.float32)
            lse = jax.scipy.special.logsumexp(logits, axis=-1)
            gold = jnp.take_along_axis(
                logits, tc[:, None], axis=-1)[:, 0]
            return carry + jnp.sum(lse - gold), None

        total, _ = jax.lax.scan(chunk_nll, jnp.float32(0.0), (xt, tg))
        return total / T

    def loss_pp(self, params: Dict[str, jax.Array], tokens: jax.Array,
                targets: jax.Array, mesh, num_microbatches: int = 0,
                pp_axis: str = "pp", rng: Optional[jax.Array] = None,
                num_chunks: int = 0) -> jax.Array:
        """Pipeline-parallel loss: the layer stack runs as a collective
        microbatch pipeline over the mesh's `pp` axis (see
        parallel/pipeline.py), embedding and LM head replicated across pp
        (their FLOPs are small next to the body; this is the standard
        praxis-style split). Differentiable — jax.grad through this gives
        the reverse pipeline automatically.

        The reference has no pipeline engine to cite; capability-new per
        SURVEY.md §5."""
        from ..parallel.pipeline import pipeline_spmd, stack_stages

        c = self.config
        P_ = mesh.shape[pp_axis]
        M = num_microbatches or max(P_, 2)
        B, S = tokens.shape
        if B % M:
            raise ValueError(f"batch {B} not divisible into {M} microbatches")
        x = self._embed(params["wte"], params["wpe"], tokens)
        D = x.shape[-1]
        layer_params = {k: v for k, v in params.items()
                        if k not in ("wte", "wpe", "lnf_g", "lnf_b")}
        if c.dropout > 0.0 and rng is not None:
            # same regularization as the non-pp path: embedding dropout +
            # per-layer residual-branch dropout keys stacked onto the
            # layer params (they stage-split with everything else)
            emb_key, layers_key = jax.random.split(rng)
            x = self._dropout(x, emb_key)
            layer_params["_dropout_key"] = jax.random.split(
                layers_key, c.n_layer)
        stages = stack_stages(layer_params, P_)
        x_mb = x.reshape(M, B // M, S, D)

        def stage_fn(lp, xs):
            def blk(h, lpp):
                return self._block(h, lpp, None), None
            body = jax.checkpoint(blk, policy=self._remat_policy()) \
                if c.remat else blk
            h, _ = jax.lax.scan(body, xs, lp)
            return h

        y_mb = pipeline_spmd(stage_fn, stages, x_mb, mesh, pp_axis=pp_axis)
        x = y_mb.reshape(B, S, D)
        x = layernorm(x, params["lnf_g"], params["lnf_b"])
        # chunked head: pipeline parallelism exists for the large-model
        # regime where full [B,S,V] f32 logits can't live in HBM.
        # M divides B, so it always divides B*S — a safe default chunking.
        return self._chunked_head_nll(params["wte"], x, targets,
                                      num_chunks or M)

    # ---- paged-KV serving path (ray_tpu.serve.llm) -------------------------

    def init_paged_cache(self, num_blocks: int,
                         block_size: int) -> Dict[str, jax.Array]:
        """Block-pool KV cache shared by every resident sequence:
        k/v [L, num_blocks, block_size, H, hd] (GPT has no GQA: KH=H)."""
        c = self.config
        shape = (c.n_layer, num_blocks, block_size, c.n_head, c.head_dim)
        return {"k": jnp.zeros(shape, c.dtype),
                "v": jnp.zeros(shape, c.dtype)}

    def _paged_layer_params(self, params: Dict[str, jax.Array], li: int):
        return {n: params[n][li] for n in
                ("ln1_g", "ln1_b", "w_qkv", "b_qkv", "w_proj", "b_proj",
                 "ln2_g", "ln2_b", "w_fc", "b_fc", "w_out", "b_out")}

    def _paged_mlp(self, x: jax.Array, lp: Dict[str, jax.Array]) -> jax.Array:
        c = self.config
        h = layernorm(x, lp["ln2_g"], lp["ln2_b"])
        h = gelu((h @ lp["w_fc"].astype(c.dtype)) + lp["b_fc"].astype(c.dtype))
        return x + (h @ lp["w_out"].astype(c.dtype)) \
            + lp["b_out"].astype(c.dtype)

    def paged_prefill(self, params: Dict[str, jax.Array],
                      cache: Dict[str, jax.Array], tokens: jax.Array,
                      length: jax.Array, block_row: jax.Array
                      ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        """Prompt pass at a static bucket shape. tokens [1, S] (padded to
        the bucket), length scalar int32 (true prompt length), block_row
        [M] — the sequence's block table. Writes the prompt's K/V into
        the paged cache and returns (last-real-token logits [V], cache).
        One XLA program per bucket size, not per request."""
        from ..ops import (mha_reference, paged_write_prefill)

        c = self.config
        S = tokens.shape[1]
        H, hd = c.n_head, c.head_dim
        x = self._embed(params["wte"], params["wpe"], tokens)   # [1, S, D]
        kc, vc = cache["k"], cache["v"]
        new_k, new_v = [], []
        for li in range(c.n_layer):
            lp = self._paged_layer_params(params, li)
            h = layernorm(x, lp["ln1_g"], lp["ln1_b"])
            qkv = (h @ lp["w_qkv"].astype(c.dtype)) \
                + lp["b_qkv"].astype(c.dtype)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(1, S, H, hd)
            k = k.reshape(1, S, H, hd)
            v = v.reshape(1, S, H, hd)
            attn = mha_reference(q, k, v, causal=True)
            new_k.append(paged_write_prefill(kc[li], block_row, k[0], length))
            new_v.append(paged_write_prefill(vc[li], block_row, v[0], length))
            x = x + attn.reshape(1, S, H * hd) @ lp["w_proj"].astype(c.dtype) \
                + lp["b_proj"].astype(c.dtype)
            x = self._paged_mlp(x, lp)
        x = layernorm(x, params["lnf_g"], params["lnf_b"])
        last = jax.lax.dynamic_index_in_dim(
            x[0], jnp.maximum(length - 1, 0), axis=0, keepdims=False)
        logits = jnp.einsum("d,vd->v", last.astype(jnp.float32),
                            params["wte"].astype(jnp.float32))
        return logits, {"k": jnp.stack(new_k), "v": jnp.stack(new_v)}

    def paged_prefill_extend(self, params: Dict[str, jax.Array],
                             cache: Dict[str, jax.Array],
                             tokens: jax.Array, start: jax.Array,
                             length: jax.Array, block_row: jax.Array
                             ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        """Suffix prefill over a cached prefix (prefix cache,
        docs/LLM_SERVE.md): positions [0, start) already sit in the
        blocks named by ``block_row`` (written by an earlier request
        that shared them); only the suffix ``tokens`` [1, S] (padded to
        the bucket, true length ``length``) is embedded, written at
        positions start.., and attended causally over the FULL paged
        context. Returns (last-real-token logits [V], cache) — exactly
        :meth:`paged_prefill` output, at suffix cost."""
        from ..ops import paged_attention_prefill, paged_write_prefill

        c = self.config
        S = tokens.shape[1]
        H, hd = c.n_head, c.head_dim
        positions = (start + jnp.arange(S))[None]               # [1, S]
        x = self._embed(params["wte"], params["wpe"], tokens, positions)
        kc, vc = cache["k"], cache["v"]
        new_k, new_v = [], []
        for li in range(c.n_layer):
            lp = self._paged_layer_params(params, li)
            h = layernorm(x, lp["ln1_g"], lp["ln1_b"])
            qkv = (h @ lp["w_qkv"].astype(c.dtype)) \
                + lp["b_qkv"].astype(c.dtype)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            kl = paged_write_prefill(kc[li], block_row,
                                     k.reshape(S, H, hd), length, start)
            vl = paged_write_prefill(vc[li], block_row,
                                     v.reshape(S, H, hd), length, start)
            new_k.append(kl)
            new_v.append(vl)
            attn = paged_attention_prefill(q.reshape(S, H, hd), kl, vl,
                                           block_row, start, length)
            x = x + attn.reshape(1, S, H * hd) @ lp["w_proj"].astype(c.dtype) \
                + lp["b_proj"].astype(c.dtype)
            x = self._paged_mlp(x, lp)
        x = layernorm(x, params["lnf_g"], params["lnf_b"])
        last = jax.lax.dynamic_index_in_dim(
            x[0], jnp.maximum(length - 1, 0), axis=0, keepdims=False)
        logits = jnp.einsum("d,vd->v", last.astype(jnp.float32),
                            params["wte"].astype(jnp.float32))
        return logits, {"k": jnp.stack(new_k), "v": jnp.stack(new_v)}

    def paged_decode_step(self, params: Dict[str, jax.Array],
                          cache: Dict[str, jax.Array], tokens: jax.Array,
                          positions: jax.Array, block_rows: jax.Array,
                          active: jax.Array
                          ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        """One continuous-batching iteration at a fixed batch shape.
        tokens/positions [B] (position = index the token is written at),
        block_rows [B, M], active [B] bool (padded slots write nothing).
        Returns (logits [B, V] f32, cache). Dense layer loop — each layer
        scatters its cache slice; decode is bandwidth-bound anyway."""
        from ..ops import paged_attention_decode, paged_write_step

        c = self.config
        B = tokens.shape[0]
        H, hd = c.n_head, c.head_dim
        x = self._embed(params["wte"], params["wpe"], tokens[:, None],
                        positions[:, None])[:, 0]              # [B, D]
        kc, vc = cache["k"], cache["v"]
        lengths = positions + 1           # attend over context incl. self
        new_k, new_v = [], []
        for li in range(c.n_layer):
            lp = self._paged_layer_params(params, li)
            h = layernorm(x, lp["ln1_g"], lp["ln1_b"])
            qkv = (h @ lp["w_qkv"].astype(c.dtype)) \
                + lp["b_qkv"].astype(c.dtype)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            kl = paged_write_step(kc[li], block_rows, positions,
                                  k.reshape(B, H, hd), active)
            vl = paged_write_step(vc[li], block_rows, positions,
                                  v.reshape(B, H, hd), active)
            new_k.append(kl)
            new_v.append(vl)
            attn = paged_attention_decode(q.reshape(B, H, hd), kl, vl,
                                          block_rows, lengths)
            x = x + attn.reshape(B, H * hd) @ lp["w_proj"].astype(c.dtype) \
                + lp["b_proj"].astype(c.dtype)
            x = self._paged_mlp(x, lp)
        x = layernorm(x, params["lnf_g"], params["lnf_b"])
        logits = jnp.einsum("bd,vd->bv", x.astype(jnp.float32),
                            params["wte"].astype(jnp.float32))
        return logits, {"k": jnp.stack(new_k), "v": jnp.stack(new_v)}

    # ---- pipeline-stage slicing (train/pipeline_cgraph.py) -----------------

    def pipeline_stages(self, params: Dict[str, jax.Array],
                        num_chunks: int):
        """Split this GPT into ``num_chunks`` pipeline chunks for the
        actor-hosted engines: chunk 0 carries the embedding, the last
        chunk the final LN + tied LM head + loss, layer blocks divide
        evenly. Returns ``(chunk_fns, chunk_params, tied)`` — with
        ``num_chunks = P * virtual_stages`` the same entry point feeds
        both the plain and the interleaved engine."""
        return gpt_pipeline_stages(self, params, num_chunks)

    def _backbone(self, params: Dict[str, jax.Array], tokens: jax.Array,
                  rng: Optional[jax.Array] = None,
                  positions: Optional[jax.Array] = None) -> jax.Array:
        """Transformer stack up to the final layernorm ([B,S,D], no head)."""
        c = self.config
        B, S = tokens.shape
        x = self._embed(params["wte"], params["wpe"], tokens, positions)
        layer_params = {k: v for k, v in params.items()
                        if k not in ("wte", "wpe", "lnf_g", "lnf_b")}
        if c.dropout > 0.0 and rng is not None:
            # GPT-2 drops embeddings + each residual-branch output; the
            # per-layer keys stack onto the layer params so the scanned
            # body stays a single compiled block
            emb_key, layers_key = jax.random.split(rng)
            x = self._dropout(x, emb_key)
            layer_params["_dropout_key"] = jax.random.split(
                layers_key, c.n_layer)
        rng = None  # keys travel inside layer_params from here

        if c.scan_layers:
            def block_fn(x, lp):
                return self._block(x, lp, rng), None

            if c.remat:
                block_fn = jax.checkpoint(block_fn,
                                          policy=self._remat_policy())
            x, _ = jax.lax.scan(block_fn, x, layer_params)
        else:
            blk = self._block
            if c.remat:
                blk = jax.checkpoint(blk, policy=self._remat_policy())
            for i in range(c.n_layer):
                lp = {k: v[i] for k, v in layer_params.items()}
                x = blk(x, lp, rng)
        return layernorm(x, params["lnf_g"], params["lnf_b"])


# ---------------------------------------------------------------------------
# pipeline-stage slicing — the model side of the actor-hosted pipeline
# engines (train/pipeline_engine.py dynamic, train/pipeline_cgraph.py
# compiled). Lives with the model because the split points (embedding /
# layer blocks / LN+head) are model knowledge, not engine knowledge.
# ---------------------------------------------------------------------------


def gpt_pipeline_stages(model: "GPT", params: Dict[str, jax.Array],
                        num_chunks: int):
    """Split a GPT into ``num_chunks`` pipeline chunks: chunk 0 carries
    the embedding, the last chunk carries the final LN + tied LM head +
    loss; layer blocks divide evenly. Returns
    ``(chunk_fns, chunk_params, tied)`` where chunk fns are
    ``fn(params, x) -> activation`` for every chunk but the last, which
    is ``fn(params, x, targets) -> scalar loss``; ``tied`` names the
    embedding/LM-head grad-exchange pair in GLOBAL chunk indices."""
    c = model.config
    L = c.n_layer
    if num_chunks < 2:
        raise ValueError("pipeline needs >= 2 chunks")
    if L % num_chunks:
        raise ValueError(
            f"{L} layers not divisible by {num_chunks} chunks")
    per = L // num_chunks
    layer_keys = [k for k in params
                  if k not in ("wte", "wpe", "lnf_g", "lnf_b")]

    def slice_layers(lo, hi):
        return {k: params[k][lo:hi] for k in layer_keys}

    chunk_params = []
    for i in range(num_chunks):
        sp = {"layers": slice_layers(i * per, (i + 1) * per)}
        if i == 0:
            sp["wte"] = params["wte"]
            sp["wpe"] = params["wpe"]
        if i == num_chunks - 1:
            sp["lnf_g"] = params["lnf_g"]
            sp["lnf_b"] = params["lnf_b"]
            if "wte" not in sp:
                sp["head"] = params["wte"]  # tied head needs its own copy
        chunk_params.append(sp)

    def run_layers(model, sp, x):
        def blk(h, lp):
            return model._block(h, lp, None), None
        h, _ = jax.lax.scan(blk, x, sp["layers"])
        return h

    def make_first(model):
        def fn(sp, tokens):
            x = model._embed(sp["wte"], sp["wpe"], tokens)
            return run_layers(model, sp, x)
        return fn

    def make_mid(model):
        def fn(sp, x):
            return run_layers(model, sp, x)
        return fn

    def make_last(model):
        def fn(sp, x, targets):
            from ..ops import cross_entropy_loss, layernorm

            h = run_layers(model, sp, x)
            h = layernorm(h, sp["lnf_g"], sp["lnf_b"])
            head = sp.get("head", sp.get("wte"))
            return cross_entropy_loss(model._lm_head(head, h), targets)
        return fn

    chunk_fns = [make_first(model)]
    for _ in range(num_chunks - 2):
        chunk_fns.append(make_mid(model))
    chunk_fns.append(make_last(model))
    # the tied embedding/head copies must exchange grads every step
    tied = [(0, "wte", num_chunks - 1, "head")]
    return chunk_fns, chunk_params, tied
