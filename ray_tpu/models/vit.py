"""ViT (BASELINE.md: ViT-B/16 PBT sweep config).

Patchify = one big reshaped matmul (MXU-friendly); encoder reuses the
scan-over-layers transformer pattern from gpt.py with bidirectional flash
attention."""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops import flash_attention, gelu, layernorm, mha_reference


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    n_layer: int = 12
    n_head: int = 12
    d_model: int = 768
    d_ff: int = 3072
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    use_flash: bool = True

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_head

    @staticmethod
    def tiny(**kw) -> "ViTConfig":
        return ViTConfig(image_size=32, patch_size=8, num_classes=10,
                         n_layer=2, n_head=2, d_model=64, d_ff=128, **kw)

    @staticmethod
    def b16(**kw) -> "ViTConfig":
        return ViTConfig(**kw)


class ViT:
    def __init__(self, config: ViTConfig):
        self.config = config

    def init(self, rng: jax.Array) -> Dict[str, jax.Array]:
        c = self.config
        pd = c.param_dtype
        L, D, F = c.n_layer, c.d_model, c.d_ff
        P = c.patch_size * c.patch_size * 3
        k = jax.random.split(rng, 8)
        std = 0.02
        return {
            "patch/w": jax.random.normal(k[0], (P, D), pd) * math.sqrt(1.0 / P),
            "patch/b": jnp.zeros((D,), pd),
            "cls": jnp.zeros((1, 1, D), pd),
            "pos": jax.random.normal(k[1], (1, c.num_patches + 1, D), pd) * std,
            "ln1_g": jnp.ones((L, D), pd), "ln1_b": jnp.zeros((L, D), pd),
            "w_qkv": jax.random.normal(k[2], (L, D, 3 * D), pd) * std,
            "b_qkv": jnp.zeros((L, 3 * D), pd),
            "w_proj": jax.random.normal(k[3], (L, D, D), pd) * std / math.sqrt(2 * L),
            "b_proj": jnp.zeros((L, D), pd),
            "ln2_g": jnp.ones((L, D), pd), "ln2_b": jnp.zeros((L, D), pd),
            "w_fc": jax.random.normal(k[4], (L, D, F), pd) * std,
            "b_fc": jnp.zeros((L, F), pd),
            "w_out": jax.random.normal(k[5], (L, F, D), pd) * std / math.sqrt(2 * L),
            "b_out": jnp.zeros((L, D), pd),
            "lnf_g": jnp.ones((D,), pd), "lnf_b": jnp.zeros((D,), pd),
            "head/w": jnp.zeros((D, c.num_classes), pd),
            "head/b": jnp.zeros((c.num_classes,), pd),
        }

    @staticmethod
    def logical_axes() -> Dict[str, Tuple[Optional[str], ...]]:
        return {
            "patch/w": (None, "embed"), "patch/b": ("embed",),
            "cls": (None, None, "embed"), "pos": (None, None, "embed"),
            "ln1_g": (None, None), "ln1_b": (None, None),
            "w_qkv": (None, "embed", "heads"), "b_qkv": (None, "heads"),
            "w_proj": (None, "heads", "embed"), "b_proj": (None, "embed"),
            "ln2_g": (None, None), "ln2_b": (None, None),
            "w_fc": (None, "embed", "mlp"), "b_fc": (None, "mlp"),
            "w_out": (None, "mlp", "embed"), "b_out": (None, "embed"),
            "lnf_g": (None,), "lnf_b": (None,),
            "head/w": ("embed", None), "head/b": (None,),
        }

    def param_shardings(self, mesh, rules=None):
        from jax.sharding import NamedSharding
        from ..parallel.mesh import AxisRules

        rules = rules or AxisRules()
        return {n: NamedSharding(mesh, rules.mesh_axes(a))
                for n, a in self.logical_axes().items()}

    def _patchify(self, images: jax.Array) -> jax.Array:
        c = self.config
        B, H, W, C = images.shape
        p = c.patch_size
        x = images.reshape(B, H // p, p, W // p, p, C)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(B, (H // p) * (W // p), p * p * C)
        return x

    def _block(self, x, lp):
        c = self.config
        B, S, D = x.shape
        H, hd = c.n_head, c.head_dim
        h = layernorm(x, lp["ln1_g"], lp["ln1_b"])
        qkv = (h @ lp["w_qkv"].astype(c.dtype)) + lp["b_qkv"].astype(c.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q, k, v = (t.reshape(B, S, H, hd) for t in (q, k, v))
        if c.use_flash and S % 8 == 0:
            attn = flash_attention(q, k, v, causal=False,
                                   block_q=min(128, S), block_k=min(128, S))
        else:
            attn = mha_reference(q, k, v, causal=False)
        x = x + (attn.reshape(B, S, D) @ lp["w_proj"].astype(c.dtype)) \
            + lp["b_proj"].astype(c.dtype)
        h = layernorm(x, lp["ln2_g"], lp["ln2_b"])
        h = gelu((h @ lp["w_fc"].astype(c.dtype)) + lp["b_fc"].astype(c.dtype))
        return x + (h @ lp["w_out"].astype(c.dtype)) + lp["b_out"].astype(c.dtype)

    def apply(self, params: Dict, images: jax.Array) -> jax.Array:
        c = self.config
        x = self._patchify(images.astype(c.dtype))
        x = x @ params["patch/w"].astype(c.dtype) + params["patch/b"].astype(c.dtype)
        B = x.shape[0]
        cls = jnp.broadcast_to(params["cls"].astype(c.dtype), (B, 1, c.d_model))
        x = jnp.concatenate([cls, x], axis=1) + params["pos"].astype(c.dtype)
        stacked = {n: params[n] for n, a in self.logical_axes().items()
                   if len(a) > 1 and a[0] is None and n in
                   ("ln1_g", "ln1_b", "w_qkv", "b_qkv", "w_proj", "b_proj",
                    "ln2_g", "ln2_b", "w_fc", "b_fc", "w_out", "b_out")}

        def block_fn(x, lp):
            return self._block(x, lp), None

        if c.remat:
            block_fn = jax.checkpoint(block_fn)
        x, _ = jax.lax.scan(block_fn, x, stacked)
        x = layernorm(x, params["lnf_g"], params["lnf_b"])
        cls_tok = x[:, 0].astype(jnp.float32)
        return cls_tok @ params["head/w"].astype(jnp.float32) \
            + params["head/b"].astype(jnp.float32)

    def loss(self, params, images, labels):
        logits = self.apply(params, images)
        onehot = jax.nn.one_hot(labels, self.config.num_classes)
        return -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), axis=-1))
