"""ray_tpu.models — model families used by the Train/Serve/RLlib layers
and the benchmark configs (BASELINE.md north stars: GPT-2, ResNet-18/CIFAR,
ViT-B/16, Llama-2-7B, PPO nets).

Design: plain-pytree functional models (init/apply pairs), parameters
stacked over layers and iterated with lax.scan (one compiled block instead
of L unrolled ones), logical-axis annotations consumed by
ray_tpu.parallel.mesh.AxisRules for dp/fsdp/tp/sp sharding, bf16 compute
with f32 master dtypes chosen per-config.
"""
from .gpt import GPT, GPTConfig
from .llama import Llama, LlamaConfig
from .resnet import ResNet, ResNetConfig
from .vit import ViT, ViTConfig
from .mlp import MLP, MLPConfig
from .moe import MoE, MoEConfig

__all__ = [
    "GPT", "GPTConfig", "Llama", "LlamaConfig", "ResNet", "ResNetConfig",
    "ViT", "ViTConfig", "MLP", "MLPConfig", "MoE", "MoEConfig",
]
