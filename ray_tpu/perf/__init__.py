"""Performance-introspection plane (ISSUE 17).

Three coupled pieces, built so the next perf arc (overlap-scheduled
collectives, chunked prefill, kernel speed) has something to aim at and
something to prove with:

- :mod:`ray_tpu.perf.recorder` — the flight recorder: an always-on,
  bounded, lock-light per-process ring of structured runtime events
  (cgraph op begin/end, channel send/recv seq, engine admissions and
  preemptions, dispatch decisions). Overhead is one attribute test when
  disabled and a deque append + dict build when enabled; the measured
  bar lives in bench rows (``profiler_overhead_pct``) and is asserted
  CPU-count-aware in tests.
- :mod:`ray_tpu.perf.report` — :class:`StepReport`: the structured
  result of ``CompiledPipelineEngine.profile()`` /
  ``LLMEngine.profile()``, with per-stage exec/bubble/recv/sync
  breakdowns, MFU, chrome-trace export, and microbatch tuning hints.
- :mod:`ray_tpu.perf.postmortem` — merged driver+worker bundle dumps
  triggered by every abort path, rendered by ``ray_tpu postmortem``.
- :mod:`ray_tpu.perf.snapshot` — the one head RPC feeding
  ``ray_tpu top``.

docs/OBSERVABILITY.md "Profiling & post-mortem" is the schema
reference.
"""
from .recorder import (FlightRecorder, get_recorder, record,  # noqa: F401
                       recorder_enabled, set_enabled)
from .report import (StepReport, analytic_bubble_frac,  # noqa: F401
                     compute_mfu)
from .postmortem import (dump_bundle, last_bundle_path,  # noqa: F401
                         load_bundle, render_bundle)

__all__ = [
    "FlightRecorder", "get_recorder", "record", "recorder_enabled",
    "set_enabled", "StepReport", "analytic_bubble_frac", "compute_mfu",
    "dump_bundle", "last_bundle_path", "load_bundle", "render_bundle",
]
