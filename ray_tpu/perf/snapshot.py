"""One head snapshot RPC feeding ``ray_tpu top``.

``head_snapshot(runtime)`` flattens the head's merged metric registry
(local + every worker/agent-shipped delta) into a wire-safe dict: node
rows, scalar series (counters + gauges, tag-qualified), and histogram
summaries. The CLI polls it and computes rates client-side by diffing
counter values between refreshes — the head does no rate bookkeeping.
Served to unregistered channels as the ``perf_snapshot`` agent-handler
method, beside ``list_nodes``/``logs_query``.
"""
from __future__ import annotations

import time
from typing import Dict

from ..util import metrics as _metrics

__all__ = ["head_snapshot"]


def _fmt_tags(tags: Dict[str, str]) -> str:
    items = sorted((k, v) for k, v in tags.items() if v)
    return ",".join(f"{k}={v}" for k, v in items)


def head_snapshot(runtime) -> dict:
    """Everything ``ray_tpu top`` renders, in one reply."""
    nodes = []
    try:
        for n in runtime.gcs.nodes():
            nodes.append({"node_id": n.node_id.hex(), "alive": n.alive,
                          "resources": dict(n.total_resources)})
    except Exception:
        pass
    scalars: Dict[str, Dict[str, float]] = {}
    hists: Dict[str, dict] = {}
    for fam in _metrics._collect_families():
        if not fam.name.startswith("ray_tpu_"):
            continue
        if fam.kind == "histogram":
            continue  # summarized below with percentiles
        series = scalars.setdefault(fam.name, {})
        for suffix, tags, value, _ex in fam.samples:
            if suffix:
                continue
            key = _fmt_tags(tags)
            # multiple worker-shipped series can share a tag set after
            # node/worker qualifiers are dropped: sum counters, keep the
            # freshest gauge write
            if fam.kind == "counter":
                series[key] = series.get(key, 0.0) + value
            else:
                series[key] = value
    for name, summ in _metrics.latency_summary().items():
        if not name.startswith("ray_tpu_"):
            continue
        hists[name] = {k: summ.get(k) for k in
                       ("count", "mean", "p50", "p95", "p99")}
    traces = None
    try:
        ts = runtime.gcs.traces
        st = ts.stats()
        if st.get("total_traces", 0):  # tracing actually on: show it
            traces = dict(st)
            traces["slowest_active"] = ts.slowest_active()
    except Exception:
        pass
    return {"time": time.time(), "nodes": nodes, "scalars": scalars,
            "histograms": hists, "traces": traces}
