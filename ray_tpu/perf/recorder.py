"""Flight recorder: an always-on, bounded, lock-light per-process ring
of structured runtime events.

Design constraints (ISSUE 17 tentpole b):

- **Always on, bounded.** The ring is a ``collections.deque(maxlen=N)``
  — append is O(1), thread-safe under the GIL, and the oldest event is
  dropped implicitly on overflow. Capacity defaults to
  ``RAY_TPU_FLIGHTREC_CAP`` (4096 events); at ~120 bytes/event the
  steady-state footprint is sub-megabyte per process.
- **Lock-light.** ``record()`` takes no lock: one enabled-flag test, a
  tuple build, a deque append, and a non-atomic length check for the
  drop counter. The drop count is reconciled exactly in ``snapshot()``
  (appended minus retained), so the occasional racy fast-path
  undercount never survives a drain; the reconciled total feeds
  ``ray_tpu_flightrec_dropped_total``.
- **Structured.** Events are ``(ts, kind, label, data)`` tuples —
  ``ts`` is ``time.time()`` (wall clock, so driver+worker rings merge
  on one axis), ``kind`` is a short dotted string from the table in
  docs/OBSERVABILITY.md (``cgraph.op.begin``, ``chan.send``,
  ``llm.admit``, ...), ``label`` identifies the instance (op key,
  channel id, request id) and ``data`` is a small dict or None.

Host modules (cgraph executor, channels, engines) hold a module-level
``_FLREC`` pointing at the process singleton — the chaos-layer hook
pattern — and guard every record with ``if _FLREC.enabled`` so the
disabled A/B leg pays one attribute load.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ..util import metrics as _metrics

__all__ = ["FlightRecorder", "get_recorder", "record",
           "recorder_enabled", "set_enabled", "DEFAULT_CAPACITY"]

DEFAULT_CAPACITY = int(os.environ.get("RAY_TPU_FLIGHTREC_CAP", "4096"))

_C_DROPPED = _metrics.Counter(
    "ray_tpu_flightrec_dropped_total",
    "flight-recorder ring events dropped (oldest-first) on overflow")


class FlightRecorder:
    """One process's event ring. ``record()`` is the hot path; all
    bookkeeping that needs exactness happens in ``snapshot()``."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 enabled: Optional[bool] = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self._appended = 0          # racy-fast increments; see snapshot()
        self._dropped_flushed = 0   # drops already shipped to the metric
        self._snap_lock = threading.Lock()
        if enabled is None:
            enabled = os.environ.get("RAY_TPU_FLIGHTREC", "1") != "0"
        self.enabled = bool(enabled)

    # -- hot path ----------------------------------------------------------

    def record(self, kind: str, label: str = "",
               data: Optional[Dict[str, Any]] = None) -> None:
        """Append one event. No lock: deque.append is GIL-atomic, and the
        ``_appended`` increment may rarely lose a tick under contention —
        acceptable, because ``snapshot()`` recomputes the drop total from
        retained length and never reports fewer drops than really
        happened after a drain."""
        if not self.enabled:
            return
        self._ring.append((time.time(), kind, label, data))
        self._appended += 1

    # -- drain / accounting ------------------------------------------------

    @property
    def dropped(self) -> int:
        """Events lost to overflow so far (monotone, reconciled)."""
        return max(0, self._appended - len(self._ring))

    def snapshot(self, clear: bool = False) -> List[dict]:
        """Drain the ring into a list of wire-safe dicts (oldest first)
        and flush the drop delta into
        ``ray_tpu_flightrec_dropped_total``."""
        with self._snap_lock:
            events = list(self._ring)
            dropped = self.dropped  # BEFORE clear: drained events are
            if clear:               # delivered, not dropped
                self._ring.clear()
                # keep the drop ledger: with the ring empty, appended
                # minus retained must still equal the historic total
                self._appended = dropped
            delta = dropped - self._dropped_flushed
            if delta > 0:
                _C_DROPPED.inc(delta)
                self._dropped_flushed += delta
        return [{"ts": ts, "kind": kind, "label": label,
                 "data": data} for ts, kind, label, data in events]

    def stats(self) -> dict:
        return {"capacity": self.capacity, "size": len(self._ring),
                "appended": self._appended, "dropped": self.dropped,
                "enabled": self.enabled}


# ---------------------------------------------------------------------------
# process singleton
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_GLOBAL: Optional[FlightRecorder] = None


def get_recorder() -> FlightRecorder:
    global _GLOBAL
    rec = _GLOBAL
    if rec is None:
        with _LOCK:
            rec = _GLOBAL
            if rec is None:
                rec = _GLOBAL = FlightRecorder()
    return rec


def record(kind: str, label: str = "",
           data: Optional[Dict[str, Any]] = None) -> None:
    """Module-level convenience for cold paths (admissions, placements,
    aborts). Hot loops should cache ``get_recorder()`` in a module
    global instead."""
    get_recorder().record(kind, label, data)


def recorder_enabled() -> bool:
    return get_recorder().enabled


def set_enabled(on: bool) -> None:
    """Flip the process recorder (the bench A/B switch). Events already
    in the ring stay; only future records are gated."""
    get_recorder().enabled = bool(on)
