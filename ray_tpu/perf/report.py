"""StepReport: the structured result of a profiling run.

One class serves both hot paths — ``kind="pipeline"`` reports carry a
per-stage exec/bubble/recv/sync breakdown plus per-op spans;
``kind="llm"`` reports carry the per-step admit/prefill/decode/retire
phase split, batch-occupancy and KV-pressure series. Both carry
throughput (tokens/s), MFU when a flops estimate is available, a
chrome-trace export (perfetto-loadable, same event shapes as
``state.timeline()``) and ``suggest()`` tuning hints.

Analytic anchors (validated in tests/test_perf.py against synthetic
schedules):

- 1F1B bubble fraction: with P stages and M microbatches of equal cost,
  ``bubble_frac == (P - 1) / (M + P - 1)``.
- MFU: ``tokens_per_s * flops_per_token / peak_flops``.
"""
from __future__ import annotations

import json
import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

__all__ = ["StepReport", "compute_mfu", "analytic_bubble_frac"]


def compute_mfu(tokens_per_s: float, flops_per_token: float,
                peak_flops: float) -> Optional[float]:
    """Model-flops utilization in [0, 1]; None when any input is
    missing/non-positive."""
    if not tokens_per_s or not flops_per_token or not peak_flops:
        return None
    if tokens_per_s <= 0 or flops_per_token <= 0 or peak_flops <= 0:
        return None
    return tokens_per_s * flops_per_token / peak_flops


def analytic_bubble_frac(num_stages: int, num_microbatches: int) -> float:
    """Ideal 1F1B pipeline bubble fraction: (P-1)/(M+P-1)."""
    p, m = int(num_stages), int(num_microbatches)
    if p < 1 or m < 1:
        raise ValueError(f"need P >= 1 and M >= 1, got P={p} M={m}")
    return (p - 1) / (m + p - 1)


@dataclass
class StepReport:
    """Everything ``profile(steps=N)`` measured, in one picklable bag.

    Times are milliseconds unless the field name says otherwise. Stage
    dicts: ``{"stage", "exec_ms", "bubble_ms", "recv_ms", "sync_ms",
    "update_ms", "ops": [{"key", "method", "t0", "t1"}, ...]}``.
    ``phases`` maps phase name -> total ms across the profiled steps
    (llm: admit/prefill/decode/retire; pipeline: compute/bubble/update).
    """

    kind: str = "pipeline"            # "pipeline" | "llm"
    engine: str = ""                  # gtag / engine id
    steps: int = 0
    wall_s: float = 0.0               # profiled window wall time
    step_ms: List[float] = field(default_factory=list)
    stages: List[dict] = field(default_factory=list)
    phases: Dict[str, float] = field(default_factory=dict)
    tokens: float = 0.0
    tokens_per_s: float = 0.0
    flops_per_token: float = 0.0
    peak_flops: float = 0.0
    num_stages: int = 0               # P
    num_microbatches: int = 0         # M
    occupancy: List[float] = field(default_factory=list)   # llm, per step
    kv_pressure: List[float] = field(default_factory=list)  # llm, per step
    events: List[dict] = field(default_factory=list)  # recorder drain
    extra: Dict[str, Any] = field(default_factory=dict)

    # -- derived -----------------------------------------------------------

    @property
    def mean_step_ms(self) -> float:
        return sum(self.step_ms) / len(self.step_ms) if self.step_ms \
            else 0.0

    @property
    def mfu(self) -> Optional[float]:
        return compute_mfu(self.tokens_per_s, self.flops_per_token,
                           self.peak_flops)

    @property
    def bubble_frac(self) -> Optional[float]:
        """Measured bubble fraction: summed recv-blocked time over
        summed busy+blocked time across stages. On the ideal 1F1B
        schedule this equals (P-1)/(M+P-1)."""
        ex = sum(s.get("exec_ms", 0.0) for s in self.stages)
        bub = sum(s.get("bubble_ms", 0.0) for s in self.stages)
        if ex + bub <= 0:
            return None
        return bub / (ex + bub)

    def phase_total_ms(self) -> float:
        return sum(self.phases.values())

    def phase_wall_ratio(self) -> Optional[float]:
        """phase-sum over measured step wall — the live-smoke acceptance
        gate asserts this lands within 10% of 1.0."""
        wall = sum(self.step_ms)
        if wall <= 0:
            return None
        return self.phase_total_ms() / wall

    # -- serialization -----------------------------------------------------

    def to_dict(self) -> dict:
        return {
            "kind": self.kind, "engine": self.engine, "steps": self.steps,
            "wall_s": self.wall_s, "step_ms": list(self.step_ms),
            "stages": self.stages, "phases": dict(self.phases),
            "tokens": self.tokens, "tokens_per_s": self.tokens_per_s,
            "flops_per_token": self.flops_per_token,
            "peak_flops": self.peak_flops, "mfu": self.mfu,
            "num_stages": self.num_stages,
            "num_microbatches": self.num_microbatches,
            "bubble_frac": self.bubble_frac,
            "mean_step_ms": self.mean_step_ms,
            "occupancy": list(self.occupancy),
            "kv_pressure": list(self.kv_pressure),
            "events": self.events, "extra": self.extra,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "StepReport":
        keep = {k: d[k] for k in (
            "kind", "engine", "steps", "wall_s", "step_ms", "stages",
            "phases", "tokens", "tokens_per_s", "flops_per_token",
            "peak_flops", "num_stages", "num_microbatches", "occupancy",
            "kv_pressure", "events", "extra") if k in d}
        return cls(**keep)

    def save(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=1, sort_keys=True)
        return path

    # -- chrome trace ------------------------------------------------------

    def to_chrome_trace(self) -> dict:
        """Perfetto/chrome://tracing-loadable timeline: the same
        complete-slice shape ``state.timeline()`` emits (``ph:"X"``,
        ``ts``/``dur`` in microseconds), one pid per stage/engine, one
        tid lane per event source."""
        out: List[dict] = []
        t0 = math.inf
        for st in self.stages:
            for op in st.get("ops", ()):
                t0 = min(t0, op.get("t0", math.inf))
        for ev in self.events:
            t0 = min(t0, ev.get("ts", math.inf))
        if not math.isfinite(t0):
            t0 = 0.0

        def us(t: float) -> float:
            return round((t - t0) * 1e6, 1)

        pid = self.engine or self.kind
        for st in self.stages:
            tid = f"stage {st.get('stage', '?')}"
            out.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": tid}})
            for op in st.get("ops", ()):
                if "t0" not in op or "t1" not in op:
                    continue
                out.append({
                    "name": op.get("key") or op.get("method", "op"),
                    "cat": "cgraph", "ph": "X", "ts": us(op["t0"]),
                    "dur": max(0.1, round((op["t1"] - op["t0"]) * 1e6, 1)),
                    "pid": pid, "tid": tid,
                    "args": {"method": op.get("method", "")}})
        for ev in self.events:
            # recorder begin/end pairs were already folded into ops by
            # the profiler; whatever remains renders as instants
            out.append({
                "name": f"{ev.get('kind', 'event')} {ev.get('label', '')}"
                        .strip(),
                "cat": "flightrec", "ph": "i", "s": "p",
                "ts": us(ev.get("ts", t0)), "pid": pid,
                "tid": "events", "args": ev.get("data") or {}})
        # per-step phase lanes (llm) / aggregate lanes (pipeline)
        cursor = 0.0
        for name, ms in sorted(self.phases.items()):
            out.append({
                "name": name, "cat": "phase", "ph": "X", "ts": cursor,
                "dur": max(0.1, round(ms * 1e3, 1)), "pid": pid,
                "tid": "phases (total ms)", "args": {"total_ms": ms}})
            cursor += max(0.1, ms * 1e3)
        return {"traceEvents": out, "displayTimeUnit": "ms",
                "otherData": {"engine": self.engine, "kind": self.kind,
                              "steps": self.steps}}

    # -- tuning hints ------------------------------------------------------

    def suggest(self) -> List[str]:
        """Microbatch/interleave tuning hints — the profile-driven
        tuning prerequisite for the overlap-scheduling arc."""
        hints: List[str] = []
        b = self.bubble_frac
        p, m = self.num_stages, self.num_microbatches
        if self.kind == "pipeline":
            if b is not None and p > 1 and m >= 1:
                ideal = analytic_bubble_frac(p, m)
                if b > 0.20:
                    target = 0.10
                    m_new = max(m + 1,
                                math.ceil((p - 1) * (1 - target) / target))
                    hints.append(
                        f"bubble fraction {b:.2f} (ideal {ideal:.2f} at "
                        f"P={p}, M={m}): raise microbatches to M={m_new} "
                        f"to push the 1F1B bubble under {target:.0%}")
                elif b < 0.05 and m > 2 * p:
                    hints.append(
                        f"bubble fraction {b:.2f} is already small at "
                        f"M={m}: reduce M toward {2 * p} to cut "
                        f"per-step latency and activation memory")
                if b > 1.5 * ideal + 0.05:
                    hints.append(
                        f"measured bubble {b:.2f} exceeds the analytic "
                        f"1F1B floor {ideal:.2f}: stages are imbalanced "
                        f"or recv-starved — rebalance layers_per_stage "
                        f"or interleave")
            sync = sum(s.get("sync_ms", 0.0) for s in self.stages)
            ex = sum(s.get("exec_ms", 0.0) for s in self.stages)
            if ex > 0 and sync > 0.15 * ex:
                hints.append(
                    f"collective sync-exposed time is "
                    f"{sync / ex:.0%} of compute: overlap the ZeRO "
                    f"reduce-scatter/all-gather legs with backward")
        else:
            occ = (sum(self.occupancy) / len(self.occupancy)
                   if self.occupancy else None)
            cap = float(self.extra.get("max_batch") or 0)
            if occ is not None and cap and occ < 0.5 * cap:
                hints.append(
                    f"mean batch occupancy {occ:.1f} of {cap:.0f}: the "
                    f"engine is admission-starved — raise arrival "
                    f"concurrency or shrink max_batch")
            if self.kv_pressure and max(self.kv_pressure) > 0.9:
                hints.append(
                    f"KV pressure peaked at "
                    f"{max(self.kv_pressure):.0%}: provision more KV "
                    f"blocks or expect preemptions")
            pre = self.phases.get("prefill", 0.0)
            tot = self.phase_total_ms()
            if tot > 0 and pre > 0.5 * tot:
                hints.append(
                    f"prefill is {pre / tot:.0%} of engine step time: "
                    f"chunked prefill would cap decode stalls")
        if not hints:
            hints.append("no obvious tuning headroom at this schedule")
        return hints
