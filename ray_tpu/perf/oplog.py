"""Per-process, per-stage op-timing sink for the step profiler.

The cgraph executor records every iterative op's wall-clock span and
cumulative exec/bubble seconds here; ``_CGStage.update()`` — which runs
as the LAST op of each step on the SAME executor thread — drains the
stage's slice into its per-step report dict, so per-op timestamps reach
the driver over the existing report channel with no new RPC surface.

Single-threaded by construction (one executor thread per loaded graph,
and the drain happens inside an op of that very schedule), but guarded
by a lock anyway: two pipeline replicas on one worker process would
otherwise race the dict.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional

__all__ = ["op_record", "bubble_record", "sync_record", "send_record",
           "stage_perf", "reset"]

_OPS_KEPT = 512  # per stage; a 4-microbatch step is ~10 ops


class _StageSink:
    __slots__ = ("exec_s", "bubble_s", "sync_s", "send_s", "ops")

    def __init__(self):
        self.exec_s = 0.0
        self.bubble_s = 0.0
        self.sync_s = 0.0   # collective sync-exposed (ZeRO legs, fsdp)
        self.send_s = 0.0   # encode + channel write (incl. backpressure)
        self.ops: deque = deque(maxlen=_OPS_KEPT)


_lock = threading.Lock()
_sinks: Dict[str, _StageSink] = {}


def _sink(stage: str) -> _StageSink:
    s = _sinks.get(stage)
    if s is None:
        with _lock:
            s = _sinks.setdefault(stage, _StageSink())
    return s


def op_record(stage: str, key: str, method: str,
              t0: float, t1: float) -> None:
    s = _sink(stage)
    s.exec_s += t1 - t0
    s.ops.append({"key": key, "method": method, "t0": t0, "t1": t1})


def bubble_record(stage: str, seconds: float) -> None:
    _sink(stage).bubble_s += seconds


def sync_record(stage: str, seconds: float) -> None:
    _sink(stage).sync_s += seconds


def send_record(stage: str, seconds: float) -> None:
    _sink(stage).send_s += seconds


def stage_perf(stage: str, drain_ops: bool = True) -> dict:
    """Cumulative totals (driver diffs across steps) + the op spans
    recorded since the last drain."""
    s = _sink(stage)
    with _lock:
        ops: List[dict] = list(s.ops)
        if drain_ops:
            s.ops.clear()
    return {"exec_s": s.exec_s, "bubble_s": s.bubble_s,
            "sync_s": s.sync_s, "send_s": s.send_s, "ops": ops}


def reset(stage: Optional[str] = None) -> None:
    with _lock:
        if stage is None:
            _sinks.clear()
        else:
            _sinks.pop(stage, None)
