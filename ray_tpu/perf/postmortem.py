"""Post-mortem bundles: every abort path drains the flight-recorder
rings (driver + whatever workers are still reachable) into one merged
JSON bundle on disk, rendered by ``ray_tpu postmortem <bundle>``.

A bundle is ``{"reason", "origin", "time", "rings": {proc: [events]},
"meta": {...}}`` where each event is the recorder's wire shape
(``{"ts", "kind", "label", "data"}``). Rendering merges rings on the
wall-clock axis and flags ``*.begin`` events with no matching ``*.end``
— on a mid-step stage kill, the killed op surfaces as exactly such a
dangling begin (asserted in tests/test_perf.py).

Dumps are throttled per ``(origin, reason)`` so a poison that fans out
through step()/teardown/abort produces one bundle, not three.
"""
from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional

from ..util import metrics as _metrics
from .recorder import get_recorder

__all__ = ["bundle_dir", "dump_bundle", "load_bundle", "render_bundle",
           "last_bundle_path", "find_dangling"]

_C_BUNDLES = _metrics.Counter(
    "ray_tpu_postmortem_bundles_total",
    "post-mortem flight-recorder bundles dumped", tag_keys=("origin",))

_THROTTLE_S = 10.0
_lock = threading.Lock()
_recent: Dict[tuple, float] = {}
_last_path: Optional[str] = None
_seq = 0  # disambiguates same-millisecond dumps from one process


def bundle_dir() -> str:
    d = os.environ.get("RAY_TPU_POSTMORTEM_DIR")
    if not d:
        d = os.path.join(tempfile.gettempdir(), "ray_tpu_postmortem")
    os.makedirs(d, exist_ok=True)
    return d


def dump_bundle(reason: str, origin: str = "driver",
                extra_rings: Optional[Dict[str, List[dict]]] = None,
                ring_fetchers: Optional[
                    Dict[str, Callable[[], List[dict]]]] = None,
                meta: Optional[dict] = None,
                throttle: bool = True) -> Optional[str]:
    """Write one merged bundle and return its path (None when
    throttled). ``extra_rings`` are pre-drained event lists keyed by
    process label; ``ring_fetchers`` are best-effort callables (worker
    RPCs) — a fetcher that raises contributes an error marker instead of
    killing the dump, because the abort being recorded may be the very
    thing that made the worker unreachable."""
    global _last_path, _seq
    key = (origin, reason.split(":", 1)[0])
    now = time.monotonic()
    if throttle:
        with _lock:
            last = _recent.get(key, -1e18)
            if now - last < _THROTTLE_S:
                return None
            _recent[key] = now
    rings: Dict[str, List[dict]] = {
        origin: get_recorder().snapshot(clear=False)}
    for proc, events in (extra_rings or {}).items():
        rings[proc] = list(events or ())
    for proc, fetch in (ring_fetchers or {}).items():
        try:
            rings[proc] = list(fetch() or ())
        except Exception as e:
            rings[proc] = [{"ts": time.time(), "kind": "postmortem.fetch_error",
                            "label": proc, "data": {"error": repr(e)}}]
    bundle = {"reason": reason, "origin": origin, "time": time.time(),
              "rings": rings, "meta": meta or {}}
    with _lock:
        _seq += 1
        seq = _seq
    fname = (f"postmortem-{int(time.time() * 1000)}"
             f"-{os.getpid()}-{seq}.json")
    path = os.path.join(bundle_dir(), fname)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(bundle, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    with _lock:
        _last_path = path
    _C_BUNDLES.inc(tags={"origin": origin})
    return path


def last_bundle_path() -> Optional[str]:
    with _lock:
        return _last_path


def load_bundle(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def find_dangling(bundle: dict) -> List[dict]:
    """``*.begin`` events with no later matching ``*.end`` for the same
    (process, event family, label) — in-flight work at the moment of
    death."""
    dangling: List[dict] = []
    for proc, events in sorted(bundle.get("rings", {}).items()):
        open_ops: Dict[tuple, dict] = {}
        for ev in events:
            kind = ev.get("kind", "")
            if kind.endswith(".begin"):
                open_ops[(kind[:-6], ev.get("label", ""))] = ev
            elif kind.endswith(".end"):
                open_ops.pop((kind[:-4], ev.get("label", "")), None)
        for (fam, label), ev in open_ops.items():
            dangling.append({"proc": proc, "family": fam, "label": label,
                             "ts": ev.get("ts", 0.0),
                             "data": ev.get("data")})
    dangling.sort(key=lambda d: (d["ts"], d["proc"], d["label"]))
    return dangling


def render_bundle(bundle: dict, tail: int = 40) -> str:
    """Human-readable post-mortem: header, dangling ops, then the last
    ``tail`` merged events. Deterministic for a fixed bundle (golden
    tested) — timestamps render relative to the earliest event."""
    rings = bundle.get("rings", {})
    merged = [dict(ev, proc=proc) for proc, events in sorted(rings.items())
              for ev in events]
    merged.sort(key=lambda e: (e.get("ts", 0.0), e.get("proc", "")))
    t0 = merged[0].get("ts", 0.0) if merged else 0.0
    lines = []
    lines.append("== post-mortem bundle ==")
    lines.append(f"reason : {bundle.get('reason', '?')}")
    lines.append(f"origin : {bundle.get('origin', '?')}")
    lines.append(f"rings  : " + ", ".join(
        f"{proc}({len(events)})" for proc, events in sorted(rings.items()))
        if rings else "rings  : (none)")
    for k, v in sorted((bundle.get("meta") or {}).items()):
        lines.append(f"meta   : {k} = {v}")
    dangling = find_dangling(bundle)
    lines.append("")
    if dangling:
        lines.append(f"-- in-flight at death ({len(dangling)}) --")
        for d in dangling:
            lines.append(f"  ! {d['proc']:<12} {d['family']:<18} "
                         f"{d['label']} (began +{d['ts'] - t0:.3f}s)")
    else:
        lines.append("-- in-flight at death: none --")
    lines.append("")
    shown = merged[-tail:]
    lines.append(f"-- last {len(shown)} of {len(merged)} events --")
    for ev in shown:
        data = ev.get("data")
        suffix = f"  {data}" if data else ""
        lines.append(f"  +{ev.get('ts', 0.0) - t0:9.3f}s "
                     f"{ev.get('proc', '?'):<12} "
                     f"{ev.get('kind', '?'):<22} "
                     f"{ev.get('label', '')}{suffix}")
    return "\n".join(lines)
