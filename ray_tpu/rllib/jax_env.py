"""Device-resident vectorized environments (pure-jax, jit/vmap/scan-able).

The reference's rollout architecture — CPU envs feeding a GPU learner
over a NCCL/object-store hop (rllib/evaluation/rollout_worker.py:660,
env_runner_v2.py) — is a CUDA-era shape. On TPU the idiomatic design is
the Podracer/"Anakin" layout (DeepMind, arXiv:2104.06272; PureJaxRL):
the env itself is a pure jax function, so rollout, GAE and the SGD
update fuse into ONE compiled program on the chip. Observations never
cross the host boundary — on a tunneled or PCIe-attached device that
removes the pixel-upload bottleneck entirely (28 KB/frame at Atari scale;
see docs/PERF_NOTES.md round-5 measurements: the ~15 MB/s tunnel caps a
host-rollout learner at ~500 frames/s regardless of compute).

A `JaxVectorEnv` is a bundle of pure functions over a batched state
pytree (leading dim = num_envs):

    state, obs = env.reset(key)
    state, obs, reward, done = env.step(state, actions)

Auto-reset on done matches the host `VectorEnv` contract
(ray_tpu/rllib/env.py): a done env's returned obs is the FIRST frame of
the new episode.
"""
from __future__ import annotations

from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class JaxVectorEnv:
    """Protocol base. Subclasses define pure reset/step over a state
    pytree; num_envs is static (shapes are compile-time constants)."""

    obs_shape: Tuple[int, ...]
    num_actions: int
    num_envs: int

    def reset(self, key: jax.Array):
        raise NotImplementedError

    def step(self, state, actions: jax.Array):
        raise NotImplementedError

    def fold_key(self, state, idx):
        """Decorrelate per-shard env randomness under shard_map: the
        global reset replicates the state's PRNG key to every device, so
        without this fold each shard's auto-reset noise would be
        identical."""
        if isinstance(state, dict) and "key" in state:
            return {**state, "key": jax.random.fold_in(state["key"], idx)}
        return state


_JAX_ENVS: Dict[str, Callable[..., JaxVectorEnv]] = {}


def register_jax_env(name: str, creator: Callable[..., JaxVectorEnv]) -> None:
    _JAX_ENVS[name] = creator


def make_jax_env(name: str, num_envs: int = 8) -> JaxVectorEnv:
    if name not in _JAX_ENVS:
        raise KeyError(f"unknown jax env {name!r}; "
                       f"registered: {sorted(_JAX_ENVS)}")
    return _JAX_ENVS[name](num_envs=num_envs)


class CartPoleJax(JaxVectorEnv):
    """CartPole-v1 dynamics (Barto-Sutton-Anderson; same constants as the
    numpy CartPoleVecEnv in ray_tpu/rllib/env.py): +1 per step, done on
    |x|>2.4, |theta|>12deg, or 500 steps."""

    GRAVITY, MASSCART, MASSPOLE = 9.8, 1.0, 0.1
    LENGTH, FORCE_MAG, TAU = 0.5, 10.0, 0.02
    X_LIMIT, THETA_LIMIT, MAX_STEPS = 2.4, 12 * 2 * np.pi / 360, 500

    obs_shape = (4,)
    num_actions = 2

    def __init__(self, num_envs: int = 8):
        self.num_envs = num_envs

    def _spawn(self, key: jax.Array, n: int) -> jax.Array:
        return jax.random.uniform(key, (n, 4), jnp.float32, -0.05, 0.05)

    def reset(self, key: jax.Array):
        key, sk = jax.random.split(key)
        x = self._spawn(sk, self.num_envs)
        state = {"x": x, "t": jnp.zeros(self.num_envs, jnp.int32),
                 "key": key}
        return state, x

    def step(self, state, actions: jax.Array):
        x, xd, th, thd = (state["x"][:, 0], state["x"][:, 1],
                          state["x"][:, 2], state["x"][:, 3])
        force = jnp.where(actions == 1, self.FORCE_MAG, -self.FORCE_MAG)
        total_m = self.MASSCART + self.MASSPOLE
        pml = self.MASSPOLE * self.LENGTH
        costh, sinth = jnp.cos(th), jnp.sin(th)
        temp = (force + pml * thd ** 2 * sinth) / total_m
        th_acc = (self.GRAVITY * sinth - costh * temp) / (
            self.LENGTH * (4.0 / 3.0 - self.MASSPOLE * costh ** 2 / total_m))
        x_acc = temp - pml * th_acc * costh / total_m
        x = x + self.TAU * xd
        xd = xd + self.TAU * x_acc
        th = th + self.TAU * thd
        thd = thd + self.TAU * th_acc
        t = state["t"] + 1
        done = ((jnp.abs(x) > self.X_LIMIT)
                | (jnp.abs(th) > self.THETA_LIMIT)
                | (t >= self.MAX_STEPS))
        new_x = jnp.stack([x, xd, th, thd], axis=1)
        key, sk = jax.random.split(state["key"])
        fresh = self._spawn(sk, x.shape[0])
        d = done[:, None]
        obs = jnp.where(d, fresh, new_x)
        state = {"x": obs, "t": jnp.where(done, 0, t), "key": key}
        return state, obs, jnp.ones(x.shape[0], jnp.float32), done


class BreakoutShapedJax(JaxVectorEnv):
    """The pixels env, device-resident: same game and constants as
    BreakoutShapedVecEnv (ray_tpu/rllib/preprocessors.py:145) with the
    WarpFrame + FrameStack(4) composition folded into the render — each
    84x84 output pixel samples the same nearest-neighbor source
    coordinate WarpFrameVec would, so the observation tensor matches the
    host pipeline's (84, 84, 4) uint8 shape and statistics.

    Ball drops from the top with horizontal drift, bounces off walls;
    the paddle must intercept: +1 per catch, 5 drops per episode.
    """

    H, W = 210, 160
    PADDLE_Y, PADDLE_HALF, BALL_HALF = 190, 8, 2
    PADDLE_SPEED, BALL_VY, DROPS = 6, 5, 5
    SIZE = 84
    # luma of the (200, 72, 72) sprite color after WarpFrameVec's
    # float->uint8 truncation
    LUMA = np.uint8(int(200 * 0.299 + 72 * 0.587 + 72 * 0.114))

    obs_shape = (84, 84, 4)
    num_actions = 4

    def __init__(self, num_envs: int = 8):
        self.num_envs = num_envs
        # nearest-neighbor source coordinates, identical to WarpFrameVec
        self._rows = jnp.asarray(
            np.linspace(0, self.H - 1, self.SIZE).round(), jnp.float32)
        self._cols = jnp.asarray(
            np.linspace(0, self.W - 1, self.SIZE).round(), jnp.float32)

    def _spawn(self, key: jax.Array, n: int):
        kx, kv = jax.random.split(key)
        bx = jax.random.uniform(kx, (n,), jnp.float32, 10.0, self.W - 10.0)
        bvx = jax.random.uniform(kv, (n,), jnp.float32, -3.0, 3.0)
        return bx, jnp.full((n,), 10.0, jnp.float32), bvx

    def _frame(self, bx, by, px) -> jax.Array:
        """One warped grayscale frame [n, 84, 84] uint8 from ball/paddle
        positions — the composition of _render + WarpFrameVec._warp,
        evaluated directly on the 84-grid."""
        bh, ph = float(self.BALL_HALF), float(self.PADDLE_HALF)
        bxi, byi, pxi = (jnp.floor(bx)[:, None], jnp.floor(by)[:, None],
                         jnp.floor(px)[:, None])
        r, c = self._rows[None, :], self._cols[None, :]
        ball_r = (r >= jnp.maximum(0.0, byi - bh)) & (r < byi + bh)
        ball_c = (c >= jnp.maximum(0.0, bxi - bh)) & (c < bxi + bh)
        pad_r = (r >= self.PADDLE_Y) & (r < self.PADDLE_Y + 4)
        pad_c = (c >= jnp.maximum(0.0, pxi - ph)) & (c < pxi + ph)
        mask = (ball_r[:, :, None] & ball_c[:, None, :]) \
            | (pad_r[:, :, None] & pad_c[:, None, :])
        return jnp.where(mask, self.LUMA, jnp.uint8(0))

    def reset(self, key: jax.Array):
        n = self.num_envs
        key, sk = jax.random.split(key)
        bx, by, bvx = self._spawn(sk, n)
        px = jnp.full((n,), self.W / 2.0, jnp.float32)
        frame = self._frame(bx, by, px)
        stack = jnp.repeat(frame[..., None], 4, axis=-1)
        state = {"bx": bx, "by": by, "bvx": bvx, "px": px,
                 "drops": jnp.full((n,), self.DROPS, jnp.int32),
                 "stack": stack, "key": key}
        return state, stack

    def step(self, state, actions: jax.Array):
        # local batch from the state, NOT self.num_envs: under shard_map
        # each device steps its own slice of the env batch
        n = state["bx"].shape[0]
        dx = jnp.where(actions == 2, float(self.PADDLE_SPEED),
                       jnp.where(actions == 3, -float(self.PADDLE_SPEED),
                                 0.0))
        px = jnp.clip(state["px"] + dx, self.PADDLE_HALF,
                      self.W - self.PADDLE_HALF)
        bx = state["bx"] + state["bvx"]
        bounce = (bx < self.BALL_HALF) | (bx > self.W - self.BALL_HALF)
        bvx = jnp.where(bounce, -state["bvx"], state["bvx"])
        bx = jnp.clip(bx, self.BALL_HALF, self.W - self.BALL_HALF)
        by = state["by"] + self.BALL_VY
        landed = by >= self.PADDLE_Y
        caught = landed & (jnp.abs(bx - px)
                           <= self.PADDLE_HALF + self.BALL_HALF)
        reward = caught.astype(jnp.float32)
        drops = state["drops"] - landed.astype(jnp.int32)
        done = landed & (drops <= 0)
        drops = jnp.where(done, self.DROPS, drops)
        key, sk = jax.random.split(state["key"])
        sbx, sby, sbvx = self._spawn(sk, n)
        bx = jnp.where(landed, sbx, bx)
        by = jnp.where(landed, sby, by)
        bvx = jnp.where(landed, sbvx, bvx)
        px = jnp.where(done, self.W / 2.0, px)
        frame = self._frame(bx, by, px)
        # FrameStackVec semantics: rolling history, but a done env's
        # whole stack refills with the new episode's first frame
        rolled = jnp.concatenate([state["stack"][..., 1:],
                                  frame[..., None]], axis=-1)
        refilled = jnp.repeat(frame[..., None], 4, axis=-1)
        stack = jnp.where(done[:, None, None, None], refilled, rolled)
        new_state = {"bx": bx, "by": by, "bvx": bvx, "px": px,
                     "drops": drops, "stack": stack, "key": key}
        return new_state, stack, reward, done


register_jax_env("CartPole-v1", lambda num_envs=8: CartPoleJax(num_envs))
register_jax_env("BreakoutShaped-v0",
                 lambda num_envs=8: BreakoutShapedJax(num_envs))
