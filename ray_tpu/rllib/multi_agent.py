"""Multi-agent RL — per-agent policies over a shared environment.

ref: rllib/env/multi_agent_env.py (dict-keyed obs/rewards per agent),
rllib/policy/policy_map.py + algorithm_config.multi_agent(policies=...,
policy_mapping_fn=...) — the reference's core multi-agent contract:
each agent id maps to a policy id; trajectories route to the mapped
policy's learner; policies train independently on their own batches.

Vectorized natively like the rest of this rllib: a MultiAgentVecEnv
steps n env copies at once with {agent_id: [n, obs_dim]} observation
dicts, rollout workers collect per-agent fragments with numpy policy
inference, and each policy's learner is the SAME fused-scan PPO learner
single-agent training uses (learner.py) — multi-agent is a routing
layer, not a new optimizer.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle
import numpy as np

import ray_tpu

from . import sample_batch as sb
from .learner import PPOLearner
from .np_policy import ensure_numpy, sample_actions
from .rollout_worker import EnvWorkerBase, worker_opts


class MultiAgentVecEnv:
    """n copies of a multi-agent env stepped as one batch.

    Contract (the vectorized form of ref multi_agent_env.py):
      agent_ids: fixed tuple of agent ids (all active every step)
      reset()  -> {agent_id: [n, obs_dim]}
      step({agent_id: [n] actions})
               -> (obs_dict, {agent_id: [n] rewards}, [n] dones, info)
    Sub-envs auto-reset on done.
    """

    num_envs: int
    obs_dim: int
    num_actions: int
    agent_ids: tuple = ()

    def reset(self, seed: Optional[int] = None) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def step(self, actions: Dict[str, np.ndarray]):
        raise NotImplementedError


class CoordinationVecEnv(MultiAgentVecEnv):
    """Two-agent repeated coordination game with observations: each agent
    sees both agents' previous actions (one-hot) and must learn to pick
    the SAME arm as its partner (+1 each when matched, 0 otherwise);
    episodes last 25 rounds. A pure-conflict-free game both independent
    learners solve quickly — the multi-agent analog of CartPole for
    tests (ref test model: rllib's rock_paper_scissors / two-step-game
    examples)."""

    EPISODE_LEN = 25
    ARMS = 3

    agent_ids = ("a0", "a1")

    def __init__(self, num_envs: int = 8, seed: int = 0):
        self.num_envs = num_envs
        self.num_actions = self.ARMS
        self.obs_dim = 2 * self.ARMS  # one-hot prev action of both agents
        self._rng = np.random.default_rng(seed)
        self._prev = np.zeros((num_envs, 2), np.int64)
        self._t = np.zeros(num_envs, np.int64)

    def _obs(self) -> Dict[str, np.ndarray]:
        eye = np.eye(self.ARMS, dtype=np.float32)
        both = np.concatenate([eye[self._prev[:, 0]],
                               eye[self._prev[:, 1]]], axis=1)
        # each agent sees (own prev, partner prev) in its own order
        own_first = np.concatenate([eye[self._prev[:, 1]],
                                    eye[self._prev[:, 0]]], axis=1)
        return {"a0": both, "a1": own_first}

    def reset(self, seed: Optional[int] = None) -> Dict[str, np.ndarray]:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._prev = self._rng.integers(0, self.ARMS, (self.num_envs, 2))
        self._t[:] = 0
        return self._obs()

    def step(self, actions: Dict[str, np.ndarray]):
        a0 = np.asarray(actions["a0"])
        a1 = np.asarray(actions["a1"])
        match = (a0 == a1).astype(np.float32)
        rewards = {"a0": match.copy(), "a1": match.copy()}
        self._prev = np.stack([a0, a1], axis=1)
        self._t += 1
        done = self._t >= self.EPISODE_LEN
        info = {}
        if done.any():
            # the 25-round cap is a TIME LIMIT, not termination: hand the
            # pre-reset obs out so samplers bootstrap V(s_final)
            info["truncated"] = done.copy()
            info["final_obs"] = self._obs()
            idx = np.nonzero(done)[0]
            self._prev[idx] = self._rng.integers(0, self.ARMS,
                                                 (len(idx), 2))
            self._t[idx] = 0
        return self._obs(), rewards, done, info


_MA_REGISTRY: Dict[str, Callable[..., MultiAgentVecEnv]] = {
    "Coordination-v0": CoordinationVecEnv,
}


def register_multi_agent_env(name: str, creator) -> None:
    _MA_REGISTRY[name] = creator


def make_multi_agent_env(name: str, num_envs: int = 8,
                         seed: int = 0) -> MultiAgentVecEnv:
    if name not in _MA_REGISTRY:
        raise ValueError(f"Unknown multi-agent env {name!r}")
    return _MA_REGISTRY[name](num_envs=num_envs, seed=seed)


class MultiAgentRolloutWorker(EnvWorkerBase):
    """Samples all agents in lockstep; emits one train batch PER POLICY
    (trajectories of every agent mapped to it, concatenated), with GAE
    computed per agent so advantages never mix across policies."""

    def __init__(self, env_name: str, num_envs: int, rollout_len: int,
                 gamma: float, lam: float, mapping_blob: bytes,
                 seed: int = 0, env_creator=None):
        # EnvWorkerBase builds single-agent envs; construct ours here but
        # reuse its episode-return bookkeeping fields/methods
        self.env = (cloudpickle.loads(env_creator)(num_envs=num_envs,
                                                   seed=seed)
                    if env_creator else
                    make_multi_agent_env(env_name, num_envs, seed))
        self.rollout_len = rollout_len
        self._rng = np.random.default_rng(seed + 1)
        self._obs = self.env.reset(seed=seed)
        self.gamma = gamma
        self.lam = lam
        self.mapping = cloudpickle.loads(mapping_blob)
        self._ep_return = np.zeros(self.env.num_envs, np.float64)
        self._finished_returns: list = []

    def env_info(self) -> dict:
        return {"obs_dim": self.env.obs_dim,
                "obs_shape": (self.env.obs_dim,),
                "num_actions": self.env.num_actions,
                "num_envs": self.env.num_envs,
                "agent_ids": tuple(self.env.agent_ids)}

    def sample(self, policy_params: Dict[str, Dict]
               ) -> Dict[str, sb.Batch]:
        params = {pid: ensure_numpy(p) for pid, p in policy_params.items()}
        T, n = self.rollout_len, self.env.num_envs
        agents = list(self.env.agent_ids)
        buf = {a: {"obs": np.empty((T, n, self.env.obs_dim), np.float32),
                   "act": np.empty((T, n), np.int64),
                   "logp": np.empty((T, n), np.float32),
                   "val": np.empty((T, n), np.float32),
                   "rew": np.empty((T, n), np.float32)}
               for a in agents}
        done_buf = np.empty((T, n), np.bool_)
        obs = self._obs
        for t in range(T):
            acts: Dict[str, np.ndarray] = {}
            for a in agents:
                p = params[self.mapping(a)]
                actions, logp, values = sample_actions(p, obs[a], self._rng)
                b = buf[a]
                b["obs"][t], b["act"][t] = obs[a], actions
                b["logp"][t], b["val"][t] = logp, values
                acts[a] = actions
            obs, rewards, done, info = self.env.step(acts)
            for a in agents:
                buf[a]["rew"][t] = rewards[a]
            if done.any() and "truncated" in info:
                # time-limit truncation is not termination: fold
                # gamma*V(s_final) into each agent's reward so GAE's
                # done-cut doesn't zero a bootstrap that should exist
                # (the rollout_worker.py:94 recipe, per agent)
                trunc = np.asarray(info["truncated"])
                if trunc.any():
                    idx = np.nonzero(trunc)[0]
                    for a in agents:
                        p = params[self.mapping(a)]
                        fo = info["final_obs"][a][idx]
                        _, _, v_final = sample_actions(p, fo, self._rng)
                        buf[a]["rew"][t, idx] += self.gamma * v_final
            done_buf[t] = done
            # per-env sum over agents is the tracked episode return
            step_rew = sum(np.asarray(rewards[a], np.float64)
                           for a in agents)
            self._track_returns(step_rew.astype(np.float32), done)
        self._obs = obs
        # per-agent GAE with each agent's own value stream
        out: Dict[str, List[sb.Batch]] = {}
        for a in agents:
            p = params[self.mapping(a)]
            _, _, last_values = sample_actions(p, obs[a], self._rng)
            b = buf[a]
            adv, ret = sb.compute_gae(b["rew"], b["val"], done_buf,
                                      last_values, self.gamma, self.lam)
            flat = lambda x: x.reshape(T * n, *x.shape[2:])  # noqa: E731
            batch = {sb.OBS: flat(b["obs"]), sb.ACTIONS: flat(b["act"]),
                     sb.LOGP: flat(b["logp"]), sb.VALUES: flat(b["val"]),
                     sb.REWARDS: flat(b["rew"]),
                     sb.DONES: flat(done_buf.copy()),
                     sb.ADVANTAGES: flat(adv), sb.RETURNS: flat(ret)}
            out.setdefault(self.mapping(a), []).append(batch)
        return {pid: sb.concat(batches) for pid, batches in out.items()}


@dataclass
class MultiAgentPPOConfig:
    """ref: algorithm_config.multi_agent(policies, policy_mapping_fn).
    policies: policy ids (params/learner per id); None -> one shared
    policy ("default") for every agent."""
    env: str = "Coordination-v0"
    env_creator: Optional[Callable] = None
    policies: Optional[List[str]] = None
    policy_mapping_fn: Optional[Callable[[str], str]] = None
    num_rollout_workers: int = 2
    num_envs_per_worker: int = 8
    rollout_fragment_length: int = 64
    gamma: float = 0.99
    lam: float = 0.95
    lr: float = 3e-4
    clip_param: float = 0.2
    vf_loss_coeff: float = 0.5
    entropy_coeff: float = 0.01
    sgd_minibatch_size: int = 256
    num_sgd_epochs: int = 4
    hidden: tuple = (64, 64)
    seed: int = 0
    worker_resources: Dict[str, float] = field(default_factory=dict)

    def build(self) -> "MultiAgentPPO":
        return MultiAgentPPO(self)


class MultiAgentPPO:
    """One PPOLearner per policy id; rollout workers route each agent's
    trajectories to its mapped policy. Tune-trainable shaped."""

    def __init__(self, config: MultiAgentPPOConfig):
        self.config = c = config
        probe = (c.env_creator(num_envs=1, seed=c.seed) if c.env_creator
                 else make_multi_agent_env(c.env, 1, c.seed))
        agent_ids = tuple(probe.agent_ids)
        if c.policies is None:
            policies = ["default"]
            mapping = (lambda agent_id: "default")
        else:
            policies = list(c.policies)
            mapping = c.policy_mapping_fn
            if mapping is None:
                raise ValueError(
                    "policies given without policy_mapping_fn")
        for a in agent_ids:
            pid = mapping(a)
            if pid not in policies:
                raise ValueError(
                    f"policy_mapping_fn({a!r}) -> {pid!r} not in "
                    f"policies {policies}")
        self.policy_ids = policies
        self.mapping = mapping
        mapping_blob = cloudpickle.dumps(mapping)
        creator_blob = (cloudpickle.dumps(c.env_creator)
                        if c.env_creator else None)
        worker_cls = ray_tpu.remote(MultiAgentRolloutWorker)
        opts = worker_opts(c.worker_resources)
        self.workers = [
            worker_cls.options(**opts).remote(
                c.env, c.num_envs_per_worker, c.rollout_fragment_length,
                c.gamma, c.lam, mapping_blob, seed=c.seed + 1000 * i,
                env_creator=creator_blob)
            for i in range(c.num_rollout_workers)]
        info = ray_tpu.get(self.workers[0].env_info.remote(), timeout=180)
        self._num_agents = max(1, len(info.get("agent_ids", ())))
        self.learners: Dict[str, PPOLearner] = {
            pid: PPOLearner(
                info["obs_dim"], info["num_actions"], lr=c.lr,
                clip=c.clip_param, vf_coeff=c.vf_loss_coeff,
                ent_coeff=c.entropy_coeff,
                minibatch_size=c.sgd_minibatch_size,
                num_epochs=c.num_sgd_epochs, hidden=c.hidden,
                seed=c.seed + 31 * i)
            for i, pid in enumerate(policies)}
        self._iteration = 0
        self._total_steps = 0
        self._recent: List[float] = []

    def train(self) -> Dict[str, Any]:
        t0 = time.monotonic()
        params_ref = ray_tpu.put(
            {pid: ln.get_params() for pid, ln in self.learners.items()})
        results = ray_tpu.get(
            [w.sample.remote(params_ref) for w in self.workers],
            timeout=300)
        sample_time = time.monotonic() - t0
        t1 = time.monotonic()
        stats: Dict[str, Any] = {}
        steps = 0
        for pid in self.policy_ids:
            batches = [r[pid] for r in results if pid in r]
            if not batches:
                continue
            batch = sb.concat(batches)
            steps += sb.num_steps(batch)
            for k, v in self.learners[pid].update(batch).items():
                stats[f"{pid}/{k}"] = v
        learn_time = time.monotonic() - t1
        for rets in ray_tpu.get(
                [w.episode_returns.remote() for w in self.workers],
                timeout=60):
            self._recent.extend(rets)
        self._recent = self._recent[-100:]
        self._iteration += 1
        # `steps` summed per-policy batch rows = AGENT steps; report env
        # steps under the shared field names so budgets/throughput stay
        # comparable with the single-agent algorithms
        env_steps = steps // self._num_agents
        self._total_steps += env_steps
        return {
            "training_iteration": self._iteration,
            "timesteps_total": self._total_steps,
            "timesteps_this_iter": env_steps,
            "agent_steps_this_iter": steps,
            "episode_reward_mean": (float(np.mean(self._recent))
                                    if self._recent else float("nan")),
            "env_steps_per_sec": env_steps / max(
                1e-9, sample_time + learn_time),
            **stats,
        }

    def save(self) -> Dict:
        import jax

        return {"policies": {pid: {
                    "params": jax.device_get(ln.params),
                    "opt_state": jax.device_get(ln.opt_state)}
                for pid, ln in self.learners.items()},
                "iteration": self._iteration,
                "total_steps": self._total_steps}

    def restore(self, ckpt: Dict) -> None:
        import jax
        import jax.numpy as jnp

        for pid, st in ckpt["policies"].items():
            ln = self.learners[pid]
            ln.params = {k: jnp.asarray(v)
                         for k, v in st["params"].items()}
            ln.opt_state = jax.tree.map(jnp.asarray, st["opt_state"])
        self._iteration = int(ckpt.get("iteration", 0))
        self._total_steps = int(ckpt.get("total_steps", 0))

    def stop(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
