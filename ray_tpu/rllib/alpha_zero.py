"""AlphaZero — self-play MCTS planning with a learned policy/value net
(Silver et al. 2017).

ref: rllib/algorithms/alpha_zero/alpha_zero.py (+ mcts.py: PUCT
selection, Dirichlet root noise, visit-count policy targets;
ranked_rewards omitted — two-player zero-sum games need no reward
ranking). The reference couples MCTS to single gym envs per worker;
here self-play actors run a BATCHED MCTS: one tree per live game, but
every simulation step evaluates all games' leaves through the network
in one batch — the vectorized-env discipline the rest of this rllib
uses, applied to tree search.

Game contract (two-player, zero-sum, turn-based) is a tiny numpy
protocol (`TicTacToe` ships as the test surface): canonical boards —
the network always sees the position from the player-to-move's
perspective, so one net plays both sides.

Learner: visit-count cross-entropy + outcome MSE, all minibatches in
one jitted lax.scan dispatch (docs/PERF_NOTES.md learner rule).
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Tuple

import numpy as np

import ray_tpu

from .rollout_worker import worker_opts


# ---------------------------------------------------------------------------
# game protocol + TicTacToe
# ---------------------------------------------------------------------------


class TicTacToe:
    """Vector-friendly two-player game: boards are [n, board_size] int8
    arrays with stones stored absolutely (+1 = X, -1 = O).

    Static/class-method protocol so MCTS/self-play need no instances —
    custom games implement exactly these names (A, OBS_DIM class attrs
    plus):
      initial(n) -> (boards [n, board_size], players [n])
      legal(boards) -> [n, A] bool
      play(boards, players, actions) -> (boards, players)  # next mover
      terminal_value(boards, players) -> [n] float in {-1, 0, +1} from
        the perspective of the PLAYER TO MOVE (players[i]): -1 means
        the mover has already lost (the usual case — the opponent just
        completed a line); nan while the game is live
      canonical(boards, players) -> [n, OBS_DIM] float32 net input from
        the player-to-move's perspective
    """

    A = 9
    OBS_DIM = 18  # own stones one-hot + opponent stones one-hot

    _WINS = np.array([[0, 1, 2], [3, 4, 5], [6, 7, 8],
                      [0, 3, 6], [1, 4, 7], [2, 5, 8],
                      [0, 4, 8], [2, 4, 6]])

    @staticmethod
    def initial(n: int) -> Tuple[np.ndarray, np.ndarray]:
        return (np.zeros((n, 9), np.int8), np.ones(n, np.int8))

    @staticmethod
    def legal(boards: np.ndarray) -> np.ndarray:
        return boards == 0

    @staticmethod
    def play(boards: np.ndarray, players: np.ndarray,
             actions: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        out = boards.copy()
        out[np.arange(len(out)), actions] = players
        return out, (-players).astype(np.int8)

    @classmethod
    def winner(cls, boards: np.ndarray) -> np.ndarray:
        """[n] in {+1, -1, 0=none-yet-or-draw}."""
        lines = boards[:, cls._WINS]          # [n, 8, 3]
        sums = lines.sum(axis=2)
        w = np.zeros(len(boards), np.int8)
        w[(sums == 3).any(axis=1)] = 1
        w[(sums == -3).any(axis=1)] = -1
        return w

    @classmethod
    def terminal_value(cls, boards: np.ndarray, players: np.ndarray
                       ) -> np.ndarray:
        """Value from the PLAYER-TO-MOVE's perspective: +1 win, -1
        loss, 0 draw; nan while the game is live."""
        w = cls.winner(boards)
        full = (boards != 0).all(axis=1)
        v = np.full(len(boards), np.nan, np.float32)
        done = (w != 0) | full
        v[done] = 0.0
        # if a line exists it belongs to the player who just moved —
        # the player to move has LOST
        v[w == players] = 1.0    # (cannot happen in legal play; safety)
        v[(w != 0) & (w != players)] = -1.0
        return v

    @staticmethod
    def canonical(boards: np.ndarray, players: np.ndarray) -> np.ndarray:
        mine = (boards == players[:, None]).astype(np.float32)
        theirs = (boards == -players[:, None]).astype(np.float32)
        return np.concatenate([mine, theirs], axis=1)


_GAMES: Dict[str, Any] = {"TicTacToe-v0": TicTacToe}


def register_game(name: str, game) -> None:
    _GAMES[name] = game


# ---------------------------------------------------------------------------
# batched MCTS (numpy, one tree per game, batched leaf evaluation)
# ---------------------------------------------------------------------------


class _Tree:
    """One game's search tree in flat arrays (ref: mcts.py Node — here
    arrays-of-nodes instead of node objects)."""

    def __init__(self, max_nodes: int, A: int, board_size: int):
        self.N = np.zeros((max_nodes, A), np.float32)   # visit counts
        self.W = np.zeros((max_nodes, A), np.float32)   # total value
        self.P = np.zeros((max_nodes, A), np.float32)   # priors
        self.children = np.full((max_nodes, A), -1, np.int32)
        self.boards = np.zeros((max_nodes, board_size), np.int8)
        self.players = np.zeros(max_nodes, np.int8)
        self.legal = np.zeros((max_nodes, A), bool)
        self.terminal_v = np.full(max_nodes, np.nan, np.float32)
        self.size = 0

    def add(self, board, player, legal, term_v) -> int:
        i = self.size
        self.size += 1
        self.boards[i], self.players[i] = board, player
        self.legal[i] = legal
        self.terminal_v[i] = term_v
        return i


def mcts_policy(game, forward_fn, boards: np.ndarray,
                players: np.ndarray, *, num_sims: int, c_puct: float,
                dirichlet_alpha: float, dirichlet_eps: float,
                rng: np.random.Generator) -> np.ndarray:
    """Run PUCT search for every live game; returns visit-count
    distributions [n, A] (ref: mcts.py compute_action + the AlphaZero
    paper's search)."""
    n, A = len(boards), game.A
    board_size = boards.shape[1]
    max_nodes = num_sims + 2
    trees = [_Tree(max_nodes, A, board_size) for _ in range(n)]
    # root eval (batched) + Dirichlet noise
    probs, _ = forward_fn(game.canonical(boards, players))
    for i, t in enumerate(trees):
        legal = game.legal(boards[i:i + 1])[0]
        term = game.terminal_value(boards[i:i + 1], players[i:i + 1])[0]
        t.add(boards[i], players[i], legal, term)
        p = probs[i] * legal
        p = p / max(p.sum(), 1e-9)
        noise = rng.dirichlet([dirichlet_alpha] * int(legal.sum()))
        p[legal] = (1 - dirichlet_eps) * p[legal] + dirichlet_eps * noise
        t.P[0] = p

    for _ in range(num_sims):
        # phase 1: descend every tree to a leaf
        paths: List[List[Tuple[int, int]]] = []
        leaf_boards = np.zeros((n, board_size), np.int8)
        leaf_players = np.zeros(n, np.int8)
        leaf_node = np.zeros(n, np.int32)
        needs_eval = np.zeros(n, bool)
        for i, t in enumerate(trees):
            node = 0
            path: List[Tuple[int, int]] = []
            while True:
                if not np.isnan(t.terminal_v[node]):
                    break  # terminal leaf
                sqrt_n = np.sqrt(max(1.0, t.N[node].sum()))
                q = np.where(t.N[node] > 0,
                             t.W[node] / np.maximum(t.N[node], 1e-9),
                             0.0)
                u = c_puct * t.P[node] * sqrt_n / (1.0 + t.N[node])
                score = np.where(t.legal[node], q + u, -np.inf)
                a = int(score.argmax())
                child = t.children[node, a]
                if child < 0:
                    # expand: play the move, add the child node
                    nb, npl = game.play(t.boards[node:node + 1],
                                        t.players[node:node + 1],
                                        np.array([a]))
                    term = game.terminal_value(nb, npl)[0]
                    legal = game.legal(nb)[0]
                    child = t.add(nb[0], npl[0], legal, term)
                    t.children[node, a] = child
                    path.append((node, a))
                    node = child
                    break
                path.append((node, a))
                node = child
            paths.append(path)
            leaf_node[i] = node
            if np.isnan(trees[i].terminal_v[node]):
                needs_eval[i] = True
                leaf_boards[i] = trees[i].boards[node]
                leaf_players[i] = trees[i].players[node]

        # phase 2: ONE batched net call for all non-terminal leaves
        if needs_eval.any():
            idx = np.nonzero(needs_eval)[0]
            probs, values = forward_fn(
                game.canonical(leaf_boards[idx], leaf_players[idx]))
            for j, i in enumerate(idx):
                t = trees[i]
                node = leaf_node[i]
                p = probs[j] * t.legal[node]
                t.P[node] = p / max(p.sum(), 1e-9)

        # phase 3: backup
        for i, t in enumerate(trees):
            node = leaf_node[i]
            if not np.isnan(t.terminal_v[node]):
                v = float(t.terminal_v[node])
            else:
                # rank of game i among the batch-evaluated leaves
                v = float(values[np.count_nonzero(needs_eval[:i])])
            # v is from the LEAF's player-to-move perspective; flip as
            # we walk back up (alternating turns)
            for (pn, pa) in reversed(paths[i]):
                v = -v  # parent is the other player
                t.N[pn, pa] += 1.0
                t.W[pn, pa] += v

    visits = np.stack([t.N[0] for t in trees])
    return visits / np.maximum(visits.sum(axis=1, keepdims=True), 1e-9)


# ---------------------------------------------------------------------------
# self-play worker / learner / driver
# ---------------------------------------------------------------------------


class AlphaZeroSelfPlayWorker:
    """Plays batched self-play games with MCTS; emits
    (canonical_obs, visit_policy, outcome) training triples."""

    def __init__(self, game_name: str, num_games: int, num_sims: int,
                 c_puct: float, temperature_moves: int,
                 dirichlet_alpha: float, dirichlet_eps: float,
                 seed: int = 0):
        from .np_policy import forward_np

        self.game = _GAMES[game_name]
        self.n = num_games
        self.num_sims = num_sims
        self.c_puct = c_puct
        self.temp_moves = temperature_moves
        self.dir_alpha = dirichlet_alpha
        self.dir_eps = dirichlet_eps
        self._rng = np.random.default_rng(seed)
        self._forward_np = forward_np

    def _forward(self, params):
        def fn(obs):
            logits, values = self._forward_np(params, obs)
            ex = np.exp(logits - logits.max(axis=1, keepdims=True))
            return ex / ex.sum(axis=1, keepdims=True), np.tanh(values)
        return fn

    def self_play(self, params: Dict) -> Dict[str, np.ndarray]:
        from .np_policy import ensure_numpy

        game = self.game
        fwd = self._forward(ensure_numpy(params))
        boards, players = game.initial(self.n)
        live = np.ones(self.n, bool)
        # per-game trajectory of (obs, pi, player)
        obs_tr: List[List[np.ndarray]] = [[] for _ in range(self.n)]
        pi_tr: List[List[np.ndarray]] = [[] for _ in range(self.n)]
        pl_tr: List[List[int]] = [[] for _ in range(self.n)]
        outcome = np.zeros(self.n, np.float32)  # from X's perspective
        move = 0
        while live.any():
            idx = np.nonzero(live)[0]
            pis = mcts_policy(
                game, fwd, boards[idx], players[idx],
                num_sims=self.num_sims, c_puct=self.c_puct,
                dirichlet_alpha=self.dir_alpha,
                dirichlet_eps=self.dir_eps, rng=self._rng)
            cano = game.canonical(boards[idx], players[idx])
            acts = np.zeros(len(idx), np.int64)
            for j, i in enumerate(idx):
                obs_tr[i].append(cano[j])
                pi_tr[i].append(pis[j])
                pl_tr[i].append(int(players[i]))
                if move < self.temp_moves:
                    acts[j] = self._rng.choice(game.A, p=pis[j])
                else:
                    acts[j] = int(pis[j].argmax())
            nb, npl = game.play(boards[idx], players[idx], acts)
            boards[idx], players[idx] = nb, npl
            term = game.terminal_value(nb, npl)
            for j, i in enumerate(idx):
                if not np.isnan(term[j]):
                    live[i] = False
                    # term is from the new player-to-move's perspective;
                    # convert to X's: player-to-move is npl[j]
                    outcome[i] = term[j] * npl[j]
            move += 1
        obs, pis, zs = [], [], []
        for i in range(self.n):
            for o, p, pl in zip(obs_tr[i], pi_tr[i], pl_tr[i]):
                obs.append(o)
                pis.append(p)
                zs.append(outcome[i] * pl)  # outcome from mover's view
        return {"obs": np.asarray(obs, np.float32),
                "pi": np.asarray(pis, np.float32),
                "z": np.asarray(zs, np.float32),
                "games": np.float32(self.n),
                "x_score": np.float32(outcome.mean())}

    def evaluate_vs_random(self, params: Dict, num_games: int,
                           seed: int = 0) -> Dict[str, float]:
        """Greedy 1-sim... full-MCTS agent as X vs uniform-random O and
        vice versa; returns non-loss rate (ref: alpha_zero examples'
        eval against random play)."""
        from .np_policy import ensure_numpy

        game = self.game
        fwd = self._forward(ensure_numpy(params))
        rng = np.random.default_rng(seed)
        results = []
        for agent_is_x in (True, False):
            boards, players = game.initial(num_games)
            live = np.ones(num_games, bool)
            outcome = np.zeros(num_games, np.float32)
            while live.any():
                idx = np.nonzero(live)[0]
                agent_turn = (players[idx] == 1) == agent_is_x
                acts = np.zeros(len(idx), np.int64)
                if agent_turn.any():
                    ai = idx[agent_turn]
                    pis = mcts_policy(
                        game, fwd, boards[ai], players[ai],
                        num_sims=self.num_sims, c_puct=self.c_puct,
                        dirichlet_alpha=self.dir_alpha,
                        dirichlet_eps=0.0, rng=rng)
                    acts[agent_turn] = pis.argmax(axis=1)
                if (~agent_turn).any():
                    ri = idx[~agent_turn]
                    legal = game.legal(boards[ri])
                    for j, gi in enumerate(ri):
                        choices = np.nonzero(legal[j])[0]
                        acts[np.nonzero(~agent_turn)[0][j]] = \
                            rng.choice(choices)
                nb, npl = game.play(boards[idx], players[idx], acts)
                boards[idx], players[idx] = nb, npl
                term = game.terminal_value(nb, npl)
                for j, i in enumerate(idx):
                    if not np.isnan(term[j]):
                        live[i] = False
                        outcome[i] = term[j] * npl[j]  # X's perspective
            agent_score = outcome if agent_is_x else -outcome
            results.append(agent_score)
        score = np.concatenate(results)
        return {"win_rate": float((score > 0).mean()),
                "draw_rate": float((score == 0).mean()),
                "non_loss_rate": float((score >= 0).mean())}


@dataclass
class AlphaZeroConfig:
    """ref: alpha_zero.py AlphaZeroConfig (num_sims, puct c, Dirichlet
    noise, temperature schedule)."""
    game: str = "TicTacToe-v0"
    num_workers: int = 2
    games_per_worker: int = 8
    num_sims: int = 32
    c_puct: float = 1.5
    temperature_moves: int = 4    # sample from visits for the first k
    dirichlet_alpha: float = 0.6
    dirichlet_eps: float = 0.25
    lr: float = 1e-3
    train_batch_size: int = 256
    num_updates_per_iter: int = 8
    replay_capacity: int = 20_000
    hidden: tuple = (64, 64)
    seed: int = 0
    worker_resources: Dict[str, float] = field(default_factory=dict)

    def build(self) -> "AlphaZero":
        return AlphaZero(self)


class AlphaZeroLearner:
    """pi: visit-count cross-entropy; v: outcome MSE — one fused scan."""

    def __init__(self, obs_dim: int, num_actions: int, c: AlphaZeroConfig):
        import functools

        import jax
        import jax.numpy as jnp
        import optax

        from .models import forward, init_policy_params

        self.params = init_policy_params(
            jax.random.PRNGKey(c.seed), obs_dim, num_actions,
            tuple(c.hidden))
        self.optimizer = optax.adam(c.lr)
        self.opt_state = self.optimizer.init(self.params)

        def loss_fn(params, mb):
            logits, values = forward(params, mb["obs"])
            logp = jax.nn.log_softmax(logits)
            pol = -jnp.mean(jnp.sum(mb["pi"] * logp, axis=1))
            val = jnp.mean((jnp.tanh(values) - mb["z"]) ** 2)
            return pol + val, {"policy_loss": pol, "value_loss": val}

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def update_many(params, opt_state, batches):
            def body(carry, mb):
                params, opt_state = carry
                (loss, stats), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params, mb)
                updates, opt_state = self.optimizer.update(grads,
                                                           opt_state)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), {**stats, "loss": loss}

            (params, opt_state), stats = jax.lax.scan(
                body, (params, opt_state), batches)
            return params, opt_state, jax.tree.map(jnp.mean, stats)

        self._update_many = update_many

    def update(self, stacked: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax
        import jax.numpy as jnp

        jb = {k: jnp.asarray(v) for k, v in stacked.items()}
        self.params, self.opt_state, stats = self._update_many(
            self.params, self.opt_state, jb)
        return {k: float(v) for k, v in jax.device_get(stats).items()}

    def get_params(self) -> Dict:
        import jax

        return jax.device_get(self.params)


class AlphaZero:
    """Self-play driver: parallel MCTS workers -> replay of
    (obs, pi, z) -> fused learner -> weight broadcast."""

    def __init__(self, config: AlphaZeroConfig):
        from .replay_buffer import ReplayBuffer

        self.config = c = config
        game = _GAMES[c.game]
        cls = ray_tpu.remote(AlphaZeroSelfPlayWorker)
        opts = worker_opts(c.worker_resources)
        self.workers = [
            cls.options(**opts).remote(
                c.game, c.games_per_worker, c.num_sims, c.c_puct,
                c.temperature_moves, c.dirichlet_alpha, c.dirichlet_eps,
                seed=c.seed + 101 * i)
            for i in range(c.num_workers)]
        self.learner = AlphaZeroLearner(game.OBS_DIM, game.A, c)
        self.buffer = ReplayBuffer(c.replay_capacity, seed=c.seed)
        self._iteration = 0
        self._total_games = 0

    def train(self) -> Dict[str, Any]:
        c = self.config
        t0 = time.monotonic()
        params_ref = ray_tpu.put(self.learner.get_params())
        outs = ray_tpu.get(
            [w.self_play.remote(params_ref) for w in self.workers],
            timeout=600)
        games, x_scores = 0, []
        for o in outs:
            games += int(o.pop("games"))
            x_scores.append(float(o.pop("x_score")))
            self.buffer.add(o)
        self._total_games += games
        stats: Dict[str, float] = {}
        # gate until one full batch exists (the sac.py pattern): a
        # shrunken B would recompile the jitted scan per new shape and
        # train on heavily duplicated rows
        if len(self.buffer) >= c.train_batch_size:
            K, B = c.num_updates_per_iter, c.train_batch_size
            mb = self.buffer.sample(K * B)
            stacked = {k: v.reshape(K, B, *v.shape[1:])
                       for k, v in mb.items()}
            stats = self.learner.update(stacked)
        self._iteration += 1
        return {"training_iteration": self._iteration,
                "games_total": self._total_games,
                "games_this_iter": games,
                "x_score_mean": float(np.mean(x_scores)),
                "buffer_positions": len(self.buffer),
                "time_this_iter_s": time.monotonic() - t0,
                **stats}

    def evaluate_vs_random(self, num_games: int = 32,
                           seed: int = 7) -> Dict[str, float]:
        params_ref = ray_tpu.put(self.learner.get_params())
        return ray_tpu.get(
            self.workers[0].evaluate_vs_random.remote(
                params_ref, num_games, seed), timeout=600)

    # -- Tune-trainable surface ------------------------------------------

    def save(self) -> Dict:
        import jax

        return {"params": jax.device_get(self.learner.params),
                "opt_state": jax.device_get(self.learner.opt_state),
                "iteration": self._iteration,
                "total_games": self._total_games,
                "buffer": self.buffer.state()}

    def restore(self, ckpt: Dict) -> None:
        import jax
        import jax.numpy as jnp

        self.learner.params = jax.tree.map(jnp.asarray, ckpt["params"])
        if "opt_state" in ckpt:
            self.learner.opt_state = jax.tree.map(jnp.asarray,
                                                  ckpt["opt_state"])
        self._iteration = int(ckpt.get("iteration", 0))
        self._total_games = int(ckpt.get("total_games", 0))
        if "buffer" in ckpt:
            self.buffer.restore(ckpt["buffer"])

    def stop(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
