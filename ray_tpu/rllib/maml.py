"""MAML — model-agnostic meta-learning for RL (Finn et al. 2017).

ref: rllib/algorithms/maml/maml.py (+ maml_torch_policy.py: inner
adaptation on per-task rollouts, outer meta-update through the
adaptation step; the reference needs a TensorFlow tape / torch
higher-order machinery for d theta'/d theta — in jax the meta-gradient
is literally `jax.grad` composed over an inner `jax.grad`, vmapped over
tasks, which is the cleanest argument in this repo for the functional
compute stack).

Loop shape (the reference's, on this runtime's actor plane):
  1. per-task rollout workers sample pre-adaptation trajectories with
     the meta-parameters theta;
  2. the learner computes EVERY task's adapted parameters
     theta_i' = theta - alpha * grad L_inner(theta; tau_i) in one
     vmapped jitted call;
  3. workers sample post-adaptation trajectories with their theta_i';
  4. the learner takes the meta-step
     theta <- theta - beta * grad_theta mean_i L_outer(theta_i'(theta);
     tau_i') — second-order by construction (jax traces through the
     inner update; first_order=True stops those gradients for the
     FOMAML variant).

Task family: PointGoalVecEnv — 2D point agent, per-task goal, reward
-dist(pos, goal): the canonical MAML-RL probe (Finn et al. 5.2).
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import cloudpickle
import numpy as np

import ray_tpu

from .rollout_worker import worker_opts


class PointGoalVecEnv:
    """2D point navigation, vectorized; the TASK is the goal position.
    obs = position (2,), action = velocity in [-0.1, 0.1]^2, reward =
    -||pos - goal||; 20-step episodes from the origin."""

    EPISODE_LEN = 20
    STEP = 0.1

    continuous = True
    action_dim = 2
    action_low = -1.0
    action_high = 1.0

    def __init__(self, num_envs: int = 8, seed: int = 0,
                 goal: Tuple[float, float] = (0.5, 0.5)):
        self.num_envs = num_envs
        self.obs_dim = 2
        self.num_actions = 0
        self.goal = np.asarray(goal, np.float64)
        self._rng = np.random.default_rng(seed)
        self._pos = np.zeros((num_envs, 2))
        self._t = np.zeros(num_envs, np.int64)

    def set_task(self, goal) -> None:
        self.goal = np.asarray(goal, np.float64)

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._pos[:] = 0.0
        self._t[:] = 0
        return self._pos.astype(np.float32)

    def step(self, actions: np.ndarray):
        a = np.clip(np.asarray(actions, np.float64), -1, 1) * self.STEP
        self._pos = self._pos + a
        self._t += 1
        reward = -np.linalg.norm(self._pos - self.goal,
                                 axis=1).astype(np.float32)
        done = self._t >= self.EPISODE_LEN
        info: Dict[str, Any] = {}
        if done.any():
            info["truncated"] = done.copy()
            info["final_obs"] = self._pos.astype(np.float32)
            idx = np.nonzero(done)[0]
            self._pos[idx] = 0.0
            self._t[idx] = 0
        return self._pos.astype(np.float32), reward, done, info


def sample_point_goal(rng: np.random.Generator) -> Tuple[float, float]:
    """Goals on the unit half-circle (ref: the point-robot task
    distribution in the MAML paper's experiments)."""
    ang = rng.uniform(0, np.pi)
    r = rng.uniform(0.3, 0.7)
    return (float(r * np.cos(ang)), float(r * np.sin(ang)))


def _policy_init(rng, obs_dim: int, action_dim: int,
                 hidden: Tuple[int, ...]):
    import jax
    import jax.numpy as jnp

    p = {}
    last = obs_dim
    ks = jax.random.split(rng, len(hidden) + 1)
    for i, h in enumerate(hidden):
        p[f"w{i}"] = jax.random.normal(ks[i], (last, h),
                                       jnp.float32) * np.sqrt(2.0 / last)
        p[f"b{i}"] = jnp.zeros((h,), jnp.float32)
        last = h
    p["w_mu"] = jax.random.normal(ks[-1], (last, action_dim),
                                  jnp.float32) * 0.01
    p["b_mu"] = jnp.zeros((action_dim,), jnp.float32)
    p["log_std"] = jnp.full((action_dim,), -0.7, jnp.float32)
    return p


def _mu_np(p: Dict[str, np.ndarray], x: np.ndarray) -> np.ndarray:
    i = 0
    while f"w{i}" in p:
        x = np.tanh(x @ p[f"w{i}"] + p[f"b{i}"])
        i += 1
    return x @ p["w_mu"] + p["b_mu"]


class MAMLTaskWorker:
    """One actor = one task: holds the env, resamples its task on
    request, and collects full-episode batches with given parameters
    (Gaussian policy, actions sampled worker-side)."""

    def __init__(self, num_envs: int, episodes_per_rollout: int,
                 seed: int = 0, env_creator=None,
                 task_sampler=None):
        self._rng = np.random.default_rng(seed)
        if env_creator is not None:
            self.env = cloudpickle.loads(env_creator)(
                num_envs=num_envs, seed=seed)
        else:
            self.env = PointGoalVecEnv(num_envs=num_envs, seed=seed)
        self._task_sampler = (cloudpickle.loads(task_sampler)
                              if task_sampler else sample_point_goal)
        self.episodes_per_rollout = episodes_per_rollout

    def resample_task(self) -> Any:
        task = self._task_sampler(self._rng)
        self.env.set_task(task)
        return task

    def set_task(self, task) -> Any:
        self.env.set_task(task)
        return task

    def rollout(self, params: Dict) -> Dict[str, np.ndarray]:
        """-> [n_episodes, T, ...] arrays (full fixed-length episodes —
        the inner/outer losses need per-episode reward-to-go)."""
        p = {k: np.asarray(v, np.float32) for k, v in params.items()}
        std = np.exp(p["log_std"])
        env = self.env
        T = env.EPISODE_LEN
        rounds = self.episodes_per_rollout
        n = env.num_envs
        obs_b = np.empty((rounds, T, n, env.obs_dim), np.float32)
        act_b = np.empty((rounds, T, n, env.action_dim), np.float32)
        rew_b = np.empty((rounds, T, n), np.float32)
        for e in range(rounds):
            obs = env.reset()
            for t in range(T):
                mu = _mu_np(p, obs)
                a = mu + self._rng.normal(0, 1, mu.shape) * std
                obs_b[e, t], act_b[e, t] = obs, a
                obs, r, done, _ = env.step(a)
                rew_b[e, t] = r
        # [rounds, T, n, ...] -> [rounds*n episodes, T, ...]
        def eps(x):
            return np.swapaxes(x, 1, 2).reshape(rounds * n, T,
                                                *x.shape[3:])

        return {"obs": eps(obs_b), "actions": eps(act_b),
                "rewards": eps(rew_b)}


@dataclass
class MAMLConfig:
    """ref: maml.py MAMLConfig (inner_adaptation_steps=1, inner_lr,
    maml_optimizer_stepsize, rollout_fragment_length per task)."""
    num_tasks: int = 4                # parallel task workers
    num_envs_per_worker: int = 8
    episodes_per_rollout: int = 2     # episodes per env per phase
    inner_lr: float = 0.1             # alpha
    outer_lr: float = 1e-3            # beta (meta Adam)
    gamma: float = 0.99
    first_order: bool = False         # FOMAML when True
    hidden: tuple = (64, 64)
    env_creator: Optional[Callable] = None
    task_sampler: Optional[Callable] = None
    seed: int = 0
    worker_resources: Dict[str, float] = field(default_factory=dict)

    def build(self) -> "MAML":
        return MAML(self)


class MAMLLearner:
    """adapt(): vmapped inner updates; meta_update(): grad through
    them. Both single jitted dispatches."""

    def __init__(self, obs_dim: int, action_dim: int, c: MAMLConfig):
        import functools

        import jax
        import jax.numpy as jnp
        import optax

        self.params = _policy_init(jax.random.PRNGKey(c.seed), obs_dim,
                                   action_dim, tuple(c.hidden))
        self.optimizer = optax.adam(c.outer_lr)
        self.opt_state = self.optimizer.init(self.params)

        def mu_fn(p, x):
            i = 0
            while f"w{i}" in p:
                x = jnp.tanh(x @ p[f"w{i}"] + p[f"b{i}"])
                i += 1
            return x @ p["w_mu"] + p["b_mu"]

        def pg_loss(p, batch):
            """REINFORCE with discounted reward-to-go, episodes
            [E, T, ...] (ref: maml policy's surrogate)."""
            obs, acts, rews = (batch["obs"], batch["actions"],
                               batch["rewards"])
            mu = mu_fn(p, obs)
            std = jnp.exp(p["log_std"])
            logp = -0.5 * jnp.sum(
                ((acts - mu) / std) ** 2
                + 2 * p["log_std"] + jnp.log(2 * jnp.pi), axis=-1)
            # discounted rewards-to-go along T
            def disc(carry, r):
                g = r + c.gamma * carry
                return g, g

            _, rtg = jax.lax.scan(disc, jnp.zeros(rews.shape[0]),
                                  rews.swapaxes(0, 1)[::-1])
            rtg = rtg[::-1].swapaxes(0, 1)            # [E, T]
            # per-TIMESTEP baseline: rtg is dominated by how many steps
            # remain, so a global mean would turn the advantage into a
            # time ramp that drowns the action signal (the role of the
            # reference MAML's fitted linear-feature baseline)
            base = rtg.mean(axis=0, keepdims=True)    # [1, T]
            adv = (rtg - base) / (rtg.std() + 1e-8)
            return -jnp.mean(logp * jax.lax.stop_gradient(adv))

        def adapt_one(theta, batch):
            g = jax.grad(pg_loss)(theta, batch)
            # clip the inner gradient: a raw REINFORCE step at
            # inner_lr=0.1 sends log_std to overflow within a few
            # compounded adaptations (measured)
            norm = jnp.sqrt(sum(jnp.sum(x * x)
                                for x in jax.tree.leaves(g)))
            scale = jnp.minimum(1.0, 1.0 / (norm + 1e-8))
            theta = jax.tree.map(
                lambda p, gg: p - c.inner_lr * scale * gg, theta, g)
            return {**theta,
                    "log_std": jnp.clip(theta["log_std"], -3.0, 0.5)}

        @jax.jit
        def adapt(theta, batches):
            """batches: [num_tasks, ...] stacked -> per-task theta'."""
            return jax.vmap(lambda b: adapt_one(theta, b))(batches)

        def meta_loss(theta, pre_batches, post_batches):
            def per_task(pre, post):
                theta_i = adapt_one(theta, pre)
                if c.first_order:
                    theta_i = jax.lax.stop_gradient(theta_i)
                return pg_loss(theta_i, post)

            return jnp.mean(jax.vmap(per_task)(pre_batches,
                                               post_batches))

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def meta_update(theta, opt_state, pre_batches, post_batches):
            loss, grads = jax.value_and_grad(meta_loss)(
                theta, pre_batches, post_batches)
            updates, opt_state = self.optimizer.update(grads, opt_state)
            theta = optax.apply_updates(theta, updates)
            return theta, opt_state, loss

        self._adapt = adapt
        self._meta_update = meta_update
        self._pg_loss = pg_loss

    def adapt(self, pre_batches: Dict[str, np.ndarray],
              params: Optional[Dict] = None) -> List[Dict]:
        """Per-task inner updates from `params` (default: the
        meta-parameters) — multi-step adaptation passes the previous
        step's adapted params back in."""
        import jax
        import jax.numpy as jnp

        theta = (self.params if params is None
                 else jax.tree.map(jnp.asarray, params))
        stacked = {k: jnp.asarray(v) for k, v in pre_batches.items()}
        thetas = self._adapt(theta, stacked)
        thetas_np = jax.device_get(thetas)
        n = next(iter(thetas_np.values())).shape[0]
        return [{k: v[i] for k, v in thetas_np.items()}
                for i in range(n)]

    def meta_update(self, pre_batches, post_batches) -> float:
        import jax.numpy as jnp

        pre = {k: jnp.asarray(v) for k, v in pre_batches.items()}
        post = {k: jnp.asarray(v) for k, v in post_batches.items()}
        self.params, self.opt_state, loss = self._meta_update(
            self.params, self.opt_state, pre, post)
        return float(loss)


class MAML:
    """Tune-trainable MAML driver over task-worker actors."""

    def __init__(self, config: MAMLConfig):
        self.config = c = config
        env_blob = (cloudpickle.dumps(c.env_creator)
                    if c.env_creator else None)
        task_blob = (cloudpickle.dumps(c.task_sampler)
                     if c.task_sampler else None)
        cls = ray_tpu.remote(MAMLTaskWorker)
        opts = worker_opts(c.worker_resources)
        self.workers = [
            cls.options(**opts).remote(
                c.num_envs_per_worker, c.episodes_per_rollout,
                seed=c.seed + 97 * i, env_creator=env_blob,
                task_sampler=task_blob)
            for i in range(c.num_tasks)]
        probe = (c.env_creator(num_envs=1, seed=0) if c.env_creator
                 else PointGoalVecEnv(num_envs=1))
        self.learner = MAMLLearner(probe.obs_dim, probe.action_dim, c)
        self._iteration = 0

    @staticmethod
    def _stack(batches: List[Dict[str, np.ndarray]]
               ) -> Dict[str, np.ndarray]:
        return {k: np.stack([b[k] for b in batches])
                for k in batches[0]}

    def train(self) -> Dict[str, Any]:
        import jax

        t0 = time.monotonic()
        # new tasks each meta-iteration (ref: maml.py resampling)
        ray_tpu.get([w.resample_task.remote() for w in self.workers],
                    timeout=120)
        theta_ref = ray_tpu.put(jax.device_get(self.learner.params))
        pre = ray_tpu.get(
            [w.rollout.remote(theta_ref) for w in self.workers],
            timeout=600)
        pre_stacked = self._stack(pre)
        adapted = self.learner.adapt(pre_stacked)
        post = ray_tpu.get(
            [w.rollout.remote(ray_tpu.put(adapted[i]))
             for i, w in enumerate(self.workers)], timeout=600)
        post_stacked = self._stack(post)
        loss = self.learner.meta_update(pre_stacked, post_stacked)
        self._iteration += 1
        pre_rew = float(np.mean([b["rewards"].sum(axis=1).mean()
                                 for b in pre]))
        post_rew = float(np.mean([b["rewards"].sum(axis=1).mean()
                                  for b in post]))
        return {"training_iteration": self._iteration,
                "meta_loss": loss,
                "pre_adaptation_reward": pre_rew,
                "post_adaptation_reward": post_rew,
                "adaptation_gain": post_rew - pre_rew,
                "episode_reward_mean": post_rew,
                "time_this_iter_s": time.monotonic() - t0}

    def adapt_to(self, task, adaptation_steps: int = 1) -> Dict:
        """Meta-test: adapt the meta-parameters to ONE given task;
        returns {pre_reward, post_reward, params}."""
        import jax

        w = self.workers[0]
        ray_tpu.get(w.set_task.remote(task), timeout=60)
        theta = jax.device_get(self.learner.params)
        pre = ray_tpu.get(w.rollout.remote(ray_tpu.put(theta)),
                          timeout=600)
        params = theta
        batch = pre
        for _ in range(adaptation_steps):
            # compound: each step adapts from the PREVIOUS step's params
            params = self.learner.adapt(self._stack([batch]),
                                        params=params)[0]
            batch = ray_tpu.get(
                w.rollout.remote(ray_tpu.put(params)), timeout=600)
        return {"pre_reward": float(pre["rewards"].sum(axis=1).mean()),
                "post_reward": float(
                    batch["rewards"].sum(axis=1).mean()),
                "params": params}

    # -- Tune-trainable surface ------------------------------------------

    def save(self) -> Dict:
        import jax

        return {"params": jax.device_get(self.learner.params),
                "opt_state": jax.device_get(self.learner.opt_state),
                "iteration": self._iteration}

    def restore(self, ckpt: Dict) -> None:
        import jax
        import jax.numpy as jnp

        self.learner.params = jax.tree.map(jnp.asarray, ckpt["params"])
        if "opt_state" in ckpt:
            self.learner.opt_state = jax.tree.map(jnp.asarray,
                                                  ckpt["opt_state"])
        self._iteration = int(ckpt.get("iteration", 0))

    def stop(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
