"""Dreamer — model-based RL: learn a latent world model, train the
policy inside its imagination (Hafner et al., DreamerV3 2023).

ref: rllib/algorithms/dreamerv3/dreamerv3.py + torch/dreamerv3_torch_model
(RSSM with categorical latents, symlog heads, KL balancing with free
bits, imagination-trained actor-critic with percentile return
normalization). This is the "lite" shape of that recipe for vector
observations: GRU-deterministic + (K categoricals x C classes)
stochastic latent, symlog MSE for reconstruction/reward/value instead
of two-hot, REINFORCE actor on imagined lambda-returns.

House TPU shape: rollout actors run the RSSM policy as numpy (GRU +
posterior + actor samples — np_policy.py rationale, mirroring the
learner's jax cells bit-for-bit in structure), the driver keeps a
sequence replay (zero-initialized latent per sequence: the posterior
re-syncs from observations within a few steps), and the ENTIRE
world-model + imagination actor-critic update block for all K sequence
minibatches runs as one jitted lax.scan dispatch per train() call
(docs/PERF_NOTES.md learner rule)."""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle
import numpy as np

import ray_tpu

from .replay_buffer import ReplayBuffer
from .rollout_worker import EnvWorkerBase, worker_opts


# ---------------------------------------------------------------------------
# symlog + parameter init
# ---------------------------------------------------------------------------


def symlog_np(x):
    return np.sign(x) * np.log1p(np.abs(x))


def _dense(rng, shapes: Dict[str, tuple]) -> Dict:
    import jax
    import jax.numpy as jnp

    ks = jax.random.split(rng, len(shapes))
    out = {}
    for k_rng, (name, shp) in zip(ks, sorted(shapes.items())):
        if name.endswith("_b"):
            out[name] = jnp.zeros(shp, jnp.float32)
        else:
            out[name] = jax.random.normal(k_rng, shp, jnp.float32) \
                * np.sqrt(2.0 / shp[0])
    return out


def init_dreamer_params(rng, obs_dim: int, num_actions: int, *,
                        deter: int, n_cat: int, n_cls: int,
                        hidden: int) -> Dict:
    import jax

    z_dim = n_cat * n_cls
    ks = jax.random.split(rng, 8)
    p = {}
    # encoder obs -> emb
    p.update({f"enc_{k}": v for k, v in _dense(ks[0], {
        "w0": (obs_dim, hidden), "w0_b": (hidden,),
        "w1": (hidden, hidden), "w1_b": (hidden,)}).items()})
    # GRU: x = [z, a_onehot] -> 3*deter gates
    p.update({f"gru_{k}": v for k, v in _dense(ks[1], {
        "wx": (z_dim + num_actions, 3 * deter),
        "wh": (deter, 3 * deter), "wx_b": (3 * deter,)}).items()})
    # prior h -> z logits ; posterior [h, emb] -> z logits
    p.update({f"prior_{k}": v for k, v in _dense(ks[2], {
        "w0": (deter, hidden), "w0_b": (hidden,),
        "w1": (hidden, z_dim), "w1_b": (z_dim,)}).items()})
    p.update({f"post_{k}": v for k, v in _dense(ks[3], {
        "w0": (deter + hidden, hidden), "w0_b": (hidden,),
        "w1": (hidden, z_dim), "w1_b": (z_dim,)}).items()})
    # decoder / reward / continue heads on [h, z]
    s_dim = deter + z_dim
    p.update({f"dec_{k}": v for k, v in _dense(ks[4], {
        "w0": (s_dim, hidden), "w0_b": (hidden,),
        "w1": (hidden, obs_dim), "w1_b": (obs_dim,)}).items()})
    # reward/continue condition on (state, action): "taking a at s
    # yields r and ends/continues the episode". This sidesteps the
    # terminal-state problem entirely — auto-reset envs never hand the
    # terminal observation out, so a state-only cont head would be
    # trained on post-reset states instead (which taught the model that
    # FRESH states terminate — the round-5 probe's failure mode)
    p.update({f"rew_{k}": v for k, v in _dense(ks[5], {
        "w0": (s_dim + num_actions, hidden), "w0_b": (hidden,),
        "w1": (hidden, 1), "w1_b": (1,)}).items()})
    p.update({f"cont_{k}": v for k, v in _dense(ks[6], {
        "w0": (s_dim + num_actions, hidden), "w0_b": (hidden,),
        "w1": (hidden, 1), "w1_b": (1,)}).items()})
    return p


def init_ac_params(rng, deter: int, z_dim: int, num_actions: int,
                   hidden: int) -> Dict:
    import jax

    s_dim = deter + z_dim
    ks = jax.random.split(rng, 2)
    p = {}
    p.update({f"actor_{k}": v for k, v in _dense(ks[0], {
        "w0": (s_dim, hidden), "w0_b": (hidden,),
        "w1": (hidden, num_actions), "w1_b": (num_actions,)}).items()})
    # small-init the value head so early returns don't swing the actor
    ac = _dense(ks[1], {"w0": (s_dim, hidden), "w0_b": (hidden,),
                        "w1": (hidden, 1), "w1_b": (1,)})
    ac["w1"] = ac["w1"] * 0.01
    p.update({f"critic_{k}": v for k, v in ac.items()})
    return p


# ---------------------------------------------------------------------------
# numpy inference (rollout side) — mirrors the jax cells in the learner
# ---------------------------------------------------------------------------


def _np_mlp2(p, prefix, x, act_last=False):
    h = np.maximum(x @ p[f"{prefix}_w0"] + p[f"{prefix}_w0_b"], 0.0)
    out = h @ p[f"{prefix}_w1"] + p[f"{prefix}_w1_b"]
    return np.maximum(out, 0.0) if act_last else out


def _np_gru(p, x, h):
    z = x @ p["gru_wx"] + h @ p["gru_wh"] + p["gru_wx_b"]
    G = h.shape[1]
    r = 1.0 / (1.0 + np.exp(-z[:, :G]))
    u = 1.0 / (1.0 + np.exp(-(z[:, G:2 * G] - 1.0)))  # update-gate bias
    c = np.tanh(z[:, 2 * G:] + (r - 1.0) * (h @ p["gru_wh"][:, 2 * G:]))
    return u * h + (1.0 - u) * c


def np_policy_step(p, ac, obs, h, z_prev, a_prev_onehot, rng, n_cat, n_cls,
                   greedy=False):
    """One rollout inference step -> (action, h, z). Mirrors the
    learner's cells; unimix 1% on the posterior like the learner."""
    x = np.concatenate([z_prev, a_prev_onehot], axis=1)
    h = _np_gru(p, x, h)
    emb = _np_mlp2(p, "enc", obs.astype(np.float32), act_last=True)
    logits = _np_mlp2(p, "post", np.concatenate([h, emb], axis=1))
    B = len(obs)
    logits = logits.reshape(B, n_cat, n_cls)
    ex = np.exp(logits - logits.max(axis=2, keepdims=True))
    probs = ex / ex.sum(axis=2, keepdims=True)
    probs = 0.99 * probs + 0.01 / n_cls
    # sample each categorical
    cdf = probs.cumsum(axis=2)
    u = rng.random((B, n_cat, 1))
    idx = (u > cdf).sum(axis=2)
    z = np.eye(n_cls, dtype=np.float32)[idx].reshape(B, -1)
    s = np.concatenate([h, z], axis=1)
    a_logits = _np_mlp2(ac, "actor", s)
    if greedy:
        a = a_logits.argmax(axis=1)
    else:
        ex = np.exp(a_logits - a_logits.max(axis=1, keepdims=True))
        ap = ex / ex.sum(axis=1, keepdims=True)
        cdf = ap.cumsum(axis=1)
        a = (rng.random((B, 1)) > cdf).sum(axis=1)
    return a.astype(np.int64), h, z


class DreamerRolloutWorker(EnvWorkerBase):
    """Samples with the latent-state policy; emits fixed-length
    sequence windows (obs/actions/rewards/dones), zero-init latent per
    sequence on the learner side."""

    def __init__(self, env_name: str, num_envs: int, rollout_len: int,
                 seq_len: int, deter: int, n_cat: int, n_cls: int,
                 seed: int = 0, env_creator=None):
        super().__init__(env_name, num_envs, rollout_len, seed,
                         env_creator)
        if rollout_len % seq_len != 0:
            raise ValueError("rollout_len must be a multiple of seq_len")
        self.seq_len = seq_len
        self.n_cat, self.n_cls = n_cat, n_cls
        n = self.env.num_envs
        self._h = np.zeros((n, deter), np.float32)
        self._z = np.zeros((n, n_cat * n_cls), np.float32)
        self._a_prev = np.zeros((n, self.env.num_actions), np.float32)

    def sample(self, wm_params: Dict, ac_params: Dict) -> Dict:
        p = {k: np.asarray(v, np.float32) for k, v in wm_params.items()}
        ac = {k: np.asarray(v, np.float32) for k, v in ac_params.items()}
        T, L = self.rollout_len, self.seq_len
        n, A = self.env.num_envs, self.env.num_actions
        obs_buf = np.empty((T, n, self.env.obs_dim), np.float32)
        act_buf = np.empty((T, n), np.int64)
        rew_buf = np.empty((T, n), np.float32)
        done_buf = np.empty((T, n), np.bool_)
        obs = self._obs
        eye = np.eye(A, dtype=np.float32)
        for t in range(T):
            a, self._h, self._z = np_policy_step(
                p, ac, obs, self._h, self._z, self._a_prev, self._rng,
                self.n_cat, self.n_cls)
            obs_buf[t], act_buf[t] = obs, a
            self._a_prev = eye[a]
            obs, reward, done, info = self.env.step(a)
            rew_buf[t], done_buf[t] = reward, done
            self._track_returns(reward, done)
            if done.any():
                idx = np.nonzero(done)[0]
                self._h[idx] = 0.0
                self._z[idx] = 0.0
                self._a_prev[idx] = 0.0
                if "truncated" in info:
                    # model learns continue-probability: time-limit
                    # truncation is not a terminal (cont stays 1)
                    done_buf[t] &= ~info["truncated"]
        self._obs = obs
        n_win = T // L

        def rows(a):
            w = np.stack([a[i * L:(i + 1) * L] for i in range(n_win)])
            return np.swapaxes(w, 1, 2).reshape(n_win * n, L,
                                                *a.shape[2:])

        return {"obs": rows(obs_buf), "actions": rows(act_buf),
                "rewards": rows(rew_buf), "dones": rows(done_buf)}


@dataclass
class DreamerConfig:
    """ref: dreamerv3.py DreamerV3Config (model_size ladder, horizon 15,
    kl balancing 0.5/0.1, free bits 1.0, unimix 0.01)."""
    env: str = "CartPole-v1"
    env_creator: Optional[Callable] = None
    num_rollout_workers: int = 1
    num_envs_per_worker: int = 8
    rollout_fragment_length: int = 64
    seq_len: int = 16
    deter: int = 128
    n_cat: int = 8
    n_cls: int = 8
    hidden: int = 128
    gamma: float = 0.997
    lam: float = 0.95
    horizon: int = 15
    wm_lr: float = 3e-4
    ac_lr: float = 1e-4
    free_bits: float = 1.0
    kl_dyn_scale: float = 0.5
    kl_rep_scale: float = 0.1
    entropy_coeff: float = 3e-3
    buffer_size: int = 4_000       # sequences
    train_batch_size: int = 16     # sequences per minibatch
    num_updates_per_iter: int = 4
    learning_starts: int = 100     # sequences
    seed: int = 0
    checkpoint_replay_buffer: bool = True
    worker_resources: Dict[str, float] = field(default_factory=dict)

    def build(self) -> "Dreamer":
        return Dreamer(self)


class DreamerLearner:
    """World-model + imagination actor-critic, fused per-iteration."""

    def __init__(self, obs_dim: int, num_actions: int, c: DreamerConfig):
        import functools

        import jax
        import jax.numpy as jnp
        import optax

        self.c = c
        z_dim = c.n_cat * c.n_cls
        self.wm = init_dreamer_params(
            jax.random.PRNGKey(c.seed), obs_dim, num_actions,
            deter=c.deter, n_cat=c.n_cat, n_cls=c.n_cls, hidden=c.hidden)
        self.ac = init_ac_params(jax.random.PRNGKey(c.seed + 1), c.deter,
                                 z_dim, num_actions, c.hidden)
        self.opt_wm = optax.chain(optax.clip_by_global_norm(100.0),
                                  optax.adam(c.wm_lr))
        self.opt_ac = optax.chain(optax.clip_by_global_norm(10.0),
                                  optax.adam(c.ac_lr))
        self.s_wm = self.opt_wm.init(self.wm)
        self.s_ac = self.opt_ac.init(self.ac)
        self._key = jax.random.PRNGKey(c.seed + 2)
        self.num_updates = 0
        A = num_actions

        def mlp2(p, prefix, x, act_last=False):
            h = jax.nn.relu(x @ p[f"{prefix}_w0"] + p[f"{prefix}_w0_b"])
            out = h @ p[f"{prefix}_w1"] + p[f"{prefix}_w1_b"]
            return jax.nn.relu(out) if act_last else out

        def gru(p, x, h):
            zg = x @ p["gru_wx"] + h @ p["gru_wh"] + p["gru_wx_b"]
            G = h.shape[1]
            r = jax.nn.sigmoid(zg[:, :G])
            u = jax.nn.sigmoid(zg[:, G:2 * G] - 1.0)
            cand = jnp.tanh(zg[:, 2 * G:]
                            + (r - 1.0) * (h @ p["gru_wh"][:, 2 * G:]))
            return u * h + (1.0 - u) * cand

        def symlog(x):
            return jnp.sign(x) * jnp.log1p(jnp.abs(x))

        def symexp(x):
            return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)

        def bounded(x, lim):
            """Smooth clamp in symlog space — the lite stand-in for the
            reference's bounded two-hot bins: an exploited model can
            hallucinate at most symexp(lim) per step, which is what kept
            the un-clamped probe's imagined returns from 2e7 blowups."""
            return lim * jnp.tanh(x / lim)

        def rew_out(p, sa):
            return symexp(bounded(mlp2(p, "rew", sa)[..., 0], 5.0))

        def val_out(p, s):
            return symexp(bounded(mlp2(p, "critic", s)[..., 0], 7.0))

        def z_dist(logits):
            lg = logits.reshape(*logits.shape[:-1], c.n_cat, c.n_cls)
            probs = 0.99 * jax.nn.softmax(lg) + 0.01 / c.n_cls
            return jnp.log(probs)

        def sample_z(key, logp):
            idx = jax.random.categorical(key, logp)
            one = jax.nn.one_hot(idx, c.n_cls)
            probs = jnp.exp(logp)
            st = one + probs - jax.lax.stop_gradient(probs)  # ST grads
            return st.reshape(*st.shape[:-2], z_dim)

        def kl_cat(lp, lq):
            """KL(p || q) summed over categoricals."""
            return (jnp.exp(lp) * (lp - lq)).sum(-1).sum(-1)

        def wm_loss(wm, batch, key):
            obs = batch["obs"]                      # [B, L, obs]
            acts = jax.nn.one_hot(batch["actions"], A)  # [B, L, A]
            d = batch["dones"].astype(jnp.float32)  # [B, L]
            B, L = d.shape
            emb = mlp2(wm, "enc", obs, act_last=True)
            a_prev = jnp.concatenate(
                [jnp.zeros((B, 1, A)), acts[:, :-1]], axis=1)
            resets = jnp.concatenate(
                [jnp.zeros((B, 1)), d[:, :-1]], axis=1)
            keys = jax.random.split(key, L)

            def step(carry, xs):
                h, z = carry
                emb_t, a_t, reset_t, k = xs
                keep = (1.0 - reset_t)[:, None]
                h, z = h * keep, z * keep
                a_t = a_t * keep
                h = gru(wm, jnp.concatenate([z, a_t], axis=1), h)
                prior_lp = z_dist(mlp2(wm, "prior", h))
                post_lp = z_dist(mlp2(
                    wm, "post", jnp.concatenate([h, emb_t], axis=1)))
                z = sample_z(k, post_lp)
                return (h, z), (h, z, prior_lp, post_lp)

            h0 = jnp.zeros((B, c.deter))
            z0 = jnp.zeros((B, z_dim))
            _, (hs, zs, prior_lp, post_lp) = jax.lax.scan(
                step, (h0, z0),
                (emb.swapaxes(0, 1), a_prev.swapaxes(0, 1),
                 resets.swapaxes(0, 1), keys))
            # [L, B, ...] -> [B, L, ...]
            hs, zs = hs.swapaxes(0, 1), zs.swapaxes(0, 1)
            prior_lp = prior_lp.swapaxes(0, 1)
            post_lp = post_lp.swapaxes(0, 1)
            s = jnp.concatenate([hs, zs], axis=-1)
            recon = mlp2(wm, "dec", s)
            l_rec = jnp.mean((recon - symlog(obs)) ** 2)
            # reward/continue heads on (s_t, a_t): r_t and 1-d_t for
            # EVERY step — no terminal-obs needed (see init note)
            sa = jnp.concatenate([s, acts], axis=-1)
            rew_pred = bounded(mlp2(wm, "rew", sa)[..., 0], 5.0)
            l_rew = jnp.mean((rew_pred
                              - symlog(batch["rewards"])) ** 2)
            cont_logit = mlp2(wm, "cont", sa)[..., 0]
            cont_tgt = 1.0 - d
            l_cont = jnp.mean(optax.sigmoid_binary_cross_entropy(
                cont_logit, cont_tgt))
            # KL balancing with free bits (ref dreamerv3 kl_dyn/kl_rep)
            kl_dyn = kl_cat(jax.lax.stop_gradient(post_lp), prior_lp)
            kl_rep = kl_cat(post_lp, jax.lax.stop_gradient(prior_lp))
            l_kl = (c.kl_dyn_scale * jnp.maximum(kl_dyn, c.free_bits)
                    + c.kl_rep_scale
                    * jnp.maximum(kl_rep, c.free_bits)).mean()
            loss = l_rec + l_rew + l_cont + l_kl
            stats = {"wm_loss": loss, "recon_loss": l_rec,
                     "reward_loss": l_rew, "kl": kl_dyn.mean()}
            # flattened posterior states seed imagination
            return loss, (jax.lax.stop_gradient(
                s.reshape(B * L, -1)), stats)

        def imagine(wm, ac, s0, key):
            """Roll the actor through the model: returns imagined
            states [H+1, N, s], actions [H, N], rewards/conts [H, N]."""
            def step(carry, k):
                s = carry
                a_logits = mlp2(ac, "actor", s)
                a = jax.random.categorical(k, a_logits)
                a_one = jax.nn.one_hot(a, A)
                sa = jnp.concatenate([s, a_one], axis=1)
                r = rew_out(wm, sa)
                cont = jax.nn.sigmoid(mlp2(wm, "cont", sa)[:, 0])
                h, z = s[:, :c.deter], s[:, c.deter:]
                h = gru(wm, jnp.concatenate([z, a_one], axis=1), h)
                k2 = jax.random.fold_in(k, 1)
                z = sample_z(k2, z_dist(mlp2(wm, "prior", h)))
                s_next = jnp.concatenate([h, z], axis=1)
                return s_next, (s_next, a, a_logits, r, cont)

            keys = jax.random.split(key, c.horizon)
            _, (ss, a_s, alog, rs, conts) = jax.lax.scan(step, s0, keys)
            return ss, a_s, alog, rs, conts

        def ac_loss(ac, wm, s0, key):
            ss, a_s, alog, rs, conts = imagine(wm, ac, s0, key)
            # full state sequence INCLUDING the replay-posterior start:
            # s_0..s_H, so the baseline for the action taken at s_t is
            # v(s_t) and the bootstrap for step t is v(s_{t+1})
            ss_full = jnp.concatenate([s0[None], ss], axis=0)  # [H+1,N,s]
            vs = val_out(ac, ss_full)                 # v(s_0)..v(s_H)
            disc = c.gamma * conts
            # lambda-returns, backward: R_t = r_t + d_t((1-lam)v_{t+1}
            #                                           + lam R_{t+1})
            def lam_step(nxt, xs):
                r, dsc, v = xs
                ret = r + dsc * ((1 - c.lam) * v + c.lam * nxt)
                return ret, ret

            _, rets = jax.lax.scan(
                lam_step, vs[-1],
                (rs[::-1], disc[::-1], vs[1:][::-1]))
            rets = rets[::-1]                         # R_0..R_{H-1}
            base = vs[:-1]                            # v(s_0)..v(s_{H-1})
            # percentile return normalization, per update (ref
            # dreamerv3: scale = max(1, P95 - P5) of the return batch)
            scale = jnp.maximum(
                1.0, jnp.percentile(rets, 95) - jnp.percentile(rets, 5))
            adv = jax.lax.stop_gradient((rets - base) / scale)
            logp = jax.nn.log_softmax(alog)
            lp_a = jnp.take_along_axis(
                logp, a_s[..., None], axis=-1)[..., 0]
            # discounted weights so early imagined steps dominate
            w = jnp.cumprod(
                jnp.concatenate([jnp.ones((1,) + disc.shape[1:]),
                                 disc[:-1]], axis=0), axis=0)
            w = jax.lax.stop_gradient(w)
            ent = -(jnp.exp(logp) * logp).sum(-1)
            actor_loss = -(w * (lp_a * adv
                                + c.entropy_coeff * ent)).mean()
            v_pred = bounded(mlp2(ac, "critic", ss_full[:-1])[..., 0],
                             7.0)
            critic_loss = jnp.mean(
                w * (v_pred - jax.lax.stop_gradient(
                    symlog(rets))) ** 2)
            loss = actor_loss + critic_loss
            return loss, {"actor_loss": actor_loss,
                          "critic_loss": critic_loss,
                          "imag_return": rets.mean(),
                          "entropy": ent.mean()}

        def one_update(carry, xs):
            wm, ac, s_wm, s_ac, key = carry
            batch = xs
            key, k1, k2 = jax.random.split(key, 3)
            (wl, (s0, wm_stats)), wg = jax.value_and_grad(
                wm_loss, has_aux=True)(wm, batch, k1)
            up, s_wm = self.opt_wm.update(wg, s_wm, wm)
            wm = optax.apply_updates(wm, up)
            (al, ac_stats), ag = jax.value_and_grad(
                ac_loss, has_aux=True)(ac, wm, s0, k2)
            up, s_ac = self.opt_ac.update(ag, s_ac, ac)
            ac = optax.apply_updates(ac, up)
            return (wm, ac, s_wm, s_ac, key), {**wm_stats, **ac_stats}

        @functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
        def update_many(wm, ac, s_wm, s_ac, key, batches):
            (wm, ac, s_wm, s_ac, key), stats = jax.lax.scan(
                one_update, (wm, ac, s_wm, s_ac, key), batches)
            return wm, ac, s_wm, s_ac, key, jax.tree.map(jnp.mean, stats)

        self._update_many = update_many

    def update(self, stacked: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax
        import jax.numpy as jnp

        K = stacked["rewards"].shape[0]
        batches = {k: jnp.asarray(v) for k, v in stacked.items()}
        (self.wm, self.ac, self.s_wm, self.s_ac, self._key,
         stats) = self._update_many(self.wm, self.ac, self.s_wm,
                                    self.s_ac, self._key, batches)
        self.num_updates += K
        return {k: float(v) for k, v in jax.device_get(stats).items()}

    def params_np(self):
        import jax

        return jax.device_get(self.wm), jax.device_get(self.ac)


class Dreamer:
    """Tune-trainable Dreamer driver (DQN shape, sequence replay)."""

    def __init__(self, config: DreamerConfig):
        self.config = c = config
        creator_blob = (cloudpickle.dumps(c.env_creator)
                        if c.env_creator else None)
        cls = ray_tpu.remote(DreamerRolloutWorker)
        opts = worker_opts(c.worker_resources)
        self.workers: List = [
            cls.options(**opts).remote(
                c.env, c.num_envs_per_worker, c.rollout_fragment_length,
                c.seq_len, c.deter, c.n_cat, c.n_cls,
                seed=c.seed + 1000 * i, env_creator=creator_blob)
            for i in range(c.num_rollout_workers)]
        info = ray_tpu.get(self.workers[0].env_info.remote(), timeout=180)
        self.learner = DreamerLearner(info["obs_dim"],
                                      info["num_actions"], c)
        self.buffer = ReplayBuffer(c.buffer_size, seed=c.seed)
        self._iteration = 0
        self._total_steps = 0
        self._total_episodes = 0
        self._recent: List[float] = []

    def train(self) -> Dict[str, Any]:
        c = self.config
        t0 = time.monotonic()
        wm_np, ac_np = self.learner.params_np()
        wm_ref, ac_ref = ray_tpu.put(wm_np), ray_tpu.put(ac_np)
        batches = ray_tpu.get(
            [w.sample.remote(wm_ref, ac_ref) for w in self.workers],
            timeout=300)
        steps = 0
        for b in batches:
            self.buffer.add(b)
            steps += b["rewards"].shape[0] * c.seq_len
        self._total_steps += steps
        stats: Dict[str, float] = {}
        if len(self.buffer) >= c.learning_starts:
            K, B = c.num_updates_per_iter, c.train_batch_size
            mb = self.buffer.sample(K * B)
            stacked = {k: v.reshape(K, B, *v.shape[1:])
                       for k, v in mb.items()}
            stats = self.learner.update(stacked)
        for rets in ray_tpu.get(
                [w.episode_returns.remote() for w in self.workers],
                timeout=60):
            self._recent.extend(rets)
            self._total_episodes += len(rets)
        self._recent = self._recent[-100:]
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "timesteps_total": self._total_steps,
            "timesteps_this_iter": steps,
            "episode_reward_mean": (float(np.mean(self._recent))
                                    if self._recent else float("nan")),
            "episodes_total": self._total_episodes,
            "num_updates": self.learner.num_updates,
            "buffer_sequences": len(self.buffer),
            "time_this_iter_s": time.monotonic() - t0,
            **stats,
        }

    # -- Tune-trainable surface ------------------------------------------

    def save(self) -> Dict:
        import jax

        L = self.learner
        ckpt = {"wm": jax.device_get(L.wm), "ac": jax.device_get(L.ac),
                "opt": jax.device_get((L.s_wm, L.s_ac)),
                "key": jax.device_get(L._key),
                "iteration": self._iteration,
                "total_steps": self._total_steps}
        if self.config.checkpoint_replay_buffer:
            ckpt["buffer"] = self.buffer.state()
        return ckpt

    def restore(self, ckpt: Dict) -> None:
        import jax
        import jax.numpy as jnp

        as_jnp = lambda t: jax.tree.map(jnp.asarray, t)  # noqa: E731
        L = self.learner
        L.wm = as_jnp(ckpt["wm"])
        L.ac = as_jnp(ckpt["ac"])
        if "opt" in ckpt:
            L.s_wm, L.s_ac = as_jnp(ckpt["opt"])
        if "key" in ckpt:
            L._key = jnp.asarray(ckpt["key"])
        self._iteration = int(ckpt.get("iteration", 0))
        self._total_steps = int(ckpt.get("total_steps", 0))
        if "buffer" in ckpt:
            self.buffer.restore(ckpt["buffer"])

    def stop(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
