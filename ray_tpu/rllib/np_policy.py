"""Numpy rollout-side policy inference.

Rollout actors deliberately never import jax: on a TPU host every extra
process initializing the backend pays seconds of startup and contends for
the chip, and for a (64, 64) fcnet a numpy forward is microseconds —
far below jit dispatch overhead, let alone a device round-trip per env
step. The learner (ray_tpu.rllib.learner) is the only RL component that
touches jax/TPU, mirroring the reference's rollout-on-CPU / learn-on-GPU
split (ref: rllib/evaluation/rollout_worker.py:660 sample loop;
rllib/core/learner/learner.py update on device).
"""
from __future__ import annotations

from typing import Dict, Tuple

import numpy as np


def ensure_numpy(params: Dict) -> Dict:
    """Normalize a param dict (possibly jax arrays off the object store)
    to float32 numpy once per rollout, so the per-step loop never pays a
    conversion."""
    return {k: np.asarray(v, dtype=np.float32) for k, v in params.items()}


def _conv2d_np(x: np.ndarray, w: np.ndarray, stride: int) -> np.ndarray:
    """VALID conv via stride-tricks im2col + one BLAS matmul (the numpy
    analog of lax.conv NHWC/HWIO). x [B,H,W,C] f32, w [kh,kw,cin,cout]."""
    B, H, W, C = x.shape
    kh, kw, ci, co = w.shape
    oh = (H - kh) // stride + 1
    ow = (W - kw) // stride + 1
    s0, s1, s2, s3 = x.strides
    patches = np.lib.stride_tricks.as_strided(
        x, (B, oh, ow, kh, kw, C),
        (s0, s1 * stride, s2 * stride, s1, s2, s3))
    out = patches.reshape(B * oh * ow, kh * kw * C) @ w.reshape(-1, co)
    return out.reshape(B, oh, ow, co)


def conv_layer_keys(params: Dict):
    """Ordered [(w_key, b_key, stride), ...] parsed from the conv{i}s{s}_w
    key grammar. THE single implementation — models.py (jax side) imports
    it from here, since this module deliberately has no jax dependency."""
    out = []
    i = 0
    while True:
        match = [k for k in params if k.startswith(f"conv{i}s")
                 and k.endswith("_w")]
        if not match:
            return out
        wk = match[0]
        out.append((wk, wk[:-2] + "_b", int(wk[len(f"conv{i}s"):-2])))
        i += 1


def forward_np(params: Dict, obs: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """obs [B, obs_dim] or [B,H,W,C] -> (logits [B, A], value [B]).
    Mirrors models.forward exactly (NatureCNN trunk for image obs, tanh
    hidden layers, separate heads)."""
    x = obs
    conv_keys = conv_layer_keys(params)
    if conv_keys:
        x = x.astype(np.float32) / 255.0 if x.dtype == np.uint8 \
            else x.astype(np.float32)
        for wk, bk, s in conv_keys:
            x = np.maximum(_conv2d_np(x, params[wk], s) + params[bk], 0.0)
        x = x.reshape(len(x), -1)
    i = 0
    while f"w{i}" in params:
        x = np.tanh(x @ params[f"w{i}"] + params[f"b{i}"])
        i += 1
    logits = x @ params["w_pi"] + params["b_pi"]
    value = (x @ params["w_v"] + params["b_v"])[:, 0]
    return logits, value


def sample_actions(params: Dict, obs: np.ndarray, rng: np.random.Generator
                   ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Rollout-side inference -> (actions, logp, values)."""
    logits, values = forward_np(params, obs)
    z = logits - logits.max(axis=1, keepdims=True)
    p = np.exp(z)
    p /= p.sum(axis=1, keepdims=True)
    u = rng.random((len(p), 1))
    actions = (p.cumsum(axis=1) < u).sum(axis=1).astype(np.int64)
    np.clip(actions, 0, p.shape[1] - 1, out=actions)
    logp = np.log(p[np.arange(len(p)), actions] + 1e-8)
    return actions, logp.astype(np.float32), values.astype(np.float32)
