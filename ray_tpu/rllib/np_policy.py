"""Numpy rollout-side policy inference.

Rollout actors deliberately never import jax: on a TPU host every extra
process initializing the backend pays seconds of startup and contends for
the chip, and for a (64, 64) fcnet a numpy forward is microseconds —
far below jit dispatch overhead, let alone a device round-trip per env
step. The learner (ray_tpu.rllib.learner) is the only RL component that
touches jax/TPU, mirroring the reference's rollout-on-CPU / learn-on-GPU
split (ref: rllib/evaluation/rollout_worker.py:660 sample loop;
rllib/core/learner/learner.py update on device).
"""
from __future__ import annotations

from typing import Dict, Tuple

import numpy as np


def ensure_numpy(params: Dict) -> Dict:
    """Normalize a param dict (possibly jax arrays off the object store)
    to float32 numpy once per rollout, so the per-step loop never pays a
    conversion."""
    return {k: np.asarray(v, dtype=np.float32) for k, v in params.items()}


def forward_np(params: Dict, obs: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """obs [B, obs_dim] -> (logits [B, A], value [B]). Mirrors
    models.forward exactly (two tanh hidden layers + separate heads)."""
    x = obs
    i = 0
    while f"w{i}" in params:
        x = np.tanh(x @ params[f"w{i}"] + params[f"b{i}"])
        i += 1
    logits = x @ params["w_pi"] + params["b_pi"]
    value = (x @ params["w_v"] + params["b_v"])[:, 0]
    return logits, value


def sample_actions(params: Dict, obs: np.ndarray, rng: np.random.Generator
                   ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Rollout-side inference -> (actions, logp, values)."""
    logits, values = forward_np(params, obs)
    z = logits - logits.max(axis=1, keepdims=True)
    p = np.exp(z)
    p /= p.sum(axis=1, keepdims=True)
    u = rng.random((len(p), 1))
    actions = (p.cumsum(axis=1) < u).sum(axis=1).astype(np.int64)
    np.clip(actions, 0, p.shape[1] - 1, out=actions)
    logp = np.log(p[np.arange(len(p)), actions] + 1e-8)
    return actions, logp.astype(np.float32), values.astype(np.float32)
