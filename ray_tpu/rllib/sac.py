"""SAC — soft actor-critic for continuous control.

ref: rllib/algorithms/sac/sac.py (SACConfig: twin Q, tanh-squashed
gaussian, target entropy = -|A|, polyak tau) and
sac/sac_torch_policy.py (actor/critic/alpha losses :220-300).

House TPU shape (the DQN recipe): numpy behavior policy in rollout
actors, host-side replay buffer, and the WHOLE per-iteration update
block — K minibatches of critic+actor+alpha+polyak — as ONE jitted
lax.scan with donated buffers, so the device behind the tunnel sees one
dispatch and one stats readback per train() call.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import cloudpickle
import numpy as np

import ray_tpu

from .env import make_env
from .replay_buffer import ReplayBuffer
from .rollout_worker import EnvWorkerBase, worker_opts

LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0


# ---------------------------------------------------------------------------
# networks (param-dict style, matching models.py)
# ---------------------------------------------------------------------------


def init_sac_params(rng, obs_dim: int, action_dim: int,
                    hidden: Tuple[int, ...] = (256, 256)) -> Dict:
    import jax
    import jax.numpy as jnp

    def mlp(key, sizes, out):
        p = {}
        last = sizes[0]
        ks = jax.random.split(key, len(sizes))
        for i, h in enumerate(sizes[1:]):
            p[f"w{i}"] = jax.random.normal(
                ks[i], (last, h), jnp.float32) * np.sqrt(2.0 / last)
            p[f"b{i}"] = jnp.zeros((h,), jnp.float32)
            last = h
        p["w_out"] = jax.random.normal(
            ks[-1], (last, out), jnp.float32) * 0.01
        p["b_out"] = jnp.zeros((out,), jnp.float32)
        return p

    import jax

    ka, k1, k2 = jax.random.split(rng, 3)
    return {
        # actor emits mean and log_std per action dim
        "actor": mlp(ka, (obs_dim, *hidden), 2 * action_dim),
        "q1": mlp(k1, (obs_dim + action_dim, *hidden), 1),
        "q2": mlp(k2, (obs_dim + action_dim, *hidden), 1),
    }


def _mlp_forward(p: Dict, x):
    import jax.numpy as jnp

    i = 0
    while f"w{i}" in p:
        x = jnp.maximum(x @ p[f"w{i}"] + p[f"b{i}"], 0.0)
        i += 1
    return x @ p["w_out"] + p["b_out"]


def actor_dist(p: Dict, obs):
    """-> (mu, log_std) for the tanh-squashed gaussian."""
    import jax.numpy as jnp

    out = _mlp_forward(p, obs)
    mu, log_std = jnp.split(out, 2, axis=-1)
    return mu, jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)


def sample_action_jax(p: Dict, obs, key, action_scale: float):
    """Reparameterized tanh-gaussian sample -> (action, logp)."""
    import jax
    import jax.numpy as jnp

    mu, log_std = actor_dist(p, obs)
    std = jnp.exp(log_std)
    eps = jax.random.normal(key, mu.shape)
    pre = mu + std * eps
    a = jnp.tanh(pre)
    # log-prob with the tanh change-of-variables (SAC appendix C)
    logp = (-0.5 * (eps ** 2 + 2 * log_std + np.log(2 * np.pi))
            - jnp.log(1 - a ** 2 + 1e-6)).sum(axis=-1)
    return a * action_scale, logp


def sample_action_np(p: Dict, obs: np.ndarray, rng: np.random.Generator,
                     action_scale: float, deterministic: bool = False
                     ) -> np.ndarray:
    """Numpy rollout-side sampling (np_policy rationale: no jax in
    actors)."""
    x = obs
    i = 0
    while f"w{i}" in p:
        x = np.maximum(x @ p[f"w{i}"] + p[f"b{i}"], 0.0)
        i += 1
    out = x @ p["w_out"] + p["b_out"]
    mu, log_std = np.split(out, 2, axis=-1)
    if deterministic:
        return np.tanh(mu) * action_scale
    std = np.exp(np.clip(log_std, LOG_STD_MIN, LOG_STD_MAX))
    pre = mu + std * rng.standard_normal(mu.shape)
    return np.tanh(pre) * action_scale


# ---------------------------------------------------------------------------
# rollout worker
# ---------------------------------------------------------------------------


class SACRolloutWorker(EnvWorkerBase):
    def __init__(self, env_name: str, num_envs: int, rollout_len: int,
                 action_scale: float, seed: int = 0, env_creator=None):
        super().__init__(env_name, num_envs, rollout_len, seed, env_creator)
        self.action_scale = action_scale

    def sample(self, actor_params: Dict, random_actions: bool = False
               ) -> Dict[str, np.ndarray]:
        p = {k: np.asarray(v, np.float32) for k, v in actor_params.items()}
        T, n = self.rollout_len, self.env.num_envs
        ad = self.env.action_dim
        obs_buf = np.empty((T, n, self.env.obs_dim), np.float32)
        next_buf = np.empty((T, n, self.env.obs_dim), np.float32)
        act_buf = np.empty((T, n, ad), np.float32)
        rew_buf = np.empty((T, n), np.float32)
        done_buf = np.empty((T, n), np.bool_)
        obs = self._obs
        for t in range(T):
            # actions are stored UNSCALED (tanh range [-1,1]) so the
            # learner's Q-nets, Bellman targets, and actor loss all live
            # on one action scale; the env boundary applies the scale
            if random_actions:  # warmup exploration
                a = self._rng.uniform(-1, 1, (n, ad))
            else:
                a = sample_action_np(p, obs, self._rng, 1.0)
            obs_buf[t], act_buf[t] = obs, a
            obs, reward, done, info = self.env.step(a * self.action_scale)
            rew_buf[t], done_buf[t] = reward, done
            next_buf[t] = obs
            if done.any():
                idx = np.nonzero(done)[0]
                if "final_obs" in info:
                    next_buf[t, idx] = info["final_obs"][idx]
                if "truncated" in info:
                    # time-limit cut still bootstraps
                    done_buf[t] &= ~info["truncated"]
            self._track_returns(reward, done)
        self._obs = obs
        flat = lambda a: a.reshape(T * n, *a.shape[2:])  # noqa: E731
        return {"obs": flat(obs_buf), "actions": flat(act_buf),
                "rewards": flat(rew_buf), "dones": flat(done_buf),
                "next_obs": flat(next_buf)}


# ---------------------------------------------------------------------------
# learner + algorithm
# ---------------------------------------------------------------------------


@dataclass
class SACConfig:
    """ref: sac/sac.py SACConfig defaults (tau 5e-3, twin Q,
    target_entropy='auto' = -|A|, initial_alpha 1.0)."""
    env: str = "Pendulum-v1"
    env_creator: Optional[Callable] = None
    num_rollout_workers: int = 1
    num_envs_per_worker: int = 8
    rollout_fragment_length: int = 32
    gamma: float = 0.99
    tau: float = 5e-3
    actor_lr: float = 3e-4
    critic_lr: float = 3e-4
    alpha_lr: float = 3e-4
    buffer_size: int = 100_000
    train_batch_size: int = 256
    num_updates_per_iter: int = 32
    learning_starts: int = 1_000
    hidden: tuple = (256, 256)
    seed: int = 0
    checkpoint_replay_buffer: bool = True
    worker_resources: Dict[str, float] = field(default_factory=dict)

    def build(self) -> "SAC":
        return SAC(self)


class SACLearner:
    def __init__(self, obs_dim: int, action_dim: int, c: SACConfig):
        import jax
        import jax.numpy as jnp
        import optax

        self.params = init_sac_params(jax.random.PRNGKey(c.seed), obs_dim,
                                      action_dim, tuple(c.hidden))
        self.target_q = jax.tree.map(
            lambda a: a.copy(), {"q1": self.params["q1"],
                                 "q2": self.params["q2"]})
        self.log_alpha = jnp.zeros(())
        self.target_entropy = -float(action_dim)
        self.opt_actor = optax.adam(c.actor_lr)
        self.opt_critic = optax.adam(c.critic_lr)
        self.opt_alpha = optax.adam(c.alpha_lr)
        self.state_actor = self.opt_actor.init(self.params["actor"])
        self.state_critic = self.opt_critic.init(
            {"q1": self.params["q1"], "q2": self.params["q2"]})
        self.state_alpha = self.opt_alpha.init(self.log_alpha)
        self.num_updates = 0
        self._key = jax.random.PRNGKey(c.seed + 1)
        self._update_many = jax.jit(self._make_update_many(c),
                                    donate_argnums=(0, 1, 2, 3))

    def _make_update_many(self, c: SACConfig):
        import jax
        import jax.numpy as jnp
        import optax

        gamma, tau = c.gamma, c.tau
        tgt_ent = self.target_entropy

        def q_val(qp, obs, act):
            return _mlp_forward(qp, jnp.concatenate([obs, act],
                                                    axis=-1))[:, 0]

        def one_update(params, target_q, log_alpha, opt_states, batch, key):
            sa, sc, sal = opt_states
            alpha = jnp.exp(log_alpha)
            k1, k2 = jax.random.split(key)

            # --- critic: entropy-regularized twin-min Bellman target
            a_next, logp_next = sample_action_jax(params["actor"],
                                                  batch["next_obs"], k1, 1.0)
            tq = jnp.minimum(
                q_val(target_q["q1"], batch["next_obs"], a_next),
                q_val(target_q["q2"], batch["next_obs"], a_next))
            not_done = 1.0 - batch["dones"].astype(jnp.float32)
            y = batch["rewards"] + gamma * not_done * (
                tq - alpha * logp_next)
            y = jax.lax.stop_gradient(y)

            def critic_loss(qs):
                l1 = jnp.mean((q_val(qs["q1"], batch["obs"],
                                     batch["actions"]) - y) ** 2)
                l2 = jnp.mean((q_val(qs["q2"], batch["obs"],
                                     batch["actions"]) - y) ** 2)
                return l1 + l2

            qs = {"q1": params["q1"], "q2": params["q2"]}
            closs, cgrads = jax.value_and_grad(critic_loss)(qs)
            cupd, sc = self.opt_critic.update(cgrads, sc, qs)
            qs = optax.apply_updates(qs, cupd)
            params = {**params, "q1": qs["q1"], "q2": qs["q2"]}

            # --- actor: maximize twin-min Q + entropy
            def actor_loss(ap):
                a, logp = sample_action_jax(ap, batch["obs"], k2, 1.0)
                q = jnp.minimum(q_val(params["q1"], batch["obs"], a),
                                q_val(params["q2"], batch["obs"], a))
                return jnp.mean(alpha * logp - q), jnp.mean(logp)

            (aloss, mean_logp), agrads = jax.value_and_grad(
                actor_loss, has_aux=True)(params["actor"])
            aupd, sa = self.opt_actor.update(agrads, sa, params["actor"])
            params = {**params,
                      "actor": optax.apply_updates(params["actor"], aupd)}

            # --- temperature: drive entropy toward the target
            def alpha_loss(la):
                return -jnp.exp(la) * jax.lax.stop_gradient(
                    mean_logp + tgt_ent)

            lloss, lgrad = jax.value_and_grad(alpha_loss)(log_alpha)
            lupd, sal = self.opt_alpha.update(lgrad, sal, log_alpha)
            log_alpha = optax.apply_updates(log_alpha, lupd)

            # --- polyak target update
            target_q = jax.tree.map(
                lambda t, o: t * (1 - tau) + o * tau, target_q,
                {"q1": params["q1"], "q2": params["q2"]})
            stats = {"critic_loss": closs, "actor_loss": aloss,
                     "alpha": jnp.exp(log_alpha), "entropy": -mean_logp}
            return params, target_q, log_alpha, (sa, sc, sal), stats

        def update_many(params, target_q, log_alpha, opt_states, batches,
                        key):
            def body(carry, batch_k):
                params, target_q, log_alpha, opt_states, key = carry
                key, sub = jax.random.split(key)
                params, target_q, log_alpha, opt_states, stats = one_update(
                    params, target_q, log_alpha, opt_states, batch_k, sub)
                return (params, target_q, log_alpha, opt_states, key), stats

            (params, target_q, log_alpha, opt_states, _), stats = \
                jax.lax.scan(body,
                             (params, target_q, log_alpha, opt_states, key),
                             batches)
            return (params, target_q, log_alpha, opt_states,
                    jax.tree.map(jnp.mean, stats))

        return update_many

    def update_many(self, batches: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax
        import jax.numpy as jnp

        K = batches["obs"].shape[0]
        self._key, sub = jax.random.split(self._key)
        jb = {k: jnp.asarray(v) for k, v in batches.items()}
        opt_states = (self.state_actor, self.state_critic, self.state_alpha)
        (self.params, self.target_q, self.log_alpha, opt_states, stats) = \
            self._update_many(self.params, self.target_q, self.log_alpha,
                              opt_states, jb, sub)
        self.state_actor, self.state_critic, self.state_alpha = opt_states
        self.num_updates += K
        return {k: float(v) for k, v in jax.device_get(stats).items()}


class SAC:
    """Tune-trainable-shaped SAC (train/save/restore/stop)."""

    def __init__(self, config: SACConfig):
        self.config = c = config
        probe = make_env(c.env, num_envs=1, seed=c.seed) \
            if c.env_creator is None else c.env_creator(num_envs=1,
                                                        seed=c.seed)
        if not getattr(probe, "continuous", False):
            raise ValueError("SAC needs a continuous-action env")
        self.action_scale = float(probe.action_high)
        obs_dim, act_dim = probe.obs_dim, probe.action_dim
        creator_blob = (cloudpickle.dumps(c.env_creator)
                        if c.env_creator else None)
        worker_cls = ray_tpu.remote(SACRolloutWorker)
        opts = worker_opts(c.worker_resources)
        self.workers: List = [
            worker_cls.options(**opts).remote(
                c.env, c.num_envs_per_worker, c.rollout_fragment_length,
                self.action_scale, seed=c.seed + 1000 * i,
                env_creator=creator_blob)
            for i in range(c.num_rollout_workers)
        ]
        self.learner = SACLearner(obs_dim, act_dim, c)
        self.buffer = ReplayBuffer(c.buffer_size, seed=c.seed)
        self._iteration = 0
        self._total_steps = 0
        self._total_episodes = 0
        self._recent: List[float] = []

    def train(self) -> Dict[str, Any]:
        c = self.config
        t0 = time.monotonic()
        warmup = self._total_steps < c.learning_starts
        actor_ref = ray_tpu.put(
            {k: np.asarray(v) for k, v in
             __import__("jax").device_get(
                 self.learner.params["actor"]).items()})
        batches = ray_tpu.get(
            [w.sample.remote(actor_ref, warmup) for w in self.workers],
            timeout=300)
        steps = 0
        for b in batches:
            self.buffer.add(b)
            steps += len(b["rewards"])
        sample_time = time.monotonic() - t0
        t1 = time.monotonic()
        stats: Dict[str, float] = {}
        self._total_steps += steps
        if len(self.buffer) >= max(c.learning_starts, c.train_batch_size):
            K, B = c.num_updates_per_iter, c.train_batch_size
            mb = self.buffer.sample(K * B)
            stacked = {k: v.reshape(K, B, *v.shape[1:])
                       for k, v in mb.items()}
            stats = self.learner.update_many(stacked)
        learn_time = time.monotonic() - t1
        for rets in ray_tpu.get(
                [w.episode_returns.remote() for w in self.workers],
                timeout=60):
            self._recent.extend(rets)
            self._total_episodes += len(rets)
        self._recent = self._recent[-100:]
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "timesteps_total": self._total_steps,
            "timesteps_this_iter": steps,
            "episode_reward_mean": (float(np.mean(self._recent))
                                    if self._recent else float("nan")),
            "episodes_total": self._total_episodes,
            "env_steps_per_sec": steps / max(1e-9, sample_time + learn_time),
            "num_updates": self.learner.num_updates,
            **stats,
        }

    def save(self) -> Dict:
        import jax

        L = self.learner
        ckpt = {"params": jax.device_get(L.params),
                "target_q": jax.device_get(L.target_q),
                "log_alpha": float(L.log_alpha),
                # Adam moments + the sampling key survive the round-trip
                # (the PPO.save invariant) — a restored run continues,
                # not restarts, its optimization trajectory
                "opt_states": jax.device_get((L.state_actor, L.state_critic,
                                              L.state_alpha)),
                "rng_key": jax.device_get(L._key),
                "iteration": self._iteration,
                "total_steps": self._total_steps}
        if self.config.checkpoint_replay_buffer:
            # same contract as DQN: a restored trial (PBT exploit,
            # pause/resume) resumes warm instead of stalling until
            # learning_starts refills
            ckpt["buffer"] = self.buffer.state()
        return ckpt

    def restore(self, ckpt: Dict) -> None:
        import jax.numpy as jnp
        import jax

        as_jnp = lambda t: jax.tree.map(jnp.asarray, t)  # noqa: E731
        L = self.learner
        L.params = as_jnp(ckpt["params"])
        L.target_q = as_jnp(ckpt["target_q"])
        L.log_alpha = jnp.asarray(ckpt.get("log_alpha", 0.0))
        if "opt_states" in ckpt:
            (L.state_actor, L.state_critic, L.state_alpha) = as_jnp(
                ckpt["opt_states"])
        if "rng_key" in ckpt:
            L._key = jnp.asarray(ckpt["rng_key"])
        self._iteration = int(ckpt.get("iteration", 0))
        self._total_steps = int(ckpt.get("total_steps", 0))
        if "buffer" in ckpt:
            self.buffer.restore(ckpt["buffer"])

    def stop(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
