"""ray_tpu.rllib — reinforcement learning on the distributed runtime.

Equivalent of RLlib's core loop (ref: rllib/algorithms/): rollout-worker
actors sampling vectorized envs, a jitted JAX PPO learner (pmean-ready
for data-parallel meshes), synchronous Algorithm.train() with object-
store weight broadcast, and a Tune-compatible trainable surface.
"""
from .algorithm import PPO, PPOConfig
from .env import CartPoleVecEnv, VectorEnv, make_env, register_env
from .learner import PPOLearner, ppo_loss
from .rollout_worker import RolloutWorker

__all__ = [
    "CartPoleVecEnv", "PPO", "PPOConfig", "PPOLearner", "RolloutWorker",
    "VectorEnv", "make_env", "ppo_loss", "register_env",
]
