"""ray_tpu.rllib — reinforcement learning on the distributed runtime.

Equivalent of RLlib's core loop (ref: rllib/algorithms/): rollout-worker
actors sampling vectorized envs, a jitted JAX PPO learner (pmean-ready
for data-parallel meshes), synchronous Algorithm.train() with object-
store weight broadcast, and a Tune-compatible trainable surface.

Lazy exports (PEP 562): rollout-worker processes unpickle their actor
class by module reference, and an eager `from .learner import ...` here
would drag jax+optax into every rollout actor — the exact cost
np_policy.py exists to avoid. Only the submodule actually touched gets
imported.
"""
from typing import TYPE_CHECKING

_EXPORTS = {
    "PPO": "algorithm", "PPOConfig": "algorithm",
    "DQN": "dqn", "DQNConfig": "dqn", "DQNLearner": "dqn",
    "DQNRolloutWorker": "dqn",
    "Impala": "impala", "ImpalaConfig": "impala",
    "ImpalaLearner": "impala",
    "SAC": "sac", "SACConfig": "sac", "SACLearner": "sac",
    "APPO": "impala", "APPOConfig": "impala",
    "DT": "dt", "DTConfig": "dt",
    "Dreamer": "dreamer", "DreamerConfig": "dreamer",
    "DreamerLearner": "dreamer",
    "SlateQ": "slateq", "SlateQConfig": "slateq",
    "InterestEvolutionVecEnv": "slateq",
    "MAML": "maml", "MAMLConfig": "maml",
    "PointGoalVecEnv": "maml", "sample_point_goal": "maml",
    "AlphaZero": "alpha_zero", "AlphaZeroConfig": "alpha_zero",
    "TicTacToe": "alpha_zero", "register_game": "alpha_zero",
    "mcts_policy": "alpha_zero",
    "MARWIL": "offline", "MARWILConfig": "offline",
    "BC": "offline", "BCConfig": "offline",
    "CQL": "cql", "CQLConfig": "cql",
    "collect_experiences": "offline", "read_experiences": "offline",
    "write_experiences": "offline",
    "MeanStdFilter": "connectors", "RunningStat": "connectors",
    "make_connector": "connectors",
    "MultiAgentPPO": "multi_agent", "MultiAgentPPOConfig": "multi_agent",
    "MultiAgentVecEnv": "multi_agent", "CoordinationVecEnv": "multi_agent",
    "make_multi_agent_env": "multi_agent",
    "register_multi_agent_env": "multi_agent",
    "ReplayBuffer": "replay_buffer",
    "PrioritizedReplayBuffer": "replay_buffer",
    "CartPoleVecEnv": "env", "PendulumVecEnv": "env", "VectorEnv": "env",
    "MemoryCueVecEnv": "env",
    "R2D2": "r2d2", "R2D2Config": "r2d2", "R2D2Learner": "r2d2",
    "ApexDQN": "apex", "ApexDQNConfig": "apex",
    "ReplayShardActor": "apex", "per_worker_epsilons": "apex",
    "make_env": "env", "register_env": "env",
    "BreakoutShapedVecEnv": "preprocessors", "wrap_atari": "preprocessors",
    "WarpFrameVec": "preprocessors", "FrameStackVec": "preprocessors",
    "MaxAndSkipVec": "preprocessors",
    "PPOLearner": "learner", "ppo_loss": "learner",
    "RolloutWorker": "rollout_worker",
    "PPOJax": "ppo_jax", "PPOJaxConfig": "ppo_jax",
    "JaxVectorEnv": "jax_env", "CartPoleJax": "jax_env",
    "BreakoutShapedJax": "jax_env", "make_jax_env": "jax_env",
    "register_jax_env": "jax_env",
    "ES": "es", "ESConfig": "es", "ESWorker": "es",
    "ARS": "ars", "ARSConfig": "ars", "ARSWorker": "ars",
    "A2C": "a2c", "A2CConfig": "a2c", "A2CLearner": "a2c",
    "PGConfig": "a2c",
    "CRR": "crr", "CRRConfig": "crr",
    "TD3": "td3", "TD3Config": "td3", "DDPGConfig": "td3",
    "TD3Learner": "td3",
    "Bandit": "bandit", "BanditConfig": "bandit",
    "BanditLinUCBConfig": "bandit", "BanditLinTSConfig": "bandit",
    "LinearBanditEnv": "bandit", "register_bandit_env": "bandit",
    "QMIX": "qmix", "QMIXConfig": "qmix",
    "MADDPG": "maddpg", "MADDPGConfig": "maddpg",
    "RendezvousVecEnv": "maddpg",
    "PolicyServerInput": "policy_server",
    "ExternalPPO": "policy_server", "ExternalPPOConfig": "policy_server",
    "PolicyClient": "policy_client",
}

__all__ = sorted(_EXPORTS)

if TYPE_CHECKING:  # static analyzers see the eager imports
    from .algorithm import PPO, PPOConfig  # noqa: F401
    from .dqn import (DQN, DQNConfig, DQNLearner,  # noqa: F401
                      DQNRolloutWorker)
    from .impala import (Impala, ImpalaConfig,  # noqa: F401
                         ImpalaLearner)
    from .replay_buffer import (PrioritizedReplayBuffer,  # noqa: F401
                                ReplayBuffer)
    from .env import (CartPoleVecEnv, VectorEnv, make_env,  # noqa: F401
                      register_env)
    from .learner import PPOLearner, ppo_loss  # noqa: F401
    from .rollout_worker import RolloutWorker  # noqa: F401


def __getattr__(name: str):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(f".{mod}", __name__), name)
