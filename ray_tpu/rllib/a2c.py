"""A2C — synchronous advantage actor-critic.

ref: rllib/algorithms/a2c/a2c.py (A2CConfig: microbatch_size grad
accumulation, sync sampling over the WorkerSet) and
rllib/algorithms/a3c/a3c_torch_policy.py (the loss: plain policy
gradient x advantage + value MSE + entropy bonus — no ratio clipping,
no multi-epoch SGD). The reference's A3C (async HogWild gradients) is
represented in this stack by the async-sampling IMPALA/APPO family;
A2C is its synchronous batched form (the reference makes the same
reduction: a2c.py subclasses a3c.py and synchronizes it).

House TPU shape: rollout workers are the shared numpy `RolloutWorker`
(GAE worker-side), and the learner applies ONE jitted update per
train() call — microbatch gradient accumulation runs as a lax.scan
inside the same dispatch, so the tunnel pays one round trip regardless
of microbatch count (docs/PERF_NOTES.md learner rule).
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle
import numpy as np

import ray_tpu

from . import sample_batch as sb
from .rollout_worker import RolloutWorker, worker_opts


@dataclass
class A2CConfig:
    """ref: a2c.py A2CConfig defaults (lr 1e-4 order, vf_loss_coeff 0.5,
    entropy_coeff 0.01, optional microbatch_size)."""
    env: str = "CartPole-v1"
    env_creator: Optional[Callable] = None
    num_rollout_workers: int = 2
    num_envs_per_worker: int = 8
    rollout_fragment_length: int = 32
    gamma: float = 0.99
    lam: float = 1.0            # A2C default: plain returns (GAE off)
    lr: float = 7e-4
    vf_loss_coeff: float = 0.5
    entropy_coeff: float = 0.01
    max_grad_norm: float = 0.5
    # None -> one whole-batch step; else grads accumulate over
    # ceil(B / microbatch_size) slices before the single optimizer step
    microbatch_size: Optional[int] = None
    hidden: tuple = (64, 64)
    observation_filter: str = "NoFilter"
    seed: int = 0
    worker_resources: Dict[str, float] = field(default_factory=dict)

    def build(self) -> "A2C":
        return A2C(self)


def PGConfig(**kw) -> A2CConfig:  # noqa: N802 — ref naming
    """Vanilla policy gradient / REINFORCE (ref: rllib/algorithms/pg/
    pg.py — the reference implements PG as the minimal policy-gradient
    loss; here that is A2C with the critic's loss weight zeroed and
    Monte-Carlo returns, the same reduction DDPGConfig makes over
    TD3)."""
    kw.setdefault("vf_loss_coeff", 0.0)
    kw.setdefault("lam", 1.0)
    return A2CConfig(**kw)


class A2CLearner:
    """One jitted grad-accumulate + apply per update()."""

    def __init__(self, obs_shape, num_actions: int, c: A2CConfig):
        import functools

        import jax
        import jax.numpy as jnp
        import optax

        from .models import forward, init_policy_params

        self.params = init_policy_params(
            jax.random.PRNGKey(c.seed), obs_shape, num_actions,
            tuple(c.hidden))
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(c.max_grad_norm), optax.adam(c.lr))
        self.opt_state = self.optimizer.init(self.params)

        def loss_fn(params, batch, total_n):
            """Weighted-SUM losses over one slice, divided by the WHOLE
            batch size: summing slice grads then equals the whole-batch
            mean gradient exactly, pads (weight 0) contribute nothing,
            and microbatch_size is a pure memory knob — advantages are
            normalized once in update(), not per slice."""
            logits, values = forward(params, batch[sb.OBS])
            w = batch["_w"]
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch[sb.ACTIONS][:, None], axis=1)[:, 0]
            adv = jax.lax.stop_gradient(batch[sb.ADVANTAGES])
            # score-function gradient: advantage is a constant multiplier
            policy_loss = -jnp.sum(w * logp * adv) / total_n
            vf_loss = jnp.sum(
                w * (values - batch[sb.RETURNS]) ** 2) / total_n
            entropy = jnp.sum(
                -w * jnp.sum(jnp.exp(logp_all) * logp_all, axis=1)
            ) / total_n
            loss = (policy_loss + c.vf_loss_coeff * vf_loss
                    - c.entropy_coeff * entropy)
            return loss, {"policy_loss": policy_loss, "vf_loss": vf_loss,
                          "entropy": entropy}

        @functools.partial(jax.jit, donate_argnums=(0, 1),
                           static_argnums=(3,))
        def update(params, opt_state, batch, total_n):
            # batch arrives [n_micro, mb, ...]; slice grads SUM to the
            # whole-batch mean gradient (see loss_fn)
            def body(acc, mb):
                (loss, stats), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params, mb, total_n)
                acc_g, acc_s = acc
                return (jax.tree.map(jnp.add, acc_g, grads),
                        jax.tree.map(jnp.add, acc_s,
                                     {**stats, "loss": loss})), None

            zero_g = jax.tree.map(jnp.zeros_like, params)
            zero_s = jax.tree.map(
                jnp.asarray, {"policy_loss": 0.0, "vf_loss": 0.0,
                              "entropy": 0.0, "loss": 0.0})
            (grads, stats), _ = jax.lax.scan(body, (zero_g, zero_s), batch)
            updates, opt_state = self.optimizer.update(grads, opt_state,
                                                       params)
            return optax.apply_updates(params, updates), opt_state, stats

        self._update = update
        self._micro = c.microbatch_size

    _LOSS_KEYS = (sb.OBS, sb.ACTIONS, sb.ADVANTAGES, sb.RETURNS)

    def update(self, batch: sb.Batch) -> Dict[str, float]:
        import jax
        import jax.numpy as jnp

        n = len(batch[sb.OBS])
        if n == 0:
            return {}
        mb = min(self._micro or n, n)
        n_micro = -(-n // mb)  # ceil: the tail rides padded, masked out
        padded = n_micro * mb
        adv = batch[sb.ADVANTAGES].astype(np.float32)
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)  # whole-batch, once
        cols = {**{k: batch[k] for k in self._LOSS_KEYS},
                sb.ADVANTAGES: adv,
                "_w": np.ones(n, np.float32)}
        jb = {}
        for k, v in cols.items():
            if padded != n:
                pad = np.zeros((padded - n, *v.shape[1:]), v.dtype)
                v = np.concatenate([v, pad])
            jb[k] = jnp.asarray(v).reshape(n_micro, mb, *v.shape[1:])
        self.params, self.opt_state, stats = self._update(
            self.params, self.opt_state, jb, n)
        return {k: float(v) for k, v in jax.device_get(stats).items()}

    def get_params(self) -> Dict:
        import jax

        return jax.device_get(self.params)


class A2C:
    """Tune-trainable synchronous A2C (same driver shape as PPO)."""

    def __init__(self, config: A2CConfig):
        from .connectors import NoFilter, make_connector

        self.config = c = config
        creator_blob = (cloudpickle.dumps(c.env_creator)
                        if c.env_creator else None)
        worker_cls = ray_tpu.remote(RolloutWorker)
        opts = worker_opts(c.worker_resources)
        self.workers: List = [
            worker_cls.options(**opts).remote(
                c.env, c.num_envs_per_worker, c.rollout_fragment_length,
                c.gamma, c.lam, seed=c.seed + 1000 * i,
                env_creator=creator_blob,
                observation_filter=c.observation_filter)
            for i in range(c.num_rollout_workers)
        ]
        info = ray_tpu.get(self.workers[0].env_info.remote(), timeout=180)
        self.obs_filter = make_connector(
            c.observation_filter, info.get("obs_shape", (info["obs_dim"],)))
        self._no_filter = isinstance(self.obs_filter, NoFilter)
        self.learner = A2CLearner(
            info.get("obs_shape", info["obs_dim"]), info["num_actions"], c)
        self._iteration = 0
        self._total_steps = 0
        self._total_episodes = 0
        self._recent: List[float] = []

    def train(self) -> Dict[str, Any]:
        from .connectors import merge_deltas

        t0 = time.monotonic()
        params_ref = ray_tpu.put(self.learner.get_params())
        batches = ray_tpu.get(
            [w.sample.remote(params_ref) for w in self.workers],
            timeout=300)
        sample_time = time.monotonic() - t0
        batch = sb.concat(batches)
        t1 = time.monotonic()
        stats = self.learner.update(batch)
        learn_time = time.monotonic() - t1
        if not self._no_filter:
            deltas = ray_tpu.get(
                [w.filter_delta.remote() for w in self.workers], timeout=60)
            state = merge_deltas(self.obs_filter, deltas)
            for w in self.workers:
                w.sync_filter.remote(state)
        for rets in ray_tpu.get(
                [w.episode_returns.remote() for w in self.workers],
                timeout=60):
            self._recent.extend(rets)
            self._total_episodes += len(rets)
        self._recent = self._recent[-100:]
        self._iteration += 1
        steps = sb.num_steps(batch)
        self._total_steps += steps
        return {
            "training_iteration": self._iteration,
            "timesteps_total": self._total_steps,
            "timesteps_this_iter": steps,
            "episode_reward_mean": (float(np.mean(self._recent))
                                    if self._recent else float("nan")),
            "episodes_total": self._total_episodes,
            "env_steps_per_sec": steps / max(1e-9,
                                             sample_time + learn_time),
            "sample_time_s": sample_time,
            "learn_time_s": learn_time,
            **stats,
        }

    # -- Tune-trainable surface ------------------------------------------

    def save(self) -> Dict:
        import jax

        ckpt = {"params": jax.device_get(self.learner.params),
                "opt_state": jax.device_get(self.learner.opt_state),
                "iteration": self._iteration,
                "total_steps": self._total_steps}
        if not self._no_filter:
            ckpt["obs_filter"] = self.obs_filter.state()
        return ckpt

    def restore(self, ckpt: Dict) -> None:
        import jax
        import jax.numpy as jnp

        self.learner.params = jax.tree.map(jnp.asarray, ckpt["params"])
        if "opt_state" in ckpt:
            self.learner.opt_state = jax.tree.map(jnp.asarray,
                                                  ckpt["opt_state"])
        self._iteration = int(ckpt.get("iteration", 0))
        self._total_steps = int(ckpt.get("total_steps", 0))
        if "obs_filter" in ckpt and not self._no_filter:
            self.obs_filter.set_state(ckpt["obs_filter"])
            ray_tpu.get([w.sync_filter.remote(ckpt["obs_filter"])
                         for w in self.workers], timeout=60)

    def stop(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
