"""PPOJax — the whole PPO loop (rollout + GAE + SGD) as one compiled
TPU program over a device-resident env.

ref: rllib/algorithms/ppo/ppo.py training_step (sample -> learn) — but
where the reference moves every observation host->device per iteration,
here the env IS a jax function (ray_tpu.rllib.jax_env), so an entire
training iteration — T env steps x n envs, bootstrap, GAE, E epochs of
minibatch SGD — is a single XLA dispatch (the Podracer/"Anakin" layout,
arXiv:2104.06272). `iters_per_step` stacks several full PPO iterations
into one dispatch via lax.scan, amortizing host round-trips: on a
tunneled device (~105 ms RTT) this is the difference between hundreds
and tens of thousands of env-steps/s. The only per-train() traffic is a
PRNG key in and a stats pytree out.

Multi-chip: pass `mesh_axis="dp"` + a Mesh to shard envs across chips;
gradients pmean over ICI inside the same compiled program
(the LearnerGroup-DDP analog; ref: rllib/core/learner/learner_group.py).
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import numpy as np

from . import sample_batch as sb


def make_gae_fn(gamma: float, lam: float):
    """GAE over a [T, n] rollout as a reverse lax.scan (the jax analog of
    sample_batch.compute_gae)."""
    import jax
    import jax.numpy as jnp

    def gae(rewards, values, dones, last_values):
        def body(carry, xs):
            last_gae, next_value = carry
            reward, value, done = xs
            not_done = 1.0 - done.astype(jnp.float32)
            delta = reward + gamma * next_value * not_done - value
            last_gae = delta + gamma * lam * not_done * last_gae
            return (last_gae, value), last_gae

        (_, _), adv = jax.lax.scan(
            body, (jnp.zeros_like(last_values), last_values),
            (rewards, values, dones), reverse=True)
        return adv, adv + values

    return gae


def make_train_step(env, optimizer, *, rollout_len: int, gamma: float,
                    lam: float, clip: float, vf_coeff: float,
                    ent_coeff: float, minibatch_size: int, num_epochs: int,
                    iters_per_step: int, mesh_axis: Optional[str] = None):
    """Build the pure (params, opt_state, env_state, obs, ep_ret, key) ->
    (params, opt_state, env_state, obs, ep_ret, key, stats) function.
    Everything inside is lax control flow: one trace, one executable."""
    import jax
    import jax.numpy as jnp

    from .learner import make_epoch_update_fn
    from .models import forward

    T = rollout_len
    gae = make_gae_fn(gamma, lam)
    epoch_update = make_epoch_update_fn(optimizer, clip, vf_coeff,
                                        ent_coeff, mesh_axis)

    def one_iter(carry, _):
        params, opt_state, env_state, obs, ep_ret, key = carry

        def rollout_body(c, _):
            env_state, obs, ep_ret, fin_sum, fin_cnt, key = c
            logits, value = forward(params, obs)
            key, sk = jax.random.split(key)
            actions = jax.random.categorical(sk, logits)
            logp = jnp.take_along_axis(jax.nn.log_softmax(logits),
                                       actions[:, None], axis=1)[:, 0]
            env_state, next_obs, reward, done = env.step(env_state, actions)
            ep_ret = ep_ret + reward
            fin_sum = fin_sum + jnp.sum(jnp.where(done, ep_ret, 0.0))
            fin_cnt = fin_cnt + jnp.sum(done.astype(jnp.float32))
            ep_ret = jnp.where(done, 0.0, ep_ret)
            return ((env_state, next_obs, ep_ret, fin_sum, fin_cnt, key),
                    (obs, actions, logp, value, reward, done))

        n = obs.shape[0]
        init = (env_state, obs, ep_ret, jnp.zeros((), jnp.float32),
                jnp.zeros((), jnp.float32), key)
        (env_state, obs, ep_ret, fin_sum, fin_cnt, key), traj = \
            jax.lax.scan(rollout_body, init, None, length=T)
        obs_t, act_t, logp_t, val_t, rew_t, done_t = traj
        _, last_values = forward(params, obs)
        adv, ret = gae(rew_t, val_t, done_t, last_values)

        flat = lambda a: a.reshape((T * n,) + a.shape[2:])  # noqa: E731
        batch = {sb.OBS: flat(obs_t), sb.ACTIONS: flat(act_t),
                 sb.LOGP: flat(logp_t), sb.ADVANTAGES: flat(adv),
                 sb.RETURNS: flat(ret)}

        N = T * n
        mb = min(minibatch_size, N)
        n_mb = N // mb
        key, pk = jax.random.split(key)
        idx = jnp.concatenate(
            [jax.random.permutation(k, N)[:n_mb * mb].reshape(n_mb, mb)
             for k in jax.random.split(pk, num_epochs)], axis=0)
        params, opt_state, ustats = epoch_update(params, opt_state, batch,
                                                 idx)
        rps = jnp.mean(rew_t)
        if mesh_axis is not None:
            # episode bookkeeping is per-shard; fold it here so the
            # replicated out_specs carry true global numbers
            fin_sum = jax.lax.psum(fin_sum, mesh_axis)
            fin_cnt = jax.lax.psum(fin_cnt, mesh_axis)
            rps = jax.lax.pmean(rps, mesh_axis)
        stats = {**ustats, "episode_return_sum": fin_sum,
                 "episodes": fin_cnt, "reward_per_step": rps}
        return (params, opt_state, env_state, obs, ep_ret, key), stats

    def train_step(params, opt_state, env_state, obs, ep_ret, key):
        if mesh_axis is not None:
            # decorrelate sampling + env noise across shards
            idx = jax.lax.axis_index(mesh_axis)
            key = jax.random.fold_in(key, idx)
            env_state = env.fold_key(env_state, idx)
        carry = (params, opt_state, env_state, obs, ep_ret, key)
        carry, stats = jax.lax.scan(one_iter, carry, None,
                                    length=iters_per_step)
        return carry, stats

    return train_step


@dataclass
class PPOJaxConfig:
    """ref: ppo.py PPOConfig — subset that applies to the fused
    single-program design. `iters_per_step` PPO iterations run per
    train() dispatch."""
    env: str = "CartPole-v1"
    num_envs: int = 64
    rollout_len: int = 64
    iters_per_step: int = 4
    lr: float = 3e-4
    gamma: float = 0.99
    lam: float = 0.95
    clip_param: float = 0.2
    vf_loss_coeff: float = 0.5
    entropy_coeff: float = 0.01
    sgd_minibatch_size: int = 1024
    num_sgd_epochs: int = 1
    hidden: Tuple[int, ...] = (64, 64)
    max_grad_norm: float = 0.5
    seed: int = 0
    # optional multi-chip: name of the mesh axis to shard envs over
    mesh_axis: Optional[str] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    def build(self, mesh=None) -> "PPOJax":
        return PPOJax(self, mesh=mesh)


class PPOJax:
    """Tune-trainable fused PPO. Single-device by default; with
    `mesh` + `config.mesh_axis` the same program runs shard_map'd with
    envs split across the axis and gradients pmean'd over ICI."""

    def __init__(self, config: PPOJaxConfig, mesh=None):
        import jax
        import jax.numpy as jnp
        import optax

        from .jax_env import make_jax_env
        from .models import init_policy_params

        c = self.config = config
        self.env = make_jax_env(c.env, num_envs=c.num_envs)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(c.max_grad_norm), optax.adam(c.lr))
        obs_shape = (self.env.obs_shape if len(self.env.obs_shape) > 1
                     else int(self.env.obs_shape[0]))
        self.params = init_policy_params(
            jax.random.PRNGKey(c.seed), obs_shape, self.env.num_actions,
            tuple(c.hidden))
        self.opt_state = self.optimizer.init(self.params)

        key = jax.random.PRNGKey(c.seed + 1)
        key, rk = jax.random.split(key)
        self.env_state, self.obs = self.env.reset(rk)
        self.ep_ret = jnp.zeros(c.num_envs, jnp.float32)
        self.key = key

        step = make_train_step(
            self.env, self.optimizer, rollout_len=c.rollout_len,
            gamma=c.gamma, lam=c.lam, clip=c.clip_param,
            vf_coeff=c.vf_loss_coeff, ent_coeff=c.entropy_coeff,
            minibatch_size=c.sgd_minibatch_size,
            num_epochs=c.num_sgd_epochs,
            iters_per_step=c.iters_per_step, mesh_axis=c.mesh_axis)
        if mesh is not None and c.mesh_axis is not None:
            from jax.sharding import PartitionSpec as P

            from ..jax_compat import shard_map

            if c.num_envs % mesh.shape[c.mesh_axis]:
                raise ValueError(
                    f"num_envs={c.num_envs} must divide the "
                    f"{c.mesh_axis!r} axis ({mesh.shape[c.mesh_axis]})")
            ax = c.mesh_axis
            rep, shd = P(), P(ax)
            # env state is a pytree mixing batched leaves (leading dim =
            # num_envs, shard those) and unbatched ones (the PRNG key —
            # replicate); derive the spec per leaf from the live state
            state_spec = jax.tree.map(
                lambda a: shd if (a.ndim and a.shape[0] == c.num_envs)
                else rep, self.env_state)
            step = shard_map(
                step, mesh=mesh,
                in_specs=(rep, rep, state_spec, shd, shd, rep),
                out_specs=((rep, rep, state_spec, shd, shd, rep), rep),
                check_vma=False)
        # obs may alias a buffer inside env_state (CartPole's state IS
        # its observation), so only the never-aliased args are donated
        self._step = jax.jit(step, donate_argnums=(0, 1, 4))
        self._iteration = 0
        self._total_steps = 0
        self._total_episodes = 0
        self._recent: list = []

    @property
    def steps_per_train(self) -> int:
        c = self.config
        return c.num_envs * c.rollout_len * c.iters_per_step

    def train(self) -> Dict[str, Any]:
        import jax

        t0 = time.monotonic()
        (self.params, self.opt_state, self.env_state, self.obs,
         self.ep_ret, self.key), stats = self._step(
            self.params, self.opt_state, self.env_state, self.obs,
            self.ep_ret, self.key)
        stats = jax.device_get(stats)  # forces the dispatch to finish
        dt = time.monotonic() - t0
        steps = self.steps_per_train
        self._iteration += 1
        self._total_steps += steps
        eps = float(stats["episodes"].sum())
        if eps > 0:
            self._recent.append(
                float(stats["episode_return_sum"].sum()) / eps)
            self._recent = self._recent[-100:]
            self._total_episodes += int(eps)
        out = {k: float(np.mean(v)) for k, v in stats.items()
               if k not in ("episode_return_sum", "episodes")}
        return {
            "training_iteration": self._iteration,
            "timesteps_total": self._total_steps,
            "timesteps_this_iter": steps,
            "episode_reward_mean": (float(np.mean(self._recent))
                                    if self._recent else float("nan")),
            "episodes_total": self._total_episodes,
            "env_steps_per_sec": steps / max(1e-9, dt),
            "train_time_s": dt,
            **out,
        }

    # -- Tune-trainable surface ------------------------------------------

    def save(self) -> Dict:
        import jax

        return {"params": jax.device_get(self.params),
                "opt_state": jax.device_get(self.opt_state),
                "key": jax.device_get(self.key),
                "iteration": self._iteration,
                "total_steps": self._total_steps}

    def restore(self, ckpt: Dict) -> None:
        import jax
        import jax.numpy as jnp

        as_jnp = lambda t: jax.tree.map(jnp.asarray, t)  # noqa: E731
        self.params = as_jnp(ckpt["params"])
        self.opt_state = as_jnp(ckpt["opt_state"])
        if "key" in ckpt:
            self.key = jnp.asarray(ckpt["key"])
        self._iteration = int(ckpt.get("iteration", 0))
        self._total_steps = int(ckpt.get("total_steps", 0))
        # env state restarts fresh: episodes in flight are not part of
        # the learning state (same stance as worker restart in PPO)

    def stop(self) -> None:
        pass
