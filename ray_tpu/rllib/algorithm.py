"""Algorithm — the train() driver.

ref: rllib/algorithms/algorithm.py (step :813, training_step :1400);
ppo/ppo.py:420 training_step = synchronous_parallel_sample over the
WorkerSet → learner update → weight broadcast. Here: N rollout-worker
actors sample in parallel, batches meet at the JAX learner, new params
broadcast through ONE object-store put per iteration.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle
import numpy as np

import ray_tpu

from . import sample_batch as sb
from .connectors import NoFilter, make_connector, merge_deltas
from .learner import PPOLearner
from .rollout_worker import RolloutWorker, worker_opts


@dataclass
class PPOConfig:
    """ref: ppo/ppo.py PPOConfig + algorithm_config.py builder pattern."""
    env: str = "CartPole-v1"
    env_creator: Optional[Callable] = None
    num_rollout_workers: int = 2
    num_envs_per_worker: int = 8
    rollout_fragment_length: int = 128
    gamma: float = 0.99
    lam: float = 0.95
    lr: float = 3e-4
    clip_param: float = 0.2
    vf_loss_coeff: float = 0.5
    entropy_coeff: float = 0.01
    sgd_minibatch_size: int = 256
    num_sgd_epochs: int = 4
    hidden: tuple = (64, 64)
    # "NoFilter" | "MeanStd": running obs normalization applied in the
    # rollout workers, stats merged across workers each iteration
    # (ref: rllib/utils/filter.py + filter_manager.py via connectors)
    observation_filter: str = "NoFilter"
    seed: int = 0
    worker_resources: Dict[str, float] = field(default_factory=dict)

    def environment(self, env: str = None, *, env_creator=None) -> "PPOConfig":
        if env is not None:
            self.env = env
        if env_creator is not None:
            self.env_creator = env_creator
        return self

    def rollouts(self, *, num_rollout_workers: int = None,
                 num_envs_per_worker: int = None,
                 rollout_fragment_length: int = None) -> "PPOConfig":
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, *, lr: float = None, gamma: float = None,
                 clip_param: float = None, entropy_coeff: float = None,
                 sgd_minibatch_size: int = None,
                 num_sgd_epochs: int = None) -> "PPOConfig":
        for k, v in [("lr", lr), ("gamma", gamma), ("clip_param", clip_param),
                     ("entropy_coeff", entropy_coeff),
                     ("sgd_minibatch_size", sgd_minibatch_size),
                     ("num_sgd_epochs", num_sgd_epochs)]:
            if v is not None:
                setattr(self, k, v)
        return self

    def build(self) -> "PPO":
        return PPO(self)


class PPO:
    """Synchronous-PPO algorithm instance (Tune-trainable shaped: train()
    returns a result dict, save/restore round-trip the learner state)."""

    def __init__(self, config: PPOConfig):
        self.config = config
        c = config
        creator_blob = (cloudpickle.dumps(c.env_creator)
                        if c.env_creator else None)
        worker_cls = ray_tpu.remote(RolloutWorker)
        opts = worker_opts(c.worker_resources)
        self.workers: List = [
            worker_cls.options(**opts).remote(
                c.env, c.num_envs_per_worker, c.rollout_fragment_length,
                c.gamma, c.lam, seed=c.seed + 1000 * i,
                env_creator=creator_blob,
                observation_filter=c.observation_filter)
            for i in range(c.num_rollout_workers)
        ]
        info = ray_tpu.get(self.workers[0].env_info.remote(), timeout=180)
        self.obs_filter = make_connector(
            c.observation_filter,
            info.get("obs_shape", (info["obs_dim"],)))
        self.learner = PPOLearner(
            info.get("obs_shape", info["obs_dim"]), info["num_actions"],
            lr=c.lr,
            clip=c.clip_param, vf_coeff=c.vf_loss_coeff,
            ent_coeff=c.entropy_coeff, minibatch_size=c.sgd_minibatch_size,
            num_epochs=c.num_sgd_epochs, hidden=c.hidden, seed=c.seed)
        self._iteration = 0
        self._total_steps = 0
        self._total_episodes = 0
        self._recent_returns: List[float] = []

    def train(self) -> Dict[str, Any]:
        """One iteration: parallel sample -> learner SGD -> broadcast."""
        t0 = time.monotonic()
        params_ref = ray_tpu.put(self.learner.get_params())
        batches = ray_tpu.get(
            [w.sample.remote(params_ref) for w in self.workers], timeout=300)
        sample_time = time.monotonic() - t0
        batch = sb.concat(batches)
        t1 = time.monotonic()
        stats = self.learner.update(batch)
        learn_time = time.monotonic() - t1
        # merge worker filter deltas AFTER the update (the batch already
        # holds filtered obs, so nothing here depends on the merge) and
        # broadcast without blocking: per-actor ordering guarantees
        # sync_filter lands before the next sample.remote
        if not isinstance(self.obs_filter, NoFilter):
            deltas = ray_tpu.get(
                [w.filter_delta.remote() for w in self.workers],
                timeout=60)
            state = merge_deltas(self.obs_filter, deltas)
            for w in self.workers:
                w.sync_filter.remote(state)
        # one blocking round for both independent per-worker fetches
        perf_refs = [w.perf_stats.remote() for w in self.workers]
        ret_refs = [w.episode_returns.remote() for w in self.workers]
        both = ray_tpu.get(perf_refs + ret_refs, timeout=60)
        perf = both[:len(self.workers)]
        for rets in both[len(self.workers):]:
            self._recent_returns.extend(rets)
            self._total_episodes += len(rets)
        self._recent_returns = self._recent_returns[-100:]
        self._iteration += 1
        steps = sb.num_steps(batch)
        self._total_steps += steps
        mean_ret = (float(np.mean(self._recent_returns))
                    if self._recent_returns else float("nan"))
        return {
            "training_iteration": self._iteration,
            "timesteps_total": self._total_steps,
            "timesteps_this_iter": steps,
            "episode_reward_mean": mean_ret,
            "episodes_total": self._total_episodes,
            "env_steps_per_sec": steps / max(1e-9, sample_time + learn_time),
            "sample_time_s": sample_time,
            "learn_time_s": learn_time,
            # per-stage rollout breakdown (summed across workers): the
            # remainder of sample_time is serialization + actor RPC
            "rollout_env_time_s": sum(p["env_s"] for p in perf),
            "rollout_infer_time_s": sum(p["infer_s"] for p in perf),
            **stats,
        }

    # -- Tune-trainable surface ----------------------------------------------

    def save(self) -> Dict:
        import jax

        ckpt = {"params": jax.device_get(self.learner.params),
                "opt_state": jax.device_get(self.learner.opt_state),
                "iteration": self._iteration,
                "total_steps": self._total_steps}
        if not isinstance(self.obs_filter, NoFilter):
            # without the filter stats a restored policy would see raw
            # (unnormalized) obs until the filter re-converged
            ckpt["obs_filter"] = self.obs_filter.state()
        return ckpt

    def restore(self, ckpt: Dict) -> None:
        import jax
        import jax.numpy as jnp

        self.learner.params = {k: jnp.asarray(v)
                               for k, v in ckpt["params"].items()}
        if "opt_state" in ckpt:  # Adam moments survive the round-trip
            self.learner.opt_state = jax.tree.map(jnp.asarray,
                                                  ckpt["opt_state"])
        else:
            self.learner.opt_state = self.learner.optimizer.init(
                self.learner.params)
        self._iteration = int(ckpt.get("iteration", 0))
        self._total_steps = int(ckpt.get("total_steps", 0))
        if "obs_filter" in ckpt and not isinstance(self.obs_filter,
                                                   NoFilter):
            self.obs_filter.set_state(ckpt["obs_filter"])
            ray_tpu.get([w.sync_filter.remote(ckpt["obs_filter"])
                         for w in self.workers], timeout=60)

    def stop(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
