"""CQL — conservative Q-learning for offline RL (discrete actions).

ref: rllib/algorithms/cql/cql.py (+ cql_torch_policy.py; Kumar et al.
2020). The continuous reference builds on SAC; this discrete variant
builds on the double-DQN learner, adding the conservative penalty

    L_CQL = alpha * E_s[ logsumexp_a Q(s,a) - Q(s, a_data) ] + L_TD

which pushes down Q on out-of-distribution actions so a policy greedy
in Q stays inside the dataset's support — the failure mode plain
off-policy TD has on static datasets.

House TPU shape: the dataset loads once, the whole per-iteration update
block (K minibatches of TD + penalty, periodic target sync inside the
scan via lax.cond) is ONE jitted dispatch. Consumes the experience
JSONL format of rllib.offline (write_experiences / read_experiences),
so datasets collected for MARWIL/BC train CQL unchanged.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List

import numpy as np

from .env import make_env
from .offline import read_experiences


def _episodes_to_transitions(episodes: List[Dict[str, np.ndarray]],
                             ) -> Dict[str, np.ndarray]:
    obs, acts, rews, dones, nxt = [], [], [], [], []
    for ep in episodes:
        T = len(ep["actions"])
        obs.append(ep["obs"][:T])
        acts.append(ep["actions"][:T])
        rews.append(ep["rewards"][:T])
        d = np.zeros(T, np.float32)
        d[-1] = 1.0
        dones.append(d)
        nx = np.concatenate([ep["obs"][1:T], ep["obs"][T - 1:T]], axis=0)
        nxt.append(nx)
    return {"obs": np.concatenate(obs).astype(np.float32),
            "actions": np.concatenate(acts).astype(np.int32),
            "rewards": np.concatenate(rews).astype(np.float32),
            "dones": np.concatenate(dones),
            "next_obs": np.concatenate(nxt).astype(np.float32)}


@dataclass
class CQLConfig:
    """ref: cql.py CQLConfig (bc_iters warmup omitted: the conservative
    penalty with a decent alpha covers the cold start on discrete
    benches)."""
    input_paths: Any = None           # JSONL file/dir(s) of experiences
    env: str = "CartPole-v1"          # for evaluate()
    gamma: float = 0.99
    lr: float = 5e-4
    cql_alpha: float = 1.0
    train_batch_size: int = 256
    num_updates_per_iter: int = 200
    target_update_freq: int = 100     # in updates, inside the scan
    hidden: tuple = (128, 128)
    seed: int = 0
    extra: Dict[str, Any] = field(default_factory=dict)

    def build(self) -> "CQL":
        return CQL(self)


class CQL:
    """Tune-trainable offline learner; evaluate() rolls the greedy
    policy in the (held-out) environment."""

    def __init__(self, config: CQLConfig):
        import functools

        import jax
        import jax.numpy as jnp
        import optax

        c = self.config = config
        if c.input_paths is None:
            raise ValueError("CQL is offline: set input_paths to the "
                             "experience JSONL file(s)")
        self.data = _episodes_to_transitions(
            read_experiences(c.input_paths))
        self._eval_env = make_env(c.env, num_envs=8, seed=c.seed + 9)
        obs_dim = self.data["obs"].shape[1]
        num_actions = int(self.data["actions"].max()) + 1
        num_actions = max(num_actions, self._eval_env.num_actions)
        self.num_actions = num_actions

        from .sac import _mlp_forward as mlp  # one canonical jnp MLP
        from .td3 import _mlp_init as mlp_init  # shared He-init

        self._mlp = mlp
        self.params = mlp_init(jax.random.PRNGKey(c.seed),
                               (obs_dim, *c.hidden), num_actions)
        self.target = jax.tree.map(lambda a: a.copy(), self.params)
        self.opt = optax.adam(c.lr)
        self.opt_state = self.opt.init(self.params)
        self.num_updates = 0

        def loss_fn(params, target, batch):
            q = mlp(params, batch["obs"])                     # [B, A]
            q_data = jnp.take_along_axis(
                q, batch["actions"][:, None], axis=1)[:, 0]
            # double-DQN target
            a_next = jnp.argmax(mlp(params, batch["next_obs"]), axis=1)
            tq = jnp.take_along_axis(
                mlp(target, batch["next_obs"]), a_next[:, None],
                axis=1)[:, 0]
            y = batch["rewards"] + c.gamma * (1 - batch["dones"]) * tq
            td = jnp.mean(jnp.square(q_data - jax.lax.stop_gradient(y)))
            # conservative penalty: soft-max over ALL actions minus the
            # dataset action's Q
            penalty = jnp.mean(
                jax.scipy.special.logsumexp(q, axis=1) - q_data)
            return td + c.cql_alpha * penalty, (td, penalty)

        def one_update(carry, xs):
            params, target, opt_state = carry
            batch, step_i = xs
            (loss, (td, pen)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target, batch)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            # step_i is the GLOBAL update index (offset rides in as a
            # traced scalar) — a scan-local index would never hit the
            # sync cadence when num_updates_per_iter < target_update_freq
            target = jax.lax.cond(
                (step_i + 1) % c.target_update_freq == 0,
                lambda _: jax.tree.map(lambda a: a.copy(), params),
                lambda t: t, target)
            return (params, target, opt_state), {
                "loss": loss, "td_loss": td, "cql_penalty": pen}

        @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
        def update_many(params, target, opt_state, batches, offset):
            K = batches["rewards"].shape[0]
            (params, target, opt_state), stats = jax.lax.scan(
                one_update, (params, target, opt_state),
                (batches, offset + jnp.arange(K)))
            return params, target, opt_state, jax.tree.map(
                jnp.mean, stats)

        self._update_many = update_many
        self._rng = np.random.default_rng(c.seed + 1)
        self._iteration = 0

    def train(self) -> Dict[str, float]:
        import jax
        import jax.numpy as jnp

        c = self.config
        t0 = time.monotonic()
        N = len(self.data["rewards"])
        K, B = c.num_updates_per_iter, min(c.train_batch_size, N)
        idx = self._rng.integers(0, N, K * B)
        stacked = {k: v[idx].reshape(K, B, *v.shape[1:])
                   for k, v in self.data.items()}
        batches = {k: jnp.asarray(v) for k, v in stacked.items()}
        self.params, self.target, self.opt_state, stats = \
            self._update_many(self.params, self.target, self.opt_state,
                              batches, jnp.asarray(self.num_updates))
        self.num_updates += K
        self._iteration += 1
        return {"training_iteration": self._iteration,
                "num_updates": self.num_updates,
                "dataset_size": N,
                "time_this_iter_s": time.monotonic() - t0,
                **{k: float(v)
                   for k, v in jax.device_get(stats).items()}}

    def evaluate(self, num_episodes: int = 20,
                 max_steps: int = 500) -> Dict[str, float]:
        import jax

        from .td3 import _mlp_np

        p = {k: np.asarray(v, np.float32)
             for k, v in jax.device_get(self.params).items()}

        def mlp_np(x):
            return _mlp_np(p, x)

        env = self._eval_env
        obs = env.reset(seed=self.config.seed + 77)
        returns: List[float] = []
        ep_ret = np.zeros(env.num_envs)
        for _ in range(max_steps * (num_episodes // env.num_envs + 2)):
            actions = np.argmax(mlp_np(obs), axis=1)
            obs, r, done, _ = env.step(actions)
            ep_ret += r
            if done.any():
                idx = np.nonzero(done)[0]
                returns.extend(ep_ret[idx].tolist())
                ep_ret[idx] = 0.0
            if len(returns) >= num_episodes:
                break
        return {"evaluation_reward_mean":
                float(np.mean(returns[:num_episodes]))
                if returns else float("nan")}

    # -- Tune-trainable surface ------------------------------------------

    def save(self) -> Dict:
        import jax

        return {"params": jax.device_get(self.params),
                "target": jax.device_get(self.target),
                "opt_state": jax.device_get(self.opt_state),
                "num_updates": self.num_updates,
                "iteration": self._iteration}

    def restore(self, ckpt: Dict) -> None:
        import jax
        import jax.numpy as jnp

        as_jnp = lambda t: jax.tree.map(jnp.asarray, t)  # noqa: E731
        self.params = as_jnp(ckpt["params"])
        self.target = as_jnp(ckpt["target"])
        if "opt_state" in ckpt:
            self.opt_state = as_jnp(ckpt["opt_state"])
        self.num_updates = int(ckpt.get("num_updates", 0))
        self._iteration = int(ckpt.get("iteration", 0))

    def stop(self) -> None:
        pass
