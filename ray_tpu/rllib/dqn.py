"""DQN — off-policy Q-learning through the rollout-actor/learner split.

ref: rllib/algorithms/dqn/dqn.py (DQNConfig, training_step :623:
sample → store → N replay updates → target sync) and
dqn/dqn_torch_policy.py (double-Q loss, huber TD, PER weight).

TPU-native shape mirrors PPO here: epsilon-greedy rollout inference is
pure numpy on the actor CPUs (np_policy.py rationale), the learner is one
jitted donated-buffer update on the JAX device, and the replay buffer
lives host-side in the driver where sampling is pointer math, not device
traffic. Only minibatches cross to the device.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle
import numpy as np

import ray_tpu

from . import sample_batch as sb
from .np_policy import ensure_numpy, forward_np
from .rollout_worker import EnvWorkerBase, worker_opts
from .replay_buffer import (PrioritizedReplayBuffer, ReplayBuffer,
                            fused_replay_update)

NEXT_OBS = "next_obs"


class DQNRolloutWorker(EnvWorkerBase):
    """Actor collecting epsilon-greedy transitions (ref:
    rollout_worker.py sample + dqn's EpsilonGreedy exploration). The Q-net
    reuses the fcnet param layout; the policy head IS the Q head."""

    def sample(self, params: Dict, epsilon: float) -> sb.Batch:
        params = ensure_numpy(params)
        T, n = self.rollout_len, self.env.num_envs
        A = self.env.num_actions
        obs_buf = np.empty((T, n, *self.env.obs_shape), self.env.obs_dtype)
        next_buf = np.empty((T, n, *self.env.obs_shape), self.env.obs_dtype)
        act_buf = np.empty((T, n), np.int64)
        rew_buf = np.empty((T, n), np.float32)
        done_buf = np.empty((T, n), np.bool_)
        obs = self._obs
        for t in range(T):
            q, _ = forward_np(params, obs)
            actions = q.argmax(axis=1)
            explore = self._rng.random(n) < epsilon
            actions = np.where(explore, self._rng.integers(0, A, size=n),
                               actions).astype(np.int64)
            obs_buf[t], act_buf[t] = obs, actions
            obs, reward, done, info = self.env.step(actions)
            rew_buf[t], done_buf[t] = reward, done
            next_buf[t] = obs
            self._track_returns(reward, done)
            if done.any():
                idx = np.nonzero(done)[0]
                if "final_obs" in info:
                    # auto-reset handed back the NEW episode's obs; the
                    # transition's s' is the pre-reset terminal state
                    next_buf[t, idx] = info["final_obs"][idx]
                if "truncated" in info:
                    # time-limit truncation still bootstraps: don't cut
                    # the target at a non-terminal state
                    done_buf[t] &= ~info["truncated"]
        self._obs = obs
        flat = lambda a: a.reshape(T * n, *a.shape[2:])  # noqa: E731
        return {sb.OBS: flat(obs_buf), sb.ACTIONS: flat(act_buf),
                sb.REWARDS: flat(rew_buf), sb.DONES: flat(done_buf),
                NEXT_OBS: flat(next_buf)}


class DQNLearner:
    """Jitted double-DQN update with a periodically synced target net
    (ref: dqn_torch_policy.py build_q_losses; learner.py donation
    rationale). Returns |TD| so prioritized replay can refresh
    priorities without a second device pass."""

    def __init__(self, obs_dim, num_actions: int, *, lr: float = 5e-4,
                 gamma: float = 0.99, double_q: bool = True,
                 hidden=(64, 64), seed: int = 0,
                 max_grad_norm: float = 10.0):
        import jax
        import optax

        from .models import init_policy_params

        self.params = init_policy_params(jax.random.PRNGKey(seed), obs_dim,
                                         num_actions, tuple(hidden))
        self.target_params = jax.tree.map(lambda a: a.copy(), self.params)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(max_grad_norm), optax.adam(lr))
        self.opt_state = self.optimizer.init(self.params)
        self._update = jax.jit(self._make_update(gamma, double_q),
                               donate_argnums=(0, 1))
        self._update_many = jax.jit(
            self._make_update_many(gamma, double_q), donate_argnums=(0, 1))
        self.num_updates = 0

    def _make_update(self, gamma: float, double_q: bool):
        import jax
        import jax.numpy as jnp
        import optax

        from .models import forward

        def q_values(params, obs):
            logits, _ = forward(params, obs)  # policy head doubles as Q head
            return logits

        def loss_fn(params, target_params, batch, weights):
            q = q_values(params, batch[sb.OBS])
            q_sa = jnp.take_along_axis(
                q, batch[sb.ACTIONS][:, None], axis=1)[:, 0]
            q_next_target = q_values(target_params, batch[NEXT_OBS])
            if double_q:
                # online net selects, target net evaluates
                a_star = q_values(params, batch[NEXT_OBS]).argmax(axis=1)
            else:
                a_star = q_next_target.argmax(axis=1)
            q_next = jnp.take_along_axis(
                q_next_target, a_star[:, None], axis=1)[:, 0]
            not_done = 1.0 - batch[sb.DONES].astype(jnp.float32)
            y = batch[sb.REWARDS] + gamma * not_done \
                * jax.lax.stop_gradient(q_next)
            td = q_sa - y
            huber = optax.huber_loss(q_sa, y, delta=1.0)
            loss = jnp.mean(weights * huber)
            return loss, (jnp.abs(td), jnp.mean(q_sa))

        def update(params, opt_state, target_params, batch, weights):
            (loss, (td_abs, mean_q)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, batch, weights)
            updates, opt_state = self.optimizer.update(grads, opt_state,
                                                       params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, td_abs, mean_q

        return update

    def _make_update_many(self, gamma: float, double_q: bool):
        """The whole per-iteration SGD block as ONE jitted lax.scan over
        pre-sampled minibatches — one dispatch and one readback no matter
        how many updates, which is what keeps the learner viable when the
        device sits behind a network tunnel (the round-2 PPO lesson,
        learner.py make_epoch_update_fn)."""
        import jax

        step = self._make_update(gamma, double_q)

        def update_many(params, opt_state, target_params, batches, weights):
            def body(carry, xs):
                params, opt_state = carry
                batch_k, w_k = xs
                params, opt_state, loss, td_abs, mean_q = step(
                    params, opt_state, target_params, batch_k, w_k)
                return (params, opt_state), (loss, td_abs, mean_q)

            (params, opt_state), (losses, td_abs, mean_qs) = jax.lax.scan(
                body, (params, opt_state), (batches, weights))
            return params, opt_state, losses, td_abs, mean_qs

        return update_many

    def update_many(self, batches: sb.Batch,
                    weights: Optional[np.ndarray] = None) -> Dict[str, Any]:
        """batches: dict of [K, B, ...] arrays — K minibatches applied
        sequentially on-device. Returns per-minibatch |TD| [K, B]."""
        import jax
        import jax.numpy as jnp

        K, B = batches[sb.OBS].shape[:2]
        w = jnp.ones((K, B)) if weights is None else jnp.asarray(weights)
        jb = {k: jnp.asarray(batches[k]) for k in
              (sb.OBS, sb.ACTIONS, sb.REWARDS, sb.DONES, NEXT_OBS)}
        (self.params, self.opt_state, losses, td_abs,
         mean_qs) = self._update_many(self.params, self.opt_state,
                                      self.target_params, jb, w)
        self.num_updates += K
        out = jax.device_get((losses, td_abs, mean_qs))
        return {"loss": float(np.mean(out[0])),
                "mean_q": float(np.mean(out[2])),
                "td_abs": np.asarray(out[1])}

    def update(self, batch: sb.Batch,
               weights: Optional[np.ndarray] = None) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        n = len(batch[sb.OBS])
        w = jnp.ones(n) if weights is None else jnp.asarray(weights)
        jb = {k: jnp.asarray(batch[k]) for k in
              (sb.OBS, sb.ACTIONS, sb.REWARDS, sb.DONES, NEXT_OBS)}
        self.params, self.opt_state, loss, td_abs, mean_q = self._update(
            self.params, self.opt_state, self.target_params, jb, w)
        self.num_updates += 1
        return {"loss": float(loss), "mean_q": float(mean_q),
                "td_abs": np.asarray(jax.device_get(td_abs))}

    def sync_target(self) -> None:
        import jax

        self.target_params = jax.tree.map(lambda a: a.copy(), self.params)

    def get_params(self) -> Dict:
        import jax

        return jax.device_get(self.params)


@dataclass
class DQNConfig:
    """ref: dqn.py DQNConfig defaults (buffer 50k, eps 1.0→0.02,
    target_network_update_freq, training_intensity)."""
    env: str = "CartPole-v1"
    env_creator: Optional[Callable] = None
    num_rollout_workers: int = 2
    num_envs_per_worker: int = 8
    rollout_fragment_length: int = 32
    gamma: float = 0.99
    lr: float = 5e-4
    buffer_size: int = 50_000
    prioritized_replay: bool = True
    prioritized_replay_alpha: float = 0.6
    prioritized_replay_beta: float = 0.4
    train_batch_size: int = 64
    num_updates_per_iter: int = 16
    learning_starts: int = 1_000
    target_update_freq: int = 200  # in learner updates
    epsilon_initial: float = 1.0
    epsilon_final: float = 0.02
    epsilon_decay_steps: int = 10_000
    double_q: bool = True
    hidden: tuple = (64, 64)
    seed: int = 0
    worker_resources: Dict[str, float] = field(default_factory=dict)
    # include the replay buffer in save() so a restored trial (Tune PBT
    # exploit, pause/resume) resumes warm; disable for image/large buffers
    # where checkpoints would be GB-sized (ref: algorithm_config
    # store_buffer_in_checkpoints)
    checkpoint_replay_buffer: bool = True

    def environment(self, env: str = None, *, env_creator=None) -> "DQNConfig":
        if env is not None:
            self.env = env
        if env_creator is not None:
            self.env_creator = env_creator
        return self

    def rollouts(self, *, num_rollout_workers: int = None,
                 num_envs_per_worker: int = None,
                 rollout_fragment_length: int = None) -> "DQNConfig":
        for k, v in [("num_rollout_workers", num_rollout_workers),
                     ("num_envs_per_worker", num_envs_per_worker),
                     ("rollout_fragment_length", rollout_fragment_length)]:
            if v is not None:
                setattr(self, k, v)
        return self

    def training(self, *, lr: float = None, gamma: float = None,
                 train_batch_size: int = None, buffer_size: int = None,
                 num_updates_per_iter: int = None,
                 learning_starts: int = None,
                 target_update_freq: int = None,
                 prioritized_replay: bool = None,
                 epsilon_decay_steps: int = None) -> "DQNConfig":
        for k, v in [("lr", lr), ("gamma", gamma),
                     ("train_batch_size", train_batch_size),
                     ("buffer_size", buffer_size),
                     ("num_updates_per_iter", num_updates_per_iter),
                     ("learning_starts", learning_starts),
                     ("target_update_freq", target_update_freq),
                     ("prioritized_replay", prioritized_replay),
                     ("epsilon_decay_steps", epsilon_decay_steps)]:
            if v is not None:
                setattr(self, k, v)
        return self

    def build(self) -> "DQN":
        return DQN(self)


class DQN:
    """Synchronous DQN (ref: dqn.py training_step): parallel epsilon-greedy
    sample → replay add → N prioritized updates → periodic target sync.
    Tune-trainable shaped like PPO."""

    def __init__(self, config: DQNConfig):
        self.config = c = config
        creator_blob = (cloudpickle.dumps(c.env_creator)
                        if c.env_creator else None)
        worker_cls = ray_tpu.remote(DQNRolloutWorker)
        opts = worker_opts(c.worker_resources)
        self.workers: List = [
            worker_cls.options(**opts).remote(
                c.env, c.num_envs_per_worker, c.rollout_fragment_length,
                seed=c.seed + 1000 * i, env_creator=creator_blob)
            for i in range(c.num_rollout_workers)]
        info = ray_tpu.get(self.workers[0].env_info.remote(), timeout=180)
        self.learner = DQNLearner(
            info.get("obs_shape", info["obs_dim"]), info["num_actions"], lr=c.lr, gamma=c.gamma,
            double_q=c.double_q, hidden=c.hidden, seed=c.seed)
        if c.prioritized_replay:
            self.buffer = PrioritizedReplayBuffer(
                c.buffer_size, alpha=c.prioritized_replay_alpha,
                beta=c.prioritized_replay_beta, seed=c.seed)
        else:
            self.buffer = ReplayBuffer(c.buffer_size, seed=c.seed)
        self._iteration = 0
        self._total_steps = 0
        self._total_episodes = 0
        self._recent_returns: List[float] = []

    def _epsilon(self) -> float:
        c = self.config
        frac = min(1.0, self._total_steps / max(1, c.epsilon_decay_steps))
        return c.epsilon_initial + frac * (c.epsilon_final
                                           - c.epsilon_initial)

    def train(self) -> Dict[str, Any]:
        c = self.config
        t0 = time.monotonic()
        eps = self._epsilon()
        params_ref = ray_tpu.put(self.learner.get_params())
        batches = ray_tpu.get(
            [w.sample.remote(params_ref, eps) for w in self.workers],
            timeout=300)
        batch = sb.concat(batches)
        steps = sb.num_steps(batch)
        self._total_steps += steps
        self.buffer.add(batch)
        sample_time = time.monotonic() - t0
        t1 = time.monotonic()
        stats: Dict[str, Any] = {}
        if len(self.buffer) >= c.learning_starts:
            # All K updates ride ONE device dispatch (lax.scan). PER
            # priorities refresh after the block rather than between
            # minibatches — K·B-transition staleness, the standard
            # trade for distributed/batched DQN variants (cf. Ape-X,
            # where actors' priorities are a full generation stale).
            K = c.num_updates_per_iter
            out = fused_replay_update(self.buffer,
                                      self.learner.update_many, K,
                                      c.train_batch_size, "td_abs")
            # target sync at block granularity (at most K updates late)
            n = self.learner.num_updates
            if n // c.target_update_freq > (n - K) // c.target_update_freq:
                self.learner.sync_target()
            stats = {"loss": out["loss"], "mean_q": out["mean_q"],
                     "num_updates": n}
        learn_time = time.monotonic() - t1
        for rets in ray_tpu.get(
                [w.episode_returns.remote() for w in self.workers],
                timeout=60):
            self._recent_returns.extend(rets)
            self._total_episodes += len(rets)
        self._recent_returns = self._recent_returns[-100:]
        self._iteration += 1
        mean_ret = (float(np.mean(self._recent_returns))
                    if self._recent_returns else float("nan"))
        return {"training_iteration": self._iteration,
                "timesteps_total": self._total_steps,
                "timesteps_this_iter": steps,
                "episode_reward_mean": mean_ret,
                "episodes_total": self._total_episodes,
                "epsilon": eps,
                "buffer_size": len(self.buffer),
                "env_steps_per_sec": steps / max(1e-9,
                                                 sample_time + learn_time),
                "sample_time_s": sample_time, "learn_time_s": learn_time,
                **stats}

    # -- Tune-trainable surface ------------------------------------------

    def save(self) -> Dict:
        import jax

        ckpt = {"params": jax.device_get(self.learner.params),
                "target_params": jax.device_get(self.learner.target_params),
                "opt_state": jax.device_get(self.learner.opt_state),
                "iteration": self._iteration,
                "total_steps": self._total_steps,
                "num_updates": self.learner.num_updates}
        if self.config.checkpoint_replay_buffer:
            # a restored trial (Tune PBT exploit, pause/resume) must not
            # restart cold: without the buffer it stalls until
            # learning_starts refills and all PER priorities are lost
            ckpt["buffer"] = self.buffer.state()
        return ckpt

    def restore(self, ckpt: Dict) -> None:
        import jax
        import jax.numpy as jnp

        as_jnp = lambda t: jax.tree.map(jnp.asarray, t)  # noqa: E731
        self.learner.params = as_jnp(ckpt["params"])
        self.learner.target_params = as_jnp(ckpt["target_params"])
        if "opt_state" in ckpt:
            self.learner.opt_state = as_jnp(ckpt["opt_state"])
        self.learner.num_updates = int(ckpt.get("num_updates", 0))
        self._iteration = int(ckpt.get("iteration", 0))
        self._total_steps = int(ckpt.get("total_steps", 0))
        if "buffer" in ckpt:
            self.buffer.restore(ckpt["buffer"])

    def stop(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
