"""RolloutWorker — an actor sampling a vector env with the current policy.

ref: rllib/evaluation/rollout_worker.py (sample :660) + env_runner_v2.py.
The whole T×n rollout is vector math: one jitted policy forward per step
over all n envs, numpy env stepping, GAE computed worker-side so the
learner receives train-ready batches through the object store.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from . import sample_batch as sb
from .connectors import NoFilter, make_connector
from .env import make_env
from .np_policy import ensure_numpy, sample_actions


def worker_opts(worker_resources):
    """Resource dict for a rollout actor: CPU becomes num_cpus, everything
    else rides through as custom resources (shared by PPO/DQN/IMPALA)."""
    opts = {"num_cpus": worker_resources.get("CPU", 1.0)}
    extra = {k: v for k, v in worker_resources.items() if k != "CPU"}
    if extra:
        opts["resources"] = extra
    return opts


class EnvWorkerBase:
    """Shared rollout-actor plumbing: env construction (by name or
    pickled creator), the persistent obs, the RNG, and episode-return
    bookkeeping. PPO / DQN / IMPALA workers differ only in sample()."""

    def __init__(self, env_name: str, num_envs: int, rollout_len: int,
                 seed: int = 0, env_creator=None):
        import cloudpickle

        if env_creator is not None:
            creator = cloudpickle.loads(env_creator)
            self.env = creator(num_envs=num_envs, seed=seed)
        else:
            self.env = make_env(env_name, num_envs=num_envs, seed=seed)
        self.rollout_len = rollout_len
        self._rng = np.random.default_rng(seed + 1)
        self._obs = self.env.reset(seed=seed)
        # episode-return bookkeeping (survives across sample() calls)
        self._ep_return = np.zeros(self.env.num_envs, np.float64)
        self._finished_returns: list = []

    def _track_returns(self, reward: np.ndarray, done: np.ndarray) -> None:
        self._ep_return += reward
        if done.any():
            idx = np.nonzero(done)[0]
            self._finished_returns.extend(self._ep_return[idx].tolist())
            self._ep_return[idx] = 0.0

    def episode_returns(self, clear: bool = True) -> list:
        out = list(self._finished_returns)
        if clear:
            self._finished_returns.clear()
        return out

    def env_info(self) -> dict:
        return {"obs_dim": self.env.obs_dim,
                "obs_shape": tuple(self.env.obs_shape),
                "num_actions": self.env.num_actions,
                "num_envs": self.env.num_envs}


class RolloutWorker(EnvWorkerBase):
    def __init__(self, env_name: str, num_envs: int, rollout_len: int,
                 gamma: float, lam: float, seed: int = 0,
                 env_creator=None, observation_filter: str = "NoFilter"):
        super().__init__(env_name, num_envs, rollout_len, seed, env_creator)
        self.gamma = gamma
        self.lam = lam
        self.filter = make_connector(observation_filter,
                                     self.env.obs_shape)
        self._perf = {"env_s": 0.0, "infer_s": 0.0}

    def filter_delta(self):
        """Stats accumulated since the last sync (merged centrally)."""
        return self.filter.delta()

    def sync_filter(self, state) -> bool:
        self.filter.set_state(state)
        return True

    def perf_stats(self, clear: bool = True) -> Dict[str, float]:
        """Cumulative seconds spent in env.step vs policy inference since
        the last call — the per-stage breakdown for locating the rollout
        bottleneck (ref: rllib sampler perf_stats, metrics.py)."""
        out = dict(self._perf)
        if clear:
            self._perf = {"env_s": 0.0, "infer_s": 0.0}
        return out

    def sample(self, params: Dict) -> sb.Batch:
        import time as _time

        params = ensure_numpy(params)  # one conversion, not one per step
        T, n = self.rollout_len, self.env.num_envs
        # a filter emits float32; only the pass-through keeps the env's
        # native dtype (uint8 image obs must not silently truncate)
        obs_dtype = (self.env.obs_dtype if isinstance(self.filter, NoFilter)
                     else np.float32)
        obs_buf = np.empty((T, n, *self.env.obs_shape), obs_dtype)
        act_buf = np.empty((T, n), np.int64)
        logp_buf = np.empty((T, n), np.float32)
        val_buf = np.empty((T, n), np.float32)
        rew_buf = np.empty((T, n), np.float32)
        done_buf = np.empty((T, n), np.bool_)
        obs = self._obs
        for t in range(T):
            fobs = self.filter(obs)  # connector: batches hold FILTERED obs
            t0 = _time.perf_counter()
            actions, logp, values = sample_actions(params, fobs, self._rng)
            self._perf["infer_s"] += _time.perf_counter() - t0
            obs_buf[t], act_buf[t] = fobs, actions
            logp_buf[t], val_buf[t] = logp, values
            t1 = _time.perf_counter()  # buffer copies stay OUT of env_s
            obs, reward, done, info = self.env.step(actions)
            self._perf["env_s"] += _time.perf_counter() - t1
            rew_buf[t], done_buf[t] = reward, done
            if done.any() and "truncated" in info:
                # time-limit truncation is not termination: fold
                # gamma*V(s_final) into the reward so GAE's done-cut
                # doesn't zero a bootstrap that should exist (ref:
                # postprocessing.py time-limit handling)
                trunc = info["truncated"]
                if trunc.any():
                    idx = np.nonzero(trunc)[0]
                    _, _, v_final = sample_actions(
                        params,
                        self.filter(info["final_obs"][idx], update=False),
                        self._rng)
                    rew_buf[t, idx] += self.gamma * v_final
            self._track_returns(reward, done)
        self._obs = obs
        _, _, last_values = sample_actions(
            params, self.filter(obs, update=False), self._rng)
        adv, ret = sb.compute_gae(rew_buf, val_buf, done_buf, last_values,
                                  self.gamma, self.lam)
        flat = lambda a: a.reshape(T * n, *a.shape[2:])  # noqa: E731
        return {
            sb.OBS: flat(obs_buf), sb.ACTIONS: flat(act_buf),
            sb.LOGP: flat(logp_buf), sb.VALUES: flat(val_buf),
            sb.REWARDS: flat(rew_buf), sb.DONES: flat(done_buf),
            sb.ADVANTAGES: flat(adv), sb.RETURNS: flat(ret),
        }

