"""External-environment protocol: PolicyServerInput + ExternalPPO.

ref: rllib/env/policy_server_input.py + rllib/env/policy_client.py —
an external simulator (a game engine, a robot, another process) drives
episodes against a policy served over HTTP: it asks for actions,
reports rewards, and the server turns the completed episodes into
train-ready batches. The reference speaks pickle over HTTP between its
client/server; here the protocol is JSON (obs/actions as lists) so a
client needs nothing but an HTTP library — no ray_tpu import, no
codegen, no pickle trust.

Server side: on-policy inference runs the same numpy policy path as the
rollout workers (np_policy.sample_actions — action, logp, value per
request), and episode completion computes GAE exactly like
RolloutWorker.sample, so ExternalPPO's learner consumes identical
batches whether experience came from local workers or external sims.
"""
from __future__ import annotations

import json
import threading
import time
import uuid
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import sample_batch as sb
from .np_policy import ensure_numpy, sample_actions


class _Episode:
    __slots__ = ("obs", "actions", "logp", "values", "rewards")

    def __init__(self):
        self.obs: List[np.ndarray] = []
        self.actions: List[int] = []
        self.logp: List[float] = []
        self.values: List[float] = []
        self.rewards: List[float] = []


class PolicyServerInput:
    """Serves get_action over HTTP and accumulates completed episodes
    into PPO sample batches (ref: policy_server_input.py)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 gamma: float = 0.99, lam: float = 0.95):
        self._gamma, self._lam = gamma, lam
        self._params: Optional[Dict[str, np.ndarray]] = None
        self._rng = np.random.default_rng(0)
        self._episodes: Dict[str, _Episode] = {}
        self._done: List[Tuple[dict, float]] = []  # (columns, ep_return)
        self._lock = threading.Lock()
        self._have_data = threading.Condition(self._lock)
        srv = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):  # noqa: N802
                n = int(self.headers.get("Content-Length", 0))
                try:
                    req = json.loads(self.rfile.read(n) or b"{}")
                    out = srv._handle(self.path.strip("/"), req)
                    body = json.dumps(out).encode()
                    code = 200
                except Exception as e:  # noqa: BLE001
                    body = json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode()
                    code = 400
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.address = self._server.server_address
        threading.Thread(target=self._server.serve_forever, daemon=True,
                         name="policy-server").start()

    # -- protocol ---------------------------------------------------------

    def _handle(self, route: str, req: dict) -> dict:
        if route == "start_episode":
            eid = req.get("episode_id") or uuid.uuid4().hex[:12]
            with self._lock:
                self._episodes[eid] = _Episode()
            return {"episode_id": eid}
        if route == "get_action":
            eid = req["episode_id"]
            obs = np.asarray(req["observation"], np.float32)
            with self._lock:
                ep = self._episodes.get(eid)
                params = self._params
            if ep is None:
                raise KeyError(f"unknown episode {eid}")
            if params is None:
                raise RuntimeError("no policy set yet")
            a, logp, v = sample_actions(params, obs[None], self._rng)
            with self._lock:
                ep.obs.append(obs)
                ep.actions.append(int(a[0]))
                ep.logp.append(float(logp[0]))
                ep.values.append(float(v[0]))
            return {"action": int(a[0])}
        if route == "log_returns":
            with self._lock:
                ep = self._episodes.get(req["episode_id"])
                if ep is None:
                    raise KeyError("unknown episode")
                ep.rewards.append(float(req["reward"]))
            return {}
        if route == "end_episode":
            eid = req["episode_id"]
            # done=True episode: no bootstrap. A truncated episode may
            # pass its final observation for V(s_T) bootstrapping.
            final_obs = req.get("observation")
            with self._lock:
                ep = self._episodes.pop(eid, None)
                params = self._params
            if ep is None or not ep.obs:
                return {}
            last_v = 0.0
            if final_obs is not None and req.get("truncated") and params:
                _, _, v = sample_actions(
                    params, np.asarray(final_obs, np.float32)[None],
                    self._rng)
                last_v = float(v[0])
            cols = self._finish(ep, last_v)
            if cols is not None:
                with self._have_data:
                    self._done.append((cols, float(np.sum(ep.rewards))))
                    self._have_data.notify_all()
            return {}
        raise ValueError(f"unknown route {route!r}")

    def _finish(self, ep: _Episode, last_value: float) -> Optional[dict]:
        T = min(len(ep.actions), len(ep.rewards))
        if T == 0:
            return None  # actions with no logged rewards: nothing usable
        rew = np.asarray(ep.rewards[:T], np.float32)[:, None]
        val = np.asarray(ep.values[:T], np.float32)[:, None]
        dones = np.zeros((T, 1), np.bool_)
        dones[-1] = True
        adv, ret = sb.compute_gae(rew, val, dones,
                                  np.asarray([last_value], np.float32),
                                  self._gamma, self._lam)
        return {
            sb.OBS: np.stack(ep.obs[:T]),
            sb.ACTIONS: np.asarray(ep.actions[:T], np.int64),
            sb.LOGP: np.asarray(ep.logp[:T], np.float32),
            sb.VALUES: val[:, 0],
            sb.REWARDS: rew[:, 0],
            sb.DONES: dones[:, 0],
            sb.ADVANTAGES: adv[:, 0],
            sb.RETURNS: ret[:, 0],
        }

    # -- trainer surface ---------------------------------------------------

    def set_policy(self, params: Dict[str, np.ndarray]) -> None:
        with self._lock:
            self._params = ensure_numpy(params)

    def collect(self, min_steps: int, timeout: float = 300.0
                ) -> Tuple[Optional[dict], List[float]]:
        """Block until >= min_steps of completed-episode experience is
        buffered; -> (concatenated batch, episode returns)."""
        deadline = time.monotonic() + timeout
        with self._have_data:
            while True:
                have = sum(len(c[sb.ACTIONS]) for c, _ in self._done)
                if have >= min_steps:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._have_data.wait(
                        min(1.0, remaining)):
                    if time.monotonic() >= deadline:
                        break
            done, self._done = self._done, []
        if not done:
            return None, []
        cols = [c for c, _ in done]
        batch = {k: np.concatenate([c[k] for c in cols]) for k in cols[0]}
        return batch, [r for _, r in done]

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()


@dataclass
class ExternalPPOConfig:
    """PPO trained purely from external-client experience."""
    obs_dim: int = 4
    num_actions: int = 2
    train_batch_size: int = 512
    gamma: float = 0.99
    lam: float = 0.95
    lr: float = 3e-4
    sgd_minibatch_size: int = 128
    num_sgd_epochs: int = 4
    hidden: tuple = (64, 64)
    host: str = "127.0.0.1"
    port: int = 0
    seed: int = 0
    extra: Dict[str, Any] = field(default_factory=dict)

    def build(self) -> "ExternalPPO":
        return ExternalPPO(self)


class ExternalPPO:
    """PPO whose only experience source is a PolicyServerInput — the
    reference's external-env deployment shape (ref: rllib/examples/
    serving/cartpole_server.py)."""

    def __init__(self, config: ExternalPPOConfig):
        from .learner import PPOLearner

        c = self.config = config
        self.learner = PPOLearner(
            c.obs_dim, c.num_actions, lr=c.lr,
            minibatch_size=c.sgd_minibatch_size,
            num_epochs=c.num_sgd_epochs, hidden=tuple(c.hidden),
            seed=c.seed)
        self.server = PolicyServerInput(c.host, c.port, gamma=c.gamma,
                                        lam=c.lam)
        self.server.set_policy(self.learner.get_params())
        self.address = self.server.address
        self._iteration = 0
        self._total_steps = 0
        self._recent: List[float] = []

    def train(self) -> Dict[str, float]:
        c = self.config
        batch, returns = self.server.collect(c.train_batch_size)
        stats: Dict[str, float] = {}
        steps = 0
        if batch is not None:
            steps = len(batch[sb.ACTIONS])
            stats = self.learner.update(batch)
            self.server.set_policy(self.learner.get_params())
        self._recent.extend(returns)
        self._recent = self._recent[-100:]
        self._total_steps += steps
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "timesteps_total": self._total_steps,
            "timesteps_this_iter": steps,
            "episode_reward_mean": (float(np.mean(self._recent))
                                    if self._recent else float("nan")),
            **stats,
        }

    def save(self) -> Dict:
        import jax

        return {"params": jax.device_get(self.learner.params),
                "iteration": self._iteration,
                "total_steps": self._total_steps}

    def restore(self, ckpt: Dict) -> None:
        import jax
        import jax.numpy as jnp

        self.learner.params = jax.tree.map(jnp.asarray, ckpt["params"])
        self.server.set_policy(self.learner.get_params())
        self._iteration = int(ckpt.get("iteration", 0))
        self._total_steps = int(ckpt.get("total_steps", 0))

    def stop(self) -> None:
        self.server.shutdown()
