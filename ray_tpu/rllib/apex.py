"""Ape-X DQN — distributed prioritized experience replay
(Horgan et al. 2018).

ref: rllib/algorithms/apex_dqn/apex_dqn.py (ApexDQNConfig:
num_replay_buffer_shards, per-worker exploration epsilons, worker-side
initial priorities) + rllib/utils/replay_buffers/multi_agent_replay_buffer
sharding and execution/learner_thread.py.

The Ape-X topology maps 1:1 onto this runtime's actor plane:

    rollout actors --(batch + initial |TD|)--> replay-shard actors
    driver learner <--(sampled minibatches)--- replay-shard actors
    driver learner --(new priorities)--------> replay-shard actors

Rollout workers hold per-actor epsilons eps_i = base^(1 + i*alpha/(N-1))
(the paper's exploration ladder), compute initial priorities with their
local numpy net, and push straight to a replay shard — the driver is NOT
on the experience path (worker->shard is an actor-to-actor call through
the object store). The learner is the house DQNLearner: all K updates of
an iteration ride one jitted lax.scan dispatch.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle
import numpy as np

import ray_tpu

from . import sample_batch as sb
from .dqn import DQNLearner, DQNRolloutWorker, NEXT_OBS
from .replay_buffer import PrioritizedReplayBuffer
from .rollout_worker import worker_opts


class ReplayShardActor:
    """One shard of the distributed prioritized replay (ref: apex_dqn.py
    ReplayActor). Additions carry worker-computed priorities instead of
    the max-priority default."""

    def __init__(self, capacity: int, alpha: float, beta: float,
                 seed: int = 0):
        self.buffer = PrioritizedReplayBuffer(capacity, alpha=alpha,
                                              beta=beta, seed=seed)
        self._added = 0
        # per-slot write generation: learner priority write-backs race
        # with worker pushes once the ring wraps; a generation mismatch
        # means the slot was overwritten mid-flight and the update must
        # be dropped, not applied to the unrelated new transition
        self._gen = np.zeros(self.buffer.capacity, np.int64)

    def add(self, batch: Dict[str, np.ndarray],
            priorities: np.ndarray) -> int:
        idx = self.buffer.add(batch)
        self.buffer.update_priorities(idx, np.asarray(priorities))
        self._gen[idx] += 1
        self._added += len(idx)
        return self._added

    def sample(self, batch_size: int):
        """-> (batch, ring_idx, slot_generations, weights) or None while
        warming up."""
        if len(self.buffer) < batch_size:
            return None
        batch, idx, w = self.buffer.sample(batch_size)
        return batch, idx, self._gen[idx].copy(), w

    def update_priorities(self, idx: np.ndarray, gen: np.ndarray,
                          td_abs: np.ndarray) -> int:
        """Applies updates only where the slot generation still matches
        the sample-time snapshot; returns how many were dropped as
        stale."""
        idx = np.asarray(idx)
        live = self._gen[idx] == np.asarray(gen)
        if live.any():
            self.buffer.update_priorities(idx[live],
                                          np.asarray(td_abs)[live])
        return int((~live).sum())

    def size(self) -> int:
        return len(self.buffer)

    def state(self) -> Dict:
        # alpha rides along: stored leaf priorities are p^alpha, and a
        # cross-config restore must de-exponentiate with the SOURCE
        # alpha, not the destination's
        return {"buffer": self.buffer.state(), "added": self._added,
                "alpha": self.buffer.alpha}

    def restore_state(self, s: Dict) -> bool:
        self.buffer.restore(s["buffer"])
        self._added = int(s.get("added", 0))
        self._gen = np.zeros(self.buffer.capacity, np.int64)
        self._gen[:len(self.buffer)] = 1
        return True


class ApexRolloutWorker(DQNRolloutWorker):
    """DQN sampling plus worker-side initial priorities and direct
    pushes to a replay shard (ref: apex_dqn.py workers computing
    td_error before ReplayActor.add)."""

    def __init__(self, env_name: str, num_envs: int, rollout_len: int,
                 epsilon: float, gamma: float, seed: int = 0,
                 env_creator=None):
        super().__init__(env_name, num_envs, rollout_len, seed,
                         env_creator)
        self.epsilon = epsilon
        self.gamma = gamma

    def _initial_priorities(self, p: Dict, batch: sb.Batch) -> np.ndarray:
        from .np_policy import forward_np

        q, _ = forward_np(p, batch[sb.OBS])
        q_sa = np.take_along_axis(q, batch[sb.ACTIONS][:, None],
                                  axis=1)[:, 0]
        q_next, _ = forward_np(p, batch[NEXT_OBS])
        not_done = 1.0 - batch[sb.DONES].astype(np.float32)
        y = batch[sb.REWARDS] + self.gamma * not_done * q_next.max(axis=1)
        return np.abs(q_sa - y) + 1e-6

    def sample_and_push(self, params: Dict, shard) -> int:
        """One rollout -> priorities -> push to the shard actor. Returns
        env-steps collected (the driver's accounting)."""
        from .np_policy import ensure_numpy

        p = ensure_numpy(params)
        batch = self.sample(p, self.epsilon)
        prios = self._initial_priorities(p, batch)
        # actor-to-actor: the batch goes worker->shard through the
        # object store; the driver never touches it
        shard.add.remote(batch, prios)
        return len(batch[sb.REWARDS])


def per_worker_epsilons(n: int, base: float = 0.4,
                        alpha: float = 7.0) -> List[float]:
    """The Ape-X exploration ladder: eps_i = base^(1 + i*alpha/(N-1))."""
    if n == 1:
        return [base]
    return [base ** (1 + i * alpha / (n - 1)) for i in range(n)]


@dataclass
class ApexDQNConfig:
    """ref: apex_dqn.py ApexDQNConfig (n_replay_shards, per-worker
    epsilon, training-intensity-style learner loop)."""
    env: str = "CartPole-v1"
    env_creator: Optional[Callable] = None
    num_rollout_workers: int = 4
    num_envs_per_worker: int = 8
    rollout_fragment_length: int = 32
    num_replay_shards: int = 2
    gamma: float = 0.99
    lr: float = 5e-4
    buffer_size: int = 50_000            # per shard
    prioritized_replay_alpha: float = 0.6
    prioritized_replay_beta: float = 0.4
    train_batch_size: int = 64
    num_updates_per_iter: int = 16
    learning_starts: int = 1_000         # transitions across all shards
    target_update_freq: int = 200
    epsilon_base: float = 0.4
    epsilon_alpha: float = 7.0
    double_q: bool = True
    hidden: tuple = (64, 64)
    seed: int = 0
    # gather all shard buffers into save() (the dqn.py warm-restore
    # rationale); off by default because Ape-X buffers are sized for
    # throughput (shards x buffer_size transitions per checkpoint)
    checkpoint_replay_buffer: bool = False
    worker_resources: Dict[str, float] = field(default_factory=dict)

    def build(self) -> "ApexDQN":
        return ApexDQN(self)


class ApexDQN:
    """Ape-X driver: async sample/push riding alongside the learner loop
    (Tune-trainable shaped)."""

    def __init__(self, config: ApexDQNConfig):
        self.config = c = config
        creator_blob = (cloudpickle.dumps(c.env_creator)
                        if c.env_creator else None)
        shard_cls = ray_tpu.remote(ReplayShardActor)
        self.shards = [
            # memory-service actors: zero CPU demand so N workers + M
            # shards fit a num_cpus=N cluster (shards only do pointer
            # math between rollout bursts)
            shard_cls.options(num_cpus=0.0).remote(
                c.buffer_size, c.prioritized_replay_alpha,
                c.prioritized_replay_beta, seed=c.seed + i)
            for i in range(c.num_replay_shards)]
        eps = per_worker_epsilons(c.num_rollout_workers, c.epsilon_base,
                                  c.epsilon_alpha)
        # the metric label is only as greedy as the ladder's last rung
        # (n=1 means eps_base itself) — reported so consumers can see it
        self._greedy_eps = eps[-1]
        worker_cls = ray_tpu.remote(ApexRolloutWorker)
        opts = worker_opts(c.worker_resources)
        self.workers: List = [
            worker_cls.options(**opts).remote(
                c.env, c.num_envs_per_worker, c.rollout_fragment_length,
                eps[i], c.gamma, seed=c.seed + 1000 * i,
                env_creator=creator_blob)
            for i in range(c.num_rollout_workers)]
        info = ray_tpu.get(self.workers[0].env_info.remote(), timeout=180)
        self.learner = DQNLearner(
            info.get("obs_shape", info["obs_dim"]), info["num_actions"],
            lr=c.lr, gamma=c.gamma, double_q=c.double_q, hidden=c.hidden,
            seed=c.seed)
        self._iteration = 0
        self._total_steps = 0
        self._total_episodes = 0
        self._recent: List[float] = []
        self._recent_greedy: List[float] = []

    def train(self) -> Dict[str, Any]:
        c = self.config
        t0 = time.monotonic()
        params_ref = ray_tpu.put(self.learner.get_params())
        # kick off all rollouts; each worker pushes to a shard on its own
        # (round-robin across iterations so shards fill evenly)
        sample_futs = [
            w.sample_and_push.remote(
                params_ref,
                self.shards[(i + self._iteration)
                            % len(self.shards)])
            for i, w in enumerate(self.workers)]

        # learner loop overlaps the rollouts: pull minibatches from the
        # shards, update in one dispatch, write priorities back
        stats: Dict[str, Any] = {}
        learn_time = 0.0
        sizes = ray_tpu.get([s.size.remote() for s in self.shards],
                            timeout=60)
        if sum(sizes) >= c.learning_starts:
            t1 = time.monotonic()
            K = c.num_updates_per_iter
            draw_shards = [self.shards[k % len(self.shards)]
                           for k in range(K)]
            draw_futs = [s.sample.remote(c.train_batch_size)
                         for s in draw_shards]
            # keep the (shard, draw) pairing through the None filter so
            # priority updates go back to the ring that produced the rows
            pairs = [(s, d) for s, d in
                     zip(draw_shards, ray_tpu.get(draw_futs, timeout=120))
                     if d is not None]
            if pairs:
                draws = [d for _, d in pairs]
                stacked = {k: np.stack([d[0][k] for d in draws])
                           for k in draws[0][0]}
                out = self.learner.update_many(
                    stacked, np.stack([d[3] for d in draws]))
                for k, (shard, (_, idx, gen, _)) in enumerate(pairs):
                    # generation-tagged: the shard drops updates whose
                    # slot was overwritten by a concurrent worker push
                    shard.update_priorities.remote(idx, gen,
                                                   out["td_abs"][k])
                n = self.learner.num_updates
                if (n // c.target_update_freq
                        > (n - len(draws)) // c.target_update_freq):
                    self.learner.sync_target()
                stats = {"loss": out["loss"], "mean_q": out["mean_q"],
                         "num_updates": n}
            learn_time = time.monotonic() - t1

        steps = sum(ray_tpu.get(sample_futs, timeout=300))
        self._total_steps += steps
        all_rets = ray_tpu.get(
            [w.episode_returns.remote() for w in self.workers],
            timeout=60)
        for i, rets in enumerate(all_rets):
            self._recent.extend(rets)
            self._total_episodes += len(rets)
            if i == len(self.workers) - 1:
                # the last worker sits at the greedy end of the epsilon
                # ladder — its returns are the policy-quality signal
                # (the paper evaluates greedily; the ladder mean is
                # dominated by the eps~0.4 explorers)
                self._recent_greedy.extend(rets)
        self._recent = self._recent[-100:]
        self._recent_greedy = self._recent_greedy[-100:]
        self._iteration += 1
        dt = time.monotonic() - t0
        return {"training_iteration": self._iteration,
                "timesteps_total": self._total_steps,
                "timesteps_this_iter": steps,
                "episode_reward_mean": (float(np.mean(self._recent))
                                        if self._recent else float("nan")),
                "episode_reward_mean_greedy": (
                    float(np.mean(self._recent_greedy))
                    if self._recent_greedy else float("nan")),
                "greedy_epsilon": self._greedy_eps,
                "episodes_total": self._total_episodes,
                "replay_transitions": int(sum(sizes)),
                "env_steps_per_sec": steps / max(1e-9, dt),
                "learn_time_s": learn_time,
                **stats}

    # -- Tune-trainable surface ------------------------------------------

    def save(self) -> Dict:
        import jax

        ckpt = {"params": jax.device_get(self.learner.params),
                "target_params": jax.device_get(
                    self.learner.target_params),
                "opt_state": jax.device_get(self.learner.opt_state),
                "iteration": self._iteration,
                "total_steps": self._total_steps,
                "num_updates": self.learner.num_updates}
        if self.config.checkpoint_replay_buffer:
            ckpt["shards"] = ray_tpu.get(
                [s.state.remote() for s in self.shards], timeout=300)
        return ckpt

    def restore(self, ckpt: Dict) -> None:
        import jax
        import jax.numpy as jnp

        as_jnp = lambda t: jax.tree.map(jnp.asarray, t)  # noqa: E731
        self.learner.params = as_jnp(ckpt["params"])
        self.learner.target_params = as_jnp(ckpt["target_params"])
        if "opt_state" in ckpt:
            self.learner.opt_state = as_jnp(ckpt["opt_state"])
        self.learner.num_updates = int(ckpt.get("num_updates", 0))
        self._iteration = int(ckpt.get("iteration", 0))
        self._total_steps = int(ckpt.get("total_steps", 0))
        if "shards" in ckpt:
            states = ckpt["shards"]
            if len(states) == len(self.shards):
                ray_tpu.get(
                    [s.restore_state.remote(state) for s, state in
                     zip(self.shards, states)], timeout=300)
            else:
                # shard-count change (PBT exploit across differently
                # configured trials): pool every checkpointed row and
                # its leaf priority and re-add in chunks round-robin so
                # every destination shard gets an even share. Rows
                # beyond the destination's total capacity follow the
                # ring's newest-wins semantics (the same rule
                # ReplayBuffer.restore applies on shrink).
                futs = []
                chunk_i = 0
                for state in states:
                    cols = state["buffer"]["cols"]
                    n_rows = len(next(iter(cols.values())))
                    leaves = state["buffer"].get("priorities")
                    # stored leaves are p^alpha_src; add() re-applies the
                    # destination alpha, so hand it the raw priority
                    # de-exponentiated with the SOURCE alpha
                    a_src = float(state.get(
                        "alpha", self.config.prioritized_replay_alpha))
                    prios = (np.maximum(np.asarray(leaves), 1e-12)
                             ** (1.0 / a_src) if leaves is not None
                             else np.ones(n_rows))
                    for lo in range(0, n_rows, 1024):
                        sl = slice(lo, min(lo + 1024, n_rows))
                        dst = self.shards[chunk_i % len(self.shards)]
                        chunk_i += 1
                        futs.append(dst.add.remote(
                            {k: v[sl] for k, v in cols.items()},
                            prios[sl]))
                ray_tpu.get(futs, timeout=600)

    def stop(self) -> None:
        for a in self.workers + self.shards:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
