"""CRR — critic-regularized regression for offline RL (discrete).

ref: rllib/algorithms/crr/crr.py (+ torch policy; Wang et al. 2020).
The actor is trained by ADVANTAGE-WEIGHTED behavior cloning

    L_pi = -E[(f(A(s, a_data)) * log pi(a_data | s)]
    f = exp(A / beta) clipped (the "exp" mode) or 1[A > 0] ("binary")

with the advantage measured by a learned Q critic under the CURRENT
policy, A(s,a) = Q(s,a) - E_{a'~pi} Q(s,a'); the critic trains by
expected-SARSA TD against a periodically synced target. Where MARWIL
weights imitation by Monte-Carlo advantage against a V baseline, CRR's
Q-critic weighting is the off-policy-correct version — the distinction
the reference keeps both algorithms for.

House TPU shape (the CQL recipe): dataset loads once, the whole
per-iteration block — K minibatches of critic TD + weighted-BC actor,
target sync inside the scan via lax.cond — is ONE jitted dispatch.
Consumes the rllib.offline experience JSONL format unchanged.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from .cql import _episodes_to_transitions
from .env import make_env
from .offline import read_experiences
from .td3 import _mlp_init


@dataclass
class CRRConfig:
    """ref: crr.py CRRConfig (weight_type exp/bin, temperature beta,
    max_weight clip)."""
    input_paths: Any = None
    episodes: Optional[List[Dict[str, np.ndarray]]] = None
    env: str = "CartPole-v1"          # for evaluate()
    gamma: float = 0.99
    actor_lr: float = 3e-4
    critic_lr: float = 3e-4
    beta: float = 1.0                 # exp-weight temperature
    weight_mode: str = "exp"          # "exp" | "binary"
    max_weight: float = 20.0
    train_batch_size: int = 256
    num_updates_per_iter: int = 200
    target_update_freq: int = 100     # in updates, inside the scan
    hidden: tuple = (128, 128)
    seed: int = 0
    evaluation_num_episodes: int = 8
    extra: Dict[str, Any] = field(default_factory=dict)

    def build(self) -> "CRR":
        return CRR(self)


class CRR:
    """Tune-trainable offline learner; evaluate() rolls the greedy actor
    in the (held-out) environment."""

    def __init__(self, config: CRRConfig):
        import functools

        import jax
        import jax.numpy as jnp
        import optax

        self.config = c = config
        episodes = (c.episodes if c.episodes is not None
                    else read_experiences(c.input_paths))
        if not episodes:
            raise ValueError("CRR needs offline data: pass episodes or "
                             "input_paths with at least one episode")
        self._data = _episodes_to_transitions(episodes)
        # the dataset's behavior policy may never have taken some
        # actions — the ENV defines the action space (the cql.py guard)
        env_actions = make_env(c.env, num_envs=1, seed=0).num_actions
        self._num_actions = max(int(self._data["actions"].max()) + 1,
                                env_actions)
        obs_dim = self._data["obs"].shape[1]
        A = self._num_actions

        ka, kq = jax.random.split(jax.random.PRNGKey(c.seed))
        self.actor = _mlp_init(ka, (obs_dim, *c.hidden), A)
        self.critic = _mlp_init(kq, (obs_dim, *c.hidden), A)
        self.target = jax.tree.map(lambda a: a.copy(), self.critic)
        self.opt_actor = optax.adam(c.actor_lr)
        self.opt_critic = optax.adam(c.critic_lr)
        self.s_actor = self.opt_actor.init(self.actor)
        self.s_critic = self.opt_critic.init(self.critic)
        self._rng = np.random.default_rng(c.seed)
        self._iteration = 0
        self.num_updates = 0

        from .sac import _mlp_forward as mlp

        def critic_loss(critic, target, actor, mb):
            pi_next = jax.nn.softmax(mlp(actor, mb["next_obs"]))
            q_next = mlp(target, mb["next_obs"])
            v_next = jnp.sum(pi_next * q_next, axis=1)  # expected SARSA
            y = mb["rewards"] + c.gamma * (1.0 - mb["dones"]) \
                * jax.lax.stop_gradient(v_next)
            q_sa = jnp.take_along_axis(
                mlp(critic, mb["obs"]),
                mb["actions"][:, None].astype(jnp.int32), axis=1)[:, 0]
            return jnp.mean((q_sa - y) ** 2)

        def actor_loss(actor, critic, mb):
            logits = mlp(actor, mb["obs"])
            logp = jax.nn.log_softmax(logits)
            lp_a = jnp.take_along_axis(
                logp, mb["actions"][:, None].astype(jnp.int32),
                axis=1)[:, 0]
            q = mlp(critic, mb["obs"])
            q_sa = jnp.take_along_axis(
                q, mb["actions"][:, None].astype(jnp.int32), axis=1)[:, 0]
            v = jnp.sum(jax.nn.softmax(logits) * q, axis=1)
            adv = jax.lax.stop_gradient(q_sa - v)
            if c.weight_mode == "binary":
                w = (adv > 0).astype(jnp.float32)
            else:
                w = jnp.minimum(jnp.exp(adv / c.beta), c.max_weight)
            w = jax.lax.stop_gradient(w)
            return -jnp.mean(w * lp_a), jnp.mean(adv)

        def one_update(carry, xs):
            actor, critic, target, s_a, s_c, step_i = carry
            mb = xs
            closs, cg = jax.value_and_grad(critic_loss)(
                critic, target, actor, mb)
            cu, s_c = self.opt_critic.update(cg, s_c, critic)
            critic = optax.apply_updates(critic, cu)
            (aloss, adv), ag = jax.value_and_grad(
                actor_loss, has_aux=True)(actor, critic, mb)
            au, s_a = self.opt_actor.update(ag, s_a, actor)
            actor = optax.apply_updates(actor, au)
            step_i = step_i + 1
            target = jax.lax.cond(
                step_i % c.target_update_freq == 0,
                lambda _: jax.tree.map(lambda x: x.copy(), critic),
                lambda t: t, target)
            return (actor, critic, target, s_a, s_c, step_i), \
                {"critic_loss": closs, "actor_loss": aloss,
                 "mean_advantage": adv}

        @functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4))
        def update_many(actor, critic, target, s_a, s_c, step_i, mbs):
            (actor, critic, target, s_a, s_c, step_i), stats = \
                jax.lax.scan(one_update,
                             (actor, critic, target, s_a, s_c, step_i),
                             mbs)
            return actor, critic, target, s_a, s_c, step_i, \
                jax.tree.map(jnp.mean, stats)

        self._update_many = update_many

    def train(self) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        c = self.config
        t0 = time.monotonic()
        n = len(self._data["rewards"])
        K, B = c.num_updates_per_iter, min(c.train_batch_size, n)
        idx = self._rng.integers(0, n, size=(K, B))
        mbs = {k: jnp.asarray(v[idx]) for k, v in self._data.items()}
        (self.actor, self.critic, self.target, self.s_actor,
         self.s_critic, step_i, stats) = self._update_many(
            self.actor, self.critic, self.target, self.s_actor,
            self.s_critic, jnp.asarray(self.num_updates), mbs)
        self.num_updates = int(step_i)
        self._iteration += 1
        return {"training_iteration": self._iteration,
                "num_updates": self.num_updates,
                "num_transitions": n,
                "train_time_s": time.monotonic() - t0,
                **{k: float(v)
                   for k, v in jax.device_get(stats).items()}}

    def evaluate(self, num_episodes: Optional[int] = None,
                 seed: int = 123) -> Dict[str, float]:
        import jax

        c = self.config
        n_eps = num_episodes or c.evaluation_num_episodes
        env = make_env(c.env, num_envs=4, seed=seed)
        from .td3 import _mlp_np

        p = jax.device_get(self.actor)
        obs = env.reset(seed=seed)
        ep_ret = np.zeros(env.num_envs)
        done_rets: List[float] = []
        while len(done_rets) < n_eps:
            logits = _mlp_np(p, obs.astype(np.float32))
            obs, r, done, _ = env.step(logits.argmax(axis=1))
            ep_ret += r
            for i in np.nonzero(done)[0]:
                done_rets.append(float(ep_ret[i]))
                ep_ret[i] = 0.0
        return {"episode_reward_mean": float(np.mean(done_rets[:n_eps])),
                "episodes": n_eps}

    # -- Tune-trainable surface ------------------------------------------

    def save(self) -> Dict:
        import jax

        return {"actor": jax.device_get(self.actor),
                "critic": jax.device_get(self.critic),
                "target": jax.device_get(self.target),
                "opt": jax.device_get((self.s_actor, self.s_critic)),
                "iteration": self._iteration,
                "num_updates": self.num_updates}

    def restore(self, ckpt: Dict) -> None:
        import jax
        import jax.numpy as jnp

        as_jnp = lambda t: jax.tree.map(jnp.asarray, t)  # noqa: E731
        self.actor = as_jnp(ckpt["actor"])
        self.critic = as_jnp(ckpt["critic"])
        self.target = as_jnp(ckpt["target"])
        if "opt" in ckpt:
            self.s_actor, self.s_critic = as_jnp(ckpt["opt"])
        self._iteration = int(ckpt.get("iteration", 0))
        self.num_updates = int(ckpt.get("num_updates", 0))

    def stop(self) -> None:
        pass  # offline: no workers
