"""PPO learner — jitted clipped-surrogate SGD, mesh-ready.

ref: rllib/algorithms/ppo/ppo_torch_policy.py loss;
rllib/core/learner/learner.py:229 (compute_gradients :558 /
apply_gradients :680 / update :1190). TPU-native shape: the whole
minibatch update is ONE jitted function with donated params/opt-state;
for multi-chip data-parallel learning, `make_update_fn(mesh_axis=...)`
inserts a psum over the mesh axis so the same code runs under
shard_map/pjit on a Mesh (the LearnerGroup-DDP analog over ICI).
"""
from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from . import sample_batch as sb
from .models import forward, init_policy_params


def ppo_loss(params: Dict, batch: Dict, clip: float, vf_coeff: float,
             ent_coeff: float) -> Tuple[jax.Array, Dict]:
    logits, values = forward(params, batch[sb.OBS])
    logp_all = jax.nn.log_softmax(logits)
    logp = jnp.take_along_axis(
        logp_all, batch[sb.ACTIONS][:, None], axis=1)[:, 0]
    ratio = jnp.exp(logp - batch[sb.LOGP])
    adv = batch[sb.ADVANTAGES]
    adv = (adv - adv.mean()) / (adv.std() + 1e-8)
    surr = jnp.minimum(ratio * adv,
                       jnp.clip(ratio, 1 - clip, 1 + clip) * adv)
    policy_loss = -surr.mean()
    vf_loss = jnp.mean((values - batch[sb.RETURNS]) ** 2)
    entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=1))
    loss = policy_loss + vf_coeff * vf_loss - ent_coeff * entropy
    stats = {"policy_loss": policy_loss, "vf_loss": vf_loss,
             "entropy": entropy,
             "kl": jnp.mean(batch[sb.LOGP] - logp)}
    return loss, stats


def make_update_fn(optimizer, clip: float, vf_coeff: float, ent_coeff: float,
                   mesh_axis: Optional[str] = None):
    """One donated-buffer minibatch step; with mesh_axis set, gradients
    psum over the data-parallel mesh axis (XLA collective over ICI —
    the NCCL-allreduce replacement)."""

    def update(params, opt_state, batch):
        (loss, stats), grads = jax.value_and_grad(
            ppo_loss, has_aux=True)(params, batch, clip, vf_coeff, ent_coeff)
        if mesh_axis is not None:
            grads = jax.lax.pmean(grads, axis_name=mesh_axis)
            stats = jax.lax.pmean(stats, axis_name=mesh_axis)
            loss = jax.lax.pmean(loss, axis_name=mesh_axis)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, stats

    return update


def make_epoch_update_fn(optimizer, clip: float, vf_coeff: float,
                         ent_coeff: float, mesh_axis: Optional[str] = None):
    """The FULL epochs x minibatches SGD pass as one jitted lax.scan over a
    host-shuffled index matrix. One dispatch and one stats readback per
    `update()` — essential when the learner device sits behind a network
    tunnel, where per-minibatch host syncs (the round-2 bench's 4 s/iter)
    dominate everything else."""
    step = make_update_fn(optimizer, clip, vf_coeff, ent_coeff, mesh_axis)

    def epoch_update(params, opt_state, batch, idx):
        # idx: [n_updates, minibatch] int32 gather indices into batch rows
        def body(carry, ix):
            params, opt_state = carry
            mb = jax.tree.map(lambda a: a[ix], batch)
            params, opt_state, loss, stats = step(params, opt_state, mb)
            return (params, opt_state), {**stats, "loss": loss}

        (params, opt_state), stats = jax.lax.scan(body, (params, opt_state),
                                                  idx)
        return params, opt_state, jax.tree.map(jnp.mean, stats)

    return epoch_update


class PPOLearner:
    """Single-process learner; LearnerGroup-style scale-out runs this under
    shard_map on a MeshGroup with mesh_axis="dp"."""

    def __init__(self, obs_dim, num_actions: int, *,
                 lr: float = 3e-4, clip: float = 0.2, vf_coeff: float = 0.5,
                 ent_coeff: float = 0.01, minibatch_size: int = 256,
                 num_epochs: int = 4, hidden=(64, 64), seed: int = 0,
                 max_grad_norm: float = 0.5):
        self.params = init_policy_params(jax.random.PRNGKey(seed), obs_dim,
                                         num_actions, tuple(hidden))
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(max_grad_norm), optax.adam(lr))
        self.opt_state = self.optimizer.init(self.params)
        self.minibatch_size = minibatch_size
        self.num_epochs = num_epochs
        self._seed = seed
        self._epoch_update = jax.jit(
            make_epoch_update_fn(self.optimizer, clip, vf_coeff, ent_coeff),
            donate_argnums=(0, 1))

    # only these batch columns feed the loss; uploading the rest would
    # waste host->device bandwidth
    _LOSS_KEYS = (sb.OBS, sb.ACTIONS, sb.LOGP, sb.ADVANTAGES, sb.RETURNS)

    def update(self, batch: sb.Batch) -> Dict[str, float]:
        n = len(batch[sb.OBS])
        if n == 0:
            return {}
        mb = min(self.minibatch_size, n)
        n_mb = n // mb
        rng = np.random.default_rng(self._seed)
        self._seed += 1
        idx = np.concatenate(
            [rng.permutation(n)[:n_mb * mb].reshape(n_mb, mb)
             for _ in range(self.num_epochs)], axis=0).astype(np.int32)
        jb = {k: jnp.asarray(batch[k]) for k in self._LOSS_KEYS}
        self.params, self.opt_state, stats = self._epoch_update(
            self.params, self.opt_state, jb, jnp.asarray(idx))
        return {k: float(v) for k, v in jax.device_get(stats).items()}

    def get_params(self) -> Dict:
        return jax.device_get(self.params)
