"""ARS — Augmented Random Search (Mania et al. 2018).

ref: rllib/algorithms/ars/ars.py (ARSConfig: num_rollouts,
rollouts_used (top-k), noise_stdev, sd_of_noise used to scale the step)
+ ars_tf_policy.py (observation filter applied inside the policy).
Differences from ES that make it "augmented": (1) only the top-k
best-performing perturbation directions (by max(pos, neg) return) enter
the update, (2) the step is divided by the standard deviation of the
returns actually used, and (3) observations are normalized by a running
mean/std whose statistics merge across workers every iteration (the
MeanStdFilter connector protocol, same as PPO's).

Same seed-regeneration trick as es.py: only (seed, sign, return)
triples plus filter deltas cross the object store.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu

from .connectors import MeanStdFilter, merge_deltas
from .es import ESWorker
from .rollout_worker import worker_opts


class ARSWorker(ESWorker):
    """ESWorker plus a synced observation filter (ref: ars.py
    Worker.do_rollouts + ars_tf_policy.py observation_filter). Only the
    episode loop changes — perturbation regeneration, shapes, and the
    evaluate protocol are inherited."""

    def __init__(self, env_name: str, hidden: tuple, sigma: float,
                 max_steps: int, seed: int = 0, env_creator=None):
        super().__init__(env_name, hidden, sigma, max_steps, seed=seed,
                         env_creator=env_creator)
        self.filter = MeanStdFilter(self.env.obs_shape)

    def _episode(self, params: Dict[str, np.ndarray],
                 update_filter: bool = True) -> float:
        from .es import _episode_return

        return _episode_return(
            params, self.env, self.max_steps,
            obs_fn=lambda o: self.filter(o, update=update_filter))

    def evaluate(self, theta: np.ndarray, seeds: List[int],
                 filter_state: Optional[Dict] = None
                 ) -> Tuple[List[Tuple[int, int, float]], Dict]:
        if filter_state is not None:
            self.filter.set_state(filter_state)
        return super().evaluate(theta, seeds), self.filter.delta()

    def evaluate_center(self, theta: np.ndarray,
                        filter_state: Optional[Dict] = None) -> float:
        if filter_state is not None:
            self.filter.set_state(filter_state)
        return super().evaluate_center(theta)


@dataclass
class ARSConfig:
    """ref: ars.py ARSConfig (num_rollouts, rollouts_used, noise_stdev,
    sgd_stepsize)."""
    env: str = "CartPole-v1"
    env_creator: Optional[Callable] = None
    num_workers: int = 2
    num_rollouts: int = 32       # perturbation PAIRS per iteration
    rollouts_used: int = 16      # top-k directions entering the update
    sigma: float = 0.05
    lr: float = 0.05
    hidden: tuple = (32,)
    max_episode_steps: int = 500
    seed: int = 0
    worker_resources: Dict[str, float] = field(default_factory=dict)

    def build(self) -> "ARS":
        return ARS(self)


class ARS:
    """Tune-trainable ARS driver."""

    def __init__(self, config: ARSConfig):
        import cloudpickle

        c = self.config = config
        creator_blob = (cloudpickle.dumps(c.env_creator)
                        if c.env_creator is not None else None)
        cls = ray_tpu.remote(ARSWorker)
        opts = worker_opts(c.worker_resources)
        self.workers = [
            cls.options(**opts).remote(
                c.env, tuple(c.hidden), c.sigma, c.max_episode_steps,
                seed=c.seed + 100 * i, env_creator=creator_blob)
            for i in range(c.num_workers)
        ]
        dim, obs_shape = ray_tpu.get(
            [self.workers[0].dim.remote(),
             self.workers[0].obs_shape.remote()], timeout=180)
        rng = np.random.default_rng(c.seed)
        # near-zero init is the ARS default (linear-policy heritage); the
        # tiny noise just breaks argmax ties deterministically
        self.theta = (rng.standard_normal(dim) * 1e-3).astype(np.float32)
        self.filter = MeanStdFilter(tuple(obs_shape))
        self._seed_seq = c.seed * 1_000_003 + 1
        self._iteration = 0
        self._total_episodes = 0

    def train(self) -> Dict[str, float]:
        c = self.config
        t0 = time.monotonic()
        n_pairs = c.num_rollouts
        seeds = [self._seed_seq + i for i in range(n_pairs)]
        self._seed_seq += n_pairs
        theta_ref = ray_tpu.put(self.theta)
        fstate = self.filter.state()
        chunks = np.array_split(np.asarray(seeds), len(self.workers))
        futs = [w.evaluate.remote(theta_ref, [int(s) for s in chunk], fstate)
                for w, chunk in zip(self.workers, chunks) if len(chunk)]
        results = ray_tpu.get(futs, timeout=600)
        triples = [t for batch, _ in results for t in batch]
        merge_deltas(self.filter, [d for _, d in results])
        returns: Dict[int, Dict[int, float]] = {}
        for seed, sign, ret in triples:
            returns.setdefault(seed, {})[sign] = ret
        pos = np.array([returns[s][1] for s in seeds], np.float32)
        neg = np.array([returns[s][-1] for s in seeds], np.float32)

        # top-k directions by best-of-pair (ref: ars.py max filtering)
        k = min(c.rollouts_used, n_pairs)
        order = np.argsort(np.maximum(pos, neg))[::-1][:k]
        used = np.concatenate([pos[order], neg[order]])
        sigma_r = float(used.std()) + 1e-8
        grad = np.zeros_like(self.theta)
        for i in order:
            eps = np.random.default_rng(seeds[int(i)]).standard_normal(
                self.theta.shape[0]).astype(np.float32)
            grad += (pos[i] - neg[i]) * eps
        self.theta = self.theta + c.lr / (k * sigma_r) * grad

        center = ray_tpu.get(
            self.workers[0].evaluate_center.remote(
                ray_tpu.put(self.theta), self.filter.state()), timeout=120)
        self._iteration += 1
        self._total_episodes += 2 * n_pairs
        return {
            "training_iteration": self._iteration,
            "episodes_total": self._total_episodes,
            "episode_reward_mean": float(center),
            "perturbation_reward_mean": float(np.mean([pos, neg])),
            "reward_std_used": sigma_r,
            "time_this_iter_s": time.monotonic() - t0,
        }

    # -- Tune-trainable surface ------------------------------------------

    def save(self) -> Dict:
        return {"theta": self.theta.copy(),
                "filter": self.filter.state(),
                "iteration": self._iteration,
                "seed_seq": self._seed_seq}

    def restore(self, ckpt: Dict) -> None:
        self.theta = np.asarray(ckpt["theta"], np.float32)
        if "filter" in ckpt:
            self.filter.set_state(ckpt["filter"])
        self._iteration = int(ckpt.get("iteration", 0))
        self._seed_seq = int(ckpt.get("seed_seq", 1))

    def stop(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
