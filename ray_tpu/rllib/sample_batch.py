"""Sample batches + advantage estimation.

ref: rllib/policy/sample_batch.py (column dict container);
rllib/evaluation/postprocessing.py compute_gae_for_sample_batch.
Batches are plain dicts of numpy arrays — they travel through the object
store and concatenate cheaply on the learner.
"""
from __future__ import annotations

from typing import Dict, List

import numpy as np

Batch = Dict[str, np.ndarray]

OBS = "obs"
ACTIONS = "actions"
REWARDS = "rewards"
DONES = "dones"
LOGP = "logp"
VALUES = "values"
ADVANTAGES = "advantages"
RETURNS = "returns"


def concat(batches: List[Batch]) -> Batch:
    keys = batches[0].keys()
    return {k: np.concatenate([b[k] for b in batches]) for k in keys}


def num_steps(batch: Batch) -> int:
    return len(batch[REWARDS])


def compute_gae(rewards: np.ndarray, values: np.ndarray, dones: np.ndarray,
                last_values: np.ndarray, gamma: float,
                lam: float) -> tuple:
    """GAE over a [T, n_envs] rollout (ref: postprocessing.py:compute_advantages).
    dones cut the bootstrap at auto-reset boundaries."""
    T, n = rewards.shape
    adv = np.zeros((T, n), np.float32)
    last_gae = np.zeros(n, np.float32)
    next_value = last_values
    for t in range(T - 1, -1, -1):
        not_done = 1.0 - dones[t].astype(np.float32)
        delta = rewards[t] + gamma * next_value * not_done - values[t]
        last_gae = delta + gamma * lam * not_done * last_gae
        adv[t] = last_gae
        next_value = values[t]
    returns = adv + values
    return adv, returns
