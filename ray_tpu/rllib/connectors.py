"""Connectors — observation/action transformation attached to policies.

ref: rllib/connectors/ (agent/obs pipelines synced rollout<->learner) and
rllib/utils/filter.py MeanStdFilter + filter_manager.py (the running
observation normalizer whose statistics merge across rollout workers
every iteration). The protocol here mirrors the reference's:

- workers apply the connector to observations AT COLLECTION TIME, so
  train batches already hold transformed obs and the learner needs no
  separate path;
- each worker accumulates statistics locally during sampling;
- the algorithm merges worker deltas after each iteration and broadcasts
  the merged state back, so all workers (and evaluation) share one
  filter.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class RunningStat:
    """Mergeable running mean/variance (Chan et al. parallel variance —
    ref: rllib/utils/filter.py RunningStat)."""

    def __init__(self, shape):
        self.n = 0
        self.mean = np.zeros(shape, np.float64)
        self.m2 = np.zeros(shape, np.float64)

    def push_batch(self, x: np.ndarray) -> None:
        x = np.asarray(x, np.float64).reshape(-1, *self.mean.shape)
        k = len(x)
        if k == 0:
            return
        bmean = x.mean(axis=0)
        bm2 = ((x - bmean) ** 2).sum(axis=0)
        self._merge(k, bmean, bm2)

    def _merge(self, n2, mean2, m22) -> None:
        n1 = self.n
        if n2 == 0:
            return
        delta = mean2 - self.mean
        n = n1 + n2
        self.mean = self.mean + delta * (n2 / n)
        self.m2 = self.m2 + m22 + delta ** 2 * (n1 * n2 / n)
        self.n = n

    def merge(self, other: "RunningStat") -> None:
        self._merge(other.n, other.mean, other.m2)

    @property
    def std(self) -> np.ndarray:
        var = self.m2 / self.n if self.n > 1 else np.ones_like(self.m2)
        return np.sqrt(np.maximum(var, 1e-8))

    def state(self) -> Dict[str, Any]:
        return {"n": self.n, "mean": self.mean.copy(),
                "m2": self.m2.copy()}

    def set_state(self, s: Dict[str, Any]) -> None:
        self.n = int(s["n"])
        self.mean = np.asarray(s["mean"], np.float64).copy()
        self.m2 = np.asarray(s["m2"], np.float64).copy()


class Connector:
    """Base: __call__ transforms an obs batch; stats sync via
    state/set_state/delta/apply_delta."""

    def __call__(self, obs: np.ndarray, update: bool = True) -> np.ndarray:
        return obs

    def state(self) -> Dict[str, Any]:
        return {}

    def set_state(self, s: Dict[str, Any]) -> None:
        pass

    def delta(self) -> Dict[str, Any]:
        """State accumulated since the last set_state (for merging)."""
        return {}


class NoFilter(Connector):
    pass


class MeanStdFilter(Connector):
    """Normalize observations by running mean/std (ref: filter.py
    MeanStdFilter). `update=False` (evaluation) transforms without
    accumulating."""

    def __init__(self, shape):
        self.rs = RunningStat(shape)
        self._base = RunningStat(shape)  # snapshot at last sync

    def __call__(self, obs: np.ndarray, update: bool = True) -> np.ndarray:
        if update:
            self.rs.push_batch(obs)
        if self.rs.n < 2:
            return np.asarray(obs, np.float32)
        return ((obs - self.rs.mean) / self.rs.std).astype(np.float32)

    def state(self) -> Dict[str, Any]:
        return self.rs.state()

    def set_state(self, s: Dict[str, Any]) -> None:
        self.rs.set_state(s)
        self._base.set_state(s)

    def delta(self) -> Dict[str, Any]:
        """The observations THIS worker saw since the last broadcast:
        subtract the base snapshot by merging counts."""
        # n_delta = n - n_base; mean/m2 deltas via reverse merge
        n_b, n_t = self._base.n, self.rs.n
        n_d = n_t - n_b
        if n_d <= 0:
            return {"n": 0, "mean": np.zeros_like(self.rs.mean),
                    "m2": np.zeros_like(self.rs.m2)}
        mean_d = (self.rs.mean * n_t - self._base.mean * n_b) / n_d
        delta = mean_d - self._base.mean
        m2_d = (self.rs.m2 - self._base.m2
                - delta ** 2 * (n_b * n_d / max(n_t, 1)))
        return {"n": n_d, "mean": mean_d, "m2": np.maximum(m2_d, 0.0)}


def make_connector(kind: str, shape) -> Connector:
    if kind in (None, "NoFilter", "no_filter", ""):
        return NoFilter()
    if kind in ("MeanStd", "MeanStdFilter"):
        return MeanStdFilter(shape)
    raise ValueError(f"unknown observation_filter {kind!r}")


def merge_deltas(central: Connector, deltas: List[Dict[str, Any]]
                 ) -> Dict[str, Any]:
    """Fold worker deltas into the central connector; returns the new
    broadcastable state (ref: filter_manager.py synchronize)."""
    if isinstance(central, MeanStdFilter):
        for d in deltas:
            if d and d.get("n", 0) > 0:
                rs = RunningStat(central.rs.mean.shape)
                rs.set_state(d)
                central.rs.merge(rs)
        state = central.rs.state()
        central._base.set_state(state)
        return state
    return {}
