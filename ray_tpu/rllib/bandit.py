"""Contextual bandits — LinUCB and linear Thompson sampling.

ref: rllib/algorithms/bandit/bandit.py (+ bandit_torch_model.py
DiscreteLinearModel): per-arm Bayesian linear models over the context
    A_k = I*lambda + sum x x^T      b_k = sum r x
    theta_k = A_k^-1 b_k
LinUCB scores theta_k.x + alpha * sqrt(x^T A_k^-1 x) (Li et al. 2010);
LinTS samples theta ~ N(theta_k, v^2 A_k^-1) (Agrawal & Goyal 2013).

Bandits are single-step decisions — no rollout workers, no replay, no
device: the posteriors are tiny dense matrices updated in closed form
on the driver. The numpy solve IS the algorithm; a chip would only add
dispatch latency (same judgment as np_policy's rollout stance).
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

import numpy as np


class ContextualBanditEnv:
    """Batch contextual bandit: observe contexts, pick arms, get
    rewards. The test model is the reference's SimpleContextualBandit
    (rllib/examples/env/bandit_envs_discrete.py)."""

    num_arms: int
    context_dim: int

    def observe(self, n: int) -> np.ndarray:
        raise NotImplementedError

    def pull(self, contexts: np.ndarray, arms: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def best_reward(self, contexts: np.ndarray) -> np.ndarray:
        """Oracle per-context best expected reward (for regret)."""
        raise NotImplementedError


class LinearBanditEnv(ContextualBanditEnv):
    """Rewards are arm-specific linear functions of the context plus
    Gaussian noise — the canonical LinUCB testbed."""

    def __init__(self, num_arms: int = 5, context_dim: int = 8,
                 noise: float = 0.1, seed: int = 0):
        rng = np.random.default_rng(seed)
        self.num_arms, self.context_dim = num_arms, context_dim
        self.noise = noise
        self._theta = rng.standard_normal((num_arms, context_dim))
        self._theta /= np.linalg.norm(self._theta, axis=1, keepdims=True)
        self._rng = rng

    def observe(self, n: int) -> np.ndarray:
        x = self._rng.standard_normal((n, self.context_dim))
        return (x / np.linalg.norm(x, axis=1, keepdims=True)
                ).astype(np.float32)

    def pull(self, contexts, arms):
        mean = np.einsum("nd,nd->n", self._theta[arms], contexts)
        return (mean + self._rng.normal(0, self.noise, len(arms))
                ).astype(np.float32)

    def best_reward(self, contexts):
        return (contexts @ self._theta.T).max(axis=1)


_BANDIT_ENVS: Dict[str, Callable[..., ContextualBanditEnv]] = {
    "LinearBandit-v0": LinearBanditEnv,
}


def register_bandit_env(name: str, creator) -> None:
    _BANDIT_ENVS[name] = creator


@dataclass
class BanditConfig:
    """ref: bandit.py BanditLinUCBConfig / BanditLinTSConfig."""
    env: str = "LinearBandit-v0"
    env_creator: Optional[Callable] = None
    exploration: str = "ucb"        # "ucb" | "thompson"
    alpha: float = 1.0              # UCB width / TS variance scale
    lambda_reg: float = 1.0
    batch_size: int = 64            # decisions per train() iteration
    seed: int = 0
    extra: Dict[str, Any] = field(default_factory=dict)

    def build(self) -> "Bandit":
        return Bandit(self)


def BanditLinUCBConfig(**kw) -> BanditConfig:  # noqa: N802 — ref naming
    return BanditConfig(exploration="ucb", **kw)


def BanditLinTSConfig(**kw) -> BanditConfig:  # noqa: N802
    return BanditConfig(exploration="thompson", **kw)


class Bandit:
    """Tune-trainable bandit driver with per-arm linear posteriors."""

    def __init__(self, config: BanditConfig):
        c = self.config = config
        if c.env_creator is not None:
            self.env = c.env_creator()
        else:
            self.env = _BANDIT_ENVS[c.env](seed=c.seed)
        K, D = self.env.num_arms, self.env.context_dim
        self._A = np.stack([np.eye(D) * c.lambda_reg for _ in range(K)])
        self._b = np.zeros((K, D))
        self._rng = np.random.default_rng(c.seed + 1)
        self._iteration = 0
        self._total_pulls = 0
        self._cum_reward = 0.0
        self._cum_regret = 0.0

    def _scores(self, contexts: np.ndarray) -> np.ndarray:
        c = self.config
        K = self.env.num_arms
        n = len(contexts)
        A_inv = np.linalg.inv(self._A)                  # [K, D, D]
        theta = np.einsum("kde,ke->kd", A_inv, self._b)  # [K, D]
        mean = contexts @ theta.T                        # [n, K]
        if c.exploration == "thompson":
            # one posterior sample per arm per decision batch
            out = np.empty((n, K))
            for k in range(K):
                L = np.linalg.cholesky(
                    A_inv[k] * (c.alpha ** 2)
                    + 1e-12 * np.eye(A_inv.shape[1]))
                th = theta[k] + L @ self._rng.standard_normal(len(L))
                out[:, k] = contexts @ th
            return out
        # LinUCB
        var = np.einsum("nd,kde,ne->nk", contexts, A_inv, contexts)
        return mean + c.alpha * np.sqrt(np.clip(var, 0, None))

    def train(self) -> Dict[str, float]:
        c = self.config
        t0 = time.monotonic()
        contexts = self.env.observe(c.batch_size)
        arms = np.argmax(self._scores(contexts), axis=1)
        rewards = self.env.pull(contexts, arms)
        for x, k, r in zip(contexts, arms, rewards):
            self._A[k] += np.outer(x, x)
            self._b[k] += r * x
        self._total_pulls += len(arms)
        self._cum_reward += float(rewards.sum())
        self._cum_regret += float(
            (self.env.best_reward(contexts) - rewards).sum())
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "timesteps_total": self._total_pulls,
            "episode_reward_mean": float(rewards.mean()),
            "cumulative_reward": self._cum_reward,
            "cumulative_regret": self._cum_regret,
            "regret_per_pull": self._cum_regret / self._total_pulls,
            "time_this_iter_s": time.monotonic() - t0,
        }

    # -- Tune-trainable surface ------------------------------------------

    def save(self) -> Dict:
        return {"A": self._A.copy(), "b": self._b.copy(),
                "iteration": self._iteration,
                "total_pulls": self._total_pulls,
                "cum_reward": self._cum_reward,
                "cum_regret": self._cum_regret,
                "rng": self._rng.bit_generator.state}

    def restore(self, ckpt: Dict) -> None:
        self._A = np.asarray(ckpt["A"])
        self._b = np.asarray(ckpt["b"])
        self._iteration = int(ckpt.get("iteration", 0))
        self._total_pulls = int(ckpt.get("total_pulls", 0))
        # cumulative metrics continue, not restart — regret_per_pull
        # divides by the restored pull count
        self._cum_reward = float(ckpt.get("cum_reward", 0.0))
        self._cum_regret = float(ckpt.get("cum_regret", 0.0))
        if "rng" in ckpt:
            self._rng.bit_generator.state = ckpt["rng"]

    def stop(self) -> None:
        pass
