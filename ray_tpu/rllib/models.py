"""Policy/value networks in plain jax (param-dict style, matching
ray_tpu.models). ref: rllib/models/catalog.py fcnet defaults
(two hidden layers, tanh); the experimental jax net the reference never
finished (rllib/models/jax/fcnet.py) is the shape this completes.
"""
from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


# NatureCNN (Mnih et al. 2015) conv stack: (out_channels, kernel, stride).
# ref: rllib/models/catalog.py conv defaults for 84x84 Atari frames.
NATURE_CONV: Tuple[Tuple[int, int, int], ...] = ((32, 8, 4), (64, 4, 2),
                                                 (64, 3, 1))


def _conv_out_hw(h: int, w: int, conv) -> Tuple[int, int]:
    for (_, k, s) in conv:
        h = (h - k) // s + 1
        w = (w - k) // s + 1
    return h, w


def init_policy_params(rng: jax.Array, obs_shape, num_actions: int,
                       hidden: Tuple[int, ...] = (64, 64),
                       conv: Tuple = NATURE_CONV) -> Dict:
    """obs_shape: int (flat vector) or (H, W, C) image — image obs get a
    NatureCNN front end before the fc trunk."""
    params = {}
    if isinstance(obs_shape, int):
        last = obs_shape
    elif len(obs_shape) == 1:
        last = int(obs_shape[0])
    else:
        H, W, C = obs_shape
        ckeys = jax.random.split(jax.random.fold_in(rng, 17), len(conv))
        cin = C
        for i, (cout, k, s) in enumerate(conv):
            fan_in = k * k * cin
            # stride rides in the key so params stay a pure array pytree
            # (an int leaf would hit the optimizer and grad maps)
            params[f"conv{i}s{s}_w"] = jax.random.normal(
                ckeys[i], (k, k, cin, cout), jnp.float32) \
                * np.sqrt(2.0 / fan_in)
            params[f"conv{i}s{s}_b"] = jnp.zeros((cout,), jnp.float32)
            cin = cout
        oh, ow = _conv_out_hw(H, W, conv)
        last = oh * ow * cin
    keys = jax.random.split(rng, len(hidden) + 2)
    for i, h in enumerate(hidden):
        params[f"w{i}"] = jax.random.normal(
            keys[i], (last, h), jnp.float32) * np.sqrt(2.0 / last)
        params[f"b{i}"] = jnp.zeros((h,), jnp.float32)
        last = h
    # separate small-init heads: policy logits + value
    params["w_pi"] = jax.random.normal(
        keys[-2], (last, num_actions), jnp.float32) * 0.01
    params["b_pi"] = jnp.zeros((num_actions,), jnp.float32)
    params["w_v"] = jax.random.normal(keys[-1], (last, 1), jnp.float32) * 1.0
    params["b_v"] = jnp.zeros((1,), jnp.float32)
    return params


from .np_policy import conv_layer_keys  # noqa: E402 — single parser


def has_conv(params: Dict) -> bool:
    return any(k.startswith("conv0s") for k in params)


def _conv_trunk(params: Dict, x: jax.Array) -> jax.Array:
    """NatureCNN forward: uint8 [B,H,W,C] -> flat [B, F]. Normalization
    (x/255) lives here so rollout and learner can both feed raw frames."""
    x = x.astype(jnp.float32) / 255.0 if x.dtype == jnp.uint8 \
        else x.astype(jnp.float32)
    for wk, bk, s in conv_layer_keys(params):
        x = jax.lax.conv_general_dilated(
            x, params[wk], window_strides=(s, s), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        x = jax.nn.relu(x + params[bk])
    return x.reshape(x.shape[0], -1)


def forward(params: Dict, obs: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """obs [B, obs_dim] or [B,H,W,C] -> (logits [B, A], value [B])."""
    x = _conv_trunk(params, obs) if has_conv(params) else obs
    i = 0
    while f"w{i}" in params:
        x = jnp.tanh(x @ params[f"w{i}"] + params[f"b{i}"])
        i += 1
    logits = x @ params["w_pi"] + params["b_pi"]
    value = (x @ params["w_v"] + params["b_v"])[:, 0]
    return logits, value


# Rollout inference is pure numpy (no jax, no device, no jit dispatch) —
# see np_policy.py. Re-exported here for API continuity.
from .np_policy import sample_actions  # noqa: E402,F401
