"""Policy/value networks in plain jax (param-dict style, matching
ray_tpu.models). ref: rllib/models/catalog.py fcnet defaults
(two hidden layers, tanh); the experimental jax net the reference never
finished (rllib/models/jax/fcnet.py) is the shape this completes.
"""
from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def init_policy_params(rng: jax.Array, obs_dim: int, num_actions: int,
                       hidden: Tuple[int, ...] = (64, 64)) -> Dict:
    keys = jax.random.split(rng, len(hidden) + 2)
    params = {}
    last = obs_dim
    for i, h in enumerate(hidden):
        params[f"w{i}"] = jax.random.normal(
            keys[i], (last, h), jnp.float32) * np.sqrt(2.0 / last)
        params[f"b{i}"] = jnp.zeros((h,), jnp.float32)
        last = h
    # separate small-init heads: policy logits + value
    params["w_pi"] = jax.random.normal(
        keys[-2], (last, num_actions), jnp.float32) * 0.01
    params["b_pi"] = jnp.zeros((num_actions,), jnp.float32)
    params["w_v"] = jax.random.normal(keys[-1], (last, 1), jnp.float32) * 1.0
    params["b_v"] = jnp.zeros((1,), jnp.float32)
    return params


def forward(params: Dict, obs: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """obs [B, obs_dim] -> (logits [B, A], value [B])."""
    x = obs
    i = 0
    while f"w{i}" in params:
        x = jnp.tanh(x @ params[f"w{i}"] + params[f"b{i}"])
        i += 1
    logits = x @ params["w_pi"] + params["b_pi"]
    value = (x @ params["w_v"] + params["b_v"])[:, 0]
    return logits, value


# Rollout inference is pure numpy (no jax, no device, no jit dispatch) —
# see np_policy.py. Re-exported here for API continuity.
from .np_policy import sample_actions  # noqa: E402,F401
