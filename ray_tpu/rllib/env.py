"""Environment API + built-in vectorized envs.

The reference samples gym envs through vector wrappers (ref:
rllib/env/vector_env.py; env_runner_v2.py). This image ships no gym, so
the API here IS the gymnasium step/reset contract, a numpy-vectorized
CartPole implements it natively (vector math, no per-env Python loop —
the >100k steps/s north star needs that), and `make_env` wraps a real
gymnasium env when one is installed.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np


class VectorEnv:
    """n independent env copies stepped as one batch."""

    num_envs: int
    obs_dim: int
    num_actions: int
    obs_dtype = np.float32

    @property
    def obs_shape(self) -> Tuple[int, ...]:
        """Per-env observation shape; image envs override with (H, W, C)."""
        return (self.obs_dim,)

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        raise NotImplementedError

    def step(self, actions: np.ndarray
             ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, Dict[str, Any]]:
        """-> (obs [n, *obs_shape], reward [n], done [n], info). Sub-envs
        auto-reset on done (the obs returned is the NEW episode's)."""
        raise NotImplementedError


class CartPoleVecEnv(VectorEnv):
    """Classic cart-pole control, vectorized over n envs in numpy
    (dynamics per the standard formulation; episode caps at 500 steps)."""

    GRAVITY = 9.8
    CART_MASS = 1.0
    POLE_MASS = 0.1
    POLE_HALF_LEN = 0.5
    FORCE = 10.0
    DT = 0.02
    X_LIMIT = 2.4
    THETA_LIMIT = 12 * 2 * np.pi / 360
    MAX_STEPS = 500

    def __init__(self, num_envs: int = 8, seed: int = 0):
        self.num_envs = num_envs
        self.obs_dim = 4
        self.num_actions = 2
        self._rng = np.random.default_rng(seed)
        self._state = np.zeros((num_envs, 4), np.float64)
        self._steps = np.zeros(num_envs, np.int64)

    def _sample_state(self, n: int) -> np.ndarray:
        return self._rng.uniform(-0.05, 0.05, size=(n, 4))

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._sample_state(self.num_envs)
        self._steps[:] = 0
        return self._state.astype(np.float32)

    def step(self, actions: np.ndarray):
        x, x_dot, theta, theta_dot = self._state.T
        force = np.where(actions == 1, self.FORCE, -self.FORCE)
        cos_t, sin_t = np.cos(theta), np.sin(theta)
        total_mass = self.CART_MASS + self.POLE_MASS
        pole_ml = self.POLE_MASS * self.POLE_HALF_LEN
        temp = (force + pole_ml * theta_dot ** 2 * sin_t) / total_mass
        theta_acc = (self.GRAVITY * sin_t - cos_t * temp) / (
            self.POLE_HALF_LEN
            * (4.0 / 3.0 - self.POLE_MASS * cos_t ** 2 / total_mass))
        x_acc = temp - pole_ml * theta_acc * cos_t / total_mass
        x = x + self.DT * x_dot
        x_dot = x_dot + self.DT * x_acc
        theta = theta + self.DT * theta_dot
        theta_dot = theta_dot + self.DT * theta_acc
        self._state = np.stack([x, x_dot, theta, theta_dot], axis=1)
        self._steps += 1
        failed = ((np.abs(x) > self.X_LIMIT)
                  | (np.abs(theta) > self.THETA_LIMIT))
        truncated = (self._steps >= self.MAX_STEPS) & ~failed
        done = failed | truncated
        reward = np.ones(self.num_envs, np.float32)
        info = {}
        if done.any():
            idx = np.nonzero(done)[0]
            # hand the pre-reset states out so the sampler can bootstrap
            # time-limit truncations with V(s_final) instead of zero
            info["truncated"] = truncated
            info["final_obs"] = self._state.astype(np.float32)
            self._state[idx] = self._sample_state(len(idx))
            self._steps[idx] = 0
        return (self._state.astype(np.float32), reward,
                done.astype(np.bool_), info)


class PendulumVecEnv(VectorEnv):
    """Classic inverted pendulum swing-up, vectorized — the repo's
    continuous-action reference task (gymnasium Pendulum-v1 dynamics:
    obs (cos th, sin th, th_dot), torque in [-2, 2], 200-step episodes).
    Continuous envs expose `action_dim`/bounds instead of num_actions."""

    MAX_SPEED = 8.0
    MAX_TORQUE = 2.0
    DT = 0.05
    G = 10.0
    M = 1.0
    L = 1.0
    MAX_STEPS = 200

    continuous = True
    action_dim = 1
    action_low = -2.0
    action_high = 2.0

    def __init__(self, num_envs: int = 8, seed: int = 0):
        self.num_envs = num_envs
        self.obs_dim = 3
        self.num_actions = 0  # discrete interface N/A
        self._rng = np.random.default_rng(seed)
        self._th = np.zeros(num_envs)
        self._thdot = np.zeros(num_envs)
        self._steps = np.zeros(num_envs, np.int64)

    def _obs(self) -> np.ndarray:
        return np.stack([np.cos(self._th), np.sin(self._th),
                         self._thdot], axis=1).astype(np.float32)

    def _sample(self, n):
        return (self._rng.uniform(-np.pi, np.pi, n),
                self._rng.uniform(-1.0, 1.0, n))

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._th, self._thdot = self._sample(self.num_envs)
        self._steps[:] = 0
        return self._obs()

    def step(self, actions: np.ndarray):
        u = np.clip(np.asarray(actions, np.float64).reshape(self.num_envs),
                    -self.MAX_TORQUE, self.MAX_TORQUE)
        th = ((self._th + np.pi) % (2 * np.pi)) - np.pi
        cost = th ** 2 + 0.1 * self._thdot ** 2 + 0.001 * u ** 2
        thdot = self._thdot + (
            3 * self.G / (2 * self.L) * np.sin(self._th)
            + 3.0 / (self.M * self.L ** 2) * u) * self.DT
        thdot = np.clip(thdot, -self.MAX_SPEED, self.MAX_SPEED)
        self._th = self._th + thdot * self.DT
        self._thdot = thdot
        self._steps += 1
        truncated = self._steps >= self.MAX_STEPS
        done = truncated.copy()
        info: Dict[str, Any] = {}
        if done.any():
            idx = np.nonzero(done)[0]
            info["truncated"] = truncated
            info["final_obs"] = self._obs()
            th_new, thdot_new = self._sample(len(idx))
            self._th[idx] = th_new
            self._thdot[idx] = thdot_new
            self._steps[idx] = 0
        return self._obs(), (-cost).astype(np.float32), done, info


class MemoryCueVecEnv(VectorEnv):
    """Recurrence probe: a cue (0 or 1) is shown in the FIRST observation
    only; the episode then runs `delay` blank steps; on the final step the
    agent earns +1 for choosing the action matching the cue. A memoryless
    policy caps at 0.5 expected return — solving it requires carrying the
    cue through time (the T-maze family of memory tasks; R2D2's test env
    here). obs = (cue0, cue1, time/len)."""

    def __init__(self, num_envs: int = 8, seed: int = 0, delay: int = 6):
        self.num_envs = num_envs
        self.obs_dim = 3
        self.num_actions = 2
        self.episode_len = delay + 2  # cue step + delay blanks + decision
        self._rng = np.random.default_rng(seed)
        self._cue = np.zeros(num_envs, np.int64)
        self._t = np.zeros(num_envs, np.int64)

    def _obs(self) -> np.ndarray:
        out = np.zeros((self.num_envs, 3), np.float32)
        show = self._t == 0
        out[show, 0] = self._cue[show] == 0
        out[show, 1] = self._cue[show] == 1
        out[:, 2] = self._t / self.episode_len
        return out

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._cue = self._rng.integers(0, 2, self.num_envs)
        self._t[:] = 0
        return self._obs()

    def step(self, actions: np.ndarray):
        self._t += 1
        done = self._t >= self.episode_len
        reward = np.zeros(self.num_envs, np.float32)
        reward[done] = (np.asarray(actions)[done]
                        == self._cue[done]).astype(np.float32)
        info: Dict[str, Any] = {}
        if done.any():
            idx = np.nonzero(done)[0]
            info["final_obs"] = self._obs()
            self._cue[idx] = self._rng.integers(0, 2, len(idx))
            self._t[idx] = 0
        return self._obs(), reward, done.astype(np.bool_), info


_REGISTRY: Dict[str, Callable[..., VectorEnv]] = {
    "CartPole-v1": CartPoleVecEnv,
    "Pendulum-v1": PendulumVecEnv,
    "MemoryCue-v0": MemoryCueVecEnv,
}


def register_env(name: str, creator: Callable[..., VectorEnv]) -> None:
    """ref: ray.tune.registry.register_env"""
    _REGISTRY[name] = creator


def make_env(name: str, num_envs: int = 8, seed: int = 0) -> VectorEnv:
    if name not in _REGISTRY:
        from . import preprocessors  # noqa: F401 — registers image envs
    if name in _REGISTRY:
        return _REGISTRY[name](num_envs=num_envs, seed=seed)
    try:  # fall back to gymnasium when installed
        import gymnasium

        return _GymnasiumVecEnv(name, num_envs, seed)
    except ImportError:
        raise ValueError(
            f"Unknown env {name!r}; register it with "
            f"ray_tpu.rllib.register_env") from None


class _GymnasiumVecEnv(VectorEnv):
    """Adapter over gymnasium.vector when the library is present."""

    def __init__(self, name: str, num_envs: int, seed: int):
        import gymnasium

        try:
            # gymnasium >= 1.0 defaults to NEXT_STEP autoreset, which would
            # break the same-step contract this adapter promises
            self._env = gymnasium.make_vec(
                name, num_envs=num_envs,
                autoreset_mode=gymnasium.vector.AutoresetMode.SAME_STEP)
        except TypeError:
            self._env = gymnasium.make_vec(name, num_envs=num_envs)
        self.num_envs = num_envs
        self.obs_dim = int(np.prod(self._env.single_observation_space.shape))
        self.num_actions = int(self._env.single_action_space.n)
        self._seed = seed

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        obs, _ = self._env.reset(seed=seed if seed is not None else self._seed)
        return np.asarray(obs, np.float32).reshape(self.num_envs, -1)

    def step(self, actions: np.ndarray):
        obs, reward, term, trunc, info = self._env.step(actions)
        done = np.asarray(term) | np.asarray(trunc)
        return (np.asarray(obs, np.float32).reshape(self.num_envs, -1),
                np.asarray(reward, np.float32), done, info)
