"""MADDPG — multi-agent DDPG with centralized critics
(Lowe et al. 2017).

ref: rllib/algorithms/maddpg/maddpg.py (MADDPGConfig: per-agent actors,
critics conditioned on ALL agents' obs+actions, target nets + Gaussian
exploration) over the ddpg losses. Decentralized execution /
centralized training: each actor mu_i sees only its own observation;
each critic Q_i(o_1..o_N, a_1..a_N) sees everything, which removes the
non-stationarity independent DDPG suffers as other agents learn.

House shape: the TD3 module's numpy-MLP rollout machinery
(td3._mlp_np), a joint-transition replay buffer, and ALL agents'
critic+actor+polyak updates for K minibatches fused into ONE jitted
lax.scan dispatch per train() call. Ships RendezvousVecEnv — a
continuous cooperative two-agent task (meet in the middle) — as the
test surface, registered as "Rendezvous-v0"."""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle
import numpy as np

import ray_tpu

from .multi_agent import MultiAgentVecEnv, register_multi_agent_env
from .replay_buffer import ReplayBuffer
from .rollout_worker import worker_opts
from .td3 import _mlp_init, _mlp_np


class RendezvousVecEnv(MultiAgentVecEnv):
    """Two point agents on the [-1,1]^2 plane; action = velocity in
    [-1,1]^2; shared reward = -distance(a0, a1) each step; 25-step
    episodes. Cooperative continuous control — the MPE simple-spread
    family reduced to its testable core (ref:
    rllib/examples/env/mock_env or MPE simple_spread usage in
    maddpg tests)."""

    EPISODE_LEN = 25
    DT = 0.1

    agent_ids = ("a0", "a1")
    continuous = True
    action_dim = 2
    action_low = -1.0
    action_high = 1.0

    def __init__(self, num_envs: int = 8, seed: int = 0):
        self.num_envs = num_envs
        self.obs_dim = 4  # own pos (2) + other's pos (2)
        self.num_actions = 0  # discrete interface N/A
        self._rng = np.random.default_rng(seed)
        self._pos = np.zeros((num_envs, 2, 2), np.float64)
        self._t = np.zeros(num_envs, np.int64)

    def _obs(self) -> Dict[str, np.ndarray]:
        p0 = self._pos[:, 0].astype(np.float32)
        p1 = self._pos[:, 1].astype(np.float32)
        return {"a0": np.concatenate([p0, p1], axis=1),
                "a1": np.concatenate([p1, p0], axis=1)}

    def reset(self, seed: Optional[int] = None) -> Dict[str, np.ndarray]:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._pos = self._rng.uniform(-1, 1, (self.num_envs, 2, 2))
        self._t[:] = 0
        return self._obs()

    def step(self, actions: Dict[str, np.ndarray]):
        for i, aid in enumerate(self.agent_ids):
            a = np.clip(np.asarray(actions[aid], np.float64), -1, 1)
            self._pos[:, i] = np.clip(self._pos[:, i] + self.DT * a,
                                      -1, 1)
        dist = np.linalg.norm(self._pos[:, 0] - self._pos[:, 1], axis=1)
        r = (-dist).astype(np.float32)
        rewards = {"a0": r.copy(), "a1": r.copy()}
        self._t += 1
        done = self._t >= self.EPISODE_LEN
        info: Dict[str, Any] = {}
        if done.any():
            info["truncated"] = done.copy()
            info["final_obs"] = self._obs()
            idx = np.nonzero(done)[0]
            self._pos[idx] = self._rng.uniform(-1, 1, (len(idx), 2, 2))
            self._t[idx] = 0
        return self._obs(), rewards, done, info


register_multi_agent_env("Rendezvous-v0", RendezvousVecEnv)


class MADDPGRolloutWorker:
    """Steps all agents' deterministic actors + exploration noise; emits
    joint transitions keyed obs_<aid>/act_<aid>/rew_<aid> (the critic
    needs the joint view — ref maddpg.py before_learn_on_batch gathering
    all agents' columns)."""

    def __init__(self, env_name: str, num_envs: int, rollout_len: int,
                 explore_sigma: float, seed: int = 0, env_creator=None):
        from .multi_agent import make_multi_agent_env

        self.env = (cloudpickle.loads(env_creator)(num_envs=num_envs,
                                                   seed=seed)
                    if env_creator else
                    make_multi_agent_env(env_name, num_envs, seed))
        self.rollout_len = rollout_len
        self.sigma = explore_sigma
        self._rng = np.random.default_rng(seed + 1)
        self._obs = self.env.reset(seed=seed)
        self._ep_return = np.zeros(self.env.num_envs, np.float64)
        self._finished: List[float] = []

    def env_info(self) -> dict:
        return {"obs_dim": self.env.obs_dim,
                "action_dim": self.env.action_dim,
                "agent_ids": tuple(self.env.agent_ids),
                "num_envs": self.env.num_envs}

    def episode_returns(self, clear: bool = True) -> List[float]:
        out = list(self._finished)
        if clear:
            self._finished.clear()
        return out

    def sample(self, actor_params: Dict[str, Dict],
               random_actions: bool = False) -> Dict[str, np.ndarray]:
        agents = list(self.env.agent_ids)
        ps = {a: {k: np.asarray(v, np.float32)
                  for k, v in actor_params[a].items()} for a in agents}
        T, n = self.rollout_len, self.env.num_envs
        ad = self.env.action_dim
        od = self.env.obs_dim
        buf = {f"obs_{a}": np.empty((T, n, od), np.float32)
               for a in agents}
        buf.update({f"act_{a}": np.empty((T, n, ad), np.float32)
                    for a in agents})
        buf.update({f"rew_{a}": np.empty((T, n), np.float32)
                    for a in agents})
        buf.update({f"next_obs_{a}": np.empty((T, n, od), np.float32)
                    for a in agents})
        buf["dones"] = np.empty((T, n), np.bool_)
        obs = self._obs
        for t in range(T):
            acts = {}
            for a in agents:
                if random_actions:
                    act = self._rng.uniform(-1, 1, (n, ad))
                else:
                    act = np.tanh(_mlp_np(ps[a], obs[a])) \
                        + self._rng.normal(0, self.sigma, (n, ad))
                acts[a] = np.clip(act, -1.0, 1.0)
                buf[f"obs_{a}"][t] = obs[a]
                buf[f"act_{a}"][t] = acts[a]
            obs, rewards, done, info = self.env.step(acts)
            for a in agents:
                buf[f"rew_{a}"][t] = rewards[a]
                buf[f"next_obs_{a}"][t] = obs[a]
            buf["dones"][t] = done
            if done.any():
                idx = np.nonzero(done)[0]
                if "final_obs" in info:
                    for a in agents:
                        buf[f"next_obs_{a}"][t, idx] = \
                            info["final_obs"][a][idx]
                if "truncated" in info:
                    buf["dones"][t] &= ~info["truncated"]
            # shared-task return bookkeeping: mean over agents
            step_r = np.mean([rewards[a] for a in agents], axis=0)
            self._ep_return += step_r
            if done.any():
                for i in np.nonzero(done)[0]:
                    self._finished.append(float(self._ep_return[i]))
                    self._ep_return[i] = 0.0
        self._obs = obs
        flat = lambda x: x.reshape(T * n, *x.shape[2:])  # noqa: E731
        return {k: flat(v) for k, v in buf.items()}


@dataclass
class MADDPGConfig:
    """ref: maddpg.py MADDPGConfig (actor/critic lr, tau, smooth targets
    off by default — plain DDPG-style per the original paper)."""
    env: str = "Rendezvous-v0"
    env_creator: Optional[Callable] = None
    num_rollout_workers: int = 1
    num_envs_per_worker: int = 8
    rollout_fragment_length: int = 25
    gamma: float = 0.95
    tau: float = 0.01
    actor_lr: float = 1e-3
    critic_lr: float = 1e-3
    buffer_size: int = 100_000
    train_batch_size: int = 256
    num_updates_per_iter: int = 16
    learning_starts: int = 1_000
    explore_sigma: float = 0.1
    hidden: tuple = (64, 64)
    seed: int = 0
    checkpoint_replay_buffer: bool = True
    worker_resources: Dict[str, float] = field(default_factory=dict)

    def build(self) -> "MADDPG":
        return MADDPG(self)


class MADDPGLearner:
    """All agents' centralized-critic + actor + polyak updates fused
    into one jitted scan (ref: maddpg losses; Lowe et al. eq. 6-7)."""

    def __init__(self, agents: List[str], obs_dim: int, action_dim: int,
                 c: MADDPGConfig):
        import functools

        import jax
        import jax.numpy as jnp
        import optax

        from .sac import _mlp_forward as mlp

        self.agents = agents
        N = len(agents)
        joint_dim = N * (obs_dim + action_dim)
        keys = jax.random.split(jax.random.PRNGKey(c.seed), 2 * N)
        self.params = {}
        for i, a in enumerate(agents):
            self.params[f"actor_{a}"] = _mlp_init(
                keys[2 * i], (obs_dim, *c.hidden), action_dim)
            self.params[f"critic_{a}"] = _mlp_init(
                keys[2 * i + 1], (joint_dim, *c.hidden), 1)
        self.target = jax.tree.map(lambda x: x.copy(), self.params)
        self.opt_actor = optax.adam(c.actor_lr)
        self.opt_critic = optax.adam(c.critic_lr)
        self.state_actor = self.opt_actor.init(
            {a: self.params[f"actor_{a}"] for a in agents})
        self.state_critic = self.opt_critic.init(
            {a: self.params[f"critic_{a}"] for a in agents})
        self.num_updates = 0

        def joint_x(batch, acts: Dict):
            cols = [batch[f"obs_{a}"] for a in agents] \
                + [acts[a] for a in agents]
            return jnp.concatenate(cols, axis=-1)

        def critic_loss(critics, target, batch):
            # target actions from target actors on next obs
            next_acts = {a: jnp.tanh(mlp(target[f"actor_{a}"],
                                         batch[f"next_obs_{a}"]))
                         for a in agents}
            xn = jnp.concatenate(
                [batch[f"next_obs_{a}"] for a in agents]
                + [next_acts[a] for a in agents], axis=-1)
            x = joint_x(batch, {a: batch[f"act_{a}"] for a in agents})
            not_done = 1.0 - batch["dones"].astype(jnp.float32)
            total = 0.0
            for a in agents:
                qn = mlp(target[f"critic_{a}"], xn)[:, 0]
                y = batch[f"rew_{a}"] + c.gamma * not_done \
                    * jax.lax.stop_gradient(qn)
                q = mlp(critics[a], x)[:, 0]
                total = total + jnp.mean((q - y) ** 2)
            return total / N

        def actor_loss(actors, params, batch):
            # each agent's actor ascends its own centralized critic with
            # the OTHER agents' batch actions held fixed
            total = 0.0
            for a in agents:
                acts = {b: (jnp.tanh(mlp(actors[a], batch[f"obs_{a}"]))
                            if b == a else batch[f"act_{b}"])
                        for b in agents}
                q = mlp(params[f"critic_{a}"], joint_x(batch, acts))[:, 0]
                total = total - jnp.mean(q)
            return total / N

        def polyak(t, p):
            return jax.tree.map(
                lambda x, y: x * (1 - c.tau) + y * c.tau, t, p)

        def one_update(carry, batch):
            params, target, s_a, s_c = carry
            critics = {a: params[f"critic_{a}"] for a in agents}
            closs, cgrads = jax.value_and_grad(critic_loss)(
                critics, target, batch)
            cu, s_c = self.opt_critic.update(cgrads, s_c, critics)
            critics = optax.apply_updates(critics, cu)
            params = {**params,
                      **{f"critic_{a}": critics[a] for a in agents}}
            actors = {a: params[f"actor_{a}"] for a in agents}
            aloss, agrads = jax.value_and_grad(actor_loss)(
                actors, params, batch)
            au, s_a = self.opt_actor.update(agrads, s_a, actors)
            actors = optax.apply_updates(actors, au)
            params = {**params,
                      **{f"actor_{a}": actors[a] for a in agents}}
            target = polyak(target, params)
            return (params, target, s_a, s_c), \
                {"critic_loss": closs, "actor_loss": aloss}

        @functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
        def update_many(params, target, s_a, s_c, batches):
            (params, target, s_a, s_c), stats = jax.lax.scan(
                one_update, (params, target, s_a, s_c), batches)
            return params, target, s_a, s_c, jax.tree.map(
                jnp.mean, stats)

        self._update_many = update_many

    def update(self, stacked: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax
        import jax.numpy as jnp

        batches = {k: jnp.asarray(v) for k, v in stacked.items()}
        (self.params, self.target, self.state_actor, self.state_critic,
         stats) = self._update_many(self.params, self.target,
                                    self.state_actor, self.state_critic,
                                    batches)
        self.num_updates += int(stacked["dones"].shape[0])
        return {k: float(v) for k, v in jax.device_get(stats).items()}

    def actor_params_np(self) -> Dict[str, Dict]:
        import jax

        return {a: jax.device_get(self.params[f"actor_{a}"])
                for a in self.agents}


class MADDPG:
    """Tune-trainable MADDPG driver (TD3 shape, joint transitions)."""

    def __init__(self, config: MADDPGConfig):
        self.config = c = config
        creator_blob = (cloudpickle.dumps(c.env_creator)
                        if c.env_creator else None)
        cls = ray_tpu.remote(MADDPGRolloutWorker)
        opts = worker_opts(c.worker_resources)
        self.workers = [
            cls.options(**opts).remote(
                c.env, c.num_envs_per_worker, c.rollout_fragment_length,
                c.explore_sigma, seed=c.seed + 31 * i,
                env_creator=creator_blob)
            for i in range(c.num_rollout_workers)]
        info = ray_tpu.get(self.workers[0].env_info.remote(), timeout=180)
        self.agents = list(info["agent_ids"])
        self.learner = MADDPGLearner(self.agents, info["obs_dim"],
                                     info["action_dim"], c)
        self.buffer = ReplayBuffer(c.buffer_size, seed=c.seed)
        self._iteration = 0
        self._total_steps = 0
        self._total_episodes = 0
        self._recent: List[float] = []

    def train(self) -> Dict[str, Any]:
        c = self.config
        t0 = time.monotonic()
        warmup = self._total_steps < c.learning_starts
        actors_ref = ray_tpu.put(self.learner.actor_params_np())
        batches = ray_tpu.get(
            [w.sample.remote(actors_ref, random_actions=warmup)
             for w in self.workers], timeout=300)
        steps = 0
        for b in batches:
            self.buffer.add(b)
            steps += len(b["dones"])
        self._total_steps += steps
        stats: Dict[str, float] = {}
        if len(self.buffer) >= max(c.learning_starts,
                                   c.train_batch_size):
            K, B = c.num_updates_per_iter, c.train_batch_size
            mb = self.buffer.sample(K * B)
            stacked = {k: v.reshape(K, B, *v.shape[1:])
                       for k, v in mb.items()}
            stats = self.learner.update(stacked)
        for rets in ray_tpu.get(
                [w.episode_returns.remote() for w in self.workers],
                timeout=60):
            self._recent.extend(rets)
            self._total_episodes += len(rets)
        self._recent = self._recent[-100:]
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "timesteps_total": self._total_steps,
            "timesteps_this_iter": steps,
            "episode_reward_mean": (float(np.mean(self._recent))
                                    if self._recent else float("nan")),
            "episodes_total": self._total_episodes,
            "num_updates": self.learner.num_updates,
            "time_this_iter_s": time.monotonic() - t0,
            **stats,
        }

    # -- Tune-trainable surface ------------------------------------------

    def save(self) -> Dict:
        import jax

        L = self.learner
        ckpt = {"params": jax.device_get(L.params),
                "target": jax.device_get(L.target),
                "opt_states": jax.device_get((L.state_actor,
                                              L.state_critic)),
                "iteration": self._iteration,
                "total_steps": self._total_steps}
        if self.config.checkpoint_replay_buffer:
            ckpt["buffer"] = self.buffer.state()
        return ckpt

    def restore(self, ckpt: Dict) -> None:
        import jax
        import jax.numpy as jnp

        as_jnp = lambda t: jax.tree.map(jnp.asarray, t)  # noqa: E731
        L = self.learner
        L.params = as_jnp(ckpt["params"])
        L.target = as_jnp(ckpt["target"])
        if "opt_states" in ckpt:
            L.state_actor, L.state_critic = as_jnp(ckpt["opt_states"])
        self._iteration = int(ckpt.get("iteration", 0))
        self._total_steps = int(ckpt.get("total_steps", 0))
        if "buffer" in ckpt:
            self.buffer.restore(ckpt["buffer"])

    def stop(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
