"""IMPALA — asynchronous distributed on-policy RL with V-trace.

ref: rllib/algorithms/impala/impala.py (async sample pipeline,
training_step :760) and vtrace off-policy correction (Espeholt et al.
2018). The architectural point vs PPO: rollout actors sample
CONTINUOUSLY against whatever weights they last saw and ship batches
into a queue; the learner consumes without barriers, so slow actors
never stall the device. The resulting policy lag is corrected by
V-trace importance weighting (rho/c clipping) inside the jitted
learner update.

TPU-native shape mirrors the house style: numpy behavior policies in
the actors (np_policy rationale), ONE jitted donated-buffer update per
consumed batch on the device, weights broadcast through the object
store every `broadcast_interval` updates.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle
import numpy as np

import ray_tpu

from .np_policy import ensure_numpy, sample_actions
from .rollout_worker import EnvWorkerBase, worker_opts


class ImpalaRolloutWorker(EnvWorkerBase):
    """Actor producing fixed-length trajectory fragments [T, n] with the
    behavior policy's log-probs (needed for the V-trace ratios). Unlike
    PPO's worker, NO advantage computation happens here — V-trace needs
    the learner's CURRENT values, not the behavior policy's."""

    def __init__(self, env_name: str, num_envs: int, rollout_len: int,
                 gamma: float = 0.99, seed: int = 0, env_creator=None):
        super().__init__(env_name, num_envs, rollout_len, seed, env_creator)
        self.gamma = gamma

    def sample(self, params: Dict) -> Dict[str, np.ndarray]:
        params = ensure_numpy(params)
        T, n = self.rollout_len, self.env.num_envs
        obs = np.empty((T + 1, n, *self.env.obs_shape), self.env.obs_dtype)
        act = np.empty((T, n), np.int64)
        logp = np.empty((T, n), np.float32)
        rew = np.empty((T, n), np.float32)
        done = np.empty((T, n), np.bool_)
        cur = self._obs
        for t in range(T):
            a, lp, _ = sample_actions(params, cur, self._rng)
            obs[t], act[t], logp[t] = cur, a, lp
            cur, r, d, info = self.env.step(a)
            rew[t], done[t] = r, d
            if d.any() and "truncated" in info:
                # Time-limit truncation is not termination, but the env
                # auto-reset already replaced cur with the NEXT episode's
                # obs — clearing done would make V-trace bootstrap from
                # the unrelated fresh episode. Keep done=True (cut the
                # chain) and fold gamma*V_behavior(s_final) into the
                # reward instead (the rollout_worker.py:73 recipe).
                trunc = info["truncated"]
                if trunc.any():
                    idx = np.nonzero(trunc)[0]
                    _, _, v_final = sample_actions(
                        params, info["final_obs"][idx], self._rng)
                    rew[t, idx] += self.gamma * v_final
            self._track_returns(r, d)
        obs[T] = cur
        self._obs = cur
        return {"obs": obs, "actions": act, "behavior_logp": logp,
                "rewards": rew, "dones": done}


class ImpalaLearner:
    """Jitted V-trace actor-critic update (Espeholt et al. eq. 1)."""

    def __init__(self, obs_dim, num_actions: int, *, lr: float = 5e-4,
                 gamma: float = 0.99, rho_clip: float = 1.0,
                 c_clip: float = 1.0, vf_coeff: float = 0.5,
                 ent_coeff: float = 0.01, hidden=(64, 64), seed: int = 0,
                 max_grad_norm: float = 10.0,
                 clip_param: Optional[float] = None):
        # clip_param set = APPO: the PPO clipped surrogate on V-trace
        # advantages instead of the plain importance-weighted PG loss
        # (ref: rllib/algorithms/appo/appo.py - APPO is IMPALA's async
        # pipeline with PPO's loss)
        self.clip_param = clip_param
        import jax
        import optax

        from .models import init_policy_params

        self.params = init_policy_params(jax.random.PRNGKey(seed), obs_dim,
                                         num_actions, tuple(hidden))
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(max_grad_norm), optax.adam(lr))
        self.opt_state = self.optimizer.init(self.params)
        self._update = jax.jit(
            self._make_update(gamma, rho_clip, c_clip, vf_coeff, ent_coeff,
                              clip_param),
            donate_argnums=(0, 1))
        self.num_updates = 0

    @staticmethod
    def _vtrace(values, bootstrap, rewards, dones, rhos, gamma,
                rho_clip, c_clip):
        """V-trace targets via a reverse lax.scan over [T, n] fragments;
        dones cut the bootstrap at (true) episode ends."""
        import jax
        import jax.numpy as jnp

        not_done = 1.0 - dones.astype(jnp.float32)
        clipped_rho = jnp.minimum(rhos, rho_clip)
        cs = jnp.minimum(rhos, c_clip)
        next_values = jnp.concatenate([values[1:], bootstrap[None]], axis=0)
        deltas = clipped_rho * (rewards + gamma * next_values * not_done
                                - values)

        def body(acc, xs):
            delta, c, nd = xs
            acc = delta + gamma * nd * c * acc
            return acc, acc

        _, adv = jax.lax.scan(body, jnp.zeros_like(bootstrap),
                              (deltas, cs, not_done), reverse=True)
        vs = values + adv  # v_s targets
        vs_next = jnp.concatenate([vs[1:], bootstrap[None]], axis=0)
        # policy-gradient advantages use one-step targets (paper eq. 1)
        pg_adv = clipped_rho * (rewards + gamma * vs_next * not_done
                                - values)
        return vs, pg_adv

    def _make_update(self, gamma, rho_clip, c_clip, vf_coeff, ent_coeff,
                     clip_param=None):
        import jax
        import jax.numpy as jnp
        import optax

        from .models import forward

        def loss_fn(params, batch):
            T, n = batch["actions"].shape
            obs_all = batch["obs"].reshape((T + 1) * n,
                                           *batch["obs"].shape[2:])
            logits_all, values_all = forward(params, obs_all)
            logits = logits_all.reshape(T + 1, n, -1)[:T]
            values = values_all.reshape(T + 1, n)
            bootstrap = values[T]
            values = values[:T]
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][..., None], axis=-1)[..., 0]
            rhos = jnp.exp(logp - batch["behavior_logp"])
            vs, pg_adv = self._vtrace(
                jax.lax.stop_gradient(values),
                jax.lax.stop_gradient(bootstrap), batch["rewards"],
                batch["dones"], jax.lax.stop_gradient(rhos), gamma,
                rho_clip, c_clip)
            if clip_param is None:
                pg_loss = -jnp.mean(logp * jax.lax.stop_gradient(pg_adv))
            else:
                adv = jax.lax.stop_gradient(pg_adv)
                # per-batch advantage normalization (the standard PPO
                # recipe; raw V-trace advantages carry return scale)
                adv = (adv - adv.mean()) / (adv.std() + 1e-8)
                ratio = rhos  # same importance ratio V-trace used;
                # gradient flows through it (only the _vtrace arg was
                # stop_gradient'ed)
                surr = jnp.minimum(
                    ratio * adv,
                    jnp.clip(ratio, 1 - clip_param, 1 + clip_param) * adv)
                pg_loss = -jnp.mean(surr)
            vf_loss = jnp.mean((values - jax.lax.stop_gradient(vs)) ** 2)
            entropy = -jnp.mean(
                jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
            loss = pg_loss + vf_coeff * vf_loss - ent_coeff * entropy
            stats = {"pg_loss": pg_loss, "vf_loss": vf_loss,
                     "entropy": entropy, "mean_rho": jnp.mean(rhos)}
            return loss, stats

        def update(params, opt_state, batch):
            (loss, stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = self.optimizer.update(grads, opt_state,
                                                       params)
            return (optax.apply_updates(params, updates), opt_state, loss,
                    stats)

        return update

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax
        import jax.numpy as jnp

        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        self.params, self.opt_state, loss, stats = self._update(
            self.params, self.opt_state, jb)
        self.num_updates += 1
        out = jax.device_get(stats)
        return {"loss": float(loss), **{k: float(v) for k, v in out.items()}}

    def get_params(self) -> Dict:
        import jax

        return jax.device_get(self.params)


@dataclass
class ImpalaConfig:
    """ref: impala.py IMPALAConfig defaults (rollout 50, broadcast every
    update, queue-fed learner)."""
    env: str = "CartPole-v1"
    env_creator: Optional[Callable] = None
    num_rollout_workers: int = 2
    num_envs_per_worker: int = 8
    rollout_fragment_length: int = 32
    gamma: float = 0.99
    lr: float = 5e-4
    rho_clip: float = 1.0
    c_clip: float = 1.0
    vf_coeff: float = 0.5
    ent_coeff: float = 0.01
    batches_per_iter: int = 8
    clip_param: Optional[float] = None  # set = APPO (PPO clip on V-trace)
    broadcast_interval: int = 1  # updates between weight publications
    max_queue: int = 8
    hidden: tuple = (64, 64)
    seed: int = 0
    worker_resources: Dict[str, float] = field(default_factory=dict)

    def environment(self, env: str = None, *,
                    env_creator=None) -> "ImpalaConfig":
        if env is not None:
            self.env = env
        if env_creator is not None:
            self.env_creator = env_creator
        return self

    def rollouts(self, *, num_rollout_workers: int = None,
                 num_envs_per_worker: int = None,
                 rollout_fragment_length: int = None) -> "ImpalaConfig":
        for k, v in [("num_rollout_workers", num_rollout_workers),
                     ("num_envs_per_worker", num_envs_per_worker),
                     ("rollout_fragment_length", rollout_fragment_length)]:
            if v is not None:
                setattr(self, k, v)
        return self

    def training(self, *, lr: float = None, gamma: float = None,
                 ent_coeff: float = None, batches_per_iter: int = None,
                 broadcast_interval: int = None) -> "ImpalaConfig":
        for k, v in [("lr", lr), ("gamma", gamma), ("ent_coeff", ent_coeff),
                     ("batches_per_iter", batches_per_iter),
                     ("broadcast_interval", broadcast_interval)]:
            if v is not None:
                setattr(self, k, v)
        return self

    def build(self) -> "Impala":
        return Impala(self)


class Impala:
    """Async pipeline: per-worker feeder threads keep one sample() in
    flight each and push results into a bounded queue (backpressure);
    train() consumes `batches_per_iter` batches, updating per batch and
    publishing fresh weights every `broadcast_interval` updates. Workers
    pick up the newest weights at their next fragment — bounded policy
    lag, corrected by V-trace."""

    def __init__(self, config: ImpalaConfig):
        self.config = c = config
        creator_blob = (cloudpickle.dumps(c.env_creator)
                        if c.env_creator else None)
        worker_cls = ray_tpu.remote(ImpalaRolloutWorker)
        opts = worker_opts(c.worker_resources)
        self.workers = [
            worker_cls.options(**opts).remote(
                c.env, c.num_envs_per_worker, c.rollout_fragment_length,
                gamma=c.gamma, seed=c.seed + 1000 * i,
                env_creator=creator_blob)
            for i in range(c.num_rollout_workers)]
        info = ray_tpu.get(self.workers[0].env_info.remote(), timeout=180)
        self.learner = ImpalaLearner(
            info.get("obs_shape", info["obs_dim"]), info["num_actions"], lr=c.lr, gamma=c.gamma,
            rho_clip=c.rho_clip, c_clip=c.c_clip, vf_coeff=c.vf_coeff,
            ent_coeff=c.ent_coeff, hidden=c.hidden, seed=c.seed,
            clip_param=c.clip_param)
        self._params_ref = ray_tpu.put(self.learner.get_params())
        self._params_lock = threading.Lock()
        import queue as _q

        self._queue: "_q.Queue" = _q.Queue(maxsize=c.max_queue)
        self._stop = threading.Event()
        self._feeders = [
            threading.Thread(target=self._feed, args=(w,), daemon=True,
                             name=f"impala-feeder-{i}")
            for i, w in enumerate(self.workers)]
        for t in self._feeders:
            t.start()
        self._iteration = 0
        self._total_steps = 0
        self._total_episodes = 0
        self._recent: List[float] = []

    def _feed(self, worker) -> None:
        """One in-flight sample per worker, forever (the async half)."""
        import queue as _q

        while not self._stop.is_set():
            try:
                with self._params_lock:
                    ref = self._params_ref
                batch = ray_tpu.get(worker.sample.remote(ref), timeout=300)
            except Exception:
                if not self._stop.is_set():
                    time.sleep(0.2)  # worker error: actor restart covers it
                continue
            # backpressure: NEVER drop a sampled batch — re-offer until a
            # slot frees or shutdown (a full queue just means the learner
            # is momentarily behind, not that the work is worthless)
            while not self._stop.is_set():
                try:
                    self._queue.put(batch, timeout=0.5)
                    break
                except _q.Full:
                    continue

    def train(self) -> Dict[str, Any]:
        c = self.config
        t0 = time.monotonic()
        stat_sums: Dict[str, float] = {}
        steps = 0
        for _ in range(c.batches_per_iter):
            batch = self._queue.get(timeout=300)
            steps += int(np.prod(batch["actions"].shape))
            for k, v in self.learner.update(batch).items():
                stat_sums[k] = stat_sums.get(k, 0.0) + float(v)
            if self.learner.num_updates % c.broadcast_interval == 0:
                new_ref = ray_tpu.put(self.learner.get_params())
                with self._params_lock:
                    self._params_ref = new_ref
        for rets in ray_tpu.get(
                [w.episode_returns.remote() for w in self.workers],
                timeout=60):
            self._recent.extend(rets)
            self._total_episodes += len(rets)
        self._recent = self._recent[-100:]
        self._iteration += 1
        self._total_steps += steps
        dt = time.monotonic() - t0
        return {
            "training_iteration": self._iteration,
            "timesteps_total": self._total_steps,
            "timesteps_this_iter": steps,
            "episode_reward_mean": (float(np.mean(self._recent))
                                    if self._recent else float("nan")),
            "episodes_total": self._total_episodes,
            "env_steps_per_sec": steps / max(1e-9, dt),
            # means over the iteration's updates, not the last batch's
            **{k: v / max(1, c.batches_per_iter)
               for k, v in stat_sums.items()},
        }

    # -- Tune-trainable surface ------------------------------------------

    def save(self) -> Dict:
        import jax

        return {"params": jax.device_get(self.learner.params),
                "opt_state": jax.device_get(self.learner.opt_state),
                "iteration": self._iteration,
                "total_steps": self._total_steps}

    def restore(self, ckpt: Dict) -> None:
        import jax
        import jax.numpy as jnp

        self.learner.params = jax.tree.map(jnp.asarray, ckpt["params"])
        if "opt_state" in ckpt:
            self.learner.opt_state = jax.tree.map(jnp.asarray,
                                                  ckpt["opt_state"])
        self._iteration = int(ckpt.get("iteration", 0))
        self._total_steps = int(ckpt.get("total_steps", 0))
        with self._params_lock:
            self._params_ref = ray_tpu.put(self.learner.get_params())

    def stop(self) -> None:
        self._stop.set()
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass


@dataclass
class APPOConfig(ImpalaConfig):
    """APPO = IMPALA's async sample pipeline + PPO's clipped surrogate on
    V-trace advantages (ref: rllib/algorithms/appo/appo.py)."""
    clip_param: Optional[float] = 0.2

    def build(self) -> "APPO":
        return APPO(self)


class APPO(Impala):
    """Asynchronous PPO (ref: appo.py). Everything but the loss is
    IMPALA: feeder threads, bounded queue, V-trace correction."""
