"""Evolution Strategies (OpenAI-ES) — embarrassingly parallel policy
search on the task plane.

ref: rllib/algorithms/es/es.py (+ es_tf_policy / optimizers.py): N
antithetic Gaussian perturbations of the policy parameters are evaluated
as full episodes on a pool of rollout actors; returns are centered-rank
normalized and combined into a gradient estimate
    g = (1 / (N * sigma)) * sum_i rank_i * eps_i
applied with Adam. The reference ships noise via a shared 250MB noise
table; here workers REGENERATE each perturbation from its integer seed
(np.default_rng(seed)), so only (seed, sign, return) triples cross the
object store — the single-controller reduction of the same trick.

Rollouts are pure numpy (np_policy.forward_np); no jax in workers — ES
is a showcase of the runtime's task fan-out, not the chip.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu

from .env import make_env
from .np_policy import forward_np
from .rollout_worker import worker_opts


def _flat_params(shapes: List[Tuple[str, tuple]], theta: np.ndarray
                 ) -> Dict[str, np.ndarray]:
    out = {}
    off = 0
    for name, shp in shapes:
        n = int(np.prod(shp))
        out[name] = theta[off:off + n].reshape(shp).astype(np.float32)
        off += n
    return out


def _init_shapes(obs_dim: int, num_actions: int,
                 hidden: Tuple[int, ...]) -> List[Tuple[str, tuple]]:
    shapes: List[Tuple[str, tuple]] = []
    last = obs_dim
    for i, h in enumerate(hidden):
        shapes.append((f"w{i}", (last, h)))
        shapes.append((f"b{i}", (h,)))
        last = h
    shapes += [("w_pi", (last, num_actions)), ("b_pi", (num_actions,)),
               ("w_v", (last, 1)), ("b_v", (1,))]
    return shapes


def _episode_return(params: Dict[str, np.ndarray], env, max_steps: int,
                    obs_fn=None) -> float:
    """One greedy episode; obs_fn (ARS's observation filter) transforms
    each obs batch before the policy sees it."""
    obs = env.reset()
    total = 0.0
    for _ in range(max_steps):
        logits, _ = forward_np(params, obs_fn(obs) if obs_fn else obs)
        actions = np.argmax(logits, axis=1)
        obs, reward, done, _ = env.step(actions)
        total += float(reward.sum())
        if done.all():
            break
    return total / env.num_envs


class ESWorker:
    """Evaluates perturbations: regenerates eps from the seed, runs one
    greedy episode per (seed, sign) (ref: es.py Worker.do_rollouts)."""

    def __init__(self, env_name: str, hidden: tuple, sigma: float,
                 max_steps: int, seed: int = 0, env_creator=None):
        import cloudpickle

        if env_creator is not None:
            self.env = cloudpickle.loads(env_creator)(num_envs=1, seed=seed)
        else:
            self.env = make_env(env_name, num_envs=1, seed=seed)
        self.shapes = _init_shapes(self.env.obs_dim, self.env.num_actions,
                                   tuple(hidden))
        self.sigma = sigma
        self.max_steps = max_steps

    def dim(self) -> int:
        return int(sum(np.prod(s) for _, s in self.shapes))

    def obs_shape(self) -> tuple:
        return tuple(self.env.obs_shape)

    def _episode(self, params: Dict[str, np.ndarray],
                 update_filter: bool = True) -> float:
        """One greedy episode; ARS overrides with a filtered variant."""
        return _episode_return(params, self.env, self.max_steps)

    def evaluate(self, theta: np.ndarray,
                 seeds: List[int]) -> List[Tuple[int, int, float]]:
        out = []
        for seed in seeds:
            eps = np.random.default_rng(seed).standard_normal(
                theta.shape[0]).astype(np.float32)
            for sign in (1, -1):
                params = _flat_params(self.shapes,
                                      theta + sign * self.sigma * eps)
                out.append((seed, sign, self._episode(params)))
        return out

    def evaluate_center(self, theta: np.ndarray) -> float:
        return self._episode(_flat_params(self.shapes, theta),
                             update_filter=False)


def _centered_ranks(x: np.ndarray) -> np.ndarray:
    """ref: es/utils.py compute_centered_ranks."""
    ranks = np.empty(len(x), dtype=np.float32)
    ranks[x.argsort()] = np.arange(len(x), dtype=np.float32)
    return ranks / (len(x) - 1) - 0.5


@dataclass
class ESConfig:
    """ref: es.py ESConfig (episodes_per_batch, noise_stdev, stepsize)."""
    env: str = "CartPole-v1"
    env_creator: Optional[Callable] = None
    num_workers: int = 2
    episodes_per_batch: int = 32    # perturbation PAIRS per iteration
    sigma: float = 0.1
    lr: float = 0.02
    l2_coeff: float = 0.005
    hidden: tuple = (32, 32)
    max_episode_steps: int = 500
    seed: int = 0
    worker_resources: Dict[str, float] = field(default_factory=dict)

    def build(self) -> "ES":
        return ES(self)


class ES:
    """Tune-trainable ES driver."""

    def __init__(self, config: ESConfig):
        import cloudpickle

        c = self.config = config
        creator_blob = (cloudpickle.dumps(c.env_creator)
                        if c.env_creator is not None else None)
        cls = ray_tpu.remote(ESWorker)
        opts = worker_opts(c.worker_resources)
        self.workers = [
            cls.options(**opts).remote(
                c.env, tuple(c.hidden), c.sigma, c.max_episode_steps,
                seed=c.seed + 100 * i, env_creator=creator_blob)
            for i in range(c.num_workers)
        ]
        dim = ray_tpu.get(self.workers[0].dim.remote(), timeout=180)
        rng = np.random.default_rng(c.seed)
        self.theta = (rng.standard_normal(dim) * 0.05).astype(np.float32)
        # Adam state
        self._m = np.zeros(dim, np.float32)
        self._v = np.zeros(dim, np.float32)
        self._t = 0
        self._seed_seq = c.seed * 1_000_003 + 1
        self._iteration = 0
        self._total_episodes = 0

    def train(self) -> Dict[str, float]:
        c = self.config
        t0 = time.monotonic()
        n_pairs = c.episodes_per_batch
        seeds = [self._seed_seq + i for i in range(n_pairs)]
        self._seed_seq += n_pairs
        theta_ref = ray_tpu.put(self.theta)
        chunks = np.array_split(np.asarray(seeds), len(self.workers))
        futs = [w.evaluate.remote(theta_ref, [int(s) for s in chunk])
                for w, chunk in zip(self.workers, chunks) if len(chunk)]
        triples = [t for batch in ray_tpu.get(futs, timeout=600)
                   for t in batch]
        returns = {}
        for seed, sign, ret in triples:
            returns.setdefault(seed, {})[sign] = ret
        pos = np.array([returns[s][1] for s in seeds], np.float32)
        neg = np.array([returns[s][-1] for s in seeds], np.float32)
        ranks = _centered_ranks(np.concatenate([pos, neg]))
        advantage = ranks[:n_pairs] - ranks[n_pairs:]
        grad = np.zeros_like(self.theta)
        for adv, seed in zip(advantage, seeds):
            eps = np.random.default_rng(seed).standard_normal(
                self.theta.shape[0]).astype(np.float32)
            grad += adv * eps
        grad = grad / (2 * n_pairs * c.sigma) - c.l2_coeff * self.theta
        # Adam ascent (ref: es/optimizers.py Adam)
        self._t += 1
        self._m = 0.9 * self._m + 0.1 * grad
        self._v = 0.999 * self._v + 0.001 * grad * grad
        mh = self._m / (1 - 0.9 ** self._t)
        vh = self._v / (1 - 0.999 ** self._t)
        self.theta = self.theta + c.lr * mh / (np.sqrt(vh) + 1e-8)

        center = ray_tpu.get(
            self.workers[0].evaluate_center.remote(
                ray_tpu.put(self.theta)), timeout=120)
        self._iteration += 1
        self._total_episodes += 2 * n_pairs
        return {
            "training_iteration": self._iteration,
            "episodes_total": self._total_episodes,
            "episode_reward_mean": float(center),
            "perturbation_reward_mean": float(np.mean([pos, neg])),
            "time_this_iter_s": time.monotonic() - t0,
        }

    # -- Tune-trainable surface ------------------------------------------

    def save(self) -> Dict:
        return {"theta": self.theta.copy(), "m": self._m.copy(),
                "v": self._v.copy(), "t": self._t,
                "iteration": self._iteration,
                "seed_seq": self._seed_seq}

    def restore(self, ckpt: Dict) -> None:
        self.theta = np.asarray(ckpt["theta"], np.float32)
        self._m = np.asarray(ckpt.get("m", np.zeros_like(self.theta)))
        self._v = np.asarray(ckpt.get("v", np.zeros_like(self.theta)))
        self._t = int(ckpt.get("t", 0))
        self._iteration = int(ckpt.get("iteration", 0))
        self._seed_seq = int(ckpt.get("seed_seq", 1))

    def stop(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
