"""Offline RL — experience IO + MARWIL/BC.

ref: rllib/offline/ (json_writer.py/json_reader.py SampleBatch files,
dataset_reader.py) and rllib/algorithms/marwil/ (MARWIL: Monotonic
Advantage Re-Weighted Imitation Learning; BC is MARWIL with beta=0 —
the same subclassing the reference uses, bc.py:24).

Experience files are JSONL of per-episode records (obs/actions/rewards/
dones lists) — readable with stdlib, diffable, and loadable through
`ray_tpu.data.read_json` as well. `collect_experiences` runs any
callable policy over a VectorEnv to produce them (the analog of
rollout-workers writing through a JsonWriter output config).

The MARWIL learner computes discounted returns per episode, fits a value
baseline, and weights the imitation log-likelihood by
exp(beta * advantage) — plain behavior cloning when beta == 0.
"""
from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .env import VectorEnv, make_env
from .models import forward, init_policy_params


# ---------------------------------------------------------------------------
# experience IO (ref: offline/json_writer.py / json_reader.py)
# ---------------------------------------------------------------------------


def write_experiences(path: str, episodes: List[Dict[str, Any]]) -> None:
    """episodes: [{obs: [T,...], actions: [T], rewards: [T]}] -> JSONL."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        for ep in episodes:
            rec = {k: np.asarray(v).tolist() for k, v in ep.items()}
            f.write(json.dumps(rec) + "\n")


def read_experiences(paths) -> List[Dict[str, np.ndarray]]:
    if isinstance(paths, str):
        paths = [paths]
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _d, names in os.walk(p):
                files.extend(os.path.join(root, n) for n in sorted(names)
                             if n.endswith((".json", ".jsonl")))
        else:
            files.append(p)
    episodes = []
    for fp in files:
        with open(fp) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                episodes.append({
                    "obs": np.asarray(rec["obs"], np.float32),
                    "actions": np.asarray(rec["actions"], np.int64),
                    "rewards": np.asarray(rec["rewards"], np.float32),
                })
    if not episodes:
        raise FileNotFoundError(f"no experience files under {paths}")
    return episodes


def collect_experiences(env: VectorEnv, policy: Callable[[np.ndarray],
                                                         np.ndarray],
                        num_episodes: int, path: Optional[str] = None,
                        seed: int = 0) -> List[Dict[str, Any]]:
    """Run `policy(obs_batch) -> actions` until num_episodes complete;
    optionally write the JSONL file. Episodes are tracked per sub-env so
    vectorized auto-resets don't splice episodes together."""
    n = env.num_envs
    obs = env.reset(seed=seed)
    cur: List[Dict[str, list]] = [
        {"obs": [], "actions": [], "rewards": []} for _ in range(n)]
    done_eps: List[Dict[str, Any]] = []
    while len(done_eps) < num_episodes:
        actions = np.asarray(policy(obs))
        for i in range(n):
            cur[i]["obs"].append(obs[i])
            cur[i]["actions"].append(actions[i])
        obs, reward, done, info = env.step(actions)
        for i in range(n):
            cur[i]["rewards"].append(reward[i])
            if done[i]:
                done_eps.append({k: np.asarray(v)
                                 for k, v in cur[i].items()})
                cur[i] = {"obs": [], "actions": [], "rewards": []}
    done_eps = done_eps[:num_episodes]
    if path:
        write_experiences(path, done_eps)
    return done_eps


# ---------------------------------------------------------------------------
# MARWIL / BC
# ---------------------------------------------------------------------------


@dataclass
class MARWILConfig:
    """ref: marwil.py MARWILConfig (beta, vf_coeff); bc.py sets beta=0."""
    env: str = "CartPole-v1"          # for evaluation only
    env_creator: Optional[Callable] = None
    input_paths: Any = None           # file/dir of JSONL experiences
    episodes: Optional[List[Dict[str, np.ndarray]]] = None  # or in-memory
    beta: float = 1.0                 # 0 = plain behavior cloning
    gamma: float = 0.99
    lr: float = 5e-4
    vf_coeff: float = 1.0
    train_batch_size: int = 512
    num_updates_per_iter: int = 32
    hidden: tuple = (64, 64)
    seed: int = 0
    evaluation_num_episodes: int = 8

    def build(self) -> "MARWIL":
        return MARWIL(self)


@dataclass
class BCConfig(MARWILConfig):
    """Behavior cloning = MARWIL with beta=0 (ref: bc.py:24)."""
    beta: float = 0.0

    def build(self) -> "BC":
        return BC(self)


class MARWIL:
    """Offline trainer: no rollout workers — train() consumes the fixed
    dataset; evaluation runs the learned policy in the env."""

    def __init__(self, config: MARWILConfig):
        import jax
        import jax.numpy as jnp
        import optax

        self.config = c = config
        episodes = (c.episodes if c.episodes is not None
                    else read_experiences(c.input_paths))
        if not episodes:
            raise ValueError("MARWIL/BC needs offline data: pass "
                             "episodes or input_paths with at least one "
                             "episode")
        # flatten episodes into transitions with discounted returns
        obs, acts, rets = [], [], []
        for ep in episodes:
            r = np.asarray(ep["rewards"], np.float32)
            g = np.zeros_like(r)
            acc = 0.0
            for t in range(len(r) - 1, -1, -1):
                acc = r[t] + c.gamma * acc
                g[t] = acc
            obs.append(np.asarray(ep["obs"], np.float32))
            acts.append(np.asarray(ep["actions"], np.int64))
            rets.append(g)
        self._obs = np.concatenate(obs)
        self._acts = np.concatenate(acts)
        rets_all = np.concatenate(rets)
        # standardize returns: raw discounted returns (O(1/(1-gamma)))
        # would make the shared-trunk value loss dwarf the imitation
        # gradient and degrade the policy head
        self._ret_mean = float(rets_all.mean())
        self._ret_std = float(rets_all.std() + 1e-8)
        self._rets = (rets_all - self._ret_mean) / self._ret_std
        # env floor: the behavior policy may never have taken some
        # actions (the cql.py num_actions guard)
        probe = (c.env_creator(num_envs=1, seed=0) if c.env_creator
                 else make_env(c.env, num_envs=1, seed=0))
        self._num_actions = max(int(self._acts.max()) + 1,
                                probe.num_actions)
        obs_shape = self._obs.shape[1:]
        self.params = init_policy_params(
            jax.random.PRNGKey(c.seed),
            obs_shape if len(obs_shape) > 1 else int(obs_shape[0]),
            self._num_actions, tuple(c.hidden))
        self.optimizer = optax.adam(c.lr)
        self.opt_state = self.optimizer.init(self.params)
        self._rng = np.random.default_rng(c.seed)
        self._iteration = 0

        beta, vf_coeff = c.beta, c.vf_coeff

        def loss_fn(params, ob, ac, ret):
            logits, values = forward(params, ob)
            logp = jax.nn.log_softmax(logits)
            logp_a = jnp.take_along_axis(logp, ac[:, None], axis=1)[:, 0]
            adv = ret - values
            if beta == 0.0:
                # plain BC needs no baseline at all (ref: bc.py — BC
                # drops the value head from the loss)
                vf_loss = jnp.float32(0.0)
                pol_loss = -jnp.mean(logp_a)
            else:
                vf_loss = jnp.mean(adv ** 2)
                # exp(beta * normalized advantage), gradient only through
                # the log-likelihood (ref: marwil_torch_policy.py loss)
                w = jnp.exp(beta * jax.lax.stop_gradient(
                    adv / (jnp.std(adv) + 1e-8)))
                w = jnp.minimum(w, 20.0)           # weight clip
                pol_loss = -jnp.mean(w * logp_a)
            return pol_loss + vf_coeff * vf_loss, (pol_loss, vf_loss)

        def update_many(params, opt_state, ob, ac, ret):
            def body(carry, xs):
                params, opt_state = carry
                o, a, r = xs
                (loss, aux), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params, o, a, r)
                updates, opt_state = self.optimizer.update(grads, opt_state)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), (loss, *aux)

            (params, opt_state), stats = jax.lax.scan(
                body, (params, opt_state), (ob, ac, ret))
            return params, opt_state, jax.tree.map(jnp.mean, stats)

        self._update_many = jax.jit(update_many, donate_argnums=(0, 1))

    def train(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        c = self.config
        t0 = time.monotonic()
        K, B = c.num_updates_per_iter, min(c.train_batch_size,
                                           len(self._obs))
        idx = self._rng.integers(0, len(self._obs), size=(K, B))
        ob = jnp.asarray(self._obs[idx])
        ac = jnp.asarray(self._acts[idx])
        ret = jnp.asarray(self._rets[idx])
        self.params, self.opt_state, (loss, pol, vf) = self._update_many(
            self.params, self.opt_state, ob, ac, ret)
        self._iteration += 1
        out = {"training_iteration": self._iteration,
               "loss": float(loss), "policy_loss": float(pol),
               "vf_loss": float(vf),
               "num_transitions": len(self._obs),
               "train_time_s": time.monotonic() - t0}
        return out

    def evaluate(self, num_episodes: Optional[int] = None,
                 seed: int = 123) -> Dict[str, float]:
        """Greedy rollouts of the learned policy in the config env."""
        import jax

        c = self.config
        n_eps = num_episodes or c.evaluation_num_episodes
        env = (c.env_creator(num_envs=4, seed=seed) if c.env_creator
               else make_env(c.env, num_envs=4, seed=seed))
        params = jax.device_get(self.params)
        from .np_policy import forward_np

        obs = env.reset(seed=seed)
        ep_ret = np.zeros(env.num_envs)
        done_rets: List[float] = []
        while len(done_rets) < n_eps:
            logits, _ = forward_np(params, obs.astype(np.float32))
            actions = logits.argmax(axis=1)
            obs, r, done, _ = env.step(actions)
            ep_ret += r
            for i in np.nonzero(done)[0]:
                done_rets.append(float(ep_ret[i]))
                ep_ret[i] = 0.0
        return {"episode_reward_mean": float(np.mean(done_rets[:n_eps])),
                "episodes": n_eps}

    # Tune-trainable surface
    def save(self) -> Dict:
        import jax

        return {"params": jax.device_get(self.params),
                "iteration": self._iteration}

    def restore(self, ckpt: Dict) -> None:
        import jax.numpy as jnp

        self.params = {k: jnp.asarray(v) for k, v in ckpt["params"].items()}
        self._iteration = int(ckpt.get("iteration", 0))

    def stop(self) -> None:
        pass  # no workers


class BC(MARWIL):
    """Behavior cloning (ref: bc.py — MARWIL with beta=0)."""
