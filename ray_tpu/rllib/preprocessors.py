"""Image-observation preprocessing wrappers + a Breakout-shaped env.

Vectorized ports of the reference's Atari pipeline (ref:
rllib/env/wrappers/atari_wrappers.py — MaxAndSkipEnv :71, WarpFrame :148,
FrameStack :206): grayscale + 84x84 resize + 4-frame stack over a
VectorEnv, operating on whole [n, H, W, C] batches.

This image ships no ALE/ROMs, so `BreakoutShapedVecEnv` stands in for the
BASELINE PPO config (Atari Breakout): native 210x160x3 uint8 frames, the
Breakout action set (NOOP/FIRE/RIGHT/LEFT), a paddle that must intercept a
falling ball — pixels-to-policy learnable, exercising the full conv +
wrapper pipeline at the real observation scale.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from .env import VectorEnv, register_env


class VecEnvWrapper(VectorEnv):
    def __init__(self, env: VectorEnv):
        self.env = env
        self.num_envs = env.num_envs
        self.num_actions = env.num_actions
        self.obs_dtype = env.obs_dtype

    @property
    def obs_shape(self):
        return self.env.obs_shape

    @property
    def obs_dim(self):
        # derived, so shape-changing wrappers (warp/stack) stay consistent
        return int(np.prod(self.obs_shape))

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        return self.env.reset(seed)

    def step(self, actions: np.ndarray):
        return self.env.step(actions)


class MaxAndSkipVec(VecEnvWrapper):
    """Repeat each action `skip` times; reward is the sum over the window
    (stopping at the first done per env so a new episode's rewards don't
    leak in); obs is the elementwise max of the last two frames (ALE
    flicker removal). ref: atari_wrappers.py:71."""

    def __init__(self, env: VectorEnv, skip: int = 4):
        super().__init__(env)
        self.skip = skip

    def step(self, actions: np.ndarray):
        # Vectorized divergence from the reference wrapper: envs that
        # finish mid-window keep being stepped (the batch moves in
        # lockstep), so the auto-reset episode consumes up to skip-1
        # stale repeats of the old action. What must NOT leak is pixels:
        # a done env returns its latest post-reset frame unmaxed rather
        # than np.maximum'd with a pre-reset frame.
        n = self.num_envs
        total = np.zeros(n, np.float32)
        done_seen = np.zeros(n, np.bool_)
        prev = obs = None
        info: Dict[str, Any] = {}
        for _ in range(self.skip):
            prev = obs
            obs, reward, done, info = self.env.step(actions)
            total += reward * (~done_seen)
            done_seen |= done
        if prev is not None:
            maxed = np.maximum(obs, prev)
            keep = done_seen.reshape((n,) + (1,) * (obs.ndim - 1))
            obs = np.where(keep, obs, maxed)
        return obs, total, done_seen, info


class WarpFrameVec(VecEnvWrapper):
    """RGB [n,H,W,3] uint8 -> grayscale 84x84x1 uint8 (luma weights +
    nearest-neighbor resize; no cv2 in this image). ref:
    atari_wrappers.py:148 WarpFrame."""

    SIZE = 84

    def __init__(self, env: VectorEnv):
        super().__init__(env)
        h, w = env.obs_shape[0], env.obs_shape[1]
        self._rows = np.linspace(0, h - 1, self.SIZE).round().astype(np.intp)
        self._cols = np.linspace(0, w - 1, self.SIZE).round().astype(np.intp)
        self.obs_dtype = np.uint8

    @property
    def obs_shape(self):
        return (self.SIZE, self.SIZE, 1)

    def _warp(self, obs: np.ndarray) -> np.ndarray:
        gray = (obs[..., 0] * 0.299 + obs[..., 1] * 0.587
                + obs[..., 2] * 0.114)
        small = gray[:, self._rows[:, None], self._cols[None, :]]
        return small.astype(np.uint8)[..., None]

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        return self._warp(self.env.reset(seed))

    def step(self, actions: np.ndarray):
        obs, reward, done, info = self.env.step(actions)
        return self._warp(obs), reward, done, info


class FrameStackVec(VecEnvWrapper):
    """Stack the last k frames along the channel axis; a done env's stack
    refills with its new episode's first frame. ref:
    atari_wrappers.py:206 FrameStack."""

    def __init__(self, env: VectorEnv, k: int = 4):
        super().__init__(env)
        self.k = k
        h, w, c = env.obs_shape
        assert c == 1, "stack grayscale frames (WarpFrameVec first)"
        self._buf = np.zeros((env.num_envs, h, w, k), env.obs_dtype)

    @property
    def obs_shape(self):
        h, w, _ = self.env.obs_shape
        return (h, w, self.k)

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        frame = self.env.reset(seed)[..., 0]
        self._buf[:] = frame[..., None]
        return self._buf.copy()

    def step(self, actions: np.ndarray):
        obs, reward, done, info = self.env.step(actions)
        self._buf = np.roll(self._buf, -1, axis=-1)
        self._buf[..., -1] = obs[..., 0]
        if done.any():
            idx = np.nonzero(done)[0]
            # post-done obs is the new episode's first frame: refill
            self._buf[idx] = obs[idx]
        return self._buf.copy(), reward, done, info


class BreakoutShapedVecEnv(VectorEnv):
    """Falling-ball catch game at Atari Breakout's native observation and
    action interface: 210x160x3 uint8 frames, actions (NOOP, FIRE, RIGHT,
    LEFT). A ball drops from the top with horizontal drift (bouncing off
    walls); the paddle at the bottom must intercept it: +1 per catch, 0
    per miss, 5 drops per episode."""

    H, W = 210, 160
    PADDLE_Y = 190
    PADDLE_HALF = 8
    BALL_HALF = 2
    PADDLE_SPEED = 6
    BALL_VY = 5
    DROPS = 5

    def __init__(self, num_envs: int = 8, seed: int = 0):
        self.num_envs = num_envs
        self.obs_dim = self.H * self.W * 3
        self.num_actions = 4
        self.obs_dtype = np.uint8
        self._rng = np.random.default_rng(seed)
        n = num_envs
        self._bx = np.zeros(n, np.float64)
        self._by = np.zeros(n, np.float64)
        self._bvx = np.zeros(n, np.float64)
        self._px = np.zeros(n, np.float64)
        self._drops = np.zeros(n, np.int64)

    @property
    def obs_shape(self):
        return (self.H, self.W, 3)

    def _spawn(self, idx: np.ndarray) -> None:
        m = len(idx)
        self._bx[idx] = self._rng.uniform(10, self.W - 10, m)
        self._by[idx] = 10.0
        self._bvx[idx] = self._rng.uniform(-3, 3, m)

    def _render(self) -> np.ndarray:
        n = self.num_envs
        frames = np.zeros((n, self.H, self.W, 3), np.uint8)
        bh = self.BALL_HALF
        ph = self.PADDLE_HALF
        for i in range(n):
            bx, by = int(self._bx[i]), int(self._by[i])
            frames[i, max(0, by - bh):by + bh,
                   max(0, bx - bh):bx + bh] = (200, 72, 72)
            px = int(self._px[i])
            frames[i, self.PADDLE_Y:self.PADDLE_Y + 4,
                   max(0, px - ph):px + ph] = (200, 72, 72)
        return frames

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        all_idx = np.arange(self.num_envs)
        self._spawn(all_idx)
        self._px[:] = self.W / 2
        self._drops[:] = self.DROPS
        return self._render()

    def step(self, actions: np.ndarray):
        # Breakout action semantics: 0 NOOP, 1 FIRE (noop here), 2 RIGHT,
        # 3 LEFT
        dx = np.where(actions == 2, self.PADDLE_SPEED,
                      np.where(actions == 3, -self.PADDLE_SPEED, 0))
        self._px = np.clip(self._px + dx, self.PADDLE_HALF,
                           self.W - self.PADDLE_HALF)
        self._bx += self._bvx
        bounce = (self._bx < self.BALL_HALF) | (self._bx > self.W - self.BALL_HALF)
        self._bvx = np.where(bounce, -self._bvx, self._bvx)
        self._bx = np.clip(self._bx, self.BALL_HALF, self.W - self.BALL_HALF)
        self._by += self.BALL_VY
        landed = self._by >= self.PADDLE_Y
        caught = landed & (np.abs(self._bx - self._px)
                           <= self.PADDLE_HALF + self.BALL_HALF)
        reward = caught.astype(np.float32)
        done = np.zeros(self.num_envs, np.bool_)
        if landed.any():
            idx = np.nonzero(landed)[0]
            self._drops[idx] -= 1
            finished = idx[self._drops[idx] <= 0]
            done[finished] = True
            self._drops[finished] = self.DROPS
            self._spawn(idx)
            if len(finished):
                self._px[finished] = self.W / 2
        return self._render(), reward, done, {}


def wrap_atari(env: VectorEnv, frame_stack: int = 4,
               max_and_skip: int = 0) -> VectorEnv:
    """The reference's wrap_deepmind composition for VectorEnvs."""
    if max_and_skip:
        env = MaxAndSkipVec(env, skip=max_and_skip)
    env = WarpFrameVec(env)
    return FrameStackVec(env, k=frame_stack)


def _make_breakout_shaped(num_envs: int = 8, seed: int = 0) -> VectorEnv:
    return wrap_atari(BreakoutShapedVecEnv(num_envs=num_envs, seed=seed))


register_env("BreakoutShaped-v0", _make_breakout_shaped)
