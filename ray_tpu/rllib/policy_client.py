"""PolicyClient — drive a remote policy from an external simulator.

ref: rllib/env/policy_client.py. Deliberately dependency-free (stdlib
urllib + json only): an external process embedding a game engine or a
hardware rig talks to a PolicyServerInput with four calls and never
imports ray_tpu:

    client = PolicyClient("http://host:port")
    eid = client.start_episode()
    action = client.get_action(eid, observation)   # list of floats
    client.log_returns(eid, reward)
    client.end_episode(eid, observation, truncated=False)
"""
from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, List, Optional


class PolicyClient:
    def __init__(self, address: str, timeout: float = 30.0):
        self._addr = address.rstrip("/")
        self._timeout = timeout

    def _post(self, route: str, payload: dict) -> dict:
        req = urllib.request.Request(
            f"{self._addr}/{route}",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self._timeout) as r:
                out = json.loads(r.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                out = json.loads(e.read() or b"{}")
            except Exception:
                out = {"error": f"HTTP {e.code}"}
        if isinstance(out, dict) and out.get("error"):
            raise RuntimeError(out["error"])
        return out

    def start_episode(self, episode_id: Optional[str] = None) -> str:
        return self._post("start_episode",
                          {"episode_id": episode_id})["episode_id"]

    def get_action(self, episode_id: str, observation: List[float]) -> Any:
        return self._post("get_action", {
            "episode_id": episode_id,
            "observation": list(map(float, observation))})["action"]

    def log_returns(self, episode_id: str, reward: float) -> None:
        self._post("log_returns", {"episode_id": episode_id,
                                   "reward": float(reward)})

    def end_episode(self, episode_id: str,
                    observation: Optional[List[float]] = None,
                    truncated: bool = False) -> None:
        payload: dict = {"episode_id": episode_id, "truncated": truncated}
        if observation is not None:
            payload["observation"] = list(map(float, observation))
        self._post("end_episode", payload)
