"""Decision Transformer — offline RL as return-conditioned sequence
modeling (Chen et al. 2021).

ref: rllib/algorithms/dt/dt.py (DTConfig: context K, target_return,
loss = action cross-entropy over trajectory segments) +
rllib/algorithms/dt/dt_torch_model.py (interleaved (R̂, s, a) tokens,
action predicted from the state token, timestep embedding added to all
three token types).

House shape: consumes the same JSONL experience files as MARWIL/BC
(offline.py), trains a compact causal transformer as ONE jitted
lax.scan over pre-sampled segment minibatches per train() call, and
evaluates by autoregressive return-conditioned rollout in a VectorEnv.
The model is deliberately self-contained jax (the segment length
3K ~ 60 tokens is far below where the GPT flash path earns its keep;
models/gpt.py stays the LM flagship)."""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .env import make_env
from .offline import read_experiences

MAX_TIMESTEP = 1024  # timestep-embedding table size (episode-step clamp)


def init_dt_params(rng, obs_dim: int, num_actions: int, d_model: int,
                   n_layer: int, n_head: int) -> Dict:
    import jax
    import jax.numpy as jnp

    D = d_model
    ks = jax.random.split(rng, 6 + 6 * n_layer)
    std = 0.02
    p = {
        "w_rtg": jax.random.normal(ks[0], (1, D), jnp.float32) * std,
        "w_obs": jax.random.normal(ks[1], (obs_dim, D),
                                   jnp.float32) * std,
        "w_act": jax.random.normal(ks[2], (num_actions, D),
                                   jnp.float32) * std,
        "wte_t": jax.random.normal(ks[3], (MAX_TIMESTEP, D),
                                   jnp.float32) * std,
        "ln_f_g": jnp.ones((D,), jnp.float32),
        "ln_f_b": jnp.zeros((D,), jnp.float32),
        "w_head": jax.random.normal(ks[4], (D, num_actions),
                                    jnp.float32) * std,
        "b_head": jnp.zeros((num_actions,), jnp.float32),
    }
    for li in range(n_layer):
        k = ks[6 + 6 * li:12 + 6 * li]
        p[f"l{li}_ln1_g"] = jnp.ones((D,), jnp.float32)
        p[f"l{li}_ln1_b"] = jnp.zeros((D,), jnp.float32)
        p[f"l{li}_qkv"] = jax.random.normal(k[0], (D, 3 * D),
                                            jnp.float32) * std
        p[f"l{li}_proj"] = jax.random.normal(
            k[1], (D, D), jnp.float32) * std / np.sqrt(2 * n_layer)
        p[f"l{li}_ln2_g"] = jnp.ones((D,), jnp.float32)
        p[f"l{li}_ln2_b"] = jnp.zeros((D,), jnp.float32)
        p[f"l{li}_fc"] = jax.random.normal(k[2], (D, 4 * D),
                                           jnp.float32) * std
        p[f"l{li}_fc_b"] = jnp.zeros((4 * D,), jnp.float32)
        p[f"l{li}_out"] = jax.random.normal(
            k[3], (4 * D, D), jnp.float32) * std / np.sqrt(2 * n_layer)
        p[f"l{li}_out_b"] = jnp.zeros((D,), jnp.float32)
    return p


def dt_forward(params: Dict, rtg, obs, acts, timesteps, pad_mask,
               n_layer: int, n_head: int):
    """Batch forward: rtg [B,K,1], obs [B,K,obs_dim], acts [B,K] int,
    timesteps [B,K] int, pad_mask [B,K] (1=real) -> action logits at the
    STATE token of every step, [B,K,A]."""
    import jax
    import jax.numpy as jnp

    B, K = acts.shape
    D = params["w_rtg"].shape[1]
    A = params["w_act"].shape[0]
    t_emb = params["wte_t"][jnp.clip(timesteps, 0, MAX_TIMESTEP - 1)]
    e_rtg = rtg @ params["w_rtg"] + t_emb
    e_obs = obs @ params["w_obs"] + t_emb
    e_act = jax.nn.one_hot(acts, A, dtype=jnp.float32) @ params["w_act"] \
        + t_emb
    # interleave (rtg_t, s_t, a_t): [B, 3K, D]
    x = jnp.stack([e_rtg, e_obs, e_act], axis=2).reshape(B, 3 * K, D)

    tok_mask = jnp.repeat(pad_mask, 3, axis=1)          # [B, 3K]
    causal = jnp.tril(jnp.ones((3 * K, 3 * K), jnp.bool_))
    attn_mask = causal[None] & tok_mask[:, None, :].astype(bool)
    bias = jnp.where(attn_mask, 0.0, -1e9)[:, None]     # [B,1,3K,3K]

    def ln(x, g, b):
        mu = x.mean(-1, keepdims=True)
        var = ((x - mu) ** 2).mean(-1, keepdims=True)
        return (x - mu) / jnp.sqrt(var + 1e-5) * g + b

    hd = D // n_head
    for li in range(n_layer):
        h = ln(x, params[f"l{li}_ln1_g"], params[f"l{li}_ln1_b"])
        qkv = h @ params[f"l{li}_qkv"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, 3 * K, n_head, hd).transpose(0, 2, 1, 3)
        k = k.reshape(B, 3 * K, n_head, hd).transpose(0, 2, 1, 3)
        v = v.reshape(B, 3 * K, n_head, hd).transpose(0, 2, 1, 3)
        scores = q @ k.transpose(0, 1, 3, 2) / np.sqrt(hd) + bias
        att = jax.nn.softmax(scores, axis=-1) @ v       # [B,H,3K,hd]
        att = att.transpose(0, 2, 1, 3).reshape(B, 3 * K, D)
        x = x + att @ params[f"l{li}_proj"]
        h = ln(x, params[f"l{li}_ln2_g"], params[f"l{li}_ln2_b"])
        h = jax.nn.gelu(h @ params[f"l{li}_fc"] + params[f"l{li}_fc_b"])
        x = x + h @ params[f"l{li}_out"] + params[f"l{li}_out_b"]

    x = ln(x, params["ln_f_g"], params["ln_f_b"])
    state_tok = x.reshape(B, K, 3, D)[:, :, 1]          # the s_t token
    return state_tok @ params["w_head"] + params["b_head"]


@dataclass
class DTConfig:
    """ref: dt.py DTConfig (context K, target_return, embed/layer dims)."""
    env: str = "CartPole-v1"          # evaluation env
    env_creator: Optional[Callable] = None
    input_paths: Any = None
    episodes: Optional[List[Dict[str, np.ndarray]]] = None
    context_len: int = 20             # K
    d_model: int = 128
    n_layer: int = 3
    n_head: int = 4
    lr: float = 1e-3
    weight_decay: float = 1e-4
    train_batch_size: int = 64        # segments per minibatch
    num_updates_per_iter: int = 32
    target_return: float = 500.0      # eval conditioning
    rtg_scale: float = 500.0          # rtg normalization divisor
    evaluation_num_episodes: int = 8
    max_eval_steps: int = 600
    seed: int = 0

    def build(self) -> "DT":
        return DT(self)


class DT:
    """Offline trainer (MARWIL driver shape): train() consumes the fixed
    dataset; evaluate() runs return-conditioned autoregressive rollouts."""

    def __init__(self, config: DTConfig):
        import functools

        import jax
        import jax.numpy as jnp
        import optax

        self.config = c = config
        episodes = (c.episodes if c.episodes is not None
                    else read_experiences(c.input_paths))
        if not episodes:
            raise ValueError("DT needs offline data: pass episodes or "
                             "input_paths with at least one episode")
        # per-episode arrays + undiscounted return-to-go suffix sums
        self._eps = []
        for ep in episodes:
            r = np.asarray(ep["rewards"], np.float32)
            rtg = np.cumsum(r[::-1])[::-1].copy()
            self._eps.append({
                "obs": np.asarray(ep["obs"], np.float32),
                "actions": np.asarray(ep["actions"], np.int64),
                "rtg": rtg})
        # env floor: the behavior policy may never have taken some
        # actions (the cql.py num_actions guard)
        probe = (c.env_creator(num_envs=1, seed=0) if c.env_creator
                 else make_env(c.env, num_envs=1, seed=0))
        self._num_actions = max(
            int(max(int(e["actions"].max()) for e in self._eps)) + 1,
            probe.num_actions)
        self._obs_dim = self._eps[0]["obs"].shape[1]
        self.params = init_dt_params(
            jax.random.PRNGKey(c.seed), self._obs_dim, self._num_actions,
            c.d_model, c.n_layer, c.n_head)
        self.optimizer = optax.adamw(c.lr, weight_decay=c.weight_decay)
        self.opt_state = self.optimizer.init(self.params)
        self._rng = np.random.default_rng(c.seed)
        self._iteration = 0

        fwd = functools.partial(dt_forward, n_layer=c.n_layer,
                                n_head=c.n_head)
        self._fwd = jax.jit(fwd)

        def loss_fn(params, batch):
            logits = fwd(params, batch["rtg"], batch["obs"],
                         batch["acts"], batch["t"], batch["mask"])
            logp = jax.nn.log_softmax(logits)
            ll = jnp.take_along_axis(logp, batch["acts"][..., None],
                                     axis=2)[..., 0]
            m = batch["mask"]
            return -(ll * m).sum() / jnp.maximum(m.sum(), 1.0)

        def update_many(params, opt_state, batches):
            def body(carry, mb):
                params, opt_state = carry
                loss, grads = jax.value_and_grad(loss_fn)(params, mb)
                updates, opt_state = self.optimizer.update(
                    grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), loss

            (params, opt_state), losses = jax.lax.scan(
                body, (params, opt_state), batches)
            return params, opt_state, losses.mean()

        self._update_many = jax.jit(update_many, donate_argnums=(0, 1))

    def _sample_segments(self, n: int) -> Dict[str, np.ndarray]:
        """Random length-K segments, left-padded (the reference
        right-aligns context the same way)."""
        c = self.config
        K = c.context_len
        out = {"rtg": np.zeros((n, K, 1), np.float32),
               "obs": np.zeros((n, K, self._obs_dim), np.float32),
               "acts": np.zeros((n, K), np.int64),
               "t": np.zeros((n, K), np.int64),
               "mask": np.zeros((n, K), np.float32)}
        ep_idx = self._rng.integers(0, len(self._eps), size=n)
        for i, ei in enumerate(ep_idx):
            ep = self._eps[ei]
            T = len(ep["actions"])
            si = int(self._rng.integers(0, T))
            seg = slice(si, min(si + K, T))
            L = seg.stop - seg.start
            out["rtg"][i, K - L:, 0] = ep["rtg"][seg] / c.rtg_scale
            out["obs"][i, K - L:] = ep["obs"][seg]
            out["acts"][i, K - L:] = ep["actions"][seg]
            out["t"][i, K - L:] = np.arange(seg.start, seg.stop)
            out["mask"][i, K - L:] = 1.0
        return out

    def train(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        c = self.config
        t0 = time.monotonic()
        K_upd, B = c.num_updates_per_iter, c.train_batch_size
        mbs = [self._sample_segments(B) for _ in range(K_upd)]
        stacked = {k: jnp.asarray(np.stack([m[k] for m in mbs]))
                   for k in mbs[0]}
        self.params, self.opt_state, loss = self._update_many(
            self.params, self.opt_state, stacked)
        self._iteration += 1
        return {"training_iteration": self._iteration,
                "loss": float(loss),
                "num_episodes": len(self._eps),
                "train_time_s": time.monotonic() - t0}

    def evaluate(self, target_return: Optional[float] = None,
                 num_episodes: Optional[int] = None,
                 seed: int = 123) -> Dict[str, float]:
        """Return-conditioned autoregressive rollout: rtg starts at the
        target and decrements by observed rewards (ref: dt.py inference
        loop)."""
        import jax.numpy as jnp

        c = self.config
        tgt = c.target_return if target_return is None else target_return
        n_eps = num_episodes or c.evaluation_num_episodes
        n = 4
        env = (c.env_creator(num_envs=n, seed=seed) if c.env_creator
               else make_env(c.env, num_envs=n, seed=seed))
        K = c.context_len
        obs = env.reset(seed=seed)
        hist_obs = [np.zeros((0, self._obs_dim), np.float32)
                    for _ in range(n)]
        hist_act = [np.zeros((0,), np.int64) for _ in range(n)]
        hist_rtg = [np.zeros((0,), np.float32) for _ in range(n)]
        rtg_now = np.full(n, tgt, np.float64)
        t_now = np.zeros(n, np.int64)
        done_rets: List[float] = []
        ep_ret = np.zeros(n)
        # per-env episode quota: without it, fast-failing envs finish
        # many short episodes before a long-running env finishes one,
        # biasing the mean toward low returns
        quota = -(-n_eps // n)
        ep_count = np.zeros(n, np.int64)
        for _ in range(c.max_eval_steps * 4):
            batch = {"rtg": np.zeros((n, K, 1), np.float32),
                     "obs": np.zeros((n, K, self._obs_dim), np.float32),
                     "acts": np.zeros((n, K), np.int64),
                     "t": np.zeros((n, K), np.int64),
                     "mask": np.zeros((n, K), np.float32)}
            for i in range(n):
                # current step enters as (rtg, s, dummy-a); history fills
                # the earlier positions
                ho = np.concatenate([hist_obs[i], obs[i:i + 1]])[-K:]
                hr = np.concatenate(
                    [hist_rtg[i], [rtg_now[i]]])[-K:].astype(np.float32)
                ha = np.concatenate([hist_act[i], [0]])[-K:]
                L = len(ho)
                batch["obs"][i, K - L:] = ho
                batch["rtg"][i, K - L:, 0] = hr / c.rtg_scale
                batch["acts"][i, K - L:] = ha
                batch["t"][i, K - L:] = np.arange(
                    max(0, t_now[i] - L + 1), t_now[i] + 1)
                batch["mask"][i, K - L:] = 1.0
            logits = np.asarray(self._fwd(
                self.params, jnp.asarray(batch["rtg"]),
                jnp.asarray(batch["obs"]), jnp.asarray(batch["acts"]),
                jnp.asarray(batch["t"]), jnp.asarray(batch["mask"])))
            actions = logits[:, -1].argmax(axis=1)
            new_obs, reward, done, _ = env.step(actions)
            for i in range(n):
                hist_obs[i] = np.concatenate(
                    [hist_obs[i], obs[i:i + 1]])[-K:]
                hist_act[i] = np.concatenate(
                    [hist_act[i], [actions[i]]])[-K:]
                hist_rtg[i] = np.concatenate(
                    [hist_rtg[i], [rtg_now[i]]])[-K:].astype(np.float32)
                ep_ret[i] += reward[i]
                rtg_now[i] = max(rtg_now[i] - reward[i], 1.0)
                t_now[i] += 1
                if done[i]:
                    if ep_count[i] < quota:
                        done_rets.append(float(ep_ret[i]))
                        ep_count[i] += 1
                    ep_ret[i] = 0.0
                    rtg_now[i] = tgt
                    t_now[i] = 0
                    hist_obs[i] = np.zeros((0, self._obs_dim), np.float32)
                    hist_act[i] = np.zeros((0,), np.int64)
                    hist_rtg[i] = np.zeros((0,), np.float32)
            obs = new_obs
            if (ep_count >= quota).all():
                break
        return {"episode_reward_mean": (float(np.mean(done_rets))
                                        if done_rets else 0.0),
                "episodes": len(done_rets),
                "target_return": float(tgt)}

    # -- Tune-trainable surface ------------------------------------------

    def save(self) -> Dict:
        import jax

        return {"params": jax.device_get(self.params),
                "opt_state": jax.device_get(self.opt_state),
                "iteration": self._iteration}

    def restore(self, ckpt: Dict) -> None:
        import jax
        import jax.numpy as jnp

        self.params = jax.tree.map(jnp.asarray, ckpt["params"])
        if "opt_state" in ckpt:
            self.opt_state = jax.tree.map(jnp.asarray, ckpt["opt_state"])
        self._iteration = int(ckpt.get("iteration", 0))

    def stop(self) -> None:
        pass  # offline: no workers
