"""TD3 — twin delayed deep deterministic policy gradient.

ref: rllib/algorithms/td3/td3.py (TD3Config: twin_q,
policy_delay=2, smooth_target_policy with target_noise 0.2 clipped
at 0.5, exploration gaussian sigma 0.1) layered over
ddpg/ddpg_torch_policy.py losses — Fujimoto et al. 2018.

House TPU shape (the SAC/DQN recipe): numpy behavior policy in rollout
actors (deterministic tanh head + exploration noise), host replay
buffer, and the whole per-iteration update block — K minibatches of
twin-critic TD, every-other-step actor + polyak — as ONE jitted
lax.scan with donated buffers: one dispatch, one stats readback per
train() call (docs/PERF_NOTES.md learner rule).
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

import cloudpickle
import numpy as np

import ray_tpu

from .replay_buffer import ReplayBuffer
from .rollout_worker import EnvWorkerBase, worker_opts


def _mlp_init(rng, sizes: Tuple[int, ...], out: int):
    import jax
    import jax.numpy as jnp

    p = {}
    last = sizes[0]
    ks = jax.random.split(rng, len(sizes))
    for i, h in enumerate(sizes[1:]):
        p[f"w{i}"] = jax.random.normal(
            ks[i], (last, h), jnp.float32) * np.sqrt(2.0 / last)
        p[f"b{i}"] = jnp.zeros((h,), jnp.float32)
        last = h
    p["w_out"] = jax.random.normal(ks[-1], (last, out), jnp.float32) * 0.01
    p["b_out"] = jnp.zeros((out,), jnp.float32)
    return p


def _mlp_np(p: Dict[str, np.ndarray], x: np.ndarray) -> np.ndarray:
    i = 0
    while f"w{i}" in p:
        x = np.maximum(x @ p[f"w{i}"] + p[f"b{i}"], 0.0)
        i += 1
    return x @ p["w_out"] + p["b_out"]


def init_td3_params(rng, obs_dim: int, action_dim: int,
                    hidden: Tuple[int, ...]) -> Dict:
    import jax

    ka, k1, k2 = jax.random.split(rng, 3)
    return {"actor": _mlp_init(ka, (obs_dim, *hidden), action_dim),
            "q1": _mlp_init(k1, (obs_dim + action_dim, *hidden), 1),
            "q2": _mlp_init(k2, (obs_dim + action_dim, *hidden), 1)}


class TD3RolloutWorker(EnvWorkerBase):
    """Deterministic tanh policy + Gaussian exploration noise (the DDPG
    behavior policy; SAC's worker samples its stochastic head instead)."""

    def __init__(self, env_name: str, num_envs: int, rollout_len: int,
                 action_scale: float, explore_sigma: float,
                 seed: int = 0, env_creator=None):
        super().__init__(env_name, num_envs, rollout_len, seed, env_creator)
        self.action_scale = action_scale
        self.sigma = explore_sigma

    def sample(self, actor_params: Dict, random_actions: bool = False
               ) -> Dict[str, np.ndarray]:
        p = {k: np.asarray(v, np.float32) for k, v in actor_params.items()}
        T, n = self.rollout_len, self.env.num_envs
        ad = self.env.action_dim
        obs_buf = np.empty((T, n, self.env.obs_dim), np.float32)
        next_buf = np.empty((T, n, self.env.obs_dim), np.float32)
        act_buf = np.empty((T, n, ad), np.float32)
        rew_buf = np.empty((T, n), np.float32)
        done_buf = np.empty((T, n), np.bool_)
        obs = self._obs
        for t in range(T):
            if random_actions:
                a = self._rng.uniform(-1, 1, (n, ad))
            else:
                a = np.tanh(_mlp_np(p, obs)) \
                    + self._rng.normal(0, self.sigma, (n, ad))
                a = np.clip(a, -1.0, 1.0)
            obs_buf[t], act_buf[t] = obs, a
            obs, reward, done, info = self.env.step(a * self.action_scale)
            rew_buf[t], done_buf[t] = reward, done
            next_buf[t] = obs
            if done.any():
                idx = np.nonzero(done)[0]
                if "final_obs" in info:
                    next_buf[t, idx] = info["final_obs"][idx]
                if "truncated" in info:
                    done_buf[t] &= ~info["truncated"]
            self._track_returns(reward, done)
        self._obs = obs
        flat = lambda a: a.reshape(T * n, *a.shape[2:])  # noqa: E731
        return {"obs": flat(obs_buf), "actions": flat(act_buf),
                "rewards": flat(rew_buf), "dones": flat(done_buf),
                "next_obs": flat(next_buf)}


@dataclass
class TD3Config:
    """ref: td3.py TD3Config defaults."""
    env: str = "Pendulum-v1"
    env_creator: Optional[Callable] = None
    num_rollout_workers: int = 1
    num_envs_per_worker: int = 8
    rollout_fragment_length: int = 32
    gamma: float = 0.99
    tau: float = 5e-3
    actor_lr: float = 1e-3
    critic_lr: float = 1e-3
    buffer_size: int = 100_000
    train_batch_size: int = 256
    num_updates_per_iter: int = 32
    learning_starts: int = 1_000
    policy_delay: int = 2
    target_noise: float = 0.2
    target_noise_clip: float = 0.5
    explore_sigma: float = 0.1
    hidden: tuple = (256, 256)
    seed: int = 0
    checkpoint_replay_buffer: bool = True
    worker_resources: Dict[str, float] = field(default_factory=dict)
    extra: Dict[str, Any] = field(default_factory=dict)

    def build(self) -> "TD3":
        return TD3(self)


class TD3Learner:
    def __init__(self, obs_dim: int, action_dim: int, c: TD3Config):
        import functools

        import jax
        import jax.numpy as jnp
        import optax

        self.params = init_td3_params(jax.random.PRNGKey(c.seed), obs_dim,
                                      action_dim, tuple(c.hidden))
        self.target = jax.tree.map(lambda a: a.copy(), self.params)
        self.opt_actor = optax.adam(c.actor_lr)
        self.opt_critic = optax.adam(c.critic_lr)
        self.state_actor = self.opt_actor.init(self.params["actor"])
        self.state_critic = self.opt_critic.init(
            {"q1": self.params["q1"], "q2": self.params["q2"]})
        self._key = jax.random.PRNGKey(c.seed + 7)
        self.num_updates = 0

        from .sac import _mlp_forward as mlp  # one canonical jnp MLP

        def q(p, obs, act):
            return mlp(p, jnp.concatenate([obs, act], axis=-1))[:, 0]

        def critic_loss(qs, target, batch, key):
            noise = jnp.clip(
                jax.random.normal(key, batch["actions"].shape)
                * c.target_noise, -c.target_noise_clip,
                c.target_noise_clip)
            a_next = jnp.clip(
                jnp.tanh(mlp(target["actor"], batch["next_obs"])) + noise,
                -1.0, 1.0)  # smoothed target policy
            tq = jnp.minimum(q(target["q1"], batch["next_obs"], a_next),
                             q(target["q2"], batch["next_obs"], a_next))
            y = batch["rewards"] + c.gamma \
                * (1.0 - batch["dones"].astype(jnp.float32)) * tq
            y = jax.lax.stop_gradient(y)
            l1 = jnp.mean(jnp.square(
                q(qs["q1"], batch["obs"], batch["actions"]) - y))
            l2 = jnp.mean(jnp.square(
                q(qs["q2"], batch["obs"], batch["actions"]) - y))
            return l1 + l2

        def actor_loss(actor_p, q1_p, batch):
            a = jnp.tanh(mlp(actor_p, batch["obs"]))
            return -jnp.mean(q(q1_p, batch["obs"], a))

        def polyak(t, p):
            return jax.tree.map(
                lambda a, b: a * (1 - c.tau) + b * c.tau, t, p)

        def one_update(carry, xs):
            params, target, s_a, s_c, key = carry
            batch, step_i = xs
            key, ck = jax.random.split(key)
            qs = {"q1": params["q1"], "q2": params["q2"]}
            closs, grads = jax.value_and_grad(critic_loss)(
                qs, target, batch, ck)
            upd, s_c = self.opt_critic.update(grads, s_c, qs)
            qs = optax.apply_updates(qs, upd)
            params = {**params, **qs}

            # delayed policy update: every policy_delay-th step
            def do_actor(args):
                params, target, s_a = args
                aloss, ag = jax.value_and_grad(actor_loss)(
                    params["actor"], params["q1"], batch)
                au, s_a = self.opt_actor.update(ag, s_a, params["actor"])
                actor_p = optax.apply_updates(params["actor"], au)
                params = {**params, "actor": actor_p}
                target = polyak(target, params)
                return params, target, s_a, aloss

            def skip_actor(args):
                params, target, s_a = args
                return params, target, s_a, jnp.zeros(())

            params, target, s_a, aloss = jax.lax.cond(
                step_i % c.policy_delay == 0, do_actor, skip_actor,
                (params, target, s_a))
            return ((params, target, s_a, s_c, key),
                    {"critic_loss": closs, "actor_loss": aloss})

        @functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
        def update_many(params, target, s_a, s_c, key, batches):
            K = batches["rewards"].shape[0]
            (params, target, s_a, s_c, key), stats = jax.lax.scan(
                one_update, (params, target, s_a, s_c, key),
                (batches, jnp.arange(K)))
            return params, target, s_a, s_c, key, jax.tree.map(
                jnp.mean, stats)

        self._update_many = update_many

    def update(self, stacked: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax
        import jax.numpy as jnp

        batches = {k: jnp.asarray(v) for k, v in stacked.items()}
        (self.params, self.target, self.state_actor, self.state_critic,
         self._key, stats) = self._update_many(
            self.params, self.target, self.state_actor,
            self.state_critic, self._key, batches)
        self.num_updates += int(stacked["rewards"].shape[0])
        return {k: float(v) for k, v in jax.device_get(stats).items()}


class TD3:
    """Tune-trainable TD3 (same driver shape as SAC)."""

    def __init__(self, config: TD3Config):
        from .env import make_env

        c = self.config = config
        probe = (cloudpickle.loads(cloudpickle.dumps(c.env_creator))(
            num_envs=1, seed=0) if c.env_creator is not None
            else make_env(c.env, num_envs=1, seed=0))
        if not hasattr(probe, "action_dim"):
            raise ValueError(f"TD3 needs a continuous-action env; "
                             f"{c.env!r} has no action_dim")
        obs_dim, act_dim = probe.obs_dim, probe.action_dim
        scale = float(getattr(probe, "action_scale", 1.0))
        creator_blob = (cloudpickle.dumps(c.env_creator)
                        if c.env_creator is not None else None)
        cls = ray_tpu.remote(TD3RolloutWorker)
        opts = worker_opts(c.worker_resources)
        self.workers = [
            cls.options(**opts).remote(
                c.env, c.num_envs_per_worker, c.rollout_fragment_length,
                scale, c.explore_sigma, seed=c.seed + 31 * i,
                env_creator=creator_blob)
            for i in range(c.num_rollout_workers)
        ]
        self.learner = TD3Learner(obs_dim, act_dim, c)
        self.buffer = ReplayBuffer(c.buffer_size, seed=c.seed)
        self._iteration = 0
        self._total_steps = 0
        self._total_episodes = 0
        self._recent: list = []

    def train(self) -> Dict[str, float]:
        import jax

        c = self.config
        t0 = time.monotonic()
        warmup = self._total_steps < c.learning_starts
        actor_np = jax.device_get(self.learner.params["actor"])
        batches = ray_tpu.get(
            [w.sample.remote(actor_np, random_actions=warmup)
             for w in self.workers], timeout=300)
        steps = 0
        for b in batches:
            self.buffer.add(b)
            steps += len(b["rewards"])
        self._total_steps += steps
        stats: Dict[str, float] = {}
        if len(self.buffer) >= max(c.learning_starts, c.train_batch_size):
            K, B = c.num_updates_per_iter, c.train_batch_size
            mb = self.buffer.sample(K * B)
            stacked = {k: v.reshape(K, B, *v.shape[1:])
                       for k, v in mb.items()}
            stats = self.learner.update(stacked)
        for rets in ray_tpu.get(
                [w.episode_returns.remote() for w in self.workers],
                timeout=60):
            self._recent.extend(rets)
            self._total_episodes += len(rets)
        self._recent = self._recent[-100:]
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "timesteps_total": self._total_steps,
            "timesteps_this_iter": steps,
            "episode_reward_mean": (float(np.mean(self._recent))
                                    if self._recent else float("nan")),
            "episodes_total": self._total_episodes,
            "num_updates": self.learner.num_updates,
            "time_this_iter_s": time.monotonic() - t0,
            **stats,
        }

    # -- Tune-trainable surface ------------------------------------------

    def save(self) -> Dict:
        import jax

        L = self.learner
        ckpt = {"params": jax.device_get(L.params),
                "target": jax.device_get(L.target),
                "opt_states": jax.device_get((L.state_actor,
                                              L.state_critic)),
                "rng_key": jax.device_get(L._key),
                "iteration": self._iteration,
                "total_steps": self._total_steps}
        if self.config.checkpoint_replay_buffer:
            ckpt["buffer"] = self.buffer.state()
        return ckpt

    def restore(self, ckpt: Dict) -> None:
        import jax
        import jax.numpy as jnp

        as_jnp = lambda t: jax.tree.map(jnp.asarray, t)  # noqa: E731
        L = self.learner
        L.params = as_jnp(ckpt["params"])
        L.target = as_jnp(ckpt["target"])
        if "opt_states" in ckpt:
            L.state_actor, L.state_critic = as_jnp(ckpt["opt_states"])
        if "rng_key" in ckpt:
            L._key = jnp.asarray(ckpt["rng_key"])
        self._iteration = int(ckpt.get("iteration", 0))
        self._total_steps = int(ckpt.get("total_steps", 0))
        if "buffer" in ckpt:
            self.buffer.restore(ckpt["buffer"])

    def stop(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass


# DDPG is TD3 with its innovations switched off (ref: ddpg.py — the
# reference implements TD3 as a DDPG subclass; the relation inverts
# cleanly here)
def DDPGConfig(**kw) -> TD3Config:  # noqa: N802 — ref naming
    kw.setdefault("policy_delay", 1)
    kw.setdefault("target_noise", 0.0)
    kw.setdefault("target_noise_clip", 0.0)
    return TD3Config(**kw)
