"""SlateQ — slate recommendation Q-learning (Ie et al. 2019).

ref: rllib/algorithms/slateq/slateq.py (+ slateq_torch_policy.py:
per-item Q decomposition under a conditional user choice model,
myopic/SARSA/QL learning targets; RecSim interest-evolution envs).
The decomposition: with a multinomial-logit user choice over the slate
(plus a no-click option),

    Q(s, slate) = sum_{i in slate} P(click i | s, slate) * Q(s, i)

so only per-ITEM Q-values are learned and the combinatorial slate space
never materializes. Greedy slate selection uses the paper's top-k
approximation: rank documents by v(s,d) * Q(s,d) (choice score times
item value).

Ships InterestEvolutionVecEnv — a vectorized numpy reduction of
RecSim's interest-evolution environment: users hold an interest vector
over topics, click via multinomial logit on doc-topic affinity, clicked
docs nudge interests and yield engagement reward; sessions last a fixed
budget. House TPU shape: numpy choice/rollout in actor workers, one
fused jitted TD block per train() call over the replay (the DQN
recipe at slate granularity)."""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import cloudpickle
import numpy as np

import ray_tpu

from .replay_buffer import ReplayBuffer
from .rollout_worker import worker_opts


class InterestEvolutionVecEnv:
    """n parallel user sessions. Per step the recommender picks a slate
    of `slate_size` docs from a fixed `num_docs` corpus; the user
    clicks one (or none) via multinomial logit over interest·topic
    affinities; clicks give engagement reward and drift the interest.

    obs = user interest vector [num_topics]; the corpus doc features
    are static and exposed via `doc_features` ([num_docs, num_topics]).
    """

    SESSION_LEN = 20
    CHOICE_SHARPNESS = 5.0   # logit scale of the user choice model

    def __init__(self, num_envs: int = 8, seed: int = 0,
                 num_docs: int = 20, num_topics: int = 5,
                 slate_size: int = 3, no_click_mass: float = 1.0):
        self.num_envs = num_envs
        self.num_docs = num_docs
        self.num_topics = num_topics
        self.slate_size = slate_size
        self.no_click_mass = no_click_mass
        self.obs_dim = num_topics
        self.num_actions = num_docs     # per-ITEM action space
        self._rng = np.random.default_rng(seed)
        # static corpus: unit-norm topic mixtures + a quality scalar
        feats = self._rng.dirichlet(np.ones(num_topics), num_docs)
        self.doc_features = feats.astype(np.float32)
        self.doc_quality = self._rng.uniform(
            0.2, 1.0, num_docs).astype(np.float32)
        self._interest = np.zeros((num_envs, num_topics))
        self._t = np.zeros(num_envs, np.int64)

    def _sample_users(self, n: int) -> np.ndarray:
        u = self._rng.dirichlet(np.ones(self.num_topics), n)
        return u

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            # offset stream: reusing default_rng(seed) verbatim would
            # replay the ctor's corpus draws, making every user's
            # interest EQUAL a doc_features row bit-for-bit
            self._rng = np.random.default_rng(seed + 0x9E3779B9)
        self._interest = self._sample_users(self.num_envs)
        self._t[:] = 0
        return self._interest.astype(np.float32)

    def choice_probs(self, slates: np.ndarray) -> np.ndarray:
        """Multinomial logit over the slate + no-click:
        [n, slate_size + 1] (last column = no click)."""
        aff = np.einsum("nt,nkt->nk", self._interest,
                        self.doc_features[slates])     # [n, k]
        scores = np.exp(aff * self.CHOICE_SHARPNESS)
        total = scores.sum(axis=1) + self.no_click_mass
        p = np.concatenate(
            [scores / total[:, None],
             (self.no_click_mass / total)[:, None]], axis=1)
        return p

    def step(self, slates: np.ndarray):
        """slates: [n, slate_size] doc indices -> (obs, reward, done,
        info with per-step click column)."""
        n, k = slates.shape
        p = self.choice_probs(slates)
        # sample the click (k = no-click)
        cdf = p.cumsum(axis=1)
        u = self._rng.random((n, 1))
        choice = (u > cdf).sum(axis=1)                  # in [0, k]
        clicked = choice < k
        doc = np.where(clicked, slates[np.arange(n),
                                       np.minimum(choice, k - 1)], -1)
        reward = np.where(
            clicked, self.doc_quality[np.maximum(doc, 0)],
            0.0).astype(np.float32)
        # interest drift toward the clicked doc's topics
        drift = np.where(clicked[:, None],
                         self.doc_features[np.maximum(doc, 0)], 0.0)
        self._interest = self._interest + 0.1 * drift
        self._interest /= self._interest.sum(axis=1, keepdims=True)
        self._t += 1
        done = self._t >= self.SESSION_LEN
        info: Dict[str, Any] = {"choice": choice, "clicked_doc": doc}
        if done.any():
            info["truncated"] = done.copy()
            info["final_obs"] = self._interest.astype(np.float32)
            idx = np.nonzero(done)[0]
            self._interest[idx] = self._sample_users(len(idx))
            self._t[idx] = 0
        return (self._interest.astype(np.float32), reward,
                done.astype(np.bool_), info)


class SlateQRolloutWorker:
    """Collects slate transitions with epsilon-greedy top-k slates under
    the current per-item Q (ref: slateq exploration via per-item
    scores)."""

    def __init__(self, num_envs: int, rollout_len: int, seed: int = 0,
                 env_creator=None, **env_kw):
        self._rng = np.random.default_rng(seed + 1)
        if env_creator is not None:
            self.env = cloudpickle.loads(env_creator)(
                num_envs=num_envs, seed=seed)
        else:
            self.env = InterestEvolutionVecEnv(num_envs=num_envs,
                                               seed=seed, **env_kw)
        self.rollout_len = rollout_len
        self._obs = self.env.reset(seed=seed)
        self._ep_return = np.zeros(self.env.num_envs)
        self._finished: List[float] = []

    def env_info(self) -> dict:
        e = self.env
        return {"obs_dim": e.obs_dim, "num_docs": e.num_docs,
                "slate_size": e.slate_size,
                "doc_features": e.doc_features,
                "no_click_mass": e.no_click_mass,
                "choice_sharpness": getattr(e, "CHOICE_SHARPNESS", 5.0)}

    def episode_returns(self, clear: bool = True) -> List[float]:
        out = list(self._finished)
        if clear:
            self._finished.clear()
        return out

    def _item_q_np(self, p: Dict, obs: np.ndarray) -> np.ndarray:
        """Q(s, d) for all docs: MLP on [user_interest, doc_feature]
        pairs, vectorized over the corpus."""
        n = len(obs)
        D = self.env.num_docs
        x = np.concatenate(
            [np.repeat(obs, D, axis=0),
             np.tile(self.env.doc_features, (n, 1))], axis=1)
        h = x
        i = 0
        while f"w{i}" in p:
            h = np.maximum(h @ p[f"w{i}"] + p[f"b{i}"], 0.0)
            i += 1
        return (h @ p["w_out"] + p["b_out"]).reshape(n, D)

    def sample(self, params: Dict, epsilon: float) -> Dict[str, np.ndarray]:
        p = {k: np.asarray(v, np.float32) for k, v in params.items()}
        env = self.env
        T, n, k = self.rollout_len, env.num_envs, env.slate_size
        obs_b = np.empty((T, n, env.obs_dim), np.float32)
        slate_b = np.empty((T, n, k), np.int64)
        choice_b = np.empty((T, n), np.int64)
        rew_b = np.empty((T, n), np.float32)
        done_b = np.empty((T, n), np.bool_)
        next_b = np.empty((T, n, env.obs_dim), np.float32)
        obs = self._obs
        for t in range(T):
            q = self._item_q_np(p, obs)                 # [n, D]
            # choice-score-weighted ranking (the paper's top-k rule):
            # v(s,d) ~ exp(5 * interest·topics)
            aff = obs @ env.doc_features.T
            sharp = getattr(env, "CHOICE_SHARPNESS", 5.0)
            score = np.exp(aff * sharp) * q
            slate = np.argsort(-score, axis=1)[:, :k]
            explore = self._rng.random(n) < epsilon
            for i in np.nonzero(explore)[0]:
                slate[i] = self._rng.choice(env.num_docs, k,
                                            replace=False)
            obs_b[t], slate_b[t] = obs, slate
            obs, reward, done, info = env.step(slate)
            choice_b[t], rew_b[t], done_b[t] = (info["choice"], reward,
                                                done)
            next_b[t] = obs
            if done.any():
                idx = np.nonzero(done)[0]
                if "final_obs" in info:
                    next_b[t, idx] = info["final_obs"][idx]
                if "truncated" in info:
                    done_b[t] &= ~info["truncated"]
            self._ep_return += reward
            for i in np.nonzero(done)[0]:
                self._finished.append(float(self._ep_return[i]))
                self._ep_return[i] = 0.0
        self._obs = obs
        flat = lambda a: a.reshape(T * n, *a.shape[2:])  # noqa: E731
        return {"obs": flat(obs_b), "slates": flat(slate_b),
                "choice": flat(choice_b), "rewards": flat(rew_b),
                "dones": flat(done_b), "next_obs": flat(next_b)}


@dataclass
class SlateQConfig:
    """ref: slateq.py SlateQConfig (slate_size, learning target QL,
    no-click handling)."""
    num_rollout_workers: int = 2
    num_envs_per_worker: int = 8
    rollout_fragment_length: int = 40
    num_docs: int = 20
    num_topics: int = 5
    slate_size: int = 3
    gamma: float = 0.95
    lr: float = 1e-3
    buffer_size: int = 50_000
    train_batch_size: int = 128
    num_updates_per_iter: int = 16
    learning_starts: int = 1_000
    target_update_freq: int = 100
    epsilon_initial: float = 1.0
    epsilon_final: float = 0.05
    epsilon_decay_steps: int = 8_000
    hidden: tuple = (64, 64)
    env_creator: Optional[Callable] = None
    seed: int = 0
    checkpoint_replay_buffer: bool = True
    worker_resources: Dict[str, float] = field(default_factory=dict)

    def build(self) -> "SlateQ":
        return SlateQ(self)


class SlateQLearner:
    """Fused per-iteration TD on the decomposed slate Q (ref:
    slateq_torch_policy.py build_slateq_losses, 'QL' target)."""

    def __init__(self, obs_dim: int, doc_features: np.ndarray,
                 slate_size: int, no_click_mass: float,
                 choice_sharpness: float, c: SlateQConfig):
        import functools

        import jax
        import jax.numpy as jnp
        import optax

        from .td3 import _mlp_init

        D, Tn = doc_features.shape
        self.params = _mlp_init(jax.random.PRNGKey(c.seed),
                                (obs_dim + Tn, *c.hidden), 1)
        self.target = jax.tree.map(lambda a: a.copy(), self.params)
        self.optimizer = optax.adam(c.lr)
        self.opt_state = self.optimizer.init(self.params)
        self.num_updates = 0
        feats = jnp.asarray(doc_features)
        k = slate_size
        sharp = choice_sharpness

        def mlp(p, x):
            i = 0
            while f"w{i}" in p:
                x = jax.nn.relu(x @ p[f"w{i}"] + p[f"b{i}"])
                i += 1
            return x @ p["w_out"] + p["b_out"]

        def item_q(p, obs):
            """[B, obs] -> [B, D]: Q for every corpus doc."""
            B = obs.shape[0]
            x = jnp.concatenate(
                [jnp.repeat(obs, D, axis=0),
                 jnp.tile(feats, (B, 1))], axis=1)
            return mlp(p, x).reshape(B, D)

        def choice_p(obs, slates):
            aff = jnp.einsum("bt,bkt->bk", obs, feats[slates])
            sc = jnp.exp(aff * sharp)
            tot = sc.sum(axis=1) + no_click_mass
            return sc / tot[:, None]                   # [B, k] click probs

        def slate_value(p, obs):
            """max_slate Q(s, slate) via the top-k approximation."""
            q = item_q(p, obs)                          # [B, D]
            aff = obs @ feats.T
            score = jnp.exp(aff * sharp) * q
            top = jax.lax.top_k(score, k)[1]            # [B, k]
            pc = choice_p(obs, top)
            q_top = jnp.take_along_axis(q, top, axis=1)
            return (pc * q_top).sum(axis=1)

        def loss_fn(p, target, mb):
            # TD on the CLICKED item's Q (no-click steps carry no item
            # gradient — the decomposition's per-item credit)
            clicked = mb["choice"] < k
            doc = jnp.take_along_axis(
                mb["slates"], jnp.minimum(mb["choice"],
                                          k - 1)[:, None], axis=1)[:, 0]
            q_all = item_q(p, mb["obs"])
            q_sd = jnp.take_along_axis(q_all, doc[:, None], axis=1)[:, 0]
            v_next = slate_value(target, mb["next_obs"])
            y = mb["rewards"] + c.gamma \
                * (1.0 - mb["dones"].astype(jnp.float32)) \
                * jax.lax.stop_gradient(v_next)
            w = clicked.astype(jnp.float32)
            return jnp.sum(w * (q_sd - y) ** 2) / jnp.maximum(
                w.sum(), 1.0)

        def one_update(carry, mb):
            p, target, opt_state, step_i = carry
            loss, g = jax.value_and_grad(loss_fn)(p, target, mb)
            up, opt_state = self.optimizer.update(g, opt_state)
            p = optax.apply_updates(p, up)
            step_i = step_i + 1
            target = jax.lax.cond(
                step_i % c.target_update_freq == 0,
                lambda _: jax.tree.map(lambda x: x.copy(), p),
                lambda t: t, target)
            return (p, target, opt_state, step_i), loss

        @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
        def update_many(p, target, opt_state, step_i, mbs):
            (p, target, opt_state, step_i), losses = jax.lax.scan(
                one_update, (p, target, opt_state, step_i), mbs)
            return p, target, opt_state, step_i, losses.mean()

        self._update_many = update_many

    def update(self, stacked: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax.numpy as jnp

        mbs = {key: jnp.asarray(v) for key, v in stacked.items()}
        (self.params, self.target, self.opt_state, step_i,
         loss) = self._update_many(self.params, self.target,
                                   self.opt_state,
                                   jnp.asarray(self.num_updates), mbs)
        self.num_updates = int(step_i)
        return {"loss": float(loss)}

    def get_params(self) -> Dict:
        import jax

        return jax.device_get(self.params)


class SlateQ:
    """Tune-trainable SlateQ driver (DQN shape, slate transitions)."""

    def __init__(self, config: SlateQConfig):
        self.config = c = config
        creator_blob = (cloudpickle.dumps(c.env_creator)
                        if c.env_creator else None)
        cls = ray_tpu.remote(SlateQRolloutWorker)
        opts = worker_opts(c.worker_resources)
        self.workers = [
            cls.options(**opts).remote(
                c.num_envs_per_worker, c.rollout_fragment_length,
                seed=c.seed + 1000 * i, env_creator=creator_blob,
                num_docs=c.num_docs, num_topics=c.num_topics,
                slate_size=c.slate_size)
            for i in range(c.num_rollout_workers)]
        info = ray_tpu.get(self.workers[0].env_info.remote(), timeout=180)
        self.learner = SlateQLearner(
            info["obs_dim"], np.asarray(info["doc_features"]),
            info["slate_size"], info["no_click_mass"],
            info["choice_sharpness"], c)
        self.buffer = ReplayBuffer(c.buffer_size, seed=c.seed)
        self._iteration = 0
        self._total_steps = 0
        self._total_episodes = 0
        self._recent: List[float] = []

    def _epsilon(self) -> float:
        c = self.config
        frac = min(1.0, self._total_steps / max(1, c.epsilon_decay_steps))
        return c.epsilon_initial + frac * (c.epsilon_final
                                           - c.epsilon_initial)

    def train(self) -> Dict[str, Any]:
        c = self.config
        t0 = time.monotonic()
        eps = self._epsilon()
        params_ref = ray_tpu.put(self.learner.get_params())
        batches = ray_tpu.get(
            [w.sample.remote(params_ref, eps) for w in self.workers],
            timeout=300)
        steps = 0
        for b in batches:
            self.buffer.add(b)
            steps += len(b["rewards"])
        self._total_steps += steps
        stats: Dict[str, float] = {}
        if len(self.buffer) >= c.learning_starts:
            K, B = c.num_updates_per_iter, c.train_batch_size
            mb = self.buffer.sample(K * B)
            stacked = {key: v.reshape(K, B, *v.shape[1:])
                       for key, v in mb.items()}
            stats = self.learner.update(stacked)
        for rets in ray_tpu.get(
                [w.episode_returns.remote() for w in self.workers],
                timeout=60):
            self._recent.extend(rets)
            self._total_episodes += len(rets)
        self._recent = self._recent[-100:]
        self._iteration += 1
        return {"training_iteration": self._iteration,
                "timesteps_total": self._total_steps,
                "timesteps_this_iter": steps,
                "episode_reward_mean": (float(np.mean(self._recent))
                                        if self._recent
                                        else float("nan")),
                "episodes_total": self._total_episodes,
                "epsilon": eps,
                "num_updates": self.learner.num_updates,
                "time_this_iter_s": time.monotonic() - t0,
                **stats}

    # -- Tune-trainable surface ------------------------------------------

    def save(self) -> Dict:
        import jax

        L = self.learner
        ckpt = {"params": jax.device_get(L.params),
                "target": jax.device_get(L.target),
                "opt_state": jax.device_get(L.opt_state),
                "iteration": self._iteration,
                "total_steps": self._total_steps,
                "num_updates": L.num_updates}
        if self.config.checkpoint_replay_buffer:
            ckpt["buffer"] = self.buffer.state()
        return ckpt

    def restore(self, ckpt: Dict) -> None:
        import jax
        import jax.numpy as jnp

        as_jnp = lambda t: jax.tree.map(jnp.asarray, t)  # noqa: E731
        L = self.learner
        L.params = as_jnp(ckpt["params"])
        L.target = as_jnp(ckpt["target"])
        if "opt_state" in ckpt:
            L.opt_state = as_jnp(ckpt["opt_state"])
        L.num_updates = int(ckpt.get("num_updates", 0))
        self._iteration = int(ckpt.get("iteration", 0))
        self._total_steps = int(ckpt.get("total_steps", 0))
        if "buffer" in ckpt:
            self.buffer.restore(ckpt["buffer"])

    def stop(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
