"""R2D2 — recurrent experience replay in distributed RL
(Kapturowski et al. 2019).

ref: rllib/algorithms/r2d2/r2d2.py (R2D2Config: replay sequences with
burn-in, zero-or-stored init states, h-function value rescaling) +
r2d2_torch_policy.py (double-Q over the LSTM unroll, sequence-level
priorities eta*max + (1-eta)*mean of |TD|).

House TPU shape: rollout actors run a small numpy LSTM per step (no jax
in workers — np_policy.py rationale) and emit fixed-length SEQUENCES
with the recurrent state captured at each window start; the driver keeps
a prioritized replay of sequences; the learner unrolls burn-in (gradient
stopped) + training segment as lax.scan inside ONE jitted dispatch per
train() call (docs/PERF_NOTES.md learner rule). Episode boundaries
inside a window reset the hidden state identically in worker and
learner, so stored and recomputed unrolls agree.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle
import numpy as np

import ray_tpu

from . import sample_batch as sb
from .replay_buffer import (PrioritizedReplayBuffer, ReplayBuffer,
                            fused_replay_update)
from .rollout_worker import EnvWorkerBase, worker_opts

H0, C0 = "h0", "c0"


def init_r2d2_params(rng, obs_dim: int, num_actions: int,
                     encoder_hidden: int, cell_size: int) -> Dict:
    import jax
    import jax.numpy as jnp

    k1, k2, k3, k4 = jax.random.split(rng, 4)
    H = cell_size
    return {
        "enc_w": jax.random.normal(k1, (obs_dim, encoder_hidden),
                                   jnp.float32)
        * np.sqrt(2.0 / obs_dim),
        "enc_b": jnp.zeros((encoder_hidden,), jnp.float32),
        "lstm_wx": jax.random.normal(k2, (encoder_hidden, 4 * H),
                                     jnp.float32)
        * np.sqrt(1.0 / encoder_hidden),
        "lstm_wh": jax.random.normal(k3, (H, 4 * H), jnp.float32)
        * np.sqrt(1.0 / H),
        "lstm_b": jnp.zeros((4 * H,), jnp.float32),
        "q_w": jax.random.normal(k4, (H, num_actions), jnp.float32) * 0.01,
        "q_b": jnp.zeros((num_actions,), jnp.float32),
    }


def _sigmoid(x: np.ndarray) -> np.ndarray:
    return 1.0 / (1.0 + np.exp(-x))


def lstm_step_np(p: Dict[str, np.ndarray], obs: np.ndarray, h: np.ndarray,
                 c: np.ndarray):
    """One numpy LSTM step: obs [n, obs_dim], h/c [n, H] -> (q, h, c).
    Mirrors the learner's jax cell bit-for-bit in structure (forget-gate
    bias +1)."""
    x = np.maximum(obs @ p["enc_w"] + p["enc_b"], 0.0)
    z = x @ p["lstm_wx"] + h @ p["lstm_wh"] + p["lstm_b"]
    H = h.shape[1]
    i, f = _sigmoid(z[:, :H]), _sigmoid(z[:, H:2 * H] + 1.0)
    g, o = np.tanh(z[:, 2 * H:3 * H]), _sigmoid(z[:, 3 * H:])
    c = f * c + i * g
    h = o * np.tanh(c)
    q = h @ p["q_w"] + p["q_b"]
    return q, h, c


class R2D2RolloutWorker(EnvWorkerBase):
    """Epsilon-greedy sampling through the recurrent policy; emits
    non-overlapping seq_len windows with (h, c) captured at each window
    start (the 'stored state' strategy — ref: r2d2.py
    zero_init_states=False path)."""

    def __init__(self, env_name: str, num_envs: int, rollout_len: int,
                 seq_len: int, cell_size: int, seed: int = 0,
                 env_creator=None):
        super().__init__(env_name, num_envs, rollout_len, seed, env_creator)
        if rollout_len % seq_len != 0:
            raise ValueError(f"rollout_fragment_length {rollout_len} must "
                             f"be a multiple of seq_len {seq_len}")
        self.seq_len = seq_len
        n = self.env.num_envs
        self._h = np.zeros((n, cell_size), np.float32)
        self._c = np.zeros((n, cell_size), np.float32)

    def sample(self, params: Dict, epsilon: float) -> sb.Batch:
        p = {k: np.asarray(v, np.float32) for k, v in params.items()}
        T, L = self.rollout_len, self.seq_len
        n, A = self.env.num_envs, self.env.num_actions
        n_win = T // L
        Hc = self._h.shape[1]
        obs_buf = np.empty((T + 1, n, self.env.obs_dim), np.float32)
        act_buf = np.empty((T, n), np.int64)
        rew_buf = np.empty((T, n), np.float32)
        done_buf = np.empty((T, n), np.bool_)
        h0_buf = np.empty((n_win, n, Hc), np.float32)
        c0_buf = np.empty((n_win, n, Hc), np.float32)
        obs = self._obs
        for t in range(T):
            if t % L == 0:
                h0_buf[t // L], c0_buf[t // L] = self._h, self._c
            q, self._h, self._c = lstm_step_np(p, obs, self._h, self._c)
            actions = q.argmax(axis=1)
            explore = self._rng.random(n) < epsilon
            actions = np.where(explore, self._rng.integers(0, A, size=n),
                               actions).astype(np.int64)
            obs_buf[t], act_buf[t] = obs, actions
            obs, reward, done, info = self.env.step(actions)
            rew_buf[t], done_buf[t] = reward, done
            self._track_returns(reward, done)
            if done.any():
                # episode boundary: recurrent state resets (time-limit
                # truncation treated as termination here — the sequence
                # target is cut either way; documented divergence from
                # dqn.py's bootstrap-through-truncation)
                idx = np.nonzero(done)[0]
                self._h[idx] = 0.0
                self._c[idx] = 0.0
        obs_buf[T] = obs
        self._obs = obs

        # windows [n_win, L(+1), n, ...] -> sequence rows [n_win*n, ...]
        def rows(a, extra: int = 0):
            w = np.stack([a[i * L:(i + 1) * L + extra]
                          for i in range(n_win)])
            return np.swapaxes(w, 1, 2).reshape(n_win * n, L + extra,
                                                *a.shape[2:])

        return {
            sb.OBS: rows(obs_buf, extra=1),
            sb.ACTIONS: rows(act_buf),
            sb.REWARDS: rows(rew_buf),
            sb.DONES: rows(done_buf),
            H0: h0_buf.reshape(n_win * n, Hc),
            C0: c0_buf.reshape(n_win * n, Hc),
        }


class R2D2Learner:
    """Jitted recurrent double-DQN over sequence minibatches: burn-in
    unroll (stop_gradient), training-segment unroll, h-function value
    rescaling, sequence priorities (ref: r2d2_torch_policy.py
    r2d2_loss)."""

    def __init__(self, obs_dim: int, num_actions: int, *, lr: float,
                 gamma: float, seq_len: int, burn_in: int,
                 encoder_hidden: int, cell_size: int,
                 use_h_function: bool = True, double_q: bool = True,
                 seed: int = 0, max_grad_norm: float = 10.0):
        import functools

        import jax
        import jax.numpy as jnp
        import optax

        self.params = init_r2d2_params(jax.random.PRNGKey(seed), obs_dim,
                                       num_actions, encoder_hidden,
                                       cell_size)
        self.target_params = jax.tree.map(lambda a: a.copy(), self.params)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(max_grad_norm), optax.adam(lr))
        self.opt_state = self.optimizer.init(self.params)
        self.num_updates = 0
        eps_h = 1e-3

        def h_fn(x):
            if not use_h_function:
                return x
            return jnp.sign(x) * (jnp.sqrt(jnp.abs(x) + 1.0) - 1.0) \
                + eps_h * x

        def h_inv(x):
            if not use_h_function:
                return x
            inner = jnp.sqrt(1.0 + 4.0 * eps_h * (jnp.abs(x) + 1.0 + eps_h))
            return jnp.sign(x) * (((inner - 1.0) / (2.0 * eps_h)) ** 2
                                  - 1.0)

        def cell(p, obs, h, c):
            x = jax.nn.relu(obs @ p["enc_w"] + p["enc_b"])
            z = x @ p["lstm_wx"] + h @ p["lstm_wh"] + p["lstm_b"]
            H = h.shape[1]
            i = jax.nn.sigmoid(z[:, :H])
            f = jax.nn.sigmoid(z[:, H:2 * H] + 1.0)
            g = jnp.tanh(z[:, 2 * H:3 * H])
            o = jax.nn.sigmoid(z[:, 3 * H:])
            c = f * c + i * g
            h = o * jnp.tanh(c)
            return (h @ p["q_w"] + p["q_b"]), h, c

        def unroll(p, obs_tl, resets_tl, h, c):
            """obs_tl [L', B, obs], resets [L', B] -> q [L', B, A]."""
            def body(carry, xs):
                h, c = carry
                obs_t, reset_t = xs
                keep = (1.0 - reset_t)[:, None]
                q, h, c = cell(p, obs_t, h * keep, c * keep)
                return (h, c), q

            (h, c), qs = jax.lax.scan(body, (h, c), (obs_tl, resets_tl))
            return qs, h, c

        def loss_fn(params, target_params, batch, weights):
            obs = jnp.swapaxes(batch[sb.OBS], 0, 1)      # [L+1, B, obs]
            dones = jnp.swapaxes(batch[sb.DONES], 0, 1)  # [L, B]
            d = dones.astype(jnp.float32)
            # reset entering step t is done at t-1 (first step: stored
            # state is already post-reset in the worker)
            resets = jnp.concatenate(
                [jnp.zeros((1, d.shape[1])), d], axis=0)  # [L+1, B]
            h, c = batch[H0], batch[C0]
            th, tc = batch[H0], batch[C0]
            if burn_in > 0:
                _, h, c = unroll(params, obs[:burn_in], resets[:burn_in],
                                 h, c)
                h, c = jax.lax.stop_gradient((h, c))
                _, th, tc = unroll(target_params, obs[:burn_in],
                                   resets[:burn_in], th, tc)
            q_on, _, _ = unroll(params, obs[burn_in:], resets[burn_in:],
                                h, c)                     # [L+1-b, B, A]
            q_tg, _, _ = unroll(target_params, obs[burn_in:],
                                resets[burn_in:], th, tc)
            acts = jnp.swapaxes(batch[sb.ACTIONS], 0, 1)[burn_in:]
            rews = jnp.swapaxes(batch[sb.REWARDS], 0, 1)[burn_in:]
            d_tr = d[burn_in:]                            # [L-b, B]
            q_sa = jnp.take_along_axis(q_on[:-1], acts[..., None],
                                       axis=2)[..., 0]
            if double_q:
                a_star = q_on[1:].argmax(axis=2)
            else:
                a_star = q_tg[1:].argmax(axis=2)
            q_next = jnp.take_along_axis(q_tg[1:], a_star[..., None],
                                         axis=2)[..., 0]
            y = h_fn(rews + gamma * (1.0 - d_tr)
                     * jax.lax.stop_gradient(h_inv(q_next)))
            td = q_sa - y
            huber = optax.huber_loss(q_sa, y, delta=1.0)  # [L-b, B]
            loss = jnp.mean(weights[None, :] * huber)
            td_abs = jnp.abs(td)
            # sequence priority: eta*max + (1-eta)*mean (ref r2d2 paper)
            prio = 0.9 * td_abs.max(axis=0) + 0.1 * td_abs.mean(axis=0)
            return loss, (prio, jnp.mean(q_sa))

        def one_update(params, opt_state, target_params, batch, weights):
            (loss, (prio, mean_q)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, batch,
                                       weights)
            updates, opt_state = self.optimizer.update(grads, opt_state,
                                                       params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, prio, mean_q

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def update_many(params, opt_state, target_params, batches, weights):
            def body(carry, xs):
                params, opt_state = carry
                batch_k, w_k = xs
                params, opt_state, loss, prio, mean_q = one_update(
                    params, opt_state, target_params, batch_k, w_k)
                return (params, opt_state), (loss, prio, mean_q)

            (params, opt_state), outs = jax.lax.scan(
                body, (params, opt_state), (batches, weights))
            return params, opt_state, outs

        self._update_many = update_many

    _KEYS = (sb.OBS, sb.ACTIONS, sb.REWARDS, sb.DONES, H0, C0)

    def update_many(self, batches: Dict[str, np.ndarray],
                    weights: Optional[np.ndarray] = None) -> Dict[str, Any]:
        """batches: dict of [K, B, L(+1), ...] arrays; -> per-sequence
        priorities [K, B]."""
        import jax
        import jax.numpy as jnp

        K, B = batches[sb.REWARDS].shape[:2]
        w = jnp.ones((K, B)) if weights is None else jnp.asarray(weights)
        jb = {k: jnp.asarray(batches[k]) for k in self._KEYS}
        (self.params, self.opt_state,
         (losses, prios, mean_qs)) = self._update_many(
            self.params, self.opt_state, self.target_params, jb, w)
        self.num_updates += K
        out = jax.device_get((losses, prios, mean_qs))
        return {"loss": float(np.mean(out[0])),
                "mean_q": float(np.mean(out[2])),
                "priorities": np.asarray(out[1])}

    def sync_target(self) -> None:
        import jax

        self.target_params = jax.tree.map(lambda a: a.copy(), self.params)

    def get_params(self) -> Dict:
        import jax

        return jax.device_get(self.params)


@dataclass
class R2D2Config:
    """ref: r2d2.py R2D2Config (burn_in, zero_init_states, h-function;
    sequence replay defaults)."""
    env: str = "CartPole-v1"
    env_creator: Optional[Callable] = None
    num_rollout_workers: int = 2
    num_envs_per_worker: int = 8
    rollout_fragment_length: int = 64
    seq_len: int = 16
    burn_in: int = 4
    gamma: float = 0.99
    lr: float = 5e-4
    buffer_size: int = 4_000          # sequences, not transitions
    prioritized_replay: bool = True
    prioritized_replay_alpha: float = 0.6
    prioritized_replay_beta: float = 0.4
    train_batch_size: int = 32        # sequences per minibatch
    num_updates_per_iter: int = 8
    learning_starts: int = 200        # sequences
    target_update_freq: int = 100     # learner updates
    epsilon_initial: float = 1.0
    epsilon_final: float = 0.02
    epsilon_decay_steps: int = 10_000
    use_h_function: bool = True
    double_q: bool = True
    encoder_hidden: int = 64
    cell_size: int = 64
    seed: int = 0
    checkpoint_replay_buffer: bool = True
    worker_resources: Dict[str, float] = field(default_factory=dict)

    def build(self) -> "R2D2":
        return R2D2(self)


class R2D2:
    """Synchronous R2D2 driver (DQN shape, sequence granularity)."""

    def __init__(self, config: R2D2Config):
        self.config = c = config
        if c.burn_in >= c.seq_len:
            raise ValueError("burn_in must be < seq_len")
        creator_blob = (cloudpickle.dumps(c.env_creator)
                        if c.env_creator else None)
        worker_cls = ray_tpu.remote(R2D2RolloutWorker)
        opts = worker_opts(c.worker_resources)
        self.workers: List = [
            worker_cls.options(**opts).remote(
                c.env, c.num_envs_per_worker, c.rollout_fragment_length,
                c.seq_len, c.cell_size, seed=c.seed + 1000 * i,
                env_creator=creator_blob)
            for i in range(c.num_rollout_workers)]
        info = ray_tpu.get(self.workers[0].env_info.remote(), timeout=180)
        self.learner = R2D2Learner(
            info["obs_dim"], info["num_actions"], lr=c.lr, gamma=c.gamma,
            seq_len=c.seq_len, burn_in=c.burn_in,
            encoder_hidden=c.encoder_hidden, cell_size=c.cell_size,
            use_h_function=c.use_h_function, double_q=c.double_q,
            seed=c.seed)
        if c.prioritized_replay:
            self.buffer = PrioritizedReplayBuffer(
                c.buffer_size, alpha=c.prioritized_replay_alpha,
                beta=c.prioritized_replay_beta, seed=c.seed)
        else:
            self.buffer = ReplayBuffer(c.buffer_size, seed=c.seed)
        self._iteration = 0
        self._total_steps = 0
        self._total_episodes = 0
        self._recent: List[float] = []

    def _epsilon(self) -> float:
        c = self.config
        frac = min(1.0, self._total_steps / max(1, c.epsilon_decay_steps))
        return c.epsilon_initial + frac * (c.epsilon_final
                                           - c.epsilon_initial)

    def train(self) -> Dict[str, Any]:
        c = self.config
        t0 = time.monotonic()
        eps = self._epsilon()
        params_ref = ray_tpu.put(self.learner.get_params())
        batches = ray_tpu.get(
            [w.sample.remote(params_ref, eps) for w in self.workers],
            timeout=300)
        batch = sb.concat(batches)
        n_seq = len(batch[sb.REWARDS])
        steps = n_seq * c.seq_len
        self._total_steps += steps
        self.buffer.add(batch)
        sample_time = time.monotonic() - t0
        t1 = time.monotonic()
        stats: Dict[str, Any] = {}
        if len(self.buffer) >= c.learning_starts:
            K = c.num_updates_per_iter
            out = fused_replay_update(self.buffer,
                                      self.learner.update_many, K,
                                      c.train_batch_size, "priorities")
            n = self.learner.num_updates
            if n // c.target_update_freq > (n - K) // c.target_update_freq:
                self.learner.sync_target()
            stats = {"loss": out["loss"], "mean_q": out["mean_q"],
                     "num_updates": n}
        learn_time = time.monotonic() - t1
        for rets in ray_tpu.get(
                [w.episode_returns.remote() for w in self.workers],
                timeout=60):
            self._recent.extend(rets)
            self._total_episodes += len(rets)
        self._recent = self._recent[-100:]
        self._iteration += 1
        return {"training_iteration": self._iteration,
                "timesteps_total": self._total_steps,
                "timesteps_this_iter": steps,
                "episode_reward_mean": (float(np.mean(self._recent))
                                        if self._recent else float("nan")),
                "episodes_total": self._total_episodes,
                "epsilon": eps,
                "buffer_sequences": len(self.buffer),
                "env_steps_per_sec": steps / max(1e-9,
                                                 sample_time + learn_time),
                "sample_time_s": sample_time, "learn_time_s": learn_time,
                **stats}

    # -- Tune-trainable surface ------------------------------------------

    def save(self) -> Dict:
        import jax

        ckpt = {"params": jax.device_get(self.learner.params),
                "target_params": jax.device_get(
                    self.learner.target_params),
                "opt_state": jax.device_get(self.learner.opt_state),
                "iteration": self._iteration,
                "total_steps": self._total_steps,
                "num_updates": self.learner.num_updates}
        if self.config.checkpoint_replay_buffer:
            ckpt["buffer"] = self.buffer.state()
        return ckpt

    def restore(self, ckpt: Dict) -> None:
        import jax
        import jax.numpy as jnp

        as_jnp = lambda t: jax.tree.map(jnp.asarray, t)  # noqa: E731
        self.learner.params = as_jnp(ckpt["params"])
        self.learner.target_params = as_jnp(ckpt["target_params"])
        if "opt_state" in ckpt:
            self.learner.opt_state = as_jnp(ckpt["opt_state"])
        self.learner.num_updates = int(ckpt.get("num_updates", 0))
        self._iteration = int(ckpt.get("iteration", 0))
        self._total_steps = int(ckpt.get("total_steps", 0))
        if "buffer" in ckpt:
            self.buffer.restore(ckpt["buffer"])

    def stop(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
