"""Replay buffers for off-policy algorithms.

ref: rllib/utils/replay_buffers/replay_buffer.py (ring storage, add/sample)
and prioritized_replay_buffer.py (sum-tree proportional prioritization per
Schaul et al. 2015). Storage is column-major preallocated numpy — the same
dict-of-arrays shape sample batches already use, so buffers concatenate
rollout-worker output with zero copies beyond the ring write.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

Batch = Dict[str, np.ndarray]


class ReplayBuffer:
    """Uniform-sampling ring buffer (ref: replay_buffer.py:71 add,
    :132 sample). Columns are allocated lazily from the first batch so the
    buffer is schema-agnostic (DQN transitions, SAC tuples, ...)."""

    def __init__(self, capacity: int = 100_000, seed: int = 0):
        self.capacity = int(capacity)
        self._cols: Dict[str, np.ndarray] = {}
        self._size = 0
        self._next = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def _ensure_storage(self, batch: Batch) -> None:
        for k, v in batch.items():
            if k not in self._cols:
                self._cols[k] = np.empty((self.capacity, *v.shape[1:]),
                                         v.dtype)

    def add(self, batch: Batch) -> np.ndarray:
        """Append a batch of rows; oldest rows are overwritten when full.
        Returns the ring indices written (prioritized subclass uses them)."""
        n = len(next(iter(batch.values())))
        self._ensure_storage(batch)
        idx = (self._next + np.arange(n)) % self.capacity
        for k, v in batch.items():
            self._cols[k][idx] = v
        self._next = int((self._next + n) % self.capacity)
        self._size = min(self._size + n, self.capacity)
        return idx

    def sample(self, batch_size: int) -> Batch:
        idx = self._rng.integers(0, self._size, size=batch_size)
        return {k: v[idx] for k, v in self._cols.items()}

    def state(self) -> Dict:
        """Checkpointable state (ref: replay_buffer.py get_state)."""
        return {"cols": {k: v[:self._size].copy()
                         for k, v in self._cols.items()},
                "next": self._next, "size": self._size}

    def restore(self, state: Dict) -> np.ndarray:
        """Restore a snapshot, possibly across a capacity change (PBT
        explore can hand a donor checkpoint from a differently-sized
        trial). On shrink the NEWEST rows win. Returns the source-row
        order of the kept rows (the prioritized subclass re-maps its
        leaf priorities with it)."""
        size = int(state["size"])
        nxt = int(state["next"])
        keep = min(size, self.capacity)
        if nxt < size:  # ring had wrapped: oldest row sits at `next`
            order = np.concatenate([np.arange(nxt, size), np.arange(0, nxt)])
        else:
            order = np.arange(size)
        order = order[len(order) - keep:]
        for k, v in state["cols"].items():
            self._cols[k] = np.empty((self.capacity, *v.shape[1:]), v.dtype)
            self._cols[k][:keep] = v[order]
        self._size = keep
        self._next = keep % self.capacity if self.capacity else 0
        return order


def fused_replay_update(buffer, update_many, K: int, B: int,
                        priority_key: str = "td_abs"):
    """The shared off-policy learner block (DQN / R2D2 / SAC shape):
    K draws -> stacked [K, B, ...] arrays -> ONE fused update_many
    dispatch -> PER priority refresh. `priority_key` names the
    per-minibatch |TD|/priority array in update_many's result. Returns
    the update_many stats dict (ref: dqn.py training_step's
    sample-then-learn block, shared here so the arithmetic lives once).
    """
    if isinstance(buffer, PrioritizedReplayBuffer):
        draws = [buffer.sample(B) for _ in range(K)]
        stacked = {k: np.stack([d[0][k] for d in draws])
                   for k in draws[0][0]}
        out = update_many(stacked, np.stack([d[2] for d in draws]))
        for i, (_, idx, _) in enumerate(draws):
            buffer.update_priorities(idx, out[priority_key][i])
    else:
        draws = [buffer.sample(B) for _ in range(K)]
        stacked = {k: np.stack([d[k] for d in draws]) for k in draws[0]}
        out = update_many(stacked)
    return out


class SumTree:
    """Binary-indexed sum tree over `capacity` leaves: O(log n) update and
    prefix-sum sampling (ref: the segment tree in
    rllib/execution/segment_tree.py)."""

    def __init__(self, capacity: int):
        # round up to a power of two so every leaf sits at the same depth —
        # the vectorized bottom-up propagation assumes level-aligned parents
        self.capacity = 1 << (capacity - 1).bit_length()
        self._tree = np.zeros(2 * self.capacity, np.float64)

    def update(self, idx: np.ndarray, values: np.ndarray) -> None:
        leaf = np.asarray(idx) + self.capacity
        self._tree[leaf] = values  # duplicate idx: last write wins
        # propagate bottom-up; each parent is recomputed from BOTH children,
        # so recomputing a parent twice (duplicate indices) is harmless
        pos = np.unique(leaf // 2)
        while True:
            self._tree[pos] = self._tree[2 * pos] + self._tree[2 * pos + 1]
            if pos[0] <= 1:
                break
            pos = np.unique(pos // 2)

    @property
    def total(self) -> float:
        return float(self._tree[1])

    def sample_prefix(self, prefix: np.ndarray) -> np.ndarray:
        """Vectorized descent: for each prefix sum p in [0, total), find the
        leaf whose cumulative range contains p."""
        pos = np.ones(len(prefix), np.int64)
        p = prefix.astype(np.float64).copy()
        while pos[0] < self.capacity:
            left = self._tree[2 * pos]
            go_right = p >= left
            p -= np.where(go_right, left, 0.0)
            pos = 2 * pos + go_right
        return pos - self.capacity


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (ref:
    prioritized_replay_buffer.py:26; Schaul et al.).  P(i) ∝ p_i^alpha;
    importance weights w_i = (N * P(i))^-beta normalized by max."""

    def __init__(self, capacity: int = 100_000, *, alpha: float = 0.6,
                 beta: float = 0.4, eps: float = 1e-6, seed: int = 0):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self.beta = beta
        self.eps = eps
        self._tree = SumTree(capacity)
        self._max_priority = 1.0

    def add(self, batch: Batch) -> np.ndarray:
        idx = super().add(batch)
        # new transitions get max priority so they are seen at least once
        self._tree.update(idx, np.full(len(idx),
                                       self._max_priority ** self.alpha))
        return idx

    def sample(self, batch_size: int
               ) -> Tuple[Batch, np.ndarray, np.ndarray]:
        """-> (batch, ring_indices, importance_weights)."""
        total = self._tree.total
        # stratified prefixes: one uniform draw per equal segment
        seg = total / batch_size
        prefix = (np.arange(batch_size)
                  + self._rng.random(batch_size)) * seg
        idx = self._tree.sample_prefix(np.minimum(prefix, total * (1 - 1e-9)))
        idx = np.minimum(idx, self._size - 1)
        probs = self._tree._tree[idx + self._tree.capacity] / total
        weights = (self._size * np.maximum(probs, 1e-12)) ** (-self.beta)
        weights /= weights.max()
        return ({k: v[idx] for k, v in self._cols.items()}, idx,
                weights.astype(np.float32))

    def update_priorities(self, idx: np.ndarray,
                          td_errors: np.ndarray) -> None:
        p = np.abs(td_errors) + self.eps
        self._max_priority = max(self._max_priority, float(p.max()))
        self._tree.update(np.asarray(idx), p ** self.alpha)

    def state(self) -> Dict:
        s = super().state()
        # leaf priorities must round-trip or a restored buffer samples
        # from a zeroed tree (NaN weights, single-row minibatches)
        leaves = self._tree._tree[self._tree.capacity:
                                  self._tree.capacity + self.capacity]
        s["priorities"] = leaves[:self._size].copy()
        s["max_priority"] = self._max_priority
        return s

    def restore(self, state: Dict) -> np.ndarray:
        order = super().restore(state)
        self._max_priority = float(state.get("max_priority", 1.0))
        prios = state.get("priorities")
        if prios is None:  # plain-buffer snapshot: everything max priority
            prios = np.full(self._size, self._max_priority ** self.alpha)
        else:
            prios = np.asarray(prios)[order]  # same keep/reorder as rows
        # fresh tree: leaves beyond the restored size would otherwise keep
        # stale priorities and skew every subsequent sample toward them
        self._tree = SumTree(self.capacity)
        if self._size:
            self._tree.update(np.arange(self._size), prios[:self._size])
        return order
