"""QMIX — monotonic value decomposition for cooperative multi-agent RL.

ref: rllib/algorithms/qmix/qmix.py + qmix_policy.py (mixer in
rllib/algorithms/qmix/model.py QMixer): per-agent Q-networks (shared
parameters + one-hot agent id) pick decentralized greedy actions; a
mixing hypernetwork conditioned on the GLOBAL state combines the chosen
per-agent Q values into Q_tot with non-negative mixing weights, so
argmax_a Q_tot = per-agent argmaxes (the monotonicity constraint —
centralized training, decentralized execution).

TPU-native shape: the whole K-minibatch update (per-agent Q forward,
target mixer, TD loss, Adam) is ONE jitted lax.scan dispatch
(`update_many`) — the same fused-learner rule every off-policy algo in
this package follows (docs/PERF_NOTES.md: the tunnel makes per-update
dispatches unaffordable). The env steps in-process: cooperative
small-team games are sampler-light, learner-heavy.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import numpy as np

from .multi_agent import make_multi_agent_env


def _init_mlp(rng, sizes):
    import jax
    import jax.numpy as jnp

    params = {}
    keys = jax.random.split(rng, len(sizes) - 1)
    for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
        params[f"w{i}"] = jax.random.normal(keys[i], (a, b),
                                            jnp.float32) * np.sqrt(2.0 / a)
        params[f"b{i}"] = jnp.zeros((b,), jnp.float32)
    return params


def _mlp(params, x, n_layers):
    import jax.numpy as jnp

    for i in range(n_layers):
        x = x @ params[f"w{i}"] + params[f"b{i}"]
        if i < n_layers - 1:
            x = jnp.maximum(x, 0.0)
    return x


@dataclass
class QMIXConfig:
    """ref: qmix.py QMIXConfig defaults (mixing_embed_dim 32, double_q,
    target update period, epsilon anneal)."""
    env: str = "Coordination-v0"
    num_envs: int = 16
    gamma: float = 0.99
    lr: float = 5e-4
    buffer_size: int = 50_000
    train_batch_size: int = 128
    num_updates_per_iter: int = 16
    rollout_len: int = 50
    learning_starts: int = 500
    target_update_freq: int = 40    # in updates
    mixing_embed_dim: int = 32
    hidden: tuple = (64,)
    epsilon_start: float = 1.0
    epsilon_end: float = 0.05
    epsilon_anneal_steps: int = 5_000
    seed: int = 0
    extra: Dict[str, Any] = field(default_factory=dict)

    def build(self) -> "QMIX":
        return QMIX(self)


class QMIXLearner:
    def __init__(self, obs_dim: int, num_actions: int, n_agents: int,
                 state_dim: int, c: QMIXConfig):
        import functools

        import jax
        import jax.numpy as jnp
        import optax

        self.n_agents, self.num_actions = n_agents, num_actions
        k = jax.random.split(jax.random.PRNGKey(c.seed), 5)
        h = list(c.hidden)
        emb = c.mixing_embed_dim
        # shared per-agent Q net; input = obs ++ one-hot agent id
        self.params = {
            "q": _init_mlp(k[0], [obs_dim + n_agents, *h, num_actions]),
            # hypernetworks: state -> mixing weights (abs() for
            # monotonicity) and biases (ref: qmix/model.py QMixer)
            "hyp_w1": _init_mlp(k[1], [state_dim, n_agents * emb]),
            "hyp_b1": _init_mlp(k[2], [state_dim, emb]),
            "hyp_w2": _init_mlp(k[3], [state_dim, emb]),
            "hyp_b2": _init_mlp(k[4], [state_dim, emb, 1]),
        }
        self.target = jax.tree.map(lambda a: a.copy(), self.params)
        self.opt = optax.adam(c.lr)
        self.opt_state = self.opt.init(self.params)
        self.num_updates = 0
        n_q_layers = len(h) + 1

        def agent_qs(qp, obs_all):
            # obs_all [B, n_agents, obs_dim] -> [B, n_agents, A]
            B = obs_all.shape[0]
            ids = jnp.eye(n_agents, dtype=jnp.float32)
            ids = jnp.broadcast_to(ids[None], (B, n_agents, n_agents))
            x = jnp.concatenate([obs_all, ids], axis=-1)
            return _mlp(qp, x, n_q_layers)

        def mix(mp, chosen_q, state):
            # chosen_q [B, n_agents], state [B, S] -> Q_tot [B]
            B = chosen_q.shape[0]
            w1 = jnp.abs(_mlp(mp["hyp_w1"], state, 1)).reshape(
                B, n_agents, emb)
            b1 = _mlp(mp["hyp_b1"], state, 1)
            hidden_l = jnp.einsum("ba,bae->be", chosen_q, w1) + b1
            hidden_l = jnp.where(hidden_l > 0, hidden_l,
                                 jnp.expm1(hidden_l))  # ELU
            w2 = jnp.abs(_mlp(mp["hyp_w2"], state, 1))
            b2 = _mlp(mp["hyp_b2"], state, 2)[:, 0]
            return jnp.sum(hidden_l * w2, axis=-1) + b2

        self._agent_qs = jax.jit(agent_qs)

        def td_loss(params, target, batch):
            qs = agent_qs(params["q"], batch["obs"])          # [B,n,A]
            chosen = jnp.take_along_axis(
                qs, batch["actions"][..., None], axis=-1)[..., 0]
            q_tot = mix(params, chosen, batch["state"])
            # double-Q: online net picks a', target net evaluates
            next_online = agent_qs(params["q"], batch["next_obs"])
            a_next = jnp.argmax(next_online, axis=-1)
            next_target = agent_qs(target["q"], batch["next_obs"])
            chosen_next = jnp.take_along_axis(
                next_target, a_next[..., None], axis=-1)[..., 0]
            q_tot_next = mix(target, chosen_next, batch["next_state"])
            y = batch["reward"] + c.gamma * (1.0 - batch["done"]) \
                * q_tot_next
            y = jax.lax.stop_gradient(y)
            return jnp.mean(jnp.square(q_tot - y))

        def one_update(carry, mb):
            params, opt_state, target = carry
            loss, grads = jax.value_and_grad(td_loss)(params, target, mb)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state, target), loss

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def update_many(params, opt_state, target, batches):
            (params, opt_state, _), losses = jax.lax.scan(
                one_update, (params, opt_state, target), batches)
            return params, opt_state, jnp.mean(losses)

        self._update_many = update_many
        import jax.numpy as jnp  # noqa: F811 — keep local alias bound

    def greedy_actions(self, obs_all: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp

        qs = self._agent_qs(self.params["q"], jnp.asarray(obs_all))
        return np.asarray(jnp.argmax(qs, axis=-1))

    def update(self, stacked: Dict[str, np.ndarray]) -> float:
        import jax.numpy as jnp

        batches = {k: jnp.asarray(v) for k, v in stacked.items()}
        self.params, self.opt_state, loss = self._update_many(
            self.params, self.opt_state, self.target, batches)
        self.num_updates += int(stacked["reward"].shape[0])
        return float(loss)

    def sync_target(self) -> None:
        import jax

        self.target = jax.tree.map(lambda a: a.copy(), self.params)


class QMIX:
    """Tune-trainable QMIX on a MultiAgentVecEnv (all agents active each
    step, shared team reward)."""

    def __init__(self, config: QMIXConfig):
        c = self.config = config
        self.env = make_multi_agent_env(c.env, num_envs=c.num_envs,
                                        seed=c.seed)
        self.agents = list(self.env.agent_ids)
        n = len(self.agents)
        obs_dim = self.env.obs_dim
        self.learner = QMIXLearner(obs_dim, self.env.num_actions, n,
                                   state_dim=n * obs_dim, c=c)
        self._rng = np.random.default_rng(c.seed + 1)
        self._obs = self.env.reset(seed=c.seed)
        # flat ring buffer of team transitions
        self._buf: Dict[str, np.ndarray] = {}
        self._buf_n = 0
        self._buf_pos = 0
        self._total_steps = 0
        self._iteration = 0
        self._ep_ret = np.zeros(c.num_envs, np.float64)
        self._recent: list = []

    def _stack_obs(self, obs: Dict[str, np.ndarray]) -> np.ndarray:
        return np.stack([obs[a] for a in self.agents], axis=1)  # [n,agents,D]

    def _epsilon(self) -> float:
        c = self.config
        frac = min(1.0, self._total_steps / max(1, c.epsilon_anneal_steps))
        return c.epsilon_start + frac * (c.epsilon_end - c.epsilon_start)

    def _add(self, tr: Dict[str, np.ndarray]) -> None:
        cap = self.config.buffer_size
        n = len(tr["reward"])
        if not self._buf:
            self._buf = {k: np.empty((cap, *v.shape[1:]), v.dtype)
                         for k, v in tr.items()}
        for k, v in tr.items():
            idx = (self._buf_pos + np.arange(n)) % cap
            self._buf[k][idx] = v
        self._buf_pos = (self._buf_pos + n) % cap
        self._buf_n = min(cap, self._buf_n + n)

    def train(self) -> Dict[str, float]:
        c = self.config
        t0 = time.monotonic()
        steps = 0
        for _ in range(c.rollout_len):
            obs_all = self._stack_obs(self._obs)          # [n, agents, D]
            greedy = self.learner.greedy_actions(obs_all)  # [n, agents]
            eps = self._epsilon()
            explore = self._rng.random(greedy.shape) < eps
            randoms = self._rng.integers(0, self.env.num_actions,
                                         greedy.shape)
            acts = np.where(explore, randoms, greedy)
            action_dict = {a: acts[:, i] for i, a in enumerate(self.agents)}
            next_obs, rewards, done, info = self.env.step(action_dict)
            team_r = np.mean([rewards[a] for a in self.agents],
                             axis=0).astype(np.float32)
            next_all = self._stack_obs(next_obs)
            state = obs_all.reshape(len(obs_all), -1)
            # time-limit truncation bootstraps (final_obs), termination
            # doesn't — Coordination's cap is a truncation
            trunc = info.get("truncated")
            term = done & ~trunc if trunc is not None else done
            nxt = next_all
            if trunc is not None and trunc.any():
                fin = self._stack_obs(info["final_obs"])
                nxt = np.where(trunc[:, None, None], fin, next_all)
            self._add({"obs": obs_all.astype(np.float32),
                       "actions": acts.astype(np.int32),
                       "reward": team_r,
                       "done": term.astype(np.float32),
                       "next_obs": nxt.astype(np.float32),
                       "state": state.astype(np.float32),
                       "next_state": nxt.reshape(len(nxt), -1)
                       .astype(np.float32)})
            self._ep_ret += team_r
            if done.any():
                idx = np.nonzero(done)[0]
                self._recent.extend(self._ep_ret[idx].tolist())
                self._ep_ret[idx] = 0.0
            self._obs = next_obs
            steps += c.num_envs
        self._total_steps += steps
        loss = float("nan")
        if self._buf_n >= max(c.learning_starts, c.train_batch_size):
            K, B = c.num_updates_per_iter, c.train_batch_size
            idx = self._rng.integers(0, self._buf_n, K * B)
            stacked = {k: v[idx].reshape(K, B, *v.shape[1:])
                       for k, v in self._buf.items()}
            loss = self.learner.update(stacked)
            if self.learner.num_updates // c.target_update_freq != \
                    (self.learner.num_updates - K) // c.target_update_freq:
                self.learner.sync_target()
        self._recent = self._recent[-100:]
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "timesteps_total": self._total_steps,
            "episode_reward_mean": (float(np.mean(self._recent))
                                    if self._recent else float("nan")),
            "epsilon": self._epsilon(),
            "loss": loss,
            "time_this_iter_s": time.monotonic() - t0,
        }

    # -- Tune-trainable surface ------------------------------------------

    def save(self) -> Dict:
        import jax

        return {"params": jax.device_get(self.learner.params),
                "target": jax.device_get(self.learner.target),
                "opt_state": jax.device_get(self.learner.opt_state),
                "num_updates": self.learner.num_updates,
                "iteration": self._iteration,
                "total_steps": self._total_steps}

    def restore(self, ckpt: Dict) -> None:
        import jax
        import jax.numpy as jnp

        as_jnp = lambda t: jax.tree.map(jnp.asarray, t)  # noqa: E731
        self.learner.params = as_jnp(ckpt["params"])
        self.learner.target = as_jnp(ckpt["target"])
        if "opt_state" in ckpt:
            self.learner.opt_state = as_jnp(ckpt["opt_state"])
        self.learner.num_updates = int(ckpt.get("num_updates", 0))
        self._iteration = int(ckpt.get("iteration", 0))
        self._total_steps = int(ckpt.get("total_steps", 0))

    def stop(self) -> None:
        pass
