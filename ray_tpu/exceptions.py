"""User-visible exceptions.

Mirrors the reference's exception taxonomy (ref: python/ray/exceptions.py):
task errors wrap the remote traceback; actor/object/worker failures are
distinct types so application code can catch them specifically.
"""
from __future__ import annotations

import traceback


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A remote task raised an exception. Holds the remote traceback and
    re-raises with ``cause`` preserved where possible."""

    def __init__(self, cause: BaseException | None = None, remote_traceback: str = "",
                 task_desc: str = ""):
        self.cause = cause
        self.remote_traceback = remote_traceback
        self.task_desc = task_desc
        super().__init__(str(self))

    def __str__(self):
        msg = f"Task failed: {self.task_desc}\n{self.remote_traceback}"
        if self.cause is not None:
            msg += f"\nCaused by: {type(self.cause).__name__}: {self.cause}"
        return msg

    @classmethod
    def from_exception(cls, exc: BaseException, task_desc: str = "") -> "TaskError":
        return cls(cause=exc, remote_traceback=traceback.format_exc(), task_desc=task_desc)


class WorkerCrashedError(RayTpuError):
    """The worker process executing a task died unexpectedly."""


class ActorError(RayTpuError):
    pass


class ActorDiedError(ActorError):
    """The actor is dead (creation failed, exited, or its node died) and will
    not be restarted (restarts exhausted)."""


class ActorUnavailableError(ActorError):
    """The actor is temporarily unreachable (restarting)."""


class ObjectLostError(RayTpuError):
    """The object's value was lost and could not be reconstructed."""

    def __init__(self, object_id_hex: str, msg: str = ""):
        self.object_id_hex = object_id_hex
        super().__init__(msg or f"Object {object_id_hex} was lost and could not be recovered.")


class OwnerDiedError(ObjectLostError):
    pass


class ObjectStoreFullError(RayTpuError):
    pass


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class TaskCancelledError(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class PlacementGroupUnschedulableError(RayTpuError):
    pass


class CompiledGraphError(RayTpuError):
    """Base class for compiled-graph (ray_tpu.cgraph) failures."""


class CompiledGraphClosedError(CompiledGraphError):
    """The compiled graph was torn down (explicitly, or because a
    participating actor or channel peer died) while executions were in
    flight; every pending ``execute()`` ref raises this."""


class ChannelFullError(CompiledGraphError):
    """A compiled-graph channel write could not complete: the payload
    exceeds the channel's pre-allocated slot capacity."""


class DataFeedError(CompiledGraphError):
    """A data-feed pump actor (ray_tpu.data.feed) attached to a
    pipeline engine died or failed while the engine was live; the
    engine aborts with this so ``recover()`` can respawn the stages
    AND re-attach the feed."""
