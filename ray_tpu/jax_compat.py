"""Version-compat shims for the moving parts of the jax API surface.

The repo targets current jax idiom (top-level ``jax.shard_map``,
``pltpu.CompilerParams``), but must also run on the jax 0.4.x line where
``shard_map`` still lives in ``jax.experimental.shard_map`` with the
``auto=`` spelling instead of ``axis_names=``. Centralizing the fallback
here keeps every kernel/pipeline call site on the modern spelling.
"""
from __future__ import annotations

try:  # jax >= 0.5: top-level export
    from jax import shard_map as _new_shard_map

    _experimental = None
except ImportError:  # jax 0.4.x
    _new_shard_map = None
    from jax.experimental.shard_map import shard_map as _experimental


def shard_map(f, mesh=None, in_specs=None, out_specs=None,
              axis_names=None, **kwargs):
    """``jax.shard_map`` with the modern signature on every jax version.

    ``axis_names`` (modern: the mesh axes the body is *manual* over) is
    translated to the 0.4.x ``auto=`` parameter (its complement) when
    running on the experimental implementation.
    """
    if _new_shard_map is not None:
        if axis_names is not None:
            kwargs["axis_names"] = axis_names
        return _new_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kwargs)
    if axis_names is not None:
        # The modern axis_names= means "manual over these axes only".
        # 0.4.x's partial-manual spelling (auto=complement) lowers a
        # PartitionId op its SPMD partitioner rejects, so run fully
        # manual instead — equivalent as long as in/out specs never
        # reference the extra axes (our callers' specs only name the
        # manual axes; the body never touches the others). The static
        # replication checker predates varying types, so it is disabled
        # to admit the pvary()-marked carries.
        kwargs.setdefault("check_rep", False)
    if "check_vma" in kwargs:  # modern name for 0.4.x's check_rep
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _experimental(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, **kwargs)


def pvary(x, axis_names):
    """Mark ``x`` as varying over ``axis_names`` inside a manual
    shard_map body. Modern jax spells this ``jax.lax.pvary`` (earlier
    preview: ``pcast(..., to="varying")``); jax 0.4.x has no varying
    types at all, so there it is the identity (pair with the
    ``check_rep=False`` fallback in :func:`shard_map`)."""
    import jax

    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, tuple(axis_names), to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, tuple(axis_names))
    return x


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` (jax >= 0.5) / ``TPUCompilerParams``
    (jax 0.4.x). Imported lazily: pallas-tpu is only needed on the
    kernel path."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    return cls(**kwargs)
