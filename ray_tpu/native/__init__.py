"""ray_tpu.native — C++ performance layer, loaded via ctypes.

The image has no pybind11; the native pieces export a C ABI and build
on first import with the system g++ into a content-hashed cached .so
(so a source edit rebuilds, and N processes race benignly via atomic
rename). `load_store_lib()` returns None when no compiler is present —
callers fall back to the pure-Python implementations, which remain the
semantics reference.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading
from typing import Optional

_SRC = os.path.join(os.path.dirname(__file__), "store.cpp")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _cache_dir() -> str:
    d = os.path.join(tempfile.gettempdir(), "ray_tpu_native")
    os.makedirs(d, exist_ok=True)
    return d


def _build() -> Optional[str]:
    with open(_SRC, "rb") as f:
        src = f.read()
    tag = hashlib.sha1(src).hexdigest()[:16]
    out = os.path.join(_cache_dir(), f"librtpu_store_{tag}.so")
    if os.path.exists(out):
        return out
    tmp = out + f".tmp.{os.getpid()}"
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
           "-o", tmp, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)  # atomic: concurrent builders race benignly
        return out
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


_WIRE_SRC = os.path.join(os.path.dirname(__file__), "wirefast.c")
_wire_mod = None
_wire_tried = False


def load_wirefast():
    """The _rtpu_wirefast CPython extension (wire-codec decode hot path),
    or None — callers fall back to the pure-Python decoder, which stays
    the semantics reference."""
    global _wire_mod, _wire_tried
    with _lock:
        if _wire_tried:
            return _wire_mod
        _wire_tried = True
        if os.environ.get("RTPU_NATIVE_WIRE", "1") != "1":
            return None
        import sysconfig

        with open(_WIRE_SRC, "rb") as f:
            src = f.read()
        tag = hashlib.sha1(src).hexdigest()[:16]
        out = os.path.join(_cache_dir(), f"_rtpu_wirefast_{tag}.so")
        if not os.path.exists(out):
            tmp = out + f".tmp.{os.getpid()}"
            cmd = ["gcc", "-O2", "-shared", "-fPIC",
                   "-I", sysconfig.get_paths()["include"],
                   "-o", tmp, _WIRE_SRC]
            try:
                subprocess.run(cmd, check=True, capture_output=True,
                               timeout=120)
                os.replace(tmp, out)
            except Exception:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                return None
        try:
            import importlib.machinery
            import importlib.util

            loader = importlib.machinery.ExtensionFileLoader(
                "_rtpu_wirefast", out)
            spec = importlib.util.spec_from_file_location(
                "_rtpu_wirefast", out, loader=loader)
            _wire_mod = importlib.util.module_from_spec(spec)
            loader.exec_module(_wire_mod)
        except Exception:
            _wire_mod = None
        return _wire_mod


def load_store_lib() -> Optional[ctypes.CDLL]:
    """The C++ store library, or None (no compiler / build failure)."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if os.environ.get("RTPU_NATIVE_STORE", "1") != "1":
            return None
        path = _build()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        u64, p = ctypes.c_uint64, ctypes.c_void_p
        lib.rtpu_store_open.restype = p
        lib.rtpu_store_open.argtypes = [ctypes.c_char_p, u64,
                                        ctypes.c_char_p, u64]
        lib.rtpu_store_create.restype = ctypes.c_int
        lib.rtpu_store_create.argtypes = [p, ctypes.c_char_p, u64]
        lib.rtpu_store_seal.restype = ctypes.c_int
        lib.rtpu_store_seal.argtypes = [p, ctypes.c_char_p, ctypes.c_int]
        lib.rtpu_store_verify.restype = ctypes.c_int
        lib.rtpu_store_verify.argtypes = [p, ctypes.c_char_p]
        lib.rtpu_store_pin.restype = ctypes.c_int
        lib.rtpu_store_pin.argtypes = [p, ctypes.c_char_p, ctypes.c_int]
        lib.rtpu_store_contains.restype = ctypes.c_int
        lib.rtpu_store_contains.argtypes = [p, ctypes.c_char_p]
        lib.rtpu_store_get.restype = ctypes.c_int
        lib.rtpu_store_get.argtypes = [p, ctypes.c_char_p,
                                       ctypes.POINTER(p),
                                       ctypes.POINTER(u64),
                                       ctypes.POINTER(ctypes.c_int)]
        lib.rtpu_store_delete.restype = ctypes.c_int
        lib.rtpu_store_delete.argtypes = [p, ctypes.c_char_p]
        lib.rtpu_store_stats.restype = None
        lib.rtpu_store_stats.argtypes = [p] + [ctypes.POINTER(u64)] * 5
        lib.rtpu_store_destroy.restype = None
        lib.rtpu_store_destroy.argtypes = [p]
        _lib = lib
        return _lib
