/* _rtpu_wirefast — C decode path for the ray_tpu typed wire codec.
 *
 * Mirrors ray_tpu/core/wire.py _decode_value exactly (same tags, same
 * bounds: 16M container cap, depth 100, trailing-byte check, struct ids
 * resolved through a Python callback into the same registry). The pure
 * Python decoder remains the semantics reference and the fallback when
 * no compiler is present; tests run both.
 *
 * The hot frames are TaskSpec pushes (~40 primitive leaves per spec) and
 * task_done payloads — decoding them here instead of bytecode is a
 * ~5-10x win on the head-throughput envelope (docs/PERF_NOTES.md r5).
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

#define T_NONE 0
#define T_TRUE 1
#define T_FALSE 2
#define T_INT 3
#define T_BIGINT 4
#define T_FLOAT 5
#define T_STR 6
#define T_BYTES 7
#define T_LIST 8
#define T_TUPLE 9
#define T_DICT 10
#define T_SET 11
#define T_STRUCT 12
#define T_FROZENSET 13

#define MAX_CONTAINER (1 << 24)
#define MAX_DEPTH 100

static PyObject *g_decode_err = NULL; /* WireDecodeError */
static PyObject *g_struct_cb = NULL;  /* (sid:int, vals:tuple) -> object */

typedef struct {
    const unsigned char *p;
    const unsigned char *end;
} Reader;

static void raise_err(const char *msg)
{
    if (!PyErr_Occurred())
        PyErr_SetString(g_decode_err ? g_decode_err : PyExc_ValueError, msg);
}

static int need(Reader *r, Py_ssize_t n)
{
    if (r->end - r->p < n) {
        raise_err("truncated frame");
        return 0;
    }
    return 1;
}

static uint32_t rd_u32(Reader *r)
{
    uint32_t v;
    memcpy(&v, r->p, 4);
    r->p += 4;
    return v;
}

static PyObject *decode_value(Reader *r, int depth)
{
    if (depth > MAX_DEPTH) {
        raise_err("frame nesting too deep");
        return NULL;
    }
    if (!need(r, 1))
        return NULL;
    unsigned char tag = *r->p++;
    switch (tag) {
    case T_NONE:
        Py_RETURN_NONE;
    case T_TRUE:
        Py_RETURN_TRUE;
    case T_FALSE:
        Py_RETURN_FALSE;
    case T_INT: {
        if (!need(r, 8))
            return NULL;
        int64_t v;
        memcpy(&v, r->p, 8);
        r->p += 8;
        return PyLong_FromLongLong((long long)v);
    }
    case T_BIGINT: {
        if (!need(r, 4))
            return NULL;
        uint32_t n = rd_u32(r);
        if (!need(r, (Py_ssize_t)n))
            return NULL;
        PyObject *v = _PyLong_FromByteArray(r->p, n, 1 /*little*/, 1 /*signed*/);
        r->p += n;
        return v;
    }
    case T_FLOAT: {
        if (!need(r, 8))
            return NULL;
        double d;
        memcpy(&d, r->p, 8);
        r->p += 8;
        return PyFloat_FromDouble(d);
    }
    case T_STR: {
        if (!need(r, 4))
            return NULL;
        uint32_t n = rd_u32(r);
        if (!need(r, (Py_ssize_t)n))
            return NULL;
        PyObject *s = PyUnicode_DecodeUTF8((const char *)r->p, n, NULL);
        if (s == NULL) {
            PyErr_Clear();
            raise_err("invalid utf-8 in frame");
            return NULL;
        }
        r->p += n;
        return s;
    }
    case T_BYTES: {
        if (!need(r, 4))
            return NULL;
        uint32_t n = rd_u32(r);
        if (!need(r, (Py_ssize_t)n))
            return NULL;
        PyObject *b = PyBytes_FromStringAndSize((const char *)r->p, n);
        r->p += n;
        return b;
    }
    case T_LIST:
    case T_TUPLE:
    case T_SET:
    case T_FROZENSET: {
        if (!need(r, 4))
            return NULL;
        uint32_t n = rd_u32(r);
        if (n > MAX_CONTAINER) {
            raise_err("container too large");
            return NULL;
        }
        if (tag == T_LIST || tag == T_TUPLE) {
            PyObject *out = (tag == T_LIST) ? PyList_New(n) : PyTuple_New(n);
            if (out == NULL)
                return NULL;
            for (uint32_t i = 0; i < n; i++) {
                PyObject *item = decode_value(r, depth + 1);
                if (item == NULL) {
                    Py_DECREF(out);
                    return NULL;
                }
                if (tag == T_LIST)
                    PyList_SET_ITEM(out, i, item);
                else
                    PyTuple_SET_ITEM(out, i, item);
            }
            return out;
        }
        PyObject *out = (tag == T_SET) ? PySet_New(NULL)
                                       : PyFrozenSet_New(NULL);
        if (out == NULL)
            return NULL;
        for (uint32_t i = 0; i < n; i++) {
            PyObject *item = decode_value(r, depth + 1);
            if (item == NULL || PySet_Add(out, item) < 0) {
                Py_XDECREF(item);
                Py_DECREF(out);
                return NULL;
            }
            Py_DECREF(item);
        }
        return out;
    }
    case T_DICT: {
        if (!need(r, 4))
            return NULL;
        uint32_t n = rd_u32(r);
        if (n > MAX_CONTAINER) {
            raise_err("container too large");
            return NULL;
        }
        PyObject *out = PyDict_New();
        if (out == NULL)
            return NULL;
        for (uint32_t i = 0; i < n; i++) {
            PyObject *k = decode_value(r, depth + 1);
            if (k == NULL) {
                Py_DECREF(out);
                return NULL;
            }
            PyObject *v = decode_value(r, depth + 1);
            if (v == NULL || PyDict_SetItem(out, k, v) < 0) {
                Py_DECREF(k);
                Py_XDECREF(v);
                Py_DECREF(out);
                return NULL;
            }
            Py_DECREF(k);
            Py_DECREF(v);
        }
        return out;
    }
    case T_STRUCT: {
        if (!need(r, 2))
            return NULL;
        uint16_t sid;
        memcpy(&sid, r->p, 2);
        r->p += 2;
        PyObject *vals = decode_value(r, depth + 1);
        if (vals == NULL)
            return NULL;
        if (!PyTuple_Check(vals)) {
            Py_DECREF(vals);
            raise_err("struct fields must be a tuple");
            return NULL;
        }
        /* the callback owns registry lookup + error wrapping */
        PyObject *out = PyObject_CallFunction(g_struct_cb, "iO", (int)sid,
                                              vals);
        Py_DECREF(vals);
        return out;
    }
    default:
        raise_err("unknown tag");
        return NULL;
    }
}

static PyObject *py_decode(PyObject *self, PyObject *arg)
{
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0)
        return NULL;
    Reader r;
    r.p = (const unsigned char *)view.buf;
    r.end = r.p + view.len;
    if (view.len < 3 || r.p[0] != 'R' || r.p[1] != 'W') {
        PyBuffer_Release(&view);
        raise_err("bad magic: not a ray_tpu control frame");
        return NULL;
    }
    if (r.p[2] != 1) {
        PyBuffer_Release(&view);
        raise_err("unsupported wire version");
        return NULL;
    }
    r.p += 3;
    PyObject *out = decode_value(&r, 0);
    if (out != NULL && r.p != r.end) {
        Py_DECREF(out);
        out = NULL;
        raise_err("trailing bytes after frame");
    }
    PyBuffer_Release(&view);
    return out;
}

static PyObject *py_init(PyObject *self, PyObject *args)
{
    PyObject *err, *cb;
    if (!PyArg_ParseTuple(args, "OO", &err, &cb))
        return NULL;
    Py_XINCREF(err);
    Py_XSETREF(g_decode_err, err);
    Py_XINCREF(cb);
    Py_XSETREF(g_struct_cb, cb);
    Py_RETURN_NONE;
}

static PyMethodDef methods[] = {
    {"decode", py_decode, METH_O,
     "decode(frame: bytes-like) -> object (wire.py-compatible)"},
    {"init", py_init, METH_VARARGS,
     "init(WireDecodeError, struct_cb(sid, vals) -> object)"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_rtpu_wirefast",
    "C decode path for the ray_tpu wire codec", -1, methods,
};

PyMODINIT_FUNC PyInit__rtpu_wirefast(void)
{
    return PyModule_Create(&moduledef);
}
