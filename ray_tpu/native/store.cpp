// Native plasma-equivalent object store core.
//
// Equivalent of the reference's plasma store internals (ref:
// src/ray/object_manager/plasma/store.h:55 ObjectLifecycleManager,
// eviction_policy.h LRUCache, object_store.h allocation). Deliberate
// design divergence: the reference maps ONE big arena and refcounts
// client attachments through IPC; here every object is its own
// shm_open()'d segment, so an evicted object's memory survives for any
// process still holding a zero-copy view (unlink semantics) without a
// cross-process refcount protocol. The C++ layer owns the hot metadata
// path: allocation accounting, LRU ordering, spill/evict decisions,
// segment lifecycle, and crc32c seal checksums (integrity check the
// pure-Python store never had).
//
// C ABI for ctypes (pybind11 is not in the image).
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <list>
#include <mutex>
#include <string>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>
#include <unordered_map>

namespace {

// software crc32c (Castagnoli), slice-by-1; ~1 GB/s — run at seal time
// on the already-written buffer, far from the memcpy hot path.
struct Crc32cTable {
  uint32_t t[256];
  Crc32cTable() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++)
        c = (c & 1) ? (0x82F63B78u ^ (c >> 1)) : (c >> 1);
      t[i] = c;
    }
  }
};

uint32_t crc32c(const uint8_t* data, size_t n) {
  // magic-static: thread-safe one-time init (two Store instances sealing
  // concurrently raced the old lazy bool-guarded fill)
  static const Crc32cTable tbl;
  const uint32_t* table = tbl.t;
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++) c = table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct Entry {
  void* base = nullptr;   // mapped segment (nullptr when spilled)
  uint64_t size = 0;
  bool sealed = false;
  bool pinned = false;
  uint32_t crc = 0;
  bool has_crc = false;
  std::string spill_path;  // non-empty when spilled to disk
  std::list<std::string>::iterator lru_it;
};

struct Store {
  std::mutex mu;
  std::string prefix;
  std::string spill_dir;
  uint64_t capacity = 0;
  uint64_t used = 0;
  uint64_t min_spill = 1 << 20;
  uint64_t num_evictions = 0;
  uint64_t num_spills = 0;
  std::unordered_map<std::string, Entry> objects;
  std::list<std::string> lru;  // front = least recently used
};

std::string seg_name(Store* s, const std::string& oid) {
  return s->prefix + "_" + oid;
}

void* map_segment(const std::string& name, uint64_t size, bool create) {
  int flags = create ? (O_RDWR | O_CREAT | O_EXCL) : O_RDWR;
  int fd = shm_open(("/" + name).c_str(), flags, 0666);
  if (fd < 0 && create && errno == EEXIST) {
    shm_unlink(("/" + name).c_str());  // stale from a previous run
    fd = shm_open(("/" + name).c_str(), flags, 0666);
  }
  if (fd < 0) return nullptr;
  uint64_t sz = size ? size : 1;
  if (create && ftruncate(fd, (off_t)sz) != 0) {
    close(fd);
    shm_unlink(("/" + name).c_str());
    return nullptr;
  }
  void* p = mmap(nullptr, sz, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  return p == MAP_FAILED ? nullptr : p;
}

void unmap_unlink(Store* s, const std::string& oid, Entry& e,
                  bool unlink_file) {
  if (e.base) {
    munmap(e.base, e.size ? e.size : 1);
    e.base = nullptr;
    if (unlink_file) shm_unlink(("/" + seg_name(s, oid)).c_str());
  }
}

// returns false when nothing more can be freed
bool free_one(Store* s, uint64_t needed) {
  for (auto it = s->lru.begin(); it != s->lru.end(); ++it) {
    auto oit = s->objects.find(*it);
    if (oit == s->objects.end()) continue;
    Entry& e = oit->second;
    if (!e.sealed || e.pinned || e.base == nullptr) continue;
    std::string oid = *it;
    if (!s->spill_dir.empty() && e.size >= s->min_spill) {
      // spill: restorable later (ref: local_object_manager.h:110)
      std::string path = s->spill_dir + "/" + seg_name(s, oid);
      FILE* f = fopen(path.c_str(), "wb");
      if (f) {
        // a short write (disk full/quota) recorded as a successful spill
        // would silently lose the object at restore time — verify both
        // the write and the flush-on-close before unmapping memory
        size_t wrote = fwrite(e.base, 1, e.size, f);
        int closed = fclose(f);
        if (wrote == e.size && closed == 0) {
          e.spill_path = path;
          unmap_unlink(s, oid, e, true);
          s->used -= e.size;
          s->num_spills++;
          return true;
        }
        unlink(path.c_str());  // drop the partial file
      }
      // spill failed: fall through to plain eviction
    }
    s->used -= e.size;
    unmap_unlink(s, oid, e, true);
    s->lru.erase(e.lru_it);
    s->objects.erase(oit);
    s->num_evictions++;
    return true;
  }
  (void)needed;
  return false;
}

void touch(Store* s, const std::string& oid, Entry& e) {
  s->lru.erase(e.lru_it);
  s->lru.push_back(oid);
  e.lru_it = std::prev(s->lru.end());
}

}  // namespace

extern "C" {

void* rtpu_store_open(const char* prefix, uint64_t capacity,
                      const char* spill_dir, uint64_t min_spill) {
  Store* s = new Store();
  s->prefix = prefix;
  s->capacity = capacity;
  s->spill_dir = spill_dir ? spill_dir : "";
  if (min_spill) s->min_spill = min_spill;
  return s;
}

// 0 ok; -1 object larger than capacity; -2 store full (all pinned)
int rtpu_store_create(void* h, const char* oid_c, uint64_t size) {
  Store* s = (Store*)h;
  std::lock_guard<std::mutex> g(s->mu);
  std::string oid(oid_c);
  auto it = s->objects.find(oid);
  if (it != s->objects.end()) {  // idempotent re-create (lineage re-run)
    Entry& e = it->second;
    if (e.base) s->used -= e.size;
    unmap_unlink(s, oid, e, true);
    if (!e.spill_path.empty()) unlink(e.spill_path.c_str());
    s->lru.erase(e.lru_it);
    s->objects.erase(it);
  }
  if (size > s->capacity) return -1;
  while (s->used + size > s->capacity) {
    if (!free_one(s, size)) return -2;
  }
  void* base = map_segment(seg_name(s, oid), size, true);
  if (!base) return -2;
  Entry e;
  e.base = base;
  e.size = size;
  s->lru.push_back(oid);
  e.lru_it = std::prev(s->lru.end());
  s->objects.emplace(oid, e);
  s->used += size;
  return 0;
}

int rtpu_store_seal(void* h, const char* oid_c, int with_crc) {
  Store* s = (Store*)h;
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->objects.find(oid_c);
  if (it == s->objects.end()) return -1;
  Entry& e = it->second;
  e.sealed = true;
  if (with_crc && e.base) {
    e.crc = crc32c((const uint8_t*)e.base, e.size);
    e.has_crc = true;
  }
  touch(s, it->first, e);
  return 0;
}

// verify a sealed object against its seal-time checksum.
// 1 = ok, 0 = CORRUPTED, -1 = unknown/no crc/spilled
int rtpu_store_verify(void* h, const char* oid_c) {
  Store* s = (Store*)h;
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->objects.find(oid_c);
  if (it == s->objects.end()) return -1;
  Entry& e = it->second;
  if (!e.has_crc || !e.base) return -1;
  return crc32c((const uint8_t*)e.base, e.size) == e.crc ? 1 : 0;
}

int rtpu_store_pin(void* h, const char* oid_c, int pinned) {
  Store* s = (Store*)h;
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->objects.find(oid_c);
  if (it == s->objects.end()) return -1;
  it->second.pinned = pinned != 0;
  return 0;
}

int rtpu_store_contains(void* h, const char* oid_c) {
  Store* s = (Store*)h;
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->objects.find(oid_c);
  return (it != s->objects.end() && it->second.sealed) ? 1 : 0;
}

// get a writable/readable pointer to the (restored-if-spilled) segment.
// returns 0 and fills ptr/size; -1 unknown; -2 restore failed
int rtpu_store_get(void* h, const char* oid_c, void** ptr,
                   uint64_t* size, int* sealed) {
  Store* s = (Store*)h;
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->objects.find(oid_c);
  if (it == s->objects.end()) return -1;
  Entry& e = it->second;
  if (e.base == nullptr) {
    if (e.spill_path.empty()) return -1;
    while (s->used + e.size > s->capacity) {
      if (!free_one(s, e.size)) return -2;
    }
    void* base = map_segment(seg_name(s, it->first), e.size, true);
    if (!base) return -2;
    FILE* f = fopen(e.spill_path.c_str(), "rb");
    if (!f) {
      munmap(base, e.size ? e.size : 1);
      return -2;
    }
    size_t got = fread(base, 1, e.size, f);
    fclose(f);
    if (got != e.size) {
      munmap(base, e.size ? e.size : 1);
      return -2;
    }
    e.base = base;
    s->used += e.size;
  }
  touch(s, it->first, e);
  *ptr = e.base;
  *size = e.size;
  *sealed = e.sealed ? 1 : 0;
  return 0;
}

int rtpu_store_delete(void* h, const char* oid_c) {
  Store* s = (Store*)h;
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->objects.find(oid_c);
  if (it == s->objects.end()) return 0;
  Entry& e = it->second;
  if (e.base) s->used -= e.size;
  unmap_unlink(s, it->first, e, true);
  if (!e.spill_path.empty()) unlink(e.spill_path.c_str());
  s->lru.erase(e.lru_it);
  s->objects.erase(it);
  return 0;
}

void rtpu_store_stats(void* h, uint64_t* used, uint64_t* capacity,
                      uint64_t* count, uint64_t* evictions,
                      uint64_t* spills) {
  Store* s = (Store*)h;
  std::lock_guard<std::mutex> g(s->mu);
  *used = s->used;
  *capacity = s->capacity;
  *count = s->objects.size();
  *evictions = s->num_evictions;
  *spills = s->num_spills;
}

void rtpu_store_destroy(void* h) {
  Store* s = (Store*)h;
  {
    std::lock_guard<std::mutex> g(s->mu);
    for (auto& kv : s->objects) {
      unmap_unlink(s, kv.first, kv.second, true);
      if (!kv.second.spill_path.empty())
        unlink(kv.second.spill_path.c_str());
    }
    s->objects.clear();
    s->lru.clear();
    s->used = 0;
  }
  delete s;
}

}  // extern "C"
