"""Flash attention for TPU (Pallas).

Tiled online-softmax attention: Q blocks stream over the grid; for each Q
block the kernel walks K/V blocks with a fori_loop keeping running max and
normalizer in f32 (VPU) and accumulating PV on the MXU. bf16 in, f32
accumulate — the standard TPU recipe (pallas_guide.md: MXU matmuls with
preferred_element_type; min tile (16,128) for bf16).

Forward is a Pallas kernel; backward is a custom VJP that recomputes
attention blockwise with jnp (XLA fuses the recompute into the dq/dk/dv
matmuls — rematerialisation trades FLOPs for HBM, the right default on
TPU). Causal masking skips fully-masked K blocks via the loop upper bound,
halving FLOPs for autoregressive models.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .attention import mha_reference

_NEG_INF = -1e30


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, sm_scale: float, causal: bool,
                block_q: int, block_k: int, seq_k: int):
    """Grid: (batch*heads, num_q_blocks). Per call: q_ref (block_q, d);
    k_ref/v_ref (seq_k, d) — whole K/V for this (batch, head) in VMEM."""
    qi = pl.program_id(1)
    q = q_ref[...].astype(jnp.float32) * sm_scale

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)

    if causal:
        # K blocks strictly beyond this Q block's diagonal contribute nothing.
        num_kb = (qi + 1) * block_q // block_k + ((qi + 1) * block_q % block_k != 0)
    else:
        num_kb = seq_k // block_k

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, acc0))
    o_ref[...] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k):
    batch, seq_q, heads, d = q.shape
    seq_k = k.shape[1]
    block_q = min(block_q, seq_q)
    block_k = min(block_k, seq_k)
    assert seq_q % block_q == 0 and seq_k % block_k == 0, (
        f"seq ({seq_q},{seq_k}) must divide blocks ({block_q},{block_k})")
    # fold batch and heads into one grid axis; move heads out of the way:
    # [B,S,H,D] -> [B*H, S, D]
    qr = q.transpose(0, 2, 1, 3).reshape(batch * heads, seq_q, d)
    kr = k.transpose(0, 2, 1, 3).reshape(batch * heads, seq_k, d)
    vr = v.transpose(0, 2, 1, 3).reshape(batch * heads, seq_k, d)

    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, seq_k=seq_k)
    grid = (batch * heads, seq_q // block_q)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, seq_k, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, seq_k, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((batch * heads, seq_q, d), q.dtype),
        interpret=_use_interpret(),
        cost_estimate=pl.CostEstimate(
            flops=4 * batch * heads * seq_q * seq_k * d // (2 if causal else 1),
            bytes_accessed=(qr.size + kr.size + vr.size) * q.dtype.itemsize,
            transcendentals=batch * heads * seq_q * seq_k,
        ),
    )(qr, kr, vr)
    return out.reshape(batch, heads, seq_q, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, sm_scale, causal, block_q, block_k):
    return _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k)


def _flash_vjp_fwd(q, k, v, sm_scale, causal, block_q, block_k):
    out = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k)
    return out, (q, k, v)


def _flash_vjp_bwd(sm_scale, causal, block_q, block_k, res, g):
    # Rematerialised backward: recompute probabilities with the reference
    # formulation and let XLA fuse. O(S^2) memory is avoided by checkpointing
    # at the layer level (jax.checkpoint in the model); for very long S the
    # ring_attention path tiles the backward too.
    q, k, v = res

    def f(q, k, v):
        return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale)

    _, vjp = jax.vjp(f, q, k, v)
    return vjp(g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128) -> jax.Array:
    """Flash attention. q/k/v: [batch, seq, heads, head_dim] -> same shape.

    head_dim should be a multiple of 128 for MXU efficiency (pads are the
    caller's job — model dims are chosen MXU-friendly instead)."""
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    if q.shape[1] < 8:  # tiny decode steps: kernel launch not worth it
        return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale)
    return _flash(q, k, v, sm_scale, causal, block_q, block_k)
